"""Shared finding type + report formatting for lint and elaboration."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Sequence


@dataclass(frozen=True)
class Finding:
    """One problem located at ``path:line`` (line 0 = whole-artifact
    findings, e.g. an elaboration failure of a preset × mesh layout)."""

    rule: str      # rule id, e.g. "stray-device-put" or "elab-train-step"
    path: str      # repo-relative file path, or "<preset>@<layout>" locus
    line: int      # 1-based; 0 when no source line applies
    message: str
    detail: str = field(default="", compare=False)  # long context, optional

    def __str__(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"{loc}: [{self.rule}] {self.message}"


def format_findings(findings: Sequence[Finding],
                    verbose: bool = False) -> str:
    """Human-readable report: findings grouped by rule, stable order."""
    if not findings:
        return "shardcheck: 0 findings"
    by_rule: Dict[str, List[Finding]] = {}
    for f in findings:
        by_rule.setdefault(f.rule, []).append(f)
    out = [f"shardcheck: {len(findings)} finding(s) in "
           f"{len(by_rule)} rule(s)"]
    for rule in sorted(by_rule):
        out.append(f"\n[{rule}] ({len(by_rule[rule])})")
        for f in sorted(by_rule[rule], key=lambda x: (x.path, x.line)):
            loc = f"{f.path}:{f.line}" if f.line else f.path
            out.append(f"  {loc}: {f.message}")
            if verbose and f.detail:
                for ln in f.detail.splitlines():
                    out.append(f"    | {ln}")
    return "\n".join(out)
