"""Plot precision-vs-step from a run's metrics.jsonl files.

The analog of the reference's results/cifar10.jpeg ("Best Precision" curve
from TensorBoard, reference README.md:35-38) — rendered straight from the
JSONL metrics channel so it works without TensorBoard.

Usage: python tools/plot_convergence.py <log_root> <out.png> [title]
"""
from __future__ import annotations

import json
import os
import sys


def read_jsonl(path):
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def main():
    log_root = sys.argv[1]
    out_png = sys.argv[2]
    title = sys.argv[3] if len(sys.argv) > 3 else "Precision vs step"
    rows = read_jsonl(os.path.join(log_root, "train", "metrics.jsonl"))

    train = [(r["step"], r["precision"]) for r in rows if "precision" in r]
    evals = [(r["step"], r["eval/precision"]) for r in rows
             if "eval/precision" in r]

    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(7.2, 4.2), dpi=130)
    blue, orange = "#2563EB", "#D97706"
    if train:
        ax.plot(*zip(*train), color=blue, linewidth=1.2, alpha=0.45,
                label="train batch precision")
    if evals:
        ax.plot(*zip(*evals), color=orange, linewidth=2.0, marker="o",
                markersize=5, label="eval precision (10k held-out)")
        bx, by = max(evals, key=lambda t: t[1])
        ax.annotate(f"best {by:.3f}", (bx, by), textcoords="offset points",
                    xytext=(-8, 10), fontsize=9, color="#374151")
    ax.set_xlabel("training step")
    ax.set_ylabel("top-1 precision")
    ax.set_ylim(0, 1.02)
    ax.set_title(title, fontsize=11)
    ax.grid(True, color="#E5E7EB", linewidth=0.6)
    for spine in ("top", "right"):
        ax.spines[spine].set_visible(False)
    ax.legend(loc="lower right", frameon=False, fontsize=9)
    fig.tight_layout()
    fig.savefig(out_png)
    print(f"wrote {out_png} ({len(train)} train pts, {len(evals)} eval pts)")


if __name__ == "__main__":
    main()
