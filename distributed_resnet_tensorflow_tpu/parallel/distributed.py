"""Multi-host bootstrap.

Replaces the reference's cluster bring-up — ``tf.train.ClusterSpec`` +
``tf.train.Server`` grpc bootstrap (reference resnet_cifar_main.py:364-380)
and Horovod's ``hvd.init()`` MPI bootstrap (reference
resnet_cifar_main_horovod.py:342) — with ``jax.distributed.initialize`` over
DCN: one process per TPU host, every process runs the same SPMD program.

Topology can come from explicit config, from SLURM env vars (the reference's
launchers derived ps/worker host lists from ``scontrol show hostnames``,
reference scripts/run_dist_tf_daint.sh:30-76 — here SLURM integration is just
reading env), or from TPU-pod metadata (jax autodetects when args are None).
"""
from __future__ import annotations

import logging
import os
from typing import Optional

import jax

log = logging.getLogger(__name__)


def initialize_from_config(mesh_cfg) -> None:
    """Initialize the distributed runtime if the config asks for >1 process."""
    if mesh_cfg.num_processes <= 1 and not mesh_cfg.coordinator_address:
        return
    initialize(
        coordinator_address=mesh_cfg.coordinator_address or None,
        num_processes=mesh_cfg.num_processes or None,
        process_id=mesh_cfg.process_id,
    )


def _enable_cpu_collectives() -> None:
    """Pick a real cross-process collectives backend for the CPU platform.

    jaxlib's default CPU collectives are single-process only ("Multiprocess
    computations aren't implemented on the CPU backend"); gloo is the
    multi-process implementation. Setting the env var is NOT enough — this
    environment's sitecustomize drives jax.config at interpreter start, so
    the flag must be flipped through jax.config before the backend
    initializes. No-op on non-CPU platforms and when the operator already
    chose an implementation."""
    try:
        # NOTE the asymmetric accessors: jax 0.4.37 exposes plain flags via
        # config.read() only, context-managed ones via attribute only
        if jax.config.read("jax_cpu_collectives_implementation") != "none":
            return  # operator/site already chose one
        platforms = jax.config.jax_platforms or ""
        if platforms.split(",")[0].strip() != "cpu":
            return
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        log.info("CPU platform multi-process: collectives set to gloo")
    except Exception as e:  # unknown option on a different jaxlib — not fatal
        log.warning("could not configure CPU collectives: %s", e)


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Idempotent `jax.distributed.initialize` with SLURM fallback.

    SLURM env contract (successor of the reference's TF_NUM_PS/TF_NUM_WORKERS
    env contract, reference scripts/run_dist_tf_daint.sh:4-27):
      SLURM_NTASKS → num_processes, SLURM_PROCID → process_id,
      SLURM_STEP_NODELIST first node:8476 → coordinator.
    """
    _enable_cpu_collectives()
    if coordinator_address is None and "SLURM_NTASKS" in os.environ and \
            int(os.environ["SLURM_NTASKS"]) > 1:
        num_processes = int(os.environ["SLURM_NTASKS"])
        process_id = int(os.environ["SLURM_PROCID"])
        nodelist = os.environ.get("SLURM_STEP_NODELIST",
                                  os.environ.get("SLURM_NODELIST", ""))
        first = _first_slurm_node(nodelist)
        coordinator_address = f"{first}:8476"
    from ..resilience.retry import retry_call

    def _preinitialized(e: BaseException) -> bool:
        # jax spells it "already initialized" in some paths and
        # "should only be called once" in State.initialize
        msg = str(e).lower()
        return "already" in msg or "only be called once" in msg

    def attempt():
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id)
        except Exception as e:
            # jax assigns its global client BEFORE connect(); without this
            # reset a retry would die on "should only be called once"
            # instead of re-attempting the connect (verified against
            # jax._src.distributed.State.initialize). NEVER shut down a
            # runtime that was initialized before our call, though — that
            # would tear down a live cluster connection
            if not _preinitialized(e):
                try:
                    jax.distributed.shutdown()
                except Exception:  # partially-initialized — best effort
                    pass
            raise

    try:
        # bounded retry: non-chief processes race the coordinator's bind at
        # job start, and transient DNS/connect failures are routine on big
        # clusters — the reference's grpc bootstrap just died there
        retry_call(
            attempt,
            retries=3, base_delay=1.0, max_delay=15.0,
            retry_on=(RuntimeError, ConnectionError, OSError),
            giveup=_preinitialized,
            description="jax.distributed.initialize")
        log.info("jax.distributed initialized: process %d/%d @ %s",
                 jax.process_index(), jax.process_count(), coordinator_address)
    except RuntimeError as e:  # already initialized before our call
        if not _preinitialized(e):
            raise
        log.info("jax.distributed already initialized")


def _first_slurm_node(nodelist: str) -> str:
    """Expand the first hostname from a SLURM nodelist like 'nid0[1234-1241]'.

    Minimal re-implementation of what the reference got from
    ``scontrol show hostnames`` (reference scripts/run_dist_tf_daint.sh:35).
    """
    if "[" not in nodelist:
        return nodelist.split(",")[0].strip()
    prefix, rest = nodelist.split("[", 1)
    spec = rest.split("]", 1)[0]
    first = spec.split(",")[0].split("-")[0]
    return f"{prefix}{first}"


def is_chief() -> bool:
    """Process 0 — successor of the reference's ``is_chief = task_index == 0``
    (reference resnet_cifar_main.py:323-335)."""
    return jax.process_index() == 0
