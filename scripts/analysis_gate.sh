#!/bin/bash
# Shardcheck gate — the seconds-fast correctness check that runs BEFORE a
# cluster allocation is spent (docs/static_analysis.md):
#
#   * project-invariant lint (analysis/rules/): stray device_put, cached
#     meshes, bare asserts, undeclared exit codes, metrics-event/config
#     drift against the declared registries;
#   * static elaboration (analysis/elaborate.py): every preset × mesh
#     layout traced abstractly on a virtual CPU mesh — PartitionSpec,
#     shape and config bugs surface here with the offending param path,
#     not as a step-1 _SpecError after a 20-minute queue wait.
#
#   scripts/analysis_gate.sh               # full gate (lint + all presets)
#   scripts/analysis_gate.sh --lint-only   # sub-second syntax/invariant pass
#
# Wired as a pre-submit step in scripts/submit_tpu_slurm.sh and into the
# pre-merge chaos gate (scripts/chaos_smoke.sh --fast). Exit 0 = clean,
# 1 = findings (per the resilience.EXIT_CONTRACT failure code).
set -euo pipefail
cd "$(dirname "$0")/.."

# all presets is `check`'s default — not hardcoded here, so pass-through
# args like `--preset smoke` or `--lint-only` scope the gate cleanly
exec env JAX_PLATFORMS=cpu python -m distributed_resnet_tensorflow_tpu.main \
  check "$@"
