"""Synthetic data — deterministic fake batches for smoke tests and benchmarks.

Successor of the reference's local smoke-run config (scripts/submit_mac_dist.sh
with bs=10, 100 steps — SURVEY.md §4.1): exercises the full distributed step
without touching disk.
"""
from __future__ import annotations

from typing import Dict, Iterator, Tuple

import numpy as np


def synthetic_iterator(batch_size: int, image_size: int = 32,
                       num_classes: int = 10, seed: int = 0,
                       channels: int = 3) -> Iterator[Dict[str, np.ndarray]]:
    """Yields random (but reproducible) image batches forever. Data is
    generated once and cycled so the iterator costs nothing per step."""
    rng = np.random.RandomState(seed)
    images = rng.randn(batch_size, image_size, image_size, channels).astype(np.float32)
    labels = rng.randint(0, num_classes, size=(batch_size,)).astype(np.int32)
    batch = {"images": images, "labels": labels}
    while True:
        yield batch


def learnable_synthetic_iterator(batch_size: int, image_size: int = 8,
                                 num_classes: int = 4, seed: int = 0,
                                 ) -> Iterator[Dict[str, np.ndarray]]:
    """Synthetic data with learnable structure (class-dependent mean) so tiny
    convergence tests can assert the loss actually falls."""
    rng = np.random.RandomState(seed)
    protos = rng.randn(num_classes, image_size, image_size, 3).astype(np.float32)
    while True:
        labels = rng.randint(0, num_classes, size=(batch_size,)).astype(np.int32)
        noise = 0.3 * rng.randn(batch_size, image_size, image_size, 3).astype(np.float32)
        images = protos[labels] + noise
        yield {"images": images, "labels": labels}
