"""lock-order-cycle: the lock acquisition-order graph stays acyclic.

Built on ``analysis/locks.py``: lock identities from their construction
sites, acquisitions from ``with <lock>:`` statements, and an edge A→B
whenever code lexically inside ``with A:`` either nests ``with B:`` or
calls (via the conservative resolver) into a function that transitively
acquires B. A cycle in that graph is a deadlock waiting for the right
thread interleaving — including the length-1 cycle of re-acquiring a
non-reentrant ``threading.Lock`` on the same call path, which needs no
second thread at all.

One finding per elementary cycle, anchored at the acquisition site that
introduces the first edge (so suppression — ``# shardcheck:
ok(lock-order-cycle)`` on that line — vets exactly one cycle). Lock
identity is per class attribute: a cycle between two INSTANCES of one
class shows up as a self-cycle on the shared identity; if the instances
are provably distinct and ordered, suppress with the audit comment.
"""
from __future__ import annotations

from typing import Iterable

from ..report import Finding
from .. import locks as locks_mod

RULE_NAME = "lock-order-cycle"
DOC = __doc__


def check(ctx) -> Iterable[Finding]:
    edges = locks_mod.build_order_graph(ctx)
    for cycle in locks_mod.find_cycles(edges):
        first = cycle[0]
        chain = " -> ".join([e.held for e in cycle] + [cycle[0].held])
        sites = "; ".join(
            f"{e.held} then {e.acquired} at {e.rel}:{e.lineno} ({e.via})"
            for e in cycle)
        yield Finding(
            RULE_NAME, first.rel, first.lineno,
            f"lock acquisition cycle {chain} — deadlock under the right "
            f"interleaving. Edges: {sites}")
