"""telemetry/ — flight recorder, goodput accounting, cluster monitor.

Three pillars (docs/observability.md):
  * tracer.py  — per-thread span API + bounded ring + Chrome-trace dumps
    (on demand, on fatal exit, and automatically on watchdog anomalies);
  * goodput.py — classify every second of the train loop into
    {compute, input_wait, checkpoint, eval, stall, restart};
  * monitor.py — ``main.py monitor``: live rollup over every per-host
    metrics stream.
"""
from .goodput import CATEGORIES, GoodputMeter, goodput  # noqa: F401
from .tracer import (  # noqa: F401
    SPAN_CATALOG, SPAN_SCHEMA_VERSION, FlightRecorder, recorder, span)


def configure_from_config(cfg, writer=None, process_index: int = 0) -> None:
    """Wire the process-global recorder from an ExperimentConfig — called
    once per entry point (main.py run_*): sets the ring bound, the dump
    directory (``<log_root>/telemetry`` unless ``telemetry.trace_dir``
    overrides), the chief's metrics writer for ``trace_dump`` rows, and
    the anomaly-profiling knobs."""
    import os
    tcfg = cfg.telemetry
    dump_dir = tcfg.trace_dir or os.path.join(cfg.log_root, "telemetry")
    recorder.configure(
        dump_dir=dump_dir, writer=writer,
        ring=max(1024, tcfg.ring_events),
        enabled=tcfg.enabled,
        process_index=process_index,
        profile_on_anomaly=tcfg.profile_on_anomaly,
        profile_secs=tcfg.profile_secs)
