"""Hangcheck tests (ISSUE 13): each thread/lock contract rule fires on a
known-bad fixture at the expected file:line, the collective-schedule
extractor emits deterministic signatures that match the declared bucket
plan (and flags a seeded mismatch), and the `main.py check` CLI honors
the exit-code contract (0 clean / 1 findings, findings carry file:line)."""
import json
import os

import pytest

from distributed_resnet_tensorflow_tpu.analysis.lint import (
    run_lint, repo_root)
from distributed_resnet_tensorflow_tpu.analysis.report import format_findings

PKG = "distributed_resnet_tensorflow_tpu"


def _by_rule(findings):
    out = {}
    for f in findings:
        out.setdefault(f.rule, []).append(f)
    return out


# ---------------------------------------------------------------------------
# cross-thread-dispatch
# ---------------------------------------------------------------------------

BAD_SPAWN = '''\
import threading


class Runner:
    def work(self, trainer, staged):
        out = trainer.jitted_train_step()(staged)          # line 6: dispatch
        return out

    def start(self):
        t = threading.Thread(target=self.work)             # line 10: spawn
        t.start()


def mystery():
    threading.Thread(target=getattr(object, "x")).start()  # line 15: dynamic
'''


def test_cross_thread_dispatch_fixture(tmp_path, monkeypatch):
    pkg = tmp_path / PKG
    pkg.mkdir()
    (pkg / "bad_threads.py").write_text(BAD_SPAWN)
    rel = os.path.join(PKG, "bad_threads.py")

    # unregistered spawn target + unresolvable dynamic target both fire
    by_rule = _by_rule(run_lint(str(tmp_path)))
    hits = {(f.path, f.line) for f in by_rule["cross-thread-dispatch"]}
    assert (rel, 10) in hits      # unregistered role
    assert (rel, 15) in hits      # dynamic target

    # registering the target with a NON-dispatch role moves the finding
    # to the dispatch-bearing call site (the jitted execution)
    from distributed_resnet_tensorflow_tpu.analysis import threads
    monkeypatch.setitem(threads.THREAD_ROLES,
                        "bad_threads.py::Runner.work", threads.ROLE_STAGING)
    by_rule = _by_rule(run_lint(str(tmp_path)))
    hits = {(f.path, f.line) for f in by_rule["cross-thread-dispatch"]}
    assert (rel, 6) in hits
    assert (rel, 10) not in hits

    # a dispatch role makes the same call legal
    monkeypatch.setitem(threads.THREAD_ROLES,
                        "bad_threads.py::Runner.work",
                        threads.ROLE_DISPATCH)
    by_rule = _by_rule(run_lint(str(tmp_path)))
    hits = {(f.path, f.line) for f in
            by_rule.get("cross-thread-dispatch", ())}
    assert (rel, 6) not in hits


def test_real_tree_spawn_sites_all_registered():
    """Every Thread/executor spawn in the real tree resolves to a role —
    the inventory in analysis/threads.THREAD_ROLES is complete (the
    docs/static_analysis.md thread-role table mirrors it)."""
    from distributed_resnet_tensorflow_tpu.analysis import threads
    from distributed_resnet_tensorflow_tpu.analysis.lint import build_context
    ctx = build_context()
    spawns = list(threads.iter_spawn_sites(ctx))
    assert len(spawns) >= 8  # batcher/swap/prefetch/imagenet×2/beat/dog/ckpt
    unresolved = [s for s in spawns if s.target is None]
    assert unresolved == [], unresolved
    unregistered = [s.target.short() for s in spawns
                    if threads.role_of(s.target) is None]
    assert unregistered == [], unregistered
    # the fleet front door's thread inventory (ISSUE 20 satellite): the
    # listener's accept/connection threads, the router's pool loops, and
    # the supervisor watch must all be spawned through resolvable,
    # registered targets — these are the roots the socket sweep walks
    shorts = {s.target.short() for s in spawns}
    assert {"serve/wire.py::ReplicaListener._accept_loop",
            "serve/wire.py::ReplicaListener._handle_conn",
            "serve/router.py::Router._health_loop",
            "serve/fleet.py::FleetSupervisor._watch"} <= shorts, shorts


# ---------------------------------------------------------------------------
# untimed-blocking-call
# ---------------------------------------------------------------------------

BAD_LOOP = '''\
import queue


def drain(q):
    item = q.get()                       # line 5: untimed get on the loop
    q.get(timeout=1.0)                   # timed: fine
    cfg = {}.get("x")                    # dict.get with args: fine
    return item


class Trainer:
    def train(self, q, worker):
        out = drain(q)
        worker.join()                    # line 14: untimed join
        return out


def helper_elsewhere(q):
    return q.get()                       # unreachable from roots: fine
'''


def test_untimed_blocking_call_fixture(tmp_path):
    pkg = tmp_path / PKG / "train"
    pkg.mkdir(parents=True)
    (pkg / "loop.py").write_text(BAD_LOOP)
    by_rule = _by_rule(run_lint(str(tmp_path)))
    rel = os.path.join(PKG, "train", "loop.py")
    hits = {(f.path, f.line) for f in by_rule["untimed-blocking-call"]}
    assert (rel, 5) in hits
    assert (rel, 14) in hits
    assert hits == {(rel, 5), (rel, 14)}, hits


BAD_SOCK = '''\
import threading


class Listener:
    def loop(self):
        self.sock.settimeout(None)           # DISARMS: not a blessing
        while True:
            conn, _ = self.sock.accept()     # line 8: untimed accept
            data = conn.recv(4096)           # line 9: untimed recv
            self.handle(data)

    def handle(self, data):
        return data

    def start(self):
        t = threading.Thread(target=self.loop)
        t.start()
'''


def test_socket_wait_sweep_fixture(tmp_path):
    """Socket waits on a spawned thread with no armed settimeout are
    findings; arming the deadline in the lifecycle method before the
    spawn (the serve/wire.py listener idiom) blesses the root."""
    pkg = tmp_path / PKG / "serve"
    pkg.mkdir(parents=True)
    (pkg / "bad_sock.py").write_text(BAD_SOCK)
    rel = os.path.join(PKG, "serve", "bad_sock.py")
    by_rule = _by_rule(run_lint(str(tmp_path)))
    hits = {(f.path, f.line) for f in by_rule["untimed-blocking-call"]
            if "socket" in f.message}
    assert hits == {(rel, 8), (rel, 9)}, hits

    (pkg / "bad_sock.py").write_text(BAD_SOCK.replace(
        "        t = threading.Thread(target=self.loop)",
        "        self.sock.settimeout(0.5)\n"
        "        t = threading.Thread(target=self.loop)"))
    by_rule = _by_rule(run_lint(str(tmp_path)))
    hits = {(f.path, f.line) for f in
            by_rule.get("untimed-blocking-call", ())
            if "socket" in f.message}
    assert hits == set(), hits


# ---------------------------------------------------------------------------
# chief-gated-collective
# ---------------------------------------------------------------------------

BAD_CHIEF = '''\
import jax
from jax import lax


def publish(x):
    return lax.psum(x, "data")


def report(writer, x):
    if jax.process_index() == 0:
        writer.write_scalars(0, {"x": 1.0})     # metrics: fine
        publish(x)                              # line 12: gated collective


def guard_form(x):
    if jax.process_index() != 0:
        return None
    return publish(x)                           # line 18: gated by guard


def everyone(x):
    return publish(x)                           # ungated: fine
'''


def test_chief_gated_collective_fixture(tmp_path):
    pkg = tmp_path / PKG
    pkg.mkdir()
    (pkg / "bad_chief.py").write_text(BAD_CHIEF)
    by_rule = _by_rule(run_lint(str(tmp_path)))
    rel = os.path.join(PKG, "bad_chief.py")
    hits = {(f.path, f.line) for f in by_rule["chief-gated-collective"]}
    assert (rel, 12) in hits
    assert (rel, 18) in hits
    assert hits == {(rel, 12), (rel, 18)}, hits


# ---------------------------------------------------------------------------
# lock-order-cycle
# ---------------------------------------------------------------------------

BAD_LOCKS = '''\
import threading

LOCK_A = threading.Lock()
LOCK_B = threading.Lock()
LOCK_C = threading.Lock()


def forward():
    with LOCK_A:
        takes_b()                        # line 10: A-held call taking B


def takes_b():
    with LOCK_B:
        pass


def backward():
    with LOCK_B:
        takes_a()                        # line 20: B-held call taking A


def takes_a():
    with LOCK_A:
        pass


def leaf_only():
    with LOCK_C:                         # no second lock: fine
        pass
'''


def test_lock_order_cycle_fixture(tmp_path):
    pkg = tmp_path / PKG
    pkg.mkdir()
    (pkg / "bad_locks.py").write_text(BAD_LOCKS)
    by_rule = _by_rule(run_lint(str(tmp_path)))
    rel = os.path.join(PKG, "bad_locks.py")
    findings = by_rule["lock-order-cycle"]
    assert len(findings) == 1, [str(f) for f in findings]
    f = findings[0]
    assert f.path == rel and f.line in (10, 20)
    assert "LOCK_A" in f.message and "LOCK_B" in f.message


def test_lock_order_self_cycle_and_suppression(tmp_path):
    src = (
        "import threading\n\n"
        "LOCK = threading.Lock()\n\n\n"
        "def outer():\n"
        "    with LOCK:\n"
        "        inner()                 # line 8: re-acquires LOCK\n\n\n"
        "def inner():\n"
        "    with LOCK:\n"
        "        pass\n")
    pkg = tmp_path / PKG
    pkg.mkdir()
    (pkg / "bad_relock.py").write_text(src)
    by_rule = _by_rule(run_lint(str(tmp_path)))
    rel = os.path.join(PKG, "bad_relock.py")
    assert {(f.path, f.line) for f in by_rule["lock-order-cycle"]} == \
        {(rel, 8)}
    # the established suppression syntax vets the cycle (marker on the
    # acquisition line, one above the edge's call line)
    (pkg / "bad_relock.py").write_text(src.replace(
        "    with LOCK:\n"
        "        inner()                 # line 8: re-acquires LOCK",
        "    with LOCK:\n"
        "        inner()  # shardcheck: ok(lock-order-cycle)"))
    by_rule = _by_rule(run_lint(str(tmp_path)))
    assert "lock-order-cycle" not in by_rule


# ---------------------------------------------------------------------------
# hangcheck-schedule: extraction, declared-plan match, determinism,
# artifact byte-identity
# ---------------------------------------------------------------------------

def _tiny_conv_preset():
    """A cheap in-envelope conv preset for schedule tests (resnet8 on
    8×8 synthetic images, batch 16 — divides 8 shards)."""
    from distributed_resnet_tensorflow_tpu.utils.config import get_preset
    cfg = get_preset("cifar10_resnet50")
    cfg.model.resnet_size = 8
    cfg.data.image_size = 8
    cfg.train.batch_size = 16
    cfg.data.eval_batch_size = 16
    return cfg


def test_extract_schedule_orders_explicit_collectives(devices):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P
    from distributed_resnet_tensorflow_tpu.analysis.collectives import (
        extract_schedule)
    mesh = Mesh(np.array(devices).reshape(8,), ("data",))

    def body(x):
        a = jax.lax.psum(x, "data")
        b = jax.lax.psum_scatter(x, "data", scatter_dimension=0,
                                 tiled=True)
        c = jax.lax.all_gather(b, "data", axis=0, tiled=True)
        return a + c

    fn = shard_map(body, mesh=mesh, in_specs=P("data"),
                   out_specs=P("data"))
    sched = extract_schedule(
        fn, jax.ShapeDtypeStruct((64, 4), jnp.float32))
    kinds = [op["op"] for op in sched]
    assert kinds == ["psum", "psum_scatter", "all_gather"]
    assert sched[0]["axes"] == ["data"]
    # bytes are PER-PARTICIPANT payloads: inside shard_map the traced
    # avals are the local shards — (64/8, 4) f32 here
    assert sched[0]["bytes"] == 8 * 4 * 4


def test_schedule_matches_declared_plan_on_tiny_preset(devices,
                                                       monkeypatch):
    from distributed_resnet_tensorflow_tpu.analysis.collectives import (
        run_collectives)
    from distributed_resnet_tensorflow_tpu.utils import config as config_mod
    monkeypatch.setitem(config_mod.PRESETS, "tiny_conv", _tiny_conv_preset)
    findings, sigs = run_collectives(["tiny_conv"])
    assert findings == [], format_findings(findings, verbose=True)
    ov = sigs["tiny_conv@dp_fsdp/overlap"]
    assert ov["plan"]["buckets"] >= 1
    assert ov["plan"]["declared_collectives"]
    ops = {op["op"] for op in ov["ops"]}
    assert "psum" in ops
    # the compressed composition halves the exchange wire bytes IN the
    # traced signature (operands are bf16 at trace time)
    comp = sigs["tiny_conv@dp_fsdp/bf16+compress"]
    assert comp["plan"]["compress"] == "bf16"
    assert sum(comp["plan"]["bucket_wire_bytes"]) * 2 == \
        sum(comp["plan"]["bucket_bytes"])


def test_schedule_plan_mismatch_is_a_finding(devices, monkeypatch):
    """Seeded drift between the declared plan and the traced exchange —
    the extractor must fail the gate at the variant locus."""
    from distributed_resnet_tensorflow_tpu.analysis import collectives
    from distributed_resnet_tensorflow_tpu.parallel import overlap
    from distributed_resnet_tensorflow_tpu.utils import config as config_mod
    monkeypatch.setitem(config_mod.PRESETS, "tiny_conv", _tiny_conv_preset)
    real = overlap.declared_bucket_collectives

    def drifted(specs, out_specs=None, reduce_axes=("data", "fsdp"),
                **kw):
        return real(specs, out_specs, reduce_axes=reduce_axes, **kw) \
            + ["all_to_all@data"]

    monkeypatch.setattr(overlap, "declared_bucket_collectives", drifted)
    findings, _ = collectives.run_collectives(["tiny_conv"])
    hits = [f for f in findings if f.rule == "hangcheck-schedule"
            and "declared" in f.message]
    assert hits, format_findings(findings, verbose=True)
    assert "tiny_conv@" in hits[0].path


def test_check_declared_plan_subsequence_semantics():
    from distributed_resnet_tensorflow_tpu.analysis.collectives import (
        check_declared_plan)
    sched = [
        {"op": "all_gather", "axes": ["fsdp"]},   # forward gather: noise
        {"op": "psum", "axes": ["data", "fsdp"]},
        {"op": "psum_scatter", "axes": ["fsdp"]},
        {"op": "psum", "axes": ["data"]},
        {"op": "psum", "axes": ["data", "fsdp"]},  # loss psum: noise
    ]
    ok = [["psum@data+fsdp", "psum_scatter@fsdp", "psum@data"]]
    assert check_declared_plan(sched, ok, "x") == []
    # a genuine order violation: psum@data precedes psum_scatter@fsdp
    # nowhere in the trace (subsequence semantics tolerate interleaved
    # noise, never reordering)
    bad = [["psum@data", "psum_scatter@fsdp"]]
    found = check_declared_plan(sched, bad, "x")
    assert found and found[0].rule == "hangcheck-schedule"


def test_artifact_is_byte_identical_across_writes(tmp_path, devices,
                                                  monkeypatch):
    from distributed_resnet_tensorflow_tpu.analysis.collectives import (
        run_collectives, write_artifact)
    from distributed_resnet_tensorflow_tpu.utils import config as config_mod
    monkeypatch.setitem(config_mod.PRESETS, "tiny_conv", _tiny_conv_preset)
    _, sigs1 = run_collectives(["tiny_conv"])
    _, sigs2 = run_collectives(["tiny_conv"])
    p1, p2 = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    write_artifact(sigs1, p1)
    write_artifact(sigs2, p2)
    b1, b2 = open(p1, "rb").read(), open(p2, "rb").read()
    assert b1 == b2
    doc = json.loads(b1)
    assert doc["schema_version"] == 1
    assert any(k.endswith("/overlap") for k in doc["signatures"])


def test_committed_artifact_matches_entry_shape():
    """The committed analysis/collective_schedules.json parses and has
    the documented shape (docs/static_analysis.md) — the gate rewrites
    it on every full sweep, so drift means someone edited it by hand."""
    from distributed_resnet_tensorflow_tpu.analysis.collectives import (
        artifact_path)
    doc = json.load(open(artifact_path()))
    assert doc["schema_version"] == 1
    sigs = doc["signatures"]
    assert any(k.endswith("/overlap") for k in sigs)
    assert any(k.endswith("/overlap+hier") for k in sigs)
    for key, entry in sigs.items():
        for op in entry["ops"]:
            base = {"op", "axes", "operands", "bytes", "count"}
            extra = set(op) - base
            assert base <= set(op), (key, op)
            # grouped (hierarchical-tier) collectives additionally carry
            # the group tiling + tier tag; flat ops must NOT grow keys —
            # that is the pre-existing-family byte-identity contract.
            assert extra <= {"tier", "groups"}, (key, op)
            if extra:
                assert key.endswith("/overlap+hier"), (key, op)


# ---------------------------------------------------------------------------
# `main.py check` CLI exit-code contract
# ---------------------------------------------------------------------------

BAD_CLI_PY = '''\
import sys


def leave():
    sys.exit(3)                                 # line 5: exit-code-contract
'''


def test_check_cli_exit_zero_on_clean_tree():
    from distributed_resnet_tensorflow_tpu.main import main
    with pytest.raises(SystemExit) as e:
        main(["check", "--lint-only"])
    assert e.value.code == 0


def test_check_cli_exit_nonzero_with_findings_and_file_line(tmp_path,
                                                            capsys):
    from distributed_resnet_tensorflow_tpu.main import main
    pkg = tmp_path / PKG
    pkg.mkdir()
    (pkg / "bad.py").write_text(BAD_CLI_PY)
    with pytest.raises(SystemExit) as e:
        main(["check", "--lint-only", "--root", str(tmp_path)])
    assert e.value.code == 1          # the EXIT_CONTRACT failure code
    out = capsys.readouterr().out
    assert os.path.join(PKG, "bad.py") + ":5" in out
    assert "exit-code-contract" in out


def test_check_cli_no_hangcheck_skips_the_rules(tmp_path):
    """--no-hangcheck mirrors --no-zero1-sweep: the four thread/lock
    rules are excluded from the lint pass (and the schedule phase is
    skipped — lint-only here keeps the test in seconds)."""
    from distributed_resnet_tensorflow_tpu.main import main
    pkg = tmp_path / PKG
    pkg.mkdir()
    (pkg / "bad_chief.py").write_text(BAD_CHIEF)
    with pytest.raises(SystemExit) as e:
        main(["check", "--lint-only", "--root", str(tmp_path)])
    assert e.value.code == 1          # hangcheck rule fires...
    with pytest.raises(SystemExit) as e:
        main(["check", "--lint-only", "--no-hangcheck",
              "--root", str(tmp_path)])
    assert e.value.code == 0          # ...and is opted out cleanly
