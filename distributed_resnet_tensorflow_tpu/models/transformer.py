"""Vision Transformer — the attention-based model family.

The reference is conv-only; this framework treats attention and long context
as first-class (ops/attention.py, ops/pallas/flash_attention.py). This module
provides the trainable model that exercises those ops end-to-end through the
same Trainer/config path as the ResNets:

  * ``VisionTransformer`` — patchify → encoder stack → mean-pool → head,
    drop-in for the classification pipeline (same (B, H, W, C) → logits
    contract as the ResNets).
  * ``attention_impl`` selects the kernel: "dense" (reference semantics),
    "blockwise" (O(T) memory lax), or "flash" (Pallas TPU kernel).

All linear algebra is MXU-shaped (model dims multiples of 128 recommended);
bf16 compute / f32 params as elsewhere.
"""
from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def _batch_axes(mesh) -> tuple:
    """Present batch axes, MINUS any the enclosing exchange shard_map
    already maps manually (parallel/overlap.py: inside its body the batch
    is per-shard local — re-splitting or constraining over those axes
    would be wrong/illegal)."""
    from ..parallel.mesh import current_manual_axes, present_batch_axes
    manual = current_manual_axes()
    return tuple(a for a in present_batch_axes(mesh) if a not in manual)


def _constrain(x: jax.Array, mesh, spec: "P") -> jax.Array:
    """with_sharding_constraint when a mesh is attached (no-op otherwise) —
    pins GSPMD's layout choice at the block boundaries. Axes the
    enclosing exchange body maps manually are filtered out of the spec
    (only auto axes may be constrained there)."""
    if mesh is None:
        return x
    from jax.sharding import NamedSharding
    from ..parallel.mesh import filter_manual_spec
    spec = filter_manual_spec(spec)
    if not any(s is not None for s in spec):
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def flash_or_dense(t: int) -> str:
    """The ONE auto rule for flash-vs-dense (no seq axis involved): the
    Pallas kernel on TPU past its measured crossover vs dense —
    docs/flash_tune_r3.json: parity at 1k tokens, 1.1× at 2k, 1.4× at 4k,
    10× at 8k. Shared by the per-block path (_apply_attention) and the
    pipelined path (stage blocks see the full t per microbatch)."""
    return "flash" if (jax.default_backend() == "tpu"
                       and t >= 2048) else "dense"


def _apply_attention(q, k, v, impl: str, mesh=None):
    if impl == "auto":
        # resolved HERE, where the true sequence length is known at trace
        # time: ring when a seq mesh axis exists; otherwise the shared
        # flash_or_dense crossover rule
        if mesh is not None and mesh.shape.get("seq", 1) > 1:
            impl = "ring"
        else:
            impl = flash_or_dense(q.shape[1])
    if impl == "dense":
        from ..ops.attention import attention
        return attention(q, k, v)
    if impl == "blockwise":
        from ..ops.attention import blockwise_attention
        return blockwise_attention(q, k, v)
    if impl in ("flash", "flash_interpret"):
        from ..ops.pallas import flash_attention
        return flash_attention(q, k, v, False, impl == "flash_interpret")
    if impl == "ring":
        from ..ops.attention import ring_attention_sharded
        if mesh is None or mesh.shape.get("seq", 1) <= 1:
            raise ValueError(
                "attention_impl='ring' needs a mesh with a seq axis > 1 "
                "(set mesh.sequence and pass the mesh to the model)")
        return ring_attention_sharded(q, k, v, mesh,
                                      batch_axes=_batch_axes(mesh))
    raise ValueError(f"unknown attention_impl {impl!r}")


class MultiHeadAttention(nn.Module):
    num_heads: int
    dtype: Any = jnp.bfloat16
    attention_impl: str = "dense"
    mesh: Any = None

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, t, d = x.shape
        if d % self.num_heads:
            raise ValueError(f"dim {d} not divisible by heads {self.num_heads}")
        hd = d // self.num_heads
        # kernels carry an explicit head axis — (D, 3, H, hd) / (H, hd, D) —
        # so tensor parallelism shards WHOLE heads (see
        # parallel/sharding.py); a fused (D, 3D) kernel column-sharded over
        # `tensor` would misalign with the q|k|v split boundaries and force
        # resharding around the split in every block
        qkv = nn.DenseGeneral((3, self.num_heads, hd), use_bias=False,
                              dtype=self.dtype, name="qkv")(x)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        out = _apply_attention(q, k, v, self.attention_impl, self.mesh)
        return nn.DenseGeneral(d, axis=(-2, -1), use_bias=False,
                               dtype=self.dtype, name="proj")(out)


class EncoderBlock(nn.Module):
    num_heads: int
    mlp_ratio: int = 4
    dtype: Any = jnp.bfloat16
    attention_impl: str = "dense"
    mesh: Any = None
    num_experts: int = 0             # >0 → Switch MoE MLP (models/moe.py)
    expert_capacity_factor: float = 1.25
    moe_top_k: int = 1
    moe_dispatch: str = "auto"

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        d = x.shape[-1]
        mesh = self.mesh
        tensor = mesh.shape.get("tensor", 1) if mesh is not None else 1
        h = nn.LayerNorm(dtype=self.dtype)(x)
        x = x + MultiHeadAttention(self.num_heads, self.dtype,
                                   self.attention_impl, mesh)(h)
        h = nn.LayerNorm(dtype=self.dtype)(x)
        if self.num_experts > 0:
            from .moe import SwitchMlp
            return x + SwitchMlp(
                num_experts=self.num_experts, mlp_ratio=self.mlp_ratio,
                capacity_factor=self.expert_capacity_factor,
                dtype=self.dtype, mesh=mesh, top_k=self.moe_top_k,
                dispatch=self.moe_dispatch)(h)
        h = nn.Dense(self.mlp_ratio * d, dtype=self.dtype)(h)
        h = nn.gelu(h)
        if tensor > 1:
            # column-parallel up-projection: hidden dim lives on `tensor`;
            # the row-parallel down-projection contracts it (XLA all-reduce).
            # Keep the token dim on `seq` when both parallelisms are active —
            # replicating it here would all-gather the 4x-dim hidden, the
            # largest activation, defeating sequence parallelism
            seq_spec = "seq" if mesh.shape.get("seq", 1) > 1 else None
            h = _constrain(h, mesh, P(_batch_axes(mesh) or None, seq_spec,
                                      "tensor"))
        h = nn.Dense(d, dtype=self.dtype)(h)
        return x + h


class VisionTransformer(nn.Module):
    num_classes: int = 10
    patch_size: int = 4
    dim: int = 128
    depth: int = 6
    num_heads: int = 4
    mlp_ratio: int = 4
    dtype: Any = jnp.bfloat16
    attention_impl: str = "dense"
    remat: bool = False
    # device mesh for sequence (`seq` axis: ring attention + token sharding),
    # tensor (`tensor` axis: Megatron-style block sharding, see
    # parallel/sharding.py param_sharding_rule), and pipeline (`pipeline`
    # axis: GPipe microbatching, models/pipeline.py) parallelism. None =
    # single-device semantics; arrays may still be batch-sharded by jit.
    mesh: Any = None
    pipeline_microbatches: int = 0  # 0 → 2 × pipeline stages
    pipeline_interleave: int = 1    # v>1 → circular schedule (v chunks/stage)
    num_experts: int = 0            # >0 → Switch MoE MLPs over `expert`
    expert_capacity_factor: float = 1.25
    moe_top_k: int = 1
    moe_dispatch: str = "auto"

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        del train  # no BN; deterministic (dropout-free baseline config)
        b, h, w, c = x.shape
        p = self.patch_size
        if h % p or w % p:
            raise ValueError(f"image {h}x{w} not divisible by patch {p}")
        x = x.astype(self.dtype)
        # patchify: conv with stride p == linear patch embed
        x = nn.Conv(self.dim, (p, p), strides=(p, p), padding="VALID",
                    dtype=self.dtype, name="patch_embed")(x)
        x = x.reshape(b, -1, self.dim)
        t = x.shape[1]
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, t, self.dim), jnp.float32)
        x = x + pos.astype(self.dtype)
        mesh = self.mesh
        seq = mesh.shape.get("seq", 1) if mesh is not None else 1
        pipeline = mesh.shape.get("pipeline", 1) if mesh is not None else 1
        if seq > 1:
            if t % seq:
                raise ValueError(f"{t} tokens not divisible by seq axis {seq}")
            # tokens sharded over `seq`: LayerNorm/MLP are token-pointwise and
            # partition cleanly; attention runs the ppermute ring
            x = _constrain(x, mesh, P(_batch_axes(mesh) or None, "seq", None))
        if pipeline > 1:
            # GPipe microbatch pipeline over stacked-parameter stages
            # (models/pipeline.py); parameterization differs from the
            # per-block modules (pack_encoder_params converts).
            # Attention inside a stage: dense, the fused Pallas flash
            # kernel (round 4), or — with a seq axis — ring attention over
            # the token sharding (round 5, pp×seq). 'auto' applies the
            # same trace-time rules as the unpipelined path: ring when a
            # seq axis exists, else flash on TPU past the measured
            # crossover (docs/flash_tune_r3.json; the pipeline's
            # per-microbatch token count is the full t).
            impl = self.attention_impl
            if impl == "auto":
                impl = "ring" if seq > 1 else flash_or_dense(t)
            allowed = ("ring", "ring_interpret") if seq > 1 else \
                ("dense", "flash", "flash_interpret")
            if impl not in allowed:
                raise ValueError(
                    f"pipeline parallelism with seq axis {seq} supports "
                    f"attention_impl in {allowed} "
                    f"(got {self.attention_impl!r})")
            from .pipeline import PipelinedEncoder
            x = PipelinedEncoder(depth=self.depth, num_heads=self.num_heads,
                                 mlp_ratio=self.mlp_ratio, dtype=self.dtype,
                                 mesh=mesh,
                                 microbatches=self.pipeline_microbatches,
                                 interleave=self.pipeline_interleave,
                                 remat=self.remat,
                                 attention_impl=impl,
                                 num_experts=self.num_experts,
                                 expert_capacity_factor=self.expert_capacity_factor,
                                 moe_top_k=self.moe_top_k,
                                 name="encoder")(x)
        else:
            block = EncoderBlock
            if self.remat:
                block = nn.remat(block)
            for _ in range(self.depth):
                x = block(self.num_heads, self.mlp_ratio, self.dtype,
                          self.attention_impl, mesh,
                          num_experts=self.num_experts,
                          expert_capacity_factor=self.expert_capacity_factor,
                          moe_top_k=self.moe_top_k,
                          moe_dispatch=self.moe_dispatch,
                          )(x)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        x = x.mean(axis=1).astype(jnp.float32)
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
