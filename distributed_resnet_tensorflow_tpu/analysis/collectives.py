"""Collective-schedule extraction: pin the comm program statically.

The collective *schedule* — which collectives a step issues, in what
order, over which axes, with how many wire bytes — is a real program
property now that the exchange is explicit (bucketed overlap, ZeRO-1
scatter/gather, compressed payloads, pipeline ppermute chains): a
reordering or a silently-merged bucket is a perf regression at best and
a cross-host deadlock at worst (two hosts issuing collectives in
different orders is the hang class the watchdog can only kill). This
phase walks the jaxprs of the already-elaborated step variants and:

  * emits an ordered signature of collective ops (kind, axis names,
    operand count, payload bytes) per preset × layout × variant. Bytes
    are PER-PARTICIPANT payloads (inside shard_map the traced avals are
    the local shards), and the traced dtype makes compressed payloads
    show their true wire bytes;
  * asserts the signature is DETERMINISTIC across two elaborations for
    every variant that carries collectives (a schedule that differs
    between traces would differ between hosts);
  * cross-checks the overlap variants against the DECLARED bucket plan
    exported by ``parallel/overlap.py`` (``overlap_stats`` →
    ``declared_collectives``): reverse-param-order bucket psums,
    reduce-scatter-before-psum for fsdp/ZeRO leaves, one tuple-psum per
    replicated group — the traced order must contain the declared
    sequence in order, or the gate fails;
  * dumps everything as ``analysis/collective_schedules.json`` (inside
    the package, committed) — byte-identical across runs, so any PR that
    changes comm behavior shows a reviewable diff.

Variants per preset (deduped across presets sharing the program, the
``trace_forward`` lesson): the plain jit train step (its jaxpr-level
schedule is EMPTY for the batch-parallel families by construction — the
exchange is left to XLA sharding propagation; non-empty is itself
information the artifact records), the shard_map'd overlap body on every
in-envelope layout, the ZeRO-1 scatter/gather composition, the bf16 +
compressed-exchange composition, the pipeline/tensor/expert layouts of
the transformer family, and the serve/predict step (smallest + largest
AOT bucket).
"""
from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .report import Finding

RULE = "hangcheck-schedule"

#: the preset whose dp_fsdp overlap variant is double-traced as the
#: in-run determinism probe (cheapest in-envelope conv program)
_DET_PROBE = "cifar10_resnet50"

#: jaxpr primitive name → normalized op kind. ``psum2`` is the
#: shard_map-era spelling of psum; ``reduce_scatter`` implements
#: ``lax.psum_scatter``. ``pbroadcast`` is a replication-rule adjustment,
#: not a wire collective — deliberately excluded.
WIRE_PRIMS = {
    "psum": "psum",
    "psum2": "psum",
    "reduce_scatter": "psum_scatter",
    "all_gather": "all_gather",
    "all_to_all": "all_to_all",
    "ppermute": "ppermute",
    "pmax": "pmax",
    "pmin": "pmin",
    "pgather": "pgather",
}


def _axes_of(eqn) -> Tuple[str, ...]:
    axes = eqn.params.get("axes", None)
    if axes is None:
        axes = eqn.params.get("axis_name", ())
    if isinstance(axes, (str, int)):
        axes = (axes,)
    return tuple(str(a) for a in axes)


def _payload_bytes(eqn) -> int:
    total = 0
    for v in eqn.invars:
        aval = getattr(v, "aval", None)
        shape = getattr(aval, "shape", None)
        dtype = getattr(aval, "dtype", None)
        if shape is None or dtype is None:
            continue
        total += int(math.prod(shape)) * np.dtype(dtype).itemsize
    return total


def _sub_jaxprs(eqn):
    # duck-typed (stable across jax releases): a ClosedJaxpr carries
    # .jaxpr, a raw Jaxpr carries .eqns; params may hold either, alone
    # or in tuples (scan bodies, cond branches, shard_map/pjit/remat)
    for val in eqn.params.values():
        stack = [val]
        while stack:
            item = stack.pop()
            name = type(item).__name__
            if name == "ClosedJaxpr":
                yield item.jaxpr
            elif name == "Jaxpr":
                yield item
            elif isinstance(item, (list, tuple)):
                stack.extend(item)


def collect_ops(jaxpr) -> List[dict]:
    """Ordered collective signature of a jaxpr (recursing into shard_map
    / pjit / scan / cond / remat sub-jaxprs in eqn order). Loop bodies
    (scan/while) contribute their body's schedule ONCE — the static
    issue order, not the dynamic repetition count."""
    out: List[dict] = []
    for eqn in jaxpr.eqns:
        kind = WIRE_PRIMS.get(eqn.primitive.name)
        if kind is not None:
            op = {
                "op": kind,
                "axes": list(_axes_of(eqn)),
                "operands": len(eqn.invars),
                "bytes": _payload_bytes(eqn),
            }
            # grouped (two-tier) collectives — the hierarchical exchange
            # (parallel/overlap): record the group SIZE (the tier width)
            # and which tier the grouping selects — consecutive device
            # blocks are the intra-host tier under the host-aware device
            # order, strided columns the inter-host tier
            groups = eqn.params.get("axis_index_groups")
            if groups:
                g0 = [int(x) for x in groups[0]]
                op["groups"] = len(g0)
                op["tier"] = "intra" if g0 == list(
                    range(g0[0], g0[0] + len(g0))) else "inter"
            out.append(op)
        for sub in _sub_jaxprs(eqn):
            out.extend(collect_ops(sub))
    return out


def extract_schedule(fn, *abstract_args) -> List[dict]:
    """Trace ``fn`` abstractly (zero compute) and return its ordered
    collective signature."""
    import jax
    return collect_ops(jax.make_jaxpr(fn)(*abstract_args).jaxpr)


def _op_sig(op: dict) -> str:
    sig = f"{op['op']}@" + "+".join(op["axes"])
    # grouped collectives carry the group size, matching the declared
    # plan's tier suffix ("psum_scatter@data[4]"); ungrouped ops keep the
    # PRE-EXISTING signature form so committed artifacts stay byte-stable
    if op.get("groups"):
        sig += f"[{op['groups']}]"
    return sig


def check_declared_plan(schedule: Sequence[dict],
                        declared: Sequence[Sequence[str]],
                        locus: str) -> List[Finding]:
    """The declared per-bucket collective sequences must appear, in
    order, within the traced schedule (the trace additionally carries
    the forward fsdp all-gathers and the loss/metric psums around the
    exchange — subsequence matching, not equality)."""
    flat_declared = [sig for bucket in declared for sig in bucket]
    traced = [_op_sig(op) for op in schedule]
    it = iter(traced)
    missing = [sig for sig in flat_declared
               if not any(t == sig for t in it)]
    if missing:
        return [Finding(
            RULE, locus, 0,
            f"traced collective schedule does not contain the declared "
            f"bucket plan in order — first missing {missing[0]!r} "
            f"(declared {len(flat_declared)} exchange ops over "
            f"{len(declared)} buckets; traced {traced})")]
    return []


def _schedule_key(name: str, layout: str, variant: str) -> str:
    return f"{name}@{layout}/{variant}"


def _trainer_for(cfg, mesh):
    from ..train.loop import Trainer
    return Trainer(cfg, mesh=mesh)


#: abstract-state memo across variants/presets: state SHAPES depend only
#: on (model, optimizer family, input dims, batch shards) — rebuilding
#: them per traced variant would be the phase's largest fixed cost
_STATE_MEMO: dict = {}


def _abstract_state(trainer, cfg):
    import dataclasses
    from ..train.state import abstract_train_state
    from ..parallel.mesh import batch_shard_count
    nb = batch_shard_count(trainer.mesh)
    # the memoized state embeds apply_fn — a module bound to ITS mesh.
    # Shaping axes bake into the module's program (pipeline microbatching,
    # the exchange-inline local param shapes), so two layouts may share a
    # state only when their full shaping signature matches; keying on the
    # batch-shard count alone handed dp_pp_ep a dp_pp-meshed apply_fn
    # (same nb=2) and the exchange-inline flax shape check caught it
    key = repr((dataclasses.asdict(cfg.model), cfg.optimizer.name,
                cfg.data.dataset, cfg.data.image_size, nb,
                tuple(trainer.mesh.shape.get(a, 1)
                      for a in ("pipeline", "tensor", "expert", "seq"))))
    state = _STATE_MEMO.get(key)
    if state is None:
        state = abstract_train_state(
            trainer.model, trainer.tx,
            (nb, cfg.data.image_size, cfg.data.image_size, 3)
            if cfg.model.name != "logistic"
            else (nb, cfg.model.input_size))
        _STATE_MEMO[key] = state
    return state


def run_collectives(preset_names: Optional[Sequence[str]] = None,
                    n_devices: int = 8
                    ) -> Tuple[List[Finding], Dict[str, dict]]:
    """The hangcheck-schedule phase: (findings, signatures). Signatures
    feed ``analysis/collective_schedules.json`` (written by the check
    CLI on full-sweep runs)."""
    import copy
    import dataclasses
    import jax
    from ..parallel.mesh import create_mesh
    from ..parallel.overlap import (overlap_stats,
                                    overlap_unsupported_reason)
    from ..utils.config import MeshConfig, PRESETS, get_preset
    from .elaborate import candidate_layouts, _abstract_batch, \
        _axis_product

    findings: List[Finding] = []
    signatures: Dict[str, dict] = {}
    if len(jax.devices()) < n_devices:
        return ([Finding(RULE, "environment", 0,
                         f"{len(jax.devices())} devices present, "
                         f"{n_devices} needed")], signatures)

    seen_programs: set = set()

    def dedupe(kind: str, cfg, layout: str, extra=()) -> bool:
        """True when this (program, layout) was already traced under
        another preset name (the schedule would be identical)."""
        key = repr((kind, dataclasses.asdict(cfg.model), cfg.data.dataset,
                    cfg.data.image_size, layout, tuple(extra)))
        if key in seen_programs:
            return True
        seen_programs.add(key)
        return False

    def record(name: str, layout: str, variant: str, builder,
               deterministic_retrace: bool, plan_check: bool) -> None:
        """Trace (maybe twice), cross-check, record the signature."""
        locus = _schedule_key(name, layout, variant)
        try:
            if plan_check:
                overlap_stats.reset()
            schedule = builder()
        except Exception as e:
            msg = f"{type(e).__name__}: {e}".splitlines()[0][:300]
            findings.append(Finding(RULE, locus, 0,
                                    f"schedule trace failed: {msg}",
                                    detail=str(e)[:4000]))
            return
        entry: dict = {"ops": schedule}
        if plan_check:
            snap = overlap_stats.snapshot()
            if snap is None or not snap.get("declared_collectives"):
                findings.append(Finding(
                    RULE, locus, 0,
                    "overlap variant traced but parallel/overlap.py "
                    "recorded no declared bucket plan — the exchange "
                    "did not run through make_bucketed_grad"))
            else:
                findings.extend(check_declared_plan(
                    schedule, snap["declared_collectives"], locus))
                entry["plan"] = {
                    "buckets": snap["buckets"],
                    "bucket_bytes": snap["bucket_bytes"],
                    "bucket_wire_bytes": snap["bucket_wire_bytes"],
                    "compress": snap["compress"],
                    "declared_collectives": snap["declared_collectives"],
                }
                # hierarchical plans carry the tier factor, the per-op
                # wire ledger and the inter-tier bytes (the 1/k claim,
                # diffable in the artifact). Flat plans omit the keys so
                # every PRE-EXISTING family stays byte-identical.
                if snap.get("hierarchy"):
                    entry["plan"]["hierarchy"] = snap["hierarchy"]
                    entry["plan"]["bucket_op_wire_bytes"] = \
                        snap["bucket_op_wire_bytes"]
                    entry["plan"]["bucket_inter_wire_bytes"] = \
                        snap["bucket_inter_wire_bytes"]
        if deterministic_retrace and schedule:
            second = builder()
            if second != schedule:
                findings.append(Finding(
                    RULE, locus, 0,
                    "collective schedule is NOT deterministic across two "
                    "elaborations — hosts tracing independently could "
                    "issue different orders (first diff at op "
                    f"{next(i for i, (a, b) in enumerate(zip(schedule, second)) if a != b) if len(second) == len(schedule) else 'count'})"))
        signatures[locus] = entry

    for name in (preset_names or sorted(PRESETS)):
        cfg = get_preset(name)
        layouts = candidate_layouts(cfg, n_devices)
        traced_plain = False
        # the low-precision composition (variant 3 below) prefers dp_fsdp
        # (both batch axes live) but must not vanish for a family whose
        # only in-envelope layout is dp — elaborate's elab-precision-step
        # traced it on the first supported layout before hangcheck took
        # the comm traces over (trace_comm_variants=False)
        compress_label = None
        if cfg.train.precision == "off":
            for _lbl, _mc in layouts:
                try:
                    _m = create_mesh(_mc, devices=jax.devices()
                                     [:_axis_product(_mc)])
                except Exception:
                    continue
                if overlap_unsupported_reason(cfg, _m) is None and \
                        (compress_label is None or _lbl == "dp_fsdp"):
                    compress_label = _lbl
        for label, mesh_cfg in layouts:
            n = _axis_product(mesh_cfg)
            try:
                mesh = create_mesh(mesh_cfg, devices=jax.devices()[:n])
            except Exception as e:
                findings.append(Finding(
                    RULE, _schedule_key(name, label, "train"), 0,
                    f"mesh build failed: {e}"))
                continue
            shaping = max(mesh_cfg.pipeline, 1) > 1 or \
                max(mesh_cfg.tensor, 1) > 1 or \
                max(mesh_cfg.expert, 1) > 1 or \
                max(mesh_cfg.sequence, 1) > 1

            # (1) plain jit train step: once per program (CNN steps don't
            # read the mesh at trace time; shaped transformer layouts do).
            # Batch size, optimizer and precision policy never shape the
            # JAXPR-LEVEL collective schedule of the jit step — grads are
            # param-shaped, the exchange is XLA propagation, the policy
            # changes dtypes not collectives — so the optimizer/precision
            # variants of one base preset dedupe onto it
            if (shaping or not traced_plain) and \
                    not dedupe("train", cfg, label if shaping else "any"):
                traced_plain = True

                def build_train(cfg=cfg, mesh=mesh):
                    trainer = _trainer_for(copy.deepcopy(cfg), mesh)
                    state = _abstract_state(trainer, cfg)
                    batch = _abstract_batch(cfg, cfg.train.batch_size)
                    return extract_schedule(trainer._train_step, state,
                                            batch)

                record(name, label, "train", build_train,
                       deterministic_retrace=shaping, plan_check=False)

            # (2) bucketed-overlap exchange, per in-envelope layout; the
            # ZeRO-1 scatter/gather composition rides the same trace for
            # presets that enable the knob
            if overlap_unsupported_reason(cfg, mesh) is None:
                zero1 = cfg.optimizer.zero1 != "off"
                if not dedupe("overlap", cfg, label,
                              (cfg.comm.bucket_mb, cfg.comm.compress,
                               cfg.train.precision, zero1,
                               cfg.optimizer.zero1_min_size)):

                    def build_overlap(cfg=cfg, mesh=mesh, zero1=zero1):
                        ocfg = copy.deepcopy(cfg)
                        ocfg.comm.overlap = "on"
                        if zero1:
                            ocfg.optimizer.zero1 = "on"
                        trainer = _trainer_for(ocfg, mesh)
                        state = _abstract_state(trainer, cfg)
                        batch = _abstract_batch(ocfg,
                                                ocfg.train.batch_size)
                        return extract_schedule(trainer._train_step,
                                                state, batch)

                    # determinism double-trace rides the cheapest
                    # in-envelope program's dp_fsdp layout (both batch
                    # axes live) — re-tracing EVERY variant would double
                    # the phase for no additional signal: the machinery
                    # under test (tree flatten order, greedy bucketing,
                    # shard_map lowering) is shared, and cross-RUN
                    # byte-identity of the artifact covers the rest
                    record(name, label,
                           "overlap+zero1" if zero1 else "overlap",
                           build_overlap,
                           deterministic_retrace=(label == "dp_fsdp"
                                                  and name == _DET_PROBE),
                           plan_check=True)

                # the accumulation composition (the scan inside the
                # exchange body, ONE bucketed exchange per optimizer
                # step): its schedule is the family's witness that wire
                # traffic is 1× per step — the scan body carries no
                # exchange collectives, the declared bucket plan follows
                # it. ONE witness per model family (the conv det-probe on
                # both batch layouts — dp_fsdp adds the scatter+accum
                # composition — and the smallest transformer preset):
                # per-preset accum traces re-record the identical bucket
                # plan and doubled the phase's cost AND the committed
                # artifact for the big presets.
                if not shaping and name in (_DET_PROBE, "vit_moe"):
                    accum = 4 if cfg.train.batch_size % (n * 4) == 0 \
                        else (2 if cfg.train.batch_size % (n * 2) == 0
                              else 0)
                    if accum and not dedupe(
                            "overlap_accum", cfg, label,
                            (cfg.comm.bucket_mb, accum)):

                        def build_accum(cfg=cfg, mesh=mesh, accum=accum):
                            acfg = copy.deepcopy(cfg)
                            acfg.comm.overlap = "on"
                            acfg.train.grad_accum_steps = accum
                            trainer = _trainer_for(acfg, mesh)
                            state = _abstract_state(trainer, cfg)
                            batch = _abstract_batch(
                                acfg, acfg.train.batch_size)
                            return extract_schedule(trainer._train_step,
                                                    state, batch)

                        record(name, label, f"overlap+accum{accum}",
                               build_accum, deterministic_retrace=False,
                               plan_check=True)

                # the hierarchical exchange (comm.hierarchy, the staged
                # RS→psum→AG restaging of every data-reducing bucket):
                # one witness per batch layout of the det-probe — dp
                # factors its 8-way data axis 4×2 (the virtual "2 hosts
                # × 4 devices"), dp_fsdp factors 4-way as 2×2 and adds
                # the fsdp-scatter composition. The explicit
                # intra_axis_size override stands in for multi-host
                # device order on the single-host CPU gate.
                if not shaping and name == _DET_PROBE:
                    dsz = max(mesh_cfg.data, 1)
                    hk = dsz // 2 if dsz >= 4 and dsz % 2 == 0 else 0
                    if hk > 1 and not dedupe(
                            "overlap_hier", cfg, label,
                            (cfg.comm.bucket_mb, hk)):

                        def build_hier(cfg=cfg, mesh=mesh, hk=hk):
                            hcfg = copy.deepcopy(cfg)
                            hcfg.comm.overlap = "on"
                            hcfg.comm.hierarchy = "on"
                            hcfg.comm.intra_axis_size = hk
                            trainer = _trainer_for(hcfg, mesh)
                            state = _abstract_state(trainer, cfg)
                            batch = _abstract_batch(
                                hcfg, hcfg.train.batch_size)
                            return extract_schedule(trainer._train_step,
                                                    state, batch)

                        record(name, label, "overlap+hier", build_hier,
                               deterministic_retrace=(label == "dp"),
                               plan_check=True)

                # (3) the full low-precision composition: bf16 step ×
                # bucketed exchange × compressed payload — wire bytes in
                # the signature come out halved because the traced
                # operands ARE bf16. One layout (dp_fsdp exercises both
                # batch axes) per program.
                if label == compress_label \
                        and not dedupe("compress", cfg, label, ()):

                    def build_compress(cfg=cfg, mesh=mesh):
                        ccfg = copy.deepcopy(cfg)
                        ccfg.train.precision = "bf16"
                        ccfg.comm.overlap = "on"
                        ccfg.comm.compress = "bf16"
                        trainer = _trainer_for(ccfg, mesh)
                        state = _abstract_state(trainer, cfg)
                        batch = _abstract_batch(ccfg,
                                                ccfg.train.batch_size)
                        return extract_schedule(trainer._train_step,
                                                state, batch)

                    record(name, label, "bf16+compress", build_compress,
                           deterministic_retrace=False, plan_check=True)

        # (3b) reshard shrink topologies (docs/resilience.md): after an
        # elastic shrink the SAME program is re-elaborated over the
        # survivor sub-mesh, and every survivor traces it independently
        # inside the reshard barrier — so the schedule on each shrunken
        # topology must be deterministic across elaborations, and is
        # pinned here per survivor count. One witness program (the
        # det-probe) on the plain data layout: a shrink changes the
        # device count and the per_host-rescaled global batch, never the
        # program. 6 and 4 of 8 devices model losing one/two hosts of a
        # four-host fleet with two devices each.
        if name == _DET_PROBE:
            per_dev = cfg.train.batch_size // n_devices
            for shrink in (6, 4):

                def build_shrink(cfg=cfg, shrink=shrink, per_dev=per_dev):
                    sub_mesh = create_mesh(MeshConfig(data=shrink),
                                           devices=jax.devices()[:shrink])
                    scfg = copy.deepcopy(cfg)
                    scfg.train.batch_size = per_dev * shrink
                    trainer = _trainer_for(scfg, sub_mesh)
                    state = _abstract_state(trainer, scfg)
                    batch = _abstract_batch(scfg, scfg.train.batch_size)
                    return extract_schedule(trainer._train_step, state,
                                            batch)

                record(name, "dp", f"reshard_s{shrink}", build_shrink,
                       deterministic_retrace=True, plan_check=False)

        # (4) serve/predict step: smallest + largest AOT bucket on the
        # first layout — forward-only, so the signature pins that serving
        # carries NO hidden collectives on the batch-parallel meshes
        if layouts and not dedupe("serve", cfg, layouts[0][0],
                                  (cfg.serve.max_batch,)):
            label, mesh_cfg = layouts[0]
            try:
                import jax as _jax
                mesh = create_mesh(mesh_cfg,
                                   devices=_jax.devices()
                                   [:_axis_product(mesh_cfg)])
                from ..serve.compile_cache import bucket_sizes
                from ..serve.server import serve_image_spec
                trainer = _trainer_for(copy.deepcopy(cfg), mesh)
                state = _abstract_state(trainer, cfg)
                pad_to = trainer.eval_pad_multiple()
                img_shape, img_dtype = serve_image_spec(cfg)
                max_batch = cfg.serve.max_batch or \
                    cfg.data.eval_batch_size
                buckets = bucket_sizes(max_batch, pad_to)
                # the dtype/collective story is bucket-independent; the
                # largest bucket is the signature, the smallest rides
                # along only for the serving workhorse preset
                probe = sorted({buckets[-1]} | (
                    {buckets[0]} if name == "imagenet_resnet50" else set()))
                for bucket in probe:
                    def build_serve(bucket=bucket, trainer=trainer,
                                    state=state):
                        import jax as __jax
                        sbatch = {"images": __jax.ShapeDtypeStruct(
                            (bucket,) + img_shape, img_dtype)}
                        return extract_schedule(trainer._predict_step,
                                                state, sbatch)
                    record(name, label, f"serve_b{bucket}", build_serve,
                           deterministic_retrace=False, plan_check=False)
            except Exception as e:
                findings.append(Finding(
                    RULE, _schedule_key(name, layouts[0][0], "serve"), 0,
                    f"serve schedule setup failed: {e}"))
    return findings, signatures


def _rle(ops: Sequence[dict]) -> List[dict]:
    """Run-length-encode consecutive identical ops for the artifact (a
    ResNet's per-BN-layer moment psums are dozens of identical 64-byte
    entries — one ``count`` line diffs better than 50 repeats)."""
    out: List[dict] = []
    for op in ops:
        if out and {k: v for k, v in out[-1].items() if k != "count"} == op:
            out[-1]["count"] += 1
        else:
            out.append({**op, "count": 1})
    return out


def write_artifact(signatures: Dict[str, dict],
                   path: Optional[str] = None) -> str:
    """Dump the signature map as the committed, reviewable artifact —
    sorted keys, fixed layout, trailing newline: byte-identical across
    runs whenever the schedules are (which the determinism check
    enforces)."""
    import json
    import os
    if path is None:
        path = artifact_path()
    doc = {"schema_version": 1, "signatures": {
        key: {**entry, "ops": _rle(entry["ops"])}
        for key, entry in signatures.items()}}
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


def artifact_path() -> str:
    import os
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "collective_schedules.json")
