"""Shardcheck tests: every lint rule fires on a known-bad fixture (with
file:line), the elaborator flags a deliberately mis-specced model, the
REAL tree lints clean, and the dispatch sanitizer catches a cross-thread
multi-device launch."""
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_resnet_tensorflow_tpu.analysis.lint import (
    run_lint, repo_root)
from distributed_resnet_tensorflow_tpu.analysis.report import (
    Finding, format_findings)

PKG = "distributed_resnet_tensorflow_tpu"


# ---------------------------------------------------------------------------
# known-bad fixture repo: one violation per rule, at a known line
# ---------------------------------------------------------------------------

BAD_PY = '''\
import functools
import os
import sys

import jax


def stray(batch, sharding):
    return jax.device_put(batch, sharding)          # line 9: stray-device-put


@functools.lru_cache(maxsize=8)
def cached(mesh, n):                                # line 13: cached-mesh
    return n


def guard(x):
    assert x is not None                            # line 18: bare-assert
    return x


def leave():
    sys.exit(3)                                     # line 23: exit-code-contract


def tell(writer):
    writer.write_event("made_up_event", {})         # line 27: registry-drift


def build(mesh):
    return mesh


memo = functools.lru_cache(maxsize=None)(build)     # line 34: cached-mesh


def record(span):
    with span("made_up_span"):                      # line 38: registry-drift (span catalog)
        pass


def stall_the_loop(f):
    os.fsync(f.fileno())                            # line 43: ckpt-io-thread


def depart():
    rc = 7                                          # line 47: exit-flow literal
    return rc


def relay():
    return depart()


def gone():
    sys.exit(relay())


def slam():
    raise SystemExit(9)                             # line 60: SystemExit literal
'''

BAD_SH = '''\
#!/bin/bash
python -m distributed_resnet_tensorflow_tpu.main --set trian.batch_size=64
# stale wildcard section reference (typo'd):
#   tune it via --set resilience.watchdogg.*
'''

BAD_MD = '''\
# stale doc
Watch for `{"event": "vanished_event"}` rows.
Spans land via `span("vanished.span")` in the tracer.
'''


@pytest.fixture()
def bad_repo(tmp_path):
    pkg = tmp_path / PKG
    pkg.mkdir()
    (pkg / "bad.py").write_text(BAD_PY)
    (tmp_path / "scripts").mkdir()
    (tmp_path / "scripts" / "bad.sh").write_text(BAD_SH)
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "bad.md").write_text(BAD_MD)
    return str(tmp_path)


def _by_rule(findings):
    out = {}
    for f in findings:
        out.setdefault(f.rule, []).append(f)
    return out


def test_each_rule_fires_with_file_and_line(bad_repo):
    by_rule = _by_rule(run_lint(bad_repo))
    bad_py = os.path.join(PKG, "bad.py")

    f = by_rule["stray-device-put"][0]
    assert (f.path, f.line) == (bad_py, 9)
    cached = {(f.path, f.line) for f in by_rule["cached-mesh"]}
    assert (bad_py, 12) in cached            # decorator form
    assert (bad_py, 34) in cached            # direct-wrap form
    f = by_rule["bare-assert"][0]
    assert (f.path, f.line) == (bad_py, 18)
    exits = {(f.path, f.line) for f in by_rule["exit-code-contract"]}
    assert (bad_py, 23) in exits         # direct sys.exit literal
    assert (bad_py, 47) in exits         # literal flowing out of depart()
    #                                      through relay() into sys.exit
    assert (bad_py, 60) in exits         # raise SystemExit(<literal>)
    assert exits == {(bad_py, 23), (bad_py, 47), (bad_py, 60)}, exits
    drift = {(f.path, f.line) for f in by_rule["registry-drift"]}
    assert (bad_py, 27) in drift                       # undeclared event
    assert (bad_py, 38) in drift                       # undeclared span
    f = by_rule["ckpt-io-thread"][0]
    assert (f.path, f.line) == (bad_py, 43)
    assert (os.path.join("scripts", "bad.sh"), 2) in drift  # bad --set knob
    assert (os.path.join("scripts", "bad.sh"), 4) in drift  # bad wildcard
    assert (os.path.join("docs", "bad.md"), 2) in drift     # stale doc event
    assert (os.path.join("docs", "bad.md"), 3) in drift     # stale doc span


def test_suppression_comment_silences_rule(bad_repo):
    path = os.path.join(bad_repo, PKG, "bad.py")
    with open(path) as f:
        src = f.read()
    src = src.replace("assert x is not None",
                      "assert x is not None  # shardcheck: ok(bare-assert)")
    with open(path, "w") as f:
        f.write(src)
    by_rule = _by_rule(run_lint(bad_repo))
    assert "bare-assert" not in by_rule
    # a suppression naming ANOTHER rule must not silence this one
    src = src.replace("# shardcheck: ok(bare-assert)",
                      "# shardcheck: ok(cached-mesh)")
    with open(path, "w") as f:
        f.write(src)
    assert "bare-assert" in _by_rule(run_lint(bad_repo))


def test_stray_device_put_covers_serve_tree(tmp_path):
    """The serving subsystem inherits the transfer invariant: a raw
    ``jax.device_put`` anywhere under serve/ (batcher, swap apply, a future
    request path) is a finding — serve transfers go through
    parallel/sharding.py (put_to_sharding / the CoalescedStager), full stop
    (docs/serving.md; ISSUE: no new raw device_put sites)."""
    pkg = tmp_path / PKG / "serve"
    pkg.mkdir(parents=True)
    (pkg / "rogue.py").write_text(
        "import jax\n\n\ndef apply_swap(tree, shardings):\n"
        "    return jax.device_put(tree, shardings)\n")
    by_rule = _by_rule(run_lint(str(tmp_path)))
    hits = {(f.path, f.line) for f in by_rule.get("stray-device-put", ())}
    assert (os.path.join(PKG, "serve", "rogue.py"), 5) in hits


ROGUE_MODEL = '''\
import jax
import jax.numpy as jnp
import flax.linen as nn


def head(x, hidden):
    x = nn.Dense(hidden)(x)                             # line 7: no dtype
    return jnp.matmul(x, x.T)                           # line 8: no cast


def fine(x, w, dtype):
    y = nn.Dense(4, dtype=dtype)(x)                     # policied: ok
    z = jnp.einsum("ij,jk->ik", y, w.astype(dtype))     # visible cast: ok
    q = jnp.dot(z, w, preferred_element_type=jnp.float32)  # pinned acc: ok
    r = jnp.matmul(q, w)  # shardcheck: ok(unpolicied-matmul)
    return r
'''


def test_unpolicied_matmul_rule(tmp_path):
    """The precision-policy lint (analysis/rules/precision_cast.py): a
    flax module without dtype= and a raw contraction with no visible
    dtype decision are flagged in models/ (file:line); dtype'd /
    preferred_element_type'd / .astype'd / suppressed sites and code
    OUTSIDE models|ops are not."""
    models = tmp_path / PKG / "models"
    models.mkdir(parents=True)
    (models / "rogue.py").write_text(ROGUE_MODEL)
    # the identical code outside the models/ops hot path: out of scope
    (tmp_path / PKG / "elsewhere.py").write_text(ROGUE_MODEL)
    by_rule = _by_rule(run_lint(str(tmp_path)))
    hits = {(f.path, f.line) for f in by_rule.get("unpolicied-matmul", ())}
    rogue = os.path.join(PKG, "models", "rogue.py")
    assert (rogue, 7) in hits
    assert (rogue, 8) in hits
    assert hits == {(rogue, 7), (rogue, 8)}, hits


def test_syntax_error_is_a_finding(tmp_path):
    pkg = tmp_path / PKG
    pkg.mkdir()
    (pkg / "broken.py").write_text("def nope(:\n")
    by_rule = _by_rule(run_lint(str(tmp_path)))
    assert "syntax-error" in by_rule


def test_real_tree_lints_clean():
    findings = run_lint(repo_root())
    assert findings == [], format_findings(findings, verbose=True)


def test_format_findings_groups_by_rule():
    out = format_findings([
        Finding("r1", "a.py", 3, "one"),
        Finding("r2", "b.py", 0, "two"),
        Finding("r1", "a.py", 9, "three"),
    ])
    assert "2 rule(s)" in out and "a.py:3" in out and "b.py: two" in out


# ---------------------------------------------------------------------------
# registries
# ---------------------------------------------------------------------------

def test_event_registry_covers_every_emitted_literal():
    """Every write_event literal in the real tree must be declared — the
    registry-drift rule enforces it, so a clean run implies coverage; this
    pins the registry itself against accidental deletion."""
    from distributed_resnet_tensorflow_tpu.utils.metrics import EVENT_SCHEMAS
    for name in ("input_stages", "corrupt_record", "heartbeat", "straggler",
                 "peer_lost", "peer_failed", "hang", "watchdog_cleared",
                 "watchdog_exit"):
        assert name in EVENT_SCHEMAS
        assert EVENT_SCHEMAS[name]["fields"], name


def test_write_event_warns_once_on_undeclared(tmp_path, caplog):
    from distributed_resnet_tensorflow_tpu.utils import metrics as m
    w = m.MetricsWriter(str(tmp_path), enable_tensorboard=False)
    with caplog.at_level("WARNING"):
        w.write_event("not_a_real_event_xyz", {"a": 1})
        w.write_event("not_a_real_event_xyz", {"a": 2})
        w.write_event("straggler", {"median": 1.0})
    w.close()
    warned = [r for r in caplog.records if "not_a_real_event_xyz" in r.message]
    assert len(warned) == 1          # once, not per row
    rows = m.read_metrics(str(tmp_path))
    assert [r.get("event") for r in rows] == \
        ["not_a_real_event_xyz", "not_a_real_event_xyz", "straggler"]


def test_config_knob_resolution():
    from distributed_resnet_tensorflow_tpu.analysis.rules.registry_drift \
        import _knob_resolves
    assert _knob_resolves("train.batch_size")
    assert _knob_resolves("resilience.watchdog.peer_timeout_secs")
    assert _knob_resolves("resilience.watchdog.*")
    assert _knob_resolves("analysis.dispatch_sanitizer")
    assert not _knob_resolves("trian.batch_size")
    assert not _knob_resolves("train.batch_sizes")
    assert not _knob_resolves("train.batch_size.*")  # leaf is not a section


def test_exit_contract_registry():
    from distributed_resnet_tensorflow_tpu.resilience import (
        EXIT_CONTRACT, FAILURE_EXIT_CODE, INTERRUPT_EXIT_CODE,
        RESUMABLE_EXIT_CODE)
    assert set(EXIT_CONTRACT) == {0, FAILURE_EXIT_CODE,
                                  RESUMABLE_EXIT_CODE, INTERRUPT_EXIT_CODE}
    assert INTERRUPT_EXIT_CODE == 130    # shell convention: 128 + SIGINT


# ---------------------------------------------------------------------------
# elaborator
# ---------------------------------------------------------------------------

def test_spec_checker_flags_misspecced_leaf(mesh8):
    from distributed_resnet_tensorflow_tpu.analysis.elaborate import (
        check_spec_tree)
    shapes = {"w": jax.ShapeDtypeStruct((6, 4), np.float32),
              "b": jax.ShapeDtypeStruct((4,), np.float32)}
    shardings = {"w": NamedSharding(mesh8, P("data")),   # 6 % 8 != 0 — bad
                 "b": NamedSharding(mesh8, P())}
    findings = list(check_spec_tree(shapes, shardings, mesh8, "fixture"))
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "elab-spec" and "'w'" in f.message \
        and "data" in f.message and "(6, 4)" not in f.message
    # rank overflow is its own message
    shardings["b"] = NamedSharding(mesh8, P(None, "data"))
    msgs = [f.message for f in
            check_spec_tree(shapes, shardings, mesh8, "fixture")]
    assert any("rank" in m for m in msgs)


def _tiny_vit_cfg(**model_kw):
    from distributed_resnet_tensorflow_tpu.utils.config import (
        ExperimentConfig, ModelConfig, DataConfig, OptimizerConfig,
        TrainConfig)
    cfg = ExperimentConfig()
    cfg.model = ModelConfig(name="vit", num_classes=10, vit_patch_size=8,
                            vit_dim=32, vit_depth=4, vit_heads=4,
                            compute_dtype="float32",
                            attention_impl="dense", **model_kw)
    cfg.data = DataConfig(dataset="synthetic", image_size=32)
    cfg.optimizer = OptimizerConfig(name="adam", schedule="constant")
    cfg.train = TrainConfig(batch_size=8, train_steps=10)
    return cfg


def test_elaborator_flags_misspecced_model(devices):
    """The deliberately mis-specced fixture: pipeline microbatches that
    cannot divide the local batch — the elaborator must name the train
    step and the divisibility, without touching a device."""
    from distributed_resnet_tensorflow_tpu.analysis.elaborate import (
        elaborate_config)
    from distributed_resnet_tensorflow_tpu.utils.config import MeshConfig
    cfg = _tiny_vit_cfg(vit_pipeline_microbatches=3)
    cfg.train.batch_size = 8          # local batch 4 over dp=2, 4 % 3 != 0
    findings = elaborate_config(cfg, MeshConfig(data=2, pipeline=2),
                                "fixture@dp_pp")
    rules = {f.rule for f in findings}
    assert "elab-train-step" in rules, format_findings(findings, True)
    msg = next(f for f in findings if f.rule == "elab-train-step").message
    assert "microbatches" in msg


def test_elaborator_clean_on_valid_pipeline_moe(devices):
    """pp×ep MoE elaborates clean — the configuration whose _SpecError
    this subsystem was built to catch (and whose fix it located)."""
    from distributed_resnet_tensorflow_tpu.analysis.elaborate import (
        elaborate_config)
    from distributed_resnet_tensorflow_tpu.utils.config import MeshConfig
    cfg = _tiny_vit_cfg(vit_num_experts=4, vit_expert_capacity_factor=4.0)
    findings = elaborate_config(
        cfg, MeshConfig(data=2, pipeline=2, expert=2), "fixture@dp_pp_ep")
    assert findings == [], format_findings(findings, verbose=True)


def test_elaborator_clean_on_smoke_preset(devices):
    from distributed_resnet_tensorflow_tpu.analysis.elaborate import (
        run_elaborate)
    findings = run_elaborate(["smoke"])
    assert findings == [], format_findings(findings, verbose=True)


def test_elaborator_traces_serve_step_per_bucket(devices, monkeypatch):
    """The serve/predict step is elaborated per bucket: a predict step
    that cannot trace becomes an elab-serve-step finding naming the
    bucket, instead of a serving replica dying while warming its AOT
    cache (serve/compile_cache.py)."""
    from distributed_resnet_tensorflow_tpu.analysis.elaborate import (
        elaborate_config)
    from distributed_resnet_tensorflow_tpu.train import loop as loop_mod
    from distributed_resnet_tensorflow_tpu.utils.config import (
        MeshConfig, get_preset)

    def broken_predict_step(prep_fn=None, precision=None, apply_fn=None):
        def step(state, batch):
            raise ValueError("serve step fixture breakage")
        return step

    monkeypatch.setattr(loop_mod, "make_predict_step", broken_predict_step)
    cfg = get_preset("smoke")
    cfg.model.resnet_size = 8
    cfg.data.image_size = 8
    findings = elaborate_config(cfg, MeshConfig(data=8), "fixture@dp")
    serve_findings = [f for f in findings if f.rule == "elab-serve-step"]
    assert serve_findings, format_findings(findings, verbose=True)
    assert "bucket" in serve_findings[0].message


def test_check_cli_lint_only():
    from distributed_resnet_tensorflow_tpu.main import main
    with pytest.raises(SystemExit) as e:
        main(["check", "--lint-only"])
    assert e.value.code == 0


# ---------------------------------------------------------------------------
# dispatch sanitizer
# ---------------------------------------------------------------------------

def test_dispatch_sanitizer_catches_cross_thread_launch(mesh8):
    from distributed_resnet_tensorflow_tpu.analysis import (
        dispatch_sanitizer as ds)
    rep = NamedSharding(mesh8, P())
    multi = jax.jit(lambda x: x + 1, out_shardings=rep)
    x = jnp.zeros((8,), jnp.float32)
    multi(x).block_until_ready()      # compile OUTSIDE the guard
    single = jax.jit(lambda x: x * 2)
    single(x).block_until_ready()
    with ds.enabled():
        multi(x).block_until_ready()  # main thread claims ownership
        multi(x).block_until_ready()  # same thread: fine
        errs = []

        def other():
            try:
                multi(x).block_until_ready()
            except Exception as e:    # noqa: BLE001 - collected for assert
                errs.append(e)

        t = threading.Thread(target=other)
        t.start()
        t.join()
        assert len(errs) == 1 and \
            isinstance(errs[0], ds.CrossThreadDispatchError)
        assert "consumer thread" in str(errs[0]) or \
            "docs/input_pipeline.md" in str(errs[0])

        # single-device launches are never restricted
        errs2 = []

        def other_single():
            try:
                single(x).block_until_ready()
            except Exception as e:    # noqa: BLE001
                errs2.append(e)

        t2 = threading.Thread(target=other_single)
        t2.start()
        t2.join()
        assert errs2 == []

        # an explicit handoff re-opens ownership
        ds.reset_owner()
        errs3 = []

        def new_owner():
            try:
                multi(x).block_until_ready()
            except Exception as e:    # noqa: BLE001
                errs3.append(e)

        t3 = threading.Thread(target=new_owner)
        t3.start()
        t3.join()
        assert errs3 == []
    assert not ds.is_installed()
    multi(x).block_until_ready()      # uninstalled: unrestricted again


def test_dispatch_sanitizer_config_knob():
    from distributed_resnet_tensorflow_tpu.utils.config import parse_args
    cfg = parse_args(["--preset", "smoke",
                      "--set", "analysis.dispatch_sanitizer=true"])
    assert cfg.analysis.dispatch_sanitizer is True


def test_ckpt_io_rule_scopes_manager_to_writer_fn(tmp_path):
    """Inside checkpoint/manager.py the durability calls are legal ONLY
    within _write (the writer-thread entry); the same call in any other
    method — e.g. a save() that fsyncs on the loop thread — is a
    finding."""
    pkg = tmp_path / PKG / "checkpoint"
    pkg.mkdir(parents=True)
    (pkg / "manager.py").write_text(
        "import os\n\n\n"
        "def _write(step):\n"
        "    os.fsync(step)        # legal: the writer entry\n\n\n"
        "def save(step, f):\n"
        "    os.fsync(f.fileno())  # line 9: loop-thread checkpoint I/O\n")
    by_rule = _by_rule(run_lint(str(tmp_path)))
    hits = {(f.path, f.line) for f in by_rule.get("ckpt-io-thread", ())}
    rel = os.path.join(PKG, "checkpoint", "manager.py")
    assert (rel, 9) in hits
    assert (rel, 5) not in hits


def test_elaborator_traces_bucketed_overlap_step(devices):
    """The gate traces the comm.overlap=on variant of every in-envelope
    preset × layout (elab-overlap-step): a clean conv preset elaborates
    without findings, and the trace actually ran (the plan registry is
    populated by the shard_map trace)."""
    from distributed_resnet_tensorflow_tpu.analysis.elaborate import (
        elaborate_config)
    from distributed_resnet_tensorflow_tpu.parallel.overlap import (
        overlap_stats)
    from distributed_resnet_tensorflow_tpu.utils.config import (
        MeshConfig, get_preset)
    cfg = get_preset("cifar10_resnet50")
    cfg.model.resnet_size = 8
    cfg.data.image_size = 8
    cfg.train.batch_size = 16
    overlap_stats.reset()
    findings = elaborate_config(cfg, MeshConfig(data=4, fsdp=2),
                                "fixture@dp_fsdp")
    assert [f for f in findings if f.rule == "elab-overlap-step"] == [], \
        [f.message for f in findings]
    assert overlap_stats.snapshot() is not None

# ---------------------------------------------------------------------------
# unsharded-opt-state rule + elab-zero1 big-mesh sweep (ISSUE 11)
# ---------------------------------------------------------------------------

def _bad_zero1_preset():
    """Fixture preset: optimizer.zero1=on over shapes no 8-way data axis
    divides (35/9/3 logistic) — the promise the rule exists to catch."""
    from distributed_resnet_tensorflow_tpu.utils.config import (
        ExperimentConfig)
    cfg = ExperimentConfig()
    cfg.model.name = "logistic"
    cfg.model.input_size = 35
    cfg.model.hidden_units = 9
    cfg.model.num_classes = 3
    cfg.optimizer.zero1 = "on"
    cfg.optimizer.zero1_min_size = 8
    return cfg


def test_unsharded_opt_state_rule_fires_with_file_and_line(monkeypatch):
    from types import SimpleNamespace
    from distributed_resnet_tensorflow_tpu.analysis.rules import (
        opt_state as rule)
    from distributed_resnet_tensorflow_tpu.utils import config as config_mod
    monkeypatch.setitem(config_mod.PRESETS, "bad_zero1", _bad_zero1_preset)
    findings = [f for f in rule.check(SimpleNamespace(root=repo_root()))
                if "bad_zero1" in f.message]
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "unsharded-opt-state"
    # anchored at the fixture FACTORY's def line in this file
    assert f.path.endswith("test_analysis.py")
    assert f.line == _bad_zero1_preset.__code__.co_firstlineno
    assert "replicated" in f.message


def test_unsharded_opt_state_rule_clean_on_real_presets():
    """The shipped zero1 presets (lars4k/lamb4k) must actually shard —
    the rule passing on the real tree IS the promise check."""
    from types import SimpleNamespace
    from distributed_resnet_tensorflow_tpu.analysis.rules import (
        opt_state as rule)
    assert list(rule.check(SimpleNamespace(root=repo_root()))) == []


def test_elab_zero1_sweep_clean_and_flags_unshardable(devices, monkeypatch):
    """The big-mesh sweep, exercised at the test harness's 8 devices
    (sizes is a parameter; the gate runs 64/256): a real zero1 preset
    elaborates clean, and a preset whose shapes defeat the rule table
    gets an elab-zero1 finding naming the fully-replicated resolution."""
    from distributed_resnet_tensorflow_tpu.analysis.elaborate import (
        run_elaborate_zero1)
    from distributed_resnet_tensorflow_tpu.utils import config as config_mod

    clean = run_elaborate_zero1(["imagenet_resnet50_lars4k"], sizes=(8,))
    assert clean == [], [f.message for f in clean]

    monkeypatch.setitem(config_mod.PRESETS, "bad_zero1", _bad_zero1_preset)
    bad = run_elaborate_zero1(["bad_zero1"], sizes=(8,))
    assert any(f.rule == "elab-zero1" and "FULLY replicated" in f.message
               for f in bad), [f.message for f in bad]
