"""ckpt-io-thread: checkpoint I/O stays off the train-loop thread.

The zero-stall checkpoint contract (docs/resilience.md, round 10): the
step-loop thread's only checkpoint costs are the device→host snapshot and
backpressure on an in-flight save — the stage/fsync/manifest/commit
protocol runs on the dedicated writer thread (``CheckpointManager._write``,
reached via ``_write_async``) or, on the deliberate sync path
(multi-process saves, ``async_save=false``), through that same function.
A durability call (``os.fsync``, ``fsync_dir``, ``write_manifest``, or a
direct staging-path write) sprinkled anywhere else is dead device time
the goodput meter would bill as a checkpoint stall — exactly the bucket
this round drove to ~0 — and it dodges the writer's span/stat accounting
(``checkpoint.writer``, the ``ckpt_async`` row).

Allowed homes: ``resilience/manifest.py`` (the commit protocol itself)
and, inside ``checkpoint/manager.py``, only the ``_write`` function (the
writer entry). Deliberate exceptions carry
``# shardcheck: ok(ckpt-io-thread)`` — e.g. the fault injector's marker
fsync (resilience/faultinject.py), which runs on the writer thread by
construction.
"""
from __future__ import annotations

import ast
from typing import Iterable, Optional

from ..report import Finding

RULE_NAME = "ckpt-io-thread"
DOC = __doc__

ALLOWED_FILES = (
    "distributed_resnet_tensorflow_tpu/resilience/manifest.py",
    # the per-host sharded payload writer (round 11): all of its
    # fsync/staging work runs on the writer thread by construction —
    # CheckpointManager._write_sharded is its only production caller
    "distributed_resnet_tensorflow_tpu/checkpoint/shards.py",
)
MANAGER_FILE = "distributed_resnet_tensorflow_tpu/checkpoint/manager.py"
MANAGER_WRITER_FNS = ("_write", "_write_sharded")

#: call names that perform checkpoint durability I/O
_IO_NAMES = ("fsync", "fsync_dir", "write_manifest", "staging_path")


def _io_call_name(node: ast.Call) -> Optional[str]:
    fn = node.func
    if isinstance(fn, ast.Name) and fn.id in _IO_NAMES:
        return fn.id
    if isinstance(fn, ast.Attribute) and fn.attr in _IO_NAMES:
        # os.fsync / manifest.fsync_dir / manifest.write_manifest
        return fn.attr
    return None


def _function_span(tree: ast.AST, name: str):
    """(start, end) line range of the named function, or None."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == name:
            return node.lineno, node.end_lineno or node.lineno
    return None


def check(ctx) -> Iterable[Finding]:
    for sf in ctx.all_python():
        if sf.tree is None or sf.rel in ALLOWED_FILES:
            continue
        writer_spans = []
        if sf.rel == MANAGER_FILE:
            writer_spans = [s for s in (_function_span(sf.tree, fn)
                                        for fn in MANAGER_WRITER_FNS)
                            if s is not None]
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _io_call_name(node)
            if name is None:
                continue
            if any(lo <= node.lineno <= hi for lo, hi in writer_spans):
                continue  # inside a writer entry — the legal homes
            yield Finding(
                RULE_NAME, sf.rel, node.lineno,
                f"checkpoint I/O call {name}() outside the writer path — "
                "staging/fsync/manifest work belongs in "
                "CheckpointManager._write/_write_sharded (writer thread), "
                "checkpoint/shards.py, or resilience/manifest.py; on the "
                "train-loop thread it is a goodput checkpoint stall the "
                "async design exists to remove")
