"""Fault-injection harness — makes every resilience behavior testable.

Nothing in the reference could SIMULATE a failure; the fault-tolerance story
was therefore untested by construction (SURVEY.md §4.4). This module is the
missing chaos tooling, used by tests/test_resilience.py and
scripts/chaos_smoke.sh:

  * :func:`deliver_signal_after` / :class:`SignalAfter` — deliver a signal
    to this (or a child) process mid-run from a timer thread.
  * :func:`corrupt_checkpoint` — tear a COMMITTED checkpoint the way real
    failures do: truncate the largest payload file (torn write / full disk)
    or flip a byte in place (bit rot), leaving the manifest stale.
  * :func:`inject_nan` — wrap a training iterator so the N-th batch carries
    non-finite pixels, driving a genuine NaN loss through the real model.
  * :func:`maybe_wrap_from_env` — env-var trigger (``DRT_FAULT_NAN_AT_BATCH``)
    so subprocess tests and chaos scripts can inject through the unmodified
    ``main.py`` CLI.

Injection is opt-in and inert by default; none of this runs unless a test or
operator asks for it.
"""
from __future__ import annotations

import logging
import os
import signal as _signal
import threading
from typing import Dict, Iterator, Optional

import numpy as np

log = logging.getLogger(__name__)

NAN_ENV_VAR = "DRT_FAULT_NAN_AT_BATCH"


# -- signals ----------------------------------------------------------------

def deliver_signal_after(delay_secs: float, sig: int = _signal.SIGTERM,
                         pid: Optional[int] = None) -> threading.Timer:
    """Arm a timer that delivers ``sig`` to ``pid`` (default: this process)
    after ``delay_secs``. Returns the started Timer (cancel() to disarm)."""
    target = os.getpid() if pid is None else pid

    def fire():
        try:
            os.kill(target, sig)
        except (ProcessLookupError, PermissionError) as e:
            log.warning("fault injection: signal %s to pid %d failed: %s",
                        sig, target, e)

    t = threading.Timer(delay_secs, fire)
    t.daemon = True
    t.start()
    return t


class SignalAfter:
    """Context manager over :func:`deliver_signal_after` that disarms on
    exit, so a test that finishes early doesn't shoot the next one."""

    def __init__(self, delay_secs: float, sig: int = _signal.SIGTERM,
                 pid: Optional[int] = None):
        self._args = (delay_secs, sig, pid)
        self._timer: Optional[threading.Timer] = None

    def __enter__(self) -> "SignalAfter":
        self._timer = deliver_signal_after(*self._args)
        return self

    def __exit__(self, *exc) -> None:
        if self._timer is not None:
            self._timer.cancel()


# -- checkpoint damage ------------------------------------------------------

def _largest_payload(step_dir: str) -> str:
    from .manifest import MANIFEST_NAME
    best, best_size = None, -1
    for dirpath, _dirs, files in os.walk(step_dir):
        for name in files:
            if name == MANIFEST_NAME:
                continue
            full = os.path.join(dirpath, name)
            size = os.path.getsize(full)
            if size > best_size:
                best, best_size = full, size
    if best is None:
        raise FileNotFoundError(f"no payload files under {step_dir}")
    return best


def corrupt_checkpoint(directory: str, step: Optional[int] = None,
                       mode: str = "truncate") -> int:
    """Damage a committed checkpoint in place (default: the latest).

    ``mode="truncate"`` drops the second half of the largest payload file —
    the shape of a torn write; ``mode="flip"`` inverts one byte mid-file
    with the size unchanged — the shape of bit rot, catchable only by
    checksum. Returns the damaged step."""
    from .manifest import committed_steps
    steps = committed_steps(directory)
    if not steps:
        raise FileNotFoundError(f"no committed checkpoints in {directory}")
    step = steps[-1] if step is None else step
    if step not in steps:
        raise FileNotFoundError(f"step {step} not committed in {directory}")
    victim = _largest_payload(os.path.join(directory, str(step)))
    size = os.path.getsize(victim)
    if mode == "truncate":
        with open(victim, "r+b") as f:
            f.truncate(max(0, size // 2))
    elif mode == "flip":
        if size == 0:
            raise ValueError(f"{victim} is empty; nothing to flip")
        with open(victim, "r+b") as f:
            f.seek(size // 2)
            byte = f.read(1)
            f.seek(size // 2)
            f.write(bytes([byte[0] ^ 0xFF]))
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")
    log.info("fault injection: %s %s (step %d, %d bytes)",
             mode, victim, step, size)
    return step


# -- NaN loss ---------------------------------------------------------------

def inject_nan(data_iter: Iterator[Dict], at_batch: int,
               key: str = "images") -> Iterator[Dict]:
    """Yield batches unchanged except the ``at_batch``-th (1-based), whose
    ``key`` entry is replaced with NaNs — the loss of that step is then
    genuinely non-finite through the whole real model/optimizer path.

    Batches without ``key`` (e.g. device-resident ``{"idx"}`` batches) pass
    through untouched; NaN injection needs the streamed-image path."""
    if at_batch < 1:
        raise ValueError(f"at_batch is 1-based, got {at_batch}")
    count = 0
    for batch in data_iter:
        count += 1
        if count == at_batch and key in batch:
            poisoned = dict(batch)
            poisoned[key] = np.full_like(
                np.asarray(batch[key], dtype=np.float32), np.nan)
            log.warning("fault injection: batch %d %r poisoned with NaN",
                        count, key)
            yield poisoned
        else:
            yield batch


_nan_armed = False


def maybe_wrap_from_env(data_iter: Iterator[Dict],
                        env: Optional[Dict[str, str]] = None) -> Iterator[Dict]:
    """Apply :func:`inject_nan` when ``DRT_FAULT_NAN_AT_BATCH`` is set to a
    positive integer — the hook main.py's train source passes through so
    subprocess tests / chaos scripts can inject without patching code.

    Arms at most ONCE per process: the NaN sentinel rebuilds the train
    source after a rollback, and re-poisoning the rebuilt stream would turn
    one injected fault into an unrecoverable run."""
    global _nan_armed
    value = (os.environ if env is None else env).get(NAN_ENV_VAR, "")
    if not value or _nan_armed:
        return data_iter
    _nan_armed = True
    try:
        at_batch = int(value)
    except ValueError:
        log.warning("ignoring malformed %s=%r", NAN_ENV_VAR, value)
        return data_iter
    if at_batch < 1:
        return data_iter
    log.warning("fault injection armed: NaN images at batch %d (%s)",
                at_batch, NAN_ENV_VAR)
    return inject_nan(data_iter, at_batch)
