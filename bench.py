"""Benchmark: ResNet-50 CIFAR-10 training steps/sec on one chip.

Comparable to the reference's single-node flagship number — CIFAR-10
ResNet-50 (6·8+2 layers), global batch 128, 13.94 steps/sec on 1× P100
(reference README.md:28-30; BASELINE.md). Synthetic data (input pipeline
excluded, same as the reference's steps/sec which measured the hot session
loop). Prints ONE JSON line.
"""
from __future__ import annotations

import json
import time

import jax
import numpy as np

BASELINE_STEPS_PER_SEC = 13.94  # reference README.md:28-30 (1x P100)


def main():
    from distributed_resnet_tensorflow_tpu.parallel import create_mesh
    from distributed_resnet_tensorflow_tpu.parallel.sharding import (
        shard_stacked_batch)
    from distributed_resnet_tensorflow_tpu.train import Trainer
    from distributed_resnet_tensorflow_tpu.utils.config import get_preset

    cfg = get_preset("cifar10_resnet50")  # resnet_size=50, bs=128, momentum
    cfg.data.dataset = "synthetic"
    cfg.train.steps_per_loop = 20  # fused multi-step dispatch (lax.scan)
    n_dev = len(jax.devices())
    cfg.mesh.data = n_dev
    mesh = create_mesh(cfg.mesh)

    trainer = Trainer(cfg, mesh=mesh)
    trainer.init_state()
    k = cfg.train.steps_per_loop
    multi_fn = trainer.jitted_multi_step(k)

    rng = np.random.RandomState(0)
    batch = shard_stacked_batch({
        "images": rng.randn(k, 128, 32, 32, 3).astype(np.float32),
        "labels": rng.randint(0, 10, (k, 128)).astype(np.int32),
    }, mesh)

    # warmup / compile
    state = trainer.state
    for _ in range(2):
        state, m = multi_fn(state, batch)
    jax.block_until_ready(state.params)

    # best-of-3 repetitions: the measurement rides a remote-tunnel TPU in
    # this environment and single runs are noisy
    loops = 10
    best_dt = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(loops):
            state, m = multi_fn(state, batch)
        jax.block_until_ready(state.params)
        best_dt = min(best_dt, time.perf_counter() - t0)

    steps_per_sec = loops * k / best_dt
    print(json.dumps({
        "metric": "cifar10_resnet50_bs128_train_steps_per_sec",
        "value": round(steps_per_sec, 2),
        "unit": "steps/sec",
        "vs_baseline": round(steps_per_sec / BASELINE_STEPS_PER_SEC, 2),
    }))


if __name__ == "__main__":
    main()
