"""Model zoo tests — shapes, param structure, variant table, v2 semantics
(covers reference resnet_model_official.py behaviors, SURVEY.md §2.1)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_resnet_tensorflow_tpu.models import (
    CifarResNetV2, ImageNetResNetV2, IMAGENET_MODEL_PARAMS, LogisticNet,
    count_params, create_model)
from distributed_resnet_tensorflow_tpu.utils.config import ModelConfig


def _init_and_apply(model, shape, train=False):
    rng = jax.random.PRNGKey(0)
    x = jnp.zeros(shape, jnp.float32)
    variables = model.init(rng, x, train=False)
    if train:
        out, _ = model.apply(variables, x, train=True, mutable=["batch_stats"])
    else:
        out = model.apply(variables, x, train=False)
    return variables, out


def test_cifar_resnet_shapes():
    model = CifarResNetV2(resnet_size=20, num_classes=10, dtype=jnp.float32)
    variables, logits = _init_and_apply(model, (4, 32, 32, 3))
    assert logits.shape == (4, 10)
    assert logits.dtype == jnp.float32


def test_cifar_resnet_size_validation():
    """6n+2 constraint (reference resnet_model_official.py:217-231)."""
    model = CifarResNetV2(resnet_size=21)
    with pytest.raises(ValueError):
        _init_and_apply(model, (1, 32, 32, 3))


def test_cifar_resnet20_param_count():
    """ResNet-20 v2 CIFAR ≈ 0.27M params (well-known figure)."""
    model = CifarResNetV2(resnet_size=20, num_classes=10, dtype=jnp.float32)
    variables, _ = _init_and_apply(model, (1, 32, 32, 3))
    n = count_params(variables["params"])
    assert 0.25e6 < n < 0.30e6, n


def test_wide_resnet_28_10_param_count():
    """WRN-28-10 ≈ 36.5M params — exercises the width generalization
    (BASELINE.json config 4)."""
    model = CifarResNetV2(resnet_size=28, width_multiplier=10,
                          num_classes=100, dtype=jnp.float32)
    variables, logits = _init_and_apply(model, (2, 32, 32, 3))
    n = count_params(variables["params"])
    assert 35e6 < n < 38e6, n
    assert logits.shape == (2, 100)


@pytest.mark.parametrize("size", [18, 50])
def test_imagenet_resnet_shapes(size):
    model = ImageNetResNetV2(resnet_size=size, num_classes=1001,
                             dtype=jnp.float32)
    variables, logits = _init_and_apply(model, (2, 64, 64, 3))
    assert logits.shape == (2, 1001)


def test_imagenet_resnet50_param_count():
    """ResNet-50 ≈ 25.6M params (1001 classes)."""
    model = ImageNetResNetV2(resnet_size=50, num_classes=1001,
                             dtype=jnp.float32)
    variables, _ = _init_and_apply(model, (1, 224, 224, 3))
    n = count_params(variables["params"])
    assert 25e6 < n < 26.5e6, n


def test_imagenet_size_table():
    """Size table parity (reference resnet_model_official.py:352-359)."""
    assert set(IMAGENET_MODEL_PARAMS) == {18, 34, 50, 101, 152, 200}
    assert IMAGENET_MODEL_PARAMS[50] == ("bottleneck", (3, 4, 6, 3))
    assert IMAGENET_MODEL_PARAMS[18] == ("building", (2, 2, 2, 2))
    model = ImageNetResNetV2(resnet_size=77)
    with pytest.raises(ValueError):
        _init_and_apply(model, (1, 64, 64, 3))


def test_batch_stats_update_in_train_mode():
    """BN moving stats must change in train mode and be used in eval —
    successor of the reference's UPDATE_OPS control-dep wiring
    (reference resnet_model.py:118-121)."""
    model = CifarResNetV2(resnet_size=20, dtype=jnp.float32)
    rng = jax.random.PRNGKey(0)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 32, 32, 3))
    variables = model.init(rng, x, train=False)
    _, mutated = model.apply(variables, x, train=True, mutable=["batch_stats"])
    old = jax.tree_util.tree_leaves(variables["batch_stats"])
    new = jax.tree_util.tree_leaves(mutated["batch_stats"])
    assert any(not np.allclose(o, n) for o, n in zip(old, new))


def test_bfloat16_compute_fp32_params():
    model = CifarResNetV2(resnet_size=20, dtype=jnp.bfloat16)
    variables, logits = _init_and_apply(model, (2, 32, 32, 3))
    # params stay fp32 (master weights), head output fp32
    kernels = jax.tree_util.tree_leaves(variables["params"])
    assert all(k.dtype == jnp.float32 for k in kernels)
    assert logits.dtype == jnp.float32


def test_logistic_net():
    """Toy MLP parity (reference logist_model.py)."""
    model = LogisticNet(num_classes=10, hidden_units=100)
    variables, logits = _init_and_apply(model, (4, 32, 32, 3))
    assert logits.shape == (4, 10)


def test_create_model_factory():
    cfg = ModelConfig(resnet_size=20, num_classes=10, compute_dtype="float32")
    m = create_model(cfg, "cifar10")
    assert isinstance(m, CifarResNetV2)
    cfg2 = ModelConfig(resnet_size=50, num_classes=1001, compute_dtype="float32")
    m2 = create_model(cfg2, "imagenet")
    assert isinstance(m2, ImageNetResNetV2)
    cfg3 = ModelConfig(name="logistic")
    assert isinstance(create_model(cfg3, "cifar10"), LogisticNet)


def test_stem_space_to_depth_parity():
    """StemConv(space_to_depth=True) computes the same conv as the plain
    7x7/2 stem — same params (mode-portable checkpoints), reassociated
    arithmetic only (fp32 here, so near-exact)."""
    from distributed_resnet_tensorflow_tpu.models.resnet import StemConv

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 32, 32, 3).astype(np.float32))
    plain = StemConv(16, space_to_depth=False, dtype=jnp.float32)
    s2d = StemConv(16, space_to_depth=True, dtype=jnp.float32)
    variables = plain.init(jax.random.PRNGKey(0), x)
    y_plain = plain.apply(variables, x)
    y_s2d = s2d.apply(variables, x)  # same param tree
    assert y_plain.shape == (2, 16, 16, 16)
    assert y_s2d.shape == y_plain.shape
    np.testing.assert_allclose(np.asarray(y_s2d), np.asarray(y_plain),
                               rtol=1e-5, atol=1e-5)

    # grads agree too (the transform is linear in both x and w)
    def loss(mode):
        m = StemConv(16, space_to_depth=mode, dtype=jnp.float32)
        return lambda v: jnp.sum(m.apply(v, x) ** 2)
    g_plain = jax.grad(loss(False))(variables)
    g_s2d = jax.grad(loss(True))(variables)
    np.testing.assert_allclose(
        np.asarray(g_s2d["params"]["kernel"]),
        np.asarray(g_plain["params"]["kernel"]), rtol=1e-4, atol=1e-4)
