"""Native C++ loader tests — behavior identical to the python parsers.

Skipped wholesale if no C++ toolchain is available to build libdrtdata.so.
"""
import os

import numpy as np
import pytest

nl = pytest.importorskip(
    "distributed_resnet_tensorflow_tpu.data.native_loader")
if not nl.native_available():
    pytest.skip("native loader unavailable (no toolchain?)",
                allow_module_level=True)

from distributed_resnet_tensorflow_tpu.data.cifar import load_cifar
from distributed_resnet_tensorflow_tpu.data.tfrecord import (
    build_example, masked_crc32c, write_tfrecords)


def test_native_crc_matches_python():
    rng = np.random.RandomState(0)
    for n in (0, 1, 7, 8, 9, 1000):
        data = rng.bytes(n)
        from distributed_resnet_tensorflow_tpu.data.tfrecord import crc32c
        assert nl.crc32c(data) == crc32c(data), n
        assert nl.masked_crc32c(data) == masked_crc32c(data), n


def _write_cifar(tmp_path, dataset):
    rng = np.random.RandomState(3)
    lb = 1 if dataset == "cifar10" else 2
    names = ([f"data_batch_{i}.bin" for i in range(1, 6)]
             if dataset == "cifar10" else ["train.bin"])
    for name in names:
        recs = np.zeros((10, lb + 3072), np.uint8)
        recs[:, :lb] = rng.randint(0, 100, (10, lb))
        recs[:, lb:] = rng.randint(0, 256, (10, 3072))
        recs.tofile(os.path.join(tmp_path, name))
    return str(tmp_path)


@pytest.mark.parametrize("dataset", ["cifar10", "cifar100"])
def test_native_cifar_matches_python(tmp_path, dataset):
    d = _write_cifar(tmp_path, dataset)
    im_py, lb_py = load_cifar(dataset, d, "train", use_native=False)
    im_c, lb_c = load_cifar(dataset, d, "train", use_native=True)
    np.testing.assert_array_equal(im_py, im_c)
    np.testing.assert_array_equal(lb_py, lb_c)


def test_native_prefetcher_reads_all_records(tmp_path):
    rng = np.random.RandomState(1)
    want = set()
    paths = []
    for s in range(3):
        recs = []
        for i in range(20):
            payload = bytes([s, i]) + rng.bytes(50)
            recs.append(payload)
            want.add(payload)
        path = os.path.join(tmp_path, f"shard-{s}")
        write_tfrecords(path, recs)
        paths.append(path)
    pf = nl.NativePrefetcher(paths, num_threads=2, verify_crc=True)
    got = set(pf)
    pf.close()
    assert got == want
    assert pf.crc_errors == 0


def test_native_prefetcher_skips_corrupt_records(tmp_path):
    path = os.path.join(tmp_path, "bad")
    write_tfrecords(path, [b"good-one", b"bad-rec!", b"good-two"])
    raw = bytearray(open(path, "rb").read())
    # corrupt the middle record's payload (offset: 12 hdr + 8 data + 4 crc + 12 hdr)
    raw[12 + 8 + 4 + 12 + 2] ^= 0xFF
    open(path, "wb").write(bytes(raw))
    pf = nl.NativePrefetcher([path], num_threads=1, verify_crc=True)
    got = list(pf)
    pf.close()
    assert b"good-one" in got and b"good-two" in got
    assert pf.crc_errors == 1


def test_native_prefetcher_truncated_shard_raises(tmp_path):
    """Mid-record EOF must be LOUD like the python reader (which raises
    IOError 'truncated record'), not a silent partial dataset."""
    path = os.path.join(tmp_path, "trunc")
    write_tfrecords(path, [b"record-one", b"record-two"])
    raw = open(path, "rb").read()
    open(path, "wb").write(raw[:-7])  # cut into the last record's footer
    pf = nl.NativePrefetcher([path], num_threads=1)
    with pytest.raises(IOError, match="truncated"):
        list(pf)
    assert pf.truncated == 1
    pf.close()
    assert pf.truncated == 1  # survives close


def test_native_cifar_load_beyond_60k(tmp_path):
    """The native CIFAR parser sizes its buffers from the file: >60000
    records load in full, identical to the python parser (no silent cap)."""
    n = 60004
    rec = np.zeros((n, 3073), np.uint8)
    rec[:, 0] = np.arange(n) % 10
    path = os.path.join(tmp_path, "big.bin")
    rec.tofile(path)
    images, labels = nl.load_cifar_native(str(path), 1, 0)
    assert len(labels) == n
    assert labels[-1] == (n - 1) % 10


def test_native_prefetcher_close_during_iteration(tmp_path):
    """close() from another thread while a consumer iterates: the consumer
    must end cleanly (StopIteration via the stop flag) and close must not
    free the native object under a live drt_prefetch_next call (the
    stop → drain in-flight → destroy protocol)."""
    import threading
    import time as _time
    rng = np.random.RandomState(3)
    path = os.path.join(tmp_path, "many")
    write_tfrecords(path, [rng.bytes(2048) for _ in range(5000)])
    pf = nl.NativePrefetcher([path] * 4, num_threads=2)
    seen = []
    errors = []

    def consume():
        try:
            for rec in pf:
                seen.append(len(rec))
        except Exception as e:  # pragma: no cover - would fail the assert
            errors.append(e)

    t = threading.Thread(target=consume)
    t.start()
    _time.sleep(0.05)
    pf.close()
    t.join(timeout=10.0)
    assert not t.is_alive(), "consumer failed to terminate after close()"
    assert not errors, errors
    assert pf.truncated == 0


def test_native_prefetcher_large_records(tmp_path):
    """Records larger than the initial 1MB buffer trigger the regrow path."""
    big = os.urandom(3 << 20)
    path = os.path.join(tmp_path, "big")
    write_tfrecords(path, [big])
    pf = nl.NativePrefetcher([path], num_threads=1)
    got = list(pf)
    pf.close()
    assert got == [big]


def test_imagenet_iterator_native_path(tmp_path):
    from distributed_resnet_tensorflow_tpu.data.imagenet import imagenet_iterator
    from distributed_resnet_tensorflow_tpu.data.preprocessing import encode_jpeg
    rng = np.random.RandomState(5)
    recs = [build_example({
        "image/encoded": [encode_jpeg(rng.randint(0, 256, (40, 40, 3), np.uint8))],
        "image/class/label": [i + 1]}) for i in range(8)]
    write_tfrecords(os.path.join(tmp_path, "train-00000-of-00001"), recs)
    it = imagenet_iterator(str(tmp_path), batch_size=4, mode="train",
                           image_size=32, num_decode_threads=1,
                           shuffle_buffer=2, use_native=True)
    b = next(it)
    assert b["images"].shape == (4, 32, 32, 3)
    assert (b["labels"] >= 1).all()


def test_native_jpeg_decode_matches_pil_path():
    """The fused C++ decode+resize+crop produces the same crop geometry as
    the PIL path under one RNG seed, with near-identical pixels (the two
    differ only in interpolation), and falls back cleanly on non-JPEG."""
    import numpy as np
    import pytest
    from distributed_resnet_tensorflow_tpu.data.native_loader import (
        decode_resize_crop_native, native_jpeg_available)
    if not native_jpeg_available():
        pytest.skip("libjpeg not available in native build")
    from distributed_resnet_tensorflow_tpu.data.preprocessing import (
        encode_jpeg, train_crop_from_bytes)
    rng = np.random.RandomState(0)
    yy, xx = np.mgrid[0:380, 0:520].astype(np.float32)
    img = np.clip(120 + 55 * np.sin(yy / 31)[..., None]
                  + 45 * np.cos(xx / 47)[..., None] * np.array([1, .6, -.4])
                  + rng.normal(0, 7, (380, 520, 3)), 0, 255).astype(np.uint8)
    data = encode_jpeg(img)
    a = train_crop_from_bytes(data, np.random.RandomState(3), 224,
                              use_native=True)
    b = train_crop_from_bytes(data, np.random.RandomState(3), 224,
                              use_native=False)
    assert a.shape == b.shape == (224, 224, 3)
    assert a.dtype == np.uint8
    corr = np.corrcoef(a.astype(float).ravel(), b.astype(float).ravel())[0, 1]
    assert corr > 0.99, corr
    # corrupt/non-JPEG input: returns None (caller falls back)
    assert decode_resize_crop_native(b"nope", 256, 0, 0, 224, False) is None


def test_native_decode_clamps_oversized_crop_window():
    """output_size larger than the resized image (e.g. eval at 384 with
    resize side 256) must clamp-replicate edges, not read past the decode
    buffer."""
    import numpy as np
    import pytest
    from distributed_resnet_tensorflow_tpu.data.native_loader import (
        decode_resize_crop_native, native_jpeg_available)
    if not native_jpeg_available():
        pytest.skip("libjpeg not available in native build")
    from distributed_resnet_tensorflow_tpu.data.preprocessing import (
        encode_jpeg, eval_crop_from_bytes)
    rng = np.random.RandomState(5)
    img = rng.randint(0, 256, (300, 400, 3), np.uint8)
    data = encode_jpeg(img)
    # crop window 384 > resized shorter side 256: top/left are negative,
    # bottom/right run past the image — all sampled via edge replication
    out = decode_resize_crop_native(data, 256, -64, -20, 384, False)
    assert out is not None and out.shape == (384, 384, 3)
    assert out.min() >= 0 and out.max() <= 255
    big = eval_crop_from_bytes(data, 384, use_native=True)
    assert big.shape == (384, 384, 3)
