"""Open-loop synthetic load generator for the inference server.

OPEN loop: arrivals are scheduled on a fixed clock (request i at its
precomputed arrival offset) regardless of completions — the load a real
user population offers, and the one that exposes queueing collapse. A
closed-loop driver (wait for each response before sending the next) would
self-throttle exactly when the server is slowest and report flattering
latency (coordinated omission). The generator never blocks on a Future
until the offered load is fully submitted; per-request latency is recorded
by the server at result time, so a late response is charged its full
queue + service time.

Load shapes (``shape=``): the arrival SCHEDULE is precomputed by
inverting the cumulative integral of a rate function, so every shape
stays coordinated-omission-free — the clock, not the server, decides
when request i goes out:

  * ``steady``  — constant ``qps`` (the historical behavior).
  * ``diurnal`` — one full sinusoid period over the run, ±50% around
    ``qps`` (day/night traffic compressed into the window).
  * ``burst``   — 70% of ``qps`` baseline with periodic 3× bursts (a
    tenth of the window each, five per run) — retry storms / batch jobs.
  * ``spike``   — ``qps`` baseline with a single 4× spike across the
    middle tenth of the window — the flash-crowd shape that trips
    admission (shed/degrade) in the fleet front door.

Every shape offers ≈ ``qps × duration`` total requests, so reports stay
comparable across shapes.
"""
from __future__ import annotations

import logging
import time
from concurrent.futures import wait as futures_wait
from typing import Optional

import numpy as np

log = logging.getLogger(__name__)

LOAD_SHAPES = ("steady", "diurnal", "burst", "spike")


def synthetic_requests(image_shape, dtype, pool: int = 32, seed: int = 0):
    """A small pool of random request images, cycled by the generator (the
    per-request content doesn't affect timing; generating fresh images at
    high QPS would bottleneck the GENERATOR, not measure the server)."""
    rng = np.random.RandomState(seed)
    dtype = np.dtype(dtype)
    if dtype == np.uint8:
        return [rng.randint(0, 256, image_shape, np.uint8)
                for _ in range(pool)]
    return [rng.randn(*image_shape).astype(dtype) for _ in range(pool)]


def _rate_fn(shape: str, qps: float, duration_secs: float):
    """Instantaneous request rate at time t ∈ [0, duration)."""
    if shape == "steady":
        return lambda t: qps
    if shape == "diurnal":
        w = 2.0 * np.pi / duration_secs
        return lambda t: qps * (1.0 + 0.5 * np.sin(w * t))
    if shape == "burst":
        period = duration_secs / 5.0

        def burst(t):
            return 3.0 * qps if (t % period) < period * 0.1 else 0.7 * qps
        return burst
    if shape == "spike":
        lo, hi = 0.45 * duration_secs, 0.55 * duration_secs
        return lambda t: 4.0 * qps if lo <= t < hi else qps
    raise ValueError(f"unknown load shape {shape!r}; "
                     f"one of {LOAD_SHAPES}")


def arrival_times(shape: str, qps: float, duration_secs: float) -> np.ndarray:
    """Precomputed arrival offsets (seconds from start) for the whole
    run: cumulative-rate inversion on a fine grid, so the i-th arrival is
    where the integral of the rate function crosses i. Deterministic and
    independent of server behavior — the open-loop guarantee."""
    rate = _rate_fn(shape, qps, duration_secs)
    grid = np.linspace(0.0, duration_secs, max(1000, int(duration_secs * 200)))
    rates = np.asarray([rate(t) for t in grid], dtype=np.float64)
    cum = np.concatenate([[0.0], np.cumsum(
        (rates[1:] + rates[:-1]) / 2.0 * np.diff(grid))])
    n = max(1, int(round(cum[-1])))
    return np.interp(np.arange(n) * (cum[-1] / n), cum, grid)


def run_open_loop(server, qps: float, duration_secs: float,
                  seed: int = 0, timeout_secs: Optional[float] = None,
                  variant: Optional[str] = None,
                  shape: str = "steady") -> dict:
    """Offer ≈ ``qps × duration_secs`` requests on the ``shape`` arrival
    schedule, then wait for every outstanding Future. Returns
    offered/completed/failed/late counts and the achieved submit rate;
    latency percentiles live in ``server.report()`` (recorded server-side
    per request).

    ``variant`` targets one serving precision variant (docs/precision.md;
    None = the replica's default) — bench's (batch, variant) serving row
    drives one open loop per variant."""
    offsets = arrival_times(shape, qps, duration_secs)
    n = len(offsets)
    pool = synthetic_requests(server.image_shape, server.image_dtype,
                              seed=seed)
    futures = []
    late = 0
    t0 = time.perf_counter()
    for i in range(n):
        target = t0 + offsets[i]
        now = time.perf_counter()
        if now < target:
            time.sleep(target - now)
        elif now - target > 0.5:
            late += 1  # generator itself fell behind the open-loop clock
        futures.append(server.submit(pool[i % len(pool)], variant=variant))
    submit_wall = time.perf_counter() - t0
    done, not_done = futures_wait(
        futures, timeout=timeout_secs if timeout_secs is not None
        else max(60.0, duration_secs))
    failed = sum(1 for f in done if f.exception() is not None)
    if not_done:
        log.error("open-loop load: %d request(s) unresolved at timeout",
                  len(not_done))
    return {
        "offered": n,
        "completed": len(done) - failed,
        "failed": failed,
        "unresolved": len(not_done),
        "late_submits": late,
        "shape": shape,
        "offered_qps": round(qps, 1),
        "achieved_submit_qps": round(n / max(submit_wall, 1e-9), 1),
        "wall_secs": round(submit_wall, 2),
    }
