"""Per-op TPU profile of the ImageNet ResNet-50 train step.

Captures a jax.profiler trace of the fused train dispatch and parses the
xplane proto directly into an HLO-op time breakdown — the auditable
evidence behind docs/perf_imagenet_r3.md (the reference kept its perf story
in README tables; this is the TPU analog with per-op receipts).

    python tools/profile_trace.py [--bs 128] [--k 8] [--sub 1] [--top 25]
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def capture(bs: int, k: int, sub: int, logdir: str) -> int:
    """Trace the fused k-step dispatch; returns the number of optimizer
    steps inside the traced window."""
    from profile_imagenet_bn import build_step
    trainer, multi_fn, batch, _one = build_step(bs, k, stat_subsample=sub)
    state = trainer.state
    for _ in range(2):  # compile + warm
        state, _ = multi_fn(state, batch)
    jax.block_until_ready(state.params)
    dispatches = 2
    with jax.profiler.trace(logdir):
        for _ in range(dispatches):
            state, _ = multi_fn(state, batch)
        jax.block_until_ready(state.params)
    return dispatches * k


def op_table(logdir: str, top: int):
    """xplane → [{op family, category, device_us, occurrences}] sorted.

    Parses the XSpace proto directly (the tensorboard_plugin_profile
    converter is binary-incompatible with this image's protobuf/TF pairing):
    the TPU plane's "XLA Ops" line carries one event per HLO-op execution
    with device_duration_ps + an hlo_category stat. Ops are grouped into
    families by stripping the trailing ".N" instance suffix — the level the
    perf doc reasons at (fusion.*, multiply_reduce_fusion.*, ...)."""
    import re
    from tensorflow.tsl.profiler.protobuf import xplane_pb2
    xplanes = sorted(glob.glob(os.path.join(
        logdir, "plugins/profile/*/*.xplane.pb")))
    if not xplanes:
        raise FileNotFoundError(f"no xplane under {logdir}")
    space = xplane_pb2.XSpace()
    with open(xplanes[-1], "rb") as f:
        space.ParseFromString(f.read())
    tpu = next((p for p in space.planes
                if p.name.startswith("/device:TPU")), None)
    if tpu is None:
        raise RuntimeError(
            f"no TPU plane in {xplanes[-1]} "
            f"({[p.name for p in space.planes]})")
    line = next((l for l in tpu.lines if l.name == "XLA Ops"), None)
    if line is None:
        raise RuntimeError(f"no 'XLA Ops' line ({[l.name for l in tpu.lines]})")
    smeta, emeta = tpu.stat_metadata, tpu.event_metadata
    # control-flow container ops whose duration INCLUDES every child op
    # below them — counting any of them would double the totals
    container = {"while", "conditional", "call", "control-flow"}
    fams = {}
    insts = {}
    for ev in line.events:
        md = emeta[ev.metadata_id]
        name_full = md.display_name or md.name
        fam = re.sub(r"\.\d+$", "", name_full)
        cat = ""
        dur_ps = ev.duration_ps
        for st in list(ev.stats) + list(md.stats):
            name = smeta[st.metadata_id].name
            if name == "hlo_category":
                cat = st.str_value or (
                    smeta[st.ref_value].name if st.ref_value else "")
            elif name == "device_duration_ps" and st.int64_value:
                dur_ps = st.int64_value
        if cat in container:
            continue
        agg = fams.setdefault((cat, fam), [0, 0])
        agg[0] += dur_ps
        agg[1] += 1
        iagg = insts.setdefault((cat, name_full), [0, 0])
        iagg[0] += dur_ps
        iagg[1] += 1
    out = [{"category": c, "op": f, "self_us": ps / 1e6, "n": n}
           for (c, f), (ps, n) in fams.items()]
    out.sort(key=lambda d: -d["self_us"])
    iout = [{"category": c, "op": f, "self_us": ps / 1e6, "n": n}
            for (c, f), (ps, n) in insts.items()]
    iout.sort(key=lambda d: -d["self_us"])
    return out[:top], iout[:top]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--bs", type=int, default=128)
    ap.add_argument("--k", type=int, default=8)
    ap.add_argument("--sub", type=int, default=1)
    ap.add_argument("--top", type=int, default=25)
    ap.add_argument("--logdir", default="/tmp/drt_trace")
    ap.add_argument("--out", default="")
    args = ap.parse_args()
    steps = capture(args.bs, args.k, args.sub, args.logdir)
    table, instances = op_table(args.logdir, args.top)
    print(f"top-{args.top} HLO ops by self time "
          f"(bs={args.bs}, k={args.k}, stat_subsample={args.sub}):")
    for d in table:
        print(f"{d['self_us']:>10.0f} us  {d['category']:<22} "
              f"{str(d['op'])[:70]}")
    total_ms = sum(d["self_us"] for d in table) / steps / 1e3
    print(f"sum of top-{args.top} ≈ {total_ms:.1f} ms/step "
          "(sanity vs measured step time)")
    print(f"\ntop-{args.top} individual op instances:")
    for d in instances:
        print(f"{d['self_us']:>10.0f} us  n={d['n']:<6} {d['category']:<20} "
              f"{str(d['op'])[:70]}")
    for d in table + instances:
        d["ms_per_step"] = round(d["self_us"] / steps / 1e3, 3)
    if args.out:
        with open(args.out, "w") as f:
            json.dump({"bs": args.bs, "k": args.k, "sub": args.sub,
                       "steps_traced": steps,
                       "note": "device self time per HLO-op family; "
                               "control-flow container ops (while/"
                               "conditional/call = sum of children) "
                               "are excluded",
                       "table": table, "instances": instances}, f, indent=2)
        print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
