"""Device-side augmentation tests (ops/augment.py) — semantics parity with
the host numpy pipeline (data/cifar.py) and the raw-uint8 train-step path."""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_resnet_tensorflow_tpu.data import cifar_iterator, standardize
from distributed_resnet_tensorflow_tpu.ops import augment


def test_standardize_matches_host():
    """Device standardize == host standardize (same TF adjusted-std math)."""
    rng = np.random.RandomState(0)
    imgs = rng.randint(0, 256, (4, 32, 32, 3)).astype(np.uint8)
    host = standardize(imgs)
    dev = np.asarray(augment.standardize(jnp.asarray(imgs)))
    np.testing.assert_allclose(dev, host, rtol=1e-5, atol=1e-5)


def test_standardize_low_variance_uses_adjusted_std():
    """Constant image: std=0 → divide by 1/sqrt(N), not by zero."""
    imgs = np.full((1, 32, 32, 3), 7, np.uint8)
    out = np.asarray(augment.standardize(jnp.asarray(imgs)))
    assert np.all(np.isfinite(out))
    np.testing.assert_allclose(out, 0.0, atol=1e-6)


def test_random_crop_flip_outputs_are_valid_windows():
    """Every augmented image must be a 32×32 window of the padded original,
    possibly horizontally flipped. A per-pixel ramp makes windows unique."""
    h = w = 32
    base = (np.arange(h * w * 3, dtype=np.float32).reshape(h, w, 3) % 251)
    imgs = np.stack([base] * 8)
    out = np.asarray(augment.random_crop_flip(
        jnp.asarray(imgs), jax.random.PRNGKey(0), pad=4))
    assert out.shape == imgs.shape
    padded = np.pad(imgs[0], ((4, 4), (4, 4), (0, 0)))
    windows = {}
    for y in range(9):
        for x in range(9):
            win = padded[y:y + h, x:x + w]
            windows[win.tobytes()] = (y, x, False)
            windows[win[:, ::-1].tobytes()] = (y, x, True)
    for i in range(8):
        assert out[i].tobytes() in windows, f"image {i} is not a valid crop"


def test_random_crop_flip_varies_across_batch():
    base = np.arange(32 * 32 * 3, dtype=np.float32).reshape(32, 32, 3)
    imgs = np.stack([base] * 16)
    out = np.asarray(augment.random_crop_flip(
        jnp.asarray(imgs), jax.random.PRNGKey(1)))
    # with 162 possible (crop, flip) outcomes, 16 identical draws ~ impossible
    assert len({out[i].tobytes() for i in range(16)}) > 1


def test_cifar_train_augment_deterministic_in_key():
    rng = np.random.RandomState(2)
    imgs = jnp.asarray(rng.randint(0, 256, (4, 32, 32, 3)).astype(np.uint8))
    a = augment.cifar_train_augment(imgs, jax.random.PRNGKey(5))
    b = augment.cifar_train_augment(imgs, jax.random.PRNGKey(5))
    c = augment.cifar_train_augment(imgs, jax.random.PRNGKey(6))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.array_equal(np.asarray(a), np.asarray(c))
    assert a.dtype == jnp.float32


def _write_fake_cifar10(tmp_path, n_per_file=20):
    rng = np.random.RandomState(0)
    for name in [f"data_batch_{i}.bin" for i in range(1, 6)] + ["test_batch.bin"]:
        recs = np.zeros((n_per_file, 1 + 3072), np.uint8)
        recs[:, 0] = rng.randint(0, 10, n_per_file)
        recs[:, 1:] = rng.randint(0, 256, (n_per_file, 3072))
        recs.tofile(os.path.join(tmp_path, name))
    return str(tmp_path)


@pytest.mark.heavy
def test_raw_iterator_and_device_augment_train_step(tmp_path):
    """End-to-end: device_augment=on makes the iterator yield raw uint8 and
    the Trainer augment + standardize inside the jitted step."""
    from distributed_resnet_tensorflow_tpu.data import (
        create_input_iterator, device_augment_enabled)
    from distributed_resnet_tensorflow_tpu.train import Trainer
    from distributed_resnet_tensorflow_tpu.utils.config import get_preset

    d = _write_fake_cifar10(tmp_path)
    cfg = get_preset("smoke")
    cfg.model.compute_dtype = "float32"
    cfg.model.resnet_size = 8
    cfg.data.dataset = "cifar10"
    cfg.data.data_dir = d
    cfg.data.device_augment = "on"
    cfg.data.prefetch_batches = 0
    cfg.train.batch_size = 16
    assert device_augment_enabled(cfg, "train")
    assert not device_augment_enabled(cfg, "eval")

    it = create_input_iterator(cfg, mode="train")
    batch = next(it)
    assert batch["images"].dtype == np.uint8  # host did NOT standardize

    tr = Trainer(cfg)
    tr.init_state()
    state, m = tr.train(it, num_steps=2)
    assert int(state.step) == 2
    assert np.isfinite(float(m["loss"]))


@pytest.mark.slow  # re-tiered out of the 870s tier-1; runs in the full (unfiltered) suite
@pytest.mark.heavy
def test_device_dataset_matches_streamed_path(tmp_path):
    """HBM-resident dataset + index batches == streamed raw-uint8 batches:
    same permutation (same seed), same device augmentation (rng is
    step-keyed), so parameter trajectories must be identical. Covers both
    the K=1 index step and the fused index scan."""
    import jax
    from distributed_resnet_tensorflow_tpu.data import (
        create_input_iterator, epoch_index_iterator, load_cifar)
    from distributed_resnet_tensorflow_tpu.train import Trainer
    from distributed_resnet_tensorflow_tpu.utils.config import get_preset

    d = _write_fake_cifar10(tmp_path)

    def base_cfg():
        cfg = get_preset("smoke")
        cfg.model.compute_dtype = "float32"
        cfg.model.resnet_size = 8
        cfg.data.dataset = "cifar10"
        cfg.data.data_dir = d
        cfg.data.prefetch_batches = 0
        cfg.train.batch_size = 16
        cfg.train.seed = 7
        return cfg

    # A: streamed raw uint8 batches, host shuffles, device augments
    cfg_a = base_cfg()
    cfg_a.data.device_augment = "on"
    cfg_a.data.device_dataset = "off"
    tr_a = Trainer(cfg_a)
    tr_a.init_state(seed=0)
    tr_a.train(create_input_iterator(cfg_a, mode="train"), num_steps=6)

    # B: dataset in (virtual) HBM, index batches — must be EXACTLY the same
    # trajectory (same permutation, same step-keyed augment rng)
    cfg_b = base_cfg()
    cfg_b.data.device_dataset = "on"
    tr_b = Trainer(cfg_b)
    tr_b.init_state(seed=0)
    images, labels = load_cifar("cifar10", d, "train")
    tr_b.attach_device_dataset(images, labels)
    it = epoch_index_iterator(len(labels), 16, seed=7)
    tr_b.train(it, num_steps=6)

    assert int(tr_a.state.step) == int(tr_b.state.step) == 6
    for a, b in zip(jax.tree_util.tree_leaves(tr_a.state.params),
                    jax.tree_util.tree_leaves(tr_b.state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # fused index scan (k=3) + unfused tail: runs, advances, stays finite
    # (scan-vs-single numeric equivalence is covered exactly by
    # test_train.test_steps_per_loop_matches_sequential on the BN-free model;
    # with BN the compiled-program difference legitimately perturbs bits)
    cfg_c = base_cfg()
    cfg_c.data.device_dataset = "on"
    cfg_c.train.steps_per_loop = 3
    tr_c = Trainer(cfg_c)
    tr_c.init_state(seed=0)
    tr_c.attach_device_dataset(images, labels)
    state, m = tr_c.train(epoch_index_iterator(len(labels), 16, seed=7),
                          num_steps=7)
    assert int(state.step) == 7
    assert np.isfinite(float(m["loss"]))


def test_epoch_index_iterator_covers_epoch_without_repeats():
    from distributed_resnet_tensorflow_tpu.data import epoch_index_iterator
    it = epoch_index_iterator(50, 16, seed=0)
    first_epoch = [next(it)["idx"] for _ in range(3)]  # 48 of 50, partial dropped
    flat = np.concatenate(first_epoch)
    assert len(set(flat.tolist())) == 48  # no repeats within the epoch
    assert all(b.dtype == np.int32 and b.shape == (16,) for b in first_epoch)


def test_device_augment_off_yields_float(tmp_path):
    from distributed_resnet_tensorflow_tpu.data import create_input_iterator
    from distributed_resnet_tensorflow_tpu.utils.config import get_preset

    d = _write_fake_cifar10(tmp_path)
    cfg = get_preset("smoke")
    cfg.data.dataset = "cifar10"
    cfg.data.data_dir = d
    cfg.data.device_augment = "off"
    cfg.data.prefetch_batches = 0
    cfg.train.batch_size = 16
    batch = next(create_input_iterator(cfg, mode="train"))
    assert batch["images"].dtype == np.float32  # host standardized
