#!/bin/bash
# Chaos smoke — run the fault-injection suite (resilience/faultinject.py):
# signal delivery mid-run, torn/bit-rotted checkpoints, injected NaN loss,
# plus the watchdog cases (killed peer, frozen peer, straggler —
# tests/test_watchdog.py + the subprocess kill-and-detect tests in
# tests/test_resilience.py). Everything runs on the fake-CPU mesh
# (tests/conftest.py) — no accelerator needed.
#
#   scripts/chaos_smoke.sh            # the FULL chaos set (incl. the
#                                     # slow-tier multi-process subprocess
#                                     # kill/freeze tests — ~minutes of real
#                                     # training children)
#   scripts/chaos_smoke.sh --fast     # seconds-fast pre-merge gate:
#                                     # shardcheck + -m "not slow and not heavy"
#   scripts/chaos_smoke.sh --elastic  # elastic-mesh e2e only: freeze one of
#                                     # four workers; assert shrink->grow with
#                                     # rc=0 and NO exit-75 (docs/resilience.md)
#   scripts/chaos_smoke.sh -k nan     # just the NaN-recovery cases
#
# NOTE: the subprocess/watchdog chaos tests are marked `slow` (tier-1 of
# the main suite excludes them for the 870 s budget) — this script is
# where they run, so the default mode deliberately applies NO marker
# filter over the two chaos test files.
set -euo pipefail
cd "$(dirname "$0")/.."

MARK_ARGS=()
if [[ "${1:-}" == "--fast" ]]; then
  MARK_ARGS=(-m "not slow and not heavy")
  shift
  # the fast pre-merge gate also runs shardcheck (lint + static
  # elaboration + hangcheck's collective-schedule/thread/lock passes,
  # scripts/analysis_gate.sh): spec/config/invariant/hang bugs should
  # die here, in seconds, not on the cluster. ANALYSIS_GATE_ARGS
  # passes through (e.g. --no-hangcheck, mirroring --no-zero1-sweep)
  scripts/analysis_gate.sh ${ANALYSIS_GATE_ARGS:-}
  # opt-in observability stage (OBS_SMOKE=1): the slow-peer perf-anomaly
  # + trace-merge + comm-report end-to-end (scripts/obs_smoke.sh, ~2 min
  # of live 2-process training — too heavy for the default seconds-fast
  # gate, which is why it is opt-in)
  if [[ "${OBS_SMOKE:-0}" == "1" ]]; then
    scripts/obs_smoke.sh
  fi
  # gate-adjacent overlap family sweep (OVERLAP_SWEEP=0 opts out): the
  # bench --overlap-ab family legs (conv dp / vit dp_tp / moe dp_pp_ep /
  # conv accum=4) on the virtual 8-device mesh — a regression in any
  # newly in-envelope exchange (a leg erroring, wire bytes no longer 1×
  # per step under accumulation) surfaces pre-submit instead of on a
  # cluster. ~2-3 min CPU; the result JSON is printed for the log.
  if [[ "${OVERLAP_SWEEP:-1}" == "1" ]]; then
    env JAX_PLATFORMS=cpu \
        XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8" \
        python bench.py --overlap-ab | tail -1 | python -c '
import json, sys
d = json.loads(sys.stdin.read())
fams = d["families"]
bad = [k for k, v in fams.items()
       if "error" in v.get("on", {}) or "error" in v.get("off", {})]
accum = fams["conv_dp_accum4"]["on"]
assert not bad, f"overlap family legs failed: {bad}: {fams}"
# wire per optimizer step must equal the gradient bytes ONCE (grad_bytes
# is recorded independently from the leaf sizes) — the 1x-per-step
# contract; the static witness that no per-microbatch exchange sneaks
# back in is the overlap+accumN hangcheck schedule in the gate above
assert accum["accum_steps"] == 4 and \
    accum["wire_bytes_per_step"] == accum["grad_bytes"], accum
# hierarchical A/B leg (ISSUE 18): the staged exchange must trace on the
# factored virtual mesh (2 "hosts" x 4 devices) and its inter-tier wire
# must drop to ~1/4 of the flat leg (pad-tolerant 3x bound)
hier = d["hierarchy"]
assert "error" not in hier, f"hierarchy leg failed: {hier}"
assert hier["intra_k"] == 4, hier
assert hier["inter_wire_bytes"] * 3 < hier["flat_inter_wire_bytes"], hier
print("overlap family sweep OK:",
      {k: v.get("on_vs_off") for k, v in fams.items()})
print("hierarchy leg OK:",
      {k: hier[k] for k in ("intra_k", "inter_wire_bytes",
                            "flat_inter_wire_bytes", "hier_vs_flat_steps")})
print(json.dumps(fams))
'
  fi
fi

if [[ "${1:-}" == "--elastic" ]]; then
  shift
  # Elastic-mesh smoke (docs/resilience.md): freeze one of FOUR workers
  # mid-training. The frozen worker's own watchdog exits it 75 (hang in the
  # host-local 'data' phase); the survivors defer their collective-hang
  # exits, attribute the peer loss, and shrink into a 3-host generation
  # restored from the last committed step; the supervisor's respawned
  # rejoiner grows the mesh back to 4 hosts; the run completes rc=0 — the
  # exit-75 requeue contract is now the FALLBACK, not the outcome.
  TROOT=$(mktemp -d)
  trap 'rm -rf "$TROOT"' EXIT
  PORT=$((20000 + RANDOM % 20000))
  set +e
  timeout -k 10 420 env JAX_PLATFORMS=cpu DRT_FAULT_FREEZE_AT_BATCH="3:8" \
    python -m distributed_resnet_tensorflow_tpu.launch \
    --num_processes 4 --devices_per_process 1 --port "$PORT" \
    --elastic --max_respawns 2 --respawn_delay_secs 2 -- \
    --preset smoke \
    --set model.name=logistic --set model.input_size=192 \
    --set model.num_classes=10 --set data.image_size=8 \
    --set train.batch_size=16 --set train.train_steps=60 \
    --set train.log_every_steps=5 --set "log_root=$TROOT" \
    --set checkpoint.save_every_steps=5 --set checkpoint.save_every_secs=0 \
    --set resilience.elastic.enabled=on \
    --set resilience.elastic.settle_secs=1 \
    --set resilience.watchdog.enabled=on \
    --set resilience.watchdog.interval_secs=0.2 \
    --set resilience.watchdog.peer_timeout_secs=5 \
    --set resilience.watchdog.min_step_timeout_secs=3 \
    --set resilience.watchdog.grace_secs=1
  rc=$?
  set -e
  if [[ $rc -ne 0 ]]; then
    echo "chaos_smoke --elastic: run exited $rc, expected 0 (no requeue)" >&2
    exit 1
  fi
  python - "$TROOT/train/metrics.jsonl" <<'PY'
import json, sys
rows = [json.loads(l) for l in open(sys.argv[1]) if l.strip()]
gens = {r["generation"] for r in rows if r.get("event") == "mesh_generation"}
reshards = [r for r in rows if r.get("event") == "reshard"]
reasons = {r["reason"] for r in reshards}
assert {0, 1, 2} <= gens, f"expected generations 0,1,2, saw {gens}"
assert "peer_lost" in reasons and "grow" in reasons, reasons
shrink = next(r for r in reshards if r["reason"] == "peer_lost")
grow = next(r for r in reshards if r["reason"] == "grow")
assert (shrink["old_hosts"], shrink["new_hosts"]) == (4, 3), shrink
assert (grow["old_hosts"], grow["new_hosts"]) == (3, 4), grow
assert shrink["restore_step"] >= 0, "shrink restarted instead of resuming"
print("elastic smoke: shrink restored step", shrink["restore_step"],
      "-> grow live at generation", grow["generation"])
PY
  # protocol trace conformance (analysis/protocol/): the reshard /
  # mesh_generation rows this chaos run recorded must replay cleanly
  # against the declared elastic-reshard-barrier spec, and the seeded
  # illegal-edge self-test proves the witness can actually fail
  env JAX_PLATFORMS=cpu python -m \
    distributed_resnet_tensorflow_tpu.analysis.protocol.conformance \
    "$TROOT/train/metrics.jsonl"
  env JAX_PLATFORMS=cpu python -m \
    distributed_resnet_tensorflow_tpu.analysis.protocol.conformance \
    --self-test-illegal-edge "$TROOT/train/metrics.jsonl"
  echo "chaos_smoke: elastic shrink->grow verified (rc=0, no exit-75," \
       "protocol trace conformant)"
  exit 0
fi

# ${arr[@]+...} form: bash <4.4 trips set -u on expanding an empty array
env JAX_PLATFORMS=cpu python -m pytest \
  tests/test_resilience.py tests/test_watchdog.py -q \
  ${MARK_ARGS[@]+"${MARK_ARGS[@]}"} -p no:cacheprovider "$@"

if [[ ${#MARK_ARGS[@]} -gt 0 ]]; then
  exit 0  # --fast gate: the flight-recorder e2e below is full-mode only
fi

# Flight-recorder smoke (docs/observability.md): freeze one of two live
# workers mid-training (the faultinject env knob) and assert the watchdog
# escalation leaves an AUTOMATIC trace dump — a trace*.json under
# <log_root>/telemetry plus a {"event": "trace_dump"} row in the chief's
# metrics — and the run still exits resumable (75).
TROOT=$(mktemp -d)
trap 'rm -rf "$TROOT"' EXIT
PORT=$((20000 + RANDOM % 20000))
set +e
timeout -k 10 240 env JAX_PLATFORMS=cpu DRT_FAULT_FREEZE_AT_BATCH="1:5" \
  python -m distributed_resnet_tensorflow_tpu.launch \
  --num_processes 2 --devices_per_process 1 --port "$PORT" -- \
  --preset smoke \
  --set model.name=logistic --set model.input_size=192 \
  --set model.num_classes=10 --set data.image_size=8 \
  --set train.batch_size=16 --set train.train_steps=100000 \
  --set train.log_every_steps=1000 --set "log_root=$TROOT" \
  --set checkpoint.save_every_steps=0 --set checkpoint.save_every_secs=0 \
  --set resilience.watchdog.enabled=on \
  --set resilience.watchdog.interval_secs=0.2 \
  --set resilience.watchdog.peer_timeout_secs=5 \
  --set resilience.watchdog.min_step_timeout_secs=3 \
  --set resilience.watchdog.grace_secs=1
rc=$?
set -e
if [[ $rc -ne 75 ]]; then
  echo "chaos_smoke: frozen-peer run exited $rc, expected resumable 75" >&2
  exit 1
fi
if ! ls "$TROOT"/telemetry/trace*.json >/dev/null 2>&1; then
  echo "chaos_smoke: no flight-recorder trace*.json under $TROOT/telemetry" >&2
  exit 1
fi
python - "$TROOT/telemetry" <<'PY'
import glob, json, sys
paths = glob.glob(sys.argv[1] + "/trace*.json")
doc = json.load(open(paths[0]))
assert doc["traceEvents"], "trace dump holds no events"
assert doc["otherData"]["span_schema_version"] >= 1
PY
if ! grep -q '"event": "trace_dump"' "$TROOT"/train/metrics.jsonl; then
  echo "chaos_smoke: no trace_dump event row in the chief's metrics" >&2
  exit 1
fi
echo "chaos_smoke: frozen-peer flight-recorder dump verified"
