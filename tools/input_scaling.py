"""Input-pipeline scaling harness (VERDICT r3 #5).

Measures, on ImageNet-format JPEG TFRecord shards:
  * the single-stream feeder ceiling (TFRecord read + CRC + Example parse,
    no decode) — python reader vs the native C++ prefetcher;
  * decoded img/s at 1/2/4 decode workers, thread pool vs process pool
    (``decode_processes``), PIL vs the native fused transform;
  * the multi-process sharded aggregate (P independent iterator processes,
    each reading files[p::P] — the multi-host deployment shape).

On this 1-core box the expected curve is FLAT (one core executes every
worker); the point of the artifact is (a) the per-worker overhead — a
drop at 2/4 workers would expose queue serialization the round-3 README
extrapolation ("~10 cores cover the chip") silently assumed away — and
(b) the measured feeder ceiling, which bounds any thread count.

    python tools/input_scaling.py   # writes docs/input_scaling_r4.json
"""
from __future__ import annotations

import json
import multiprocessing as mp
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))
OUT = os.path.join(REPO, "docs", "input_scaling_r4.json")


def synth_dir(n_images=512):
    import tempfile
    from make_synth_imagenet import write_split
    d = os.path.join(tempfile.gettempdir(), "drt_scaling_imagenet")
    if not os.path.exists(os.path.join(d, "train-00007-of-00008")):
        os.makedirs(d, exist_ok=True)
        write_split(d, "train", 8, 8, num_classes=16,
                    per_class=n_images // 16, seed=0)
    return d


def feeder_rate(d, use_native, n=400):
    """Records/s of the raw (read + CRC + parse) stream, decode excluded."""
    from distributed_resnet_tensorflow_tpu.data.imagenet import (
        dataset_filenames, _example_to_sample)
    from distributed_resnet_tensorflow_tpu.data.tfrecord import (
        parse_example, read_tfrecords)
    files = dataset_filenames(d, "train")

    def stream():
        if use_native:
            from distributed_resnet_tensorflow_tpu.data.native_loader import (
                NativePrefetcher)
            while True:
                pf = NativePrefetcher(files, num_threads=4)
                yield from pf
                pf.close()
        else:
            while True:
                for f in files:
                    yield from read_tfrecords(f)

    it = stream()
    for _ in range(50):  # warm
        next(it)
    t0 = time.perf_counter()
    for _ in range(n):
        _example_to_sample(parse_example(next(it)))
    return round(n / (time.perf_counter() - t0), 1)


def decode_rate(d, workers, processes, use_native, batches=8, bs=64):
    from distributed_resnet_tensorflow_tpu.data.imagenet import (
        imagenet_iterator)
    it = imagenet_iterator(
        d, bs, "train", image_size=224, shuffle_buffer=64,
        num_decode_threads=0 if processes else workers,
        decode_processes=workers if processes else 0,
        use_native=use_native, device_standardize=True)
    next(it)  # warm the pool
    t0 = time.perf_counter()
    for _ in range(batches):
        next(it)
    return round(bs * batches / (time.perf_counter() - t0), 1)


def _shard_worker(d, p, num_shards, bs, batches, q):
    from distributed_resnet_tensorflow_tpu.data.imagenet import (
        imagenet_iterator)
    it = imagenet_iterator(d, bs, "train", image_size=224, shuffle_buffer=64,
                           shard_index=p, num_shards=num_shards,
                           num_decode_threads=2, device_standardize=True)
    next(it)
    t0 = time.perf_counter()
    for _ in range(batches):
        next(it)
    q.put(bs * batches / (time.perf_counter() - t0))


def sharded_aggregate(d, num_shards, bs=32, batches=6):
    """P independent full-pipeline processes over disjoint file shards —
    the multi-host shape (one iterator per host feeding its own chip)."""
    ctx = mp.get_context("fork")
    q = ctx.Queue()
    procs = [ctx.Process(target=_shard_worker,
                         args=(d, p, num_shards, bs, batches, q))
             for p in range(num_shards)]
    for p in procs:
        p.start()
    rates = [q.get() for _ in procs]
    for p in procs:
        p.join()
    return round(sum(rates), 1)


def _one_point(d, label, workers):
    """Executed in a FRESH subprocess per grid point: forking worker
    processes from an interpreter that already ran thread-pool iterators
    (live daemon feeder/decoder threads) can inherit a held lock and
    deadlock the child — each measurement gets a thread-free parent."""
    procs = label.startswith("processes")
    native = label.endswith("native")
    print(decode_rate(d, workers, procs, native))


def main():
    import subprocess
    d = synth_dir()
    from distributed_resnet_tensorflow_tpu.data.native_loader import (
        native_available, native_jpeg_available)
    out = {"host_cores": os.cpu_count(),
           "native_reader": bool(native_available()),
           "native_jpeg": bool(native_jpeg_available())}
    out["feeder_records_per_sec"] = {
        "python_reader": feeder_rate(d, False),
    }
    if out["native_reader"]:
        out["feeder_records_per_sec"]["native_prefetcher"] = feeder_rate(
            d, True)
    for label, native in (("threads_pil", False),
                          ("threads_native", True),
                          ("processes_pil", False),
                          ("processes_native", True)):
        if native and not out["native_jpeg"]:
            continue
        row = {}
        for w in (1, 2, 4):
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--point", label, str(w)],
                capture_output=True, text=True, timeout=300)
            row[w] = float(r.stdout.strip().splitlines()[-1]) \
                if r.returncode == 0 and r.stdout.strip() else None
        out[label] = row
        print(label, row, flush=True)
    out["sharded_aggregate_img_per_sec"] = {
        p: sharded_aggregate(d, p) for p in (1, 2)}
    print("sharded", out["sharded_aggregate_img_per_sec"], flush=True)
    with open(OUT, "w") as f:
        json.dump(out, f, indent=2)
    print(f"wrote {OUT}")


if __name__ == "__main__":
    if len(sys.argv) > 2 and sys.argv[1] == "--point":
        _one_point(synth_dir(), sys.argv[2], int(sys.argv[3]))
    else:
        main()
