"""Protocol model checker tests (ISSUE 20 tentpole): every declared spec
checks clean exhaustively, the committed artifact is byte-identical to a
fresh run, every seeded mutation produces a counterexample action
schedule anchored at the spec registration's file:line, the runtime
trace replayer accepts legal rows and flags each class of illegal row at
its line, and the protocol-drift lint rule catches spec/implementation
divergence."""
import json
import os

import pytest

from distributed_resnet_tensorflow_tpu.analysis.protocol import (
    artifact_path, check_model, check_rows, check_stream, load_specs,
    run_protocol, write_artifact)

PKG = "distributed_resnet_tensorflow_tpu"

#: spec name -> (seeded mutation, violated invariant, action that must
#: appear in the counterexample schedule)
MUTATION_LEGS = {
    "elastic-reshard-barrier": (
        "blind_commit_overwrite", "at_most_one_commit_per_round",
        "commit_round"),
    "ckpt-sharded-commit": (
        "skip_marker_wait", "committed_step_has_all_done_markers",
        "finalize_rename"),
    "replica-health-replace": (
        "illegal_health_edge", "dead_to_ready_only_via_replace_ladder",
        "zombie_revive"),
    "canary-swap-pin": (
        "apply_unpinned", "pinned_replica_never_applies_unpinned_commit",
        "swap_poll"),
}


def _specs_by_name():
    return {spec.name: spec for spec in load_specs()}


# ---------------------------------------------------------------------------
# exhaustive check: clean models, determinism, artifact byte-identity
# ---------------------------------------------------------------------------

def test_all_declared_specs_check_clean():
    specs = _specs_by_name()
    assert set(specs) == set(MUTATION_LEGS)
    for spec in specs.values():
        findings, stats = check_model(spec)
        assert findings == [], [str(f) for f in findings]
        assert stats["states"] > 1 and stats["transitions"] > 0
        assert not stats["truncated"]
        assert stats["fingerprint"].startswith("sha256:")
        # the ISSUE 20 contract: >=1 safety and >=1 liveness per protocol
        assert spec.safety_names(), spec.name
        assert spec.liveness_names(), spec.name


def test_run_protocol_is_deterministic():
    f1, doc1 = run_protocol()
    f2, doc2 = run_protocol()
    assert f1 == [] and f2 == []
    assert doc1 == doc2
    assert doc1["schema_version"] == 1
    assert set(doc1["specs"]) == set(MUTATION_LEGS)


def test_committed_artifact_matches_fresh_run(tmp_path):
    """analysis/protocol_models.json is the gate-refreshed inventory —
    a fresh exhaustive run must reproduce it byte-for-byte."""
    _, doc = run_protocol()
    fresh = str(tmp_path / "fresh.json")
    write_artifact(doc, fresh)
    assert open(fresh, "rb").read() == open(artifact_path(), "rb").read()
    committed = json.load(open(artifact_path()))
    for name, entry in committed["specs"].items():
        assert entry["declared_at"].count(":") == 1
        rel, line = entry["declared_at"].split(":")
        assert os.path.exists(os.path.join(
            os.path.dirname(artifact_path()), "..", "..", rel)), rel
        assert int(line) > 0


# ---------------------------------------------------------------------------
# seeded mutations: the checker catches the bug class each guard prevents
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", sorted(MUTATION_LEGS))
def test_seeded_mutation_yields_counterexample_at_spec_site(name):
    spec = _specs_by_name()[name]
    mutation, invariant, schedule_action = MUTATION_LEGS[name]
    findings, stats = check_model(spec, frozenset({mutation}))
    hits = [f for f in findings if invariant in f.message]
    assert hits, [str(f) for f in findings]
    f = hits[0]
    # anchored at the registration site in the implementation module
    assert (f.path, f.line) == (spec.path, spec.line)
    assert f.path.endswith(".py") and f.path.startswith(PKG)
    # the counterexample is a concrete action schedule featuring the
    # weakened guard's action
    assert schedule_action in f.message
    assert "schedule:" in f.detail and "final state:" in f.detail


def test_unknown_mutation_is_rejected():
    spec = _specs_by_name()["elastic-reshard-barrier"]
    with pytest.raises(ValueError, match="unknown mutation"):
        check_model(spec, frozenset({"not_a_mutation"}))


# ---------------------------------------------------------------------------
# trace conformance: legal rows replay clean, each illegal class flagged
# ---------------------------------------------------------------------------

def _h(line, frm, to, reason, replica=0):
    return (line, {"event": "replica_health", "replica": replica,
                   "from": frm, "to": to, "reason": reason})


def test_conformance_accepts_legal_health_and_ladder_rows():
    rows = [
        _h(1, "warming", "ready", "probe_ok"),
        _h(2, "ready", "suspect", "failures"),
        _h(3, "suspect", "ready", "recovered"),
        _h(4, "ready", "dead", "beat_stale"),
        (5, {"event": "replica_replace", "action": "kill",
             "replica": 0, "reason": "wedged"}),
        (6, {"event": "replica_replace", "action": "respawn",
             "replica": 0}),
        (7, {"event": "replica_replace", "action": "readmit",
             "replica": 0}),
        _h(8, "dead", "warming", "readmit"),
        _h(9, "warming", "ready", "probe_ok"),
    ]
    assert check_rows(rows) == []


def test_conformance_flags_illegal_health_edge_and_chain_break():
    findings = check_rows([_h(3, "dead", "ready", "probe_ok")])
    assert [f.line for f in findings] == [3]
    assert "undeclared replica_health edge" in findings[0].message
    # chain break: the row leaves a state the replica never landed in
    findings = check_rows([
        _h(1, "warming", "ready", "probe_ok"),
        _h(2, "suspect", "dead", "failures"),
    ])
    assert [f.line for f in findings] == [2]
    assert "chain break" in findings[0].message


def test_conformance_flags_ladder_violations():
    # respawn with no preceding kill
    findings = check_rows([(4, {"event": "replica_replace",
                                "action": "respawn", "replica": 1})])
    assert [f.line for f in findings] == [4]
    assert "ladder violation" in findings[0].message
    # anything after gave_up (the ladder is terminal)
    findings = check_rows([
        (1, {"event": "replica_replace", "action": "gave_up",
             "replica": 1, "reason": "dead"}),
        (2, {"event": "replica_replace", "action": "kill",
             "replica": 1, "reason": "dead"}),
    ])
    assert [f.line for f in findings] == [2]
    assert "after gave_up" in findings[0].message


def test_conformance_flags_canary_discipline():
    # rollback without a start
    findings = check_rows([(7, {"event": "canary", "action": "rollback",
                                "step": 100,
                                "reason": "p99_regression"})])
    assert [f.line for f in findings] == [7]
    assert "without a preceding start" in findings[0].message
    # the single-replica promote is the one declared exemption
    assert check_rows([(1, {"event": "canary", "action": "promote",
                            "step": 100,
                            "reason": "single_replica"})]) == []
    # two concurrent canaries
    findings = check_rows([
        (1, {"event": "canary", "action": "start", "step": 100}),
        (2, {"event": "canary", "action": "start", "step": 200}),
    ])
    assert [f.line for f in findings] == [2]
    assert "one canary at a time" in findings[0].message


def test_conformance_flags_generation_and_commit_monotonicity():
    findings = check_rows([
        (1, {"event": "mesh_generation", "generation": 2}),
        (2, {"event": "mesh_generation", "generation": 1}),
    ])
    assert [f.line for f in findings] == [2]
    assert "only ever advance" in findings[0].message
    findings = check_rows([(3, {"event": "reshard", "reason": "peer_lost",
                                "old_hosts": 2, "new_hosts": 2,
                                "generation": 1})])
    assert [f.line for f in findings] == [3]
    assert "must shrink" in findings[0].message
    findings = check_rows([
        (1, {"event": "ckpt_shard", "process": 0,
             "last_committed_step": 50}),
        (2, {"event": "ckpt_shard", "process": 0,
             "last_committed_step": 40}),
    ])
    assert [f.line for f in findings] == [2]
    assert "never un-commits" in findings[0].message


def test_conformance_stream_spans_rotation_and_skips_torn_lines(tmp_path):
    """A protocol round split across a rotation replays whole (the .1
    segment is prepended), and a torn mid-write line is skipped the way
    the monitor skips it."""
    stream = tmp_path / "metrics.jsonl"
    rot = tmp_path / "metrics.jsonl.1"
    rot.write_text(
        json.dumps({"event": "replica_health", "replica": 0,
                    "from": "warming", "to": "ready",
                    "reason": "probe_ok"}) + "\n"
        + json.dumps({"event": "canary", "action": "start",
                      "step": 100}) + "\n")
    stream.write_text(
        json.dumps({"event": "canary", "action": "promote", "step": 100,
                    "reason": "promoted"}) + "\n"
        + '{"event": "replica_health", "replica": 0, "fr')  # torn tail
    assert check_stream(str(stream)) == []
    # WITHOUT the rotated segment the promote has no start -> finding
    rot.unlink()
    findings = check_stream(str(stream))
    assert findings and "without a preceding start" in findings[0].message


def test_conformance_cli_self_test_catches_seeded_edge(tmp_path, capsys):
    from distributed_resnet_tensorflow_tpu.analysis.protocol import (
        conformance)
    stream = tmp_path / "metrics.jsonl"
    stream.write_text(json.dumps(
        {"event": "replica_health", "replica": 0, "from": "warming",
         "to": "ready", "reason": "probe_ok"}) + "\n")
    assert conformance.main([str(stream)]) == 0
    assert conformance.main(["--self-test-illegal-edge",
                             str(stream)]) == 0
    assert "seeded illegal edge caught" in capsys.readouterr().out
    # a genuinely dirty stream exits nonzero with file:line
    stream.write_text(json.dumps(
        {"event": "replica_health", "replica": 0, "from": "dead",
         "to": "ready", "reason": "probe_ok"}) + "\n")
    assert conformance.main([str(stream)]) == 1


# ---------------------------------------------------------------------------
# protocol-drift lint rule
# ---------------------------------------------------------------------------

def test_protocol_drift_clean_on_real_tree():
    from distributed_resnet_tensorflow_tpu.analysis.lint import (
        build_context)
    from distributed_resnet_tensorflow_tpu.analysis.rules import (
        protocol_drift)
    findings = list(protocol_drift.check(build_context()))
    assert findings == [], [str(f) for f in findings]


def _drifted_spec(path):
    from distributed_resnet_tensorflow_tpu.analysis.protocol.spec import (
        ProtocolSpec)
    return ProtocolSpec(
        name="drifted", title="seeded drift", path=path, line=7,
        modules=(path, os.path.join(PKG, "serve", "gone.py")),
        bounds={}, model=lambda m: None,
        literals={"no_such_literal_anywhere_9f3": "renamed away"},
        event_edges={"not_an_event": {}},
        enum_checks=(("canary", "action", ("start", "promote")),))


def test_protocol_drift_fires_on_seeded_divergence(monkeypatch):
    from distributed_resnet_tensorflow_tpu.analysis.lint import (
        build_context)
    from distributed_resnet_tensorflow_tpu.analysis.protocol import spec \
        as spec_mod
    from distributed_resnet_tensorflow_tpu.analysis.rules import (
        protocol_drift)
    anchor = os.path.join(PKG, "serve", "fleet.py")
    monkeypatch.setattr(spec_mod, "_REGISTRY",
                        {"drifted": _drifted_spec(anchor)})
    monkeypatch.setattr(spec_mod, "_SPEC_MODULES", ())
    findings = list(protocol_drift.check(build_context()))
    msgs = "\n".join(f.message for f in findings)
    assert all((f.path, f.line) == (anchor, 7) for f in findings)
    assert "does not exist in the tree" in msgs          # orphaned module
    assert "appears in none of the modeled sources" in msgs  # dead literal
    assert "not declared in" in msgs                     # unknown event
    assert "enum drift" in msgs                          # enum mismatch


def test_check_cli_no_protocol_skips_the_rule(tmp_path, monkeypatch):
    """--no-protocol mirrors --no-hangcheck: the protocol-drift rule is
    excluded from the lint pass (and the model phase is skipped)."""
    from distributed_resnet_tensorflow_tpu.analysis.protocol import spec \
        as spec_mod
    from distributed_resnet_tensorflow_tpu.main import main
    pkg = tmp_path / PKG / "serve"
    pkg.mkdir(parents=True)
    (pkg / "fleetish.py").write_text("PROTOCOL = 'here'\n")
    anchor = os.path.join(PKG, "serve", "fleetish.py")
    monkeypatch.setattr(spec_mod, "_REGISTRY",
                        {"drifted": _drifted_spec(anchor)})
    monkeypatch.setattr(spec_mod, "_SPEC_MODULES", ())
    with pytest.raises(SystemExit) as e:
        main(["check", "--lint-only", "--root", str(tmp_path)])
    assert e.value.code == 1          # seeded drift fires...
    with pytest.raises(SystemExit) as e:
        main(["check", "--lint-only", "--no-protocol",
              "--root", str(tmp_path)])
    assert e.value.code == 0          # ...and is opted out cleanly
