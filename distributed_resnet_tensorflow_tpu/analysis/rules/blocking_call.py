"""untimed-blocking-call: loop/dispatch threads never park unbounded.

A ``queue.get()``, ``Event.wait()`` or ``Thread.join()`` with no timeout
on the train-loop or serve-dispatch thread turns ANY upstream death into
a silent permanent hang: the producer thread that crashed without
posting its sentinel leaves the consumer parked forever, the watchdog's
"stalled progress" verdict fires minutes later (if armed at all), and
the job burns its allocation until the SLURM limit. Bounded waits with a
liveness re-check turn the same failure into a loud error in seconds.

The rule roots at ``analysis/threads.LOOP_ROOTS`` (the train/eval loop
entries and the serve dispatch body) plus every spawn target registered
with the ``dispatch`` role, walks the resolved call graph, and flags any
reachable zero-argument ``.get()`` / ``.wait()`` / ``.join()`` (no
``timeout=``). Zero-arg is the discriminator: ``dict.get(k)``,
``str.join(xs)``, ``os.path.join(a, b)`` all carry arguments; the
blocking signatures bare of arguments are the queue/event/thread forms.

Regression notes (findings this rule surfaced on the real tree, fixed in
the same round it landed):

  * ``data/device_prefetch.threaded_iterator`` — the consumer's
    ``q.get()`` was untimed; a worker thread killed without posting its
    ``_STOP``/error sentinel (interpreter teardown, a hard crash in
    native decode) would park the train loop forever. Now a 5 s timed
    get that re-checks ``thread.is_alive()`` and raises loudly when the
    worker died silently.
  * ``data/imagenet.imagenet_iterator`` — the in-process decoder path's
    ``out_q.get()`` had the same shape (the PROCESS path already polled
    liveness); both paths now share the timed-get-plus-liveness idiom.
"""
from __future__ import annotations

import ast
from typing import Iterable

from ..report import Finding
from .. import threads as threads_mod
from ..callgraph import call_target, body_walk, get_callgraph

RULE_NAME = "untimed-blocking-call"
DOC = __doc__

_BLOCKING_ATTRS = ("get", "wait", "join")


def _untimed_blocking(call: ast.Call) -> bool:
    fn = call.func
    if not isinstance(fn, ast.Attribute) or fn.attr not in _BLOCKING_ATTRS:
        return False
    if any(kw.arg == "timeout" for kw in call.keywords):
        return False
    # positional timeouts: Event.wait(t) / join(t) / Queue.get(block, t).
    # A one-positional-arg .get(x) is almost always dict.get(key) — flag
    # it only when the arg is literally True (Queue.get(True) blocks
    # forever exactly like bare get()); same for get(block=True).
    if fn.attr == "get":
        for kw in call.keywords:
            if kw.arg == "block":
                return isinstance(kw.value, ast.Constant) and \
                    kw.value.value is True
        if call.args:
            return len(call.args) == 1 and \
                isinstance(call.args[0], ast.Constant) and \
                call.args[0].value is True
        return True
    return not call.args


def check(ctx) -> Iterable[Finding]:
    graph = get_callgraph(ctx)
    wanted = set(threads_mod.LOOP_ROOTS)
    roots = [key for key, fn in graph.funcs.items()
             if fn.short() in wanted]
    for spawn in threads_mod.iter_spawn_sites(ctx):
        if spawn.target is not None and \
                threads_mod.role_of(spawn.target) == \
                threads_mod.ROLE_DISPATCH:
            roots.append(spawn.target.key)
    for key in sorted(graph.reachable(roots)):
        fn = graph.funcs[key]
        for node in body_walk(fn.node):
            if isinstance(node, ast.Call) and _untimed_blocking(node):
                name, _ = call_target(node)
                yield Finding(
                    RULE_NAME, fn.rel, node.lineno,
                    f"untimed blocking .{name}() reachable from the "
                    "loop/dispatch thread — a dead producer parks this "
                    "thread forever; use a timed wait that re-checks "
                    "liveness and fails loudly "
                    "(docs/static_analysis.md hangcheck)")
