"""ViT model family tests — attention-based models through the same
Trainer/config path as the ResNets."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_resnet_tensorflow_tpu.models import VisionTransformer, create_model
from distributed_resnet_tensorflow_tpu.utils.config import ModelConfig, get_preset


def test_vit_shapes_and_dtype():
    model = VisionTransformer(num_classes=10, patch_size=4, dim=32, depth=2,
                              num_heads=2, dtype=jnp.float32)
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x)
    logits = model.apply(variables, x)
    assert logits.shape == (2, 10) and logits.dtype == jnp.float32


def test_vit_attention_impls_agree():
    """dense and blockwise attention give the same model output."""
    x = jnp.asarray(np.random.RandomState(0).randn(2, 16, 16, 3), jnp.float32)
    outs = []
    for impl in ("dense", "blockwise"):
        model = VisionTransformer(num_classes=4, patch_size=4, dim=32,
                                  depth=1, num_heads=2, dtype=jnp.float32,
                                  attention_impl=impl)
        variables = model.init(jax.random.PRNGKey(0), x)
        outs.append(np.asarray(model.apply(variables, x)))
    np.testing.assert_allclose(outs[0], outs[1], rtol=2e-5, atol=2e-5)


def test_vit_invalid_configs():
    x = jnp.zeros((1, 30, 30, 3))
    with pytest.raises(ValueError):
        VisionTransformer(patch_size=4).init(jax.random.PRNGKey(0), x)
    x2 = jnp.zeros((1, 32, 32, 3))
    with pytest.raises(ValueError):
        VisionTransformer(dim=30, num_heads=4).init(jax.random.PRNGKey(0), x2)


def test_vit_trains_through_trainer():
    from distributed_resnet_tensorflow_tpu.data import learnable_synthetic_iterator
    from distributed_resnet_tensorflow_tpu.train import Trainer
    cfg = get_preset("smoke")
    cfg.model.name = "vit"
    cfg.model.num_classes = 4
    cfg.model.compute_dtype = "float32"
    cfg.model.vit_dim = 32
    cfg.model.vit_depth = 1
    cfg.model.vit_heads = 2
    cfg.data.image_size = 8
    cfg.train.batch_size = 16
    cfg.optimizer.name = "adam"
    cfg.optimizer.schedule = "constant"
    cfg.optimizer.learning_rate = 1e-3
    cfg.optimizer.weight_decay = 0.0
    tr = Trainer(cfg)
    tr.init_state()
    it = learnable_synthetic_iterator(16, 8, 4, seed=2)
    losses = []
    from distributed_resnet_tensorflow_tpu.parallel import shard_batch
    step = tr.jitted_train_step()
    for _ in range(25):
        tr.state, m = step(tr.state, shard_batch(next(it), tr.mesh))
        losses.append(float(m["cross_entropy"]))
    assert np.mean(losses[-5:]) < np.mean(losses[:5])


def test_create_model_vit_factory():
    cfg = ModelConfig(name="vit", num_classes=10, compute_dtype="float32")
    m = create_model(cfg, "cifar10")
    assert isinstance(m, VisionTransformer)
