"""Elastic mesh: shrink/grow the job across MESH GENERATIONS instead of
requeue-and-restart.

The requeue loop (watchdog -> exit 75 -> supervisor/SLURM restart, PR 4)
pays a full job restart — scheduler round-trip, cluster re-init, input
warmup — for every single lost host, at the OLD topology. Horovod
(arXiv:1802.05799) famously has the same shape: a dead worker kills the
ring. This module makes peer loss a RESHARD instead:

  generation g (N hosts)
      │  peer-loss verdict (resilience/watchdog.py) surfaces as a
      │  gloo/collective error on the survivors' main threads
      ▼
  JOIN BARRIER (file-based — no collectives, peers are DEAD):
      every survivor posts ``round-{g+1}/join-{worker}.json``; once
      membership is stable for ``settle_secs`` the chief candidate
      commits ``commit.json`` via exclusive create, pinning the new
      membership, the epoch-suffixed coordinator
      (parallel/distributed.elastic_coordinator) and the committed
      checkpoint step to restore from
      ▼
  TEARDOWN + RE-INIT (parallel/distributed.teardown_for_reshard):
      abandon the dead mesh's blocking shutdown, reset jax's global
      distributed state, re-``initialize`` over the survivors
      ▼
  REBUILD + RESTORE: fresh Trainer over the shrunken mesh (every
      PartitionSpec / zero1 rule re-elaborates against the new topology),
      last committed checkpoint restored through the sharded M≠N
      assemble path (checkpoint/shards.py), global batch rescaled by
      ``batch_policy`` — generation g+1 (N-1 hosts) resumes stepping.

GROW is the same transition from the other side: the supervisor
(launch.py --elastic) respawns the dead worker with ``DRT_ELASTIC_REJOIN``;
the rejoiner posts its join for round g+1 and waits, the live chief
notices the pending join between steps, coordinates a stop + force-save,
and the whole fleet (survivors + rejoiner) meets in the same barrier.

Worker identity: the ORIGINAL ``mesh.process_id`` (the launcher slot) is
the stable ``worker_id`` for the whole process lifetime; each committed
generation maps its member worker_ids, sorted, onto jax ranks 0..n-1.
Worker 0 must survive every generation — it hosts the per-generation
coordinator — so losing it is infeasible and falls back to the exit-75
requeue contract, as does dropping under ``min_hosts``, a barrier
timeout, or an exhausted ``max_generations`` budget (docs/resilience.md:
75 is now the FALLBACK, not the only answer).

The decision logic lives in :class:`CoordinatorSM`, pure of file I/O and
real time (fake-clock unit tests, tests/test_elastic.py);
:class:`ElasticRuntime` is the impure driver main.py wires in.
"""
from __future__ import annotations

import copy
import json
import logging
import math
import os
import shutil
import time
from typing import Callable, Optional, Set

from .preemption import RESUMABLE_EXIT_CODE
from ..analysis.protocol.spec import Model, ProtocolSpec, register_spec

log = logging.getLogger(__name__)


class ReshardRequired(Exception):
    """Unwind the step loop into the generation loop (main.py): the mesh
    must transition. ``reason`` is peer_lost | grow."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(f"{reason}: {detail}" if detail else reason)
        self.reason = reason
        self.detail = detail


class ElasticImpossible(Exception):
    """A reshard cannot happen (chief lost, < min_hosts, barrier timeout,
    generation budget exhausted, non-elastic layout). Callers fall back
    to the classic resumable exit (75)."""

    def __init__(self, reason: str, exit_code: int = RESUMABLE_EXIT_CODE):
        super().__init__(reason)
        self.reason = reason
        self.exit_code = exit_code


# ---------------------------------------------------------------------------
# Pure decision logic
# ---------------------------------------------------------------------------

class CoordinatorSM:
    """The join-round decision state machine, pure of I/O and real time.

    Drive it with ``step(now, members, commit)`` where ``members`` is the
    set of worker_ids whose join files exist for the round and ``commit``
    is the committed record if one exists. Returns one of:

      ``("wait", None)``     — poll again
      ``("commit", None)``   — THIS worker should attempt the exclusive
                               commit (it is the chief, membership has
                               been stable for ``settle_secs`` and is
                               feasible). The attempt may still lose the
                               exclusive-create race — feed the resulting
                               commit back in on the next step.
      ``("done", record)``   — a commit exists and includes us: adopt it
      ``("abort", reason)``  — infeasible or timed out: exit-75 fallback

    Commit authority: only worker 0 ever commits — the next generation's
    coordinator lives on worker 0's host (parallel/distributed.
    elastic_coordinator), so a membership without it is infeasible and
    simply never commits; everyone times out into the 75 fallback.
    Membership changes reset the settle window: several near-simultaneous
    failures (or a grow racing a late survivor) collapse into ONE
    transition instead of a cascade.
    """

    def __init__(self, worker_id: int, min_hosts: int = 2,
                 settle_secs: float = 2.0, timeout_secs: float = 60.0):
        self.worker_id = worker_id
        self.min_hosts = max(1, min_hosts)
        self.settle_secs = settle_secs
        self.timeout_secs = timeout_secs
        self._start: Optional[float] = None
        self._members: Optional[Set[int]] = None
        self._stable_since: Optional[float] = None

    def step(self, now: float, members: Set[int],
             commit: Optional[dict]):
        if self._start is None:
            self._start = now
        if commit is not None:
            if self.worker_id in commit.get("members", ()):
                return ("done", commit)
            # committed without us: we observed the round too late (our
            # own join raced the settle window) — we are not in the new
            # mesh, leave through the requeue path
            return ("abort",
                    f"generation {commit.get('generation')} committed "
                    f"without worker {self.worker_id}")
        if now - self._start >= self.timeout_secs:
            return ("abort",
                    f"join barrier timed out after {self.timeout_secs:.0f}s "
                    f"(members {sorted(members)}, need >= {self.min_hosts} "
                    "and worker 0)")
        members = set(members)
        if members != self._members:
            self._members = members
            self._stable_since = now
            return ("wait", None)
        if (self.worker_id == 0 and 0 in members
                and len(members) >= self.min_hosts
                and self._stable_since is not None
                and now - self._stable_since >= self.settle_secs):
            return ("commit", None)
        return ("wait", None)


def rescaled_batch(policy: str, base_global_batch: int,
                   base_shards: int, new_shards: int):
    """New generation's global batch under ``batch_policy``.

    ``per_host`` keeps each batch shard's slice constant — the global
    batch scales with the topology (the LR is deliberately NOT rescaled;
    docs/resilience.md). ``keep_global`` keeps the original global batch
    when the new shard count divides it, else falls back to per_host.
    Returns ``(global_batch, policy_applied)``."""
    per_shard = max(1, base_global_batch // max(1, base_shards))
    if policy == "keep_global":
        if base_global_batch % max(1, new_shards) == 0:
            return base_global_batch, "keep_global"
        log.warning(
            "elastic batch_policy=keep_global: global batch %d not "
            "divisible by %d batch shards — falling back to per_host",
            base_global_batch, new_shards)
    return per_shard * new_shards, "per_host"


# ---------------------------------------------------------------------------
# File driver
# ---------------------------------------------------------------------------

def _write_json_atomic(path: str, doc: dict) -> None:
    tmp = path + f".tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, sort_keys=True)
        f.flush()
        # elastic barrier control plane, not checkpoint payload: the step
        # loop is already stopped for the reshard when these are written
        os.fsync(f.fileno())  # shardcheck: ok(ckpt-io-thread)
    os.replace(tmp, path)


def _read_json(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


class ElasticState:
    """The shared-directory side of the barrier: one ``round-{g}`` dir per
    transition holding ``join-{worker}.json`` files and the exclusive
    ``commit.json``, plus the top-level ``generation.json`` describing
    the LIVE generation (what a rejoining peer reads first)."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _round_dir(self, gen: int) -> str:
        return os.path.join(self.directory, f"round-{gen}")

    def post_join(self, gen: int, worker_id: int, info: dict) -> None:
        d = self._round_dir(gen)
        os.makedirs(d, exist_ok=True)
        _write_json_atomic(os.path.join(d, f"join-{worker_id}.json"),
                           {"worker_id": worker_id, **info})

    def members(self, gen: int) -> Set[int]:
        d = self._round_dir(gen)
        out: Set[int] = set()
        try:
            names = os.listdir(d)
        except OSError:
            return out
        for name in names:
            if name.startswith("join-") and name.endswith(".json"):
                try:
                    out.add(int(name[len("join-"):-len(".json")]))
                except ValueError:
                    pass
        return out

    def read_commit(self, gen: int) -> Optional[dict]:
        return _read_json(os.path.join(self._round_dir(gen), "commit.json"))

    def try_commit(self, gen: int, record: dict) -> dict:
        """Exclusive-create commit: first writer wins, everyone honors
        the file's content (including a winner that raced us)."""
        d = self._round_dir(gen)
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, "commit.json")
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(record, f, sort_keys=True)
            f.flush()
            # reshard-barrier commit record (control plane; loop stopped)
            os.fsync(f.fileno())  # shardcheck: ok(ckpt-io-thread)
        try:
            # hard link = exclusive create with full content already in
            # place (no torn reads through the 'x' + write window)
            os.link(tmp, path)
        except FileExistsError:
            pass
        finally:
            try:
                os.remove(tmp)
            except OSError:
                pass
        committed = _read_json(path)
        return committed if committed is not None else record

    def read_generation(self) -> Optional[dict]:
        return _read_json(os.path.join(self.directory, "generation.json"))

    def write_generation(self, record: dict) -> None:
        _write_json_atomic(os.path.join(self.directory, "generation.json"),
                           record)

    def cleanup_rounds(self, before_gen: int) -> None:
        """Drop round dirs older than ``before_gen`` (their commits are
        history once a newer generation is LIVE in generation.json)."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        for name in names:
            if not name.startswith("round-"):
                continue
            try:
                g = int(name[len("round-"):])
            except ValueError:
                continue
            if g < before_gen:
                shutil.rmtree(os.path.join(self.directory, name),
                              ignore_errors=True)


# ---------------------------------------------------------------------------
# Runtime driver
# ---------------------------------------------------------------------------

class ElasticRuntime:
    """main.py's handle on the elastic machinery for ONE process lifetime.

    Holds the stable ``worker_id`` (the launcher's original
    ``mesh.process_id``), the current generation + membership, and drives
    transitions: ``transition()`` runs the file barrier and returns the
    committed record; ``derive_config()`` maps a record onto a concrete
    per-generation config; ``mark_live()`` publishes generation.json +
    the mesh_generation metrics row once the new mesh steps.

    ``watchdog_defer`` is the escalation fork resilience/watchdog.py
    calls before a peer-lost hard exit: True while this process can (or
    is busy trying to) reshard instead of dying.
    """

    def __init__(self, cfg, worker_id=None, num_processes=None,
                 clock=time.monotonic, wall_clock=time.time):
        self.cfg = cfg
        self.ecfg = cfg.resilience.elastic
        # identity: explicit override for launched runs where the config
        # carries the slot (rejoin), jax's live rank otherwise (SLURM
        # autodetect leaves cfg.mesh.process_id at its default)
        self.worker_id = int(cfg.mesh.process_id if worker_id is None
                             else worker_id)
        self.base_processes = int(cfg.mesh.num_processes
                                  if num_processes is None
                                  else num_processes)
        self.base_coordinator = cfg.mesh.coordinator_address or ""
        self.base_global_batch = int(cfg.train.batch_size)
        self._clock = clock
        self._wall = wall_clock
        state_dir = self.ecfg.state_dir or os.path.join(
            cfg.log_root, "elastic")
        self.state = ElasticState(state_dir) if self.enabled else None
        self.generation = 0
        self.members: Set[int] = set(range(max(1, self.base_processes)))
        self.in_transition = False
        self._defer_since: Optional[float] = None
        self._last_join_poll = 0.0
        self._transitions = 0

    # -- predicates ----------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return (str(self.ecfg.enabled).lower() in ("on", "true", "1")
                and self.base_processes > 1)

    def _layout_elastic(self) -> Optional[str]:
        """None when the mesh layout can reshard, else why not: the data
        axis must be the wildcard (-1) so it re-resolves over any device
        count, and the fixed axes' product must divide the per-host
        device count (each host holds whole non-data blocks — the
        contiguous-batch-slice requirement, parallel/mesh.py)."""
        m = self.cfg.mesh
        if m.data != -1:
            return (f"mesh.data={m.data} is pinned — elastic needs the "
                    "data axis as the -1 wildcard")
        import jax
        fixed = math.prod(1 if s in (0, -1) else s for s in
                          (m.pipeline, m.fsdp, m.expert, m.sequence,
                           m.tensor))
        local = jax.local_device_count()
        if fixed > local or local % fixed != 0:
            return (f"fixed mesh axes product {fixed} does not divide the "
                    f"per-host device count {local}")
        return None

    def can_reshard(self) -> bool:
        """The watchdog/teardown fork's question: is attempting a shrink
        transition worth deferring the exit-75 for?"""
        if not self.enabled or self.state is None:
            return False
        if not self.base_coordinator:
            # SLURM/TPU-pod autodetected worlds carry no explicit
            # coordinator_address to derive epoch-suffixed ports from
            log.warning("elastic: no mesh.coordinator_address to derive "
                        "per-generation coordinators from — falling back "
                        "to exit 75")
            return False
        if self.ecfg.max_generations and \
                self._transitions >= self.ecfg.max_generations:
            log.warning("elastic: generation budget exhausted (%d) — "
                        "falling back to exit 75", self.ecfg.max_generations)
            return False
        why = self._layout_elastic()
        if why is not None:
            log.warning("elastic: layout not reshardable (%s) — falling "
                        "back to exit 75", why)
            return False
        return True

    def watchdog_defer(self) -> bool:
        """Escalation fork (resilience/watchdog.py _maybe_exit): defer a
        peer-lost/collective-hang hard exit while a reshard is
        possible/in progress, bounded by ``reshard_timeout_secs`` from
        the FIRST defer.

        The commit-without-us break covers the non-adjacent survivor: a
        peer's death only RAISES in the collectives of its gloo ring
        neighbours — a survivor two hops away stays wedged with no
        exception and can never reach the barrier on its main thread.
        Once the next round commits without us, deferring is pointless:
        return False so the watchdog exits 75 and the supervisor
        respawns us as a rejoiner into the round after."""
        if not self.can_reshard():
            return False
        now = self._clock()
        if self._defer_since is None:
            self._defer_since = now
            log.info("elastic: deferring watchdog peer-lost exit — will "
                     "reshard instead (bound %.0fs)",
                     self.ecfg.reshard_timeout_secs)
        if not self.in_transition and self.state is not None:
            commit = self.state.read_commit(self.generation + 1)
            if commit is not None and \
                    self.worker_id not in commit.get("members", ()):
                log.warning(
                    "elastic: round %d committed without worker %d while "
                    "the main thread is wedged — ending the defer; the "
                    "75 exit lets the supervisor respawn us as a rejoiner",
                    self.generation + 1, self.worker_id)
                return False
        return now - self._defer_since < self.ecfg.reshard_timeout_secs

    def pending_join(self, force: bool = False) -> bool:
        """Throttled check (the chief's between-steps grow poll): has a
        replacement/new worker posted a join for the NEXT round?
        ``force`` skips the throttle — the post-loop grow fork must read
        the CURRENT state on every process, not a cached negative."""
        if not self.enabled or self.state is None:
            return False
        now = self._clock()
        if not force and \
                now - self._last_join_poll < max(0.05, self.ecfg.poll_secs):
            return False
        self._last_join_poll = now
        pending = self.state.members(self.generation + 1) - self.members
        return bool(pending)

    # -- the transition ------------------------------------------------------
    def _build_record(self, next_gen: int, members: Set[int], reason: str,
                      restore_step: Optional[int]) -> dict:
        from ..parallel.distributed import elastic_coordinator
        import jax
        # batch shards are DEVICES along the batch axes, not hosts —
        # keep_global's divisibility check must see the real shard count
        # (per-host rescale is invariant to the per-host device factor)
        ldc = max(1, jax.local_device_count())
        gbs, applied = rescaled_batch(
            self.ecfg.batch_policy, self.base_global_batch,
            self.base_processes * ldc, len(members) * ldc)
        return {
            "generation": next_gen,
            "members": sorted(int(w) for w in members),
            "coordinator": elastic_coordinator(
                self.base_coordinator, next_gen, self.ecfg.port_stride),
            "restore_step": -1 if restore_step is None else int(restore_step),
            "global_batch": gbs,
            "batch_policy": applied,
            "reason": reason,
            "time": self._wall(),
        }

    def transition(self, reason: str,
                   restore_step_fn: Callable[[], Optional[int]],
                   timeout_secs: Optional[float] = None) -> dict:
        """Run the join barrier for round ``generation+1`` and adopt the
        committed record. Raises :class:`ElasticImpossible` on abort.
        ``restore_step_fn`` is called by the committing chief to pin the
        checkpoint step the new generation restores from (survivors and
        rejoiners then restore that EXACT step — no post-teardown
        agreement broadcast needed)."""
        if not self.enabled or self.state is None:
            raise ElasticImpossible("elastic disabled")
        if not self.can_reshard():
            raise ElasticImpossible("reshard infeasible "
                                    "(budget/layout — see log)")
        ecfg = self.ecfg
        next_gen = self.generation + 1
        timeout = ecfg.barrier_timeout_secs if timeout_secs is None \
            else timeout_secs
        self.in_transition = True
        sm = CoordinatorSM(self.worker_id, min_hosts=ecfg.min_hosts,
                           settle_secs=ecfg.settle_secs,
                           timeout_secs=timeout)
        self.state.post_join(next_gen, self.worker_id, {
            "reason": reason, "from_generation": self.generation,
            "time": self._wall()})
        log.info("elastic: joined round %d (reason %s) as worker %d",
                 next_gen, reason, self.worker_id)
        while True:
            action, payload = sm.step(
                self._clock(), self.state.members(next_gen),
                self.state.read_commit(next_gen))
            if action == "done":
                record = payload
                break
            if action == "abort":
                self.in_transition = False
                self._defer_since = None
                raise ElasticImpossible(payload)
            if action == "commit":
                record = self._build_record(
                    next_gen, self.state.members(next_gen), reason,
                    restore_step_fn())
                committed = self.state.try_commit(next_gen, record)
                log.info("elastic: committed round %d: members %s "
                         "restore_step %s", next_gen,
                         committed.get("members"),
                         committed.get("restore_step"))
                continue  # adopt through the normal read path
            time.sleep(max(0.05, ecfg.poll_secs))
        self.generation = int(record["generation"])
        self.members = set(record["members"])
        self._transitions += 1
        log.info("elastic: adopted generation %d: members %s (rank %d), "
                 "coordinator %s, restore step %s, global batch %s",
                 self.generation, record["members"], self.rank(record),
                 record["coordinator"], record["restore_step"],
                 record["global_batch"])
        return record

    def rejoin(self, restore_step_fn: Optional[
            Callable[[], Optional[int]]] = None) -> dict:
        """A respawned/replacement worker's entry (DRT_ELASTIC_REJOIN):
        read the live generation, post a join for the next round, wait
        for the fleet to meet us there. Returns the committed record.
        ``restore_step_fn`` matters when the WHOLE fleet died and every
        worker comes back as a rejoiner: the rejoined chief is then the
        round's committer and must still pin the newest committed
        checkpoint, or the new generation restarts from step 0."""
        if not self.enabled or self.state is None:
            raise ElasticImpossible("elastic disabled")
        if restore_step_fn is None:
            restore_step_fn = lambda: None  # noqa: E731
        deadline = self._clock() + self.ecfg.rejoin_timeout_secs
        live = self.state.read_generation()
        if live is not None:
            self.generation = int(live.get("generation", 0))
            self.members = set(live.get("members", ()))
        log.info("elastic: rejoin as worker %d — live generation %d, "
                 "posting join for round %d", self.worker_id,
                 self.generation, self.generation + 1)
        while True:
            remaining = deadline - self._clock()
            if remaining <= 0:
                raise ElasticImpossible(
                    f"rejoin timed out after "
                    f"{self.ecfg.rejoin_timeout_secs:.0f}s")
            try:
                return self.transition(
                    "rejoin", restore_step_fn,
                    timeout_secs=min(remaining,
                                     self.ecfg.barrier_timeout_secs))
            except ElasticImpossible as e:
                # the live fleet may have advanced a generation while we
                # waited (e.g. another peer died, or the survivors' shrink
                # round committed before our join landed): re-read and
                # retry against the new round until the rejoin deadline
                live = self.state.read_generation()
                new_gen = int(live.get("generation", 0)) if live else None
                if new_gen is None or new_gen <= self.generation:
                    # generation.json lags the commit (the fleet is still
                    # restoring) — the committed round itself names the
                    # generation to chase
                    c = self.state.read_commit(self.generation + 1)
                    if c is not None and \
                            self.worker_id not in c.get("members", ()):
                        new_gen = int(c.get("generation",
                                            self.generation + 1))
                        live = c
                if new_gen is not None and new_gen > self.generation:
                    self.generation = new_gen
                    self.members = set(live.get("members", ()))
                    log.info("elastic: rejoin retargeting round %d (%s)",
                             self.generation + 1, e.reason)
                    continue
                raise

    # -- post-transition helpers --------------------------------------------
    def rank(self, record: dict) -> int:
        members = sorted(record["members"])
        return members.index(self.worker_id)

    def derive_config(self, record: dict):
        """The committed record mapped onto a concrete config for this
        generation: new world size/rank/coordinator + rescaled batch.
        Everything else (model, data, checkpoint dir, log_root) carries
        over — the new Trainer re-elaborates every sharding rule from
        this config against the new device count."""
        cfg2 = copy.deepcopy(self.cfg)
        cfg2.mesh.num_processes = len(record["members"])
        cfg2.mesh.process_id = self.rank(record)
        cfg2.mesh.coordinator_address = record["coordinator"]
        cfg2.train.batch_size = int(record["global_batch"])
        return cfg2

    def mark_live(self, record: Optional[dict], step: int,
                  writer=None) -> None:
        """The generation is stepping: chief publishes generation.json
        (what rejoiners bootstrap from), tombstones departed heartbeat
        ranks, drops stale round dirs, and emits the mesh_generation
        metrics row. Safe to call every generation including 0."""
        self.in_transition = False
        self._defer_since = None
        if not self.enabled or self.state is None:
            return
        import jax
        if jax.process_index() != 0:
            return
        doc = {
            "generation": self.generation,
            "members": sorted(self.members),
            "coordinator": (record or {}).get(
                "coordinator", self.base_coordinator),
            "restore_step": (record or {}).get("restore_step", -1),
            "global_batch": (record or {}).get(
                "global_batch", self.base_global_batch),
            "time": self._wall(),
        }
        self.state.write_generation(doc)
        self.state.cleanup_rounds(self.generation)
        from .heartbeat import tombstone_departed
        wd_cfg = self.cfg.resilience.watchdog
        hb_dir = wd_cfg.heartbeat_dir or os.path.join(
            self.cfg.log_root, "heartbeats")
        tombstone_departed(hb_dir, range(jax.process_count()))
        if writer is not None:
            writer.write_event("mesh_generation", {
                "generation": self.generation,
                "hosts": jax.process_count(),
                "devices": jax.device_count(),
                "step": int(step),
                "coordinator": doc["coordinator"],
            })


# ---------------------------------------------------------------------------
# declared protocol model (analysis/protocol/, docs/static_analysis.md)
# ---------------------------------------------------------------------------

def _reshard_model(mutations):
    """One reshard round, 3 hosts, exhaustive over every interleaving of
    joins, crashes, settle expiry, the commit race and adoption.

    State: ``(host_states, members, commit, settled, n_commits)`` —
    ``host_states[i]`` in out/joined/done/aborted/dead, ``members`` the
    sorted join-marker set, ``commit`` the committed member tuple from
    commit.json (None before), ``settled`` whether the settle window has
    elapsed since the last membership change, ``n_commits`` how many
    times commit.json was created this round (capped at 2 — the safety
    invariant fires at 2, counting higher only grows the state space).

    Small-scope bounds baked in: the coordinator (host 0) never crashes —
    losing it is the exit-75 requeue path, outside this round's protocol
    — and exactly one round is played (rounds are independent by
    construction: round-{gen} directories never collide).
    """
    n_hosts, min_hosts = 3, 2

    def actions(s):
        hs, mem, commit, settled, nc = s
        mem_set = set(mem)
        out = []
        for i in range(n_hosts):
            if hs[i] == "out":
                h2 = hs[:i] + ("joined",) + hs[i + 1:]
                out.append((f"join({i})",
                            (h2, tuple(sorted(mem_set | {i})),
                             commit, False, nc)))
            if hs[i] == "joined":
                if commit is not None:
                    # adopt-commit-first rule: a joined host that finds
                    # commit.json follows it — done if it is a member,
                    # aborted ("committed without us" -> exit 75) if not
                    to = "done" if i in commit else "aborted"
                    h2 = hs[:i] + (to,) + hs[i + 1:]
                    out.append((f"adopt({i})" if to == "done"
                                else f"abort_foreign({i})",
                                (h2, mem, commit, settled, nc)))
                if i != 0:   # bound: the coordinator host never crashes
                    h2 = hs[:i] + ("dead",) + hs[i + 1:]
                    out.append((f"crash({i})",
                                (h2, tuple(sorted(mem_set - {i})),
                                 commit, False, nc)))
        if commit is None and not settled and mem:
            out.append(("settle_tick", (hs, mem, commit, True, nc)))
        can_commit = (hs[0] == "joined" and 0 in mem_set
                      and len(mem) >= min_hosts and settled)
        if can_commit and (commit is None
                           or "blind_commit_overwrite" in mutations):
            # the exclusive os.link create makes the first writer win;
            # the mutation models a plain open() overwrite instead
            out.append(("commit_round",
                        (hs, mem, mem, settled, min(nc + 1, 2))))
        if commit is None and hs[0] == "joined" and len(mem) < min_hosts:
            h2 = tuple("aborted" if h == "joined" else h for h in hs)
            out.append(("abort_timeout", (h2, mem, commit, settled, nc)))
        return out

    def _single_commit(s):
        return s[4] <= 1

    def _done_only_committed(s):
        hs, _, commit, _, _ = s
        return all(h != "done" or (commit is not None and i in commit)
                   for i, h in enumerate(hs))

    return Model(
        init=(("out",) * n_hosts, (), None, False, 0),
        actions=actions,
        invariants=(
            ("at_most_one_commit_per_round", _single_commit),
            ("done_only_inside_committed_membership",
             _done_only_committed),
        ),
        liveness=(
            ("every_joined_host_leaves_the_barrier", "eventually",
             lambda s: "joined" not in s[0]),
            ("settle_window_can_commit", "reachable",
             lambda s: s[2] is not None),
        ),
    )


RESHARD_PROTOCOL = register_spec(ProtocolSpec(
    name="elastic-reshard-barrier",
    title="elastic reshard barrier: join markers, settle window, "
          "first-writer-wins commit.json, adopt-commit-first",
    modules=("distributed_resnet_tensorflow_tpu/resilience/elastic.py",),
    bounds={"hosts": 3, "min_hosts": 2, "rounds": 1, "settle_ticks": 1},
    model=_reshard_model,
    mutations=("blind_commit_overwrite",),
    event_edges={
        "reshard": {"reasons": ("peer_lost", "hang", "grow", "rejoin")},
        "mesh_generation": {},
    },
    literals={
        "commit.json": "the round's first-writer-wins commit marker",
        "generation.json": "the adopted-generation record",
        "round-": "per-round barrier directory prefix",
        "join-": "per-worker join marker prefix",
    },
    enum_checks=(
        ("reshard", "reason", ("peer_lost", "hang", "grow", "rejoin")),
    ),
))
