"""Input-pipeline stage telemetry tests (fast, `-m 'not slow'` CI smoke).

The overlapped input pipeline's attribution (bench.py imagenet_input,
docs/input_pipeline.md) is computed FROM the stage counters in
utils.metrics.input_stages — if those counters silently rot, the bench
would keep printing an attribution built on nothing. This suite pins the
contract: counters populate during real training, are monotone, and export
through MetricsWriter/InputStagesHook to metrics.jsonl.
"""
import threading

import numpy as np

from distributed_resnet_tensorflow_tpu.utils.metrics import (
    MetricsWriter, StageStats, input_stages, read_metrics)


def test_stage_stats_accumulate_and_rates():
    s = StageStats()
    s.add("decode", 0.5, items=10, nbytes=100)
    s.add("decode", 0.5, items=10, nbytes=100)
    s.add("transfer", 0.25, items=20)
    snap = s.snapshot()
    assert snap["decode"]["count"] == 2
    assert snap["decode"]["items"] == 20
    assert np.isclose(snap["decode"]["seconds"], 1.0)
    assert snap["decode"]["bytes"] == 200
    assert np.isclose(s.rates()["decode"], 20.0)
    assert np.isclose(s.rates()["transfer"], 80.0)
    s.reset()
    assert s.snapshot() == {}


def test_stage_stats_per_thread_rate_estimate():
    """A 4-worker stage that spent 1 thread-second per worker on 100 items
    ran at ~100 items/s (items / busiest thread), not 25."""
    s = StageStats()
    barrier = threading.Barrier(4)

    def worker():
        s.add("decode", 1.0, items=25)
        barrier.wait(5)  # keep all 4 threads alive at once (no ident reuse)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = s.snapshot()
    assert snap["decode"]["workers"] == 4
    assert np.isclose(snap["decode"]["seconds"], 4.0)
    assert np.isclose(snap["decode"]["max_thread_seconds"], 1.0)
    assert np.isclose(s.rates()["decode"], 100.0)


def test_pipeline_counters_populated_and_monotone():
    """The CI tripwire for attribution telemetry: a real (tiny) training
    run must populate the staging counters, and they must be monotone in
    work done — so bench.py's counter-based attribution can't silently
    read an empty registry."""
    from distributed_resnet_tensorflow_tpu.data import (
        learnable_synthetic_iterator)
    from distributed_resnet_tensorflow_tpu.train import Trainer
    from distributed_resnet_tensorflow_tpu.utils.config import get_preset

    cfg = get_preset("smoke")
    cfg.model.compute_dtype = "float32"
    cfg.model.resnet_size = 8
    cfg.model.num_classes = 4
    cfg.data.image_size = 8
    cfg.train.batch_size = 16
    cfg.data.coalesced_transfer = "on"   # auto resolves off on CPU
    input_stages.reset()
    tr = Trainer(cfg)
    tr.init_state()
    it = learnable_synthetic_iterator(16, 8, 4)
    tr.train(it, num_steps=2)
    snap1 = input_stages.snapshot()
    for stage in ("stage", "transfer", "dispatch_wait"):
        assert stage in snap1, (stage, sorted(snap1))
        assert snap1[stage]["count"] > 0
        assert snap1[stage]["seconds"] >= 0.0
    assert snap1["stage"]["items"] >= 2 * 16
    assert snap1["stage"]["bytes"] > 0
    tr.train(it, num_steps=4, start_step=2)
    snap2 = input_stages.snapshot()
    for stage in ("stage", "transfer"):
        assert snap2[stage]["count"] >= snap1[stage]["count"]
        assert snap2[stage]["items"] >= snap1[stage]["items"]
        assert snap2[stage]["seconds"] >= snap1[stage]["seconds"]
    assert snap2["stage"]["items"] > snap1["stage"]["items"]


def test_input_stages_hook_writes_event(tmp_path):
    from distributed_resnet_tensorflow_tpu.train.hooks import InputStagesHook

    input_stages.reset()
    input_stages.add("decode", 0.1, items=5)
    w = MetricsWriter(str(tmp_path), enable_tensorboard=False)
    hook = InputStagesHook(w, every_steps=10)
    hook(5, None, {})     # below cadence: no record
    hook(10, None, {})    # fires
    w.write_scalars(11, {"loss": 1.0})
    w.close()
    recs = read_metrics(str(tmp_path))
    events = [r for r in recs if r.get("event") == "input_stages"]
    scalars = [r for r in recs if "event" not in r]
    assert len(events) == 1
    assert events[0]["step"] == 10
    assert events[0]["stages"]["decode"]["items"] == 5
    # scalar consumers can still filter rows by the "event" key
    assert scalars and scalars[0]["loss"] == 1.0
