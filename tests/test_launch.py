"""Local multi-process launcher test — real 2-process SPMD over a loopback
coordinator (successor of the reference's submit_mac_dist.sh smoke cluster,
SURVEY.md §4.1)."""
import socket
import sys

import pytest

from distributed_resnet_tensorflow_tpu.launch import launch_local


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_two_process_spmd_train(tmp_path):
    rc = launch_local(
        num_processes=2,
        devices_per_process=8,  # explicit: 2 procs × 8 fake devices
        main_args=[
            "--preset", "smoke",
            "--set", "model.name=logistic",
            "--set", "model.input_size=192",   # 8*8*3
            "--set", "model.num_classes=10",
            "--set", "data.image_size=8",
            "--set", "train.batch_size=16",  # 2 procs × 8 fake devices
            "--set", "train.train_steps=6",
            "--set", "train.steps_per_loop=2",  # covers make_global_stacked_batch
            "--set", "train.log_every_steps=2",
            "--set", f"log_root={tmp_path}",
            "--set", "checkpoint.save_every_steps=0",
            "--set", "checkpoint.save_every_secs=0",
        ],
        port=_free_port())
    assert rc == 0


@pytest.mark.slow
def test_two_process_pipeline_vit_checkpoint_eval(tmp_path):
    """VERDICT r4 #2: the flagship machinery through REAL multi-process —
    2 processes x 4 fake devices, a pipelined ViT whose `pipeline` mesh
    axis (outermost, so stage 0 = process 0, stage 1 = process 1) spans
    the process boundary, mode=train_and_eval (a multi-process
    evaluate() every round), checkpoint save -> relaunch -> restore ->
    continue. Asserts step continuity from the checkpoint layout and the
    eval rounds recorded in the chief's metrics JSONL."""
    import json
    import os

    def run(train_steps, port):
        return launch_local(
            num_processes=2,
            devices_per_process=4,
            main_args=[
                "--preset", "smoke",
                "--set", "model.name=vit",
                "--set", "model.compute_dtype=float32",
                "--set", "model.num_classes=4",
                "--set", "model.vit_dim=32",
                "--set", "model.vit_depth=4",
                "--set", "model.vit_heads=2",
                "--set", "model.vit_pipeline_microbatches=2",
                "--set", "mesh.data=4",
                "--set", "mesh.pipeline=2",
                "--set", "data.image_size=8",
                "--set", "data.eval_batch_size=8",
                "--set", "train.batch_size=8",
                "--set", f"train.train_steps={train_steps}",
                "--set", "train.eval_every_steps=2",
                "--set", "train.log_every_steps=2",
                "--set", "eval.eval_batch_count=2",
                "--set", "mode=train_and_eval",
                "--set", f"log_root={tmp_path}",
                "--set", "checkpoint.save_every_steps=2",
                "--set", "checkpoint.save_every_secs=0",
            ],
            port=port)

    assert run(4, _free_port()) == 0
    ckpt_dir = os.path.join(str(tmp_path), "ckpt")
    steps1 = {int(d) for d in os.listdir(ckpt_dir) if d.isdigit()}
    assert 4 in steps1, steps1

    # relaunch: must RESTORE step 4 (not retrain 1-4) and continue to 8
    assert run(8, _free_port()) == 0
    steps2 = {int(d) for d in os.listdir(ckpt_dir) if d.isdigit()}
    assert 8 in steps2, steps2

    # chief metrics JSONL: eval rounds at 2,4 (run 1) then 6,8 (run 2) —
    # a rerun of steps 1-4 would duplicate the early eval steps
    with open(os.path.join(str(tmp_path), "train", "metrics.jsonl")) as f:
        eval_steps = [r["step"] for r in map(json.loads, f)
                      if "eval/precision" in r]
    assert eval_steps == [2, 4, 6, 8], eval_steps
