"""Toy 1-hidden-layer MLP — debug stand-in for ResNet.

Parity with reference logist_model.py (LRNet: flattened image → dense(hidden)
→ ReLU → dense(classes), reference logist_model.py:14-58). Used to debug the
distribution layer without conv cost, like the reference's commented swap at
resnet_cifar_main.py:257.

``dtype`` is the compute dtype (the precision-policy hook,
parallel/precision.py); it defaults to f32 — the toy's historical
behavior — and is only narrowed by an explicit policy/variant override
through ``models.create_model``. Params stay f32 masters (flax
param_dtype default) and the logits leave f32 like every model family.
"""
from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


class LogisticNet(nn.Module):
    num_classes: int = 10
    hidden_units: int = 100
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        del train
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        x = nn.Dense(self.hidden_units, dtype=self.dtype)(x)
        x = nn.relu(x)
        # f32 head: logits always leave full-precision (the model-family
        # contract the CE/metrics path relies on)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)
