"""Crash-consistent checkpoint commit protocol: manifest + fsync + rename.

A checkpoint directory is COMMITTED iff it is named by its bare step number
(``<dir>/<step>/``). Writers stage into ``<dir>/_staging.<step>/``, write a
``MANIFEST.json`` listing every payload file with its size and SHA-256,
fsync the manifest and the staging dir, then ``os.replace`` the staging dir
onto the final name — a single atomic rename on POSIX. A crash at any point
leaves either no ``<step>/`` entry at all (stale staging dirs are swept on
the next manager construction) or a fully-written one; readers
(``CheckpointManager.restore``, the evaluator's ``wait_for_new_checkpoint``)
never observe a torn checkpoint under its committed name.

The manifest additionally lets ``restore()`` detect payload damage that
happened AFTER commit (truncation by a full disk, bit rot, a partial rsync)
and fall back to the newest older checkpoint that still verifies, instead of
crashing — the reference's ``tf.train.Saver`` trusted latest_checkpoint
blindly (SURVEY.md §2.14).

Checkpoints written before this protocol existed (plain orbax
``CheckpointManager`` layout) carry no manifest; they verify as ``"legacy"``
— accepted, with a log line that integrity can't be proven.
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
from typing import Dict, List, Tuple

log = logging.getLogger(__name__)

MANIFEST_NAME = "MANIFEST.json"
MANIFEST_FORMAT = 1
_STAGING_PREFIX = "_staging."


def staging_path(directory: str, step: int) -> str:
    return os.path.join(directory, f"{_STAGING_PREFIX}{step}")


def is_staging_name(name: str) -> bool:
    return name.startswith(_STAGING_PREFIX)


def fsync_dir(path: str) -> None:
    """Durably record directory-entry changes (the rename itself)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # platform without dir fds — best effort
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def file_sha256(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def _payload_files(step_dir: str) -> List[str]:
    """Every regular file under ``step_dir`` except the manifest itself,
    as sorted relative paths."""
    out = []
    for dirpath, _dirnames, filenames in os.walk(step_dir):
        for name in filenames:
            rel = os.path.relpath(os.path.join(dirpath, name), step_dir)
            if rel != MANIFEST_NAME:
                out.append(rel)
    return sorted(out)


def write_manifest(step_dir: str, step: int) -> Dict:
    """Checksum every payload file and durably write ``MANIFEST.json``
    inside ``step_dir`` (fsync file, then fsync the dir so the entry itself
    is on disk before the commit rename)."""
    files = {}
    for rel in _payload_files(step_dir):
        full = os.path.join(step_dir, rel)
        # fsync every payload file BEFORE the manifest: the serializer
        # (orbax) does not fsync, so without this the hash below describes
        # page-cache contents — power loss after the commit rename could
        # leave a committed step whose payload never reached disk
        fd = os.open(full, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
        files[rel] = {"size": os.path.getsize(full),
                      "sha256": file_sha256(full)}
    manifest = {"format": MANIFEST_FORMAT, "step": step, "files": files}
    path = os.path.join(step_dir, MANIFEST_NAME)
    with open(path, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    fsync_dir(step_dir)
    return manifest


def manifest_status(step_dir: str) -> Tuple[str, str]:
    """Verify a committed checkpoint dir against its manifest.

    Returns ``("ok", "")`` when every listed file exists with matching size
    and SHA-256 and no extra payload appeared; ``("legacy", ...)`` when no
    manifest exists (pre-protocol checkpoint — integrity unprovable but not
    known-bad); ``("bad", reason)`` on any mismatch.
    """
    path = os.path.join(step_dir, MANIFEST_NAME)
    if not os.path.exists(path):
        return "legacy", "no manifest (written before the commit protocol)"
    try:
        with open(path) as f:
            manifest = json.load(f)
        files = manifest["files"]
        # extra payload (partial rsync debris, concurrent-writer leftovers)
        # is damage too: orbax may trip over it long after we said "ok"
        extra = sorted(set(_payload_files(step_dir)) - set(files))
        if extra:
            return "bad", f"unlisted payload file(s): {extra[:4]}"
        for rel, meta in files.items():
            full = os.path.join(step_dir, rel)
            if not os.path.exists(full):
                return "bad", f"missing payload file {rel}"
            size = os.path.getsize(full)
            if size != meta.get("size"):
                return "bad", (f"size mismatch in {rel}: "
                               f"{size} != {meta.get('size')}")
            # size check first: the common torn write (truncation) is
            # caught without reading the file; the hash catches in-place
            # corruption
            if file_sha256(full) != meta.get("sha256"):
                return "bad", f"checksum mismatch in {rel}"
    except (OSError, ValueError, KeyError, TypeError) as e:
        # also covers the dir vanishing mid-verification (another process
        # quarantined it on a shared FS) — that ranks as damaged here and
        # the caller falls back, instead of crashing the whole restore
        return "bad", f"unreadable checkpoint/manifest: {e}"
    return "ok", ""


def manifest_digest(step_dir: str) -> str:
    """SHA-256 of the committed step's ``MANIFEST.json`` bytes — a compact
    identity for the checkpoint's CONTENT (the manifest lists every payload
    file with its size and hash, so two commits with identical payloads get
    identical digests). Consumers: the serving hot-swap path reports which
    exact checkpoint is live (``poll_new_checkpoint``, serve/swap.py).
    Empty string for legacy/pre-protocol checkpoints with no manifest."""
    path = os.path.join(step_dir, MANIFEST_NAME)
    try:
        with open(path, "rb") as f:
            return hashlib.sha256(f.read()).hexdigest()
    except OSError:
        return ""


def committed_steps(directory: str) -> List[int]:
    """Steps with a COMMITTED checkpoint dir (bare-numeric name), sorted
    ascending. Staging dirs, orbax tmp dirs (``<step>.orbax-checkpoint-
    tmp-*``), and sidecar files never match."""
    try:
        names = os.listdir(directory)
    except (FileNotFoundError, NotADirectoryError):
        return []
    return sorted(int(n) for n in names
                  if n.isdigit() and os.path.isdir(os.path.join(directory, n)))


def sweep_staging(directory: str) -> int:
    """Remove leftover staging dirs from a crashed writer. Returns the
    number removed. Call only when no other writer can be live (manager
    construction)."""
    import shutil
    removed = 0
    try:
        names = os.listdir(directory)
    except (FileNotFoundError, NotADirectoryError):
        return 0
    for name in names:
        full = os.path.join(directory, name)
        if is_staging_name(name) and os.path.isdir(full):
            shutil.rmtree(full, ignore_errors=True)
            removed += 1
    if removed:
        log.info("swept %d stale checkpoint staging dir(s) in %s",
                 removed, directory)
    return removed
