"""Open-loop synthetic load generator for the inference server.

OPEN loop: arrivals are scheduled on a fixed clock (request i at
``t0 + i/qps``) regardless of completions — the load a real user
population offers, and the one that exposes queueing collapse. A
closed-loop driver (wait for each response before sending the next) would
self-throttle exactly when the server is slowest and report flattering
latency (coordinated omission). The generator never blocks on a Future
until the offered load is fully submitted; per-request latency is recorded
by the server at result time, so a late response is charged its full
queue + service time.
"""
from __future__ import annotations

import logging
import time
from concurrent.futures import wait as futures_wait
from typing import Optional

import numpy as np

log = logging.getLogger(__name__)


def synthetic_requests(image_shape, dtype, pool: int = 32, seed: int = 0):
    """A small pool of random request images, cycled by the generator (the
    per-request content doesn't affect timing; generating fresh images at
    high QPS would bottleneck the GENERATOR, not measure the server)."""
    rng = np.random.RandomState(seed)
    dtype = np.dtype(dtype)
    if dtype == np.uint8:
        return [rng.randint(0, 256, image_shape, np.uint8)
                for _ in range(pool)]
    return [rng.randn(*image_shape).astype(dtype) for _ in range(pool)]


def run_open_loop(server, qps: float, duration_secs: float,
                  seed: int = 0, timeout_secs: Optional[float] = None,
                  variant: Optional[str] = None) -> dict:
    """Offer ``qps`` requests/sec for ``duration_secs``, then wait for every
    outstanding Future. Returns offered/completed/failed/late counts and
    the achieved submit rate; latency percentiles live in
    ``server.report()`` (recorded server-side per request).

    ``variant`` targets one serving precision variant (docs/precision.md;
    None = the replica's default) — bench's (batch, variant) serving row
    drives one open loop per variant."""
    n = max(1, int(qps * duration_secs))
    pool = synthetic_requests(server.image_shape, server.image_dtype,
                              seed=seed)
    futures = []
    late = 0
    t0 = time.perf_counter()
    for i in range(n):
        target = t0 + i / qps
        now = time.perf_counter()
        if now < target:
            time.sleep(target - now)
        elif now - target > 0.5:
            late += 1  # generator itself fell behind the open-loop clock
        futures.append(server.submit(pool[i % len(pool)], variant=variant))
    submit_wall = time.perf_counter() - t0
    done, not_done = futures_wait(
        futures, timeout=timeout_secs if timeout_secs is not None
        else max(60.0, duration_secs))
    failed = sum(1 for f in done if f.exception() is not None)
    if not_done:
        log.error("open-loop load: %d request(s) unresolved at timeout",
                  len(not_done))
    return {
        "offered": n,
        "completed": len(done) - failed,
        "failed": failed,
        "unresolved": len(not_done),
        "late_submits": late,
        "offered_qps": round(qps, 1),
        "achieved_submit_qps": round(n / max(submit_wall, 1e-9), 1),
        "wall_secs": round(submit_wall, 2),
    }
