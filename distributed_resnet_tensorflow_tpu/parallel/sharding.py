"""Sharding rules for params / optimizer state / batches.

Replaces the reference's ``tf.train.replica_device_setter`` variable placement
(reference resnet_cifar_main.py:392-396 — round-robin variables onto ps tasks)
with ``NamedSharding`` annotations: parameters are replicated by default (pure
DP, matching the reference capability) and optionally sharded ZeRO-style over
the ``fsdp`` axis for large models/optimizers, with XLA inserting
all-gather/reduce-scatter instead of grpc push/pull.
"""
from __future__ import annotations

import threading
import time
import weakref
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def coerce_batch_dtypes(batch: Any) -> Any:
    """Narrow platform-default 64-bit leaves before the host→device hop.

    Labels/indices arrive int64 whenever they pass through a numpy op that
    defaults to the platform int (np.arange/np.concatenate on mixed inputs,
    a user-supplied list), and jax silently ships the 8-byte payload —
    doubling label transfer bytes for data the model reads as int32 anyway
    (x64 is off; jax would truncate AFTER the transfer). One shared
    coercion, applied by every put path (shard_batch / make_global_batch /
    the coalesced stager): integer leaves → int32, float64 → float32.
    """
    def fix(x):
        dt = getattr(x, "dtype", None)
        if dt is None:
            return x
        if dt == np.int64:
            return np.asarray(x, np.int32)
        if dt == np.float64:
            return np.asarray(x, np.float32)
        return x

    return jax.tree_util.tree_map(fix, batch)


def stacked_encoder_spec(leaf_name: str, ndim: int, tensor: int = 1) -> P:
    """PartitionSpec for one PipelinedEncoder stacked-param leaf: ``pipeline``
    on the leading depth axis, plus (when ``tensor`` > 1) the Megatron
    placement on the head/hidden axis — whole heads of qkv (L,D,3,H,hd) and
    proj (L,H,hd,D), columns of mlp_w1 (L,D,F)/mlp_b1 (L,F), rows of
    mlp_w2 (L,F,D) — and, for the MoE pipeline (pp×ep), ``expert`` on the
    expert-stacked axis of moe_w1/b1/w2/b2 (L,E,...) while the router
    stays replicated across ``expert`` (routing must be globally
    consistent). Single source of truth for BOTH the training-state
    sharding (param_sharding_rule) and the pipeline shard_map in_specs
    (models/pipeline.py) — they must agree or every step reshards."""
    if leaf_name.startswith("moe_"):
        if tensor > 1:
            # Megatron INSIDE each expert (MoE×tensor, round 5): columns
            # of moe_w1 (L,E,D,F)/moe_bias1 (L,E,F), rows of moe_w2
            # (L,E,F,D); moe_bias2 stays replicated across `tensor`
            # (added after the completing psum, models/moe.expert_ffn)
            spec = {
                "moe_w1": P("pipeline", "expert", None, "tensor"),
                "moe_bias1": P("pipeline", "expert", "tensor"),
                "moe_w2": P("pipeline", "expert", "tensor", None),
            }.get(leaf_name)
            if spec is not None:
                return spec
        return P(*(("pipeline", "expert") + (None,) * (ndim - 2)))
    if tensor > 1:
        spec = {
            "qkv_kernel": P("pipeline", None, None, "tensor", None),
            "proj_kernel": P("pipeline", "tensor", None, None),
            "mlp_w1": P("pipeline", None, "tensor"),
            "mlp_b1": P("pipeline", "tensor"),
            "mlp_w2": P("pipeline", "tensor", None),
        }.get(leaf_name)
        if spec is not None:
            return spec
    return P(*(("pipeline",) + (None,) * (ndim - 1)))


# (leaf, shape, tensor) triples already warned about below — once per
# distinct drop-back, not per retrace/model rebuild
_TENSOR_DROPBACK_WARNED: set = set()


def _warn_tensor_dropback(path: str, shape, tensor: int) -> None:
    """A requested tensor split the shape does not divide falls back to
    replication — numerics stay correct, but the leaf's FLOPs (often the
    dominant MLP matmuls) then run in full on every tensor peer. Silent
    replicated compute is the failure mode the Trainer's dead-axis config
    checks exist to prevent, so say it loudly, once per leaf shape."""
    key = (path.rsplit("['", 1)[-1], tuple(shape), tensor)
    if key in _TENSOR_DROPBACK_WARNED:
        return
    _TENSOR_DROPBACK_WARNED.add(key)
    import logging
    logging.getLogger(__name__).warning(
        "tensor axis (%d) does not divide the split dim of %s (shape %s) "
        "— this leaf will REPLICATE across tensor peers; pick model dims "
        "divisible by the tensor axis", tensor, path, tuple(shape))


def param_sharding_rule(path: str, shape: tuple, mesh: Mesh,
                        fsdp_min_size: int = 2 ** 16) -> P:
    """Parameter placement rule.

    Tensor parallelism (Megatron-style, transformer blocks only): when the
    ``tensor`` axis is >1, attention heads and the MLP hidden dim split
    column-/row-wise so each block needs exactly one all-reduce, inserted by
    XLA at the row-parallel contraction:

        qkv kernel (D, 3, H, hd) → P(None, None, "tensor", None)  (whole heads)
        out  kernel (H, hd, D)   → P("tensor", None, None)
        mlp  up    (D, 4D)       → P(None, "tensor")
        mlp  down  (4D, D)       → P("tensor", None)

    ZeRO-3-style fsdp: shard the largest dimension of big params over
    ``fsdp`` when it divides evenly; small params stay replicated (a sharded
    1-D BN scale buys nothing and costs collective latency)."""
    pipeline = mesh.shape.get("pipeline", 1)
    if pipeline > 1 and "['encoder']" in path and shape \
            and shape[0] % pipeline == 0:
        # PipelinedEncoder stacks per-layer params on a leading depth axis;
        # sharding it over `pipeline` (× `tensor` on the Megatron axes) puts
        # each stage's weights (and optimizer moments) on its own devices —
        # matching the shard_map in_specs so no per-step resharding is needed
        leaf = path.rsplit("['", 1)[-1].rstrip("]'")
        spec = stacked_encoder_spec(leaf, len(shape),
                                    mesh.shape.get("tensor", 1))
        # only honor a tensor split the shape actually divides (dropping
        # back to the tensor-free spec keeps `expert` on MoE leaves)
        for axis_name, dim in zip(spec, shape):
            if axis_name == "tensor" and dim % mesh.shape["tensor"]:
                _warn_tensor_dropback(path, shape, mesh.shape["tensor"])
                return stacked_encoder_spec(leaf, len(shape), 1)
        return spec
    expert = mesh.shape.get("expert", 1)
    tensor = mesh.shape.get("tensor", 1)
    if "SwitchMlp" in path and "router" not in path and shape:
        # Switch MoE expert-stacked weights: each expert group holds its
        # own experts (+ moments); the router stays replicated. With a
        # tensor axis, each expert's FFN additionally splits Megatron-
        # style (w1/bias1 columns, w2 rows; one psum — expert_ffn), so
        # ep×tp and tp-only MoE stop replicating the dominant FLOPs.
        e_ax = "expert" if (expert > 1 and shape[0] % expert == 0) else None
        leaf = path.rsplit("['", 1)[-1].rstrip("]'")
        t_pos = {"w1": 2, "bias1": 1, "w2": 1}.get(leaf)
        spec = [e_ax] + [None] * (len(shape) - 1)
        if tensor > 1 and t_pos is not None and len(shape) > t_pos:
            if shape[t_pos] % tensor == 0:
                spec[t_pos] = "tensor"
            else:
                _warn_tensor_dropback(path, shape, tensor)
        if any(spec):
            return P(*spec)
        # no expert/tensor split applies — fall through to the fsdp rule
    if tensor > 1 and ("EncoderBlock" in path or "MultiHeadAttention" in path):
        if "kernel" in path:
            split_dim = None
            if "qkv" in path and len(shape) == 4:
                split_dim, spec = 2, P(None, None, "tensor", None)
            elif "proj" in path and len(shape) == 3:
                split_dim, spec = 0, P("tensor", None, None)
            elif "Dense_0" in path and len(shape) == 2:
                split_dim, spec = 1, P(None, "tensor")
            elif "Dense_1" in path and len(shape) == 2:
                split_dim, spec = 0, P("tensor", None)
            if split_dim is not None:
                if shape[split_dim] % tensor == 0:
                    return spec
                _warn_tensor_dropback(path, shape, tensor)
        if "bias" in path and len(shape) == 1 and "Dense_0" in path \
                and shape[0] % tensor == 0:
            return P("tensor")
    fsdp = mesh.shape["fsdp"]
    if fsdp <= 1 or int(np.prod(shape)) < fsdp_min_size:
        return P()
    # choose the largest axis divisible by the fsdp size
    order = sorted(range(len(shape)), key=lambda i: -shape[i])
    for i in order:
        if shape[i] % fsdp == 0:
            spec = [None] * len(shape)
            spec[i] = "fsdp"
            return P(*spec)
    return P()


def tree_param_shardings(params: Any, mesh: Mesh,
                         fsdp_min_size: int = 2 ** 16):
    """Map a param pytree to NamedShardings via `param_sharding_rule`."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    out = []
    for path, leaf in flat:
        name = "/".join(str(p) for p in path)
        spec = param_sharding_rule(name, np.shape(leaf), mesh)
        out.append(NamedSharding(mesh, spec))
    return jax.tree_util.tree_unflatten(treedef, out)


# ---------------------------------------------------------------------------
# ZeRO-1: optimizer-state / weight-update sharding over the `data` axis
# (arXiv:2004.13336 — "Automatic Cross-Replica Sharding of Weight Update
# in Data-Parallel Training"). The regex→PartitionSpec rule-table shape
# follows the `match_partition_rules` exemplar (SNIPPETS.md [2]).
# ---------------------------------------------------------------------------

#: leaves below this many ELEMENTS stay replicated under ZeRO-1 by default
#: (config knob: optimizer.zero1_min_size) — sharding a (64,) BN-scale
#: moment buys bytes nobody misses and costs a collective per step
ZERO1_MIN_SIZE = 2048


class _SizesMesh:
    """Duck-typed stand-in for a Mesh where only axis SIZES matter (the
    sharding rules read nothing else) — lets the lint rule and the
    big-mesh elaboration sweep resolve specs without materializing 256
    virtual devices."""

    def __init__(self, sizes: Dict[str, int]):
        # every axis present (param_sharding_rule indexes "fsdp" directly)
        self.shape = {"pipeline": 1, "data": 1, "fsdp": 1, "expert": 1,
                      "seq": 1, "tensor": 1, **sizes}


def match_partition_rules(rules, tree_shapes):
    """``(regex, maker)`` rule table → a PartitionSpec pytree (the
    SNIPPETS.md [2] ``match_partition_rules`` pattern): for every leaf the
    FIRST rule whose regex searches the flattened ``/``-joined path wins;
    ``maker`` is either a literal PartitionSpec or a callable
    ``(path, shape) -> PartitionSpec``. Raises if no rule matches — a
    rule table is exhaustive by contract (end it with ``(".*", ...)``)."""
    import re
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree_shapes)
    out = []
    for path, leaf in flat:
        name = "/".join(str(p) for p in path)
        for pattern, maker in rules:
            if re.search(pattern, name) is not None:
                spec = maker(name, np.shape(leaf)) if callable(maker) \
                    else maker
                out.append(spec)
                break
        else:
            raise ValueError(f"no partition rule matched leaf {name!r}")
    return jax.tree_util.tree_unflatten(treedef, out)


def _zero1_augment(base_spec: P, shape, data: int, min_size: int,
                   report: Optional["Zero1Report"], name: str) -> P:
    """Insert ``data`` into ``base_spec`` on the largest FREE dim it
    divides; fall back to the base (replicated-over-data) spec otherwise,
    counting why. Dims already sharded (fsdp/tensor/...) are left alone —
    composing axes on one dim would entangle the reduce-scatter layout
    with the fsdp gather order for marginal extra savings."""
    nbytes = int(np.prod(shape, dtype=np.int64)) * 4  # f32 moments
    if data <= 1:
        if report:
            report.count(name, nbytes, None, "no-data-axis")
        return base_spec
    if int(np.prod(shape, dtype=np.int64)) < min_size:
        if report:
            report.count(name, nbytes, None, "below-min-size")
        return base_spec
    base = tuple(base_spec) + (None,) * (len(shape) - len(base_spec))
    order = sorted(range(len(shape)), key=lambda i: -shape[i])
    for d in order:
        if base[d] is None and shape[d] % data == 0:
            spec = list(base)
            spec[d] = "data"
            if report:
                report.count(name, nbytes, d, "sharded")
            return P(*spec)
    if report:
        report.count(name, nbytes, None, "no-divisible-dim")
    return base_spec


def zero1_rules(mesh, min_size: int = ZERO1_MIN_SIZE,
                report: Optional["Zero1Report"] = None):
    """The ZeRO-1 rule table for OPTIMIZER-STATE leaves: regex on the
    flattened path → PartitionSpec (first match wins). Scalar bookkeeping
    (step counts, schedule state) stays replicated; moment tensors
    (momentum ``trace``, Adam/LAMB ``mu``/``nu``) and any other
    param-shaped leaf shard their largest free dim over ``data`` on top
    of the base fsdp/tensor placement (``param_sharding_rule``), falling
    back to the base spec — counted in ``report`` — when nothing
    divides. ``mesh`` may be a real Mesh or a ``_SizesMesh``."""
    data = mesh.shape.get("data", 1)

    def shard(name, shape):
        base = param_sharding_rule(name, shape, mesh)
        return _zero1_augment(base, shape, data, min_size, report, name)

    def replicate(name, shape):
        if report:
            report.count(name, int(np.prod(shape, dtype=np.int64)) * 4,
                         None, "bookkeeping")
        return P()

    return (
        # optimizer bookkeeping scalars/schedules: never sharded. Matched
        # at NamedTuple-ATTR positions only (flattened as ".count") — a
        # PARAM named e.g. "scale" flattens as "['scale']" and must fall
        # through to the moment rules below
        (r"\.(count|mini_step|gradient_step|inner_state|"
         r"notfinite_count|scale)($|/)", replicate),
        # moment tensors: momentum trace, Adam/LAMB mu+nu — the ZeRO-1
        # payload proper
        (r"\.(trace|mu|nu)($|/)", shard),
        # anything else param-shaped (future optimizers) gets the same
        # treatment; scalars fall below min_size and replicate
        (r".*", shard),
    )


class Zero1Report:
    """Counted record of one ZeRO-1 spec resolution: how many leaves (and
    bytes) actually sharded over ``data`` vs fell back replicated, and
    why — the ``{"event": "zero1"}`` row (train/hooks.Zero1Hook), the
    bench ``zero1`` row, and the ``unsharded-opt-state`` lint rule all
    read this instead of re-deriving it."""

    def __init__(self, data: int = 1):
        self.data = max(1, int(data))
        self.sharded_leaves = 0
        self.replicated_leaves = 0
        self.sharded_bytes = 0
        self.replicated_bytes = 0
        self.reasons: Dict[str, int] = {}
        self.decisions: Dict[str, Optional[int]] = {}

    def count(self, name: str, nbytes: int, dim: Optional[int],
              reason: str) -> None:
        self.decisions[name] = dim
        self.reasons[reason] = self.reasons.get(reason, 0) + 1
        if dim is None:
            self.replicated_leaves += 1
            self.replicated_bytes += int(nbytes)
        else:
            self.sharded_leaves += 1
            self.sharded_bytes += int(nbytes)

    @property
    def bytes_per_replica(self) -> int:
        """Per-replica optimizer-state bytes under this resolution:
        sharded leaves cost 1/data, replicated leaves full."""
        return self.replicated_bytes + self.sharded_bytes // self.data

    def snapshot(self) -> Dict[str, Any]:
        total = self.sharded_bytes + self.replicated_bytes
        return {
            "data_shards": self.data,
            "sharded_leaves": self.sharded_leaves,
            "replicated_leaves": self.replicated_leaves,
            "sharded_bytes": self.sharded_bytes,
            "replicated_bytes": self.replicated_bytes,
            "bytes_per_replica": self.bytes_per_replica,
            "bytes_per_replica_unsharded": total,
            "reasons": dict(self.reasons),
        }


class Zero1Stats:
    """Process-global record of the most recent ZeRO-1 resolution +
    exchange-payload accounting (reduce-scatter/all-gather bytes from the
    bucket plan) — what the ``{"event": "zero1"}`` metrics row and
    bench.py's ``zero1`` row export. Mirrors overlap_stats' contract."""

    def __init__(self):
        self._lock = threading.Lock()
        self._snap: Optional[Dict[str, Any]] = None

    def record_report(self, report: Zero1Report) -> None:
        with self._lock:
            base = self._snap or {}
            self._snap = {**base, **report.snapshot()}

    def record_gather(self, bucket_bytes, bucket_leaves,
                      compress=None, wire_bytes=None) -> None:
        """Bucketed param-update all-gather plan (parallel/overlap.py):
        per-bucket FULL-leaf bytes in issue order. ``compress`` /
        ``wire_bytes`` carry the comm.compress wire format (the SAME
        plan, narrower payload — docs/precision.md)."""
        bucket_bytes = [int(b) for b in bucket_bytes]
        with self._lock:
            base = self._snap or {}
            self._snap = {**base,
                          "gather_buckets": len(bucket_bytes),
                          "gather_bucket_bytes": bucket_bytes,
                          "gather_bucket_leaves": [int(n) for n in
                                                   bucket_leaves],
                          "gather_compress": compress or "off",
                          "gather_wire_bytes":
                              [int(b) for b in wire_bytes]
                              if wire_bytes is not None else bucket_bytes}

    def reset(self) -> None:
        with self._lock:
            self._snap = None

    def snapshot(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return dict(self._snap) if self._snap is not None else None


#: process-global ZeRO-1 telemetry (one training process = one resolution)
zero1_stats = Zero1Stats()


def zero1_unsupported_reason(cfg, mesh) -> Optional[str]:
    """None when the ZeRO-1 sharded weight update applies to this
    (cfg, mesh); else a one-line reason. The envelope is wider than the
    overlap path's (no BN/accum/model-family restrictions — the sharded
    update is a layout transformation, not a step rewrite): it needs only
    a >1 ``data`` axis and no program-shaping axes (those bake their own
    shard_maps and optimizer layouts into the model)."""
    if mesh.shape.get("data", 1) <= 1:
        return ("a single data shard holds the whole optimizer state "
                "either way — nothing to shard")
    for axis in ("pipeline", "tensor", "expert", "seq"):
        if mesh.shape.get(axis, 1) > 1:
            return (f"mesh axis {axis!r} > 1 already lays the optimizer "
                    "state out with the model's own shard_maps; the "
                    "ZeRO-1 rule table covers data/fsdp meshes")
    return None


def resolve_zero1(cfg, mesh) -> bool:
    """``optimizer.zero1`` → active? ``auto`` = on iff the run has >1
    process (where per-replica optimizer memory binds) and the envelope
    supports it; ``on`` forces — raising the reason, except on a
    single-data-shard mesh (what checkpoint CONSUMERS like the standalone
    evaluator and 1-device serving replicas see when they build a Trainer
    from a training config: a train-step-only knob must resolve off
    loudly there, not crash them — the comm.overlap precedent)."""
    import logging
    mode = cfg.optimizer.zero1
    if mode not in ("auto", "on", "off"):
        raise ValueError(f"unknown optimizer.zero1 setting {mode!r}")
    if mode == "off":
        return False
    reason = zero1_unsupported_reason(cfg, mesh)
    if mode == "on":
        if reason is not None:
            if mesh.shape.get("data", 1) * mesh.shape.get("fsdp", 1) <= 1:
                logging.getLogger(__name__).warning(
                    "optimizer.zero1=on resolved OFF: %s", reason)
                return False
            raise ValueError(
                f"optimizer.zero1=on is unsupported here: {reason}")
        return True
    return reason is None and jax.process_count() > 1


def zero1_grad_specs(params, mesh, min_size: int = ZERO1_MIN_SIZE,
                     report: Optional[Zero1Report] = None):
    """Per-leaf ZeRO-1 PartitionSpecs for a PARAM-shaped tree (grads and
    updates): the base ``param_sharding_rule`` placement with ``data``
    inserted on the largest free divisible dim. This is the layout the
    reduce-scattered gradients land in and the one the optimizer shard
    update runs in — it must agree leaf-by-leaf with the optimizer-state
    shardings (``zero1_state_shardings`` applies the same augment to the
    mirrored moment leaves), or every step would reshard."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    data = mesh.shape.get("data", 1)
    out = []
    for path, leaf in flat:
        name = "/".join(str(p) for p in path)
        base = param_sharding_rule(name, np.shape(leaf), mesh)
        out.append(_zero1_augment(base, np.shape(leaf), data, min_size,
                                  report, name))
    return jax.tree_util.tree_unflatten(treedef, out)


def zero1_state_shardings(opt_state_shapes, mesh: Mesh,
                          min_size: int = ZERO1_MIN_SIZE,
                          report: Optional[Zero1Report] = None):
    """NamedShardings for an OPTIMIZER-STATE tree under ZeRO-1: the rule
    table (``zero1_rules``) resolves every leaf. Requires a real Mesh
    (NamedShardings embed it); spec-only callers (lint, big-mesh sweeps)
    use ``zero1_rules`` with a ``_SizesMesh`` directly."""
    specs = match_partition_rules(zero1_rules(mesh, min_size, report),
                                  opt_state_shapes)
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P))


def shard_batch(batch: Any, mesh: Mesh) -> Any:
    """Device-put a host batch with the leading dim split over the batch axes.

    For multi-host, use `make_global_batch` instead — each process contributes
    its local shard (the reference's Horovod path never sharded input at all;
    each rank shuffled the full dataset independently, SURVEY.md §3.2 — fixed
    here by construction).
    """
    from .mesh import data_sharding
    sharding = data_sharding(mesh)
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, sharding), coerce_batch_dtypes(batch))


def pad_batch_to_multiple(batch: dict, multiple: int) -> dict:
    """Pad the leading dim to a multiple of the batch-shard count, adding (or
    extending) a float "mask" entry so padded rows don't count in metrics.
    Needed because an eval batch (reference used 100, resnet_cifar_eval.py)
    need not divide the device count."""
    b = next(iter(batch.values())).shape[0]
    rem = b % multiple
    if rem == 0:
        return batch
    pad = multiple - rem
    out = {}
    for k, v in batch.items():
        if k == "mask":
            continue
        pad_width = ((0, pad),) + ((0, 0),) * (v.ndim - 1)
        out[k] = np.pad(np.asarray(v), pad_width)
    mask = batch.get("mask")
    if mask is None:
        mask = np.ones((b,), np.float32)
    out["mask"] = np.concatenate([np.asarray(mask),
                                  np.zeros((pad,), np.float32)])
    return out


def pad_batch_to_bucket(batch: dict, bucket: int) -> dict:
    """Pad the leading dim up to EXACTLY ``bucket`` rows — the serving
    batcher's padding (serve/batcher.py): a partial group of in-flight
    requests lands in its power-of-two bucket so every bucket size maps to
    ONE AOT-compiled program. Same mask semantics as
    ``pad_batch_to_multiple`` (padded rows carry mask 0); buckets are sized
    in multiples of ``Trainer.eval_pad_multiple`` so the padded batch also
    divides over the batch shards (× pipeline microbatches)."""
    b = next(iter(batch.values())).shape[0]
    if b > bucket:
        raise ValueError(f"batch of {b} rows does not fit bucket {bucket}")
    pad = bucket - b
    out = {}
    for k, v in batch.items():
        if k == "mask":
            continue
        pad_width = ((0, pad),) + ((0, 0),) * (np.asarray(v).ndim - 1)
        out[k] = np.pad(np.asarray(v), pad_width)
    mask = batch.get("mask")
    if mask is None:
        mask = np.ones((b,), np.float32)
    out["mask"] = np.concatenate([np.asarray(mask, np.float32),
                                  np.zeros((pad,), np.float32)])
    return out


def shard_stacked_batch(batch: Any, mesh: Mesh) -> Any:
    """Like shard_batch but for K-step stacked batches (K, B, ...): the K
    axis is unsharded (scan iterates it), B splits over the batch axes."""
    from .mesh import data_sharding
    sharding = NamedSharding(mesh, P(None, *data_sharding(mesh).spec))
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, sharding), coerce_batch_dtypes(batch))


def make_global_stacked_batch(local_batch: Any, mesh: Mesh) -> Any:
    """Multi-process variant of shard_stacked_batch: each process holds
    (K, B_local, ...); the global array is (K, B_local·num_input_shards,
    ...). The multiplier is the number of DISTINCT batch slices across
    processes (mesh.process_batch_slice) — equal to process_count for pure
    data-over-processes, smaller when a non-batch axis spans processes
    (those processes feed identical replicated slices)."""
    from .mesh import data_sharding, process_batch_slice
    sharding = NamedSharding(mesh, P(None, *data_sharding(mesh).spec))
    _, n_shards = process_batch_slice(mesh)

    def _make(x):
        global_shape = (x.shape[0], x.shape[1] * n_shards) + x.shape[2:]
        return jax.make_array_from_process_local_data(sharding, x, global_shape)

    return jax.tree_util.tree_map(_make, coerce_batch_dtypes(local_batch))


def _issue_device_put(arrays, devices):
    """The ONE host→device transfer issue point of the coalesced staging
    path: a single batched ``jax.device_put`` call moves every per-device
    staging region of a batch. Module-level so tests can wrap it with a
    counting shim and assert exactly one transfer per training batch."""
    return jax.device_put(arrays, devices)


def put_to_sharding(tree, shardings):
    """Generic host→device placement for the NON-coalesced paths (device
    dataset upload, index batches, per-leaf fallback). This module is the
    single home of ``jax.device_put``: every transfer either funnels
    through ``_issue_device_put`` (coalesced hot path) or this thin
    wrapper, so transfer accounting and the thread-safety story
    (docs/input_pipeline.md) have exactly one file to audit — enforced by
    ``analysis/rules/device_put.py`` (stray-device-put)."""
    return jax.device_put(tree, shardings)


def _device_batch_shards(mesh: Mesh):
    """[(device, batch_shard_id)] for this process's addressable devices,
    ordered by mesh position. shard_id = data_coord * fsdp_size + fsdp_coord
    — the same linearization data_sharding uses for the leading batch dim."""
    ax = {name: i for i, name in enumerate(mesh.axis_names)}
    fsdp_size = mesh.shape.get("fsdp", 1)
    out = []
    pi = jax.process_index()
    for idx in np.ndindex(mesh.devices.shape):
        dev = mesh.devices[idx]
        if dev.process_index != pi:
            continue
        d = idx[ax["data"]] if "data" in ax else 0
        f = idx[ax["fsdp"]] if "fsdp" in ax else 0
        out.append((dev, d * fsdp_size + f))
    return out


def _staging_fields(spec: Tuple, batch_axis: int, b_local: int, pb: int,
                    with_seed: bool):
    """Byte layout of one batch spec inside a per-device staging region:
    ``(fields, region_nbytes, seed_off)``. ``with_seed`` reserves a
    trailing 4-byte slot for the fused-augment RNG counter (see
    ``_build_unpack``) so the per-batch augmentation key rides the ONE
    coalesced transfer instead of costing a second host→device hop.
    Shared by the live ``_StagingLayout`` and the allocation-free
    ``abstract_staged_unpack`` gate path — the two must lay bytes out
    identically or the gate would trace a different program than
    production runs."""
    fields = []
    off = 0
    for key, shape, dtype in spec:
        if len(shape) <= batch_axis or shape[batch_axis] != b_local:
            raise ValueError(
                f"leaf {key!r} shape {shape} does not carry the batch "
                f"dim {b_local} on axis {batch_axis}")
        rest = shape[batch_axis + 1:]
        k_steps = shape[0] if batch_axis == 1 else 1
        nbytes = pb * int(np.prod(rest, dtype=np.int64)) \
            * k_steps * dtype.itemsize
        fields.append((key, shape, dtype, off, int(nbytes)))
        off += (int(nbytes) + 7) // 8 * 8  # 8-byte-align every leaf
    seed_off = None
    if with_seed:
        seed_off = off
        off += 8
    return tuple(fields), off, seed_off


class _StagingLayout:
    """Byte layout of one batch spec inside the coalesced staging buffer,
    plus its reusable host ring and compiled device-side unpack."""

    __slots__ = ("fields", "region_nbytes", "ring_buf", "inflight", "slot",
                 "unpack", "pb", "batch_axis", "seed_off")

    def __init__(self, mesh: Mesh, spec: Tuple, stacked: bool, ring: int,
                 shards, augment: Optional[Tuple] = None,
                 augment_seed: int = 0):
        self.batch_axis = 1 if stacked else 0
        n_shards = batch_shard_count_total(mesh)
        n_local = len({s for _, s in shards})
        b_local = spec[0][1][self.batch_axis]
        if b_local % n_local:
            raise ValueError(
                f"local batch {b_local} not divisible by this process's "
                f"{n_local} batch shards")
        self.pb = b_local // n_local
        self.fields, self.region_nbytes, self.seed_off = _staging_fields(
            spec, self.batch_axis, b_local, self.pb, augment is not None)
        self.ring_buf = np.empty((ring, len(shards), self.region_nbytes),
                                 np.uint8)
        self.inflight: list = [None] * ring
        self.slot = 0
        self.unpack = _build_unpack(mesh, self.fields, stacked, n_shards,
                                    self.pb, augment=augment,
                                    seed_off=self.seed_off,
                                    augment_seed=augment_seed)

    def pack(self, batch, shards, lo_shard: int, ctr: int = 0):
        """Copy each device's rows of every leaf into its staging region
        (one host memcpy pass); returns (slot, per-device uint8 views).
        ``ctr`` is the stager's put counter — written into every shard's
        seed slot when the layout carries a fused augment, so the unpack
        program derives a fresh per-batch RNG key from the staged bytes
        themselves."""
        slot = self.slot
        self.slot = (slot + 1) % len(self.inflight)
        prev = self.inflight[slot]
        if prev is not None:
            # the slot's previous transfer may still be reading the host
            # buffer (async H2D): wait before overwriting
            jax.block_until_ready(prev)
            self.inflight[slot] = None
        buf = self.ring_buf[slot]
        stacked = self.batch_axis == 1
        for di, (_dev, shard) in enumerate(shards):
            r0 = (shard - lo_shard) * self.pb
            r1 = r0 + self.pb
            for key, shape, dtype, off, nbytes in self.fields:
                src = batch[key][:, r0:r1] if stacked else batch[key][r0:r1]
                dst = buf[di, off:off + nbytes].view(dtype)
                np.copyto(dst.reshape(src.shape), src)
        if self.seed_off is not None:
            seed_bytes = np.frombuffer(
                np.uint32(ctr & 0xFFFFFFFF).tobytes(), np.uint8)
            buf[:, self.seed_off:self.seed_off + 4] = seed_bytes
        # (1, region) row views: the per-device shard shape of the global
        # (n_shards, region) flat array
        return slot, [buf[di:di + 1] for di in range(len(shards))]


def batch_shard_count_total(mesh: Mesh) -> int:
    return mesh.shape.get("data", 1) * mesh.shape.get("fsdp", 1)


# unpack programs shared across equal meshes (weak keys: a cache entry dies
# with its mesh instead of pinning device arrays — see mesh.py note)
_UNPACK_CACHE: "weakref.WeakKeyDictionary[Mesh, Dict]" = \
    weakref.WeakKeyDictionary()
_UNPACK_LOCK = threading.Lock()


def _build_unpack(mesh: Mesh, fields: Tuple, stacked: bool, n_shards: int,
                  pb: int, augment: Optional[Tuple] = None,
                  seed_off: Optional[int] = None, augment_seed: int = 0):
    """Compile flat (n_shards, region_bytes) uint8 → the batch pytree.

    Each leaf is sliced out of its shard's region, bitcast to its dtype and
    reshaped back; the shard axis merges into the batch dim. The slicing is
    shard-local, so XLA lowers it to per-device copies — no collectives.

    ``augment`` = (leaf_name, kind, pad) — a hashable spec resolved by
    ``ops.augment.device_augment_fn`` — FUSES the device-side train
    augmentation into this same program: the named leaf (raw uint8 crops)
    comes out flipped/jittered/standardized float32, so augmentation costs
    no extra dispatch and runs exactly once per staged batch. Its RNG key
    derives from a per-put counter embedded in the staged bytes at
    ``seed_off`` (see ``_StagingLayout.pack``) — fresh draws per batch
    with still exactly ONE host→device transfer. Reading that counter
    broadcasts 4 bytes from shard 0 (the one non-shard-local access);
    the augment ops themselves are batch-elementwise and stay shard-local
    under GSPMD. Like the rest of the program, a fused-augment unpack is a
    multi-device execution: consumer-thread dispatch only (StagedBatch).
    """
    from .mesh import data_sharding
    key = (fields, stacked, augment, augment_seed)
    with _UNPACK_LOCK:
        per_mesh = _UNPACK_CACHE.get(mesh)
        if per_mesh is None:
            per_mesh = {}
            _UNPACK_CACHE[mesh] = per_mesh
        hit = per_mesh.get(key)
    if hit is not None:
        return hit
    flat_sh = NamedSharding(mesh, P(("data", "fsdp")))
    leaf_sh = data_sharding(mesh) if not stacked else \
        NamedSharding(mesh, P(None, *data_sharding(mesh).spec))

    def unpack(flat):
        import jax.numpy as jnp
        out = {}
        for name, shape, dtype, off, nbytes in fields:
            jdt = dtype if dtype != np.bool_ else np.dtype(np.uint8)
            seg = jax.lax.slice(flat, (0, off), (n_shards, off + nbytes))
            if stacked:
                k_steps, rest = shape[0], shape[2:]
                tgt = (n_shards, k_steps, pb) + rest
            else:
                rest = shape[1:]
                tgt = (n_shards, pb) + rest
            isize = np.dtype(dtype).itemsize
            if isize > 1:
                seg = seg.reshape(tgt + (isize,))
            else:
                seg = seg.reshape(tgt)
            val = jax.lax.bitcast_convert_type(seg, jdt)
            if dtype == np.bool_:
                val = val.astype(jnp.bool_)
            if stacked:
                val = val.transpose((1, 0, 2) + tuple(
                    range(3, 3 + len(rest))))
                val = val.reshape((shape[0], n_shards * pb) + rest)
            else:
                val = val.reshape((n_shards * pb,) + rest)
            out[name] = val
        if augment is not None:
            from ..ops.augment import device_augment_fn
            leaf_name, kind, pad = augment
            fn = device_augment_fn(kind, pad)
            seg = jax.lax.slice(flat, (0, seed_off), (1, seed_off + 4))
            ctr = jax.lax.bitcast_convert_type(seg.reshape((4,)),
                                               jnp.uint32)
            akey = jax.random.fold_in(
                jax.random.PRNGKey(augment_seed), ctr)
            img = out[leaf_name]
            if stacked:
                # one key per scan step of the fused-loop group, applied
                # with lax.map so the float32 intermediate is one
                # microbatch at a time, not the whole (K, B, ...) group
                keys = jax.random.split(akey, img.shape[0])
                img = jax.lax.map(lambda kv: fn(kv[0], kv[1]),
                                  (img, keys))
            else:
                img = fn(img, akey)
            out[leaf_name] = img
        return out

    out_sh = {name: leaf_sh for name, *_ in fields}
    jitted = jax.jit(unpack, in_shardings=flat_sh, out_shardings=out_sh)
    with _UNPACK_LOCK:
        per_mesh[key] = jitted
    return jitted


def abstract_staged_unpack(mesh: Mesh, batch_shapes: Dict,
                           stacked: bool = False,
                           augment: Optional[Tuple] = None,
                           augment_seed: int = 0):
    """Trace the coalesced unpack(+fused augment) program ABSTRACTLY —
    zero allocation, zero compile — and return its output
    ShapeDtypeStructs. The static-elaboration gate (analysis/elaborate.py)
    calls this per preset so an unpack or fused-augment program that
    cannot trace is a pre-submit finding, not a step-1 crash on the
    cluster. ``batch_shapes`` maps leaf name → ShapeDtypeStruct exactly
    as the host iterator would deliver the batch."""
    spec = tuple(sorted(
        (k, tuple(v.shape), np.dtype(v.dtype))
        for k, v in batch_shapes.items()))
    shards = _device_batch_shards(mesh)
    if not shards:
        raise ValueError("no addressable devices on this process")
    n_local = len({s for _, s in shards})
    batch_axis = 1 if stacked else 0
    b_local = spec[0][1][batch_axis]
    if b_local % n_local:
        raise ValueError(
            f"local batch {b_local} not divisible by this process's "
            f"{n_local} batch shards")
    pb = b_local // n_local
    fields, region, seed_off = _staging_fields(
        spec, batch_axis, b_local, pb, augment is not None)
    n_shards = batch_shard_count_total(mesh)
    unpack = _build_unpack(mesh, fields, stacked, n_shards, pb,
                           augment=augment, seed_off=seed_off,
                           augment_seed=augment_seed)
    return jax.eval_shape(
        unpack, jax.ShapeDtypeStruct((n_shards, region), np.uint8))


class StagedBatch:
    """A batch whose bytes are on device (single coalesced transfer issued)
    but whose leaf arrays are not yet sliced out.

    The split exists for thread safety: the staging thread only MOVES DATA
    (``device_put`` has no cross-device rendezvous, so it is safe to issue
    concurrently with the main thread's jitted steps), while ``finalize()``
    — the tiny compiled unpack program, a multi-device XLA execution —
    must run on the CONSUMER thread. Launching multi-device executions
    from two threads interleaves their per-device enqueue order and can
    deadlock against a collective-bearing train/eval step (observed on the
    CPU backend); dispatching unpack and step from one thread keeps the
    order consistent by construction. Dispatch is async, so none of the
    overlap is lost.
    """

    __slots__ = ("flat", "_unpack")

    def __init__(self, flat, unpack):
        self.flat = flat
        self._unpack = unpack

    def block_until_ready(self):
        """Wait for the host→device transfer (used by the staging thread's
        transfer-time accounting; jax.block_until_ready duck-calls this)."""
        self.flat.block_until_ready()
        return self

    def finalize(self):
        """Slice/bitcast the device-resident bytes into the batch pytree.
        Consumer-thread only (see class docstring)."""
        return self._unpack(self.flat)


def finalize_staged(batch):
    """Resolve a StagedBatch to its pytree; pass anything else through."""
    return batch.finalize() if isinstance(batch, StagedBatch) else batch


# live stagers, for the device-memory telemetry's staging-ring occupancy
# (telemetry/memory.py): weak so a Trainer teardown releases its rings
_LIVE_STAGERS: "weakref.WeakSet" = weakref.WeakSet()


def staging_occupancy() -> Tuple[int, int]:
    """(ring slots, slots with an in-flight H2D transfer) summed across
    every live CoalescedStager's layouts — the staging-ring occupancy the
    ``{"event": "memory"}`` rows report. Lock-free reads of telemetry-
    grade accuracy: a slot flipping mid-scan is off by one for one
    sample."""
    slots = inflight = 0
    for stager in list(_LIVE_STAGERS):
        for layout in list(stager._layouts.values()):
            slots += len(layout.inflight)
            inflight += sum(1 for p in layout.inflight if p is not None)
    return slots, inflight


class CoalescedStager:
    """Coalesced host→device staging: ONE transfer issue per batch.

    Instead of a ``device_put`` per leaf (and per shard under the hood),
    each batch is packed into one contiguous, reused (ring-buffered) host
    staging region per addressable device, moved with a single batched
    ``device_put`` call, and assembled into a global flat array via
    ``make_array_from_single_device_arrays`` (no host-side gather — every
    device receives exactly its shard's bytes). ``put`` returns a
    ``StagedBatch``; the consumer finalizes it into leaf arrays via a tiny
    compiled on-device program (see StagedBatch for why that split is
    load-bearing). Fewer, larger transfers is what moves
    ``device_put_MBps``; the ring means zero per-batch host allocation on
    the hot path.

    ``stacked=True`` stages (K, B, ...) fused-loop batches (batch dim =
    axis 1). Works single- and multi-process (each process contributes its
    addressable devices' regions). Thread-safe: one lock serializes pack +
    issue, so the train and eval staging threads may share a stager.

    Stage counters: pack time → "stage", transfer issue → "transfer"
    (``records_stages`` tells device_prefetch to only add its completion
    wait, not re-count items).

    ``augment`` = (leaf_name, kind, pad): fuse the device-side train
    augmentation for that leaf into the unpack program (see
    ``_build_unpack``) — the imagenet flip/jitter/standardize runs inside
    the one XLA program that already unpacks the staged uint8 buffer,
    drawing fresh RNG per put via a counter embedded in the staged bytes.
    Train-path stagers only: an augmenting stager must never serve eval
    or serving batches (Trainer keeps separate neutral stagers for
    those).
    """

    records_stages = True

    def __init__(self, mesh: Mesh, stacked: bool = False, ring: int = 3,
                 augment: Optional[Tuple] = None, augment_seed: int = 0):
        self.mesh = mesh
        self.stacked = stacked
        self.ring = max(2, ring)
        self.augment = augment
        self.augment_seed = augment_seed
        self._put_ctr = 0
        self._lock = threading.Lock()
        self._layouts: Dict[Tuple, _StagingLayout] = {}
        self._shards = _device_batch_shards(mesh)
        if not self._shards:
            raise ValueError("no addressable devices on this process")
        self._devices = [d for d, _ in self._shards]
        self._n_shards = batch_shard_count_total(mesh)
        self._lo_shard = min(s for _, s in self._shards)
        _LIVE_STAGERS.add(self)  # staging-ring occupancy telemetry

    def _spec_of(self, batch) -> Tuple:
        return tuple(sorted(
            (k, np.shape(v), np.dtype(np.asarray(v).dtype))
            for k, v in batch.items()))

    def __call__(self, batch):
        return self.put(batch)

    def put(self, batch):
        from ..utils.metrics import input_stages
        batch = coerce_batch_dtypes(
            {k: np.asarray(v) for k, v in batch.items()})
        items = 0
        for key in ("labels", "idx"):
            if key in batch:
                items = int(batch[key].size)
                break
        with self._lock:
            t0 = time.perf_counter()
            spec = self._spec_of(batch)
            layout = self._layouts.get(spec)
            if layout is None:
                layout = _StagingLayout(self.mesh, spec, self.stacked,
                                        self.ring, self._shards,
                                        augment=self.augment,
                                        augment_seed=self.augment_seed)
                self._layouts[spec] = layout
            ctr = self._put_ctr
            self._put_ctr += 1
            slot, views = layout.pack(batch, self._shards, self._lo_shard,
                                      ctr)
            t1 = time.perf_counter()
            nbytes = len(views) * layout.region_nbytes
            input_stages.add("stage", t1 - t0, items=items, nbytes=nbytes)
            pieces = _issue_device_put(views, self._devices)
            layout.inflight[slot] = pieces
            flat = jax.make_array_from_single_device_arrays(
                (self._n_shards, layout.region_nbytes),
                NamedSharding(self.mesh, P(("data", "fsdp"))), pieces)
            input_stages.add("transfer", time.perf_counter() - t1,
                             items=items, nbytes=nbytes)
            return StagedBatch(flat, layout.unpack)

    def put_now(self, batch):
        """put + finalize in one call — for single-thread callers (tests,
        step_flops); the pipelined path finalizes on the consumer thread."""
        return self.put(batch).finalize()


def make_global_batch(local_batch: Any, mesh: Mesh) -> Any:
    """Assemble a global jax.Array from per-process local data (multi-host).
    Global batch = local × num distinct batch slices (see
    make_global_stacked_batch)."""
    from .mesh import data_sharding, process_batch_slice
    sharding = data_sharding(mesh)
    _, n_shards = process_batch_slice(mesh)

    def _make(x):
        global_shape = (x.shape[0] * n_shards,) + x.shape[1:]
        return jax.make_array_from_process_local_data(sharding, x, global_shape)

    return jax.tree_util.tree_map(_make, coerce_batch_dtypes(local_batch))
