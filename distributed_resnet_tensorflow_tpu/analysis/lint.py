"""Project-invariant linter driver.

Walks the repository, parses every relevant file ONCE into a
:class:`LintContext`, and runs each rule module from ``rules/`` over it.
Rules are plain modules exposing ``RULE_NAME``, ``DOC`` and
``check(ctx) -> Iterable[Finding]`` — adding a rule is adding a module
and listing it in ``rules.ALL_RULES`` (docs/static_analysis.md).

Suppression: a finding is dropped when its source line (or the line
above) carries ``# shardcheck: ok`` or ``# shardcheck: ok(<rule-name>)``.
Suppressions are for deliberate, reviewed exceptions — the comment is the
audit trail.
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from .report import Finding

PACKAGE = "distributed_resnet_tensorflow_tpu"

_SUPPRESS_RE = re.compile(
    r"#\s*shardcheck:\s*ok(?:\(\s*(?P<rules>[\w\-, ]+)\s*\))?")


@dataclass
class SourceFile:
    """One parsed file. ``tree`` is None for non-Python files (and for
    Python files with syntax errors, which become their own finding)."""

    path: str                    # absolute
    rel: str                     # repo-relative (what findings report)
    text: str
    tree: Optional[ast.AST] = None

    @property
    def lines(self) -> List[str]:
        return self.text.splitlines()


@dataclass
class LintContext:
    root: str
    package_py: List[SourceFile] = field(default_factory=list)
    top_py: List[SourceFile] = field(default_factory=list)     # repo-root *.py
    scripts: List[SourceFile] = field(default_factory=list)    # scripts/*.sh
    docs: List[SourceFile] = field(default_factory=list)       # docs/*.md + README
    parse_errors: List[Finding] = field(default_factory=list)

    def all_python(self) -> List[SourceFile]:
        return self.package_py + self.top_py


def repo_root() -> str:
    """The repository root = parent of the package directory."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def _load(path: str, root: str, python: bool,
          errors: List[Finding]) -> SourceFile:
    with open(path, encoding="utf-8") as f:
        text = f.read()
    rel = os.path.relpath(path, root)
    sf = SourceFile(path=path, rel=rel, text=text)
    if python:
        try:
            sf.tree = ast.parse(text, filename=rel)
        except SyntaxError as e:
            errors.append(Finding("syntax-error", rel, e.lineno or 0,
                                  f"unparseable python: {e.msg}"))
    return sf


def build_context(root: Optional[str] = None) -> LintContext:
    root = root or repo_root()
    ctx = LintContext(root=root)
    pkg_dir = os.path.join(root, PACKAGE)
    for dirpath, dirnames, filenames in os.walk(pkg_dir):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                ctx.package_py.append(_load(os.path.join(dirpath, fn), root,
                                            True, ctx.parse_errors))
    for fn in sorted(os.listdir(root)):
        if fn.endswith(".py"):
            ctx.top_py.append(_load(os.path.join(root, fn), root, True,
                                    ctx.parse_errors))
    scripts_dir = os.path.join(root, "scripts")
    if os.path.isdir(scripts_dir):
        for fn in sorted(os.listdir(scripts_dir)):
            if fn.endswith(".sh"):
                ctx.scripts.append(_load(os.path.join(scripts_dir, fn), root,
                                         False, ctx.parse_errors))
    docs_dir = os.path.join(root, "docs")
    if os.path.isdir(docs_dir):
        for fn in sorted(os.listdir(docs_dir)):
            if fn.endswith(".md"):
                ctx.docs.append(_load(os.path.join(docs_dir, fn), root,
                                      False, ctx.parse_errors))
    readme = os.path.join(root, "README.md")
    if os.path.exists(readme):
        ctx.docs.append(_load(readme, root, False, ctx.parse_errors))
    return ctx


def _suppressed(sf: SourceFile, finding: Finding) -> bool:
    """True when the finding's line (or the line above it) carries a
    ``# shardcheck: ok`` marker naming no rule or this rule."""
    if not finding.line:
        return False
    lines = sf.lines
    for ln in (finding.line, finding.line - 1):
        if 1 <= ln <= len(lines):
            m = _SUPPRESS_RE.search(lines[ln - 1])
            if m:
                named = m.group("rules")
                if named is None:
                    return True
                if finding.rule in {r.strip() for r in named.split(",")}:
                    return True
    return False


def run_lint(root: Optional[str] = None,
             rule_names: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run every (or the named) lint rule; returns unsuppressed findings."""
    from . import rules as rules_pkg
    ctx = build_context(root)
    by_rel: Dict[str, SourceFile] = {
        sf.rel: sf for sf in
        ctx.package_py + ctx.top_py + ctx.scripts + ctx.docs}
    findings = list(ctx.parse_errors)
    for mod in rules_pkg.ALL_RULES:
        if rule_names and mod.RULE_NAME not in rule_names:
            continue
        for f in mod.check(ctx):
            sf = by_rel.get(f.path)
            if sf is not None and _suppressed(sf, f):
                continue
            findings.append(f)
    return findings
