"""Distributed health watchdog: hang detection, peer loss, stragglers.

PR 1 made a single process survive signals, torn checkpoints and NaNs; this
module covers the failures that involve OTHER processes. Synchronous SPMD
training blocks inside a collective when any peer dies or wedges — the
survivors hang until the SLURM wall clock expires, billing an entire
allocation for nothing (the straggler/host-loss regime of arXiv:1811.05233).
The watchdog turns that into a bounded, requeue-able event:

  detection (one daemon thread per process, ticking every ``interval_secs``;
  the zero-I/O local-hang check runs every tick, while the shared-FS beat
  scan — N file opens per poll, O(N²) fleet-wide — runs only every
  ``max(interval_secs, peer_timeout_secs/4)`` so detection never taxes the
  filesystem the checkpoints live on):
    (a) **peer loss** — a peer's beats (resilience/heartbeat.py) stop:
        its latest beat is older than ``peer_timeout_secs`` and its last
        phase was not a deliberate departure (done/preempted).
    (b) **hang** — OUR main thread stops making progress: the publisher's
        ``progress`` counter (train steps + eval batches) is stalled past
        ``max(min_step_timeout_secs, step_timeout_scale × rolling
        per-step-time EWMA)`` while in a monitored phase. The rolling
        deadline means a 50 ms/step CIFAR run is declared hung in seconds,
        a 20 s/step 32k-batch run is not declared hung during a slow step.
    (c) **peer failure** — a peer published a final ``phase="failed"``
        beat: it died on a real error; survivors must stop but the launcher
        must NOT requeue-mask the failure.
    (d) **stragglers** — per-host step-rate skew over a rolling window,
        exported as ``{"event": "straggler"}`` metrics rows (accounting
        only; no teardown).

  escalation for (a)/(b): log + metrics row → request a graceful stop
  through the existing preemption stop path (works when peers are still
  responsive: every process stops at the same boundary, commits the
  preemption checkpoint, exits 75) → after ``grace_secs``, if the process
  is still here (main thread stuck inside a collective that will never
  complete), ``os._exit(75)`` FROM THE DAEMON THREAD — the launcher
  supervisor (launch.py) and the SLURM shim read 75 as "requeue and
  resume". For (c) the hard exit code is 1: a real failure propagates as a
  real failure. Before exiting the verdict is re-verified so a transient
  blip (GC pause, FS hiccup resuming beats) cancels the teardown.

See docs/resilience.md for the exit-code contract and the metrics.jsonl
schemas; tests/test_watchdog.py drives every path with a fake transport
and clock, tests/test_resilience.py kills a live 2-process run.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, Optional

from .heartbeat import (Beat, BeatTransport, DEPARTED_PHASES,
                        HeartbeatPublisher, MONITORED_PHASES, PHASE_FAILED)
from .preemption import FAILURE_EXIT_CODE, RESUMABLE_EXIT_CODE  # noqa: F401

log = logging.getLogger(__name__)


def watchdog_enabled(wd_cfg, process_count: int) -> bool:
    """Resolve the ``resilience.watchdog.enabled`` tri-state: auto = on iff
    the run actually has peers (single-process runs have nothing to watch —
    a local hang there still surfaces via the operator/SLURM timeout)."""
    if wd_cfg.enabled == "on":
        return True
    if wd_cfg.enabled == "off":
        return False
    if wd_cfg.enabled != "auto":
        raise ValueError(
            f"unknown resilience.watchdog.enabled {wd_cfg.enabled!r}")
    return process_count > 1


class Watchdog:
    """One daemon detection thread; all knobs injectable for tests.

    ``request_stop(reason)`` is the graceful path (PreemptionListener's
    stop flag); ``exit_fn`` is the hard path (``os._exit`` — must be safe
    from a non-main thread with the main thread wedged, which rules out
    sys.exit/atexit). ``writer`` (chief-only by convention) receives the
    typed metrics rows; every process still logs.
    """

    def __init__(self, transport: BeatTransport,
                 publisher: HeartbeatPublisher,
                 process_id: int, num_processes: int, cfg,
                 writer=None,
                 request_stop: Optional[Callable[[str], None]] = None,
                 clock=time.monotonic, wall_clock=time.time,
                 exit_fn=os._exit, anomaly_cfg=None):
        self.transport = transport
        self.publisher = publisher
        self.process_id = process_id
        self.num_processes = num_processes
        self.cfg = cfg
        self.writer = writer
        # perf-anomaly sentinel (telemetry.anomaly_* knobs, a
        # TelemetryConfig or None = disabled): the online step-time
        # outlier detector riding this detection thread — see
        # _check_perf_anomaly
        self.anomaly_cfg = anomaly_cfg
        self.request_stop = request_stop
        self._clock = clock
        self._wall = wall_clock
        self._exit_fn = exit_fn
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._disarmed = False
        # verdict state: (kind, exit_code, detail, fired_at_monotonic)
        self._fired: Optional[tuple] = None
        # straggler accounting: pid -> deque[(wall_time, step)]
        self._history: Dict[int, deque] = {}
        self._last_export = self._clock()
        # flight-recorder: one automatic dump per straggler episode — a
        # host flapping around the ratio must not dump every window
        self._straggler_dumped = False
        # peer-loss only needs peer_timeout_secs granularity, so the
        # shared-FS beat scan (N opens per poll; O(N^2) fleet-wide) runs at
        # a fraction of the timeout instead of every tick — only the
        # zero-I/O local-hang check needs the interval_secs cadence
        self._peer_poll_secs = max(cfg.interval_secs,
                                   cfg.peer_timeout_secs / 4.0)
        self._last_peer_poll = float("-inf")
        # perf-anomaly episode state: one firing per slow regime (+ a
        # cooldown), re-armed by the first healthy sample — a
        # persistently slow host must not dump a trace per tick
        self._anomaly_seen_seq = 0
        self._anomaly_active = False
        self._anomaly_last_fire = float("-inf")
        # elastic escalation fork (resilience/elastic.py): hook() -> bool,
        # True while a peer-lost (or collective-hang, see _maybe_exit)
        # hard exit must be DEFERRED because the main thread can reshard
        # into a smaller mesh generation instead of dying 75. The hook
        # owns its own time bound (reshard_timeout_secs) so a wedged
        # transition still exits.
        self._elastic_defer: Optional[Callable[[], bool]] = None

    def set_elastic(self, hook: Optional[Callable[[], bool]]) -> None:
        """Install the elastic runtime's defer hook (main.py wiring)."""
        self._elastic_defer = hook

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "Watchdog":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="drt-watchdog", daemon=True)
            self._thread.start()
        return self

    def disarm(self) -> None:
        """The run is leaving through a legitimate path (finished, preempted,
        failing with its own traceback) — the watchdog must not hard-exit
        out from under the orderly shutdown."""
        self._disarmed = True

    def close(self) -> None:
        self.disarm()
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.cfg.interval_secs + 1.0)
            self._thread = None

    def fired(self) -> Optional[str]:
        """The detection verdict ("peer_lost" | "hang" | "peer_failed"),
        or None."""
        return self._fired[0] if self._fired else None

    # -- detection loop ------------------------------------------------------
    def _run(self) -> None:
        while not self._stop.wait(self.cfg.interval_secs):
            try:
                self._tick(self._clock())
            except Exception:  # detection must never kill the run itself
                log.exception("watchdog tick failed")

    def _tick(self, now: float) -> None:
        # read the beat files only at the slower peer-poll cadence — except
        # while a verdict is pending, when grace re-verification wants the
        # freshest beats it can get (firing is rare; the cost is irrelevant)
        peers: Optional[Dict[int, Beat]] = None
        if self._fired is not None or \
                now - self._last_peer_poll >= self._peer_poll_secs:
            peers = self._poll_peers(now)
        wall_now = self._wall()
        if self._fired is None and not self._disarmed:
            verdict = (self._check_peers(peers, wall_now)
                       if peers is not None else None) \
                or self._check_local_hang(now)
            if verdict is not None:
                self._escalate(*verdict, now=now)
        elif self._fired is not None:
            self._maybe_exit(now, peers)
        if self._fired is None and not self._disarmed:
            # perf-anomaly sentinel: a SLOW step is not a hang — no
            # teardown, no stop request — but it deserves the same
            # flight-recorder evidence a hang gets, while it is happening
            self._check_perf_anomaly(now)
        # chief-only: _export is a no-op without a writer, and the extra
        # beat-directory scan it would force on every non-chief process
        # is exactly the shared-FS tax detection must not impose
        if self.writer is not None and \
                now - self._last_export >= self.cfg.straggler_window_secs:
            self._last_export = now
            if peers is None:
                peers = self._poll_peers(now)
            self._export(peers, wall_now)

    def _poll_peers(self, now: float) -> Dict[int, Beat]:
        peers = self.transport.peers()
        self._last_peer_poll = now
        if self.writer is not None:  # history only feeds _export
            self._record_history(peers, self._wall())
        return peers

    # -- (a)/(c): peers ------------------------------------------------------
    def _check_peers(self, peers: Dict[int, Beat],
                     wall_now: float) -> Optional[tuple]:
        # scan EVERY peer before answering: a fatal `failed` beat must win
        # over another peer's mere staleness, or the 75 would requeue-mask
        # the real failure under SLURM's max-task-code aggregation
        lost: Optional[tuple] = None
        for pid in range(self.num_processes):
            if pid == self.process_id:
                continue
            beat = peers.get(pid)
            if beat is None:
                # never beat in THIS run: bootstrap failures are the
                # distributed-init retry's problem, not ours — flagging
                # here would race every process's startup
                continue
            if beat.phase == PHASE_FAILED:
                return ("peer_failed", FAILURE_EXIT_CODE,
                        f"process {pid} (host {beat.host}) reported a fatal "
                        f"error at step {beat.step}")
            if beat.phase in DEPARTED_PHASES:
                continue
            age = wall_now - beat.wall_time
            if lost is None and age > self.cfg.peer_timeout_secs:
                lost = ("peer_lost", RESUMABLE_EXIT_CODE,
                        f"process {pid} (host {beat.host}, pid {beat.pid}) "
                        f"last beat {age:.1f}s ago at step {beat.step} "
                        f"phase {beat.phase!r}")
        return lost

    # -- (b): local hang -----------------------------------------------------
    def _hang_deadline(self, snap: dict) -> float:
        est = snap.get("ewma_step_secs")
        if est:
            # progress ticks once per fused-loop boundary, not per step —
            # the deadline is per UPDATE: est × stride × scale, or a
            # healthy steps_per_loop=64 scan would read as a hang
            stride = max(1, snap.get("step_stride") or 1)
            return max(self.cfg.min_step_timeout_secs,
                       self.cfg.step_timeout_scale * est * stride)
        return self.cfg.min_step_timeout_secs

    def _check_local_hang(self, now: float) -> Optional[tuple]:
        snap = self.publisher.snapshot()
        if snap["phase"] not in MONITORED_PHASES:
            return None  # init/compile/save legitimately make no progress
        stalled = now - snap["last_progress_t"]
        deadline = self._hang_deadline(snap)
        if stalled > deadline:
            est = snap.get("ewma_step_secs")
            return ("hang", RESUMABLE_EXIT_CODE,
                    f"no progress for {stalled:.1f}s at step {snap['step']} "
                    f"phase {snap['phase']!r} (deadline {deadline:.1f}s"
                    + (f", rolling step time {est:.3f}s" if est else "")
                    + ")")
        return None

    # -- perf-anomaly sentinel ----------------------------------------------
    @staticmethod
    def _median(ordered) -> float:
        mid = len(ordered) // 2
        return ordered[mid] if len(ordered) % 2 else \
            (ordered[mid - 1] + ordered[mid]) / 2.0

    def _check_perf_anomaly(self, now: float) -> None:
        """Online step-time outlier detection (telemetry.anomaly_*): the
        WORST per-step-time sample since the last judgment against the
        preceding window's median + max(anomaly_mad_k × MAD,
        (anomaly_min_ratio − 1) × median). Judging every fresh sample —
        not just the newest — matters because several steps land per
        watchdog tick on a fast run, and a transient 2×-slow step
        followed by fast ones must not slip through the tick phase. MAD
        adapts the threshold to the run's own jitter; the ratio floor
        keeps an ultra-steady run (MAD ≈ 0) from flagging micro-hiccups.
        A hit writes a ``perf_anomaly`` metrics row and dumps the flight
        recorder — evidence while the slowness is LIVE — but never tears
        the run down: slow-but-alive is an observability event, not a
        failure (docs/observability.md)."""
        acfg = self.anomaly_cfg
        if acfg is None or not getattr(acfg, "anomaly_detection", False):
            return
        st = self.publisher.step_times()
        n_new = st["seq"] - self._anomaly_seen_seq
        if n_new <= 0:
            return  # no new sample since the last judgment
        self._anomaly_seen_seq = st["seq"]
        samples = st["samples"]
        min_base = max(4, acfg.anomaly_min_samples)
        # the judged batch never eats into the baseline's minimum — at
        # bootstrap (everything is "fresh") only the tail is judged
        n_new = min(n_new, max(1, len(samples) - min_base))
        base = samples[:-n_new][-max(4, acfg.anomaly_window):]
        if len(base) < min_base:
            return
        newest = max(samples[-n_new:])
        window = sorted(base)
        median = self._median(window)
        mad = self._median(sorted(abs(s - median) for s in window))
        threshold = median + max(acfg.anomaly_mad_k * mad,
                                 (acfg.anomaly_min_ratio - 1.0) * median)
        if newest <= threshold:
            self._anomaly_active = False  # episode over; re-arm
            return
        if self._anomaly_active or \
                now - self._anomaly_last_fire < acfg.anomaly_cooldown_secs:
            return
        self._anomaly_active = True
        self._anomaly_last_fire = now
        snap = self.publisher.snapshot()
        detail = (f"step {snap['step']}: {newest:.3f}s/step vs rolling "
                  f"median {median:.3f}s (MAD {mad:.4f}s, threshold "
                  f"{threshold:.3f}s, window {len(window)}) — slow but "
                  "alive, no teardown")
        log.warning("watchdog: perf anomaly — %s", detail)
        self._write_event("perf_anomaly", {
            "step": snap["step"], "detail": detail,
            "step_secs": round(newest, 6),
            "median_secs": round(median, 6),
            "mad_secs": round(mad, 6),
            "threshold_secs": round(threshold, 6),
            "window": len(window)})
        try:
            from ..telemetry.tracer import recorder
            recorder.dump_on_anomaly("perf_anomaly", detail)
        except Exception:  # pragma: no cover - observability best effort
            log.exception("watchdog: perf-anomaly flight-recorder dump "
                          "failed")

    # -- escalation ----------------------------------------------------------
    def _escalate(self, kind: str, code: int, detail: str,
                  now: float) -> None:
        self._fired = (kind, code, detail, now)
        log.error("watchdog: %s — %s; requesting coordinated stop, hard "
                  "exit %d in %.1fs if the step loop is stuck",
                  kind, detail, code, self.cfg.grace_secs)
        self._write_event(kind, {"detail": detail, "exit_code": code,
                                 "grace_secs": self.cfg.grace_secs})
        # flight recorder: dump the span ring NOW, from this (daemon)
        # thread, while the wedged state is still in memory — the whole
        # reason the recorder exists (telemetry/tracer.py). A hang's dead
        # time is also charged to the goodput "stall" bucket so the
        # breakdown reflects the incident, not just the logs.
        self._flight_record(kind, detail)
        if self.request_stop is not None:
            try:
                self.request_stop(kind)
            except Exception:  # pragma: no cover - stop path best effort
                log.exception("watchdog: graceful stop request failed")

    def _flight_record(self, kind: str, detail: str) -> None:
        try:
            from ..telemetry.tracer import recorder
            if kind == "hang":
                from ..telemetry.goodput import goodput
                snap = self.publisher.snapshot()
                goodput.add("stall",
                            max(0.0, self._clock()
                                - snap["last_progress_t"]))
            recorder.dump_on_anomaly(kind, detail)
        except Exception:  # pragma: no cover - observability best effort
            log.exception("watchdog: flight-recorder dump failed")

    def _fresh_verdict(self, kind: str, code: int, detail: str,
                       peers: Dict[int, Beat], now: float) -> Optional[tuple]:
        """Re-derive the verdict at grace expiry. The situation may have
        CHANGED during the window — notably a peer publishing a final
        ``failed`` beat after we fired ``peer_lost`` must upgrade the exit
        to the failure code, or the SLURM max-task-code aggregation would
        requeue-mask the real error under our 75."""
        if kind == "peer_failed":
            # a published fatal error does not un-happen (and the beat
            # file can vanish with its host — don't re-require it)
            return (kind, code, detail)
        fresh = self._check_peers(peers, self._wall())
        if fresh is None and kind == "hang":
            fresh = self._check_local_hang(now)
        return fresh

    def _maybe_exit(self, now: float, peers: Dict[int, Beat]) -> None:
        kind, code, detail, fired_at = self._fired
        if self._disarmed:
            return
        if now - fired_at < self.cfg.grace_secs:
            return
        fresh = self._fresh_verdict(kind, code, detail, peers, now)
        if fresh is None:
            # transient blip (GC pause, FS hiccup): cancel the teardown.
            # The graceful stop request stays set — stopping resumable on
            # a false alarm is safe; dying on one is not.
            log.warning("watchdog: %s cleared within the grace window "
                        "(%s) — teardown cancelled", kind, detail)
            self._write_event("watchdog_cleared", {"kind": kind})
            self._fired = None
            return
        # the coordinated stop may be succeeding RIGHT NOW even though the
        # verdict still holds (a lost peer's beats stay stale forever): if
        # the main thread is inside the final checkpoint save the stop
        # path exists to commit, exiting would tear that very save.
        # Bounded — a save wedged on the dead peer still dies at the cap.
        if self.publisher.snapshot()["phase"] == "save" and \
                now - fired_at < max(self.cfg.grace_secs,
                                     self.cfg.min_step_timeout_secs):
            return
        # elastic fork: a lost peer is not a death sentence when the main
        # thread can reshard — hold the 75 back while the hook says the
        # transition is possible/in progress (it returns False once its
        # reshard_timeout_secs bound expires, restoring the requeue path).
        # A HANG verdict defers too, but only while our own phase is
        # 'train': blocked inside a collective means the stall is
        # plausibly a PEER's (the culprit's own verdict reads phase
        # 'data'/'eval' and exits promptly; once it dies, our wedged
        # collective raises and failure_verdict attributes the peer loss
        # on the main thread). A hang in the 'data' phase is OUR input
        # pipeline — exit now so an elastic fleet can shrink around us.
        deferrable = fresh[0] == "peer_lost" or (
            fresh[0] == "hang"
            and self.publisher.snapshot()["phase"] == "train")
        if deferrable and self._elastic_defer is not None:
            try:
                if self._elastic_defer():
                    return
            except Exception:  # never let the hook break the escalation
                log.exception("watchdog: elastic defer hook failed")
        self.exit_now(*fresh)

    def exit_now(self, kind: str, code: int, detail: str) -> None:
        """Hard teardown: flush observability, then ``os._exit`` — the only
        exit that works from a daemon thread while the main thread is wedged
        in a collective (sys.exit would run atexit, whose
        jax.distributed.shutdown barrier blocks on the very peers that are
        gone)."""
        if self._disarmed:
            # the main thread disarmed while the daemon was inside the
            # (slow, shared-FS) verdict re-check: the run is leaving
            # through an orderly path — exiting now would 75 a run that
            # actually completed
            log.warning("watchdog: %s verdict overtaken by an orderly "
                        "shutdown — exit suppressed (%s)", kind, detail)
            return
        log.error("watchdog: %s — exiting %d for the launcher/SLURM requeue "
                  "contract (%s)", kind, code, detail)
        self._write_event("watchdog_exit", {"kind": kind, "exit_code": code,
                                            "detail": detail})
        if self.writer is not None:
            try:
                self.writer.flush()
            except Exception:  # pragma: no cover
                pass
        logging.shutdown()
        self._exit_fn(code)

    # -- exception-path classification --------------------------------------
    def failure_verdict(self, wait_secs: Optional[float] = None,
                        poll_secs: float = 0.25) -> Optional[tuple]:
        """Called from the MAIN thread after a collective/runtime error: was
        it caused by a peer dying? Gloo/coordination errors surface within
        milliseconds of a peer's death — before its beats are stale — so
        this polls up to ``wait_secs`` (default: peer_timeout + 2 beat
        intervals) for the beats to confirm. Returns (kind, exit_code,
        detail) or None (no peer evidence: the error is OURS)."""
        if self._fired is not None and self._fired[0] != "hang":
            return self._fired[:3]
        # a pending HANG verdict does not bind this path: the collective
        # raising IS new evidence that the stall was a peer's death (the
        # daemon's elastic fork is deferring that 75 right now) — fall
        # through to the beat poll so the verdict names the peer
        if wait_secs is None:
            wait_secs = self.cfg.peer_timeout_secs + 2 * self.cfg.interval_secs
        deadline = self._clock() + wait_secs
        while True:
            verdict = self._check_peers(self.transport.peers(), self._wall())
            if verdict is not None:
                self._write_event(verdict[0], {
                    "detail": verdict[2], "exit_code": verdict[1],
                    "via": "collective_error"})
                # this path bypasses _escalate (the verdict came from the
                # main thread's exception) — the dump must still happen
                self._flight_record(verdict[0], verdict[2])
                return verdict
            if self._clock() >= deadline:
                return None
            time.sleep(poll_secs)

    # -- (d): straggler accounting + heartbeat export ------------------------
    def _record_history(self, peers: Dict[int, Beat],
                        wall_now: float) -> None:
        horizon = 2 * self.cfg.straggler_window_secs
        for pid, beat in peers.items():
            hist = self._history.setdefault(pid, deque())
            if not hist or beat.wall_time > hist[-1][0]:
                hist.append((beat.wall_time, beat.step))
            while hist and hist[0][0] < wall_now - horizon:
                hist.popleft()

    def _rates(self, wall_now: float) -> Dict[int, float]:
        out: Dict[int, float] = {}
        cutoff = wall_now - self.cfg.straggler_window_secs
        for pid, hist in self._history.items():
            window = [(t, s) for t, s in hist if t >= cutoff]
            if len(window) >= 2 and window[-1][0] > window[0][0]:
                out[pid] = (window[-1][1] - window[0][1]) / \
                    (window[-1][0] - window[0][0])
        return out

    def _export(self, peers: Dict[int, Beat], wall_now: float) -> None:
        if self.writer is None or not peers:
            return
        hosts = {str(pid): {"step": b.step, "progress": b.progress,
                            "phase": b.phase, "host": b.host,
                            "age_secs": round(wall_now - b.wall_time, 3)}
                 for pid, b in sorted(peers.items())}
        self._write_event("heartbeat", {"hosts": hosts})
        rates = self._rates(wall_now)
        if not rates:
            return
        # true median: the upper-middle element alone would be the MAX in
        # a 2-host world, flagging against the fastest host instead
        median = self._median(sorted(rates.values()))
        max_step = max(b.step for b in peers.values())
        flagged = sorted(
            pid for pid, r in rates.items()
            if median > 0 and r > 0 and median / r >= self.cfg.straggler_ratio)
        for pid in flagged:
            log.warning(
                "watchdog: process %d is a straggler: %.2f steps/s vs "
                "median %.2f over the last %.0fs window", pid, rates[pid],
                median, self.cfg.straggler_window_secs)
        if flagged and not self._straggler_dumped:
            # straggler ESCALATION (first flag of the run): leave a
            # flight-recorder dump so "why is host 3 slow" starts from
            # what its threads were doing, not from a re-run
            self._straggler_dumped = True
            self._flight_record(
                "straggler",
                f"processes {flagged} slower than median by >= "
                f"{self.cfg.straggler_ratio}x")
        elif not flagged:
            self._straggler_dumped = False  # episode over; re-arm
        self._write_event("straggler", {
            "window_secs": self.cfg.straggler_window_secs,
            "rates": {str(pid): round(r, 4) for pid, r in sorted(rates.items())},
            "median": round(median, 4),
            "lag_steps": {str(pid): int(max_step - b.step)
                          for pid, b in sorted(peers.items())},
            "flagged": flagged,
        })

    def _write_event(self, event: str, payload: dict) -> None:
        if self.writer is None:
            return
        try:
            self.writer.write_event(event, payload)
        except Exception:  # pragma: no cover - observability best effort
            log.exception("watchdog: metrics event %r failed", event)
