"""Pre-activation ResNet v2 — TPU-native functional re-design.

Capability parity with the reference graph builders
(reference resnet_model_official.py):
  * CIFAR variant: 6n+2 layers, 3 stages of 16/32/64 filters, stem 3x3 conv,
    final BN+ReLU + global average pool + dense head
    (reference resnet_model_official.py:217-278), generalized with a
    ``width_multiplier`` for Wide-ResNet-28-10.
  * ImageNet variant: 7x7/2 stem + 3x3/2 maxpool, 4 stages 64/128/256/512,
    sizes 18/34/50/101/152/200 via a block-count table
    (reference resnet_model_official.py:281-359).
  * Fixed padding for strided convs (reference resnet_model_official.py:53-91).
  * BatchNorm momentum 0.997, eps 1e-5 (reference resnet_model_official.py:37-38).

TPU-first design decisions (NOT in the reference):
  * NHWC only — the layout XLA:TPU prefers; the reference's NCHW/NHWC switch
    (resnet_model_official.py:244-248) existed for cuDNN and is dropped.
  * bfloat16 compute / float32 params & batch stats (MXU-native mixed precision).
  * Cross-replica batch norm: under ``jit`` over a sharded batch the moments are
    global by construction (XLA inserts the all-reduce); under ``shard_map`` /
    ``pmap`` pass ``axis_name`` to get an explicit ``lax.pmean`` of moments.
    This fixes the per-replica-BN accuracy gap the reference documented
    (reference README.md:38,54).
  * Optional ``remat`` (jax.checkpoint) on residual stages to trade FLOPs for
    HBM when scaling batch size.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

ModuleDef = Any

# Block-count table for ImageNet sizes (reference resnet_model_official.py:352-359).
IMAGENET_MODEL_PARAMS = {
    18: ("building", (2, 2, 2, 2)),
    34: ("building", (3, 4, 6, 3)),
    50: ("bottleneck", (3, 4, 6, 3)),
    101: ("bottleneck", (3, 4, 23, 3)),
    152: ("bottleneck", (3, 8, 36, 3)),
    200: ("bottleneck", (3, 24, 36, 3)),
}


# Round-4 negative result (docs/perf_imagenet_r4.md): re-expressing the
# stem 3x3/2 max pool as an elementwise max over the 9 shifted strided
# views — to replace the backward's serial select_and_scatter (1.3 ms/step,
# docs/perf_imagenet_r3_ops.json) with fusable masks — measured 12 ms/step
# WORSE (57.5 vs 45.0 ms): the nine [N,114,114,64] mask+pad passes the
# autodiff produces cost ~10x the op they remove. reduce_window stays.


class ConvFixedPadding(nn.Module):
    """Conv with SAME padding for stride 1, explicit fixed padding otherwise
    (reference resnet_model_official.py:80-91).

    The fixed padding is folded into the conv op's own low/high padding
    rather than materialized as a separate ``jnp.pad`` — numerically
    identical (conv with explicit padding == pad + VALID by definition of
    ``lax.conv_general_dilated``) but it removes a standalone ``pad`` HLO
    per strided conv that XLA was executing unfused (measured 0.6 ms/step
    on ImageNet RN50 bs128, docs/perf_imagenet_r3_ops.json)."""

    filters: int
    kernel_size: int
    strides: int = 1
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        if self.strides > 1:
            pad_total = self.kernel_size - 1
            pad = (pad_total // 2, pad_total - pad_total // 2)
            padding = (pad, pad)
        else:
            padding = "SAME"
        return nn.Conv(
            self.filters,
            (self.kernel_size, self.kernel_size),
            strides=(self.strides, self.strides),
            padding=padding,
            use_bias=False,
            kernel_init=nn.initializers.variance_scaling(2.0, "fan_out", "truncated_normal"),
            dtype=self.dtype,
            param_dtype=self.param_dtype,
        )(x)


class StemConv(nn.Module):
    """The ImageNet 7x7/2 stem conv, optionally evaluated via
    space-to-depth.

    The plain formulation gives the MXU a contraction of 7·7·3 with only
    3 input channels — a shape XLA tiles poorly. With
    ``space_to_depth=True`` the SAME arithmetic is re-expressed: the input
    is rearranged [N,224,224,3] → [N,115,115,12] (2×2 pixel blocks into
    channels) and the kernel [7,7,3,F] → [4,4,12,F] (zero-padded to 8 taps,
    split even/odd), turning the stem into a 4×4/1 conv whose taps align
    with the block grid. Weights are stored in the canonical [7,7,3,F]
    layout either way, so checkpoints are mode-portable. Equivalence is
    exact in math (same multiply-adds, reassociated) and pinned by
    tests/test_models.py::test_stem_space_to_depth_parity.
    """

    filters: int = 64
    space_to_depth: bool = False
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        f = self.filters
        w = self.param(
            "kernel",
            nn.initializers.variance_scaling(2.0, "fan_out",
                                             "truncated_normal"),
            (7, 7, 3, f), self.param_dtype)
        dn = ("NHWC", "HWIO", "NHWC")
        if not self.space_to_depth:
            return jax.lax.conv_general_dilated(
                x, w.astype(self.dtype), (2, 2), ((3, 3), (3, 3)),
                dimension_numbers=dn)
        n, h, wd, c = x.shape
        if h % 2 or wd % 2 or c != 3:
            raise ValueError(
                f"space-to-depth stem needs even HxW RGB input, got {x.shape}")
        # kernel: zero tap at the BEGINNING of each spatial dim (k 7→8), so
        # with input padding (4, 2) every tap p = 2·out + a lands at
        # s2d cell (out + a//2, a%2) — a VALID 4×4 conv over the s2d grid
        w8 = jnp.pad(w, ((1, 0), (1, 0), (0, 0), (0, 0)))
        w4 = w8.reshape(4, 2, 4, 2, 3, f).transpose(0, 2, 1, 3, 4, 5) \
               .reshape(4, 4, 12, f)
        xp = jnp.pad(x, ((0, 0), (4, 2), (4, 2), (0, 0)))
        hs, ws = (h + 6) // 2, (wd + 6) // 2
        xs = xp.reshape(n, hs, 2, ws, 2, c).transpose(0, 1, 3, 2, 4, 5) \
               .reshape(n, hs, ws, 4 * c)
        return jax.lax.conv_general_dilated(
            xs, w4.astype(self.dtype), (1, 1), "VALID",
            dimension_numbers=dn)


class BatchNormRelu(nn.Module):
    """Normalization + ReLU, dispatched on ``norm``:

      * "batch"  — BN (momentum 0.997, eps 1e-5 — reference
        resnet_model_official.py:37-48). Stats in float32. ``groups=1`` →
        cross-replica BN (global moments); ``groups=G`` → per-replica/
        reference BN numerics (ops/batch_norm.py). ``axis_name`` adds
        explicit pmean under shard_map.
      * "frozen" — BN applied from the RUNNING statistics even in training
        (the trainable frozen-BN fine-tune contract): scale/bias still
        learn, the batch-moment passes and their cross-replica semantics
        disappear, stats never update. From-scratch this is a learned
        per-channel affine (stats stay at init 0/1); from a checkpoint it
        is classic frozen-BN fine-tuning.
      * "group"  — GroupNorm over ``norm_groups`` channel groups
        (ops/batch_norm.ChannelGroupNorm): batch-independent, stateless,
        no train/eval split — the BN-free training contract.
    """

    momentum: float = 0.997
    epsilon: float = 1e-5
    dtype: Any = jnp.bfloat16
    axis_name: Optional[str] = None
    groups: int = 1
    relu: bool = True
    stat_subsample: int = 1
    norm: str = "batch"
    norm_groups: int = 32

    @nn.compact
    def __call__(self, x: jax.Array, train: bool) -> jax.Array:
        if self.norm == "group":
            from ..ops.batch_norm import ChannelGroupNorm
            x = ChannelGroupNorm(groups=self.norm_groups,
                                 epsilon=self.epsilon,
                                 dtype=self.dtype)(x, train)
        elif self.norm in ("batch", "frozen"):
            from ..ops.batch_norm import GroupedBatchNorm
            x = GroupedBatchNorm(
                momentum=self.momentum,
                epsilon=self.epsilon,
                dtype=self.dtype,
                groups=self.groups,
                axis_name=self.axis_name,
                stat_subsample=self.stat_subsample,
            )(x, train and self.norm != "frozen")
        else:
            raise ValueError(
                f"model.norm must be batch|frozen|group, got {self.norm!r}")
        if self.relu:
            x = nn.relu(x)
        return x


class BuildingBlock(nn.Module):
    """v2 building block: BN-ReLU preact → 3x3 conv (stride) → BN-ReLU → 3x3
    conv, identity/projection shortcut taken after the preact
    (reference resnet_model_official.py:94-130)."""

    filters: int
    strides: int
    use_projection: bool
    dtype: Any = jnp.bfloat16
    axis_name: Optional[str] = None
    bn_groups: int = 1
    bn_momentum: float = 0.997
    bn_epsilon: float = 1e-5
    bn_stat_subsample: int = 1
    norm: str = "batch"
    norm_groups: int = 32

    @nn.compact
    def __call__(self, x: jax.Array, train: bool) -> jax.Array:
        bn = partial(BatchNormRelu, momentum=self.bn_momentum,
                     epsilon=self.bn_epsilon, dtype=self.dtype,
                     axis_name=self.axis_name, groups=self.bn_groups,
                     stat_subsample=self.bn_stat_subsample,
                     norm=self.norm, norm_groups=self.norm_groups)
        conv = partial(ConvFixedPadding, dtype=self.dtype)
        shortcut = x
        x = bn()(x, train)
        if self.use_projection:
            shortcut = conv(self.filters, 1, self.strides)(x)
        x = conv(self.filters, 3, self.strides)(x)
        x = bn()(x, train)
        x = conv(self.filters, 3, 1)(x)
        return x + shortcut


class BottleneckBlock(nn.Module):
    """v2 bottleneck: preact → 1x1 f → 3x3 f (stride) → 1x1 4f
    (reference resnet_model_official.py:133-175)."""

    filters: int
    strides: int
    use_projection: bool
    dtype: Any = jnp.bfloat16
    axis_name: Optional[str] = None
    bn_groups: int = 1
    bn_momentum: float = 0.997
    bn_epsilon: float = 1e-5
    bn_stat_subsample: int = 1
    norm: str = "batch"
    norm_groups: int = 32

    @nn.compact
    def __call__(self, x: jax.Array, train: bool) -> jax.Array:
        bn = partial(BatchNormRelu, momentum=self.bn_momentum,
                     epsilon=self.bn_epsilon, dtype=self.dtype,
                     axis_name=self.axis_name, groups=self.bn_groups,
                     stat_subsample=self.bn_stat_subsample,
                     norm=self.norm, norm_groups=self.norm_groups)
        conv = partial(ConvFixedPadding, dtype=self.dtype)
        shortcut = x
        x = bn()(x, train)
        if self.use_projection:
            shortcut = conv(4 * self.filters, 1, self.strides)(x)
        x = conv(self.filters, 1, 1)(x)
        x = bn()(x, train)
        x = conv(self.filters, 3, self.strides)(x)
        x = bn()(x, train)
        x = conv(4 * self.filters, 1, 1)(x)
        return x + shortcut


class BlockLayer(nn.Module):
    """One stage: first block projects + strides, the rest are identity
    (reference resnet_model_official.py:178-214)."""

    block_cls: Callable[..., nn.Module]
    filters: int
    num_blocks: int
    strides: int
    dtype: Any = jnp.bfloat16
    axis_name: Optional[str] = None
    bn_groups: int = 1
    remat: bool = False
    bn_momentum: float = 0.997
    bn_epsilon: float = 1e-5
    bn_stat_subsample: int = 1
    norm: str = "batch"
    norm_groups: int = 32

    @nn.compact
    def __call__(self, x: jax.Array, train: bool) -> jax.Array:
        block_cls = self.block_cls
        if self.remat:
            block_cls = nn.remat(block_cls, static_argnums=(2,))
        for i in range(self.num_blocks):
            x = block_cls(
                filters=self.filters,
                strides=self.strides if i == 0 else 1,
                use_projection=(i == 0),
                dtype=self.dtype,
                axis_name=self.axis_name,
                bn_groups=self.bn_groups,
                bn_momentum=self.bn_momentum,
                bn_epsilon=self.bn_epsilon,
                bn_stat_subsample=self.bn_stat_subsample,
                norm=self.norm, norm_groups=self.norm_groups,
            )(x, train)
        return x


class CifarResNetV2(nn.Module):
    """CIFAR ResNet v2 generator: 6n+2 layers
    (reference resnet_model_official.py:217-278), widened by
    ``width_multiplier`` (Wide-ResNet-28-10 = size 28, width 10)."""

    resnet_size: int = 50
    num_classes: int = 10
    width_multiplier: int = 1
    dtype: Any = jnp.bfloat16
    axis_name: Optional[str] = None
    bn_groups: int = 1
    remat: bool = False
    bn_momentum: float = 0.997
    bn_epsilon: float = 1e-5
    bn_stat_subsample: int = 1
    norm: str = "batch"
    norm_groups: int = 32

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        # classic preact convention 6n+2 (reference resnet_model_official.py:231);
        # Wide-ResNet papers count the same topology as 6n+4 (WRN-28-10 → n=4)
        if (self.resnet_size - 2) % 6 == 0:
            num_blocks = (self.resnet_size - 2) // 6
        elif (self.resnet_size - 4) % 6 == 0:
            num_blocks = (self.resnet_size - 4) // 6
        else:
            raise ValueError(
                f"cifar resnet_size must be 6n+2 or 6n+4, got {self.resnet_size}")
        k = self.width_multiplier
        x = x.astype(self.dtype)
        x = ConvFixedPadding(16, 3, 1, dtype=self.dtype)(x)
        for i, (filters, strides) in enumerate(((16 * k, 1), (32 * k, 2), (64 * k, 2))):
            x = BlockLayer(
                block_cls=BuildingBlock, filters=filters, num_blocks=num_blocks,
                strides=strides, dtype=self.dtype, axis_name=self.axis_name,
                bn_groups=self.bn_groups, remat=self.remat,
                bn_momentum=self.bn_momentum, bn_epsilon=self.bn_epsilon,
                bn_stat_subsample=self.bn_stat_subsample,
                norm=self.norm, norm_groups=self.norm_groups,
            )(x, train)
        x = BatchNormRelu(momentum=self.bn_momentum, epsilon=self.bn_epsilon,
                          dtype=self.dtype, axis_name=self.axis_name,
                          groups=self.bn_groups,
                          stat_subsample=self.bn_stat_subsample,
                          norm=self.norm,
                          norm_groups=self.norm_groups)(x, train)
        x = jnp.mean(x, axis=(1, 2))  # global avg pool (8x8 at 32px input)
        x = x.astype(jnp.float32)
        return nn.Dense(self.num_classes,
                        kernel_init=nn.initializers.variance_scaling(1.0, "fan_in", "truncated_normal"),
                        dtype=jnp.float32)(x)


class ImageNetResNetV2(nn.Module):
    """ImageNet ResNet v2 generator
    (reference resnet_model_official.py:281-359)."""

    resnet_size: int = 50
    num_classes: int = 1001
    dtype: Any = jnp.bfloat16
    axis_name: Optional[str] = None
    bn_groups: int = 1
    remat: bool = False
    bn_momentum: float = 0.997
    bn_epsilon: float = 1e-5
    bn_stat_subsample: int = 1
    norm: str = "batch"
    norm_groups: int = 32
    stem_space_to_depth: bool = False

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        if self.resnet_size not in IMAGENET_MODEL_PARAMS:
            raise ValueError(
                f"imagenet resnet_size must be one of {sorted(IMAGENET_MODEL_PARAMS)}, "
                f"got {self.resnet_size}")
        block_kind, block_counts = IMAGENET_MODEL_PARAMS[self.resnet_size]
        block_cls = BottleneckBlock if block_kind == "bottleneck" else BuildingBlock

        x = x.astype(self.dtype)
        x = StemConv(64, space_to_depth=self.stem_space_to_depth,
                     dtype=self.dtype)(x)
        # reference semantics: tf.layers.max_pooling2d(..., padding='SAME')
        # (resnet_model_official.py:314-316) — SAME maxpool pads with -inf
        # (padding never wins the max) and at 112/2 pads (0,1), NOT the
        # zero-pad (1,1) this model used through round 3; SAME is both the
        # faithful geometry and one fused op cheaper (no standalone pad HLO)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, num_blocks in enumerate(block_counts):
            x = BlockLayer(
                block_cls=block_cls, filters=64 * (2 ** i), num_blocks=num_blocks,
                strides=1 if i == 0 else 2, dtype=self.dtype,
                axis_name=self.axis_name, bn_groups=self.bn_groups,
                remat=self.remat, bn_momentum=self.bn_momentum,
                bn_epsilon=self.bn_epsilon,
                bn_stat_subsample=self.bn_stat_subsample,
                norm=self.norm, norm_groups=self.norm_groups,
            )(x, train)
        x = BatchNormRelu(momentum=self.bn_momentum, epsilon=self.bn_epsilon,
                          dtype=self.dtype, axis_name=self.axis_name,
                          groups=self.bn_groups,
                          stat_subsample=self.bn_stat_subsample,
                          norm=self.norm,
                          norm_groups=self.norm_groups)(x, train)
        x = jnp.mean(x, axis=(1, 2))  # global avg pool (7x7 at 224px input)
        x = x.astype(jnp.float32)
        return nn.Dense(self.num_classes,
                        kernel_init=nn.initializers.variance_scaling(1.0, "fan_in", "truncated_normal"),
                        dtype=jnp.float32)(x)


def create_model(model_cfg, dataset: str, axis_name: Optional[str] = None,
                 remat: bool = False, bn_groups: int = 1,
                 mesh=None, compute_dtype=None) -> nn.Module:
    """Model factory; replaces the dataset dispatch in reference
    resnet_model.py:69-76 (which hard-coded resnet_size=50 for both).

    ``compute_dtype`` overrides ``model_cfg.compute_dtype`` — the
    mixed-precision policy's hook (parallel/precision.py: the Trainer
    passes the policy dtype; the serving variant builder passes the
    variant dtype). None keeps the legacy per-family contract, including
    the logistic toy's pinned-f32 compute."""
    dtype = jnp.dtype(compute_dtype) if compute_dtype is not None \
        else jnp.dtype(model_cfg.compute_dtype)
    if model_cfg.name == "logistic":
        from .logistic import LogisticNet
        # the toy MLP historically ignored compute_dtype (f32 always);
        # only an explicit policy/variant override changes its compute —
        # the legacy path must stay bit-identical
        return LogisticNet(num_classes=model_cfg.num_classes,
                           hidden_units=model_cfg.hidden_units,
                           dtype=dtype if compute_dtype is not None
                           else jnp.float32)
    if model_cfg.name == "vit":
        from .transformer import VisionTransformer
        attn = model_cfg.attention_impl
        seq = mesh.shape.get("seq", 1) if mesh is not None else 1
        if attn == "auto" and seq > 1:
            # a seq axis routes through ring attention (sequence parallel);
            # the remaining flash-vs-dense choice is made at trace time
            # where the true token count is known. transformer._apply_attention
            # applies the SAME rules for direct VisionTransformer users — this
            # early resolution only makes model.attention_impl introspectable
            attn = "ring"
        if attn == "ring" and seq <= 1:
            raise ValueError(
                "attention_impl='ring' requires mesh.sequence > 1")
        return VisionTransformer(
            num_classes=model_cfg.num_classes,
            patch_size=model_cfg.vit_patch_size,
            dim=model_cfg.vit_dim, depth=model_cfg.vit_depth,
            num_heads=model_cfg.vit_heads, dtype=dtype,
            attention_impl=attn, remat=remat, mesh=mesh,
            pipeline_microbatches=model_cfg.vit_pipeline_microbatches,
            pipeline_interleave=model_cfg.vit_pipeline_interleave,
            num_experts=model_cfg.vit_num_experts,
            expert_capacity_factor=model_cfg.vit_expert_capacity_factor,
            moe_top_k=model_cfg.vit_moe_top_k,
            moe_dispatch=model_cfg.vit_moe_dispatch)
    if dataset in ("cifar10", "cifar100", "synthetic"):
        return CifarResNetV2(
            resnet_size=model_cfg.resnet_size,
            num_classes=model_cfg.num_classes,
            width_multiplier=model_cfg.width_multiplier,
            dtype=dtype, axis_name=axis_name, bn_groups=bn_groups, remat=remat,
            bn_momentum=model_cfg.bn_momentum, bn_epsilon=model_cfg.bn_epsilon,
            bn_stat_subsample=model_cfg.bn_stat_subsample,
            norm=model_cfg.norm, norm_groups=model_cfg.gn_groups)
    if dataset == "imagenet":
        return ImageNetResNetV2(
            resnet_size=model_cfg.resnet_size,
            num_classes=model_cfg.num_classes,
            dtype=dtype, axis_name=axis_name, bn_groups=bn_groups, remat=remat,
            bn_momentum=model_cfg.bn_momentum, bn_epsilon=model_cfg.bn_epsilon,
            bn_stat_subsample=model_cfg.bn_stat_subsample,
            norm=model_cfg.norm, norm_groups=model_cfg.gn_groups,
            stem_space_to_depth=model_cfg.stem_space_to_depth)
    raise ValueError(f"unknown dataset {dataset!r}")


def count_params(params) -> int:
    return sum(p.size for p in jax.tree_util.tree_leaves(params))
