"""Checkpoint management — crash-consistent, verified, auto-resuming.

Capability parity with the reference's checkpointing (SURVEY.md §2.14):
  * chief-written, time-based checkpoints every 60 s (CIFAR) / 600 s
    (ImageNet) via ``MonitoredTrainingSession(save_checkpoint_secs=...)``
    (reference resnet_cifar_main.py:327-329, resnet_imagenet_main.py:250-261),
  * automatic resume from the latest checkpoint on restart
    (MonitoredTrainingSession semantics),
  * read-only polling restore for the evaluator
    (reference resnet_cifar_eval.py:101-109).

Beyond the reference, saves are CRASH-CONSISTENT (resilience/manifest.py):
arrays serialize (orbax) into a staging dir, a manifest with per-file sizes
and SHA-256 checksums is fsynced, and a single atomic rename commits the
step — a preemption or crash at any instant leaves either a fully-committed
checkpoint or none, never a torn one under a committed name. ``restore()``
verifies the manifest and, instead of crashing on damage, falls back to the
newest OLDER checkpoint that still verifies; ``wait_for_new_checkpoint``
(the evaluator's polling primitive) only ever reports committed steps. The
reference's ``tf.train.Saver``/``latest_checkpoint`` pair trusted the
filesystem blindly on both counts.

TPU-native as before: checkpoints are sharded-array aware (every process
participates in saving its shards), saves can be asynchronous (training
continues while the previous state serializes from a host-side snapshot),
and step- and time-based cadences are supported simultaneously.
"""
from __future__ import annotations

import logging
import os
import shutil
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, List, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from ..resilience.manifest import (committed_steps, manifest_status,
                                   staging_path, sweep_staging,
                                   fsync_dir, write_manifest)
from ..resilience.retry import retry_call
from ..telemetry.tracer import span

log = logging.getLogger(__name__)

_PAYLOAD_DIR = "data"          # our layout: <dir>/<step>/data/...
_LEGACY_PAYLOAD_DIR = "default"  # pre-manifest orbax CheckpointManager layout


class CheckpointCorrupt(RuntimeError):
    """An explicitly-requested checkpoint failed verification/restore."""


def _saveable(state) -> dict:
    """The pytree part of a TrainState (drops static apply_fn/tx)."""
    return {
        "step": state.step,
        "params": state.params,
        "batch_stats": state.batch_stats,
        "opt_state": state.opt_state,
    }


def _host_snapshot(tree):
    """Device→host copy of every jax.Array leaf, so an async write can
    proceed while the train loop donates/overwrites the live buffers (same
    contract orbax's async checkpointing provides).

    Two passes: the first ISSUES every copy asynchronously
    (``copy_to_host_async`` — the transfers land in the runtime's pinned
    staging buffers and run back-to-back on the D2H stream), the second
    materializes them. The loop thread therefore pays ONE overlapped
    transfer of the whole state instead of len(leaves) serial round-trips
    — the snapshot cost the goodput ``checkpoint`` bucket charges. The
    wait itself cannot move off this thread: the caller is about to
    donate these buffers to the next step."""
    leaves = jax.tree_util.tree_leaves(tree)
    for x in leaves:
        if isinstance(x, jax.Array):
            try:
                x.copy_to_host_async()
            except Exception:  # backend without async copies — pass 2 blocks
                break
    return jax.tree_util.tree_map(
        lambda x: np.asarray(x) if isinstance(x, jax.Array) else x, tree)


class CheckpointManager:
    """Commit-protocol checkpoint store with the save-cadence policy.

    save cadence = step-based (``save_every_steps``) OR time-based
    (``save_every_secs``), whichever fires first — the reference only had the
    time axis (reference resnet_cifar_main.py:329).
    """

    def __init__(self, directory: str, max_to_keep: int = 5,
                 save_every_steps: int = 0, save_every_secs: float = 0.0,
                 async_save: bool = True,
                 layout_stamp: Optional[dict] = None,
                 verify_on_restore: bool = True,
                 io_retries: int = 3,
                 writer: bool = True,
                 sharded: str = "auto",
                 finalize_timeout_secs: float = 300.0):
        # layout_stamp: declares how depth-stacked params are ORDERED (the
        # circular pipeline schedule stores stage-major order, a function of
        # (pstages, interleave) — models/pipeline.py). Saved as a sidecar so
        # a restore under a different layout fails loudly instead of running
        # layers in a silently-permuted network order.
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._layout_stamp = layout_stamp
        self.save_every_steps = save_every_steps
        self.save_every_secs = save_every_secs
        self.max_to_keep = max_to_keep
        self.verify_on_restore = verify_on_restore
        self.io_retries = io_retries
        self._last_save_time = time.monotonic()
        self._last_save_step = 0
        # a truly SYNCHRONOUS checkpointer (ocp.StandardCheckpointer is
        # async under the hood): the commit rename must not race orbax's
        # background writer — async happens on OUR worker thread, over a
        # host snapshot, with the whole stage→manifest→rename sequence
        self._ckptr = ocp.Checkpointer(ocp.StandardCheckpointHandler())
        # per-host SHARDED payloads (checkpoint/shards.py): each host's
        # writer stages only the pieces its devices own; the multi-process
        # finalize coordinates over marker files — no collectives on the
        # writer thread, which is what makes multi-process saves ASYNC-
        # capable at all. auto = on iff the run has peers; the
        # single-payload orbax layout stays the single-process default
        # (and both layouts restore from either writer).
        if sharded not in ("auto", "on", "off"):
            raise ValueError(f"unknown checkpoint.sharded setting "
                             f"{sharded!r}")
        self._sharded = sharded == "on" or (
            sharded == "auto" and jax.process_count() > 1)
        self.finalize_timeout_secs = finalize_timeout_secs
        # async: host-snapshot on the caller thread (correct wrt donated
        # buffers), serialize+commit on one background worker. Multi-process
        # saves may only run async on the SHARDED layout (the orbax path
        # barriers its collective write internally — a per-process thread
        # would skew that barrier; the sharded writer coordinates over
        # files instead).
        self._async = async_save and (jax.process_count() == 1
                                      or self._sharded)
        self._executor = (ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="drt-ckpt")
            if self._async else None)
        self._pending: Optional[Future] = None
        if writer and jax.process_index() == 0:
            # stale staging dirs are uncommitted leftovers of a crashed or
            # preempted writer; a WRITER constructing here means no other
            # writer is live on this directory. Read-side managers (the
            # polling evaluator, ``writer=False``) must NOT sweep — they
            # share the directory with a live trainer whose in-flight
            # async save owns the staging dir they'd be deleting
            sweep_staging(self.directory)
        # fail at construction, not at the first save cadence minutes into
        # training: everything the layout check needs already exists here
        self._check_layout()

    # -- policy ------------------------------------------------------------
    def should_save(self, step: int) -> bool:
        from ..utils import cadence_crossed
        # boundary-crossing (not modulo): fused loops only surface loop-end
        # steps, which need not be multiples of the cadence
        if self.save_every_steps and cadence_crossed(
                step, self.save_every_steps, self._last_save_step):
            return True
        if self.save_every_secs and \
                time.monotonic() - self._last_save_time >= self.save_every_secs:
            return True
        return False

    def maybe_save(self, step: int, state) -> bool:
        if not self.should_save(step):
            return False
        self.save(step, state)
        return True

    # -- layout sidecar ----------------------------------------------------
    @property
    def _layout_path(self) -> str:
        return os.path.join(self.directory, "layout.json")

    def saved_layout(self) -> Optional[dict]:
        import json
        try:
            with open(self._layout_path) as f:
                return json.load(f)
        except (OSError, ValueError):
            # unreadable/corrupt sidecar ranks as absent; _check_layout then
            # assumes the conservative network order, which refuses rather
            # than silently permutes
            return None

    @staticmethod
    def _strip_meta(stamp):
        """Layout comparison ignores the bookkeeping key."""
        if not stamp:
            return stamp
        return {k: v for k, v in stamp.items() if k != "applies_from_step"}

    def _check_layout(self) -> None:
        cur = self._layout_stamp
        if cur is None:
            return  # caller declared no stacked layout — nothing to enforce
        latest = self.latest_step()
        if latest is None:
            # no committed checkpoint — an orphaned sidecar (stamp written,
            # save failed) conflicts with nothing and gets overwritten
            return
        saved = self.saved_layout()
        if saved is not None:
            af = saved.get("applies_from_step")
            if af is not None and af > latest:
                # the sidecar is written before the commit; a crash between
                # the two leaves a stamp describing a step that never
                # landed. Ignore it — the committed checkpoints all predate
                # it (ADVICE r3 #4)
                saved = None
        # checkpoints that predate layout stamping could only have been
        # network order
        saved = self._strip_meta(saved) or {"encoder_order": "network"}
        circular = "circular" in (saved.get("encoder_order"),
                                  cur.get("encoder_order"))
        if circular and saved != cur:
            raise ValueError(
                f"checkpoint {self.directory} stores stacked encoder params "
                f"in layout {saved} but this run uses {cur}; restoring would "
                "silently permute layer order. Migrate with "
                "models.pipeline.repack_stacked_params, or match "
                "mesh.pipeline / model.vit_pipeline_interleave")

    def _write_layout(self, step: int) -> None:
        # chief-only + atomic: every host shares this directory, and
        # concurrent truncating writes could leave unparseable JSON.
        # ``applies_from_step`` records the first step this stamp describes,
        # so a stamp orphaned by a crash before the commit can be recognized
        # (newer than every committed step) and ignored
        if jax.process_index() != 0:
            return
        import json
        import tempfile
        fd, tmp = tempfile.mkstemp(dir=self.directory, suffix=".layout")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump({**self._layout_stamp, "applies_from_step": step},
                          f)
            os.replace(tmp, self._layout_path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)

    # -- commit protocol ---------------------------------------------------
    def all_steps(self) -> List[int]:
        """Committed steps (ascending). Staging/tmp dirs never appear."""
        return committed_steps(self.directory)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def save(self, step: int, state, force: bool = False) -> None:
        """Commit ``state`` as step ``step`` (no-op if already committed).

        ``force=True`` additionally OVERWRITES an existing committed step:
        the final/preemption saves rely on it — a stale same-numbered
        checkpoint from an earlier run in the same directory must not
        swallow the current state (the cadence policy lives in
        ``maybe_save``, which never forces)."""
        # goodput: ONLY what the STEP-LOOP thread pays for this save is
        # checkpoint wall — backpressure on a still-in-flight previous
        # save, the device→host snapshot, and (sync path) the whole write.
        # The async writer thread's stage/fsync/commit time deliberately
        # charges NOTHING here: it overlaps compute, and billing it as
        # checkpoint would claim a stall that never happened. Writer-side
        # seconds are accounted separately (utils.metrics.ckpt_async_stats
        # → the {"event": "ckpt_async"} row). The nested spans below charge
        # nothing extra under the outermost-categorized-span rule.
        from ..utils.metrics import ckpt_async_stats
        with span("checkpoint.save", category="checkpoint", step=step):
            # backpressure: a new save must not overtake an in-flight one
            # — the writer owns one snapshot at a time, and commit order
            # must follow step order (wait re-raises a failed write)
            t0 = time.perf_counter()
            overtook = self._pending is not None and not self._pending.done()
            self.wait_until_finished()
            if overtook:
                ckpt_async_stats.add(
                    overtakes=1,
                    backpressure_seconds=time.perf_counter() - t0)
            if step in self.all_steps() and not force:
                return  # idempotent: step already checkpointed
            self._check_layout()
            if self._layout_stamp is not None:
                saved = self.saved_layout()
                # rewrite when the layout differs OR the existing stamp's
                # applies_from_step is ahead of this commit (a crash orphan
                # from an earlier run; left alone it would outrank every
                # step this run commits and _check_layout would keep
                # discarding it)
                if (self._strip_meta(saved) != self._layout_stamp
                        or (saved or {}).get("applies_from_step",
                                             step) > step):
                    self._write_layout(step)
            tree = _saveable(state)
            if self._sharded:
                from . import shards as shards_mod
                t1 = time.perf_counter()
                with span("checkpoint.snapshot", step=step):
                    parts = shards_mod.host_snapshot_parts(tree)
                ckpt_async_stats.add(
                    saves=1, snapshot_seconds=time.perf_counter() - t1)
                if jax.process_count() > 1:
                    # pre-handoff coordination ON THE LOOP THREAD (the
                    # only thread collectives may run on): the chief
                    # clears stale staging from a crashed earlier
                    # attempt, then the SNAPSHOT BARRIER guarantees
                    # every host snapshotted THIS step and sees the
                    # cleaned staging before any writer touches it. The
                    # writer threads coordinate over marker files only.
                    if jax.process_index() == 0:
                        # deliberate loop-thread exception: this cleanup
                        # must finish before the barrier releases peers'
                        # writers (a writer-thread rmtree could eat a
                        # peer's freshly staged shard)
                        staging = staging_path(self.directory, step)  # shardcheck: ok(ckpt-io-thread)
                        if os.path.isdir(staging):
                            shutil.rmtree(staging)
                    from jax.experimental import multihost_utils
                    multihost_utils.sync_global_devices(
                        f"drt_ckpt_snapshot_{step}")
                if self._async:
                    self._pending = self._executor.submit(
                        self._write_sharded_async, step, parts, force)
                else:
                    ckpt_async_stats.add(sync_saves=1)
                    self._write_sharded(step, parts, force)
                    ckpt_async_stats.add(committed=1, step=step)
            elif self._async:
                t1 = time.perf_counter()
                with span("checkpoint.snapshot", step=step):
                    snapshot = _host_snapshot(tree)
                ckpt_async_stats.add(
                    saves=1, snapshot_seconds=time.perf_counter() - t1)
                self._pending = self._executor.submit(self._write_async,
                                                      step, snapshot, force)
            else:
                ckpt_async_stats.add(saves=1, sync_saves=1)
                self._write(step, tree, force)
                ckpt_async_stats.add(committed=1, step=step)
            self._last_save_time = time.monotonic()
            self._last_save_step = step

    def _write_async(self, step: int, tree, force: bool = False) -> None:
        """The dedicated writer thread's entry: the full stage → fsync →
        manifest → atomic-rename commit protocol over the host snapshot.
        Host I/O only — no jax dispatch happens here (pinned by the
        dispatch-sanitizer test), so it cannot interleave device enqueue
        order with the train loop. Wall time lands in ckpt_async_stats,
        NOT the goodput checkpoint bucket (it overlaps compute)."""
        from ..utils.metrics import ckpt_async_stats
        t0 = time.perf_counter()
        with span("checkpoint.writer", step=step):
            self._write(step, tree, force)
        ckpt_async_stats.add(committed=1, step=step,
                             writer_seconds=time.perf_counter() - t0)

    def _write_sharded_async(self, step: int, parts,
                             force: bool = False) -> None:
        """Writer-thread entry for the SHARDED layout: host I/O + marker-
        file coordination only — no jax dispatch, no collectives (the
        property that lets multi-process saves run async at all)."""
        from ..utils.metrics import ckpt_async_stats
        t0 = time.perf_counter()
        with span("checkpoint.writer", step=step):
            self._write_sharded(step, parts, force)
        ckpt_async_stats.add(committed=1, step=step,
                             writer_seconds=time.perf_counter() - t0)

    def _write_sharded(self, step: int, parts, force: bool = False) -> None:
        """Per-host sharded stage → marker → (chief) finalize
        (checkpoint/shards.py): every process writes only the array
        pieces its devices own plus a durable ``.done`` marker; the chief
        waits for all markers, then runs the usual manifest + atomic
        commit rename. Peers wait for the chief's rename to become
        visible so ``wait_until_finished`` (and the preemption final
        save) keeps its "committed when it returns" meaning on every
        host. docs/resilience.md has the timeline."""
        from . import shards as shards_mod
        from ..utils.metrics import ckpt_async_stats
        staging = staging_path(self.directory, step)
        final = os.path.join(self.directory, str(step))
        pidx = jax.process_index()
        chief = pidx == 0
        multi = jax.process_count() > 1

        def stage_and_commit():
            if os.path.isdir(final) and not force:
                return  # committed on an earlier attempt: done
            # fresh staging per single-process attempt; the multi-process
            # cleanup happened on the loop thread BEFORE the snapshot
            # barrier (save()) — a writer-thread rmtree here could eat a
            # peer's freshly staged shard
            if chief and not multi and os.path.isdir(staging):
                shutil.rmtree(staging)
            t0 = time.perf_counter()
            with span("checkpoint.shard", step=step):
                nbytes, nfiles = shards_mod.write_host_shards(
                    staging, pidx, parts)
                shards_mod.write_done_marker(staging, pidx)
                fsync_dir(os.path.join(staging, shards_mod.SHARDS_DIR))
            ckpt_async_stats.add(shard_bytes=nbytes, shard_files=nfiles,
                                 shard_seconds=time.perf_counter() - t0)
            deadline = time.monotonic() + self.finalize_timeout_secs
            if chief:
                with span("checkpoint.finalize", step=step):
                    t1 = time.perf_counter()
                    need = set(range(jax.process_count()))
                    while not need <= shards_mod.done_markers(staging):
                        if time.monotonic() > deadline:
                            missing = sorted(
                                need - shards_mod.done_markers(staging))
                            raise TimeoutError(
                                f"sharded save step {step}: hosts "
                                f"{missing} never staged their shards "
                                f"within {self.finalize_timeout_secs}s")
                        time.sleep(0.05)
                    ckpt_async_stats.add(
                        finalize_wait_seconds=time.perf_counter() - t1)
                    # chaos window: env-armed nap between staging and
                    # commit — the kill-during-sharded-commit test's
                    # SIGKILL target (resilience/faultinject.py)
                    from ..resilience.faultinject import \
                        maybe_delay_ckpt_commit
                    maybe_delay_ckpt_commit(step)
                    if os.path.isdir(final):
                        # forced overwrite (see _write): move the stale
                        # same-numbered dir aside before the rename
                        aside = final + ".replaced"
                        shutil.rmtree(aside, ignore_errors=True)
                        os.replace(final, aside)
                        shutil.rmtree(aside, ignore_errors=True)
                    with span("checkpoint.fsync", step=step):
                        write_manifest(staging, step)
                    with span("checkpoint.commit", step=step):
                        os.replace(staging, final)
                        fsync_dir(self.directory)
            else:
                # peers block until the chief's commit rename lands (the
                # staging dir vanishes atomically with it): a process
                # must not report its save finished — or exit, for the
                # final preemption save — before the step is committed
                with span("checkpoint.finalize", step=step):
                    t1 = time.perf_counter()
                    while os.path.isdir(staging):
                        if time.monotonic() > deadline:
                            raise TimeoutError(
                                f"sharded save step {step}: the chief "
                                "never committed within "
                                f"{self.finalize_timeout_secs}s")
                        time.sleep(0.05)
                    ckpt_async_stats.add(
                        finalize_wait_seconds=time.perf_counter() - t1)
                    if not os.path.isdir(final):
                        raise RuntimeError(
                            f"sharded save step {step}: staging vanished "
                            "without a committed step — the chief's "
                            "writer failed")

        error: Optional[BaseException] = None
        try:
            # single-process attempts retry like _write (idempotent:
            # staging rebuilt from the in-memory parts); multi-process
            # does one attempt — a re-staging host would race the
            # chief's marker wait
            retry_call(stage_and_commit,
                       retries=self.io_retries if not multi else 0,
                       retry_on=(OSError,),
                       description=f"sharded checkpoint write "
                                   f"(step {step})")
        except BaseException as e:
            error = e
            if chief and not multi:
                shutil.rmtree(staging, ignore_errors=True)
        if error is not None:
            raise error
        if chief:
            self._apply_retention()

    def _write(self, step: int, tree, force: bool = False) -> None:
        """Stage → manifest(fsync) → rename(commit) → retention."""
        staging = staging_path(self.directory, step)
        final = os.path.join(self.directory, str(step))
        chief = jax.process_index() == 0

        def write_and_commit():
            if os.path.isdir(final):
                if not force:
                    # the commit may have landed on a previous attempt whose
                    # error came after the rename (parent-dir fsync): done
                    return
                # forced overwrite: move the stale same-numbered dir aside
                # (it stops being "committed" the moment the rename lands;
                # the brief no-committed-step window only risks falling
                # back one step on a crash exactly here)
                if chief:
                    aside = final + ".replaced"
                    shutil.rmtree(aside, ignore_errors=True)
                    os.replace(final, aside)
                    shutil.rmtree(aside, ignore_errors=True)
            # fresh staging per attempt: a failed try leaves partial orbax
            # state (incl. its own tmp dirs) that must not pollute the
            # manifest of a successful retry
            if chief and os.path.isdir(staging):
                shutil.rmtree(staging)
            # every process participates: orbax writes this process's array
            # shards and barriers internally before finalizing the payload.
            # Flight-recorder spans split the commit protocol so a dump
            # shows WHICH leg a slow/stuck save was in (stage vs fsync vs
            # rename) — runs on the writer thread when async
            with span("checkpoint.stage", step=step):
                self._ckptr.save(os.path.join(staging, _PAYLOAD_DIR),
                                 args=ocp.args.StandardSave(tree))
            # chaos window: env-armed nap between staging and commit (the
            # kill-during-async-commit test's SIGKILL target); inert in
            # production (resilience/faultinject.py)
            from ..resilience.faultinject import maybe_delay_ckpt_commit
            maybe_delay_ckpt_commit(step)
            if chief:
                with span("checkpoint.fsync", step=step):
                    write_manifest(staging, step)
                with span("checkpoint.commit", step=step):
                    os.replace(staging, final)
                    fsync_dir(self.directory)

        multi = jax.process_count() > 1
        error: Optional[BaseException] = None
        try:
            # the retried region covers the WHOLE stage→manifest→rename
            # sequence — on flaky NFS the manifest fsyncs and the rename
            # are as OSError-prone as the write; each attempt is idempotent
            # (staging rebuilt, a landed commit short-circuits). Retries
            # are single-process only: orbax's sharded save barriers
            # internally, so one process re-entering it while the others
            # have moved on would desync the collective
            retry_call(write_and_commit,
                       retries=self.io_retries if not multi else 0,
                       retry_on=(OSError,),
                       description=f"checkpoint write (step {step})")
        except BaseException as e:
            error = e
            if chief:
                shutil.rmtree(staging, ignore_errors=True)
        if multi:
            # the barrier is reached on BOTH success and failure paths: no
            # process may report the save finished (or exit, for the final
            # preemption save) before the commit rename is visible, and a
            # chief-side commit error must not strand the others here.
            # (A process that died outright still hangs peers until orbax's
            # barrier timeout — that is the distributed-runtime failure
            # domain, not ours.)
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices(f"drt_ckpt_commit_{step}")
        if error is not None:
            raise error
        if chief:
            self._apply_retention()

    def _apply_retention(self) -> None:
        if not self.max_to_keep or self.max_to_keep <= 0:
            return
        steps = self.all_steps()
        for old in steps[:-self.max_to_keep]:
            shutil.rmtree(os.path.join(self.directory, str(old)),
                          ignore_errors=True)

    # -- restore -----------------------------------------------------------
    def _payload_path(self, step: int) -> str:
        step_dir = os.path.join(self.directory, str(step))
        for name in (_PAYLOAD_DIR, _LEGACY_PAYLOAD_DIR):
            cand = os.path.join(step_dir, name)
            if os.path.isdir(cand):
                return cand
        return step_dir  # bare orbax tree (oldest layout)

    def _quarantine(self, step: int) -> None:
        """Move a damaged checkpoint aside (``<step>.corrupt``): the commit
        protocol keys idempotency on committed step numbers, so a damaged
        dir left under its committed name would block the re-trained step
        from ever committing again. Chief-only; losing the race on a shared
        FS is harmless (the other rename already did the job)."""
        if jax.process_index() != 0:
            return
        src = os.path.join(self.directory, str(step))
        dst = src + ".corrupt"
        try:
            if os.path.isdir(dst):
                shutil.rmtree(dst)
            os.replace(src, dst)
            log.warning("quarantined damaged checkpoint step %d -> %s",
                        step, dst)
        except OSError:
            pass

    def _agreed_pick(self) -> Optional[int]:
        """Chief verifies candidates newest-first and broadcasts the first
        step that passes (or -1 for none); peers follow the broadcast. Must
        be called by ALL processes at the same program point."""
        import numpy as np
        from jax.experimental import multihost_utils
        pick = -1  # -1: no checkpoints at all; -2: all damaged (loud)
        if jax.process_index() == 0:
            steps = sorted(self.all_steps(), reverse=True)
            pick = -2 if steps else -1
            for s in steps:
                ok, detail = self._verify(s)
                if ok:
                    pick = s
                    break
                log.warning("checkpoint step %d failed verification (%s) — "
                            "falling back to an older checkpoint", s, detail)
                self._quarantine(s)
        pick = int(multihost_utils.broadcast_one_to_all(
            np.asarray(pick, dtype=np.int64)))
        if pick == -2:
            raise CheckpointCorrupt(
                f"every committed checkpoint in {self.directory} failed "
                "verification — refusing to silently restart from scratch; "
                "move or delete the directory to start over")
        return None if pick < 0 else pick

    def _verify(self, step: int) -> Tuple[bool, str]:
        """(usable, detail) for a committed step per its manifest."""
        if not self.verify_on_restore:
            return True, "verification disabled"
        status, detail = manifest_status(
            os.path.join(self.directory, str(step)))
        if status == "bad":
            return False, detail
        if status == "legacy":
            log.info("checkpoint step %d: %s — restoring unverified",
                     step, detail)
        return True, detail

    def restore(self, state, step: Optional[int] = None):
        """Restore into the sharding/structure of ``state`` (shardings are
        taken from the abstract target, so restored arrays land exactly
        where the live ones are). Returns (new_state, restored_step) or
        (state, None) when no committed checkpoint exists.

        With ``step=None`` the newest VALID checkpoint wins: a candidate
        whose manifest fails to verify, or whose deserialization throws
        (torn write that predates the manifest protocol), is skipped with a
        warning and the next older one is tried. An explicitly requested
        ``step`` that fails raises :class:`CheckpointCorrupt` instead —
        the caller asked for that exact state."""
        # drain an in-flight async save first: its commit rename and
        # retention rmtree must not race the scan below (a step vanishing
        # mid-verification would be spuriously quarantined)
        self.wait_until_finished()
        explicit = step is not None
        if jax.process_count() > 1 and not explicit:
            # multi-host scan: per-process listdir + verify would let stale
            # NFS attribute caches give hosts DIVERGENT picks (different
            # steps restored → the next collective hangs). The chief alone
            # walks its candidates and broadcasts ONE chosen step; every
            # process then restores exactly that step. Cost: deserialize
            # failures of the agreed step raise instead of falling back
            # (manifest-verified fallback is preserved) — orbax's restore
            # is collective, so a per-host deserialize fallback could
            # never be safe anyway.
            step = self._agreed_pick()
            if step is None:
                return state, None
            explicit = True
            agreed = True
        else:
            agreed = False
        candidates = [step] if explicit else \
            sorted(self.all_steps(), reverse=True)
        if not candidates:
            return state, None
        self._check_layout()
        abstract = jax.tree_util.tree_map(
            ocp.utils.to_shape_dtype_struct, _saveable(state))
        failures = []
        for s in candidates:
            # `agreed`: the chief vouched for this step — a peer's stale
            # directory listing must not veto it (orbax fails loudly if
            # the step is truly absent)
            if explicit and not agreed and s not in self.all_steps():
                raise FileNotFoundError(
                    f"checkpoint step {s} is not committed in "
                    f"{self.directory} (have {self.all_steps()})")
            # agreed steps were verified (and peers' stale caches must not
            # re-veto them); everything else verifies here
            ok, detail = (True, "") if agreed else self._verify(s)
            if not ok:
                if explicit:
                    raise CheckpointCorrupt(
                        f"checkpoint step {s} failed verification: {detail}")
                log.warning("checkpoint step %d failed verification (%s) — "
                            "falling back to an older checkpoint", s, detail)
                failures.append((s, detail))
                self._quarantine(s)
                continue
            try:
                from . import shards as shards_mod
                step_dir = os.path.join(self.directory, str(s))
                if shards_mod.is_sharded_layout(step_dir):
                    # per-host sharded layout: reassemble each leaf from
                    # every host's pieces and re-shard into the LIVE
                    # state's rule-table layout — works across a
                    # different writer host count by construction
                    restored = self._restore_sharded(step_dir, abstract)
                else:
                    restored = self._ckptr.restore(
                        self._payload_path(s),
                        args=ocp.args.StandardRestore(abstract))
            except Exception as e:
                if explicit:
                    raise CheckpointCorrupt(
                        f"checkpoint step {s} failed to deserialize: {e}"
                    ) from e
                log.warning("checkpoint step %d failed to deserialize (%s) "
                            "— falling back to an older checkpoint", s, e)
                failures.append((s, str(e)))
                # NO quarantine here: unlike a manifest mismatch (verified
                # content damage), a deserialization error can be
                # environmental (host OOM, transient FS) or a caller-side
                # shape/config mismatch — renaming intact checkpoints
                # .corrupt on those would let a later resume silently
                # restart from scratch after the caller fixes their config
                continue
            if failures:
                log.warning(
                    "restored step %d after skipping damaged checkpoint(s) "
                    "%s", s, [f[0] for f in failures])
            new_state = state.replace(
                step=restored["step"], params=restored["params"],
                batch_stats=restored["batch_stats"],
                opt_state=restored["opt_state"])
            # resume continues the cadence from the restored step — without
            # this, the first maybe_save after a restart fires immediately
            # off-cadence
            self._last_save_step = s
            self._last_save_time = time.monotonic()
            return new_state, s
        raise CheckpointCorrupt(
            f"every committed checkpoint in {self.directory} failed to "
            f"restore: {failures} — refusing to silently restart from "
            "scratch; move or delete the directory to start over")

    def _restore_sharded(self, step_dir: str, abstract):
        """Restore one committed SHARDED checkpoint into the structure/
        shardings of ``abstract``: merge every host index, reassemble
        each leaf from its byte-range pieces (cross-host-count safe),
        validate shape+dtype against the live state, and place per the
        target sharding — the re-shard path that lets a 2-host save
        restore at 1 host and vice versa. Any inconsistency raises; the
        caller's fallback ladder then tries the next older checkpoint."""
        from . import shards as shards_mod
        flat, treedef = jax.tree_util.tree_flatten_with_path(abstract)
        out = []
        with shards_mod.ShardReader(step_dir) as reader:
            keys = reader.keys()
            for path, leaf in flat:
                key = shards_mod.leaf_key(path)
                if key not in keys:
                    raise ValueError(
                        f"sharded checkpoint is missing state leaf {key}")
                arr = reader.assemble(key)
                shape = tuple(getattr(leaf, "shape", ()))
                if tuple(np.shape(arr)) != shape:
                    raise ValueError(
                        f"leaf {key}: checkpoint shape "
                        f"{tuple(np.shape(arr))} != state shape {shape}")
                dtype = getattr(leaf, "dtype", None)
                if dtype is not None and np.dtype(arr.dtype) != \
                        np.dtype(dtype):
                    raise ValueError(
                        f"leaf {key}: checkpoint dtype {arr.dtype} != "
                        f"state dtype {dtype}")
                sharding = getattr(leaf, "sharding", None)
                if sharding is not None:
                    np_arr = np.asarray(arr)
                    arr = jax.make_array_from_callback(
                        shape, sharding,
                        lambda idx, a=np_arr: a[idx])
                out.append(arr)
        return jax.tree_util.tree_unflatten(treedef, out)

    # -- teardown ----------------------------------------------------------
    def wait_until_finished(self) -> None:
        """Block until the in-flight async save (if any) has committed;
        re-raises its error so a failed save can't pass silently."""
        pending, self._pending = self._pending, None
        if pending is not None:
            # goodput: the caller (step-loop) thread is stalled on
            # checkpoint I/O right here
            with span("checkpoint.wait", category="checkpoint"):
                pending.result()

    def close(self) -> None:
        self.wait_until_finished()
        if self._executor is not None:
            self._executor.shutdown(wait=True)


def poll_new_checkpoint(directory: str, last_seen: Optional[int]
                        ) -> Optional[Tuple[int, str, str]]:
    """Non-blocking single poll: the newest COMMITTED checkpoint newer than
    ``last_seen``, or None. Returns ``(step, step_dir, manifest_digest)`` —
    the digest (SHA-256 of MANIFEST.json, "" for pre-protocol checkpoints)
    identifies the checkpoint's exact content, so consumers that act on a
    new step (the serving hot-swap thread, serve/swap.py) can report WHICH
    state went live, and callers own their sleep policy instead of
    busy-sleeping a fixed interval inside this module (the evaluator uses
    jittered backoff, the swap thread a jittered fixed cadence).

    Only commit-renamed step dirs are visible (resilience/manifest.py), so
    a poller can never pick up a checkpoint mid-write."""
    from ..resilience.manifest import manifest_digest
    steps = committed_steps(directory)
    newest = steps[-1] if steps else None
    if newest is None or (last_seen is not None and newest <= last_seen):
        return None
    step_dir = os.path.join(directory, str(newest))
    return newest, step_dir, manifest_digest(step_dir)


def wait_for_new_checkpoint(directory: str, last_seen: Optional[int],
                            timeout_secs: float = 0.0,
                            poll_secs: float = 60.0) -> Optional[int]:
    """Block until a COMMITTED checkpoint newer than ``last_seen`` appears —
    the fixed-interval polling primitive (reference resnet_cifar_eval.py:
    99-141 polled get_checkpoint_state + slept 60 s). timeout 0 = single
    poll. Thin blocking wrapper over ``poll_new_checkpoint``."""
    deadline = time.monotonic() + timeout_secs if timeout_secs else None
    while True:
        hit = poll_new_checkpoint(directory, last_seen)
        if hit is not None:
            return hit[0]
        if deadline is None or time.monotonic() >= deadline:
            return None
        time.sleep(min(poll_secs, max(0.0, deadline - time.monotonic())))
