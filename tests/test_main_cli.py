"""CLI entry-point tests — the one binary replacing the reference's six mains
(SURVEY.md §1 L3)."""
import os

import numpy as np

from distributed_resnet_tensorflow_tpu import main as main_mod


def _args(tmp_path, *extra):
    return ["--preset", "smoke",
            "--set", "model.compute_dtype=float32",
            "--set", "model.resnet_size=8",
            "--set", "data.image_size=8",
            "--set", "train.batch_size=16",
            "--set", f"log_root={tmp_path}",
            "--set", f"checkpoint.directory={tmp_path}/ckpt",
            "--set", "checkpoint.async_save=false",
            *extra]


def test_main_train_mode(tmp_path, capsys):
    main_mod.main(_args(
        tmp_path,
        "--set", "train.train_steps=4",
        "--set", "train.log_every_steps=2",
        "--set", "checkpoint.save_every_steps=2",
        "--set", "checkpoint.save_every_secs=0",
    ))
    out = capsys.readouterr().out
    assert "step 2" in out and "step 4" in out
    # checkpoints + metrics written
    assert os.path.isdir(os.path.join(tmp_path, "ckpt"))
    assert os.path.exists(os.path.join(tmp_path, "train", "metrics.jsonl"))


def test_main_train_and_eval_mode(tmp_path, capsys):
    main_mod.main(_args(
        tmp_path,
        "--set", "mode=train_and_eval",
        "--set", "train.train_steps=4",
        "--set", "train.eval_every_steps=2",
        "--set", "eval.eval_batch_count=1",
        "--set", "checkpoint.save_every_steps=2",
        "--set", "checkpoint.save_every_secs=0",
    ))
    out = capsys.readouterr().out
    assert "eval @ step 2" in out and "eval @ step 4" in out


def test_main_eval_once_mode(tmp_path):
    # first train + checkpoint...
    main_mod.main(_args(
        tmp_path,
        "--set", "train.train_steps=2",
        "--set", "checkpoint.save_every_steps=2",
        "--set", "checkpoint.save_every_secs=0",
    ))
    # ...then one-shot evaluation against the written checkpoint
    main_mod.main(_args(
        tmp_path,
        "--set", "mode=eval",
        "--set", "eval.eval_once=true",
        "--set", "eval.eval_batch_count=1",
    ))
    import json
    path = os.path.join(tmp_path, "eval", "metrics.jsonl")
    recs = [json.loads(l) for l in open(path) if l.strip()]
    assert recs and "eval/precision" in recs[-1]
    assert "eval/best_precision" in recs[-1]
