"""Approximate static call graph over the package — hangcheck's substrate.

The thread/lock contract rules (``rules/thread_dispatch.py``,
``rules/blocking_call.py``, ``rules/chief_collective.py``,
``rules/lock_order.py``) all need the same question answered: *starting
from this function, which other package functions can execution reach?*
This module builds a name-based call graph over the already-parsed
``lint.LintContext`` ASTs, resolved conservatively:

  * ``name(...)``        → a function of that name in the SAME file, else
    the unique package-wide match (ambiguous names resolve to nothing);
  * ``self.name(...)``   → the enclosing class's method of that name,
    else the unique package-wide match;
  * ``obj.name(...)``    → the unique package-wide match only.

Unresolvable calls (callbacks, ``getattr``, iterator protocols, lambdas
passed around) contribute NO edges — hangcheck is deliberately an
UNDER-approximation: a finding means a concrete static path exists, and
a clean pass means "no path the resolver can see", not a proof. Nested
functions/closures are reachable from their enclosing function (defining
a worker body counts as reaching it — that is exactly how the threaded
input stages hand work around), and generator bodies are treated as
ordinary functions (iteration runs them).

The graph is built once per ``LintContext`` and memoized on it, so the
four hangcheck rules share one construction.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Set, Tuple

PACKAGE = "distributed_resnet_tensorflow_tpu"


@dataclass
class FuncNode:
    """One function/method definition (nested functions included)."""

    rel: str                 # repo-relative file path
    qualname: str            # e.g. "Trainer.train", "outer.<locals>.inner"
    name: str                # bare name
    lineno: int
    node: ast.AST            # the FunctionDef/AsyncFunctionDef
    cls: Optional[str] = None        # innermost enclosing class name
    nested: List["FuncKey"] = field(default_factory=list)

    @property
    def key(self) -> "FuncKey":
        return (self.rel, self.qualname)

    def short(self) -> str:
        """Package-relative display id, e.g. ``serve/batcher.py::DynamicBatcher._run``."""
        rel = self.rel
        prefix = PACKAGE + "/"
        if rel.startswith(prefix):
            rel = rel[len(prefix):]
        return f"{rel}::{self.qualname}"


FuncKey = Tuple[str, str]  # (rel, qualname)

#: method names so common on stdlib containers/files/threads that a
#: unique package-wide match on an arbitrary receiver is almost surely a
#: COLLISION, not a call (``self._compiled.get(key)`` is ``dict.get``,
#: not ``ServeCompileCache.get``). The fallback resolver never matches
#: these; ``self.<name>()`` with a known enclosing class still resolves
#: precisely through the class index.
GENERIC_ATTRS = frozenset({
    "get", "put", "add", "clear", "flush", "close", "open", "join",
    "wait", "start", "stop", "run", "append", "appendleft", "pop",
    "popleft", "update", "copy", "remove", "extend", "insert", "sort",
    "write", "read", "send", "recv", "submit", "result", "acquire",
    "release", "items", "keys", "values", "count", "index", "setdefault",
})


def body_walk(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk a function's OWN body: descends into everything except nested
    function/class definitions (their statements belong to their own
    nodes; the nesting edge keeps them reachable). Lambdas are walked —
    they execute in the enclosing frame for our purposes."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def call_target(call: ast.Call) -> Tuple[Optional[str], Optional[str]]:
    """(bare-name, self-attr-or-None) of a call's target: ``f(...)`` →
    ("f", None); ``self.m(...)`` → ("m", "self"); ``obj.m(...)`` →
    ("m", "obj"/None-ish receiver name)."""
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id, None
    if isinstance(fn, ast.Attribute):
        recv = fn.value.id if isinstance(fn.value, ast.Name) else ""
        return fn.attr, recv
    return None, None


class CallGraph:
    """Name-resolved call graph over a set of parsed SourceFiles."""

    def __init__(self, files):
        self.funcs: Dict[FuncKey, FuncNode] = {}
        self.by_name: Dict[str, List[FuncNode]] = {}
        self.by_file_name: Dict[Tuple[str, str], List[FuncNode]] = {}
        self.by_class_method: Dict[Tuple[str, str], List[FuncNode]] = {}
        self._files = [sf for sf in files if sf.tree is not None]
        for sf in self._files:
            self._index_file(sf)
        self._edges: Dict[FuncKey, List[FuncKey]] = {}
        self._reach_memo: Dict[FuncKey, Set[FuncKey]] = {}

    # -- construction -------------------------------------------------------
    def _index_file(self, sf) -> None:
        def visit(node, qual: List[str], cls: Optional[str],
                  parent: Optional[FuncNode]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    q = qual + [child.name]
                    fn = FuncNode(rel=sf.rel, qualname=".".join(q),
                                  name=child.name, lineno=child.lineno,
                                  node=child, cls=cls)
                    self.funcs[fn.key] = fn
                    self.by_name.setdefault(child.name, []).append(fn)
                    self.by_file_name.setdefault(
                        (sf.rel, child.name), []).append(fn)
                    if cls is not None:
                        self.by_class_method.setdefault(
                            (cls, child.name), []).append(fn)
                    if parent is not None:
                        parent.nested.append(fn.key)
                    visit(child, q + ["<locals>"], cls, fn)
                elif isinstance(child, ast.ClassDef):
                    visit(child, qual + [child.name], child.name, parent)
                else:
                    visit(child, qual, cls, parent)

        visit(sf.tree, [], None, None)

    # -- resolution ---------------------------------------------------------
    def resolve_name(self, name: str, rel: str) -> List[FuncNode]:
        """A bare-name callable reference: same file first, then the
        unique package-wide match."""
        local = [f for f in self.by_file_name.get((rel, name), ())]
        if local:
            return local
        cands = self.by_name.get(name, [])
        return cands if len(cands) == 1 else []

    def resolve_call(self, call: ast.Call,
                     caller: FuncNode) -> List[FuncNode]:
        name, recv = call_target(call)
        if name is None:
            return []
        if recv is None:
            return self.resolve_name(name, caller.rel)
        if recv == "self" and caller.cls is not None:
            own = self.by_class_method.get((caller.cls, name))
            if own:
                return list(own)
        if name in GENERIC_ATTRS:
            return []  # collision-prone names never fallback-resolve
        cands = self.by_name.get(name, [])
        return cands if len(cands) == 1 else []

    # -- reachability -------------------------------------------------------
    def edges(self, key: FuncKey) -> List[FuncKey]:
        cached = self._edges.get(key)
        if cached is not None:
            return cached
        fn = self.funcs.get(key)
        out: List[FuncKey] = []
        if fn is not None:
            out.extend(fn.nested)  # defining a closure reaches its body
            for node in body_walk(fn.node):
                if isinstance(node, ast.Call):
                    out.extend(c.key for c in self.resolve_call(node, fn))
        self._edges[key] = out
        return out

    def reachable(self, roots) -> Set[FuncKey]:
        """Every FuncKey reachable from the given root keys (inclusive)."""
        seen: Set[FuncKey] = set()
        stack = [r for r in roots]
        while stack:
            key = stack.pop()
            if key in seen:
                continue
            seen.add(key)
            stack.extend(self.edges(key))
        return seen


def get_callgraph(ctx) -> CallGraph:
    """The shared per-LintContext graph (built once, memoized on ctx)."""
    graph = getattr(ctx, "_hangcheck_callgraph", None)
    if graph is None:
        graph = CallGraph(ctx.all_python())
        ctx._hangcheck_callgraph = graph
    return graph
