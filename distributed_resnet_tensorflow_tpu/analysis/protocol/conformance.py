"""Runtime trace conformance: replay recorded event rows against the
declared protocol state machines.

Every chaos run doubles as a protocol-conformance witness: the rows the
fleet/elastic machinery writes to ``metrics.jsonl`` (``replica_health``,
``replica_replace``, ``canary``, ``reshard``, ``mesh_generation``,
``ckpt_shard``) are validated edge-by-edge against the ``event_edges``
tables the specs declare (analysis/protocol/spec.py) — an edge the model
does not allow is a finding at the stream's file:line, whether it came
from a live run, a smoke, or a test fixture.

Wired into ``scripts/serve_fleet_smoke.sh`` and ``scripts/chaos_smoke.sh
--elastic`` as::

    python -m distributed_resnet_tensorflow_tpu.analysis.protocol.conformance \
        <log_root>/route/metrics.jsonl <log_root>/serve-r*/metrics.jsonl

plus a ``--self-test-illegal-edge`` leg that appends a synthetic
``dead -> ready`` health row and exits 0 only if the checker catches it
— the smoke proves the witness can actually fail.

Torn lines (a crash or rotation mid-write) are skipped like the monitor
does; rows of undeclared event kinds are ignored. Chain continuity for
``replica_health`` tolerates a restart back to the declared initial
state (a fresh health object after a stream rotation).
"""
from __future__ import annotations

import json
from typing import Dict, Iterable, List, Optional, Tuple

from ..report import Finding
from .spec import load_specs

RULE_NAME = "protocol-trace"


def _tables() -> Dict[str, dict]:
    merged: Dict[str, dict] = {}
    for spec in load_specs():
        for kind, table in spec.event_edges.items():
            merged[kind] = dict(table, spec=spec.name)
    return merged


class _Replay:
    """Stateful per-stream replayer; one instance per file so
    cross-stream interleaving never manufactures false edges."""

    def __init__(self, source: str):
        self.source = source
        self.tables = _tables()
        self.findings: List[Finding] = []
        self._health_last: Dict[object, str] = {}      # replica -> to-state
        self._ladder: Dict[object, str] = {}           # replica -> rung
        self._canary_active: Optional[int] = None
        self._last_generation: Dict[str, int] = {}     # event kind -> gen
        self._ckpt_last: Dict[object, int] = {}        # process -> committed

    def _bad(self, line: int, msg: str) -> None:
        self.findings.append(Finding(RULE_NAME, self.source, line, msg))

    # -- replica_health ----------------------------------------------------
    def _replica_health(self, line: int, row: dict, table: dict) -> None:
        frm, to = row.get("from"), row.get("to")
        reason = row.get("reason")
        rid = row.get("replica")
        if (frm, to, reason) not in table["edges"]:
            self._bad(line, f"undeclared replica_health edge "
                            f"{frm!r} -> {to!r} ({reason!r}) for replica "
                            f"{rid} — not in the declared health state "
                            f"machine ({table['spec']})")
            return
        last = self._health_last.get(rid)
        if last is not None and frm != last and frm != table["initial"]:
            self._bad(line, f"replica_health chain break for replica "
                            f"{rid}: row leaves {frm!r} but the previous "
                            f"row landed in {last!r}")
        self._health_last[rid] = to

    # -- replica_replace ---------------------------------------------------
    def _replica_replace(self, line: int, row: dict, table: dict) -> None:
        action, rid = row.get("action"), row.get("replica")
        reason = row.get("reason")
        if action not in table["actions"]:
            self._bad(line, f"undeclared replica_replace action "
                            f"{action!r} for replica {rid}")
            return
        if reason is not None and reason not in table["reasons"]:
            self._bad(line, f"undeclared replica_replace reason "
                            f"{reason!r} for replica {rid}")
        rung = self._ladder.get(rid, "watching")
        ladder = table["ladder"]          # ("kill", "respawn", "readmit")
        if rung == "gave_up":
            self._bad(line, f"replica_replace {action!r} for replica "
                            f"{rid} after gave_up (the ladder is "
                            "terminal)")
            return
        if action == "gave_up":
            self._ladder[rid] = "gave_up"
            return
        expect = {"watching": ladder[0], ladder[0]: ladder[1],
                  ladder[1]: ladder[2]}.get(rung)
        if action != expect:
            self._bad(line, f"replica_replace ladder violation for "
                            f"replica {rid}: {action!r} while at rung "
                            f"{rung!r} (declared order "
                            f"{' -> '.join(ladder)})")
        self._ladder[rid] = "watching" if action == ladder[2] else action

    # -- canary ------------------------------------------------------------
    def _canary(self, line: int, row: dict, table: dict) -> None:
        action, step = row.get("action"), row.get("step")
        reason = row.get("reason")
        if action not in table["actions"]:
            self._bad(line, f"undeclared canary action {action!r}")
            return
        allowed = table["reasons_by_action"].get(action)
        if reason is not None and allowed is not None \
                and reason not in allowed:
            self._bad(line, f"undeclared canary reason {reason!r} for "
                            f"action {action!r}")
        if action == "start":
            if self._canary_active is not None:
                self._bad(line, f"canary start for step {step} while "
                                f"step {self._canary_active} is still "
                                "undecided (one canary at a time)")
            self._canary_active = step
            return
        # promote / rollback
        if self._canary_active is None:
            if not (action == "promote" and reason == "single_replica"):
                self._bad(line, f"canary {action!r} for step {step} "
                                "without a preceding start")
        elif step != self._canary_active:
            self._bad(line, f"canary {action!r} for step {step} but the "
                            f"active canary is step "
                            f"{self._canary_active}")
        self._canary_active = None

    # -- reshard / mesh_generation ----------------------------------------
    def _reshard(self, line: int, row: dict, table: dict) -> None:
        reason = row.get("reason")
        if reason not in table["reasons"]:
            self._bad(line, f"undeclared reshard reason {reason!r}")
        old, new = row.get("old_hosts"), row.get("new_hosts")
        if isinstance(old, int) and isinstance(new, int):
            if reason == "peer_lost" and not new < old:
                self._bad(line, f"reshard peer_lost must shrink the "
                                f"mesh: old_hosts={old} new_hosts={new}")
            if reason == "grow" and not new > old:
                self._bad(line, f"reshard grow must grow the mesh: "
                                f"old_hosts={old} new_hosts={new}")
        rs = row.get("restore_step")
        if isinstance(rs, int) and rs < -1:
            self._bad(line, f"reshard restore_step {rs} (< -1; -1 means "
                            "fresh init, committed steps are >= 0)")
        self._generation_monotonic(line, row, "reshard")

    def _mesh_generation(self, line: int, row: dict, table: dict) -> None:
        self._generation_monotonic(line, row, "mesh_generation")

    def _generation_monotonic(self, line: int, row: dict,
                              kind: str) -> None:
        gen = row.get("generation")
        if not isinstance(gen, int):
            return
        last = self._last_generation.get(kind)
        if last is not None and gen <= last:
            self._bad(line, f"{kind} generation went {last} -> {gen}; "
                            "generations only ever advance")
        self._last_generation[kind] = gen

    # -- ckpt_shard --------------------------------------------------------
    def _ckpt_shard(self, line: int, row: dict, table: dict) -> None:
        proc = row.get("process")
        last = row.get("last_committed_step")
        if isinstance(last, int):
            if last < -1:
                self._bad(line, f"ckpt_shard last_committed_step {last}")
            prev = self._ckpt_last.get(proc)
            if prev is not None and last < prev:
                self._bad(line, f"ckpt_shard last_committed_step went "
                                f"{prev} -> {last} for process {proc}; "
                                "a committed step never un-commits")
            self._ckpt_last[proc] = last

    _HANDLERS = {
        "replica_health": _replica_health,
        "replica_replace": _replica_replace,
        "canary": _canary,
        "reshard": _reshard,
        "mesh_generation": _mesh_generation,
        "ckpt_shard": _ckpt_shard,
    }

    def feed(self, line: int, row: dict) -> None:
        kind = row.get("event")
        handler = self._HANDLERS.get(kind)
        if handler is not None and kind in self.tables:
            handler(self, line, row, self.tables[kind])


def check_rows(rows: Iterable[Tuple[int, dict]],
               source: str = "<rows>") -> List[Finding]:
    """Validate ``(lineno, row)`` pairs from one stream."""
    replay = _Replay(source)
    for line, row in rows:
        replay.feed(line, row)
    return replay.findings


def read_stream(path: str) -> List[Tuple[int, dict]]:
    """Parse one metrics.jsonl (or rotated segment), skipping torn
    lines the way telemetry/monitor.py does."""
    out: List[Tuple[int, dict]] = []
    with open(path, encoding="utf-8", errors="replace") as f:
        for i, raw in enumerate(f, 1):
            raw = raw.strip()
            if not raw:
                continue
            try:
                row = json.loads(raw)
            except ValueError:
                continue   # torn mid-write (crash/rotation) — skip
            if isinstance(row, dict):
                out.append((i, row))
    return out


def check_stream(path: str) -> List[Finding]:
    """Replay one stream file; a rotated sibling ``<path>.1`` is
    prepended so a protocol round spanning a rotation replays whole."""
    import os
    rows: List[Tuple[int, dict]] = []
    if os.path.exists(path + ".1"):
        rows += read_stream(path + ".1")
    rows += read_stream(path)
    return check_rows(rows, source=os.path.relpath(path))


def main(argv=None) -> int:
    import argparse
    import os
    from ..report import format_findings
    ap = argparse.ArgumentParser(
        prog="python -m distributed_resnet_tensorflow_tpu.analysis."
             "protocol.conformance",
        description="replay metrics.jsonl rows against the declared "
                    "protocol state machines (docs/static_analysis.md)")
    ap.add_argument("streams", nargs="+", help="metrics.jsonl paths")
    ap.add_argument("--self-test-illegal-edge", action="store_true",
                    help="append a synthetic dead->ready health row to "
                         "the first stream's rows and exit 0 only if "
                         "the checker catches it (the smoke's witness-"
                         "can-fail leg)")
    ns = ap.parse_args(argv)
    if ns.self_test_illegal_edge:
        rows = read_stream(ns.streams[0])
        seeded_line = (rows[-1][0] if rows else 0) + 1
        rows.append((seeded_line, {
            "event": "replica_health", "replica": 0,
            "from": "dead", "to": "ready", "reason": "probe_ok"}))
        findings = check_rows(rows, source=os.path.relpath(ns.streams[0]))
        caught = [f for f in findings if f.line == seeded_line]
        if caught:
            print("self-test: seeded illegal edge caught:\n"
                  + format_findings(caught))
            return 0
        print("self-test FAILED: the seeded dead->ready edge was not "
              "flagged")
        return 1
    findings: List[Finding] = []
    n_rows = 0
    for path in ns.streams:
        rows: List[Tuple[int, dict]] = []
        if os.path.exists(path + ".1"):
            rows += read_stream(path + ".1")
        rows += read_stream(path)
        n_rows += len(rows)
        findings += check_rows(rows, source=os.path.relpath(path))
    print(f"protocol-trace: {len(findings)} finding(s) over "
          f"{n_rows} row(s) in {len(ns.streams)} stream(s)")
    if findings:
        print(format_findings(findings, verbose=True))
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
