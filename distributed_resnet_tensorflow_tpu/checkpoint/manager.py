"""Checkpoint management — orbax-backed, async, auto-resuming.

Capability parity with the reference's checkpointing (SURVEY.md §2.14):
  * chief-written, time-based checkpoints every 60 s (CIFAR) / 600 s
    (ImageNet) via ``MonitoredTrainingSession(save_checkpoint_secs=...)``
    (reference resnet_cifar_main.py:327-329, resnet_imagenet_main.py:250-261),
  * automatic resume from the latest checkpoint on restart
    (MonitoredTrainingSession semantics),
  * read-only polling restore for the evaluator
    (reference resnet_cifar_eval.py:101-109).

TPU-native upgrades: checkpoints are sharded-array aware (every process
participates in saving its shards — there is no single "chief" writing the
full state over NFS), saves are asynchronous (training continues while the
previous step serializes), and both step-based and time-based cadences are
supported simultaneously.
"""
from __future__ import annotations

import logging
import os
import time
from typing import Any, Optional, Tuple

import jax
import orbax.checkpoint as ocp

log = logging.getLogger(__name__)


def _saveable(state) -> dict:
    """The pytree part of a TrainState (drops static apply_fn/tx)."""
    return {
        "step": state.step,
        "params": state.params,
        "batch_stats": state.batch_stats,
        "opt_state": state.opt_state,
    }


class CheckpointManager:
    """Thin policy wrapper over ``orbax.checkpoint.CheckpointManager``.

    save cadence = step-based (``save_every_steps``) OR time-based
    (``save_every_secs``), whichever fires first — the reference only had the
    time axis (reference resnet_cifar_main.py:329).
    """

    def __init__(self, directory: str, max_to_keep: int = 5,
                 save_every_steps: int = 0, save_every_secs: float = 0.0,
                 async_save: bool = True,
                 layout_stamp: Optional[dict] = None):
        # layout_stamp: declares how depth-stacked params are ORDERED (the
        # circular pipeline schedule stores stage-major order, a function of
        # (pstages, interleave) — models/pipeline.py). Saved as a sidecar so
        # a restore under a different layout fails loudly instead of running
        # layers in a silently-permuted network order.
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._layout_stamp = layout_stamp
        self.save_every_steps = save_every_steps
        self.save_every_secs = save_every_secs
        self._last_save_time = time.monotonic()
        self._last_save_step = 0
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            enable_async_checkpointing=async_save,
        )
        self._mngr = ocp.CheckpointManager(self.directory, options=options)
        # fail at construction, not at the first save cadence minutes into
        # training: everything the layout check needs already exists here
        self._check_layout()

    # -- policy ------------------------------------------------------------
    def should_save(self, step: int) -> bool:
        from ..utils import cadence_crossed
        # boundary-crossing (not modulo): fused loops only surface loop-end
        # steps, which need not be multiples of the cadence
        if self.save_every_steps and cadence_crossed(
                step, self.save_every_steps, self._last_save_step):
            return True
        if self.save_every_secs and \
                time.monotonic() - self._last_save_time >= self.save_every_secs:
            return True
        return False

    def maybe_save(self, step: int, state) -> bool:
        if not self.should_save(step):
            return False
        self.save(step, state)
        return True

    # -- mechanics ---------------------------------------------------------
    @property
    def _layout_path(self) -> str:
        return os.path.join(self.directory, "layout.json")

    def saved_layout(self) -> Optional[dict]:
        import json
        try:
            with open(self._layout_path) as f:
                return json.load(f)
        except (OSError, ValueError):
            # unreadable/corrupt sidecar ranks as absent; _check_layout then
            # assumes the conservative network order, which refuses rather
            # than silently permutes
            return None

    @staticmethod
    def _strip_meta(stamp):
        """Layout comparison ignores the bookkeeping key."""
        if not stamp:
            return stamp
        return {k: v for k, v in stamp.items() if k != "applies_from_step"}

    def _check_layout(self) -> None:
        cur = self._layout_stamp
        if cur is None:
            return  # caller declared no stacked layout — nothing to enforce
        latest = self.latest_step()
        if latest is None:
            # no committed checkpoint — an orphaned sidecar (stamp written,
            # save failed) conflicts with nothing and gets overwritten
            return
        saved = self.saved_layout()
        if saved is not None:
            af = saved.get("applies_from_step")
            if af is not None and af > latest:
                # the sidecar is written before the (async) orbax commit; a
                # crash between the two leaves a stamp describing a step
                # that never landed. Ignore it — the committed checkpoints
                # all predate it (ADVICE r3 #4)
                saved = None
        # checkpoints that predate layout stamping could only have been
        # network order
        saved = self._strip_meta(saved) or {"encoder_order": "network"}
        circular = "circular" in (saved.get("encoder_order"),
                                  cur.get("encoder_order"))
        if circular and saved != cur:
            raise ValueError(
                f"checkpoint {self.directory} stores stacked encoder params "
                f"in layout {saved} but this run uses {cur}; restoring would "
                "silently permute layer order. Migrate with "
                "models.pipeline.repack_stacked_params, or match "
                "mesh.pipeline / model.vit_pipeline_interleave")

    def _write_layout(self, step: int) -> None:
        # chief-only + atomic: every host shares this directory, and
        # concurrent truncating writes could leave unparseable JSON.
        # ``applies_from_step`` records the first step this stamp describes,
        # so a stamp orphaned by a crash before the async commit can be
        # recognized (newer than every committed step) and ignored
        if jax.process_index() != 0:
            return
        import json
        import tempfile
        fd, tmp = tempfile.mkstemp(dir=self.directory, suffix=".layout")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump({**self._layout_stamp, "applies_from_step": step},
                          f)
            os.replace(tmp, self._layout_path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)

    def save(self, step: int, state, force: bool = False) -> None:
        if step in self._mngr.all_steps():
            return  # idempotent: step already checkpointed
        self._check_layout()
        if self._layout_stamp is not None:
            saved = self.saved_layout()
            # rewrite when the layout differs OR the existing stamp's
            # applies_from_step is ahead of this commit (a crash orphan
            # from an earlier run; left alone it would outrank every step
            # this run commits and _check_layout would keep discarding it)
            if (self._strip_meta(saved) != self._layout_stamp
                    or (saved or {}).get("applies_from_step", step) > step):
                self._write_layout(step)
        self._mngr.save(step, args=ocp.args.StandardSave(_saveable(state)),
                        force=force)
        self._last_save_time = time.monotonic()
        self._last_save_step = step

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def restore(self, state, step: Optional[int] = None):
        """Restore into the sharding/structure of ``state`` (shardings are
        taken from the abstract target, so restored arrays land exactly where
        the live ones are). Returns (new_state, restored_step) or
        (state, None) when no checkpoint exists."""
        if step is None:
            step = self.latest_step()
        if step is None:
            return state, None
        self._check_layout()
        abstract = jax.tree_util.tree_map(
            ocp.utils.to_shape_dtype_struct, _saveable(state))
        restored = self._mngr.restore(
            step, args=ocp.args.StandardRestore(abstract))
        new_state = state.replace(
            step=restored["step"], params=restored["params"],
            batch_stats=restored["batch_stats"],
            opt_state=restored["opt_state"])
        # resume continues the cadence from the restored step — without this,
        # the first maybe_save after a restart fires immediately off-cadence
        self._last_save_step = step
        self._last_save_time = time.monotonic()
        return new_state, step

    def wait_until_finished(self) -> None:
        self._mngr.wait_until_finished()

    def close(self) -> None:
        self._mngr.wait_until_finished()
        self._mngr.close()


def wait_for_new_checkpoint(directory: str, last_seen: Optional[int],
                            timeout_secs: float = 0.0,
                            poll_secs: float = 60.0) -> Optional[int]:
    """Block until a checkpoint newer than ``last_seen`` appears — the
    evaluator's polling primitive (reference resnet_cifar_eval.py:99-141
    polled get_checkpoint_state + slept 60 s). timeout 0 = single poll."""
    deadline = time.monotonic() + timeout_secs if timeout_secs else None
    while True:
        try:
            steps = ocp.utils.checkpoint_steps(directory)
        except (FileNotFoundError, ValueError):
            steps = []
        newest = max(steps) if steps else None
        if newest is not None and (last_seen is None or newest > last_seen):
            return newest
        if deadline is None or time.monotonic() >= deadline:
            return None
        time.sleep(min(poll_secs, max(0.0, deadline - time.monotonic())))
