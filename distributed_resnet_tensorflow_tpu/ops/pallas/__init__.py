from .softmax_xent import softmax_xent, softmax_xent_mean  # noqa: F401
from .flash_attention import flash_attention  # noqa: F401
