"""Profiling / tracing — the subsystem the reference left vestigial.

The reference had commented-out ``tf.contrib.tfprof`` param/FLOP counting
(reference resnet_single.py:58-66, commented at resnet_cifar_main.py:260-268)
and measured throughput offline from log timestamps (SURVEY.md §5). Here:

  * ``count_params`` / ``flops_per_step``  — live counters from the compiled
    XLA executable (cost analysis), not estimates.
  * ``mfu``                                — model FLOPs utilization against
    a per-generation peak table.
  * ``trace``                              — context manager around
    ``jax.profiler`` emitting a TensorBoard-viewable trace.
"""
from __future__ import annotations

import contextlib
import logging
from typing import Any, Dict, Iterator, Optional

import jax

log = logging.getLogger(__name__)

# bf16 peak TFLOP/s per JAX DEVICE by TPU generation (public spec-sheet
# numbers). mfu() multiplies by jax.device_count(), and on v2/v3 JAX
# exposes each of the chip's 2 cores as a device — so those entries are
# per-CORE (chip peak / 2); v4+ are one device per chip.
TPU_PEAK_TFLOPS = {
    "v2": 45.0 / 2, "v3": 123.0 / 2,
    "v4": 275.0, "v5e": 197.0, "v5 lite": 197.0, "v5p": 459.0, "v6e": 918.0,
}


def count_params(params: Any) -> int:
    return sum(p.size for p in jax.tree_util.tree_leaves(params))


def flops_per_step(jitted_fn, *example_args) -> Optional[float]:
    """FLOPs of one compiled step, from XLA's own cost analysis."""
    try:
        compiled = jitted_fn.lower(*example_args).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, list):  # older jax returns [dict]
            cost = cost[0]
        return float(cost.get("flops", 0.0)) or None
    except Exception as e:  # cost analysis not supported on this backend
        log.debug("cost analysis unavailable: %s", e)
        return None


def detect_peak_tflops() -> Optional[float]:
    try:
        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        return None
    for key, peak in TPU_PEAK_TFLOPS.items():
        if key in kind:
            return peak
    return None


def mfu(steps_per_sec: float, step_flops: float,
        num_devices: Optional[int] = None,
        peak_tflops: Optional[float] = None) -> Optional[float]:
    """Model FLOPs utilization in [0,1]: achieved / peak."""
    peak = peak_tflops or detect_peak_tflops()
    if not peak or not step_flops:
        return None
    n = num_devices or jax.device_count()
    return (steps_per_sec * step_flops) / (peak * 1e12 * n)


@contextlib.contextmanager
def trace(logdir: str) -> Iterator[None]:
    """jax.profiler trace → TensorBoard 'profile' plugin directory."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def trace_window(logdir: str, duration_secs: float = 5.0) -> str:
    """On-demand jax.profiler window: start, wait ``duration_secs``, stop.

    The flight recorder's anomaly hook (telemetry/tracer.py,
    ``telemetry.profile_on_anomaly``) calls this from the watchdog's
    daemon thread so a hang/straggler incident captures DEVICE-side
    activity alongside the host-side span dump — profiling runs out of
    band of the (possibly wedged) main thread. Safe to call anywhere; a
    profiler that is already active raises inside jax and the caller
    treats that as best-effort."""
    import os
    import time as _time
    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    try:
        _time.sleep(max(0.1, duration_secs))
    finally:
        jax.profiler.stop_trace()
    log.info("jax.profiler window (%.1fs) captured to %s",
             duration_secs, logdir)
    return logdir


def summarize_model(trainer, batch=None) -> Dict[str, Any]:
    """Params + per-step FLOPs + peak for the trainer's compiled step."""
    out: Dict[str, Any] = {
        "params": count_params(trainer.state.params),
        "devices": jax.device_count(),
        "peak_tflops_per_chip": detect_peak_tflops(),
    }
    if batch is not None:
        step = trainer.jitted_train_step()
        out["flops_per_step"] = flops_per_step(step, trainer.state, batch)
    return out
