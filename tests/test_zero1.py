"""ZeRO-1 sharded weight update (parallel/sharding.py rule table +
train/loop.py; arXiv:2004.13336).

The load-bearing claims, pinned on the virtual 8-device mesh:

  * the ZeRO-1 step is numerically allclose (f32 tolerance) to the
    replicated update on dp AND dp_fsdp — and the replicated (off) path
    is the untouched exactness oracle;
  * the gather-order-insensitive part is BIT-identical: under
    comm.overlap, many-bucket vs single-bucket ZeRO-1 runs (both the
    reduce-scatter exchange and the param-update all-gather re-bucket)
    produce bitwise-equal params — bucketing is scheduling, never math;
  * the optimizer state is ACTUALLY sharded: per-replica optimizer bytes
    shrink by exactly (N-1)/N for the shardable leaves, measured from
    the live state's shard shapes;
  * the regex→PartitionSpec rule table (match_partition_rules) resolves
    moment tensors sharded, bookkeeping scalars replicated, and a PARAM
    named like a bookkeeping attr ("scale") is NOT swallowed by the
    attr rule;
  * the resolver refuses unsupported combinations loudly and resolves
    off (with a warning) for single-shard checkpoint consumers.
"""
import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

from distributed_resnet_tensorflow_tpu.parallel import create_mesh
from distributed_resnet_tensorflow_tpu.parallel.sharding import (
    ZERO1_MIN_SIZE, Zero1Report, _SizesMesh, match_partition_rules,
    resolve_zero1, zero1_grad_specs, zero1_rules, zero1_stats,
    zero1_unsupported_reason)
from distributed_resnet_tensorflow_tpu.train import Trainer
from distributed_resnet_tensorflow_tpu.utils.config import (MeshConfig,
                                                            get_preset)


def _tiny_cfg(**kw):
    cfg = get_preset("smoke")
    cfg.model.compute_dtype = "float32"
    cfg.model.resnet_size = 8
    cfg.model.num_classes = 4
    cfg.data.image_size = 8
    cfg.train.batch_size = 16
    cfg.optimizer.schedule = "constant"
    cfg.checkpoint.save_every_secs = 0.0
    for k, v in kw.items():
        cfg.override(k, v)
    return cfg


def _fixed_batches(n=4, bs=16, size=8, classes=4):
    rng = np.random.RandomState(7)
    imgs = rng.randn(n, bs, size, size, 3).astype(np.float32)
    labs = rng.randint(0, classes, (n, bs)).astype(np.int32)
    return [{"images": imgs[i], "labels": labs[i]} for i in range(n)]


def _train(mesh_cfg, batches, **kw):
    cfg = _tiny_cfg(**kw)
    tr = Trainer(cfg, mesh=create_mesh(mesh_cfg))
    tr.init_state()
    state, metrics = tr.train(iter(list(batches)), num_steps=len(batches))
    flat = np.concatenate([np.asarray(l).ravel() for l in
                           jax.tree_util.tree_leaves(state.params)])
    return tr, state, flat, metrics


def _opt_bytes_per_replica(state):
    total = 0
    for leaf in jax.tree_util.tree_leaves(state.opt_state):
        if not hasattr(leaf, "sharding"):
            continue
        shard_shape = leaf.sharding.shard_shape(leaf.shape)
        total += int(np.prod(shard_shape, dtype=np.int64)) * \
            leaf.dtype.itemsize
    return total


# ---------------------------------------------------------------------------
# numerics (the acceptance claim)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mesh_cfg", [
    MeshConfig(data=8),
    # dp_fsdp re-tiered out of the 870s tier-1 (ISSUE 17, ~12s): the dp
    # leg pins the replicated-update equivalence; the dp_fsdp×zero1
    # cross keeps its tier-1 pin via test_zero1_overlap_matches_plain_
    # path[dp_fsdp], the full (unfiltered) suite runs this leg too
    pytest.param(MeshConfig(data=4, fsdp=2), marks=pytest.mark.slow),
], ids=["dp", "dp_fsdp"])
@pytest.mark.parametrize("opt", [
    "momentum",
    # re-tiered out of the 870s tier-1 (ISSUE 13): the momentum leg pins
    # the exchange numerics; the LAMB leg re-runs them with the heavier
    # trust-ratio optimizer and stays in the full (unfiltered) suite
    pytest.param("lamb", marks=pytest.mark.slow),
])
def test_zero1_matches_replicated_update(mesh_cfg, opt):
    """ZeRO-1 on vs off after a few steps: allclose at f32 tolerance
    (the reduction trees differ — reduce-scatter + sharded norms vs the
    replicated update). The off path is byte-for-byte the pre-ZeRO step
    (no code touches it when the knob is off), so this doubles as the
    exactness-oracle check."""
    batches = _fixed_batches()
    kw = {"optimizer.name": opt}
    if opt == "lamb":
        kw["optimizer.weight_decay"] = "1e-4"
    _, _, off, m0 = _train(mesh_cfg, batches, **kw)
    tr, st, on, m1 = _train(mesh_cfg, batches, **kw,
                            **{"optimizer.zero1": "on",
                               "optimizer.zero1_min_size": "16"})
    assert tr.zero1_active
    np.testing.assert_allclose(on, off, rtol=2e-4, atol=2e-5)
    assert abs(float(m0["loss"]) - float(m1["loss"])) < 1e-4
    # ...and the state is genuinely sharded, not just relabeled
    sharded = [l for l in jax.tree_util.tree_leaves(st.opt_state)
               if hasattr(l, "sharding")
               and not l.sharding.is_fully_replicated]
    assert sharded, "zero1=on left every optimizer leaf replicated"


@pytest.mark.slow  # re-tiered out of the 870s tier-1 (ISSUE 20, ~11s: two
# full trainings under zero1+overlap); tier-1 keeps the zero1+overlap path
# via test_zero1_overlap_matches_plain_path[dp] and the bucketing
# bit-identity claim via test_bucketed_is_bit_identical_to_unbucketed[dp];
# the full (unfiltered) suite still runs this composition
def test_zero1_overlap_bucketing_is_bit_identical(devices):
    """The gather-order-insensitive pinned claim: under comm.overlap,
    re-bucketing BOTH collectives legs (reduce-scatter exchange and the
    param-update all-gather) may only change scheduling — many tiny
    buckets vs one giant bucket must produce BITWISE-equal params."""
    batches = _fixed_batches()
    kw = {"comm.overlap": "on", "optimizer.zero1": "on",
          "optimizer.zero1_min_size": "16"}
    _, _, many, _ = _train(MeshConfig(data=8), batches, **kw,
                           **{"comm.bucket_mb": "0.05"})
    plan = zero1_stats.snapshot()
    assert plan is not None and plan.get("gather_buckets", 0) > 1, plan
    _, _, one, _ = _train(MeshConfig(data=8), batches, **kw,
                          **{"comm.bucket_mb": "4096"})
    assert zero1_stats.snapshot()["gather_buckets"] == 1
    np.testing.assert_array_equal(many, one)


@pytest.mark.parametrize("mesh_cfg", [
    MeshConfig(data=8),
    MeshConfig(data=4, fsdp=2),
], ids=["dp", "dp_fsdp"])
def test_zero1_overlap_matches_plain_path(mesh_cfg):
    """ZeRO-1 composed with the bucketed exchange agrees with the plain
    replicated jit path to float rounding."""
    batches = _fixed_batches()
    _, _, base, _ = _train(mesh_cfg, batches)
    _, _, over, _ = _train(mesh_cfg, batches,
                           **{"comm.overlap": "on", "comm.bucket_mb": "0.1",
                              "optimizer.zero1": "on",
                              "optimizer.zero1_min_size": "16"})
    np.testing.assert_allclose(over, base, rtol=2e-4, atol=2e-5)


@pytest.mark.slow  # re-tiered out of the 870s tier-1 (ISSUE 13); the bench zero1 row measures the same live shard shapes
def test_zero1_memory_shrinks_by_n_minus_1_over_n(devices):
    """Per-replica optimizer bytes, measured from live shard shapes: the
    shardable leaves cost exactly 1/N per replica; the total matches the
    partition report's projection."""
    batches = _fixed_batches(n=1)
    _, st_off, _, _ = _train(MeshConfig(data=8), batches,
                             **{"optimizer.name": "lamb",
                                "optimizer.weight_decay": "1e-4"})
    tr, st_on, _, _ = _train(MeshConfig(data=8), batches,
                             **{"optimizer.name": "lamb",
                                "optimizer.weight_decay": "1e-4",
                                "optimizer.zero1": "on",
                                "optimizer.zero1_min_size": "16"})
    off_bytes = _opt_bytes_per_replica(st_off)
    on_bytes = _opt_bytes_per_replica(st_on)
    plan = zero1_stats.snapshot()
    assert plan["bytes_per_replica"] == on_bytes
    assert plan["bytes_per_replica_unsharded"] == off_bytes
    # shardable leaves shrink by exactly (N-1)/N
    assert plan["sharded_bytes"] > 0
    assert on_bytes == plan["replicated_bytes"] + \
        plan["sharded_bytes"] // 8
    # and they dominate this model, so the total shrinks hard too
    assert on_bytes < off_bytes / 4


# ---------------------------------------------------------------------------
# rule table
# ---------------------------------------------------------------------------

def test_match_partition_rules_first_match_wins_and_exhaustive():
    shapes = {"a": jax.ShapeDtypeStruct((8, 4), np.float32),
              "b": jax.ShapeDtypeStruct((3,), np.float32)}
    specs = match_partition_rules(
        ((r"a", P("data", None)), (r".*", P())), shapes)
    assert specs["a"] == P("data", None) and specs["b"] == P()
    with pytest.raises(ValueError, match="no partition rule"):
        match_partition_rules(((r"a", P()),), shapes)


def test_zero1_rules_classification():
    """Moment tensors shard on their largest free divisible dim;
    bookkeeping NamedTuple attrs (.count) replicate; a PARAM keyed
    "scale" (a dict key, not an attr) is NOT swallowed by the
    bookkeeping rule; non-divisible and small leaves fall back counted."""
    import optax
    p = {"w": np.zeros((128, 64), np.float32),
         "scale": np.zeros((256,), np.float32),       # param named scale
         "odd": np.zeros((129, 3), np.float32),       # nothing divides by 8
         "tiny": np.zeros((4,), np.float32)}
    state = jax.eval_shape(lambda: optax.lamb(0.01).init(p))
    report = Zero1Report(8)
    specs = match_partition_rules(
        zero1_rules(_SizesMesh({"data": 8}), min_size=16, report=report),
        state)
    adam = specs[0]
    assert adam.count == P()
    assert adam.mu["w"] == P("data", None)
    assert adam.mu["scale"] == P("data")
    assert adam.mu["odd"] == P()
    assert adam.mu["tiny"] == P()
    snap = report.snapshot()
    assert snap["reasons"]["sharded"] == 4          # w + scale, mu and nu
    assert snap["reasons"]["no-divisible-dim"] == 2  # odd, mu and nu
    assert snap["reasons"]["below-min-size"] == 2    # tiny, mu and nu
    assert snap["reasons"]["bookkeeping"] == 1      # .count
    assert snap["bytes_per_replica"] < snap["bytes_per_replica_unsharded"]


def test_zero1_grad_specs_agree_with_state_layout(mesh8):
    """The grads-tree specs (reduce-scatter targets) and the
    optimizer-state moment specs must name the same data dim per leaf —
    disagreement would reshard every step."""
    import optax
    p = {"w": np.zeros((128, 64), np.float32),
         "v": np.zeros((64, 32), np.float32)}
    gspecs = zero1_grad_specs(p, mesh8, min_size=16)
    state = jax.eval_shape(lambda: optax.sgd(0.1, momentum=0.9).init(p))
    sspecs = match_partition_rules(
        zero1_rules(mesh8, min_size=16), state)
    trace = sspecs[0].trace  # optax.sgd(momentum=...) chains TraceState
    assert gspecs["w"] == trace["w"]
    assert gspecs["v"] == trace["v"]


# ---------------------------------------------------------------------------
# resolver / envelope
# ---------------------------------------------------------------------------

def test_zero1_resolver_gates(devices):
    mesh = create_mesh(MeshConfig(data=8))
    assert resolve_zero1(_tiny_cfg(), mesh) is False            # default off
    assert resolve_zero1(
        _tiny_cfg(**{"optimizer.zero1": "on"}), mesh) is True
    # auto stays off single-process (the multi-host memory bind is the
    # target)
    assert resolve_zero1(
        _tiny_cfg(**{"optimizer.zero1": "auto"}), mesh) is False
    with pytest.raises(ValueError, match="unknown optimizer.zero1"):
        resolve_zero1(_tiny_cfg(**{"optimizer.zero1": "maybe"}), mesh)
    # a single-data-shard mesh is what checkpoint consumers see — a
    # forced train-only knob must resolve off loudly, not crash them
    single = create_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
    assert resolve_zero1(
        _tiny_cfg(**{"optimizer.zero1": "on"}), single) is False
    # program-shaping axes are outside the envelope
    pp = create_mesh(MeshConfig(data=4, pipeline=2))
    assert zero1_unsupported_reason(
        _tiny_cfg(**{"optimizer.zero1": "on"}), pp) is not None
    with pytest.raises(ValueError, match="pipeline"):
        resolve_zero1(_tiny_cfg(**{"optimizer.zero1": "on"}), pp)


def test_lamb_and_warmup_poly_available():
    """The large-batch recipe pieces: LAMB builds + trains, warmup_poly
    warms linearly then decays polynomially to 0, and the new presets
    resolve end to end."""
    from distributed_resnet_tensorflow_tpu.train.schedules import (
        create_schedule, linear_scaled_lr, warmup_poly)
    sched = warmup_poly(warmup_steps=10, peak=2.0, total_steps=110)
    assert float(sched(0)) == 0.0
    np.testing.assert_allclose(float(sched(5)), 1.0, rtol=1e-6)
    np.testing.assert_allclose(float(sched(10)), 2.0, rtol=1e-6)
    assert float(sched(60)) < 2.0
    np.testing.assert_allclose(float(sched(110)), 0.0, atol=1e-7)
    assert linear_scaled_lr(0.1, 4096) == pytest.approx(1.6)
    for preset in ("imagenet_resnet50_lars4k", "imagenet_resnet50_lamb4k"):
        cfg = get_preset(preset)
        assert cfg.optimizer.zero1 == "on"
        assert cfg.optimizer.warmup_steps > 0
        create_schedule(cfg.optimizer)  # resolves without error


def test_zero1_event_row(tmp_path, devices):
    from distributed_resnet_tensorflow_tpu.train.hooks import Zero1Hook
    from distributed_resnet_tensorflow_tpu.utils.metrics import (
        MetricsWriter, read_metrics)
    zero1_stats.reset()
    batches = _fixed_batches(n=2)
    cfg = _tiny_cfg(**{"optimizer.zero1": "on",
                       "optimizer.zero1_min_size": "16"})
    tr = Trainer(cfg, mesh=create_mesh(MeshConfig(data=8)))
    assert tr.zero1_active
    tr.init_state()
    w = MetricsWriter(str(tmp_path), enable_tensorboard=False)
    hook = Zero1Hook(w, every_steps=1)
    tr.train(iter(batches), num_steps=2, hooks=(hook,))
    w.close()
    rows = [r for r in read_metrics(str(tmp_path))
            if r.get("event") == "zero1"]
    assert len(rows) == 1  # one row per resolved plan, not per step
    row = rows[0]
    assert row["data_shards"] == 8
    assert row["sharded_leaves"] > 0
    assert row["bytes_per_replica"] < row["bytes_per_replica_unsharded"]
