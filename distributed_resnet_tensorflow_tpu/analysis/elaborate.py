"""Static elaboration: trace every preset × mesh layout abstractly.

For each configuration this module builds a VIRTUAL device mesh
(``utils/virtual_devices.py`` — the same fake-CPU-mesh trick the test
suite and ``dryrun_multichip`` use), constructs the real Trainer, and
pushes shape/dtype-only values through:

  * state construction  (``train/state.abstract_train_state``),
  * the sharding rules  (every leaf's PartitionSpec validated against its
    shape and the mesh — the offending PARAM PATH and spec are reported,
    not a 40-frame XLA traceback),
  * the train step      (``jax.eval_shape`` of value_and_grad — this is
    where shard_map in/out-spec errors, rank errors and divisibility
    errors surface at trace time; the pp×ep MoE ``_SpecError`` of
    tests/test_pipeline.py was located exactly this way),
  * the eval step,
  * the bucketed-overlap train step (``comm.overlap=on``,
    parallel/overlap.py) for every layout inside its envelope — the
    shard_map'd exchange traces per preset × layout so the knob can't
    compile-crash on first cluster use,
  * the serve/predict step, once per batch bucket the inference server
    would AOT-compile (serve/compile_cache.bucket_sizes),
  * the coalesced staged-unpack program — with the fused on-device
    imagenet augmentation when the preset would run it
    (parallel/sharding.abstract_staged_unpack), flat and stacked, and
  * the checkpoint-restore contract (layout stamp + unique leaf paths).

Zero data, zero compute, no compilation: the whole ``--all-presets``
sweep runs in seconds on CPU — cheap enough to be a pre-submit gate
(``scripts/analysis_gate.sh``) instead of a 20-minute queue wait that
ends in a step-1 crash.
"""
from __future__ import annotations

import math
from typing import Iterable, List, Optional, Sequence, Tuple

import numpy as np

from .report import Finding


def _findings_from_exc(rule: str, locus: str, phase: str,
                       exc: Exception) -> Finding:
    msg = f"{type(exc).__name__}: {exc}"
    first = msg.splitlines()[0][:300]
    return Finding(rule, locus, 0, f"{phase}: {first}", detail=msg[:4000])


def candidate_layouts(cfg, n_devices: int) -> List[Tuple[str, "object"]]:
    """(label, MeshConfig) pairs worth elaborating for this config.

    Always the two data-parallel shapes every model family supports; for
    the transformer family additionally a pipeline and a tensor layout
    (those axes only have consumers there — Trainer rejects them
    elsewhere). Layouts that cannot satisfy the model's own divisibility
    contracts (depth % stages, heads % tensor, local batch % microbatches)
    are filtered HERE — the elaborator's job is finding bugs in valid
    configs, not re-reporting documented constraints."""
    from ..utils.config import MeshConfig
    out = [("dp", MeshConfig(data=n_devices))]
    if n_devices % 2 == 0:
        out.append(("dp_fsdp", MeshConfig(data=n_devices // 2, fsdp=2)))
    if cfg.model.name == "vit":
        from ..models.pipeline import resolve_microbatches
        depth = cfg.model.vit_depth
        heads = cfg.model.vit_heads
        hidden = 4 * cfg.model.vit_dim
        bs = cfg.train.batch_size
        v = max(1, cfg.model.vit_pipeline_interleave)
        p = 2
        m = resolve_microbatches(cfg.model.vit_pipeline_microbatches, p)

        def pp_ok(local_b: int) -> bool:
            # mirror PipelinedEncoder's OWN contract exactly (depth %
            # (P*v), local batch % M, and M >= P only under the circular
            # schedule's wrap) — stricter filtering here would silently
            # drop layouts that run fine, laxer would re-report the
            # encoder's documented ValueErrors as gate findings
            return depth % (p * v) == 0 and local_b % m == 0 and \
                (v == 1 or m >= p)

        # dp=2 × pp=2: each data shard runs its own 2-stage pipeline
        if pp_ok(bs // 2):
            out.append(("dp_pp", MeshConfig(data=2, pipeline=p)))
        if heads % 2 == 0 and hidden % 2 == 0 and n_devices % 8 == 0:
            out.append(("dp_tp", MeshConfig(data=4, tensor=2)))
        e = cfg.model.vit_num_experts
        if e > 0 and e % 2 == 0 and pp_ok(bs // 2):
            out.append(("dp_pp_ep",
                        MeshConfig(data=2, pipeline=2, expert=2)))
    return out


def _axis_product(mesh_cfg) -> int:
    return math.prod(max(1, s) for s in (
        mesh_cfg.data, mesh_cfg.fsdp, mesh_cfg.tensor, mesh_cfg.pipeline,
        mesh_cfg.sequence, mesh_cfg.expert))


def _abstract_batch(cfg, batch_size: int):
    """Shape/dtype skeleton of one host batch as the input pipeline would
    deliver it on this backend (float32 images after host-side prep)."""
    import jax
    if cfg.model.name == "logistic":
        img = jax.ShapeDtypeStruct((batch_size, cfg.model.input_size),
                                   np.float32)
    else:
        s = cfg.data.image_size
        img = jax.ShapeDtypeStruct((batch_size, s, s, 3), np.float32)
    lab = jax.ShapeDtypeStruct((batch_size,), np.int32)
    return {"images": img, "labels": lab}


def check_spec_tree(state_shapes, shardings, mesh,
                    locus: str) -> Iterable[Finding]:
    """Validate every leaf's PartitionSpec against its shape and the mesh:
    spec rank ≤ array rank, and every named axis (product) divides its
    dimension. This is the report that names the offending param path and
    spec instead of a runtime ``_SpecError``."""
    import jax
    flat_shapes = jax.tree_util.tree_flatten_with_path(state_shapes)[0]
    flat_shard = jax.tree_util.tree_flatten_with_path(shardings)[0]
    shard_by_path = {jax.tree_util.keystr(p): s for p, s in flat_shard}
    for path, leaf in flat_shapes:
        key = jax.tree_util.keystr(path)
        sh = shard_by_path.get(key)
        spec = getattr(sh, "spec", None)
        if spec is None:
            continue
        shape = tuple(getattr(leaf, "shape", ()))
        if len(spec) > len(shape):
            yield Finding(
                "elab-spec", locus, 0,
                f"param {key}: spec {spec} has rank {len(spec)} but the "
                f"leaf has shape {shape} (rank {len(shape)})")
            continue
        for d, names in enumerate(spec):
            if names is None:
                continue
            names = names if isinstance(names, tuple) else (names,)
            size = math.prod(mesh.shape.get(n, 1) for n in names)
            if size and shape[d] % size:
                yield Finding(
                    "elab-spec", locus, 0,
                    f"param {key}: spec {spec} maps dim {d} "
                    f"(size {shape[d]}) onto mesh axes {names} of total "
                    f"size {size}, which does not divide it")


def elaborate_config(cfg, mesh_cfg, locus: str,
                     trace_steps: bool = True,
                     trace_forward: bool = True,
                     trace_comm_variants: bool = True,
                     _state_cache: Optional[dict] = None,
                     _precision_seen: Optional[set] = None) -> List[Finding]:
    """Elaborate ONE (config, mesh layout): returns findings (empty=clean).

    ``trace_steps=False`` skips the train/eval-step traces (the expensive
    part) — used by run_elaborate for layouts whose step graph is
    IDENTICAL to one already traced: a CNN's step does not read the mesh
    at trace time (only jit placement does), so dp vs dp_fsdp re-traces
    would buy nothing. Transformer configs re-trace per layout (the mesh
    is baked into the pipeline/tensor/expert program). ``_state_cache``
    memoizes the abstract state per batch-shard count for the same
    reason.

    ``trace_forward=False`` additionally skips the OPTIMIZER-INDEPENDENT
    traces (eval step, serve buckets) — used when another preset with
    the identical forward config (model × data × serve) already traced
    them: the large-batch optimizer variants (lars4k/lamb4k/lars32k)
    share imagenet_resnet50's forward exactly, and re-sweeping every
    serve bucket per optimizer would triple the gate's largest cost for
    zero coverage.

    ``trace_comm_variants=False`` skips the comm-program traces this
    phase shares with hangcheck's schedule extractor — the
    ``comm.overlap=on`` step and the bf16 + compressed-exchange
    composition. When the hangcheck-schedule phase runs (the gate's
    default), ``analysis/collectives.py`` traces those SAME programs via
    ``jax.make_jaxpr`` (reporting trace failures as findings with the
    same semantics), so re-eval_shaping them here would double the
    gate's largest cost for zero coverage; ``--no-hangcheck`` flips them
    back on."""
    import jax
    from ..parallel.mesh import batch_shard_count, create_mesh
    from ..train.loop import Trainer
    from ..train.state import abstract_train_state, state_shardings
    from ..utils.config import stacked_layout_stamp

    findings: List[Finding] = []
    n = _axis_product(mesh_cfg)
    devices = jax.devices()[:n]
    if len(devices) < n:
        return [Finding("elab-env", locus, 0,
                        f"layout needs {n} devices but only "
                        f"{len(devices)} present — run under "
                        "utils.virtual_devices.apply_virtual_cpu")]
    try:
        mesh = create_mesh(mesh_cfg, devices=devices)
        trainer = Trainer(cfg, mesh=mesh)
    except Exception as e:
        return [_findings_from_exc("elab-build", locus, "trainer build", e)]

    try:
        nb = batch_shard_count(mesh)
        cache_key = (nb, cfg.model.name == "vit" and (
            mesh.shape.get("pipeline", 1), mesh.shape.get("tensor", 1),
            mesh.shape.get("expert", 1), mesh.shape.get("seq", 1)))
        state_shapes = None if _state_cache is None \
            else _state_cache.get(cache_key)
        if state_shapes is None:
            state_shapes = abstract_train_state(
                trainer.model, trainer.tx,
                (nb, cfg.data.image_size, cfg.data.image_size, 3)
                if cfg.model.name != "logistic"
                else (nb, cfg.model.input_size))
            if _state_cache is not None:
                _state_cache[cache_key] = state_shapes
    except Exception as e:
        return [_findings_from_exc("elab-state", locus, "state init", e)]

    try:
        shardings = state_shardings(state_shapes, mesh)
        findings.extend(check_spec_tree(state_shapes, shardings, mesh,
                                        locus))
    except Exception as e:
        findings.append(_findings_from_exc("elab-spec", locus,
                                           "sharding rules", e))
        return findings

    # train step: trace fwd+bwd+optimizer abstractly. shard_map spec/rank
    # mismatches, collective-axis errors and AD residual issues all fire
    # at trace time (zero compute)
    if trace_steps:
        try:
            batch = _abstract_batch(cfg, cfg.train.batch_size)
            jax.eval_shape(trainer._train_step, state_shapes, batch)
        except Exception as e:
            findings.append(_findings_from_exc("elab-train-step", locus,
                                               "train step", e))

        # eval step: batch padded exactly as Trainer.evaluate pads it
        # (batch shards × pipeline microbatches). Optimizer-independent:
        # skipped when an identical-forward preset already traced it
        # (trace_forward)
        try:
            if trace_forward:
                pad_to = trainer.eval_pad_multiple()
                ebs = cfg.data.eval_batch_size
                ebs = ebs + (-ebs) % pad_to  # pad_batch_to_multiple contract
                ebatch = _abstract_batch(cfg, ebs)
                ebatch["mask"] = jax.ShapeDtypeStruct((ebs,), np.float32)
                jax.eval_shape(trainer._eval_step, state_shapes, ebatch)
        except Exception as e:
            findings.append(_findings_from_exc("elab-eval-step", locus,
                                               "eval step", e))

        # serve/predict step: every batch bucket the inference server
        # would AOT-compile for this preset (serve/compile_cache.py —
        # power-of-two buckets in multiples of the eval pad floor, the
        # request dtype from serve_image_spec), traced abstractly so a
        # bucket that can't trace is a gate finding here, not a serving
        # replica that dies warming its compile cache. Optimizer-
        # independent like the eval step (trace_forward).
        buckets = []
        try:
            if trace_forward:
                from ..serve.compile_cache import bucket_sizes
                from ..serve.server import serve_image_spec
                pad_to = trainer.eval_pad_multiple()
                img_shape, img_dtype = serve_image_spec(cfg)
                # the SAME cap resolution the server uses
                # (InferenceServer): a preset pinning serve.max_batch
                # past eval_batch_size gets its real buckets elaborated,
                # not the eval-sized ones
                max_batch = cfg.serve.max_batch or cfg.data.eval_batch_size
                buckets = bucket_sizes(max_batch, pad_to)
        except Exception as e:
            findings.append(_findings_from_exc("elab-serve-step", locus,
                                               "serve step setup", e))
            buckets = []
        for bucket in buckets:
            # per-bucket try: one gate run reports EVERY bad bucket, not
            # whack-a-mole one per run
            try:
                sbatch = {"images": jax.ShapeDtypeStruct(
                    (bucket,) + img_shape, img_dtype)}
                jax.eval_shape(trainer._predict_step, state_shapes, sbatch)
            except Exception as e:
                findings.append(_findings_from_exc(
                    "elab-serve-step", locus,
                    f"serve step (bucket {bucket})", e))

        # bucketed-overlap train step (parallel/overlap.py): the
        # comm.overlap=on variant of this preset × layout, traced
        # abstractly — a shard_map spec/rank error, a bucket plan that
        # cannot exchange a leaf, or a BN-axis mistake is a gate finding
        # here, not a step-1 crash when an operator first flips the knob
        # on a cluster. The layout-aware envelope covers the transformer
        # family too (dp_tp / dp_pp / dp_pp_ep trace their partial-auto /
        # inline-pipeline exchanges); the state shapes are reused — the
        # axis-named model has an identical param tree.
        try:
            import copy
            from ..parallel.overlap import overlap_unsupported_reason
            if trace_comm_variants and \
                    overlap_unsupported_reason(cfg, mesh) is None:
                ocfg = copy.deepcopy(cfg)
                ocfg.comm.overlap = "on"
                otrainer = Trainer(ocfg, mesh=mesh)
                batch = _abstract_batch(ocfg, ocfg.train.batch_size)
                jax.eval_shape(otrainer._train_step, state_shapes, batch)
        except Exception as e:
            findings.append(_findings_from_exc("elab-overlap-step", locus,
                                               "bucketed overlap step", e))

        # the gradient-accumulation composition: the scan runs INSIDE
        # the exchange body (one bucketed exchange per optimizer step),
        # so its trace is a different program than the plain overlap
        # step. One accum factor per preset, on its batch-only layout —
        # the shaped layouts share the body machinery just traced above.
        try:
            import copy
            from ..parallel.overlap import overlap_unsupported_reason
            shaped = any(mesh.shape.get(a, 1) > 1
                         for a in ("pipeline", "tensor", "expert", "seq"))
            if trace_comm_variants and not shaped:
                acfg = copy.deepcopy(cfg)
                acfg.comm.overlap = "on"
                acfg.train.grad_accum_steps = 4 if cfg.train.batch_size \
                    % (batch_shard_count(mesh) * 4) == 0 else 2
                if overlap_unsupported_reason(acfg, mesh) is None:
                    atrainer = Trainer(acfg, mesh=mesh)
                    batch = _abstract_batch(acfg, acfg.train.batch_size)
                    jax.eval_shape(atrainer._train_step, state_shapes,
                                   batch)
        except Exception as e:
            findings.append(_findings_from_exc(
                "elab-overlap-step", locus,
                "bucketed overlap + accumulation step", e))

        # the hierarchical-exchange composition (comm.hierarchy=on): the
        # staged RS -> inter-psum -> AG program is a different trace
        # than the flat exchange — a grouped-collective spec error or a
        # padding/rank bug in the staged concat must surface here, not
        # when an operator first factors a real multi-host mesh. Forced
        # via comm.intra_axis_size (no real host boundary on the gate's
        # virtual mesh); batch-only layouts, data axis factorable.
        try:
            import copy
            from ..parallel.overlap import overlap_unsupported_reason
            shaped = any(mesh.shape.get(a, 1) > 1
                         for a in ("pipeline", "tensor", "expert", "seq"))
            dsize = int(mesh.shape.get("data", 1))
            if trace_comm_variants and not shaped and dsize >= 4 \
                    and dsize % 2 == 0:
                hcfg = copy.deepcopy(cfg)
                hcfg.comm.overlap = "on"
                hcfg.comm.hierarchy = "on"
                hcfg.comm.intra_axis_size = dsize // 2
                if overlap_unsupported_reason(hcfg, mesh) is None:
                    htrainer = Trainer(hcfg, mesh=mesh)
                    batch = _abstract_batch(hcfg, hcfg.train.batch_size)
                    jax.eval_shape(htrainer._train_step, state_shapes,
                                   batch)
        except Exception as e:
            findings.append(_findings_from_exc(
                "elab-overlap-step", locus,
                "bucketed overlap + hierarchical exchange step", e))

        # bf16 precision-policy step (parallel/precision.py): the
        # train.precision=bf16 variant of this preset × layout, traced
        # abstractly over the SAME f32 master state shapes (the policy's
        # whole contract) — a policy cast that breaks a shard_map spec,
        # a model family that can't take the dtype override, or a
        # fused-kernel dtype mismatch is a gate finding here, not a
        # step-1 crash when an operator first flips the knob. Presets
        # that already pin precision=bf16 were traced above; the
        # compressed-exchange composition rides the overlap envelope.
        try:
            import copy
            import dataclasses as _dc
            from ..parallel.overlap import overlap_unsupported_reason
            # dedupe across presets sharing the identical
            # (model, data, optimizer) triple — the schedule/batch
            # variants of one base preset would re-trace the same bf16
            # program (the trace_forward lesson from round 11). Batch
            # size is deliberately NOT in the key: this trace hunts
            # DTYPE bugs, which are batch-independent; divisibility is
            # the main elab-train-step trace's job, per preset.
            pkey = repr((_dc.asdict(cfg.model), cfg.data.dataset,
                         cfg.data.image_size, cfg.optimizer.name))
            seen = _precision_seen if _precision_seen is not None \
                else set()
            if cfg.train.precision == "off" and pkey not in seen:
                seen.add(pkey)
                pcfg = copy.deepcopy(cfg)
                pcfg.train.precision = "bf16"
                ptrainer = Trainer(pcfg, mesh=mesh)
                batch = _abstract_batch(pcfg, pcfg.train.batch_size)
                jax.eval_shape(ptrainer._train_step, state_shapes, batch)
                if trace_forward:
                    # the serving reduced-precision VARIANT forwards,
                    # one bucket each (the dtype path is
                    # bucket-independent) — traced over the CAST
                    # abstract state, exactly what ServeCompileCache
                    # compiles each variant against. "bf16" covers the
                    # cast-dtype path, "int8" the weight-only
                    # quantize/dequantize path (marker-dict param tree)
                    from ..parallel.precision import make_variant_cast
                    pad_to = ptrainer.eval_pad_multiple()
                    from ..serve.server import serve_image_spec
                    vshape, vdtype = serve_image_spec(pcfg)
                    vbatch = {"images": jax.ShapeDtypeStruct(
                        (pad_to,) + vshape, vdtype)}
                    for variant in ("bf16", "int8"):
                        vstep = ptrainer.make_variant_predict_step(
                            variant)
                        vstate = jax.eval_shape(
                            make_variant_cast(variant), state_shapes)
                        jax.eval_shape(vstep, vstate, vbatch)
                if trace_comm_variants and \
                        overlap_unsupported_reason(pcfg, mesh) is None:
                    # bf16 step × bucketed exchange × compressed payload
                    # — the full low-precision composition (skipped when
                    # hangcheck's schedule phase traces it instead)
                    ccfg = copy.deepcopy(pcfg)
                    ccfg.comm.overlap = "on"
                    ccfg.comm.compress = "bf16"
                    ctrainer = Trainer(ccfg, mesh=mesh)
                    jax.eval_shape(ctrainer._train_step, state_shapes,
                                   batch)
        except Exception as e:
            findings.append(_findings_from_exc(
                "elab-precision-step", locus, "bf16 precision step", e))

        # coalesced staged-unpack program (parallel/sharding._build_unpack)
        # — and, for imagenet presets, the FUSED on-device augmentation
        # riding inside it — traced abstractly per preset, flat and
        # stacked, same gate contract as the serve buckets: an unpack or
        # augment program that cannot trace is a finding here, not a
        # step-1 crash after cluster spin-up. Layouts whose local batch
        # does not divide the batch shards are skipped (every put path
        # rejects those loudly at runtime already — not this gate's bug
        # class).
        try:
            from ..parallel.sharding import (_device_batch_shards,
                                             abstract_staged_unpack)
            bs = cfg.train.batch_size
            n_local = len({s for _, s in _device_batch_shards(mesh)})
            if bs % n_local == 0:
                imagenet = cfg.data.dataset == "imagenet"
                img_dt = np.uint8 if imagenet else np.float32
                # trace the augmenting unpack only when the Trainer
                # would actually build one (imagenet + device_augment
                # not forced off + no transfer reuse — loop.py mirrors
                # this); the neutral unpack is traced for every preset
                fuses = imagenet and cfg.data.device_augment != "off" \
                    and cfg.data.echo_transfer <= 1
                augments = [None] + (
                    [("images", "imagenet_train", cfg.data.augment_pad)]
                    if fuses else [])
                s = cfg.data.image_size
                k = max(2, cfg.train.steps_per_loop)
                for stacked in (False, True):
                    if cfg.model.name == "logistic":
                        ishape = (cfg.model.input_size,)
                    else:
                        ishape = (s, s, 3)
                    lead = (k, bs) if stacked else (bs,)
                    batch_shapes = {
                        "images": jax.ShapeDtypeStruct(lead + ishape,
                                                       img_dt),
                        "labels": jax.ShapeDtypeStruct(lead, np.int32)}
                    for augment in augments:
                        abstract_staged_unpack(
                            mesh, batch_shapes, stacked=stacked,
                            augment=augment, augment_seed=cfg.train.seed)
        except Exception as e:
            findings.append(_findings_from_exc(
                "elab-unpack", locus, "staged unpack (+fused augment)", e))

    # restore contract: the layout stamp must compute, and every leaf path
    # must be unique (the checkpoint manifest is keyed by flattened path)
    try:
        stacked_layout_stamp(cfg)
        flat = jax.tree_util.tree_flatten_with_path(state_shapes)[0]
        keys = [jax.tree_util.keystr(p) for p, _ in flat]
        dupes = {k for k in keys if keys.count(k) > 1}
        if dupes:
            findings.append(Finding(
                "elab-restore", locus, 0,
                f"duplicate state leaf paths {sorted(dupes)[:3]} — the "
                "checkpoint manifest cannot address them"))
    except Exception as e:
        findings.append(_findings_from_exc("elab-restore", locus,
                                           "restore contract", e))
    return findings


#: virtual mesh sizes the ZeRO-1 big-mesh sweep validates against —
#: catching a spec that only breaks at scale (a moment dim 64 devices
#: divide but 256 don't) STATICALLY, before any cluster time
ZERO1_SWEEP_SIZES = (64, 256)


def run_elaborate_zero1(preset_names: Optional[Sequence[str]] = None,
                        sizes: Sequence[int] = ZERO1_SWEEP_SIZES
                        ) -> List[Finding]:
    """The ``elab-zero1`` big-mesh sweep: for every in-envelope preset —
    one that enables ``optimizer.zero1`` (on/auto; a preset with the
    knob off has no ZeRO-1 step or sharded specs to elaborate) and whose
    global batch the layout divides — resolve the ZeRO-1 sharded
    optimizer-state specs on virtual 64- and 256-device dp and dp_fsdp
    meshes and spec-check every leaf (``check_spec_tree`` — the
    offending leaf PATH, not a step-1 ``_SpecError`` on a real pod); for
    presets that PIN the knob on, additionally ``eval_shape`` the full
    ZeRO-1 train step (reduce-scatter constraint + sharded update +
    gather) on the largest mesh. Zero compute; rides the same gate
    budget contract as the 8-device sweep (scripts/analysis_gate.sh)."""
    import copy
    import jax
    from ..parallel.mesh import create_mesh
    from ..parallel.sharding import (ZERO1_MIN_SIZE, Zero1Report,
                                     zero1_state_shardings,
                                     zero1_unsupported_reason)
    from ..train.loop import Trainer
    from ..train.state import abstract_train_state
    from ..utils.config import MeshConfig, PRESETS, get_preset

    import dataclasses
    findings: List[Finding] = []
    need = max(sizes)
    if len(jax.devices()) < need:
        return [Finding(
            "elab-env", "zero1-sweep", 0,
            f"{len(jax.devices())} devices present, {need} needed — the "
            "check CLI must size the virtual CPU mesh for the ZeRO-1 "
            "sweep before jax initializes")]
    # abstract states shared across presets with the identical
    # (model, optimizer) pair — the large-batch variants of one base
    # preset differ only in schedule hyperparams, not state SHAPES
    shared_states: dict = {}
    for name in (preset_names or sorted(PRESETS)):
        cfg = get_preset(name)
        if cfg.optimizer.zero1 == "off":
            continue  # no ZeRO-1 step/specs to elaborate for this preset
        state_key = repr((dataclasses.asdict(cfg.model),
                          cfg.optimizer.name, cfg.data.dataset,
                          cfg.data.image_size))
        state_shapes = shared_states.get(state_key)
        traced = False
        for n in sorted(sizes, reverse=True):
            if cfg.train.batch_size % n:
                continue  # the layout cannot host this preset's batch
            layouts = [(f"zero1-dp{n}", MeshConfig(data=n)),
                       (f"zero1-dp{n // 2}f2",
                        MeshConfig(data=n // 2, fsdp=2))]
            for label, mesh_cfg in layouts:
                locus = f"{name}@{label}"
                try:
                    mesh = create_mesh(mesh_cfg, devices=jax.devices()[:n])
                except Exception as e:
                    findings.append(_findings_from_exc(
                        "elab-zero1", locus, "mesh build", e))
                    continue
                if zero1_unsupported_reason(cfg, mesh) is not None:
                    continue  # outside the envelope — documented, not a bug
                try:
                    if state_shapes is None:
                        # model/optimizer shapes are mesh-independent for
                        # the batch-parallel families: build once per
                        # (model, optimizer), spec-check every
                        # (preset, size, layout)
                        t = Trainer(copy.deepcopy(cfg), mesh=mesh)
                        state_shapes = abstract_train_state(
                            t.model, t.tx,
                            (1, cfg.data.image_size,
                             cfg.data.image_size, 3)
                            if cfg.model.name != "logistic"
                            else (1, cfg.model.input_size))
                        shared_states[state_key] = state_shapes
                except Exception as e:
                    findings.append(_findings_from_exc(
                        "elab-zero1", locus, "state init", e))
                    break
                try:
                    min_size = cfg.optimizer.zero1_min_size \
                        or ZERO1_MIN_SIZE
                    report = Zero1Report(mesh.shape.get("data", 1))
                    opt_sh = zero1_state_shardings(
                        state_shapes.opt_state, mesh, min_size=min_size,
                        report=report)
                    findings.extend(check_spec_tree(
                        state_shapes.opt_state, opt_sh, mesh, locus))
                    if cfg.optimizer.zero1 == "on" and \
                            report.sharded_leaves == 0:
                        findings.append(Finding(
                            "elab-zero1", locus, 0,
                            "optimizer.zero1=on resolves FULLY replicated "
                            f"at {n} data shards "
                            f"(reasons: {report.reasons}) — the promised "
                            "per-replica memory cut vanishes at this "
                            "scale"))
                except Exception as e:
                    findings.append(_findings_from_exc(
                        "elab-zero1", locus, "zero1 sharding rules", e))
                    continue
                # trace the full ZeRO-1 step once per preset that PINS
                # the knob on, on the largest dp layout — the reduce-
                # scatter constraint / sharded update / gather must
                # TRACE at scale, not just spec-check ("auto" presets
                # spec-check only: their step is covered by the 8-device
                # sweep and the "on" presets' traces)
                if cfg.optimizer.zero1 == "on" and not traced \
                        and mesh_cfg.fsdp <= 1:
                    traced = True
                    try:
                        ocfg = copy.deepcopy(cfg)
                        ocfg.optimizer.zero1 = "on"
                        otrainer = Trainer(ocfg, mesh=mesh)
                        batch = _abstract_batch(ocfg,
                                                ocfg.train.batch_size)
                        jax.eval_shape(otrainer._train_step,
                                       state_shapes, batch)
                    except Exception as e:
                        findings.append(_findings_from_exc(
                            "elab-zero1", locus, "zero1 train step", e))
    return findings


def run_elaborate(preset_names: Optional[Sequence[str]] = None,
                  n_devices: int = 8,
                  trace_comm_variants: bool = True) -> List[Finding]:
    """Elaborate the named presets (default: all) across their candidate
    layouts. Call ``apply_virtual_cpu(n_devices)`` BEFORE the jax backend
    initializes (main.py's ``check`` subcommand does)."""
    import jax
    from ..utils.config import PRESETS, get_preset

    findings: List[Finding] = []
    if len(jax.devices()) < n_devices:
        return [Finding(
            "elab-env", "environment", 0,
            f"{len(jax.devices())} devices present, {n_devices} needed — "
            "the check CLI must set up the virtual CPU mesh before jax "
            "initializes")]
    import dataclasses
    seen_forward: set = set()
    precision_seen: set = set()  # bf16-trace dedupe across presets
    for name in (preset_names or sorted(PRESETS)):
        cfg = get_preset(name)
        state_cache: dict = {}
        traced = False
        # optimizer-independent traces (eval step, serve buckets) dedupe
        # across presets sharing the identical forward config — the
        # large-batch optimizer variants of one base preset
        fwd_key = repr((dataclasses.asdict(cfg.model),
                        dataclasses.asdict(cfg.data),
                        dataclasses.asdict(cfg.serve)))
        fwd = fwd_key not in seen_forward
        seen_forward.add(fwd_key)
        for label, mesh_cfg in candidate_layouts(cfg, n_devices):
            # the step graph only changes with PROGRAM-SHAPING axes
            # (pipeline/tensor/expert/seq bake shard_maps into the model);
            # dp vs dp_fsdp re-traces the identical graph, so trace once
            # per distinct program and spec-check every layout
            shaping = max(mesh_cfg.pipeline, 1) > 1 or \
                max(mesh_cfg.tensor, 1) > 1 or \
                max(mesh_cfg.expert, 1) > 1 or \
                max(mesh_cfg.sequence, 1) > 1
            trace = shaping or not traced
            findings.extend(
                elaborate_config(cfg, mesh_cfg, f"{name}@{label}",
                                 trace_steps=trace,
                                 trace_forward=trace and fwd,
                                 trace_comm_variants=trace_comm_variants,
                                 _state_cache=state_cache,
                                 _precision_seen=precision_seen))
            traced = True
    return findings
