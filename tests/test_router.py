"""serve/router.py + serve/fleet.py + serve/loadgen.py — the fleet front
door's tier-1 tables (docs/serving.md fleet section).

The three routing state machines are pure and clock-injected, so every
table here runs with a fake clock and zero sockets: the replica health
SM (warming → ready ⇄ degraded, suspect → dead, drain/readmit), the
canary controller (start → confirm → promote / rollback, bad-step
memory), least-outstanding replica choice, and SLO admission
(shed/degrade). The threaded tests drive a real Router with in-memory
fake replica clients — a dead replica mid-load must cost ZERO client
errors (hedge + retry absorb it), and a seeded p99 regression must roll
the canary back without the bad step ever reaching a baseline replica.
The kill-a-real-process recovery path is the slow tier
(scripts/serve_fleet_smoke.sh and the subprocess test below)."""
import json
import os
import time
from concurrent.futures import Future

import numpy as np
import pytest

from distributed_resnet_tensorflow_tpu.serve.loadgen import (LOAD_SHAPES,
                                                             arrival_times,
                                                             run_open_loop)
from distributed_resnet_tensorflow_tpu.serve.router import (
    CanaryController, ReplicaHealth, RequestShed, RouteError, Router,
    percentile_ms, pick_replica, top1_confidence)
from distributed_resnet_tensorflow_tpu.serve.wire import ReplicaError
from distributed_resnet_tensorflow_tpu.utils.config import RouteConfig


def _rcfg(**kw):
    cfg = RouteConfig()
    for k, v in kw.items():
        setattr(cfg, k, v)
    return cfg


# ---------------------------------------------------------------------------
# registries (the cheap runtime tripwire; the registry-drift lint is the
# static enforcement)
# ---------------------------------------------------------------------------

def test_route_events_and_spans_registered():
    from distributed_resnet_tensorflow_tpu.telemetry.tracer import \
        SPAN_CATALOG
    from distributed_resnet_tensorflow_tpu.utils.metrics import EVENT_SCHEMAS
    for name in ("route", "replica_health", "canary", "shed",
                 "replica_replace"):
        assert name in EVENT_SCHEMAS
    for name in ("route.attempt", "route.health"):
        assert name in SPAN_CATALOG


def test_router_threads_registered_for_lint():
    from distributed_resnet_tensorflow_tpu.analysis.threads import (
        LOOP_ROOTS, THREAD_ROLES)
    for key in ("serve/router.py::Router._dispatch_loop",
                "serve/router.py::Router._worker_loop",
                "serve/router.py::Router._health_loop",
                "serve/wire.py::ReplicaListener._accept_loop",
                "serve/wire.py::ReplicaListener._handle_conn",
                "serve/fleet.py::FleetSupervisor._watch"):
        assert key in THREAD_ROLES
    # the route path is covered by the untimed-blocking-call rule
    assert "serve/router.py::Router._dispatch_loop" in LOOP_ROOTS
    assert "serve/wire.py::ReplicaListener._handle_conn" in LOOP_ROOTS


# ---------------------------------------------------------------------------
# replica health state machine
# ---------------------------------------------------------------------------

def test_health_warming_to_ready_on_probe():
    h = ReplicaHealth(0)
    tr = h.on_success()
    assert (tr.frm, tr.to, tr.reason) == ("warming", "ready", "probe_ok")
    assert h.on_success() is None  # already ready: no edge


def test_health_failures_escalate_suspect_then_dead():
    h = ReplicaHealth(0, suspect_after=2, dead_after=4)
    h.on_success()
    assert h.on_failure() is None                 # 1 failure: still ready
    tr = h.on_failure()
    assert (tr.to, tr.reason) == ("suspect", "failures")
    assert h.on_failure() is None                 # 3: still suspect
    tr = h.on_failure()
    assert (tr.to, tr.reason) == ("dead", "failures")
    assert h.on_failure() is None                 # dead absorbs failures


def test_health_suspect_recovers_on_success():
    h = ReplicaHealth(0, suspect_after=1)
    h.on_success()
    h.on_failure()
    assert h.state == "suspect"
    tr = h.on_success()
    assert (tr.to, tr.reason) == ("ready", "recovered")
    assert h.failures == 0


def test_health_stale_beat_kills_but_warming_exempt():
    h = ReplicaHealth(0, beat_stale_secs=10.0)
    assert h.on_beat(99.0) is None       # warming: supervisor bounds it
    h.on_success()
    assert h.on_beat(9.0) is None
    tr = h.on_beat(11.0)
    assert (tr.to, tr.reason) == ("dead", "beat_stale")
    assert tr.beat_age_secs == 11.0


def test_health_slo_pressure_hysteresis():
    h = ReplicaHealth(0, slo_p99_ms=100.0)
    h.on_success()
    tr = h.on_pressure(150.0)
    assert (tr.to, tr.reason) == ("degraded", "slo_pressure")
    assert h.on_pressure(90.0) is None   # within hysteresis band: stays
    tr = h.on_pressure(70.0)             # < 0.8 × SLO: recovers
    assert (tr.to, tr.reason) == ("ready", "recovered")


def test_health_drain_then_readmit_cycle():
    h = ReplicaHealth(0, suspect_after=1, dead_after=2)
    h.on_success()
    h.on_failure()
    h.on_failure()
    assert h.state == "dead"
    assert h.drain().to == "draining"
    assert h.on_failure() is None        # draining absorbs failures
    tr = h.readmit()
    assert (tr.to, tr.reason) == ("warming", "readmit")
    assert h.failures == 0 and h.beat_age is None
    assert h.on_success().to == "ready"


# ---------------------------------------------------------------------------
# replica choice + small helpers
# ---------------------------------------------------------------------------

def _fleet_health(states):
    out = {}
    for rid, state in enumerate(states):
        h = ReplicaHealth(rid)
        h.state = state
        out[rid] = h
    return out


def test_pick_replica_least_outstanding():
    health = _fleet_health(["ready", "ready", "ready"])
    assert pick_replica(health, {0: 3, 1: 1, 2: 2}) == 1
    assert pick_replica(health, {0: 1, 1: 1, 2: 2}) == 0  # tie → low rid


def test_pick_replica_exclude_is_preference_not_veto():
    health = _fleet_health(["ready", "ready", "dead"])
    assert pick_replica(health, {0: 0, 1: 5}, exclude=(0,)) == 1
    # every routable replica already tried: still goes somewhere
    assert pick_replica(health, {0: 0, 1: 5}, exclude=(0, 1)) == 0


def test_pick_replica_fallback_and_exhaustion():
    health = _fleet_health(["warming", "dead", "draining"])
    assert pick_replica(health, {}) == 0      # warming is the fallback
    health = _fleet_health(["dead", "draining"])
    assert pick_replica(health, {}) is None


def test_percentile_and_confidence_helpers():
    assert percentile_ms([]) is None
    assert percentile_ms([5.0]) == 5.0
    assert percentile_ms(list(range(1, 101)), q=99.0) == 99
    assert percentile_ms([3.0, 1.0, 2.0], q=50.0) == 2.0
    assert top1_confidence(np.array([0.0, 0.0])) == pytest.approx(0.5)
    assert top1_confidence(np.array([100.0, 0.0])) == pytest.approx(1.0)
    assert top1_confidence(np.array([np.nan, 1.0])) == 0.0  # poisoned
    assert top1_confidence(np.array([])) == 0.0


# ---------------------------------------------------------------------------
# canary controller (fake clock throughout)
# ---------------------------------------------------------------------------

def _canary_cfg(**kw):
    kw.setdefault("canary_fraction", 0.25)   # ceil(0.25 × 3) = 1 canary
    kw.setdefault("canary_window_secs", 10.0)
    kw.setdefault("canary_min_samples", 2)
    kw.setdefault("canary_confirm_secs", 30.0)
    return _rcfg(**kw)


def test_canary_start_pins_fraction_and_baseline():
    c = CanaryController(_canary_cfg(), initial_step=2)
    rows, pins = c.observe_commit(4, healthy=[0, 1, 2], all_ids=[0, 1, 2],
                                  now=0.0)
    assert rows[0]["action"] == "start" and rows[0]["step"] == 4
    assert rows[0]["canary"] == [0]      # healthy-sorted prefix
    # canary pinned forward, the rest re-pinned to the incumbent
    assert sorted(pins) == [(0, 4), (1, 2), (2, 2)]
    # a second commit observation while active is a no-op
    assert c.observe_commit(5, [0, 1, 2], [0, 1, 2], 1.0) == ([], [])


def test_canary_always_keeps_a_control_arm():
    # even an absurd fraction leaves one baseline replica to compare
    # against — an all-canary rollout is just an ungated swap
    c = CanaryController(_canary_cfg(canary_fraction=1.0), initial_step=2)
    rows, pins = c.observe_commit(4, [0, 1, 2], [0, 1, 2], now=0.0)
    assert rows[0]["canary"] == [0, 1]
    assert (2, 2) in pins


def test_canary_promote_after_clean_window():
    c = CanaryController(_canary_cfg(), initial_step=2)
    _, pins = c.observe_commit(4, [0, 1, 2], [0, 1, 2], now=0.0)
    canary = {r for r, s in pins if s == 4}
    for rid in canary:
        c.observe_completion(rid, 4, 10.0, 0.9)
        c.observe_completion(rid, 4, 12.0, 0.9)
    for rid in {0, 1, 2} - canary:
        c.observe_completion(rid, 2, 11.0, 0.9)
        c.observe_completion(rid, 2, 9.0, 0.9)
    assert c.tick(5.0) == ([], [])       # window not elapsed
    rows, pins = c.tick(10.5)
    assert rows[0]["action"] == "promote" and not rows[0]["rollback"]
    assert c.fleet_step == 4 and c.active is None
    assert sorted(pins) == [(0, 4), (1, 4), (2, 4)]  # fleet-wide


def test_canary_p99_regression_rolls_back_and_remembers():
    c = CanaryController(_canary_cfg(canary_p99_ratio=2.0), initial_step=2)
    _, pins = c.observe_commit(4, [0, 1, 2], [0, 1, 2], now=0.0)
    canary = {r for r, s in pins if s == 4}
    for rid in canary:
        for _ in range(3):
            c.observe_completion(rid, 4, 500.0, 0.9)   # regressed arm
    for rid in {0, 1, 2} - canary:
        for _ in range(3):
            c.observe_completion(rid, 2, 10.0, 0.9)
    rows, pins = c.tick(10.5)
    assert rows[0]["action"] == "rollback" and rows[0]["rollback"]
    assert rows[0]["reason"] == "p99_regression"
    assert rows[0]["p99_canary_ms"] >= rows[0]["p99_base_ms"]
    assert c.fleet_step == 2 and 4 in c.bad_steps
    assert sorted(pins) == [(r, 2) for r in sorted(canary)]  # back to 2
    # a bad step never restarts a canary
    assert c.observe_commit(4, [0, 1, 2], [0, 1, 2], 20.0) == ([], [])


def test_canary_confidence_collapse_rolls_back():
    c = CanaryController(_canary_cfg(canary_conf_drop=0.2), initial_step=2)
    _, pins = c.observe_commit(4, [0, 1, 2], [0, 1, 2], now=0.0)
    canary = {r for r, s in pins if s == 4}
    for rid in canary:
        for _ in range(3):
            c.observe_completion(rid, 4, 10.0, 0.3)    # garbage checkpoint
    for rid in {0, 1, 2} - canary:
        for _ in range(3):
            c.observe_completion(rid, 2, 10.0, 0.9)
    rows, _ = c.tick(10.5)
    assert rows[0]["reason"] == "confidence_regression"
    assert rows[0]["rollback"] and 4 in c.bad_steps


def test_canary_no_confirm_rolls_back():
    # the canary replica never served the new step (gate held, replica
    # wedged, checkpoint unreadable): after confirm_secs the step is
    # condemned without latency evidence
    c = CanaryController(_canary_cfg(canary_confirm_secs=30.0),
                         initial_step=2)
    c.observe_commit(4, [0, 1, 2], [0, 1, 2], now=0.0)
    assert c.tick(29.0) == ([], [])
    rows, _ = c.tick(31.0)
    assert rows[0]["reason"] == "no_confirm" and rows[0]["rollback"]


def test_canary_ping_observation_confirms_but_never_samples():
    # a canary starved of regular traffic confirms its swap through the
    # health ping's pong step (observe_step); the verdict's latency and
    # confidence evidence still comes only from real completions, so a
    # ping-confirmed-but-unsampled canary rides the starved-promote
    # grace, never a latency comparison against nothing
    cfg = _canary_cfg(canary_fraction=1.0, canary_min_samples=2,
                      canary_window_secs=10.0, canary_confirm_secs=30.0)
    c = CanaryController(cfg, initial_step=2)
    _, pins = c.observe_commit(4, [0, 1, 2], [0, 1, 2], now=0.0)
    canary = sorted(r for r, s in pins if s == 4)
    assert c.unconfirmed == canary
    # traffic concentrates on the first canary; the second only pings
    c.observe_completion(canary[0], 4, 10.0, 0.9)
    c.observe_completion(canary[0], 4, 12.0, 0.9)
    assert c.unconfirmed == canary[1:]
    c.observe_step(canary[1], 2)          # stale pong: not yet swapped
    assert c.unconfirmed == canary[1:]
    c.observe_step(canary[1], 4)          # pong at the canary step
    assert c.unconfirmed == []
    assert len(c.active.c_lat) == 2       # pings contributed no samples
    # control arm never sampled → starved-promote grace, not no_confirm
    assert c.tick(31.0) == ([], [])
    rows, _ = c.tick(41.0)
    assert rows[0]["action"] == "promote" and c.fleet_step == 4


def test_canary_starved_promotes_after_grace():
    # confirmed but traffic died before min_samples accumulated: promote
    # after window + confirm grace instead of wedging forever
    cfg = _canary_cfg(canary_min_samples=50, canary_window_secs=10.0,
                      canary_confirm_secs=30.0)
    c = CanaryController(cfg, initial_step=2)
    _, pins = c.observe_commit(4, [0, 1, 2], [0, 1, 2], now=0.0)
    for rid, s in pins:
        if s == 4:
            c.observe_completion(rid, 4, 10.0, 0.9)
    assert c.tick(15.0) == ([], [])
    rows, _ = c.tick(41.0)
    assert rows[0]["action"] == "promote" and c.fleet_step == 4


def test_canary_single_replica_promotes_directly():
    c = CanaryController(_canary_cfg(), initial_step=2)
    rows, pins = c.observe_commit(4, [0], [0], now=0.0)
    assert rows[0]["action"] == "promote"
    assert rows[0]["reason"] == "single_replica"
    assert pins == [(0, 4)] and c.fleet_step == 4 and c.active is None


# ---------------------------------------------------------------------------
# admission (no threads: submit() decides under the lock)
# ---------------------------------------------------------------------------

def _ready_router(cfg, nreplicas=2):
    clients = {rid: object() for rid in range(nreplicas)}
    router = Router(cfg, clients, image_shape=(4,), image_dtype=np.float32)
    for h in router.health.values():
        h.on_success()
    return router


def test_admission_sheds_past_queue_threshold():
    router = _ready_router(_rcfg(shed_queue_ms=100.0))
    router._ewma_ms = 50.0
    router.outstanding[0] = 4            # est: 4 × 50 / 2 = 100ms ≥ 100
    fut = router.submit(np.zeros(4, np.float32))
    assert isinstance(fut.exception(timeout=1), RequestShed)
    assert router.shed == 1 and router.requests == 0


def test_admission_degrades_unpinned_traffic_first():
    router = _ready_router(_rcfg(shed_queue_ms=10_000.0,
                                 degrade_queue_ms=50.0,
                                 degrade_variant="int8"))
    router._ewma_ms = 50.0
    router.outstanding[0] = 4            # est 100ms: past degrade only
    router.submit(np.zeros(4, np.float32))
    assert router.degraded == 1
    assert router._intake.get_nowait().variant == "int8"
    # a request that PINNED its variant is never rewritten
    router.submit(np.zeros(4, np.float32), variant="f32")
    assert router.degraded == 1
    assert router._intake.get_nowait().variant == "f32"


def test_admission_accepts_under_threshold():
    router = _ready_router(_rcfg(shed_queue_ms=100.0,
                                 degrade_queue_ms=50.0,
                                 degrade_variant="int8"))
    router._ewma_ms = 10.0
    fut = router.submit(np.zeros(4, np.float32))
    assert router.requests == 1 and router.shed == 0
    assert router.degraded == 0
    assert not fut.done()


# ---------------------------------------------------------------------------
# threaded router against in-memory fake replicas
# ---------------------------------------------------------------------------

class _FakeReplica:
    """In-memory stand-in for wire.TcpReplicaClient: request/ping/reset/
    close, a settable step (the pin/swap stand-in), a settable delay and
    a kill switch."""

    def __init__(self, step=2, delay=0.0, dead=False):
        self.step = step
        self.delay = delay
        self.dead = dead
        self.requests = 0

    def request(self, image, variant, timeout_secs):
        if self.dead:
            raise ReplicaError("connection refused")
        if self.delay:
            time.sleep(self.delay)
        self.requests += 1
        return np.array([4.0, 0.0, 0.0, 0.0], np.float32), self.step

    def ping(self, timeout_secs=2.0):
        if self.dead:
            raise ReplicaError("connection refused")
        return {"pong": True, "step": self.step, "outstanding": 0}

    def reset(self):
        pass

    def close(self):
        pass


def _threaded_cfg(**kw):
    kw.setdefault("workers", 2)
    kw.setdefault("health_interval_secs", 0.05)
    kw.setdefault("hedge_ms", 60)
    kw.setdefault("attempt_timeout_ms", 1000)
    kw.setdefault("request_timeout_ms", 4000)
    kw.setdefault("suspect_after_failures", 1)
    kw.setdefault("dead_after_failures", 3)
    kw.setdefault("row_interval_secs", 3600.0)
    return _rcfg(**kw)


def test_router_dead_replica_costs_zero_client_errors():
    # small service time so outstanding piles up and the least-
    # outstanding policy actually spreads attempts onto the dead replica
    fakes = {0: _FakeReplica(delay=0.005), 1: _FakeReplica(delay=0.005),
             2: _FakeReplica(dead=True)}
    router = Router(_threaded_cfg(), fakes, (4,), np.float32).start()
    try:
        futs = [router.submit(np.zeros(4, np.float32)) for _ in range(30)]
        for fut in futs:
            row, step = fut.result(timeout=10.0)
            assert step == 2
        deadline = time.monotonic() + 5.0   # health pings finish the
        while (router.health_state(2) != "dead"      # condemnation
               and time.monotonic() < deadline):
            time.sleep(0.02)
    finally:
        router.close()
    rep = router.report()
    assert rep["completed"] == 30 and rep["errors"] == 0
    assert rep["retries"] + rep["hedges"] >= 1   # the dead replica's
    assert router.health_state(2) == "dead"      # attempts were absorbed
    assert fakes[0].requests + fakes[1].requests >= 30


def test_router_hedge_rescues_a_stalled_attempt():
    # replica 0 answers but far slower than hedge_ms: the hedge lands on
    # replica 1 and resolves the request first
    fakes = {0: _FakeReplica(delay=1.0), 1: _FakeReplica()}
    router = Router(_threaded_cfg(hedge_ms=50, workers=2), fakes,
                    (4,), np.float32).start()
    try:
        t0 = time.monotonic()
        row, step = router.submit(np.zeros(4, np.float32)) \
            .result(timeout=10.0)
        wall = time.monotonic() - t0
    finally:
        router.close()
    assert wall < 1.0                    # did not wait out the slow arm
    assert router.report()["hedges"] >= 1


def test_router_canary_promote_end_to_end_in_memory():
    # pins executed by flipping the fake's step — the swapper stand-in;
    # small bursts of concurrent traffic feed BOTH canary arms
    fakes = {r: _FakeReplica(step=2, delay=0.002) for r in range(3)}

    def pin(rid, step):
        fakes[rid].step = step

    cfg = _threaded_cfg(canary_fraction=0.25, canary_window_secs=0.4,
                        canary_min_samples=2, canary_confirm_secs=5.0)
    router = Router(cfg, fakes, (4,), np.float32,
                    committed_steps_fn=lambda: [2, 4], pin_fn=pin,
                    initial_step=2).start()
    try:
        deadline = time.monotonic() + 15.0
        while (router.canary.fleet_step != 4
               and time.monotonic() < deadline):
            futs = [router.submit(np.zeros(4, np.float32))
                    for _ in range(6)]
            for fut in futs:
                fut.result(timeout=5.0)
            time.sleep(0.01)
    finally:
        router.close()
    assert router.canary.fleet_step == 4
    assert all(f.step == 4 for f in fakes.values())  # promoted fleet-wide
    assert router.report()["errors"] == 0


def test_router_canary_rollback_never_reaches_baseline():
    fakes = {r: _FakeReplica(step=2) for r in range(3)}

    def pin(rid, step):
        # the p99-regressing checkpoint: any replica pinned to step 4
        # becomes slow (DRT_FAULT_SERVE_SLOW_MS=…@4 in the real smoke)
        fakes[rid].step = step
        fakes[rid].delay = 0.2 if step == 4 else 0.0

    # enough workers that the slow canary attempt cannot head-of-line
    # block the control arm (which would inflate baseline p99 and mask
    # the regression)
    cfg = _threaded_cfg(canary_fraction=0.25, canary_window_secs=0.5,
                        canary_min_samples=3, canary_confirm_secs=8.0,
                        canary_p99_ratio=2.0, hedge_ms=5000, workers=8)
    router = Router(cfg, fakes, (4,), np.float32,
                    committed_steps_fn=lambda: [2, 4], pin_fn=pin,
                    initial_step=2).start()
    try:
        deadline = time.monotonic() + 20.0
        while (4 not in router.canary.bad_steps
               and router.canary.fleet_step != 4   # promote = failure,
               and time.monotonic() < deadline):   # fail fast
            futs = [router.submit(np.zeros(4, np.float32))
                    for _ in range(6)]
            for fut in futs:
                fut.result(timeout=5.0)
            time.sleep(0.01)
    finally:
        router.close()
    assert 4 in router.canary.bad_steps
    assert router.canary.fleet_step == 2
    # rollback re-pinned every canary to the incumbent; with the bad
    # step remembered, NO replica ends pinned at 4
    assert all(f.step == 2 for f in fakes.values())


def test_router_close_fails_stuck_requests():
    fakes = {0: _FakeReplica(dead=True)}
    router = Router(_threaded_cfg(request_timeout_ms=60_000,
                                  attempt_timeout_ms=60_000), fakes,
                    (4,), np.float32).start()
    fut = router.submit(np.zeros(4, np.float32))
    time.sleep(0.1)
    router.close()
    with pytest.raises(RouteError):
        fut.result(timeout=1.0)


# ---------------------------------------------------------------------------
# load shapes (coordinated-omission-free arrival schedules)
# ---------------------------------------------------------------------------

def test_arrival_times_monotone_and_bounded():
    for shape in LOAD_SHAPES:
        t = arrival_times(shape, qps=50.0, duration_secs=4.0)
        assert np.all(np.diff(t) >= -1e-9), shape
        assert t[0] >= 0.0 and t[-1] <= 4.0 + 1e-6, shape
        # total offered mass stays the same order as qps × duration
        assert 0.5 * 200 <= len(t) <= 2.0 * 200, (shape, len(t))


def test_arrival_times_steady_is_uniform():
    t = arrival_times("steady", qps=100.0, duration_secs=2.0)
    assert len(t) == 200
    np.testing.assert_allclose(np.diff(t), 0.01, atol=1e-3)


def test_arrival_times_spike_concentrates_midwindow():
    t = arrival_times("spike", qps=100.0, duration_secs=10.0)
    mid = np.sum((t >= 4.5) & (t < 5.5))
    edge = np.sum(t < 1.0)
    assert mid > 3.0 * edge              # 4× rate across the middle tenth


def test_arrival_times_rejects_unknown_shape():
    with pytest.raises(ValueError):
        arrival_times("sawtooth", 10.0, 1.0)


class _InstantServer:
    image_shape = (2, 2, 3)
    image_dtype = np.dtype(np.float32)

    def __init__(self):
        self.submitted = 0

    def submit(self, image, variant=None):
        self.submitted += 1
        fut = Future()
        fut.set_result((np.zeros(4, np.float32), 0))
        return fut


def test_run_open_loop_reports_shape():
    server = _InstantServer()
    rep = run_open_loop(server, qps=200.0, duration_secs=0.25,
                        shape="burst")
    assert rep["shape"] == "burst"
    assert rep["offered"] == server.submitted
    assert rep["completed"] == rep["offered"] and rep["failed"] == 0


# ---------------------------------------------------------------------------
# fault knobs + fleet plumbing (pure FS)
# ---------------------------------------------------------------------------

def test_serve_faults_env_parsing_and_scoping():
    from distributed_resnet_tensorflow_tpu.resilience.faultinject import \
        ServeFaults
    env = {"DRT_FAULT_SERVE_WEDGE_AT_BATCH": "1:5",
           "DRT_FAULT_SERVE_SLOW_MS": "250@4"}
    f0 = ServeFaults.from_env(0, env)
    assert f0.wedge_at_batch is None          # wedge scoped to replica 1
    assert (f0.slow_ms, f0.slow_from_step) == (250.0, 4)
    f1 = ServeFaults.from_env(1, env)
    assert f1.wedge_at_batch == 5 and f1.armed
    assert ServeFaults.from_env(0, {}).armed is False


def test_serve_faults_slow_gates_on_serving_step(monkeypatch):
    from distributed_resnet_tensorflow_tpu.resilience import faultinject
    naps = []
    monkeypatch.setattr(faultinject.time, "sleep", naps.append)
    f = faultinject.ServeFaults(slow_ms=250.0, slow_from_step=4)
    f.maybe_fire(1, serving_step=2)           # below the poisoned step
    assert naps == []
    f.maybe_fire(2, serving_step=4)
    assert naps == [0.25]
    # @0 means "always" but never fires on fresh-init (-1) serving
    g = faultinject.ServeFaults(slow_ms=100.0, slow_from_step=0)
    g.maybe_fire(1, serving_step=-1)
    assert naps == [0.25]


def test_write_pin_atomic_and_gate_holds_without_pin(tmp_path):
    from distributed_resnet_tensorflow_tpu.serve.fleet import (pin_path,
                                                               write_pin)
    from distributed_resnet_tensorflow_tpu.serve.swap import \
        CheckpointSwapper
    write_pin(str(tmp_path), 0, 4)
    path = pin_path(str(tmp_path), 0)
    assert json.load(open(path)) == {"target_step": 4}
    assert not os.path.exists(path + ".tmp")
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    gate = str(tmp_path / "serve-r1" / "SWAP_CONTROL.json")
    swapper = CheckpointSwapper(str(ckpt), gate_path=gate)
    # armed gate with NO pin: hold — never chase the newest commit (the
    # unvalidated-checkpoint leak the canary exists to prevent)
    (ckpt / "7").mkdir()
    assert swapper.poll_once() is None
    # pinned ahead of the directory (pin raced the commit): keep polling
    write_pin(str(tmp_path), 1, 9)
    assert swapper.poll_once() is None
    assert swapper._gate_applied is None


def test_fleet_replica_dir_layout_matches_server():
    # fleet.replica_dir and server.serve_stream_dir must agree — the pin
    # the supervisor writes is the file the replica's swapper reads
    from distributed_resnet_tensorflow_tpu.serve.fleet import (pin_path,
                                                               replica_dir)
    assert replica_dir("/r", 3) == "/r/serve-r3"
    assert pin_path("/r", 3) == "/r/serve-r3/SWAP_CONTROL.json"


# ---------------------------------------------------------------------------
# slow tier: a REAL fleet (subprocess replicas) killed and recovered
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_fleet_kill_and_recover_subprocess(tmp_path):
    """SIGKILL a real serving replica process mid-fleet: the router
    condemns it, the supervisor replaces it (kill → respawn → warm →
    readmit rows), and requests keep succeeding throughout with zero
    client-visible errors. The full chaos story (canary rollback on a
    seeded p99 regression, baseline purity) is
    scripts/serve_fleet_smoke.sh."""
    import signal

    from distributed_resnet_tensorflow_tpu.serve.fleet import FleetSupervisor
    from distributed_resnet_tensorflow_tpu.serve.server import \
        serve_image_spec
    from distributed_resnet_tensorflow_tpu.serve.wire import TcpReplicaClient
    from distributed_resnet_tensorflow_tpu.utils.config import get_preset

    cfg = get_preset("smoke")
    cfg.model.resnet_size = 8
    cfg.model.compute_dtype = "float32"
    cfg.data.image_size = 8
    cfg.train.batch_size = 16
    cfg.data.eval_batch_size = 16
    cfg.mesh.data = 1
    cfg.log_root = str(tmp_path)
    cfg.checkpoint.directory = os.path.join(str(tmp_path), "ckpt")
    cfg.serve.max_queue_delay_ms = 5.0
    cfg.route.replicas = 2
    cfg.route.health_interval_secs = 0.3
    cfg.route.watch_interval_secs = 0.3
    cfg.route.replica_grace_secs = 2.0
    cfg.route.suspect_after_failures = 1
    cfg.route.dead_after_failures = 2

    fleet = FleetSupervisor(cfg)
    router = None
    try:
        fleet.start()  # no checkpoint: replicas serve fresh-init params
        clients = {rid: TcpReplicaClient("127.0.0.1", port)
                   for rid, port in fleet.ports.items()}
        shape, dtype = serve_image_spec(cfg)
        router = Router(cfg.route, clients, shape, dtype,
                        beats_dir=fleet.beats_dir,
                        initial_step=fleet.pinned_step).start()
        fleet.attach_router(router)
        fleet.start_watch()
        img = np.zeros(shape, dtype)
        for _ in range(4):
            router.submit(img).result(timeout=30.0)

        victim_pid = fleet.procs[0].pid
        os.kill(victim_pid, signal.SIGKILL)
        # traffic keeps flowing while the watchdog replaces replica 0
        deadline = time.monotonic() + 180.0
        while time.monotonic() < deadline:
            router.submit(img).result(timeout=30.0)
            if (fleet.replaces >= 1
                    and router.health_state(0) in ("ready", "degraded")):
                break
            time.sleep(0.2)
        assert fleet.replaces >= 1, "watchdog never replaced the replica"
        assert router.health_state(0) in ("ready", "degraded"), \
            "killed replica never readmitted"
        assert fleet.procs[0].pid != victim_pid
        # the replacement serves: force a request through replica 0
        pong = clients[0].ping(timeout_secs=5.0)
        assert pong.get("pong") is True
        assert router.report()["errors"] == 0
    finally:
        if router is not None:
            router.close()
        fleet.stop()
