"""exit-code-contract: process exit codes come from the declared registry.

Launchers key requeue-vs-fail decisions off exit codes (docs/resilience.md:
0 = done, 75 = resumable/requeue, 1 = real failure, 130 = operator ^C). A
stray ``sys.exit(3)`` silently breaks that protocol — SLURM would treat a
resumable condition as a hard failure or vice versa. This rule flags any
integer literal outside ``resilience.EXIT_CONTRACT`` that becomes a
process exit code by any of three routes:

  * a direct ``sys.exit(<n>)`` / ``os._exit(<n>)`` call;
  * a ``raise SystemExit(<n>)`` (the same call in exception clothing);
  * an **exit-flow function**: when ``sys.exit(f(...))`` appears, ``f``'s
    returned literals ARE exit codes — both ``return <n>`` and
    ``name = <n>`` where ``name`` is returned (the launch.py
    ``rc = 130; ...; return rc`` shape that hid from the original rule),
    followed one call level deep (``return g(...)`` inside ``f`` makes
    ``g`` exit-flow too, same module only).

Named constants (RESUMABLE_EXIT_CODE, INTERRUPT_EXIT_CODE, ...) and
computed codes (exit-code pass-through in launchers) are accepted — the
contract is about new literals.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..report import Finding

RULE_NAME = "exit-code-contract"
DOC = __doc__


def _contract_codes() -> set:
    from ...resilience import EXIT_CONTRACT
    return set(EXIT_CONTRACT)


def _is_exit_call(node: ast.Call) -> bool:
    fn = node.func
    if isinstance(fn, ast.Attribute) and fn.attr in ("exit", "_exit"):
        base = fn.value
        return isinstance(base, ast.Name) and base.id in ("sys", "os")
    return False


def _is_system_exit_raise(node: ast.Raise) -> Optional[ast.Call]:
    exc = node.exc
    if isinstance(exc, ast.Call) and isinstance(exc.func, ast.Name) \
            and exc.func.id == "SystemExit":
        return exc
    return None


def _int_literal(node: ast.AST) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    return None


def _called_name(node: ast.AST) -> Optional[str]:
    """Bare function name of a same-module call: ``f(...)`` -> "f"."""
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        return node.func.id
    return None


def _walk_same_scope(fn: ast.FunctionDef) -> Iterable[ast.AST]:
    """Walk ``fn``'s body WITHOUT descending into nested function
    definitions (a closure's returns are not the enclosing function's
    exit codes)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _exit_flow_literals(fn: ast.FunctionDef
                        ) -> Tuple[List[Tuple[int, int]], Set[str]]:
    """(literal exit codes flowing out of ``fn`` as ``(code, lineno)``,
    names of same-module functions whose return value ``fn`` returns).

    A literal flows out via ``return <n>`` directly, or via
    ``name = <n>`` when some ``return name`` exists in the function —
    an over-approximation (the assignment might be dead by the return)
    that is exactly right for a lint: an undeclared literal sitting in
    an exit-code variable is the bug whether or not today's control
    flow reaches it.
    """
    body = list(_walk_same_scope(fn))   # nested defs keep their own story
    returned_names: Set[str] = set()
    callees: Set[str] = set()
    for node in body:
        if isinstance(node, ast.Return) and node.value is not None:
            if isinstance(node.value, ast.Name):
                returned_names.add(node.value.id)
            name = _called_name(node.value)
            if name is not None:
                callees.add(name)
    out: List[Tuple[int, int]] = []
    for node in body:
        if isinstance(node, ast.Return) and node.value is not None:
            lit = _int_literal(node.value)
            if lit is not None:
                out.append((lit, node.lineno))
        elif isinstance(node, ast.Assign):
            lit = _int_literal(node.value)
            if lit is None:
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id in returned_names:
                    out.append((lit, node.lineno))
    return out, callees


def check(ctx) -> Iterable[Finding]:
    codes = _contract_codes()
    for sf in ctx.all_python():
        if sf.tree is None:
            continue
        funcs: Dict[str, ast.FunctionDef] = {
            n.name: n for n in ast.walk(sf.tree)
            if isinstance(n, ast.FunctionDef)}
        exit_args: List[ast.AST] = []
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call) and _is_exit_call(node) \
                    and node.args:
                exit_args.append(node.args[0])
            elif isinstance(node, ast.Raise):
                exc = _is_system_exit_raise(node)
                if exc is not None and exc.args:
                    exit_args.append(exc.args[0])

        # (a) direct literals handed to sys.exit/os._exit/SystemExit
        for arg in exit_args:
            lit = _int_literal(arg)
            if lit is not None and lit not in codes:
                yield Finding(
                    RULE_NAME, sf.rel, arg.lineno,
                    f"exit code {lit} is not in the declared contract "
                    f"{sorted(codes)} (resilience.EXIT_CONTRACT) — "
                    "launchers cannot classify it; declare it or reuse "
                    "an existing code")

        # (b) literals flowing out of exit-flow functions:
        # sys.exit(f(...)) makes every literal f returns an exit code
        roots = {name for arg in exit_args
                 if (name := _called_name(arg)) is not None}
        seen: Set[str] = set()
        frontier = [n for n in roots if n in funcs]
        while frontier:
            name = frontier.pop()
            if name in seen:
                continue
            seen.add(name)
            literals, callees = _exit_flow_literals(funcs[name])
            for lit, lineno in literals:
                if lit not in codes:
                    yield Finding(
                        RULE_NAME, sf.rel, lineno,
                        f"exit code {lit} flows out of {name}() into a "
                        f"sys.exit(...) but is not in the declared "
                        f"contract {sorted(codes)} "
                        "(resilience.EXIT_CONTRACT) — launchers cannot "
                        "classify it; declare it or reuse an existing "
                        "code")
            frontier += [c for c in callees if c in funcs and c not in seen]
