"""serve/ — AOT-compiled batched inference with hot checkpoint swap,
and the fleet front door above it.

The serving path the ROADMAP north-star requires and the reference never
had (its pipeline ended at the checkpoint): ``main.py serve`` turns a
training run's committed checkpoints into live low-latency capacity, and
``main.py route`` (serve/router.py + serve/fleet.py) turns N such
replicas into a service — health-routed dispatch, hedged retries,
watchdog replace, canary rollout with auto-rollback, SLO-aware
shedding. docs/serving.md is the manual; tests/test_serve.py,
tests/test_router.py and scripts/serve{,_fleet}_smoke.sh exercise it on
CPU.

Import layering: ``router``/``wire``/``fleet``/``loadgen`` are
numpy-and-sockets only (no jax) so the front door and its tier-1 tables
never pay — or depend on — a device runtime; they are therefore NOT
re-exported here (this package ``__init__`` pulls in the jax-backed
server).
"""
from .batcher import DynamicBatcher  # noqa: F401
from .compile_cache import (ServeCompileCache, bucket_sizes,  # noqa: F401
                            pick_bucket)
from .loadgen import run_open_loop, synthetic_requests  # noqa: F401
from .server import (InferenceServer, serve_image_spec,  # noqa: F401
                     serve_stream_dir)
from .swap import CheckpointSwapper, PendingSwap  # noqa: F401
