"""ImageNet input pipeline over TFRecord shards.

Parity with the reference's duplicated input_fn/record_parser
(reference resnet_imagenet_main.py:103-183, resnet_imagenet_eval.py:70-150):
  * shard naming train-{i:05d}-of-01024 / validation-{i:05d}-of-00128
    (reference :106-112),
  * Example parsing of image/encoded + image/class/label
    (reference record_parser:115-136; bbox features parsed but unused by the
    crop the reference actually applied — VGG preprocessing ignores them),
  * file-level shuffle each epoch + sample-level shuffle buffer
    (reference :98-99,163,174),
  * VGG preprocess train/eval (preprocessing.py), labels already 1-based
    with 0 = background ⇒ num_classes=1001 dense ids (the reference one-hotted
    to 1001, resnet_imagenet_main.py:151-155; we keep dense ids and one-hot
    in the loss).

Multi-process sharding: each process reads files[shard_index::num_shards] —
disjoint by construction (the reference's Horovod path read everything
everywhere, SURVEY.md §3.2).

Parallelism: a pool of decode threads feeding a bounded queue — host-side
successor of tf.data's num_parallel_calls=5 map (reference :166-168). Each
worker decodes via PIL (DCT-scaled draft) or, with ``use_native`` and a
libjpeg-enabled build, the fused C++ transform (native/dataloader.cc —
scaled decode + resize/crop/flip in one GIL-free call, measured 1.6× the
PIL rate per core); the C++ record prefetcher feeds the bytes.
"""
from __future__ import annotations

import glob
import os
import queue as queue_mod
import threading
import time
from typing import Dict, Iterator, List, Optional

import numpy as np

from .tfrecord import parse_example, read_tfrecords

TRAIN_SHARDS = 1024   # reference resnet_imagenet_main.py:106
VAL_SHARDS = 128      # reference resnet_imagenet_main.py:111
SHUFFLE_BUFFER = 1500  # reference resnet_imagenet_main.py:174


def dataset_filenames(data_dir: str, mode: str) -> List[str]:
    """Accept both the exact reference naming and any train-*/validation-*
    TFRecord layout present in data_dir."""
    prefix = "train" if mode == "train" else "validation"
    files = sorted(glob.glob(os.path.join(data_dir, f"{prefix}-*")))
    if not files:
        raise FileNotFoundError(
            f"no {prefix}-* TFRecord shards under {data_dir!r}")
    return files


def _example_to_sample(features: Dict) -> Optional[tuple]:
    enc = features.get("image/encoded")
    label = features.get("image/class/label")
    if not enc or label is None or len(label) == 0:
        return None
    return bytes(enc[0]), int(label[0])


def imagenet_iterator(data_dir: str, batch_size: int, mode: str,
                      image_size: int = 224, seed: int = 0,
                      shard_index: int = 0, num_shards: int = 1,
                      num_decode_threads: int = 4,
                      prefetch_batches: int = 2,
                      shuffle_buffer: int = SHUFFLE_BUFFER,
                      use_native: bool = False,
                      device_standardize: bool = False,
                      device_flip: bool = False,
                      decode_processes: int = 0,
                      deterministic: bool = False,
                      max_corrupt_records: int = 0,
                      verify_crc: bool = False,
                      ) -> Iterator[Dict[str, np.ndarray]]:
    """``device_standardize``: batches stay uint8 (crop done, VGG
    mean-subtract deferred to ops/augment inside the jitted step or the
    staged-unpack program) — 4× smaller host→device transfers and no host
    float pass. Both modes use the fused DCT-scaled decode
    (preprocessing.decode_and_resize).

    ``device_flip``: the device augmentation owns the horizontal flip
    (ops/augment.imagenet_train_augment draws one per appearance — fresh
    per echo, data/echo.py), so the host decode draws its flip (the RNG
    stream contract keeps the draw order: side, top, left, flip) but does
    NOT apply it. Train mode only; without it device-augmented batches
    would be flipped twice.

    ``decode_processes`` > 0 replaces the decode THREAD pool with worker
    PROCESSES (fork): full GIL independence for the decode stage, at the
    price of pickling jpeg bytes in and decoded crops out. The thread pool
    already scales while decoders hold the GIL released (PIL and the
    native transform both release it); the process pool is the escape
    hatch for hosts where the python-side feeder contends
    (tools/input_scaling.py measures both, docs/input_scaling_r4.json).
    Workers start via forkserver/spawn (fork from a threaded parent can
    inherit held locks), so the calling program needs the standard
    ``if __name__ == "__main__"`` guard multiprocessing requires.

    ``deterministic``: two iterators built with identical arguments yield
    byte-identical batch streams regardless of worker scheduling. Needed
    when several processes feed the SAME replicated batch slice (a
    non-batch mesh axis spans processes — parallel/mesh.py
    process_batch_slice): without it, decode workers emit in completion
    order and draw augmentations from per-worker RNG streams, so replica
    processes silently assemble different batches. Mechanism: samples are
    sequence-tagged at the feeder, each item's augmentation RNG derives
    from (seed, sequence) instead of the worker's stream, and the
    consumer reorders by sequence; the native record PREFETCHER is
    bypassed (its file interleave is thread-timing-dependent) while the
    native JPEG decode stays usable.
    """
    files = dataset_filenames(data_dir, mode)
    if num_shards > 1:
        total_files = len(files)
        files = files[shard_index::num_shards]
        if not files:
            raise ValueError(f"process {shard_index}: no files to read "
                             f"({num_shards} shards over {total_files} files)")
    is_train = mode == "train"
    rng = np.random.RandomState(seed + shard_index)

    # native C++ multithreaded record reader. Train: file order is
    # thread-interleaved → extra shuffle for free. Eval (round 4): also
    # allowed — aggregate eval metrics are order-independent and the
    # prefetcher delivers every record exactly once, so only the
    # meaningless per-batch composition changes (VERDICT r3 #6: the
    # single-stream python reader capped a 50k validation pass)
    native = use_native and not deterministic
    if use_native and deterministic:
        # say it: the operator asked for the native record prefetcher
        # (the r3 fix for the single-stream reader cap) but determinism
        # must bypass its thread-timing-dependent file interleave — eval
        # wall-clock on this process is back on the python reader
        import logging
        logging.getLogger(__name__).warning(
            "use_native prefetcher disabled: deterministic mode (replica "
            "processes share a batch slice) requires a stable record "
            "order; the python reader streams files in order instead "
            "(native JPEG decode stays active)")
    if native:
        try:
            from .native_loader import NativePrefetcher, native_available
            native = native_available()
        except Exception:
            native = False

    def record_stream(ordered_files):
        if native:
            # record-reader threads track the decode width (round 9): a
            # 4-thread reader fed an 8-wide decode pool starved it on
            # fast storage
            pf = NativePrefetcher(
                list(ordered_files),
                num_threads=min(len(ordered_files),
                                max(4, decode_processes,
                                    num_decode_threads)))
            try:
                yield from pf
            finally:
                pf.close()
        else:
            # max_corrupt_records > 0: tolerate truncated tails / torn
            # shards with counted skips (data/tfrecord.py; {"event":
            # "corrupt_record"} rows via CorruptRecordsHook). Flipped
            # payload bytes are only caught when verify_crc is on (a
            # python CRC32C pass per record — data.verify_crc). The
            # native C++ prefetcher has its own CRC handling, stays strict.
            for path in ordered_files:
                yield from read_tfrecords(path, verify_crc=verify_crc,
                                          max_corrupt=max_corrupt_records)

    # stage 1: raw (jpeg_bytes, label) stream with file + buffer shuffle
    def raw_stream():
        epoch = 0
        while True:
            order = rng.permutation(len(files)) if is_train else range(len(files))
            buf: List[tuple] = []
            for rec in record_stream([files[fi] for fi in order]):
                sample = _example_to_sample(parse_example(rec))
                if sample is None:
                    continue
                if is_train and shuffle_buffer > 1:
                    buf.append(sample)
                    if len(buf) >= shuffle_buffer:
                        j = rng.randint(len(buf))
                        yield buf.pop(j)
                else:
                    yield sample
            while buf:
                j = rng.randint(len(buf))
                yield buf.pop(j)
            epoch += 1
            if not is_train:
                return

    # stage 2: parallel decode+preprocess workers (threads, or processes
    # when decode_processes > 0)
    use_procs = decode_processes > 0
    n_workers = decode_processes if use_procs else num_decode_threads
    emit_uint8 = device_standardize
    # the fused C++ decode (one GIL-free call per image) when built with
    # libjpeg; PIL otherwise — identical crop geometry either way
    native_decode = False
    if use_native:
        try:
            from .native_loader import native_jpeg_available
            native_decode = native_jpeg_available()
        except Exception:
            native_decode = False
        if deterministic and not native_decode:
            # replica peers that DO have the native build will decode the
            # same records through libjpeg's interpolation path — pixel
            # divergence deterministic mode cannot see. Loud, so a
            # heterogeneous fleet is discoverable from the degraded host.
            import logging
            logging.getLogger(__name__).warning(
                "native JPEG decode unavailable on this process but "
                "deterministic mode is on: if replica peers resolve the "
                "native path, their batches will differ pixel-wise from "
                "this host's PIL decode — install the native loader on "
                "all hosts (or set data.use_native_loader=false fleet-"
                "wide)")

    # worker processes ship their decode stage-counters back as
    # _StageDelta messages on the result queue (merged below): without the
    # merge, bench's input attribution under decode_processes > 0
    # undercounted decode busy time — the workers' own registries die with
    # the workers
    if use_procs:
        import multiprocessing as mp
        # NOT "fork": the parent is multi-threaded by the time an iterator
        # is built (JAX runtime threads, earlier iterators' feeders), and a
        # child forked while another thread holds a lock (malloc, logging)
        # can deadlock — observed nondeterministically in round 4.
        # forkserver forks from a clean single-threaded server process;
        # spawn is the fallback where it's unavailable. The worker body
        # (_decode_worker) is module-level and numpy/PIL-only, so both
        # start methods can import it.
        try:
            ctx = mp.get_context("forkserver")
        except ValueError:  # platform without forkserver
            ctx = mp.get_context("spawn")
        in_q = ctx.Queue(maxsize=4 * batch_size)
        out_q = ctx.Queue(maxsize=max(2, prefetch_batches) * batch_size)
        workers = [
            ctx.Process(target=_decode_worker,
                        args=(in_q, out_q,
                              seed * 7919 if deterministic
                              else seed * 7919 + i,
                              is_train, image_size, native_decode,
                              emit_uint8, deterministic, i, device_flip),
                        daemon=True)
            for i in range(n_workers)]
        for w in workers:
            w.start()
        # parent only, AFTER the workers start (children must keep normal
        # join semantics so their final puts flush at exit): without this,
        # an abandoned iterator leaves the parent's atexit joining a queue
        # feeder thread that can never drain once workers are gone
        in_q.cancel_join_thread()
        out_q.cancel_join_thread()
    else:
        in_q = queue_mod.Queue(maxsize=4 * batch_size)
        out_q = queue_mod.Queue(
            maxsize=max(2, prefetch_batches) * batch_size)
    stop = threading.Event()

    def _put_checked(item) -> bool:
        """Timed put so the feeder notices `stop` even when the queue is
        full (a blocking put would never wake once consumers are gone —
        at interpreter exit multiprocessing joins its queue threads and a
        stuck feeder turns teardown into a hang)."""
        while not stop.is_set():
            try:
                in_q.put(item, timeout=0.2)
                return True
            except queue_mod.Full:
                continue
        return False

    def feeder():
        try:
            for seq, sample in enumerate(raw_stream()):
                if not _put_checked((seq, sample) if deterministic
                                    else sample):
                    return
            for _ in range(n_workers):
                if not _put_checked(_END):
                    return
        except BaseException as e:
            out_q.put(_Failure(repr(e)))

    def decoder(widx: int):
        try:
            # deterministic: ONE shared seed base — the item's RNG derives
            # from its sequence number, not from which worker got it
            wseed = seed * 7919 if deterministic else seed * 7919 + widx
            _decode_loop(in_q, out_q, wseed, is_train,
                         image_size, native_decode, emit_uint8, stop,
                         deterministic, widx, device_flip)
        except BaseException as e:
            out_q.put(_Failure(repr(e)))

    worker_threads = [threading.Thread(target=feeder, daemon=True)]
    if not use_procs:
        worker_threads += [
            threading.Thread(target=decoder, args=(i,), daemon=True)
            for i in range(n_workers)]
    for t in worker_threads:
        t.start()

    def batches():
        images = np.empty((batch_size, image_size, image_size, 3),
                          np.uint8 if emit_uint8 else np.float32)
        labels = np.empty((batch_size,), np.int32)
        fill = 0
        ended = 0
        # deterministic reorder state: emit strictly by sequence number.
        # The out-of-order window is bounded by in-flight items
        # (in_q capacity + workers), so `pending` stays small.
        expected = [0]
        pending: Dict[int, tuple] = {}

        def in_order(item):
            """Payloads ready to consume, in sequence order
            (deterministic mode only)."""
            seq, payload = item
            pending[seq] = payload
            while expected[0] in pending:
                yield pending.pop(expected[0])
                expected[0] += 1

        def next_item():
            # a worker killed without enqueueing _Failure or _END (a
            # signal death for processes; interpreter teardown or a hard
            # native crash for threads) must become a loud error, not a
            # permanent out_q.get() block — timed get + liveness poll on
            # BOTH paths (hangcheck untimed-blocking-call,
            # docs/static_analysis.md)
            while True:
                try:
                    return out_q.get(timeout=5.0)
                except queue_mod.Empty:
                    if not use_procs:
                        # decode THREADS: all dead with nothing queued
                        # means items were lost, not still in flight
                        if not any(t.is_alive() for t in worker_threads):
                            try:
                                return out_q.get_nowait()
                            except queue_mod.Empty:
                                raise RuntimeError(
                                    "imagenet decode thread(s) died "
                                    "without reporting — stream lost"
                                ) from None
                        continue
                    dead = [w for w in workers if not w.is_alive()
                            and w.exitcode not in (0, None)]
                    if dead:
                        raise RuntimeError(
                            "imagenet decode worker(s) died without "
                            f"reporting: exitcodes "
                            f"{[w.exitcode for w in dead]}") from None

        from ..utils.metrics import input_stages
        try:
            while True:
                item = next_item()
                if isinstance(item, _StageDelta):
                    # decode-PROCESS counter snapshot: merge into the
                    # parent registry under a per-worker key so
                    # max_thread_seconds still means "busiest worker"
                    input_stages.add("decode", item.seconds,
                                     items=item.count, nbytes=item.nbytes,
                                     worker=("decode-proc", item.widx))
                    continue
                if isinstance(item, _Failure):
                    raise RuntimeError(
                        f"imagenet pipeline worker failed: {item.err}")
                if item is _END or isinstance(item, _EndMarker):
                    ended += 1
                    if ended == n_workers:
                        # every worker's items precede its own _END in
                        # queue order, so by the n-th _END all items have
                        # been consumed and `pending` has drained. Explicit
                        # raise (not assert): under `python -O` a violated
                        # invariant must still fail loudly, not silently
                        # drop the tail of a deterministic eval stream
                        if pending:
                            raise RuntimeError(
                                "imagenet deterministic reorder drain "
                                f"invariant violated: {len(pending)} "
                                "item(s) undelivered at stream end, first "
                                f"seqs {sorted(pending)[:4]} — refusing to "
                                "silently drop the stream tail")
                        if fill and not is_train:
                            # final partial eval batch: pad + mask
                            mask = np.zeros((batch_size,), np.float32)
                            mask[:fill] = 1.0
                            images[fill:] = 0.0
                            labels[fill:] = 0
                            yield {"images": images.copy(),
                                   "labels": labels.copy(), "mask": mask}
                        return
                    continue
                # non-deterministic stays a plain tuple wrap — no
                # per-image generator on the measured host hot path
                for payload in (in_order(item) if deterministic
                                else (item,)):
                    images[fill], labels[fill] = payload
                    fill += 1
                    if fill == batch_size:
                        yield {"images": images.copy(),
                               "labels": labels.copy()}
                        fill = 0
        finally:
            stop.set()
            if use_procs:
                # don't let atexit try to flush/join the queue threads:
                # with the workers gone the pipes never drain
                in_q.cancel_join_thread()
                out_q.cancel_join_thread()
                for w in workers:
                    w.terminate()

    return batches()


class _EndMarker:
    """Worker-exhausted sentinel that survives a multiprocessing queue."""


class _StageDelta:
    """A decode worker PROCESS's stage-counter increment, shipped to the
    parent over the result queue (pickle-friendly; see ``_decode_loop``).
    The parent merges it into ``utils.metrics.input_stages`` so bench's
    input attribution sees process-pool decode busy time too."""

    __slots__ = ("widx", "count", "seconds", "nbytes")

    def __init__(self, widx: int, count: int, seconds: float, nbytes: int):
        self.widx = widx
        self.count = count
        self.seconds = seconds
        self.nbytes = nbytes


class _Failure:
    def __init__(self, err: str):
        self.err = err


_END = _EndMarker()


def _decode_loop(in_q, out_q, wseed, is_train, image_size, native_decode,
                 emit_uint8, stop=None, deterministic=False, widx=0,
                 device_flip=False):
    from .preprocessing import (RGB_MEANS, eval_crop_from_bytes,
                                train_crop_from_bytes)
    import queue as queue_mod

    from ..telemetry.tracer import span
    from ..utils.metrics import input_stages
    wrng = np.random.RandomState(wseed)
    # decode counters flush in small groups: an input_stages.add per image
    # would contend the registry lock across the whole decode pool (and a
    # _StageDelta per image would double the result-queue traffic)
    pend_n = 0
    pend_s = pend_b = 0

    def flush_counters():
        """Thread mode: straight into the process registry. Process mode
        (stop is None): our registry dies with this worker — ship the
        delta to the parent over the result queue instead (merged into
        the parent's input_stages; see imagenet_iterator.batches)."""
        nonlocal pend_n, pend_s, pend_b
        if not pend_n:
            return
        if stop is None:
            out_q.put(_StageDelta(widx, pend_n, pend_s, pend_b))
        else:
            input_stages.add("decode", pend_s, items=pend_n, nbytes=pend_b)
        pend_n = 0
        pend_s = pend_b = 0

    def put_checked(item) -> bool:
        """Timed put in thread mode so `stop` is observed even on a FULL
        out_q (decoders outpacing an abandoned consumer park here, not in
        get). Process mode (stop=None) keeps the blocking put — workers
        are terminate()d."""
        if stop is None:
            out_q.put(item)
            return True
        while not stop.is_set():
            try:
                out_q.put(item, timeout=0.2)
                return True
            except queue_mod.Full:
                continue
        return False

    try:
        while stop is None or not stop.is_set():
            # timed get in thread mode so `stop` is observed between
            # items: an abandoned iterator (eval warmup, a polling
            # evaluator sized below the dataset) sets `stop` while workers
            # sit in get(); a blocking get would strand
            # num_decode_threads daemon threads per iterator, growing
            # unboundedly in a long-lived poll loop.
            try:
                item = in_q.get(timeout=None if stop is None else 0.2)
            except queue_mod.Empty:
                continue
            if item is _END or isinstance(item, _EndMarker):
                # counters BEFORE the _END marker: the parent stops
                # consuming at the n-th _END, so a delta after ours could
                # only be read by luck
                flush_counters()
                put_checked(_END)
                return
            if deterministic:
                # per-item RNG from the sample's sequence number: the same
                # record gets the same augmentation no matter which worker
                # decodes it (see imagenet_iterator's `deterministic`)
                seq, (data, label) = item
                rng = np.random.RandomState((wseed + 2654435761 * seq)
                                            % (2 ** 32))
            else:
                seq, (data, label) = None, item
                rng = wrng
            t0 = time.perf_counter()
            with span("input.decode"):
                if is_train:
                    img = train_crop_from_bytes(data, rng, image_size,
                                                use_native=native_decode,
                                                apply_flip=not device_flip)
                else:
                    img = eval_crop_from_bytes(data, image_size,
                                               use_native=native_decode)
                if not emit_uint8:
                    img = img.astype(np.float32) / 255.0 - RGB_MEANS
            # decode busy time (stage counters, utils/metrics.py); worker
            # PROCESSES flush deltas to the parent (flush_counters)
            pend_n += 1
            pend_s += time.perf_counter() - t0
            pend_b += img.nbytes
            if pend_n >= 16:
                flush_counters()
            out = (img, label) if seq is None else (seq, (img, label))
            if not put_checked(out):
                return
    finally:
        # thread mode only: a worker PROCESS's terminal flush would land
        # AFTER its _END (already flushed there) and could race the
        # parent's teardown drain
        if stop is not None:
            flush_counters()


def _decode_worker(in_q, out_q, wseed, is_train, image_size, native_decode,
                   emit_uint8, deterministic=False, widx=0,
                   device_flip=False):
    """Process-pool worker body (fork target)."""
    try:
        _decode_loop(in_q, out_q, wseed, is_train, image_size,
                     native_decode, emit_uint8, deterministic=deterministic,
                     widx=widx, device_flip=device_flip)
    except BaseException as e:  # pragma: no cover - transported to parent
        out_q.put(_Failure(repr(e)))
