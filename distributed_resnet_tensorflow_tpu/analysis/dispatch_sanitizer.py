"""Runtime dispatch sanitizer: ONE thread launches multi-device programs.

The PR 2 constraint (docs/input_pipeline.md, parallel/sharding.StagedBatch):
two threads launching multi-device XLA executions interleave their
per-device enqueue order and can DEADLOCK against a collective-bearing
step — observed on the CPU backend, and the reason ``StagedBatch.finalize``
must run on the consumer thread while the staging thread only moves bytes
(``device_put`` has no cross-device rendezvous and stays safe).

Until now that rule lived in a docs paragraph. This module makes it
executable: ``install()`` wraps jax's compiled-execution entry point
(``pxla.ExecuteReplicated.__call__``); the first thread to launch a
multi-device execution becomes the OWNER, and any later launch from a
different thread raises :class:`CrossThreadDispatchError` immediately —
at the offending call site, with both thread names — instead of wedging
the cluster at the next collective.

Opt-in and NOT free: jit's C++ fastpath dispatches cached executions
without touching Python, so while the sanitizer is installed the
fastpath is disabled (``_get_fastpath_data`` returns None) and the jit
caches are cleared — every dispatch pays the Python-path overhead and
armed/disarmed transitions recompile. That is the honest price of
instrumenting every launch; use it in debug/bringup runs, not
production. Set ``--set analysis.dispatch_sanitizer=true`` (wired in
main.py), or use ``enabled()`` / ``install()`` directly in tests.
Single-device executions are never restricted.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Optional

_lock = threading.Lock()
_installed = False
_orig_call = None
_orig_fastpath = None
_owner: Optional[tuple] = None  # (thread_ident, thread_name)


class CrossThreadDispatchError(RuntimeError):
    """A second thread launched a multi-device XLA execution."""


def _owner_claim_or_raise(n_devices: int, program: str) -> None:
    global _owner
    if n_devices <= 1:
        return
    me = threading.current_thread()
    with _lock:
        if _owner is None:
            _owner = (me.ident, me.name)
            return
        if _owner[0] == me.ident:
            return
        owner_name = _owner[1]
    raise CrossThreadDispatchError(
        f"multi-device execution {program!r} launched from thread "
        f"{me.name!r} while thread {owner_name!r} owns multi-device "
        "dispatch — two dispatching threads interleave per-device enqueue "
        "order and can deadlock a collective-bearing step "
        "(docs/input_pipeline.md threading model; StagedBatch.finalize "
        "belongs on the consumer thread). Move this launch to the owner "
        "thread, or call analysis.dispatch_sanitizer.reset_owner() at a "
        "legitimate ownership handoff.")


def install() -> None:
    """Idempotently wrap the compiled-execution entry point (and route
    every dispatch through it by disabling jit's C++ fastpath)."""
    global _installed, _orig_call, _orig_fastpath
    import jax
    from jax._src import pjit as _pjit
    from jax._src.interpreters import pxla

    with _lock:
        if _installed:
            return
        _orig_call = pxla.ExecuteReplicated.__call__
        _orig_fastpath = _pjit._get_fastpath_data
        orig = _orig_call

        def guarded(self, *args):
            _owner_claim_or_raise(len(self._local_devices),
                                  getattr(self, "name", "<unknown>"))
            return orig(self, *args)

        # patch INSIDE the lock: a concurrent install() must not observe
        # _installed=True while the original, unguarded entry points are
        # still in place
        pxla.ExecuteReplicated.__call__ = guarded
        # keep dispatch on the Python path while armed: the C++ fastpath
        # replays cached executions without entering __call__ at all
        _pjit._get_fastpath_data = lambda *a, **k: None
        _installed = True
    # flush fastpath data cached before arming (recompiles on next call)
    jax.clear_caches()


def uninstall() -> None:
    global _installed, _orig_call, _orig_fastpath, _owner
    import jax
    from jax._src import pjit as _pjit
    from jax._src.interpreters import pxla

    with _lock:
        if not _installed:
            return
        pxla.ExecuteReplicated.__call__ = _orig_call
        _pjit._get_fastpath_data = _orig_fastpath
        _installed = False
        _orig_call = None
        _orig_fastpath = None
        _owner = None
    # drop the fastpath-less cached entries so production dispatch speed
    # returns (recompiles on next call)
    jax.clear_caches()


def reset_owner() -> None:
    """Forget the owning thread — for legitimate handoffs (e.g. a runner
    that finishes its train loop on one thread and evaluates on another).
    The next multi-device launch claims ownership."""
    global _owner
    with _lock:
        _owner = None


def is_installed() -> bool:
    return _installed


@contextlib.contextmanager
def enabled():
    """Scoped install/uninstall (tests)."""
    install()
    try:
        yield
    finally:
        uninstall()
