"""serve/ — AOT batched inference server with hot checkpoint swap.

Covers the contracts docs/serving.md promises: bucket selection + padding
(bucketed logits == unbatched eval logits), queue-delay coalescing under
concurrent submitters, hot-swap atomicity (in-flight requests complete on
the old params, the next batch sees the new step), torn checkpoints
rejected by manifest verification without disturbing the serving params,
and the whole arrangement running clean under the cross-thread dispatch
sanitizer (the PR 2 single-dispatch-thread constraint, enforced)."""
import os
import time

import jax
import numpy as np
import pytest

from distributed_resnet_tensorflow_tpu.checkpoint import CheckpointManager
from distributed_resnet_tensorflow_tpu.serve import (InferenceServer,
                                                     bucket_sizes,
                                                     pick_bucket)
from distributed_resnet_tensorflow_tpu.utils.config import get_preset


def _tiny_cfg(tmp_path, **kw):
    cfg = get_preset("smoke")
    cfg.model.compute_dtype = "float32"
    cfg.model.resnet_size = 8
    cfg.model.num_classes = 4
    cfg.data.image_size = 8
    cfg.data.eval_batch_size = 16       # buckets on the 8-dev mesh: [8, 16]
    cfg.train.batch_size = 16
    cfg.log_root = str(tmp_path)
    cfg.checkpoint.directory = os.path.join(str(tmp_path), "ckpt")
    cfg.checkpoint.async_save = False
    cfg.serve.max_queue_delay_ms = 20.0
    cfg.serve.poll_interval_secs = 0.2
    for k, v in kw.items():
        cfg.override(k, v)
    return cfg


def _images(n, rng=None):
    rng = rng or np.random.RandomState(0)
    return rng.randn(n, 8, 8, 3).astype(np.float32)


def _commit(cfg, server, step, scale=None):
    """Commit the server's current params (optionally rescaled) as a
    checkpoint at ``step`` — the training publisher stand-in. Everything
    happens HOST-side (np.asarray pulls + numpy math): the threaded tests
    run under the dispatch sanitizer with the dispatch thread owning
    multi-device executions, so the publisher must not launch any."""
    mngr = CheckpointManager(cfg.checkpoint.directory, async_save=False,
                             max_to_keep=100)
    st = server.trainer.state

    def host(x):
        return np.asarray(x)

    params = jax.tree_util.tree_map(
        (lambda x: host(x) * scale) if scale is not None else host,
        st.params)
    st = st.replace(step=np.asarray(step, np.int32), params=params,
                    batch_stats=jax.tree_util.tree_map(host, st.batch_stats),
                    opt_state=jax.tree_util.tree_map(host, st.opt_state))
    mngr.save(step, st, force=True)
    mngr.close()


# ---------------------------------------------------------------------------
# pure helpers
# ---------------------------------------------------------------------------

def test_bucket_sizes_power_of_two_with_pad_floor():
    assert bucket_sizes(16, 8) == [8, 16]
    assert bucket_sizes(100, 8) == [8, 16, 32, 64, 104]  # cap rounded up
    assert bucket_sizes(4, 1) == [1, 2, 4]
    assert bucket_sizes(1, 1) == [1]
    with pytest.raises(ValueError):
        bucket_sizes(0, 8)


def test_pick_bucket_smallest_fit():
    buckets = [8, 16, 32]
    assert pick_bucket(buckets, 1) == 8
    assert pick_bucket(buckets, 8) == 8
    assert pick_bucket(buckets, 9) == 16
    with pytest.raises(ValueError):
        pick_bucket(buckets, 33)


def test_pad_batch_to_bucket_mask_semantics():
    from distributed_resnet_tensorflow_tpu.parallel.sharding import (
        pad_batch_to_bucket)
    batch = {"images": np.ones((3, 4, 4, 3), np.float32),
             "labels": np.arange(3, dtype=np.int32)}
    out = pad_batch_to_bucket(batch, 8)
    assert out["images"].shape == (8, 4, 4, 3)
    assert out["labels"].shape == (8,)
    np.testing.assert_array_equal(out["mask"],
                                  [1, 1, 1, 0, 0, 0, 0, 0])
    # already at the bucket: untouched content, full mask
    full = pad_batch_to_bucket(batch, 3)
    np.testing.assert_array_equal(full["mask"], [1, 1, 1])
    with pytest.raises(ValueError):
        pad_batch_to_bucket(batch, 2)


def test_serve_events_registered():
    # the registry-drift lint enforces this statically; this is the cheap
    # runtime tripwire against a rename that dodges the linter
    from distributed_resnet_tensorflow_tpu.utils.metrics import EVENT_SCHEMAS
    for name in ("serve_request", "serve_batch", "serve_swap"):
        assert name in EVENT_SCHEMAS


# ---------------------------------------------------------------------------
# serving correctness (deterministic single-thread driving)
# ---------------------------------------------------------------------------

@pytest.mark.heavy
def test_bucketed_logits_match_unbatched_eval(tmp_path):
    """Bucket selection + padding correctness: logits served out of a
    padded bucket batch equal the unbatched eval forward per example
    (train=False BN → rows are batch-independent)."""
    cfg = _tiny_cfg(tmp_path)
    server = InferenceServer(cfg)
    server.start(start_threads=False)
    imgs = _images(3)
    futures = [server.submit(im) for im in imgs]
    served = server.service_once()
    assert served == 3
    # all three coalesced into the smallest fitting bucket (8)
    assert server.batcher.batches == 1
    assert server.latency.summary_ms()["bucket_8"]["count"] == 3
    # warm cache honored: the request paid zero compiles
    assert server.cache.serve_time_compiles == 0

    predict = server.trainer.jitted_predict_step()
    for im, fut in zip(imgs, futures):
        row, step = fut.result(timeout=5)
        assert step == -1  # fresh init, no checkpoint
        ref = np.asarray(predict(server.trainer.state, {"images": im[None]}))
        np.testing.assert_allclose(row, ref[0], rtol=1e-5, atol=1e-5)

    # spec violations are rejected loudly, never silently cast/served:
    # a uint8 image against the float32 spec would serve unstandardized
    # pixels, a wrong shape a garbled batch
    with pytest.raises(ValueError):
        server.submit((imgs[0] * 255).astype(np.uint8))
    with pytest.raises(ValueError):
        server.submit(np.zeros((4, 4, 3), np.float32))
    server.close()
    assert server.dropped == 0


@pytest.mark.heavy
def test_hot_swap_atomicity(tmp_path):
    """In-flight requests complete on the OLD params; the batch after the
    boundary sees the new checkpoint step; torn checkpoints are rejected
    without touching the serving params."""
    cfg = _tiny_cfg(tmp_path)
    server = InferenceServer(cfg)
    server.start(start_threads=False)
    img = _images(1)[0]

    # publish step 7 with rescaled params, make it pending
    _commit(cfg, server, 7, scale=0.5)
    pending = server.swapper.poll_once()
    assert pending is not None and pending.step == 7

    f_old = server.submit(img)
    server.service_once()     # dispatches f_old, THEN applies the swap
    row_old, step_old = f_old.result(timeout=5)
    assert step_old == -1     # in-flight batch finished on the old params
    assert server.serving_step == 7  # swap landed at the batch boundary

    f_new = server.submit(img)
    server.service_once()
    row_new, step_new = f_new.result(timeout=5)
    assert step_new == 7
    # the swapped params are actually live (logits changed)
    assert not np.allclose(row_old, row_new)
    server.close()
    assert server.dropped == 0 and server.swaps == 1


@pytest.mark.heavy
def test_torn_checkpoint_rejected_serving_undisturbed(tmp_path):
    cfg = _tiny_cfg(tmp_path)
    server = InferenceServer(cfg)
    server.start(start_threads=False)
    img = _images(1)[0]

    # good step 3 swaps in
    _commit(cfg, server, 3)
    assert server.swapper.poll_once() is not None
    server.service_once()
    assert server.serving_step == 3

    # step 5 committed, then damaged after commit (truncation/bit rot):
    # manifest verification must reject it off the request path
    _commit(cfg, server, 5, scale=2.0)
    _corrupt_step(cfg, 5)
    assert server.swapper.poll_once() is None
    assert server.swapper.rejected == 1
    f1 = server.submit(img)
    server.service_once()
    assert f1.result(timeout=5)[1] == 3  # still serving the old step
    assert server.serving_step == 3

    # a later GOOD commit still swaps in (the bad step was skipped, not
    # retried forever)
    _commit(cfg, server, 9, scale=3.0)
    assert server.swapper.poll_once() is not None
    server.service_once()
    assert server.serving_step == 9

    # hot-path fallback: TWO new commits land between polls and the
    # newest tears — the poll must surface the older GOOD one instead of
    # leaving the replica stale (same contract as the startup walk)
    _commit(cfg, server, 12, scale=4.0)
    _commit(cfg, server, 15, scale=5.0)
    _corrupt_step(cfg, 15)
    pending = server.swapper.poll_once()
    assert pending is not None and pending.step == 12
    server.service_once()
    assert server.serving_step == 12
    assert server.swapper.poll_once() is None  # 15 skipped, not re-tried
    server.close()
    assert server.dropped == 0


def _corrupt_step(cfg, step):
    step_dir = os.path.join(cfg.checkpoint.directory, str(step))
    payloads = [os.path.join(dp, f)
                for dp, _, fs in os.walk(step_dir) for f in fs
                if f != "MANIFEST.json"]
    with open(max(payloads, key=os.path.getsize), "ab") as f:
        f.write(b"torn")


@pytest.mark.heavy
def test_startup_falls_back_past_torn_newest(tmp_path):
    """A restarting replica whose NEWEST commit is torn serves the newest
    older checkpoint that verifies — never fresh-init params."""
    cfg = _tiny_cfg(tmp_path)
    boot = InferenceServer(cfg)
    _commit(cfg, boot, 2)
    _commit(cfg, boot, 5, scale=2.0)
    _corrupt_step(cfg, 5)
    server = InferenceServer(cfg)
    server.start(start_threads=False)
    assert server.serving_step == 2          # fell back, not random init
    assert server.swapper.rejected == 1
    assert server.swaps == 0
    # the background poll is anchored PAST the damaged newest step: only
    # a genuinely newer commit swaps in
    assert server.swapper.poll_once() is None
    _commit(cfg, server, 8, scale=3.0)
    assert server.swapper.poll_once() is not None
    server.service_once()
    assert server.serving_step == 8
    server.close()


@pytest.mark.heavy
def test_mismatched_checkpoint_rejected_without_poisoning(tmp_path):
    """A same-tree checkpoint from a DIFFERENT model config (other
    num_classes → other head shape) is rejected at apply time; serving
    continues on the old params instead of poisoning every later batch
    with an executable/input mismatch."""
    from distributed_resnet_tensorflow_tpu.train import Trainer
    cfg = _tiny_cfg(tmp_path)
    server = InferenceServer(cfg)
    server.start(start_threads=False)
    img = _images(1)[0]

    other_cfg = _tiny_cfg(tmp_path, **{"model.num_classes": "10"})
    other = Trainer(other_cfg)
    other.init_state()
    mngr = CheckpointManager(cfg.checkpoint.directory, async_save=False)
    host = jax.tree_util.tree_map(np.asarray, other.state)
    mngr.save(4, host.replace(step=np.asarray(4, np.int32)), force=True)
    mngr.close()

    assert server.swapper.poll_once() is not None  # loads fine host-side
    f1 = server.submit(img)
    server.service_once()          # apply attempt at the boundary: reject
    assert server.serving_step == -1 and server.swaps == 0
    assert server.swapper.rejected == 1
    # the replica still answers (no poisoned state swapped in)
    assert f1.result(timeout=5)[0].shape == (4,)
    f2 = server.submit(img)
    server.service_once()
    assert f2.result(timeout=5)[0].shape == (4,)
    server.close()
    assert server.dropped == 0 and server.batcher.errors == 0


@pytest.mark.heavy
def test_startup_restore_applied_once_and_not_a_hot_swap(tmp_path):
    """A checkpoint present at startup is applied exactly once (not
    re-applied by the first batch-boundary hook) and does NOT count as a
    hot swap — `swaps` only counts checkpoints published while serving."""
    cfg = _tiny_cfg(tmp_path)
    boot = InferenceServer(cfg)       # only to mint a checkpoint to serve
    _commit(cfg, boot, 2)
    server = InferenceServer(cfg)
    server.start(start_threads=False)
    assert server.serving_step == 2
    assert server.swaps == 0          # startup restore is not a hot swap
    assert not server.swapper.has_pending  # claimed, not parked
    f = server.submit(_images(1)[0])
    server.service_once()             # boundary hook must not re-apply
    assert f.result(timeout=5)[1] == 2
    assert server.swaps == 0
    _commit(cfg, server, 6, scale=0.5)
    assert server.swapper.poll_once() is not None
    server.service_once()
    assert server.serving_step == 6 and server.swaps == 1
    server.close()


@pytest.mark.heavy
def test_close_drains_queued_requests_without_dispatch_thread(tmp_path):
    """Thread-less mode: requests still queued at close() are served by
    the closing (caller) thread — accepted means answered."""
    cfg = _tiny_cfg(tmp_path)
    server = InferenceServer(cfg)
    server.start(start_threads=False)
    futures = [server.submit(im) for im in _images(3)]
    server.close()                    # no service_once ran
    assert all(f.result(timeout=5)[0].shape == (4,) for f in futures)
    assert server.dropped == 0
    with pytest.raises(RuntimeError):
        server.submit(_images(1)[0])  # intake sealed


# ---------------------------------------------------------------------------
# threaded serving (real dispatch + swap threads)
# ---------------------------------------------------------------------------

@pytest.mark.heavy
def test_queue_delay_batching_under_concurrent_submitters(tmp_path):
    """Concurrent submitters coalesce: N requests land in far fewer than N
    dispatched batches under a generous queue delay, and every future
    resolves (zero dropped)."""
    import threading
    cfg = _tiny_cfg(tmp_path, **{"serve.max_queue_delay_ms": "300"})
    server = InferenceServer(cfg)
    server.start(start_threads=True)
    imgs = _images(6, np.random.RandomState(1))
    futures = [None] * 6

    def submitter(i):
        futures[i] = server.submit(imgs[i])

    threads = [threading.Thread(target=submitter, args=(i,))
               for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    rows = [f.result(timeout=30) for f in futures]
    assert len(rows) == 6
    server.close()
    assert server.dropped == 0 and server.batcher.errors == 0
    # 6 requests within a 300ms window → coalesced, not 6 single-row
    # batches (allow scheduler slop: the first dispatch may slip out alone)
    assert 1 <= server.batcher.batches <= 3
    counts = {k: v["count"]
              for k, v in server.latency.summary_ms().items()}
    assert sum(counts.values()) == 6


@pytest.mark.heavy
# re-tiered out of the 870s tier-1 (ISSUE 17, ~20s: threaded hot-swap
# soak under the dispatch sanitizer). The swap protocol stays covered
# in tier-1 by the startup-fallback / mismatched-checkpoint /
# restore-once tests, and the live serve plane (including swaps under
# load) runs in scripts/obs_smoke.sh and scripts/chaos_smoke.sh; the
# full (unfiltered) suite runs this soak.
@pytest.mark.slow
def test_threaded_swap_and_sanitizer_clean(tmp_path):
    """End-to-end with REAL dispatch + swap threads, under the cross-thread
    dispatch sanitizer: requests served, a checkpoint published mid-serve
    hot-swaps in (applied by the dispatch thread, idle or not), no
    CrossThreadDispatchError, zero dropped requests."""
    from distributed_resnet_tensorflow_tpu.analysis import dispatch_sanitizer
    cfg = _tiny_cfg(tmp_path)
    server = InferenceServer(cfg)
    with dispatch_sanitizer.enabled():
        server.start(start_threads=True)
        imgs = _images(4, np.random.RandomState(2))
        pre = [server.submit(im) for im in imgs]
        assert all(f.result(timeout=30)[1] == -1 for f in pre)

        _commit(cfg, server, 11, scale=0.25)
        deadline = time.monotonic() + 20
        while server.swaps == 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert server.swaps == 1, "hot swap never landed"

        post = [server.submit(im) for im in imgs]
        assert all(f.result(timeout=30)[1] == 11 for f in post)
        server.close()
    assert server.batcher.errors == 0
    assert server.dropped == 0
    assert server.cache.serve_time_compiles == 0


@pytest.mark.heavy
def test_hot_swap_reads_sharded_checkpoint(tmp_path):
    """A trainer running per-host SHARDED checkpoints (checkpoint.sharded,
    checkpoint/shards.py) publishes a layout the serving hot-swap must
    read: the swapper rebuilds step/params/batch_stats from the shard
    indexes (never opening the optimizer shards) and applies it like any
    orbax checkpoint."""
    cfg = _tiny_cfg(tmp_path)
    server = InferenceServer(cfg)
    server.start(start_threads=False)
    img = _images(1)[0]

    # commit the server's params rescaled, via the SHARDED writer
    mngr = CheckpointManager(cfg.checkpoint.directory, async_save=False,
                             max_to_keep=100, sharded="on")
    st = server.trainer.state
    host = lambda x: np.asarray(x)  # noqa: E731
    st = st.replace(step=np.asarray(9, np.int32),
                    params=jax.tree_util.tree_map(
                        lambda x: host(x) * 0.5, st.params),
                    batch_stats=jax.tree_util.tree_map(host, st.batch_stats),
                    opt_state=jax.tree_util.tree_map(host, st.opt_state))
    mngr.save(9, st, force=True)
    mngr.close()
    from distributed_resnet_tensorflow_tpu.checkpoint import shards
    assert shards.is_sharded_layout(
        os.path.join(cfg.checkpoint.directory, "9"))

    pending = server.swapper.poll_once()
    assert pending is not None and pending.step == 9
    f = server.submit(img)
    server.service_once()
    f.result(timeout=5)
    server.service_once()
    assert server.serving_step == 9
    server.close()
    assert server.dropped == 0
