from .mesh import (  # noqa: F401
    AXES,
    present_batch_axes,
    batch_shard_count,
    create_mesh,
    data_sharding,
    local_batch_size,
    replicated,
    resolve_axis_sizes,
)
from .sharding import (  # noqa: F401
    make_global_batch,
    param_sharding_rule,
    shard_batch,
    tree_param_shardings,
)
from .distributed import initialize, initialize_from_config, is_chief  # noqa: F401
