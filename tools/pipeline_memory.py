"""Compiled-memory receipts for the pipeline schedules.

XLA's whole-program model means pipeline "memory behavior" is decided at
compile time — so it can be MEASURED at compile time: this tool compiles the
encoder's value_and_grad over a fake dp×pp mesh for a grid of
(schedule, remat, microbatch count) and records
``compiled.memory_analysis().temp_size_in_bytes`` (activations + workspace).

Global batch is FIXED across the whole grid (microbatch size = B/M) so the
rows isolate the schedule, not the batch. What the grid substantiates
(models/pipeline.py module docstring):
  * ``remat=True`` bounds the activation stash (the per-tick residual drops
    to the stage inputs that scan transposition must keep) — the XLA-native
    stand-in for 1F1B's eager-backward memory bound,
  * at fixed batch the non-remat stash is ~flat in M (it is the B·t·d
    stage-boundary stash), so the bubble knobs are: raise M (smaller
    microbatches, less per-tick MXU work) or raise interleave v (same
    microbatch size, v× more ICI hops) — the circular schedule trades
    neither in memory, costing only its O(B·t·d) wrap queue.

Usage:  python tools/pipeline_memory.py [--out docs/pipeline_memory_r3.json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_resnet_tensorflow_tpu.utils.virtual_devices import (  # noqa: E402
    apply_virtual_cpu, force_cpu_platform)

apply_virtual_cpu(8)

import jax  # noqa: E402

force_cpu_platform()

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

DIM, DEPTH, HEADS, TOKENS = 256, 8, 8, 128
DATA, PIPE = 2, 4
BATCH = 64  # global batch, fixed across the grid (divisible by DATA * max M)


def compile_case(mesh, microbatches: int, interleave: int, remat: bool):
    from distributed_resnet_tensorflow_tpu.models.pipeline import (
        PipelinedEncoder)
    b = BATCH
    enc = PipelinedEncoder(depth=DEPTH, num_heads=HEADS, dtype=jnp.float32,
                           mesh=mesh, microbatches=microbatches,
                           interleave=interleave, remat=remat)
    x = jnp.zeros((b, TOKENS, DIM), jnp.float32)
    params = enc.init(jax.random.PRNGKey(0), x)["params"]

    def loss(p, xx):
        return (enc.apply({"params": p}, xx) ** 2).sum()

    lowered = jax.jit(jax.value_and_grad(loss)).lower(params, x)
    ma = lowered.compile().memory_analysis()
    return {
        "batch": b,
        "temp_mb": round(ma.temp_size_in_bytes / 2**20, 2),
        "args_mb": round(ma.argument_size_in_bytes / 2**20, 2),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="")
    args = ap.parse_args()
    from distributed_resnet_tensorflow_tpu.parallel import create_mesh
    from distributed_resnet_tensorflow_tpu.utils.config import MeshConfig
    mesh = create_mesh(MeshConfig(data=DATA, pipeline=PIPE))

    grid = []
    for sched, v in (("gpipe", 1), ("circular", 2)):
        for remat in (False, True):
            for m in (4, 8, 16):
                row = {"schedule": sched, "interleave": v, "remat": remat,
                       "microbatches": m,
                       "bubble": round((PIPE - 1) / (v * m + PIPE - 1), 3)}
                row.update(compile_case(mesh, m, v, remat))
                grid.append(row)
                print({k: row[k] for k in
                       ("schedule", "remat", "microbatches", "bubble",
                        "temp_mb")})

    out = {
        "workload": {"dim": DIM, "depth": DEPTH, "heads": HEADS,
                     "tokens": TOKENS, "mesh": {"data": DATA, "pipeline": PIPE},
                     "global_batch": BATCH,
                     "dtype": "float32", "backend": "cpu (fake 8-device mesh; "
                     "temp bytes are backend-portable HLO buffer sizes)"},
        "metric": "compiled.memory_analysis().temp_size_in_bytes per device",
        "grid": grid,
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=2)
        print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
