"""Fault-injection suite for the resilience subsystem (docs/resilience.md):
preemption signals, crash-consistent checkpoint commit/fallback, NaN
rollback + LR back-off, bounded retries. Run standalone via
scripts/chaos_smoke.sh; everything here is tier-1 (CPU fake mesh)."""
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from distributed_resnet_tensorflow_tpu.checkpoint import (
    CheckpointManager, wait_for_new_checkpoint)
from distributed_resnet_tensorflow_tpu.checkpoint.manager import (
    CheckpointCorrupt)
from distributed_resnet_tensorflow_tpu.data import learnable_synthetic_iterator
from distributed_resnet_tensorflow_tpu.resilience import (
    Preempted, PreemptionListener, RESUMABLE_EXIT_CODE,
    committed_steps, retry_call)
from distributed_resnet_tensorflow_tpu.resilience import faultinject
from distributed_resnet_tensorflow_tpu.resilience.sentinel import (
    TooManyNanRetries, train_with_nan_recovery)
from distributed_resnet_tensorflow_tpu.resilience.manifest import (
    manifest_status)
from distributed_resnet_tensorflow_tpu.train import Trainer
from distributed_resnet_tensorflow_tpu.train.hooks import NanGuardHook
from distributed_resnet_tensorflow_tpu.utils.config import get_preset


# ---------------------------------------------------------------------------
# retry.py
# ---------------------------------------------------------------------------

def test_retry_succeeds_after_transient_failures():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    assert retry_call(flaky, retries=3, base_delay=0.0,
                      sleep=lambda s: None) == "ok"
    assert len(calls) == 3


def test_retry_bounded_and_reraises_original():
    calls = []

    def always_down():
        calls.append(1)
        raise OSError("still down")

    with pytest.raises(OSError, match="still down"):
        retry_call(always_down, retries=2, base_delay=0.0,
                   sleep=lambda s: None)
    assert len(calls) == 3  # 1 original + 2 retries, no more


def test_retry_giveup_short_circuits_permanent_errors():
    calls = []

    def already():
        calls.append(1)
        raise RuntimeError("coordinator already initialized")

    with pytest.raises(RuntimeError):
        retry_call(already, retries=5, base_delay=0.0,
                   retry_on=(RuntimeError,),
                   giveup=lambda e: "already" in str(e),
                   sleep=lambda s: None)
    assert len(calls) == 1  # permanent: no retries burned


def test_retry_backoff_schedule_exponential_and_capped():
    delays = []

    def always_down():
        raise OSError("down")

    with pytest.raises(OSError):
        retry_call(always_down, retries=4, base_delay=0.1, max_delay=0.4,
                   jitter=0.0, sleep=delays.append)
    # base * 2^attempt, capped at max_delay; no sleep after the last try
    assert delays == pytest.approx([0.1, 0.2, 0.4, 0.4])


def test_retry_jitter_stays_within_fraction():
    delays = []

    def always_down():
        raise OSError("down")

    with pytest.raises(OSError):
        retry_call(always_down, retries=10, base_delay=1.0, max_delay=1.0,
                   jitter=0.5, sleep=delays.append)
    assert len(delays) == 10
    assert all(0.5 <= d <= 1.5 for d in delays)  # ±50% around the cap


def test_retry_unlisted_exception_passes_through_immediately():
    calls = []

    def typeerror():
        calls.append(1)
        raise ValueError("not retryable")

    with pytest.raises(ValueError, match="not retryable"):
        retry_call(typeerror, retries=5, base_delay=0.0,
                   retry_on=(OSError,), sleep=lambda s: None)
    assert len(calls) == 1


def test_retry_passes_args_kwargs_and_returns_value():
    def add(a, b, scale=1):
        return (a + b) * scale

    assert retry_call(add, 2, 3, scale=10, retries=0) == 50
    with pytest.raises(ValueError):
        retry_call(add, retries=-1)


# ---------------------------------------------------------------------------
# collective_should_stop throttling (the multi-host stop agreement)
# ---------------------------------------------------------------------------

class _FakeAllgather:
    """Stand-in for multihost_utils.process_allgather: records each call's
    local flag, returns a canned cross-process OR."""

    def __init__(self, remote_flag=False):
        self.calls = []
        self.remote_flag = remote_flag

    def __call__(self, arr):
        local = bool(np.asarray(arr)[0])
        self.calls.append(local)
        return np.asarray([local or self.remote_flag])


@pytest.fixture()
def fake_allgather(monkeypatch):
    from jax.experimental import multihost_utils
    fake = _FakeAllgather()
    monkeypatch.setattr(multihost_utils, "process_allgather", fake)
    return fake


def test_collective_stop_throttles_the_host_collective(fake_allgather):
    from distributed_resnet_tensorflow_tpu.resilience.preemption import (
        collective_should_stop)
    listener = PreemptionListener(signals=())
    stop = collective_should_stop(listener, sync_every=8)
    assert not any(stop() for _ in range(7))
    assert len(fake_allgather.calls) == 0   # between sync points: local only
    assert stop() is False                  # 8th poll pays the collective
    assert len(fake_allgather.calls) == 1
    # a LOCAL stop request must not flip the answer between sync points —
    # stopping unilaterally is the deadlock this function exists to prevent
    listener.request_stop("test")
    assert not any(stop() for _ in range(7))
    assert len(fake_allgather.calls) == 1
    assert stop() is True                   # next sync point agrees
    assert len(fake_allgather.calls) == 2
    assert fake_allgather.calls[-1] is True  # our flag was in the gather


def test_collective_stop_sticky_after_agreement(fake_allgather):
    from distributed_resnet_tensorflow_tpu.resilience.preemption import (
        collective_should_stop)
    listener = PreemptionListener(signals=())
    listener.request_stop("test")
    stop = collective_should_stop(listener, sync_every=2)
    assert stop() is False and stop() is True
    n = len(fake_allgather.calls)
    # once agreed, no further collectives: the loop is exiting
    assert stop() is True and stop() is True
    assert len(fake_allgather.calls) == n


def test_collective_stop_mirrors_peer_preemption(fake_allgather):
    from distributed_resnet_tensorflow_tpu.resilience.preemption import (
        collective_should_stop)
    fake_allgather.remote_flag = True       # some OTHER process was signaled
    listener = PreemptionListener(signals=())
    stop = collective_should_stop(listener, sync_every=1)
    assert stop() is True
    assert listener.preempted()
    assert listener.reason() == "peer preempted"


# ---------------------------------------------------------------------------
# preemption.py
# ---------------------------------------------------------------------------

def test_preemption_listener_flags_sigterm_and_restores_handler():
    prev = signal.getsignal(signal.SIGTERM)
    listener = PreemptionListener()
    assert listener.install()
    try:
        assert not listener.should_stop()
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.monotonic() + 5.0
        while not listener.should_stop() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert listener.preempted()
        assert "SIGTERM" in listener.reason()
    finally:
        listener.uninstall()
    assert signal.getsignal(signal.SIGTERM) is prev


def test_preemption_deadline():
    listener = PreemptionListener(signals=(), deadline_secs=0.05)
    with listener:
        assert not listener.preempted() or True  # may legally be False yet
        time.sleep(0.06)
        assert listener.should_stop()
        assert listener.reason() == "deadline"


# ---------------------------------------------------------------------------
# commit protocol + restore fallback (no model compile: minimal state)
# ---------------------------------------------------------------------------

class _State:
    """Minimal TrainState-like object for CheckpointManager."""

    def __init__(self, v: float):
        self.step = int(v)
        self.params = {"w": np.full(256, float(v), np.float32)}
        self.batch_stats = {}
        self.opt_state = {}

    def replace(self, **kw):
        out = _State(0)
        out.__dict__.update(self.__dict__)
        out.__dict__.update(kw)
        return out


def _fill(state) -> float:
    return float(np.asarray(state.params["w"])[0])


def test_commit_protocol_manifest_and_no_staging(tmp_path):
    d = str(tmp_path / "c")
    m = CheckpointManager(d, async_save=False)
    m.save(1, _State(1))
    assert m.all_steps() == [1]
    # committed layout: bare-numeric dir, verified manifest, no staging left
    assert manifest_status(os.path.join(d, "1")) == ("ok", "")
    assert not [n for n in os.listdir(d) if n.startswith("_staging")]
    # the evaluator's poll primitive sees the committed step...
    assert wait_for_new_checkpoint(d, None, timeout_secs=0.0) == 1
    # ...but never a staging dir
    os.makedirs(os.path.join(d, "_staging.9"))
    assert wait_for_new_checkpoint(d, 1, timeout_secs=0.0) is None
    m.close()


def test_torn_latest_falls_back_to_previous_valid(tmp_path):
    d = str(tmp_path / "c")
    m = CheckpointManager(d, async_save=False)
    for s in (1, 2, 3):
        m.save(s, _State(s))
    faultinject.corrupt_checkpoint(d, mode="truncate")  # tears step 3
    st, step = m.restore(_State(0))
    assert step == 2 and _fill(st) == 2.0
    # the damaged dir is quarantined so a re-trained step 3 can commit
    assert committed_steps(d) == [1, 2]
    assert os.path.isdir(os.path.join(d, "3.corrupt"))
    m.save(3, _State(33))  # re-commit after rollback must not be blocked
    st, step = m.restore(_State(0))
    assert step == 3 and _fill(st) == 33.0
    m.close()


def test_bitflip_detected_by_checksum(tmp_path):
    """Same size, one byte flipped — only the SHA-256 can catch this."""
    d = str(tmp_path / "c")
    m = CheckpointManager(d, async_save=False)
    m.save(1, _State(1))
    m.save(2, _State(2))
    faultinject.corrupt_checkpoint(d, step=2, mode="flip")
    status, detail = manifest_status(os.path.join(d, "2"))
    assert status == "bad" and "checksum" in detail
    st, step = m.restore(_State(0))
    assert step == 1 and _fill(st) == 1.0
    m.close()


def test_explicitly_requested_corrupt_step_raises(tmp_path):
    d = str(tmp_path / "c")
    m = CheckpointManager(d, async_save=False)
    m.save(1, _State(1))
    m.save(2, _State(2))
    faultinject.corrupt_checkpoint(d, step=2, mode="truncate")
    with pytest.raises(CheckpointCorrupt):
        m.restore(_State(0), step=2)
    m.close()


def test_all_checkpoints_corrupt_refuses_fresh_start(tmp_path):
    d = str(tmp_path / "c")
    m = CheckpointManager(d, async_save=False)
    m.save(1, _State(1))
    m.save(2, _State(2))
    faultinject.corrupt_checkpoint(d, step=1, mode="flip")
    faultinject.corrupt_checkpoint(d, step=2, mode="truncate")
    with pytest.raises(CheckpointCorrupt, match="refusing"):
        m.restore(_State(0))
    m.close()


def test_legacy_checkpoint_without_manifest_restores(tmp_path):
    d = str(tmp_path / "c")
    m = CheckpointManager(d, async_save=False)
    m.save(1, _State(1))
    os.remove(os.path.join(d, "1", "MANIFEST.json"))
    m2 = CheckpointManager(d, async_save=False)
    st, step = m2.restore(_State(0))
    assert step == 1 and _fill(st) == 1.0
    m.close(); m2.close()


def test_async_save_commits_retains_and_sweeps(tmp_path):
    d = str(tmp_path / "c")
    os.makedirs(os.path.join(d, "_staging.7"))  # crashed-writer leftover
    m = CheckpointManager(d, async_save=True, max_to_keep=2)
    assert not os.path.isdir(os.path.join(d, "_staging.7"))  # swept at init
    for s in (1, 2, 3):
        m.save(s, _State(s))
    m.wait_until_finished()
    assert m.all_steps() == [2, 3]  # retention applied
    st, step = m.restore(_State(0))
    assert step == 3 and _fill(st) == 3.0
    m.close()


# ---------------------------------------------------------------------------
# NaN sentinel (real Trainer, logistic model for compile speed)
# ---------------------------------------------------------------------------

def _tiny_cfg(tmp_path):
    cfg = get_preset("smoke")
    cfg.model.name = "logistic"
    cfg.model.input_size = 192  # 8*8*3
    cfg.model.hidden_units = 32
    cfg.model.num_classes = 4
    cfg.model.compute_dtype = "float32"
    cfg.data.image_size = 8
    cfg.train.batch_size = 16
    cfg.train.log_every_steps = 1
    cfg.optimizer.schedule = "constant"
    cfg.optimizer.learning_rate = 0.05
    cfg.log_root = str(tmp_path)
    cfg.checkpoint.directory = os.path.join(str(tmp_path), "ckpt")
    cfg.checkpoint.async_save = False
    return cfg


def test_nan_guard_checks_grad_norm_too():
    h = NanGuardHook(every_steps=1)
    h(1, None, {"loss": 1.0, "grad_norm": 2.0})  # finite: no raise
    with pytest.raises(NanGuardHook.NanLossError, match="grad_norm"):
        h(2, None, {"loss": 1.0, "grad_norm": float("inf")})


def test_nan_sentinel_rolls_back_backs_off_and_recovers(tmp_path):
    cfg = _tiny_cfg(tmp_path)
    tr = Trainer(cfg)
    tr.init_state()
    mngr = CheckpointManager(cfg.checkpoint.directory, async_save=False)
    state, _ = tr.train(learnable_synthetic_iterator(16, 8, 4), num_steps=5)
    mngr.save(5, state)
    base_lr = float(tr.schedule(0))

    def factory(attempt):
        if attempt == 0:  # 3rd batch after resume (step 8) goes NaN
            return faultinject.inject_nan(
                learnable_synthetic_iterator(16, 8, 4, seed=1), at_batch=3)
        return learnable_synthetic_iterator(16, 8, 4, seed=10 + attempt)

    guard = NanGuardHook(every_steps=1)
    state, metrics = train_with_nan_recovery(
        tr, mngr, factory, num_steps=20, hooks=(guard,), start_step=5,
        max_strikes=2, lr_backoff=0.5)
    # the run converged to the target step despite the injected NaN...
    assert int(state.step) == 20
    assert np.isfinite(float(metrics["loss"]))
    import jax
    leaves = [np.asarray(l) for l in jax.tree_util.tree_leaves(state.params)]
    assert all(np.isfinite(l).all() for l in leaves)
    # ...after exactly one rollback with the LR backed off 0.5x
    assert float(tr.schedule(0)) == pytest.approx(0.5 * base_lr)
    mngr.close()


def test_nan_sentinel_gives_up_after_max_strikes(tmp_path):
    cfg = _tiny_cfg(tmp_path)
    tr = Trainer(cfg)
    tr.init_state()
    mngr = CheckpointManager(cfg.checkpoint.directory, async_save=False)

    def factory(attempt):  # every attempt is poisoned immediately
        return faultinject.inject_nan(
            learnable_synthetic_iterator(16, 8, 4, seed=attempt), at_batch=1)

    guard = NanGuardHook(every_steps=1)
    with pytest.raises(TooManyNanRetries):
        train_with_nan_recovery(tr, mngr, factory, num_steps=10,
                                hooks=(guard,), max_strikes=2, lr_backoff=0.5)
    mngr.close()


# ---------------------------------------------------------------------------
# stop_fn + run_train preemption wiring
# ---------------------------------------------------------------------------

def test_trainer_stop_fn_stops_at_step_boundary(tmp_path):
    cfg = _tiny_cfg(tmp_path)
    tr = Trainer(cfg)
    tr.init_state()
    seen = []

    def hook(step, state, metrics):
        seen.append(step)

    state, _ = tr.train(learnable_synthetic_iterator(16, 8, 4),
                        num_steps=50, hooks=(hook,),
                        stop_fn=lambda: len(seen) >= 3)
    assert int(state.step) == 3
    assert seen == [1, 2, 3]  # no extra steps after the stop


def test_run_train_deadline_preempts_commits_and_resumes(tmp_path):
    """The in-process analog of a maintenance-window preemption: run_train
    under a deadline stops at a step boundary, commits a checkpoint, and
    raises Preempted; a relaunch resumes from exactly that step."""
    from distributed_resnet_tensorflow_tpu.main import run_train
    cfg = _tiny_cfg(tmp_path)
    cfg.train.train_steps = 100000  # unbounded-ish: only the deadline stops it
    cfg.checkpoint.save_every_steps = 100000  # no cadence save before preempt
    cfg.checkpoint.save_every_secs = 0.0
    cfg.resilience.deadline_secs = 1.0  # elapses during/after compile
    with pytest.raises(Preempted):
        run_train(cfg)
    steps = committed_steps(cfg.checkpoint.directory)
    assert steps, "preemption must commit a checkpoint even off-cadence"
    assert manifest_status(
        os.path.join(cfg.checkpoint.directory, str(steps[-1])))[0] == "ok"

    cfg2 = _tiny_cfg(tmp_path)
    cfg2.train.train_steps = steps[-1] + 5
    cfg2.resilience.deadline_secs = 0.0
    state, _ = run_train(cfg2)
    assert int(state.step) == steps[-1] + 5


@pytest.mark.slow  # re-tiered out of the 870s tier-1; runs in the full suite and chaos_smoke.sh default mode
def test_evaluator_skips_damaged_checkpoint(tmp_path):
    """A long-running polling evaluator must skip a checkpoint that gets
    damaged (or quarantined/reaped) between poll and restore, not die —
    that damage is exactly what the resilience layer exists to survive."""
    from distributed_resnet_tensorflow_tpu.evaluator import Evaluator
    cfg = _tiny_cfg(tmp_path)
    cfg.eval.eval_batch_count = 1
    tr = Trainer(cfg)
    tr.init_state()
    mngr = CheckpointManager(cfg.checkpoint.directory, async_save=False)
    state, _ = tr.train(learnable_synthetic_iterator(16, 8, 4), num_steps=2)
    mngr.save(2, state)
    mngr.close()
    faultinject.corrupt_checkpoint(cfg.checkpoint.directory, step=2,
                                   mode="flip")
    ev = Evaluator(cfg, data_iter=learnable_synthetic_iterator(16, 8, 4))
    out = ev.run(timeout_secs=0.0)  # must not raise
    assert out == {}            # nothing evaluable existed...
    assert ev.last_step == 2    # ...but the damaged step was consumed/skipped


def test_evaluator_exits_nonzero_after_consecutive_failures(tmp_path):
    """eval.max_consecutive_failures: a checkpoint stream where EVERY step
    is damaged must end the evaluator with an error, not an infinite
    skip-and-poll loop (the single-skip tolerance above stays)."""
    from distributed_resnet_tensorflow_tpu.evaluator import Evaluator
    cfg = _tiny_cfg(tmp_path)
    cfg.eval.eval_batch_count = 1
    cfg.eval.max_consecutive_failures = 2
    tr = Trainer(cfg)
    tr.init_state()
    mngr = CheckpointManager(cfg.checkpoint.directory, async_save=False)
    state, _ = tr.train(learnable_synthetic_iterator(16, 8, 4), num_steps=2)
    ev = Evaluator(cfg, data_iter=learnable_synthetic_iterator(16, 8, 4))
    # the poller only surfaces the NEWEST checkpoint, so a broken stream is
    # one damaged step per poll: first poll skips (1/2), second must raise
    mngr.save(1, state)
    faultinject.corrupt_checkpoint(cfg.checkpoint.directory, step=1,
                                   mode="flip")
    assert ev.run(timeout_secs=0.0) == {}
    assert ev.consecutive_failures == 1
    mngr.save(2, state)
    faultinject.corrupt_checkpoint(cfg.checkpoint.directory, step=2,
                                   mode="flip")
    with pytest.raises(RuntimeError, match="consecutive"):
        ev.run(timeout_secs=0.0)
    mngr.close()


def test_evaluator_failure_count_resets_on_success(tmp_path):
    from distributed_resnet_tensorflow_tpu.evaluator import Evaluator
    cfg = _tiny_cfg(tmp_path)
    cfg.eval.eval_batch_count = 1
    cfg.eval.max_consecutive_failures = 2
    tr = Trainer(cfg)
    tr.init_state()
    mngr = CheckpointManager(cfg.checkpoint.directory, async_save=False)
    state, _ = tr.train(learnable_synthetic_iterator(16, 8, 4), num_steps=2)
    ev = Evaluator(cfg, data_iter=learnable_synthetic_iterator(16, 8, 4))
    # damaged, good, damaged: a success between failures must reset the
    # bound, so the second damaged step is 1/2 again — never a raise
    for s, damage in ((1, True), (2, False), (3, True)):
        mngr.save(s, state)
        if damage:
            faultinject.corrupt_checkpoint(cfg.checkpoint.directory, step=s,
                                           mode="flip")
        out = ev.run(timeout_secs=0.0)
        if not damage:
            assert out and "precision" in out
    mngr.close()
    assert ev.last_step == 3
    assert ev.consecutive_failures == 1


# ---------------------------------------------------------------------------
# watchdog fault cases (freeze / slow) — wrapper behavior; the detection
# logic itself is unit-tested in tests/test_watchdog.py
# ---------------------------------------------------------------------------

def test_inject_freeze_blocks_at_batch(monkeypatch):
    naps = []
    monkeypatch.setattr(faultinject.time, "sleep",
                        lambda s: naps.append(s))
    batches = [{"x": i} for i in range(4)]
    out = list(faultinject.inject_freeze(iter(batches), at_batch=3,
                                         freeze_secs=123.0))
    assert out == batches  # batches still flow once the nap ends (tests)
    assert naps == [123.0]


def test_inject_slow_delays_every_batch(monkeypatch):
    naps = []
    monkeypatch.setattr(faultinject.time, "sleep",
                        lambda s: naps.append(s))
    batches = [{"x": i} for i in range(3)]
    assert list(faultinject.inject_slow(iter(batches), 0.25)) == batches
    assert naps == [0.25, 0.25, 0.25]


def test_env_fault_scoping_targets_one_process(monkeypatch):
    """DRT_FAULT_FREEZE_AT_BATCH="1:5" must arm only on process 1 — the
    launcher hands every child the same environment."""
    naps = []
    monkeypatch.setattr(faultinject.time, "sleep",
                        lambda s: naps.append(s))
    monkeypatch.setattr(faultinject, "_freeze_armed", False)
    import jax
    batches = [{"x": i} for i in range(6)]
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    it = faultinject.maybe_wrap_from_env(
        iter(batches), env={faultinject.FREEZE_ENV_VAR: "1:5"})
    assert list(it) == batches and naps == []  # not our process: untouched
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    it = faultinject.maybe_wrap_from_env(
        iter(batches), env={faultinject.FREEZE_ENV_VAR: "1:5"})
    assert list(it) == batches
    assert len(naps) == 1  # froze once, before batch 5
    # a rebuilt stream (NaN-sentinel rollback) must NOT re-freeze: one
    # injected wedge would otherwise recur at batch 5 of every replay
    it = faultinject.maybe_wrap_from_env(
        iter(batches), env={faultinject.FREEZE_ENV_VAR: "1:5"})
    assert list(it) == batches and len(naps) == 1


def test_env_slow_fault_unscoped_applies_everywhere(monkeypatch):
    naps = []
    monkeypatch.setattr(faultinject.time, "sleep",
                        lambda s: naps.append(s))
    batches = [{"x": i} for i in range(3)]
    it = faultinject.maybe_wrap_from_env(
        iter(batches), env={faultinject.SLOW_ENV_VAR: "0.1"})
    assert list(it) == batches
    assert naps == [0.1, 0.1, 0.1]


def test_env_slow_fault_late_onset_form(monkeypatch):
    """``S@N`` delays only from batch N on — the healthy-baseline-then-
    slow-regime shape the perf-anomaly sentinel detects (ISSUE 14)."""
    naps = []
    monkeypatch.setattr(faultinject.time, "sleep",
                        lambda s: naps.append(s))
    batches = [{"x": i} for i in range(5)]
    it = faultinject.maybe_wrap_from_env(
        iter(batches), env={faultinject.SLOW_ENV_VAR: "0.2@4"})
    assert list(it) == batches
    assert naps == [0.2, 0.2]  # batches 4 and 5 only
    assert faultinject._parse_slow("0.5") == (0.5, 1)
    assert faultinject._parse_slow("0.5@12") == (0.5, 12)
    with pytest.raises(ValueError):
        faultinject._parse_slow("junk@3")
    with pytest.raises(ValueError):
        faultinject._parse_slow("0.5@0")  # from_batch is 1-based
    # malformed values disarm loudly instead of crashing the run
    naps.clear()
    it = faultinject.maybe_wrap_from_env(
        iter(batches), env={faultinject.SLOW_ENV_VAR: "oops"})
    assert list(it) == batches and naps == []


def test_env_nan_injection_hook(monkeypatch):
    batches = [{"images": np.ones((2, 2), np.float32),
                "labels": np.zeros((2,), np.int32)} for _ in range(3)]
    monkeypatch.setenv(faultinject.NAN_ENV_VAR, "2")
    monkeypatch.setattr(faultinject, "_nan_armed", False)
    wrapped = faultinject.maybe_wrap_from_env(iter(batches))
    out = [next(wrapped) for _ in range(3)]
    assert np.isfinite(out[0]["images"]).all()
    assert np.isnan(out[1]["images"]).all()
    assert np.isfinite(out[2]["images"]).all()
    # second wrap in the same process stays clean (sentinel retry contract)
    wrapped2 = faultinject.maybe_wrap_from_env(iter(batches))
    assert all(np.isfinite(next(wrapped2)["images"]).all() for _ in range(3))


# ---------------------------------------------------------------------------
# launch.py supervisor policy (fast, fake children)
# ---------------------------------------------------------------------------

class _FakeChild:
    """Popen stand-in: exits with ``code`` once ``after_secs`` elapse (never,
    when None); dies to any signal the supervisor sends."""

    def __init__(self, code=None, after_secs=0.0):
        self._code = code
        self._deadline = time.monotonic() + after_secs
        self.returncode = None
        self.signals = []

    def poll(self):
        if self.returncode is None and self._code is not None and \
                time.monotonic() >= self._deadline:
            self.returncode = self._code
        return self.returncode

    def send_signal(self, sig):
        self.signals.append(sig)
        self.returncode = -sig

    def kill(self):
        self.send_signal(signal.SIGKILL)

    def wait(self, timeout=None):
        if self.poll() is None:
            raise subprocess.TimeoutExpired("fake", timeout)
        return self.returncode


def _supervise(monkeypatch, children, **kw):
    from distributed_resnet_tensorflow_tpu import launch
    monkeypatch.setattr(launch, "_spawn",
                        lambda *a, **k: list(children))
    return launch.launch_local(len(children), [], poll_secs=0.01, **kw)


def test_supervisor_clean_first_exit_spares_slow_sibling(monkeypatch):
    """A slower sibling after a CLEAN exit is a healthy run finishing at
    different speeds (final checkpoint drain) — it must not be torn down
    inside child_grace_secs, and the run must report success."""
    fast = _FakeChild(code=0)
    slow = _FakeChild(code=0, after_secs=0.5)
    rc = _supervise(monkeypatch, [fast, slow], child_grace_secs=0.1)
    assert rc == 0
    assert slow.signals == []     # outlived 5x the bad-exit grace unharmed


def test_supervisor_bad_first_exit_tears_down_and_reports_failure(monkeypatch):
    """A NONZERO exit arms the short countdown: the wedged sibling is
    SIGTERMed after child_grace_secs and the child's real failure code
    wins the aggregation (never masked as resumable)."""
    dead = _FakeChild(code=1)
    wedged = _FakeChild()         # never exits on its own
    rc = _supervise(monkeypatch, [dead, wedged], child_grace_secs=0.1)
    assert rc == 1
    assert signal.SIGTERM in wedged.signals


def test_supervisor_resumable_first_exit_spares_draining_sibling(monkeypatch):
    """Exit 75 is a deliberate resumable departure (fleet-wide preemption):
    a sibling still draining its preemption checkpoint must not be torn
    down inside child_grace_secs — that would tear the very save the
    grace exists to protect."""
    fast = _FakeChild(code=RESUMABLE_EXIT_CODE)
    slow = _FakeChild(code=RESUMABLE_EXIT_CODE, after_secs=0.5)
    rc = _supervise(monkeypatch, [fast, slow], child_grace_secs=0.1)
    assert rc == RESUMABLE_EXIT_CODE
    assert slow.signals == []


def test_aggregate_rc_forced_childs_own_failure_not_masked():
    """A torn-down child that still exits with its OWN positive non-75
    code crashed for real — it must win the aggregation, or a
    deterministically-broken job requeues until MAX_REQUEUES."""
    from distributed_resnet_tensorflow_tpu.launch import _aggregate_rc
    assert _aggregate_rc([1, 2], forced={1}) == 1    # first real failure
    assert _aggregate_rc([75, 1], forced={1}) == 1   # not masked as 75
    assert _aggregate_rc([0, -15], forced={1}) == RESUMABLE_EXIT_CODE
    assert _aggregate_rc([0, 75], forced={1}) == RESUMABLE_EXIT_CODE


def test_supervisor_signal_death_is_resumable(monkeypatch):
    """A child killed by a signal (host loss / OOM shape) arms teardown and
    aggregates to 75: requeue-and-resume, not failure."""
    killed = _FakeChild(code=-signal.SIGKILL)
    wedged = _FakeChild()
    rc = _supervise(monkeypatch, [killed, wedged], child_grace_secs=0.1)
    assert rc == RESUMABLE_EXIT_CODE
    assert signal.SIGTERM in wedged.signals


# ---------------------------------------------------------------------------
# watchdog end-to-end: real 2-process SPMD worlds under launch.py
# ---------------------------------------------------------------------------

def _watchdog_launch_args(tmp_path, train_steps, *extra):
    return [
        "--preset", "smoke",
        "--set", "model.name=logistic",
        "--set", "model.input_size=192",
        "--set", "model.num_classes=10",
        "--set", "data.image_size=8",
        "--set", "train.batch_size=16",
        "--set", f"train.train_steps={train_steps}",
        "--set", "train.log_every_steps=1000",
        "--set", f"log_root={tmp_path}",
        "--set", "checkpoint.save_every_steps=0",
        "--set", "checkpoint.save_every_secs=0",
        "--set", "resilience.watchdog.enabled=on",
        "--set", "resilience.watchdog.interval_secs=0.2",
        "--set", "resilience.watchdog.peer_timeout_secs=3",
        "--set", "resilience.watchdog.grace_secs=1",
        "--set", "resilience.watchdog.min_step_timeout_secs=120",
        "--set", "resilience.watchdog.straggler_window_secs=1",
        *extra,
    ]


def _metric_events(tmp_path, sub="train"):
    path = os.path.join(str(tmp_path), sub, "metrics.jsonl")
    try:
        with open(path) as f:
            return [json.loads(l) for l in f if l.strip()]
    except FileNotFoundError:
        return []


@pytest.mark.slow  # re-tiered out of the 870s tier-1; runs in the full suite and chaos_smoke.sh default mode
@pytest.mark.heavy
def test_watchdog_kill_and_detect_survivor_exits_resumable(tmp_path):
    """THE acceptance scenario: SIGKILL one of two launch.py workers
    mid-training. Without the watchdog the survivor blocks in the next
    collective until the allocation's wall clock; with it, the survivor
    must exit 75 within the configured detection deadline, the supervisor
    must reap everything, and the chief's metrics must record the peer
    loss."""
    import socket
    import threading

    from distributed_resnet_tensorflow_tpu.launch import launch_local

    s = socket.socket(); s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]; s.close()
    procs = []
    result = {}

    def run():
        result["rc"] = launch_local(
            2, _watchdog_launch_args(tmp_path, 1_000_000),
            devices_per_process=1, port=port, procs_out=procs)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    # wait for REAL training progress on both processes (beats flowing)
    hb_dir = os.path.join(str(tmp_path), "heartbeats")
    deadline = time.time() + 300
    started = False
    while time.time() < deadline:
        beats = []
        for pid in (0, 1):
            try:
                with open(os.path.join(hb_dir, f"proc{pid}.json")) as f:
                    beats.append(json.load(f))
            except (OSError, ValueError):
                break
        if len(beats) == 2 and all(b["step"] >= 3 for b in beats):
            started = True
            break
        if result.get("rc") is not None:
            raise AssertionError(
                f"launcher exited rc={result['rc']} before the kill")
        time.sleep(0.1)
    assert started, "2-process training never started beating"

    victim = procs[1]          # the NON-chief worker (chief keeps metrics)
    victim.send_signal(signal.SIGKILL)
    killed_at = time.monotonic()
    # peer_timeout(3) + grace(1) + collective/teardown slack — well under
    # the launcher's 30s sibling grace, so the SURVIVOR's own watchdog
    # (not the supervisor's SIGTERM) must be what ends it
    t.join(timeout=60)
    assert not t.is_alive(), "launcher still waiting: survivor hung"
    detect_secs = time.monotonic() - killed_at
    assert result["rc"] == RESUMABLE_EXIT_CODE, result
    # the supervisor reaped both children
    assert all(p.poll() is not None for p in procs)
    assert detect_secs < 45, f"teardown took {detect_secs:.0f}s"
    # chief (the survivor) recorded the detection before exiting
    events = {r.get("event") for r in _metric_events(tmp_path)}
    assert "peer_lost" in events, sorted(e for e in events if e)
    assert "watchdog_exit" in events


@pytest.mark.slow  # re-tiered out of the 870s tier-1; runs in the full suite and chaos_smoke.sh default mode
@pytest.mark.heavy
def test_watchdog_normal_run_emits_heartbeat_and_straggler_rows(tmp_path):
    """A healthy 2-process run with the watchdog on: completes cleanly
    (no spurious teardown) AND leaves heartbeat + straggler accounting
    rows in the chief's metrics.jsonl."""
    import socket

    from distributed_resnet_tensorflow_tpu.launch import launch_local

    s = socket.socket(); s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]; s.close()
    rc = launch_local(
        2,
        # 600 steps ≈ several seconds of steady-state beating, so the 1s
        # accounting windows fill and export on any host speed
        _watchdog_launch_args(tmp_path, 600),
        devices_per_process=1, port=port)
    assert rc == 0
    rows = _metric_events(tmp_path)
    events = [r for r in rows if "event" in r]
    kinds = {r["event"] for r in events}
    assert "heartbeat" in kinds, sorted(kinds)
    assert "straggler" in kinds, sorted(kinds)
    hb = [r for r in events if r["event"] == "heartbeat"][-1]
    assert set(hb["hosts"]) == {"0", "1"}
    strag = [r for r in events if r["event"] == "straggler"][-1]
    assert set(strag["rates"]) <= {"0", "1"}
    # and no teardown events on a healthy run
    assert not kinds & {"peer_lost", "hang", "watchdog_exit", "peer_failed"}


# ---------------------------------------------------------------------------
# kill-and-resume: SIGTERM a real main.py run mid-way (satellite)
# ---------------------------------------------------------------------------

@pytest.mark.slow  # re-tiered out of the 870s tier-1; runs in the full suite and chaos_smoke.sh default mode
@pytest.mark.heavy
def test_sigterm_kill_and_resume_exact_continuation(tmp_path):
    """SIGTERM a live trainer: it must exit with the resumable code (75)
    leaving a committed checkpoint at its stop step; the relaunch must reach
    the target with a contiguous, monotonic metrics stream — no duplicated
    or skipped steps across the preemption boundary."""
    from distributed_resnet_tensorflow_tpu.utils.virtual_devices import (
        virtual_cpu_env)

    ckpt_dir = os.path.join(str(tmp_path), "ckpt")
    args = [
        sys.executable, "-m", "distributed_resnet_tensorflow_tpu.main",
        "--preset", "smoke",
        "--set", "model.name=logistic",
        "--set", "model.input_size=192",
        "--set", "model.hidden_units=800",  # slow the step a little
        "--set", "model.num_classes=10",
        "--set", "data.image_size=8",
        "--set", "train.batch_size=8",
        "--set", "train.log_every_steps=1000",
        "--set", "train.summary_every_steps=1",  # JSONL row per step
        "--set", f"log_root={tmp_path}",
        "--set", "checkpoint.save_every_steps=100000",  # only preempt saves
        "--set", "checkpoint.save_every_secs=0",
    ]
    env = virtual_cpu_env(1)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    jsonl = os.path.join(str(tmp_path), "train", "metrics.jsonl")

    def metric_steps():
        # scalar rows only: typed {"event": ...} records (input_stages
        # telemetry) share the step key and would double-count steps
        try:
            with open(jsonl) as f:
                return [r["step"]
                        for r in (json.loads(l) for l in f if l.strip())
                        if "event" not in r]
        except FileNotFoundError:
            return []

    # run 1: unbounded-ish; SIGTERM once a few steps are on record
    p = subprocess.Popen(args + ["--set", "train.train_steps=1000000"],
                         env=env, cwd=repo,
                         stdout=subprocess.DEVNULL,
                         stderr=subprocess.DEVNULL)
    try:
        deadline = time.time() + 300
        while time.time() < deadline:
            if len(metric_steps()) >= 3:
                break
            if p.poll() is not None:
                raise AssertionError("trainer exited before it was killed")
            time.sleep(0.1)
        else:
            raise AssertionError("no metrics appeared before the deadline")
        p.send_signal(signal.SIGTERM)
        rc = p.wait(timeout=120)
    finally:
        if p.poll() is None:
            p.kill()
    assert rc == RESUMABLE_EXIT_CODE, rc  # the launcher contract

    steps = committed_steps(ckpt_dir)
    assert steps, "graceful preemption must leave a committed checkpoint"
    preempt = steps[-1]
    rows_run1 = metric_steps()
    # the checkpoint is at the exact last finished (and logged) step, and
    # it passes verification — committed, not torn
    assert preempt == rows_run1[-1], (preempt, rows_run1[-6:])
    assert manifest_status(os.path.join(ckpt_dir, str(preempt)))[0] == "ok"

    # run 2: resume to a bounded target
    target = preempt + 15
    rc2 = subprocess.run(
        args + ["--set", f"train.train_steps={target}"], env=env, cwd=repo,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        timeout=600).returncode
    assert rc2 == 0
    all_rows = metric_steps()
    resumed = all_rows[len(rows_run1):]
    # exact continuation: preempt+1 ... target, nothing skipped or repeated
    assert resumed == list(range(preempt + 1, target + 1)), resumed[:5]
    # and the combined stream is strictly monotonic across the boundary
    assert all_rows == sorted(set(all_rows)), "metrics stream not monotonic"
