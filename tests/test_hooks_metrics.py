"""Hooks + metrics writer tests (reference observability, SURVEY.md §2.15)."""
import os

import numpy as np

from distributed_resnet_tensorflow_tpu.train.hooks import (
    CheckpointHook, LoggingHook, SummaryHook)
from distributed_resnet_tensorflow_tpu.utils.metrics import (
    MetricsWriter, Throughput, read_metrics)


def test_metrics_writer_jsonl_roundtrip(tmp_path):
    w = MetricsWriter(str(tmp_path), enable_tensorboard=False)
    w.write_scalars(10, {"loss": 1.5, "precision": 0.5})
    w.write_scalars(20, {"loss": 1.0, "precision": 0.7})
    w.close()
    recs = read_metrics(str(tmp_path))
    assert len(recs) == 2
    assert recs[0]["step"] == 10 and recs[0]["loss"] == 1.5
    assert recs[1]["precision"] == 0.7


def test_metrics_writer_tensorboard(tmp_path):
    w = MetricsWriter(str(tmp_path), enable_tensorboard=True)
    w.write_scalars(1, {"loss": 2.0})
    w.close()
    # tensorboardX event file written alongside the jsonl
    assert any(f.startswith("events") for f in os.listdir(tmp_path))


def test_logging_hook_cadence():
    lines = []
    h = LoggingHook(every_steps=10, batch_size=128, print_fn=lines.append)
    m = {"loss": np.float32(1.0), "precision": np.float32(0.5),
         "learning_rate": np.float32(0.1)}
    for step in range(1, 31):
        h(step, None, m)
    assert len(lines) == 3
    assert "step 10" in lines[0] and "loss 1.0000" in lines[0]
    # throughput appears once a window exists
    assert "stp/s" in lines[1]


def test_summary_hook_cadence(tmp_path):
    w = MetricsWriter(str(tmp_path), enable_tensorboard=False)
    h = SummaryHook(w, every_steps=5)
    for step in range(1, 11):
        h(step, None, {"loss": float(step)})
    w.close()
    recs = read_metrics(str(tmp_path))
    assert [r["step"] for r in recs] == [5, 10]


def test_throughput_meter():
    t = Throughput(batch_size=64)
    assert t.update(0) == {}
    import time
    time.sleep(0.01)
    out = t.update(10)
    assert out["steps_per_sec"] > 0
    assert np.isclose(out["images_per_sec"], out["steps_per_sec"] * 64)


def test_checkpoint_hook_delegates(tmp_path):
    calls = []

    class FakeMngr:
        def maybe_save(self, step, state):
            calls.append(step)

    h = CheckpointHook(FakeMngr())
    h(7, "state", {})
    assert calls == [7]


def test_nan_guard_hook():
    import pytest
    from distributed_resnet_tensorflow_tpu.train.hooks import NanGuardHook
    h = NanGuardHook(every_steps=10)
    h(10, None, {"loss": 1.0})           # fine
    h(5, None, {"loss": float("nan")})   # off-cadence: not checked
    with pytest.raises(NanGuardHook.NanLossError):
        h(20, None, {"loss": float("nan")})
    seen = []
    h2 = NanGuardHook(every_steps=1, on_nan=lambda s, m: seen.append(s))
    h2(3, None, {"loss": float("inf")})
    assert seen == [3]


def test_write_images(tmp_path):
    w = MetricsWriter(str(tmp_path), enable_tensorboard=True)
    w.write_images(1, "inputs", np.random.rand(2, 8, 8, 3).astype(np.float32))
    w.close()
    assert any(f.startswith("events") for f in os.listdir(tmp_path))


def test_cadence_crossing_with_fused_loops():
    """Hooks observing only loop-end steps (k=3) must still fire when the
    cadence (10) is crossed, even though 10 % 3 != 0."""
    from distributed_resnet_tensorflow_tpu.train.hooks import cadence_crossed
    fired = []
    last = 0
    for step in range(3, 100, 3):   # loop-end steps 3,6,9,12,...
        if cadence_crossed(step, 10, last):
            fired.append(step)
            last = step
    assert fired == [12, 21, 30, 42, 51, 60, 72, 81, 90]

    lines = []
    h = LoggingHook(every_steps=10, print_fn=lines.append)
    for step in range(3, 31, 3):
        h(step, None, {"loss": 1.0})
    assert len(lines) == 3  # crossed 10, 20, 30


def test_checkpoint_manager_crossing_cadence(tmp_path):
    from distributed_resnet_tensorflow_tpu.checkpoint import CheckpointManager
    m = CheckpointManager(str(tmp_path / "x"), save_every_steps=10,
                          save_every_secs=0.0, async_save=False)
    assert not m.should_save(3)
    assert m.should_save(12)          # crossed 10
    m._last_save_step = 12            # as save() would set
    assert not m.should_save(18)
    assert m.should_save(21)          # crossed 20
    m.close()
