"""bare-assert: runtime invariants in package code must not be ``assert``.

``python -O`` strips assert statements, so an invariant guarded by one
silently vanishes in optimized deployments — PR 1 converted the imagenet
drain invariant to a RuntimeError for exactly this reason. This rule flags
every ``assert`` in package (non-test) code; tests are free to assert
(that is what they are for), and the rare intentional debug-only assert
can carry ``# shardcheck: ok(bare-assert)``.
"""
from __future__ import annotations

import ast
from typing import Iterable

from ..report import Finding

RULE_NAME = "bare-assert"
DOC = __doc__


def check(ctx) -> Iterable[Finding]:
    # package files only: tests/ are not scanned by the driver, and
    # repo-top driver glue (__graft_entry__.py, bench.py) asserts on its
    # own argv contracts, which die loudly either way
    for sf in ctx.package_py:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Assert):
                yield Finding(
                    RULE_NAME, sf.rel, node.lineno,
                    "bare assert guards a runtime invariant — it vanishes "
                    "under python -O; raise RuntimeError/ValueError instead")
