"""Switch MoE tests (models/moe.py) — routing/capacity semantics, expert-axis
sharding equivalence, and the Trainer integration with the aux loss."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_resnet_tensorflow_tpu.models.moe import SwitchMlp
from distributed_resnet_tensorflow_tpu.parallel import create_mesh
from distributed_resnet_tensorflow_tpu.utils.config import MeshConfig, get_preset


def _mesh(**axes):
    return create_mesh(MeshConfig(**axes))


def test_single_expert_equals_plain_mlp():
    """E=1 with ample capacity routes every token to the one expert with
    gate 1.0 (softmax over one logit), so SwitchMlp == its MLP."""
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 8, 16).astype(np.float32))
    moe = SwitchMlp(num_experts=1, capacity_factor=1.0, dtype=jnp.float32)
    variables = moe.init(jax.random.PRNGKey(0), x)
    got = moe.apply(variables, x)

    p = variables["params"]
    import flax.linen as nn
    h = x @ p["w1"][0] + p["bias1"][0]
    want = nn.gelu(h) @ p["w2"][0] + p["bias2"][0]
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_capacity_drop_zeroes_overflow_tokens():
    """capacity 1 with all tokens routed to one expert: exactly one token
    gets expert output; the rest fall through with zero MLP contribution."""
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(1, 6, 8).astype(np.float32))
    moe = SwitchMlp(num_experts=2, capacity_factor=0.17,  # cap = 1
                    dtype=jnp.float32)
    variables = moe.init(jax.random.PRNGKey(0), x)
    # force all tokens to expert 0 via a large router bias
    params = jax.tree_util.tree_map(lambda v: v, variables["params"])
    params["router"]["bias"] = jnp.asarray([100.0, -100.0])
    out = np.asarray(moe.apply({"params": params}, x))
    nonzero_tokens = (np.abs(out[0]).sum(-1) > 1e-6).sum()
    assert nonzero_tokens == 1  # one slot of capacity, rest dropped


def test_expert_sharded_matches_unsharded():
    """expert axis sharding is numerically invisible: same outputs with the
    stacked expert weights sharded over `expert` (+ data-sharded batch)."""
    from distributed_resnet_tensorflow_tpu.parallel.sharding import (
        tree_param_shardings)
    mesh = _mesh(data=2, expert=4)
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(4, 8, 16).astype(np.float32))
    plain = SwitchMlp(num_experts=4, dtype=jnp.float32)
    sharded = SwitchMlp(num_experts=4, dtype=jnp.float32, mesh=mesh)
    variables = plain.init(jax.random.PRNGKey(0), x)
    want = np.asarray(plain.apply(variables, x))

    shardings = tree_param_shardings(
        {"SwitchMlp_0": variables["params"]}, mesh)["SwitchMlp_0"]
    flat = {"/".join(str(p) for p in path): s for path, s in
            jax.tree_util.tree_flatten_with_path(shardings)[0]}
    assert any("expert" in str(s.spec) for n, s in flat.items() if "w1" in n)
    assert all("expert" not in str(s.spec)
               for n, s in flat.items() if "router" in n)

    sharded_params = jax.device_put(variables["params"], shardings)
    got = np.asarray(jax.jit(
        lambda p, x: sharded.apply({"params": p}, x))(sharded_params, x))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_moe_vit_trains_with_aux_loss():
    """ViT + Switch MoE over mesh.expert trains through the Trainer; the
    sown load-balancing loss makes loss > cross_entropy (wd off)."""
    from distributed_resnet_tensorflow_tpu.data import (
        learnable_synthetic_iterator)
    from distributed_resnet_tensorflow_tpu.train import Trainer
    cfg = get_preset("smoke")
    cfg.model.name = "vit"
    cfg.model.num_classes = 4
    cfg.model.compute_dtype = "float32"
    cfg.model.vit_dim = 32
    cfg.model.vit_depth = 2
    cfg.model.vit_heads = 2
    cfg.model.vit_num_experts = 4
    cfg.data.image_size = 8
    cfg.train.batch_size = 8
    cfg.mesh.data = 2
    cfg.mesh.expert = 4
    cfg.optimizer.weight_decay = 0.0
    tr = Trainer(cfg)
    tr.init_state()
    state, m = tr.train(learnable_synthetic_iterator(8, 8, 4), num_steps=2)
    assert int(state.step) == 2
    assert np.isfinite(float(m["loss"]))
    # Switch aux loss is >= 1 by Cauchy-Schwarz (E·Σ f_e·p_e ≥ 1 for any
    # routing), so with wd=0 loss must exceed plain cross-entropy
    assert float(m["loss"]) > float(m["cross_entropy"])


def test_expert_axis_requires_moe_model():
    from distributed_resnet_tensorflow_tpu.train import Trainer
    cfg = get_preset("smoke")
    cfg.model.name = "vit"
    cfg.mesh.data = 2
    cfg.mesh.expert = 4
    with pytest.raises(ValueError, match="vit_num_experts"):
        Trainer(cfg)
    cfg.model.vit_num_experts = 6  # not divisible by 4
    with pytest.raises(ValueError, match="divisible"):
        Trainer(cfg)
    # MoE x tensor parallelism is not composed: rejected, not replicated
    cfg2 = get_preset("smoke")
    cfg2.model.name = "vit"
    cfg2.model.vit_num_experts = 4
    cfg2.mesh.data = 4
    cfg2.mesh.tensor = 2
    with pytest.raises(ValueError, match="tensor"):
        Trainer(cfg2)
