"""What-if performance planner: ``main.py plan`` + the drift sentinel.

The repo owns both halves of an analytic cost model and this module
joins them (ROADMAP item 5): the committed static schedule
(``analysis/collective_schedules.json`` — ordered collectives with true
wire bytes per preset × layout × knob variant) says WHAT must move, and
the per-fabric bandwidth catalog (telemetry/bandwidth.py, fed by
``parallel/overlap.probe_comm_plan``) says how fast this fabric has
demonstrably moved it. On top ride a catalogued roofline compute term
and an abstract-state HBM occupancy model, so for any candidate the
planner predicts, WITHOUT running it:

  * per-step wall time   — compute (step FLOPs over an assumed-MFU
    roofline, or a measured step time when the caller has one) plus the
    EXPOSED communication: every scheduled collective costed as
    ``latency + bytes/bandwidth``, with the declared bucket plan's
    exchange earning overlap credit (it hides behind backprop up to
    ``OVERLAP_EFFICIENCY`` of the compute time — arXiv:1711.00705's
    premise, bench.py's overlap row its measurement),
  * per-device HBM watermark — sharded abstract train state + a gradient
    copy + an activation estimate + staging-ring occupancy, the same
    shapes ``analysis/elaborate.py`` validates (calibrated against the
    live ``memory`` rows by the drift sentinel), and
  * comm fraction        — exposed comm over the predicted step.

``main.py plan`` ranks the candidates and RECOMMENDS a layout; the
``plan-drift`` gate phase (analysis/plan_drift.py) re-runs the model
over the committed schedules with the baked-in REFERENCE constants and
commits the diffable ``analysis/plan_catalog.json``. Live runs arm a
:class:`DriftSentinel` (train/hooks.py PlanDriftHook): predicted vs
measured step time (heartbeat EWMA), comm seconds (``comm_timing``
probe) and HBM (``memory`` rows) — sustained divergence beyond
``telemetry.plan_tolerance`` emits a ``plan_drift`` row and a
flight-recorder dump. docs/planner.md is the operator manual.

Every number here is a MODEL, not a measurement: the constants below
are order-of-magnitude anchors chosen once and kept stable so the
committed catalog diffs only when a schedule or the model changes.
Predictions carry their assumptions (``bandwidth_source``) and the
sentinel exists precisely because models drift from reality.
"""
from __future__ import annotations

import argparse
import json
import logging
import math
import time
from typing import Callable, Dict, List, Optional, Tuple

log = logging.getLogger(__name__)

# -- reference constants (the deterministic side of the model) -----------
# Used for the committed plan_catalog.json so it is byte-identical on
# every machine; live predictions prefer the fabric's measured catalog.

#: conservative achieved collective bandwidth (wire bytes/sec) — the
#: order of a virtual-8 CPU psum and well under any real ICI link
REFERENCE_BYTES_PER_SEC = 4.0e8
#: fixed per-collective issue/latency cost
REFERENCE_LATENCY_SECS = 2.0e-4
#: per-device peak (bf16) the roofline compute term assumes — the v4
#: row of utils/profiling.TPU_PEAK_TFLOPS
REFERENCE_PEAK_TFLOPS = 275.0
#: assumed model FLOP utilization of that peak (a well-tuned ResNet/ViT
#: lands 0.3-0.5; docs/planner.md discusses sensitivity)
ASSUMED_MFU = 0.40
#: fraction of compute time the bucketed exchange can hide behind
#: (bench.py's overlap row measures the realized fraction)
OVERLAP_EFFICIENCY = 0.7
#: train-step FLOPs ≈ this × forward FLOPs (fwd + bwd ≈ 3×)
TRAIN_FLOPS_MULTIPLIER = 3.0
#: activation-footprint heuristic: fwd FLOPs per byte of live
#: activation memory (conv/attention stacks land within a small factor)
ACT_FLOPS_PER_BYTE = 50.0

#: schedule ops that can carry a gradient-exchange bucket's payload
#: (same set main.py comm-report matches on)
_EXCHANGE_OPS = ("psum", "psum_scatter")

#: staged (hierarchical) plans additionally issue an intra-tier
#: all-gather leg; only op-wire-ledger matching admits it (a forward
#: fsdp all-gather must never steal a flat bucket match)
_EXCHANGE_OPS_HIER = _EXCHANGE_OPS + ("all_gather",)

#: variants of the committed schedule the planner costs as knob
#: candidates (serve_* and reshard_* variants are not train steps)
PLAN_VARIANTS = ("train", "overlap", "overlap+zero1", "overlap+accum2",
                 "overlap+accum4", "overlap+hier", "bf16+compress")

#: bucket_mb candidates the startup autotune pass costs (the configured
#: value always joins the set)
TUNE_BUCKET_MB = (0.25, 1.0, 4.0, 16.0)

#: a probed tier bandwidth this many × the flat row's is a measurement
#: lie (the seeded-probe-lie tests): the tuner then distrusts the tier
#: rows and falls back to the flat plan, loudly
TUNE_SANITY_FACTOR = 100.0


def layout_label(mesh_cfg) -> str:
    """The catalog-style layout name ("dp", "dp_fsdp", "dp_pp_ep", ...)
    of a MeshConfig — the ``layout`` field of live ``plan`` rows, same
    vocabulary the committed schedule keys use."""
    parts = ["dp"]
    for attr, tag in (("fsdp", "fsdp"), ("tensor", "tp"),
                      ("pipeline", "pp"), ("sequence", "sp"),
                      ("expert", "ep")):
        if getattr(mesh_cfg, attr, 1) > 1:
            parts.append(tag)
    return "_".join(parts)


def _ring_scale(n: int) -> float:
    """Ring-allreduce wire-traffic factor 2(n-1)/n — how scheduled
    bytes (traced on the canonical 8-device mesh) scale to another
    device count."""
    n = max(2, int(n))
    return 2.0 * (n - 1) / n


# -- bandwidth -----------------------------------------------------------
class BandwidthTable:
    """Resolves a reduce-axis signature (``"data+fsdp"``) to
    ``(bytes_per_sec, latency_secs)``. Three sources, in the order a
    live prediction prefers them: a fresh probe snapshot, the fabric's
    persisted catalog, the baked-in reference row."""

    def __init__(self, source: str,
                 axes: Optional[Dict[str, Tuple[float, float]]] = None,
                 default_bps: float = REFERENCE_BYTES_PER_SEC,
                 default_latency: float = REFERENCE_LATENCY_SECS):
        self.source = source
        self.axes = axes or {}
        self.default_bps = float(default_bps)
        self.default_latency = float(default_latency)

    @classmethod
    def reference(cls) -> "BandwidthTable":
        return cls("reference")

    @classmethod
    def from_catalog(cls, doc: Optional[dict]) -> Optional["BandwidthTable"]:
        if not doc or not doc.get("axes"):
            return None
        axes = {}
        for sig, e in doc["axes"].items():
            bps = float(e.get("bytes_per_sec", 0.0))
            lat = float(e.get("latency_secs", 0.0))
            if bps > 0:
                axes[sig] = (bps, max(0.0, lat))
        if not axes:
            return None
        # the fallback for unprobed axis sets: the catalog's own median
        bps_all = sorted(v[0] for v in axes.values())
        lat_all = sorted(v[1] for v in axes.values())
        return cls("catalog", axes,
                   default_bps=bps_all[len(bps_all) // 2],
                   default_latency=lat_all[len(lat_all) // 2])

    @classmethod
    def from_probe(cls, snapshot: Optional[dict]
                   ) -> Optional["BandwidthTable"]:
        """A ``comm_timing`` snapshot/row as a table (bench.py's A/B
        legs predict against the probe they just ran)."""
        if not snapshot or not snapshot.get("buckets"):
            return None
        by_sig: Dict[str, Tuple[float, float]] = {}
        for b in snapshot["buckets"]:
            bps = float(b.get("wire_bytes_per_sec", 0.0))
            lat = float(b.get("probe_secs", 0.0))
            if bps <= 0:
                continue
            sig = b.get("axes") or "data"
            old = by_sig.get(sig)
            by_sig[sig] = (max(bps, old[0]) if old else bps,
                           min(lat, old[1]) if old else lat)
        # hierarchical tier legs (probe hier_k) land under the catalog's
        # tiered key form — "<axes>:intra" / "<axes>:inter"
        for t in snapshot.get("tiers") or []:
            bps = float(t.get("wire_bytes_per_sec", 0.0))
            lat = float(t.get("probe_secs", 0.0))
            if bps <= 0:
                continue
            sig = f"{t.get('axes') or 'data'}:{t.get('tier', 'intra')}"
            old = by_sig.get(sig)
            by_sig[sig] = (max(bps, old[0]) if old else bps,
                           min(lat, old[1]) if old else lat)
        if not by_sig:
            return None
        t = cls("probe", by_sig)
        # defaults from the FLAT rows when any exist: a tier row's
        # bandwidth describes a sub-group, not an unknown full axis set
        flat = {k: v for k, v in by_sig.items() if ":" not in k} or by_sig
        t.default_bps = max(v[0] for v in flat.values())
        t.default_latency = min(v[1] for v in flat.values())
        return t

    def lookup(self, axes_sig: str) -> Tuple[float, float]:
        hit = self.axes.get(axes_sig)
        if hit is not None:
            return hit
        base, _, tier = axes_sig.partition(":")
        if tier:
            # tiered query, no tiered row: the flat row for the same axis
            # set is the honest stand-in (same wire, no tier split)
            hit = self.axes.get(base)
            if hit is not None:
                return hit
        # nearest axis set (most shared names; matching tier preferred;
        # deterministic tie-break)
        want = set(base.split("+"))
        best = None
        for name in sorted(self.axes):
            nbase, _, ntier = name.partition(":")
            score = (len(want & set(nbase.split("+"))),
                     1 if ntier == tier else 0)
            if score[0] and (best is None or score > best[0]):
                best = (score, self.axes[name])
        return best[1] if best else (self.default_bps,
                                     self.default_latency)


def measured_bandwidth_table() -> Optional[BandwidthTable]:
    """This fabric's persisted catalog as a table, when one exists."""
    from . import bandwidth
    return BandwidthTable.from_catalog(bandwidth.load_catalog())


# -- compute (roofline) --------------------------------------------------
def flops_per_example(cfg) -> float:
    """Catalogued FORWARD FLOPs per example — an analytic model per
    family, documented in docs/planner.md. Anchors: RN50@224 ≈ 4.1
    GFLOPs fwd, scaled by depth/width/spatial; ViT from the standard
    24·n·d² + 4·n²·d per block."""
    m = cfg.model
    if m.name == "logistic":
        return 2.0 * m.input_size * m.hidden_units \
            + 2.0 * m.hidden_units * m.num_classes
    if m.name == "vit":
        s = cfg.data.image_size
        n = max(1, s // max(1, m.vit_patch_size)) ** 2
        d = m.vit_dim
        per_block = 24.0 * n * d * d + 4.0 * n * n * d
        if m.vit_num_experts > 0 and m.vit_moe_top_k > 1:
            # top-k>1 routes each token through k expert MLPs (the MLP
            # is 16·n·d² of the 24)
            per_block += (m.vit_moe_top_k - 1) * 16.0 * n * d * d
        return m.vit_depth * per_block + 2.0 * n * d * d  # + patch embed
    # resnet family: anchor RN50@224, scale depth linearly, width
    # quadratically, spatial quadratically
    s = cfg.data.image_size
    return 4.1e9 * (m.resnet_size / 50.0) * (m.width_multiplier ** 2) \
        * (s / 224.0) ** 2


def predict_compute_secs(cfg, n_devices: int, accum: int = 1,
                         peak_tflops: Optional[float] = None) -> float:
    """Roofline compute term for one OPTIMIZER step: global batch ×
    accum microbatches of forward+backward FLOPs, spread ideally over
    the devices, at ``ASSUMED_MFU`` of peak."""
    peak = (peak_tflops or REFERENCE_PEAK_TFLOPS) * 1e12
    examples = cfg.train.batch_size * max(1, accum)
    step_flops = examples * flops_per_example(cfg) * TRAIN_FLOPS_MULTIPLIER
    return step_flops / max(1, n_devices) / (peak * ASSUMED_MFU)


# -- communication + step time -------------------------------------------
def _expanded_ops(signature: dict) -> List[dict]:
    out: List[dict] = []
    for op in signature.get("ops", []):
        for _ in range(int(op.get("count", 1))):
            out.append(op)
    return out


def predict_from_signature(signature: dict, bandwidth: BandwidthTable,
                           compute_secs: float,
                           devices: int = 8) -> dict:
    """Cost one committed schedule signature: every scheduled collective
    as ``latency + bytes/bandwidth`` (ring-scaled when predicting a
    device count other than the canonical 8 the schedule traced at),
    overlap credit for the declared bucket plan's exchange ops."""
    plan = signature.get("plan") or {}
    # staged (hierarchical) plans carry the per-op wire ledger, aligned
    # 1:1 with the declared RS→psum→AG sequence — match op-by-op against
    # it; flat plans keep the one-op-per-bucket match
    op_wire = plan.get("bucket_op_wire_bytes")
    if op_wire:
        match_wire = [int(x) for b in op_wire for x in b]
        exchange_ops = _EXCHANGE_OPS_HIER
    else:
        match_wire = [int(b) for b in plan.get("bucket_wire_bytes") or []]
        exchange_ops = _EXCHANGE_OPS
    scale = _ring_scale(devices) / _ring_scale(8)
    comm_secs = 0.0
    exchange_secs = 0.0
    wire_bytes = 0
    cursor = 0
    for op in _expanded_ops(signature):
        nbytes = int(op.get("bytes", 0)) * scale
        sig = "+".join(op.get("axes") or [])
        if op.get("tier"):
            # grouped (two-tier) collectives cost against the tiered
            # bandwidth row ("data+fsdp:intra" / ":inter")
            sig = f"{sig}:{op['tier']}"
        bps, lat = bandwidth.lookup(sig)
        secs = lat + nbytes / bps
        comm_secs += secs
        wire_bytes += int(nbytes)
        # in-order subsequence match against the bucket plan (the
        # comm-report discipline): matched ops are the overlappable
        # gradient exchange
        if op.get("op") in exchange_ops and cursor < len(match_wire) \
                and int(op.get("bytes", -1)) == match_wire[cursor]:
            cursor += 1
            exchange_secs += secs
    exposed = (comm_secs - exchange_secs) \
        + max(0.0, exchange_secs - OVERLAP_EFFICIENCY * compute_secs)
    step_secs = compute_secs + exposed
    return {
        "step_secs": step_secs,
        "compute_secs": compute_secs,
        "comm_secs": comm_secs,
        "comm_exposed_secs": exposed,
        "comm_fraction": exposed / step_secs if step_secs > 0 else 0.0,
        "wire_bytes": wire_bytes,
    }


def tune_comm_plan(snapshot: dict, table: BandwidthTable, *,
                   intra_k: Optional[int],
                   bucket_mb: float,
                   bucket_mb_candidates=TUNE_BUCKET_MB) -> dict:
    """The startup autotune's chooser (comm.autotune=startup): given the
    traced plan snapshot (parallel/overlap.overlap_stats — grad bytes,
    per-bucket reduce-axis sets, the configured compress) and a
    bandwidth table (ideally carrying the probe's tiered rows), cost
    every (bucket_mb × flat-vs-hierarchical × compress) candidate with
    the planner's collective model and return the cheapest. Pure and
    deterministic given its inputs — the autotune-determinism contract
    the tests pin.

    First-order model, documented in docs/planner.md: the gradient is
    one payload on its DOMINANT reduce-axis set (the set carrying the
    most bucket bytes); a flat bucket costs ``lat + W/bps``; a staged
    bucket costs the RS and AG legs on the intra tier plus the 1/k psum
    on the inter tier. Compression candidates never introduce a lossy
    wire dtype the operator didn't configure — options are "off" and
    the snapshot's own compress.

    Fallback discipline (the seeded-probe-lie tests): hierarchical
    candidates are only costed when the table carries MEASURED tier rows
    for the dominant set, and those rows pass the TUNE_SANITY_FACTOR
    plausibility screen against the flat row — otherwise the tuner
    stays flat and logs the reason loudly. Returns {bucket_mb,
    hierarchy (k or 0), compress, predicted_secs, axes, source,
    candidates, fallback}."""
    grad_bytes = int(snapshot.get("grad_bytes") or 0)
    sigs = snapshot.get("bucket_reduce_axes") or ["data+fsdp"]
    sizes = snapshot.get("bucket_bytes") or [grad_bytes]
    by_sig: Dict[str, int] = {}
    for sig, nb in zip(sigs, sizes):
        by_sig[sig] = by_sig.get(sig, 0) + int(nb)
    # dominant reduce-axis set: most bytes, lexicographic tie-break
    sig = sorted(by_sig, key=lambda s: (-by_sig[s], s))[0]
    cur_compress = snapshot.get("compress", "off") or "off"
    compress_opts = ["off"] if cur_compress == "off" \
        else ["off", cur_compress]
    itemsize = {"off": 4, "bf16": 2, "fp16": 2}

    fallback = None
    k = int(intra_k) if intra_k else 0
    if k > 1 and "data" not in sig.split("+"):
        k, fallback = 0, ("dominant reduce set %r has no data axis" % sig)
    bps_f, lat_f = table.lookup(sig)
    if k > 1:
        if f"{sig}:intra" not in table.axes \
                or f"{sig}:inter" not in table.axes:
            k, fallback = 0, (
                f"no measured tier rows for {sig!r} in the "
                f"{table.source} table")
        else:
            bps_i, lat_i = table.lookup(f"{sig}:intra")
            bps_e, lat_e = table.lookup(f"{sig}:inter")
            implausible = [
                f"{t}={bps:.3g} B/s vs flat {bps_f:.3g} B/s"
                for t, bps in (("intra", bps_i), ("inter", bps_e))
                if not (0 < bps <= TUNE_SANITY_FACTOR * bps_f)]
            if implausible:
                k, fallback = 0, (
                    "tier bandwidth rows fail the plausibility screen "
                    f"(×{TUNE_SANITY_FACTOR:g} of the flat row): "
                    + "; ".join(implausible))
    if fallback:
        log.warning("comm autotune: hierarchical candidates DISABLED — "
                    "%s; tuning flat only", fallback)

    def cost(mb: float, hier: int, compress: str) -> float:
        cap = max(1, int(mb * 2 ** 20))
        n = max(1, -(-grad_bytes // cap))  # ceil
        w = (grad_bytes / n) * itemsize[compress] / 4.0
        if hier:
            return n * (2 * (lat_i + w / bps_i)
                        + (lat_e + (w / hier) / bps_e))
        return n * (lat_f + w / bps_f)

    mbs = sorted(set(float(m) for m in bucket_mb_candidates)
                 | {float(bucket_mb)})
    scored = []
    for mb in mbs:
        for hier in ([0, k] if k > 1 else [0]):
            for compress in compress_opts:
                scored.append((round(cost(mb, hier, compress), 9),
                               mb != float(bucket_mb), hier == 0,
                               mb, hier, compress))
    # cheapest wins; ties prefer the configured bucket_mb, then the
    # hierarchical form (it was only admitted with measured tier rows),
    # then the smaller cap / plainer wire — fully deterministic
    scored.sort(key=lambda t: (t[0], t[1], t[2], t[3], t[5]))
    best = scored[0]
    return {
        "bucket_mb": best[3],
        "hierarchy": best[4],
        "compress": best[5],
        "predicted_secs": best[0],
        "axes": sig,
        "source": table.source,
        "fallback": fallback,
        "candidates": {
            f"bucket{mb:g}mb/"
            + (f"hier{hier}" if hier else "flat")
            + (f"/{compress}" if compress != "off" else ""):
            secs for secs, _, _, mb, hier, compress in scored},
    }


# -- HBM watermark -------------------------------------------------------
def _tree_bytes(shapes) -> int:
    import jax
    import numpy as np
    total = 0
    for leaf in jax.tree_util.tree_leaves(shapes):
        total += int(math.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
    return total


def _sharded_bytes_per_device(shapes, shardings, mesh) -> int:
    """Per-device bytes of an abstract tree under its shardings: each
    leaf's bytes divided by the product of the mesh axes its
    PartitionSpec names (replicated leaves land whole on every
    device)."""
    import jax
    import numpy as np
    leaves = jax.tree_util.tree_leaves(shapes)
    shard_leaves = jax.tree_util.tree_leaves(
        shardings, is_leaf=lambda x: hasattr(x, "spec"))
    total = 0
    for leaf, sh in zip(leaves, shard_leaves):
        nbytes = int(math.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
        divisor = 1
        spec = getattr(sh, "spec", None)
        for entry in (spec or ()):
            if entry is None:
                continue
            names = entry if isinstance(entry, tuple) else (entry,)
            for name in names:
                divisor *= max(1, mesh.shape.get(name, 1))
        total += nbytes // max(1, divisor)
    return total


def predict_hbm_bytes(cfg, trainer, devices: int = 8) -> Optional[dict]:
    """Per-device HBM watermark model: sharded train state (params +
    optimizer) + a gradient copy sized like the params + the activation
    heuristic + two staging-ring slots of input batch. The live
    calibration target is the ``memory`` rows' per-device
    ``live_peak_bytes``."""
    try:
        from ..analysis.collectives import _abstract_state
        from ..parallel.mesh import batch_shard_count
        state = _abstract_state(trainer, cfg)
        shardings = trainer._state_shardings(state)
        mesh = trainer.mesh
        state_pd = _sharded_bytes_per_device(state, shardings, mesh)
        # grads are sized and sharded like the params subtree
        grad_pd = _sharded_bytes_per_device(state.params, shardings.params,
                                            mesh)
        nb = batch_shard_count(mesh)
        # schedule traced at 8 devices; other counts only grow the data
        # axis, which shrinks the per-device batch, not the state
        local_examples = cfg.train.batch_size / max(1, nb) * (8.0 / devices)
        act = local_examples * flops_per_example(cfg) / ACT_FLOPS_PER_BYTE
        if cfg.model.name == "logistic":
            batch_bytes = local_examples * cfg.model.input_size * 4
        else:
            s = cfg.data.image_size
            batch_bytes = local_examples * s * s * 3 * 4
        staging = 2 * batch_bytes
        return {"hbm_bytes": int(state_pd + grad_pd + act + staging),
                "state_bytes": int(state_pd),
                "grad_bytes": int(grad_pd),
                "act_bytes": int(act),
                "staging_bytes": int(staging)}
    except Exception:
        log.exception("HBM watermark model failed (prediction degrades "
                      "to time/comm only)")
        return None


# -- candidate enumeration (main.py plan / the gate phase) ---------------
def _variant_knobs(cfg, variant: str) -> dict:
    accum = 1
    if "accum" in variant:
        accum = int(variant.rsplit("accum", 1)[1])
    return {
        "precision": "bf16" if variant.startswith("bf16") else
        cfg.train.precision,
        "zero1": "zero1" in variant,
        "compress": "bf16" if "compress" in variant else "off",
        "bucket_mb": cfg.comm.bucket_mb,
        "accum": accum,
        "overlap": variant != "train",
        "hierarchy": "hier" in variant,
    }


def plan_for_preset(preset: str, signatures: Dict[str, dict],
                    n_devices: int = 8,
                    bandwidth: Optional[BandwidthTable] = None,
                    include_hbm: bool = True,
                    measured_compute_secs: Optional[float] = None,
                    peak_tflops: Optional[float] = None) -> dict:
    """Cost every committed (layout, variant) candidate of one preset
    and rank them. Pure given its inputs when ``bandwidth`` is the
    reference table — the plan-catalog byte-identity contract."""
    from ..utils.config import get_preset
    from ..analysis.elaborate import candidate_layouts
    from .tracer import recorder

    cfg = get_preset(preset)
    bandwidth = bandwidth or BandwidthTable.reference()
    layouts = dict(candidate_layouts(cfg, n_devices))
    trainers: Dict[str, object] = {}
    candidates: Dict[str, dict] = {}
    for key in sorted(signatures):
        name, rest = key.split("@", 1)
        layout, variant = rest.split("/", 1)
        if name != preset or variant not in PLAN_VARIANTS:
            continue
        with recorder.span("plan.predict", preset=preset, layout=layout,
                           variant=variant):
            knobs = _variant_knobs(cfg, variant)
            compute = measured_compute_secs if measured_compute_secs \
                else predict_compute_secs(cfg, n_devices,
                                          accum=knobs["accum"],
                                          peak_tflops=peak_tflops)
            pred = predict_from_signature(signatures[key], bandwidth,
                                          compute, devices=n_devices)
            if include_hbm and layout in layouts:
                trainer = trainers.get(layout)
                if trainer is None:
                    trainer = _trainer_for_layout(cfg, layouts[layout])
                    trainers[layout] = trainer
                if trainer is not None:
                    hbm = predict_hbm_bytes(cfg, trainer,
                                            devices=n_devices)
                    if hbm:
                        pred.update(hbm)
            pred["knobs"] = knobs
            candidates[f"{layout}/{variant}"] = _round_prediction(pred)
    ranked = rank_candidates(candidates)
    return {"preset": preset, "devices": n_devices,
            "bandwidth_source": bandwidth.source,
            "candidates": candidates,
            "ranked": ranked,
            "recommended": _recommend(candidates, ranked)}


def _trainer_for_layout(cfg, mesh_cfg):
    """A Trainer on a virtual mesh of the layout's shape (shared state
    memo with the hangcheck phase); None when the layout cannot build
    here (the prediction then omits HBM rather than failing)."""
    try:
        import copy
        import jax
        from ..analysis.elaborate import _axis_product
        from ..parallel.mesh import create_mesh
        from ..train.loop import Trainer
        c = copy.deepcopy(cfg)
        c.mesh = copy.deepcopy(mesh_cfg)
        # partial-coverage layouts (dp_pp covers 4 of 8 devices) build on
        # a device slice, the hangcheck-schedule discipline
        mesh = create_mesh(c.mesh,
                           devices=jax.devices()[:_axis_product(c.mesh)])
        return Trainer(c, mesh=mesh)
    except Exception as e:
        log.warning("planner: layout trainer unavailable (%s); HBM "
                    "omitted", e)
        return None


def _round_prediction(pred: dict) -> dict:
    """Stable rounding so the committed catalog never diffs on float
    noise: seconds to microsecond-ish precision, fractions to 1e-4."""
    out = {}
    for k, v in pred.items():
        if k.endswith("_secs"):
            out[k] = round(float(v), 9)
        elif k == "comm_fraction":
            out[k] = round(float(v), 4)
        elif isinstance(v, float):
            out[k] = round(v, 6)
        else:
            out[k] = v
    return out


def rank_candidates(candidates: Dict[str, dict]) -> List[str]:
    """Fastest predicted step first; HBM then name break ties."""
    return sorted(candidates,
                  key=lambda k: (candidates[k]["step_secs"],
                                 candidates[k].get("hbm_bytes", 0), k))


def _recommend(candidates: Dict[str, dict],
               ranked: List[str]) -> Optional[str]:
    """The recommended LAYOUT choice compares like with like: the
    fastest candidate among the plain ``overlap`` variants (every
    layout traces one), falling back to the overall ranking."""
    overlap_only = [k for k in ranked if k.endswith("/overlap")]
    return (overlap_only or ranked or [None])[0]


def recommend_layout(preset: str, n_devices: int = 8,
                     bandwidth: Optional[BandwidthTable] = None
                     ) -> Optional[Tuple[str, object]]:
    """(layout name, MeshConfig) the planner ranks first for this
    preset — launch.py's --auto-layout hook. None when the preset has
    no committed schedules (a new preset must run the gate first)."""
    from ..utils.config import get_preset
    from ..analysis.elaborate import candidate_layouts
    from .comm_report import load_schedules

    signatures = load_schedules()
    if not any(k.startswith(preset + "@") for k in signatures):
        return None
    plan = plan_for_preset(preset, signatures, n_devices=n_devices,
                           bandwidth=bandwidth
                           or measured_bandwidth_table(),
                           include_hbm=False)
    rec = plan.get("recommended")
    if not rec:
        return None
    layout = rec.split("/", 1)[0]
    cfg = get_preset(preset)
    for name, mesh_cfg in candidate_layouts(cfg, n_devices):
        if name == layout:
            return name, mesh_cfg
    return None


# -- live-run prediction (the drift sentinel's reference point) ----------
def predict_live(cfg, trainer,
                 bandwidth: Optional[BandwidthTable] = None
                 ) -> Optional[dict]:
    """Predict THIS run's step time / comm seconds / HBM from the live
    traced bucket plan (parallel/overlap.overlap_stats) — no committed
    schedule needed, so it works for any preset/override combination
    actually running. Returns None until the exchange plan has traced
    (the sentinel arms lazily) or when the run has no bucketed
    exchange to model."""
    import jax
    from ..parallel.overlap import overlap_stats
    from ..utils.profiling import detect_peak_tflops

    snap = overlap_stats.snapshot()
    if snap is None:
        return None
    if bandwidth is None:
        bandwidth = measured_bandwidth_table() or BandwidthTable.reference()
    n_devices = jax.device_count()
    accum = max(1, int(snap.get("accum_steps", 1)))
    peak = detect_peak_tflops()
    compute = predict_compute_secs(cfg, n_devices, accum=accum,
                                   peak_tflops=peak)
    comm = 0.0
    for wire, sig in zip(snap["bucket_wire_bytes"],
                         snap.get("bucket_reduce_axes",
                                  ["data"] * len(snap["bucket_wire_bytes"]))):
        bps, lat = bandwidth.lookup(sig)
        comm += lat + int(wire) / bps
    exposed = max(0.0, comm - OVERLAP_EFFICIENCY * compute)
    step = compute + exposed
    pred = {
        "step_secs": step,
        "compute_secs": compute,
        "comm_secs": comm,
        "comm_exposed_secs": exposed,
        "comm_fraction": exposed / step if step > 0 else 0.0,
        "wire_bytes": int(snap.get("wire_bytes", 0)),
    }
    hbm = predict_hbm_bytes(cfg, trainer, devices=n_devices)
    if hbm:
        pred.update(hbm)
    return _round_prediction(pred)


# -- drift sentinel ------------------------------------------------------
class DriftSentinel:
    """Predicted-vs-measured divergence detector. Per metric: a check
    whose ratio ``measured/predicted`` leaves ``[1/tolerance,
    tolerance]`` grows a streak; ``window`` consecutive divergent
    checks open an EPISODE, which fires exactly once; the episode ends
    when a check lands back inside tolerance. A global cooldown gates
    successive fires — a persistently mispredicted run must page once,
    not once per cadence (the perf-anomaly sentinel's discipline,
    resilience/watchdog.py)."""

    METRICS = ("step_secs", "comm_secs", "hbm_bytes")

    def __init__(self, predicted: dict, tolerance: float = 3.0,
                 window: int = 8, cooldown_secs: float = 300.0,
                 clock: Callable[[], float] = time.monotonic):
        self.predicted = {m: float(predicted[m]) for m in self.METRICS
                          if float(predicted.get(m) or 0.0) > 0.0}
        self.tolerance = max(1.0 + 1e-9, float(tolerance))
        self.window = max(1, int(window))
        self.cooldown_secs = max(0.0, float(cooldown_secs))
        self._clock = clock
        self._streak: Dict[str, int] = {}
        self._in_episode: Dict[str, bool] = {}
        self._last_fire_t: Optional[float] = None

    def check(self, metric: str, measured: Optional[float]
              ) -> Optional[dict]:
        """Feed one measurement; a dict (the ``plan_drift`` row body)
        exactly when the sentinel fires, else None."""
        predicted = self.predicted.get(metric)
        if predicted is None or measured is None or measured <= 0:
            return None
        ratio = float(measured) / predicted
        divergent = ratio > self.tolerance or ratio < 1.0 / self.tolerance
        if not divergent:
            self._streak[metric] = 0
            self._in_episode[metric] = False
            return None
        self._streak[metric] = self._streak.get(metric, 0) + 1
        if self._streak[metric] < self.window \
                or self._in_episode.get(metric):
            return None
        now = self._clock()
        if self._last_fire_t is not None \
                and now - self._last_fire_t < self.cooldown_secs:
            return None  # cooldown: keep the streak, fire later
        self._last_fire_t = now
        self._in_episode[metric] = True
        return {"metric": metric,
                "predicted": round(predicted, 9),
                "measured": round(float(measured), 9),
                "ratio": round(ratio, 4),
                "tolerance": self.tolerance,
                "windows": self._streak[metric]}


# -- CLI -----------------------------------------------------------------
def render_plan(plan: dict) -> str:
    lines = [f"== plan :: {plan['preset']} @ {plan['devices']} device(s) "
             f"(bandwidth: {plan['bandwidth_source']}) =="]
    hdr = (f"  {'rank':>4} {'layout/variant':<24} {'step ms':>9} "
           f"{'comp ms':>9} {'comm ms':>9} {'frac':>6} {'HBM MB':>8} "
           f"{'wire MB':>8}")
    lines.append(hdr)
    for i, key in enumerate(plan["ranked"], 1):
        c = plan["candidates"][key]
        hbm = c.get("hbm_bytes")
        hbm_txt = f"{hbm / 1e6:>8.1f}" if hbm is not None else f"{'-':>8}"
        lines.append(
            f"  {i:>4} {key:<24} {c['step_secs'] * 1e3:>9.3f} "
            f"{c['compute_secs'] * 1e3:>9.3f} "
            f"{c['comm_secs'] * 1e3:>9.3f} {c['comm_fraction']:>6.3f} "
            f"{hbm_txt} {c['wire_bytes'] / 1e6:>8.2f}")
    if plan.get("recommended"):
        lines.append(f"  recommended: {plan['recommended']}")
    return "\n".join(lines)


def main_plan(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="main.py plan",
        description="what-if performance planner: predict step time / "
                    "HBM / comm fraction per candidate layout from the "
                    "committed collective schedules × the fabric "
                    "bandwidth catalog (docs/planner.md)")
    ap.add_argument("--preset", action="append", default=[],
                    help="preset(s) to plan (default: every preset with "
                         "committed schedules)")
    ap.add_argument("--devices", type=int, default=8,
                    help="device count to predict for (default 8, the "
                         "canonical schedule mesh)")
    ap.add_argument("--bandwidth", default="auto",
                    help="'auto' (fabric catalog, else reference), "
                         "'reference', or a catalog JSON path")
    ap.add_argument("--schedules", default="",
                    help="collective_schedules.json path (default: the "
                         "committed artifact)")
    ap.add_argument("--no-hbm", action="store_true",
                    help="skip the HBM watermark model (no virtual-mesh "
                         "trainer builds — much faster)")
    ap.add_argument("--root", default=None,
                    help="also write registered {'event': 'plan'} rows "
                         "into this log root")
    ap.add_argument("--json", action="store_true",
                    help="emit the plans as JSON")
    ns = ap.parse_args(argv)

    from ..utils.virtual_devices import apply_virtual_cpu
    if not ns.no_hbm:
        apply_virtual_cpu(max(8, ns.devices))
    from . import bandwidth as bw_mod
    from .comm_report import load_schedules

    signatures = load_schedules(ns.schedules or None)
    if not signatures:
        print("plan: no committed schedules — run "
              "`main.py check` first (docs/static_analysis.md)")
        return 1
    if ns.bandwidth == "reference":
        table = BandwidthTable.reference()
    elif ns.bandwidth == "auto":
        table = measured_bandwidth_table() or BandwidthTable.reference()
    else:
        table = BandwidthTable.from_catalog(
            bw_mod.load_catalog(path=ns.bandwidth))
        if table is None:
            print(f"plan: no readable bandwidth catalog at "
                  f"{ns.bandwidth}")
            return 1
    presets = ns.preset or sorted({k.split("@", 1)[0]
                                   for k in signatures})
    plans = []
    for preset in presets:
        if not any(k.startswith(preset + "@") for k in signatures):
            print(f"plan: preset {preset!r} has no committed schedules; "
                  "skipping")
            continue
        plans.append(plan_for_preset(
            preset, signatures, n_devices=ns.devices, bandwidth=table,
            include_hbm=not ns.no_hbm))
    if ns.root:
        import os
        from ..utils.metrics import MetricsWriter
        writer = MetricsWriter(os.path.join(ns.root, "plan"),
                               enable_tensorboard=False)
        for plan in plans:
            for key in plan["ranked"]:
                layout, variant = key.split("/", 1)
                writer.write_event("plan", {
                    "preset": plan["preset"], "layout": layout,
                    "devices": plan["devices"],
                    "knobs": plan["candidates"][key]["knobs"],
                    "predicted": {k: v for k, v in
                                  plan["candidates"][key].items()
                                  if k != "knobs"},
                    "bandwidth_source": plan["bandwidth_source"],
                    "recommended": key == plan["recommended"]})
        writer.flush()
    if ns.json:
        print(json.dumps(plans, indent=1, sort_keys=True))
    else:
        for plan in plans:
            print(render_plan(plan))
    return 0
