"""Mesh/sharding tests on the fake 8-device mesh — the distributed layer
that replaces the reference's grpc PS and Horovod backends (SURVEY.md
§2.8-2.9). Verifies the sharded step equals the single-device step: sync
data parallelism by construction (what SyncReplicasOptimizer promised)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_resnet_tensorflow_tpu.parallel import (
    batch_shard_count, create_mesh, data_sharding, local_batch_size,
    param_sharding_rule, resolve_axis_sizes, shard_batch,
    tree_param_shardings)
from distributed_resnet_tensorflow_tpu.utils.config import MeshConfig, get_preset


def test_resolve_axis_sizes():
    sizes = resolve_axis_sizes(MeshConfig(data=-1), 8)
    assert sizes == (1, 8, 1, 1, 1, 1)
    sizes = resolve_axis_sizes(MeshConfig(data=4, fsdp=2), 8)
    assert sizes == (1, 4, 2, 1, 1, 1)
    with pytest.raises(ValueError):
        resolve_axis_sizes(MeshConfig(data=3), 8)
    with pytest.raises(ValueError):
        resolve_axis_sizes(MeshConfig(data=-1, fsdp=-1), 8)


def test_create_mesh_dp(mesh8):
    assert mesh8.shape["data"] == 8
    assert batch_shard_count(mesh8) == 8
    assert local_batch_size(64, mesh8) == 8
    with pytest.raises(ValueError):
        local_batch_size(10, mesh8)


def test_tensor_dropback_warns_once(caplog):
    """An indivisible tensor split silently replicating the leaf's FLOPs
    must be loud (once per leaf shape): the sharding rule logs the
    drop-back for plain encoder kernels AND MoE expert leaves."""
    import logging
    from distributed_resnet_tensorflow_tpu.parallel import sharding as sh
    mesh = create_mesh(MeshConfig(data=4, tensor=2))
    sh._TENSOR_DROPBACK_WARNED.clear()
    with caplog.at_level(logging.WARNING):
        spec = param_sharding_rule(
            "['EncoderBlock_0']['Dense_0']['kernel']", (32, 33), mesh)
        assert spec == P()  # dropped back to replication (33 % 2 != 0)
        spec = param_sharding_rule(
            "['EncoderBlock_0']['SwitchMlp_0']['w1']", (4, 32, 33), mesh)
        assert "tensor" not in tuple(spec)
        # repeat: warned once per distinct leaf shape
        param_sharding_rule(
            "['EncoderBlock_0']['Dense_0']['kernel']", (32, 33), mesh)
    msgs = [r for r in caplog.records if "REPLICATE" in r.getMessage()]
    assert len(msgs) == 2
    # divisible shapes stay silent and sharded
    assert param_sharding_rule(
        "['EncoderBlock_0']['Dense_0']['kernel']", (32, 64), mesh) \
        == P(None, "tensor")


def test_shard_batch_places_on_batch_axis(mesh8):
    batch = {"images": np.zeros((16, 8, 8, 3), np.float32),
             "labels": np.zeros((16,), np.int32)}
    out = shard_batch(batch, mesh8)
    assert out["images"].sharding.is_equivalent_to(
        data_sharding(mesh8), ndim=4)
    # each device holds 16/8=2 rows
    shard = out["images"].addressable_shards[0]
    assert shard.data.shape == (2, 8, 8, 3)


def test_param_sharding_rule(mesh_dp_fsdp):
    # small param → replicated
    assert param_sharding_rule("bn/scale", (64,), mesh_dp_fsdp) == P()
    # big matrix → sharded over fsdp on a divisible dim
    spec = param_sharding_rule("dense/kernel", (512, 1024), mesh_dp_fsdp)
    assert "fsdp" in spec
    # indivisible dims stay replicated
    assert param_sharding_rule("odd", (513, 1023), mesh_dp_fsdp) == P()


@pytest.mark.heavy
# re-tiered out of the 870s tier-1 (ISSUE 17, ~21s: the slowest single
# test — a full sharded-vs-single-device training oracle). The sharded
# step's numerics stay pinned in tier-1 by test_overlap's bucketed-vs-
# default allclose legs and test_fsdp_state_sharding's spec checks; the
# full (unfiltered) suite runs this oracle.
@pytest.mark.slow
def test_sharded_step_matches_single_device(mesh8):
    """The crux: dp-sharded training step == serial step (sync DP exactness).
    The reference could only approximate this promise through
    SyncReplicasOptimizer's token machinery (reference resnet_model.py:102-135)."""
    from distributed_resnet_tensorflow_tpu.train import Trainer
    from distributed_resnet_tensorflow_tpu.data import learnable_synthetic_iterator

    def build(mesh_cfg):
        cfg = get_preset("smoke")
        cfg.model.compute_dtype = "float32"
        cfg.model.resnet_size = 8
        cfg.model.num_classes = 4
        cfg.data.image_size = 8
        cfg.train.batch_size = 16
        cfg.optimizer.schedule = "constant"
        cfg.mesh = mesh_cfg
        return cfg

    it = learnable_synthetic_iterator(16, 8, 4, seed=11)
    batch = next(it)

    tr1 = Trainer(build(MeshConfig(data=1)),
                  mesh=create_mesh(MeshConfig(data=1),
                                   devices=jax.devices()[:1]))
    tr8 = Trainer(build(MeshConfig(data=8)), mesh=mesh8)
    tr1.init_state(seed=0)
    tr8.init_state(seed=0)

    s1, m1 = tr1.jitted_train_step()(tr1.state, shard_batch(batch, tr1.mesh))
    s8, m8 = tr8.jitted_train_step()(tr8.state, shard_batch(batch, tr8.mesh))

    # forward/loss agree to fp exactness; parameters after one update agree
    # up to gradient all-reduce reassociation noise (partial sums over 8
    # devices reduce in a different order than one device — inherent fp32)
    assert np.isclose(float(m1["loss"]), float(m8["loss"]), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(s1.params),
                    jax.tree_util.tree_leaves(s8.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=5e-4)


@pytest.mark.heavy
def test_fsdp_state_sharding(mesh_dp_fsdp):
    """Params/opt state shard over fsdp (ZeRO) — the capability replacing
    ps-side variable placement (reference resnet_cifar_main.py:392-396)."""
    from distributed_resnet_tensorflow_tpu.train import Trainer
    cfg = get_preset("smoke")
    cfg.model.compute_dtype = "float32"
    cfg.model.resnet_size = 8
    cfg.model.width_multiplier = 4   # big enough convs to cross the fsdp size threshold
    cfg.data.image_size = 32
    cfg.mesh = MeshConfig(data=4, fsdp=2)
    tr = Trainer(cfg, mesh=mesh_dp_fsdp)
    state = tr.init_state()
    shardings = [l.sharding for l in jax.tree_util.tree_leaves(state.params)]
    # at least one large leaf actually sharded over fsdp
    assert any("fsdp" in (s.spec[i] or "")
               for s in shardings if s.spec
               for i in range(len(s.spec)) if s.spec[i]), \
        "no parameter sharded over fsdp"
    # and the sharded train step still runs
    from distributed_resnet_tensorflow_tpu.data import synthetic_iterator
    it = synthetic_iterator(16, 32, 10)
    state, m = tr.train(it, num_steps=1)
    assert np.isfinite(float(m["loss"]))


def _stager_batch(rng):
    return {"images": rng.randint(0, 256, (16, 8, 8, 3)).astype(np.uint8),
            "labels": rng.randint(0, 10, (16,)).astype(np.int64),
            "mask": np.ones((16,), np.float32)}


def test_coalesced_stager_matches_shard_batch(mesh8, rng):
    """The coalesced single-transfer path must be value-, dtype- and
    sharding-identical to per-leaf shard_batch — including the int64→int32
    label narrowing both paths apply before the host→device hop."""
    from distributed_resnet_tensorflow_tpu.parallel.sharding import (
        CoalescedStager)
    st = CoalescedStager(mesh8, stacked=False, ring=3)
    batch = _stager_batch(rng)
    out, ref = st.put_now(batch), shard_batch(batch, mesh8)
    for k in batch:
        assert out[k].dtype == ref[k].dtype, k
        assert out[k].sharding == ref[k].sharding, k
        np.testing.assert_array_equal(np.asarray(out[k]), np.asarray(ref[k]))
    assert out["labels"].dtype == np.int32  # int64 halved on the wire
    # ring reuse: many puts through the same layout stay correct
    for _ in range(6):
        b = _stager_batch(rng)
        o = st.put_now(b)
        np.testing.assert_array_equal(np.asarray(o["images"]), b["images"])
    # a second spec (no mask) builds its own layout on the fly
    b2 = {k: v for k, v in _stager_batch(rng).items() if k != "mask"}
    o2 = st.put_now(b2)
    np.testing.assert_array_equal(np.asarray(o2["images"]), b2["images"])


def test_coalesced_stager_stacked_and_fsdp(mesh8, mesh_dp_fsdp, rng):
    from distributed_resnet_tensorflow_tpu.parallel.sharding import (
        CoalescedStager, shard_stacked_batch)
    sb = {"images": rng.randint(0, 256, (3, 16, 8, 8, 3)).astype(np.uint8),
          "labels": rng.randint(0, 10, (3, 16)).astype(np.int64)}
    for mesh in (mesh8, mesh_dp_fsdp):
        st = CoalescedStager(mesh, stacked=True, ring=3)
        out, ref = st.put_now(sb), shard_stacked_batch(sb, mesh)
        for k in sb:
            assert out[k].dtype == ref[k].dtype
            assert out[k].sharding == ref[k].sharding, (k, mesh.shape)
            np.testing.assert_array_equal(np.asarray(out[k]),
                                          np.asarray(ref[k]))


def test_coalesced_stager_replicated_nonbatch_axis(rng):
    """tensor>1 mesh: several devices hold the SAME batch shard; each must
    receive its own copy of the shard's staging region."""
    from distributed_resnet_tensorflow_tpu.parallel.sharding import (
        CoalescedStager)
    mesh = create_mesh(MeshConfig(data=4, tensor=2))
    st = CoalescedStager(mesh)
    batch = _stager_batch(rng)
    out, ref = st.put_now(batch), shard_batch(batch, mesh)
    for k in batch:
        np.testing.assert_array_equal(np.asarray(out[k]), np.asarray(ref[k]))
        assert out[k].sharding == ref[k].sharding


def test_put_paths_coerce_label_dtype(mesh8):
    """Labels must cross host→device as int32 on EVERY put path (the
    satellite audit): int64 labels (platform-default numpy) are narrowed by
    shard_batch / shard_stacked_batch / the stager alike."""
    from distributed_resnet_tensorflow_tpu.parallel.sharding import (
        shard_stacked_batch)
    flat = {"images": np.zeros((8, 4, 4, 3), np.uint8),
            "labels": np.arange(8)}                      # int64 by default
    assert flat["labels"].dtype == np.int64
    assert shard_batch(flat, mesh8)["labels"].dtype == np.int32
    stacked = {"images": np.zeros((2, 8, 4, 4, 3), np.uint8),
               "labels": np.zeros((2, 8), np.int64)}
    assert shard_stacked_batch(stacked, mesh8)["labels"].dtype == np.int32
    # float64 narrows too (an accidental float mask would double its bytes)
    m = shard_batch({"images": np.zeros((8, 2), np.float64),
                     "labels": np.zeros((8,), np.int32)}, mesh8)
    assert m["images"].dtype == np.float32
