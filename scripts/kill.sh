#!/bin/bash
# Parity with reference scripts/kill.sh (pkill python3) — scoped to this
# framework's processes instead of every python on the node.
pkill -f "distributed_resnet_tensorflow_tpu.main" || true
pkill -f "distributed_resnet_tensorflow_tpu.launch" || true
