"""Hot checkpoint swap: new params restored OFF the request path.

A background thread polls the checkpoint directory with the committed-
manifest machinery (``resilience.manifest.committed_steps`` — the same
primitive behind ``checkpoint.manager.poll_new_checkpoint``; only
commit-renamed steps are ever visible), walks new steps newest-first past
damaged ones, verifies the manifest, and deserializes the payload into
HOST numpy trees. Nothing here touches the
device: the restored tree is parked as a *pending swap* that the serving
dispatch thread picks up at a batch boundary (serve/batcher.py
``boundary_hook``) and applies atomically — in-flight requests complete on
the old params, the next batch sees the new checkpoint, zero requests
dropped, zero downtime.

A torn/damaged checkpoint (manifest verification failure, deserialization
error) is REJECTED without disturbing the serving params: the swap thread
logs it, records the rejection, advances past the bad step (so it doesn't
spin on it — exactly the evaluator's skip contract, docs/resilience.md)
and keeps polling for the next good commit.
"""
from __future__ import annotations

import json
import logging
import os
import random
import threading
import time
from typing import Callable, Optional

import numpy as np

from ..resilience.manifest import (committed_steps, manifest_digest,
                                   manifest_status)
from ..telemetry.tracer import span
from ..analysis.protocol.spec import Model, ProtocolSpec, register_spec

log = logging.getLogger(__name__)

_PAYLOAD_DIRS = ("data", "default")  # manager.py layout, then legacy orbax


def _payload_path(step_dir: str) -> str:
    for name in _PAYLOAD_DIRS:
        cand = os.path.join(step_dir, name)
        if os.path.isdir(cand):
            return cand
    return step_dir  # bare orbax tree (oldest layout)


class PendingSwap:
    """A verified checkpoint restored to host memory, ready to apply."""

    __slots__ = ("step", "digest", "params", "batch_stats", "restore_ms")

    def __init__(self, step: int, digest: str, params, batch_stats,
                 restore_ms: float):
        self.step = step
        self.digest = digest
        self.params = params
        self.batch_stats = batch_stats
        self.restore_ms = restore_ms


class CheckpointSwapper:
    """Background poll → verify → host-restore → pending-swap handoff.

    ``poll_once()`` is the whole state machine (also called directly by
    tests and by the server's startup restore); ``start()`` runs it on a
    daemon thread at a jittered ``poll_secs`` cadence (±50% — many serving
    replicas sharing a checkpoint FS must not poll in lockstep).
    ``on_reject(step, reason)`` fires for damaged checkpoints (the server
    emits the rejected ``serve_swap`` metrics row there).
    """

    def __init__(self, directory: str, poll_secs: float = 5.0,
                 on_reject: Optional[Callable[[int, str], None]] = None,
                 seed: int = 0, gate_path: Optional[str] = None):
        import orbax.checkpoint as ocp
        self.directory = directory
        self.poll_secs = max(0.1, poll_secs)
        self.last_seen: Optional[int] = None
        self.rejected = 0
        # router-pinned serving (serve.swap_gate): with the gate armed
        # the swapper ONLY follows the control file at gate_path
        # ({"target_step": N}, written atomically by the fleet front
        # door) — forward for a canary/promote, BACKWARD for a rollback,
        # and HOLDS (keeps current params) while no pin exists. Chasing
        # the newest commit before the router pins it would leak an
        # unvalidated checkpoint to a baseline replica.
        self.gate_path = gate_path
        self._gate_applied: Optional[int] = None
        self._gate_bad: set = set()
        self._on_reject = on_reject
        self._ckptr = ocp.Checkpointer(ocp.StandardCheckpointHandler())
        self._pending: Optional[PendingSwap] = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._rng = random.Random(seed)

    # -- one poll turn (no device work; safe on any thread) ----------------
    def poll_once(self) -> Optional[PendingSwap]:
        """Walk the committed steps NEWER than ``last_seen`` newest-first
        until one verifies and loads — the manager.restore fallback
        contract (docs/resilience.md) applied to serving: a torn newest
        commit must not hide a strictly newer GOOD one (trainer committed
        4 then 6 between polls, 6 tore → serve 4, not stale params
        forever). ``last_seen`` advances to the newest committed step
        regardless, so bad steps are skipped, never re-verified every
        poll.

        Under a swap gate (``gate_path``) the walk is replaced by
        pin-following: restore exactly the pinned step when it is
        committed and not known-bad, whatever direction that moves the
        replica; hold with no (or an unreadable) pin."""
        if self.gate_path is not None:
            return self._poll_gated(self._read_gate())
        steps = committed_steps(self.directory)
        if self.last_seen is not None:
            steps = [s for s in steps if s > self.last_seen]
        if not steps:
            return None
        self.last_seen = steps[-1]
        for step in reversed(steps):
            step_dir = os.path.join(self.directory, str(step))
            pending = self._load_step(step, step_dir,
                                      manifest_digest(step_dir))
            if pending is not None:
                return pending
        return None

    def _read_gate(self) -> Optional[int]:
        """The pinned step, or None when no control file exists yet (a
        replica spawned before any checkpoint was committed — hold)."""
        try:
            with open(self.gate_path) as f:
                data = json.load(f)
            return int(data["target_step"])
        except (OSError, ValueError, KeyError, TypeError):
            return None

    def _poll_gated(self, target: Optional[int]) -> Optional[PendingSwap]:
        if (target is None or target == self._gate_applied
                or target in self._gate_bad):
            return None
        if target < 0 or target not in committed_steps(self.directory):
            # pinned ahead of the directory (pin raced the commit) — keep
            # polling; the step will appear or the pin will move
            return None
        step_dir = os.path.join(self.directory, str(target))
        pending = self._load_step(target, step_dir,
                                  manifest_digest(step_dir))
        if pending is None:
            # a damaged pinned step must not be re-verified every poll;
            # the router sees no confirmation and rolls the canary back
            self._gate_bad.add(target)
            return None
        self._gate_applied = target
        return pending

    def restore_newest_valid(self) -> Optional[PendingSwap]:
        """STARTUP restore: the newest committed checkpoint that verifies,
        falling back past damaged ones — a restarting replica must never
        serve fresh-init params while a good checkpoint exists. Same walk
        as ``poll_once`` with nothing seen yet."""
        return self.poll_once()

    def _load_step(self, step: int, step_dir: str,
                   digest: str) -> Optional[PendingSwap]:
        """Verify + host-restore one committed step; parks (and returns)
        the PendingSwap, or records the rejection and returns None."""
        with span("serve.swap_restore", step=step):
            return self._load_step_inner(step, step_dir, digest)

    def _load_step_inner(self, step: int, step_dir: str,
                         digest: str) -> Optional[PendingSwap]:
        t0 = time.perf_counter()
        status, detail = manifest_status(step_dir)
        if status == "bad":
            return self._reject(step, f"manifest verification failed: "
                                      f"{detail}")
        if status == "legacy":
            log.info("serve swap: checkpoint step %d has no manifest "
                     "(pre-protocol) — restoring unverified", step)
        try:
            # restore to HOST (no abstract target -> numpy leaves): the
            # dispatch thread owns all device placement (module docstring)
            from ..checkpoint import shards as shards_mod
            if shards_mod.is_sharded_layout(step_dir):
                # per-host sharded layout (a trainer with
                # checkpoint.sharded on): reassemble the serving subtrees
                # from the shard indexes — the optimizer shards this
                # replica never needs are not even read
                with shards_mod.ShardReader(step_dir) as reader:
                    host = {
                        "step": int(np.asarray(
                            reader.read_subtree("step"))),
                        "params": reader.read_subtree("params"),
                        "batch_stats": reader.read_subtree("batch_stats"),
                    }
            else:
                tree = self._ckptr.restore(_payload_path(step_dir))
                host = {
                    "step": int(np.asarray(tree["step"])),
                    "params": tree["params"],
                    "batch_stats": tree["batch_stats"],
                }
        except Exception as e:  # torn pre-manifest payloads land here
            return self._reject(step, f"deserialization failed: "
                                      f"{type(e).__name__}: {e}")
        pending = PendingSwap(
            host["step"], digest, host["params"], host["batch_stats"],
            restore_ms=(time.perf_counter() - t0) * 1000.0)
        with self._lock:
            # newest wins: an unapplied older pending swap is superseded —
            # serving an intermediate checkpoint late would move the
            # replica BACKWARD relative to the directory
            self._pending = pending
        log.info("serve swap: checkpoint step %d restored off-path in "
                 "%.0fms (digest %s)", pending.step, pending.restore_ms,
                 (digest or "none")[:12])
        return pending

    def _reject(self, step: int, reason: str) -> None:
        self.rejected += 1
        log.warning("serve swap: REJECTED checkpoint step %d — %s; serving "
                    "params untouched, polling for the next commit",
                    step, reason)
        if self._on_reject is not None:
            self._on_reject(step, reason)
        return None

    def take_pending(self) -> Optional[PendingSwap]:
        """Claim the pending swap (dispatch thread, at a batch boundary)."""
        with self._lock:
            pending, self._pending = self._pending, None
        return pending

    @property
    def has_pending(self) -> bool:
        with self._lock:
            return self._pending is not None

    # -- background thread -------------------------------------------------
    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception:
                # a transient FS error must not kill the swap thread — the
                # server would silently stop tracking training forever
                log.exception("serve swap poll failed; retrying")
            self._stop.wait(self.poll_secs * self._rng.uniform(0.5, 1.5))

    def start(self) -> "CheckpointSwapper":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="drt-serve-swap")
            self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None


# ---------------------------------------------------------------------------
# declared protocol model (analysis/protocol/, docs/static_analysis.md)
# ---------------------------------------------------------------------------

def _canary_pin_model(mutations):
    """The SWAP_CONTROL.json pin protocol: a 2-replica fleet (one canary
    arm, one control arm) racing one committed checkpoint through the
    canary ladder, each replica's swapper polling its own pin file.

    State: ``(ctrl, pin0, pin1, app0, app1, leaked)`` — ``ctrl`` the
    CanaryController phase (idle / active / promoted / rolled_back),
    ``pinN``/``appN`` the pinned and applied step ("old"/"new") of
    replica 0 (canary arm) and 1 (control arm), ``leaked`` whether any
    swapper ever applied a step its pin did not name (the gating bug
    class ``_poll_gated`` exists to prevent).
    """
    def actions(s):
        ctrl, pin0, pin1, app0, app1, leaked = s
        out = []
        if ctrl == "idle":
            # a committed step appears: canary arm pinned forward, the
            # control arm re-pinned to the incumbent
            out.append(("commit_new",
                        ("active", "new", "old", app0, app1, leaked)))
        pins, apps = (pin0, pin1), (app0, app1)
        for i in range(2):
            if "apply_unpinned" in mutations:
                # an ungated swapper chases the newest commit directly
                if ctrl != "idle" and apps[i] != "new":
                    a2 = ["new" if j == i else apps[j] for j in range(2)]
                    out.append((f"swap_poll({i})",
                                (ctrl, pin0, pin1, a2[0], a2[1],
                                 leaked or pins[i] != "new")))
            elif apps[i] != pins[i]:
                a2 = [pins[j] if j == i else apps[j] for j in range(2)]
                out.append((f"swap_poll({i})",
                            (ctrl, pin0, pin1, a2[0], a2[1], leaked)))
        if ctrl == "active":
            if app0 == "new":
                # canary confirmed + verdict clean: fleet-wide re-pin
                out.append(("promote",
                            ("promoted", "new", "new", app0, app1,
                             leaked)))
            out.append(("rollback",
                        ("rolled_back", "old", pin1, app0, app1,
                         leaked)))
        return out

    return Model(
        init=("idle", "old", "old", "old", "old", False),
        actions=actions,
        invariants=(
            ("pinned_replica_never_applies_unpinned_commit",
             lambda s: not s[5]),
            ("control_arm_stays_on_incumbent_while_canary_active",
             lambda s: s[0] != "active" or s[4] == "old"),
        ),
        liveness=(
            ("canary_verdict_reached", "eventually",
             lambda s: s[0] != "active"),
            ("promote_can_converge_fleet_wide", "reachable",
             lambda s: s[0] == "promoted" and s[3] == "new"
             and s[4] == "new"),
        ),
    )


CANARY_PIN_PROTOCOL = register_spec(ProtocolSpec(
    name="canary-swap-pin",
    title="canary swap-control pin: SWAP_CONTROL.json per-replica pins, "
          "gated swapper, promote/rollback re-pin",
    modules=("distributed_resnet_tensorflow_tpu/serve/swap.py",
             "distributed_resnet_tensorflow_tpu/serve/fleet.py",
             "distributed_resnet_tensorflow_tpu/serve/router.py"),
    bounds={"replicas": 2, "commits": 1},
    model=_canary_pin_model,
    mutations=("apply_unpinned",),
    event_edges={
        "canary": {
            "actions": ("start", "promote", "rollback"),
            "reasons_by_action": {
                "promote": ("promoted", "single_replica"),
                "rollback": ("p99_regression", "confidence_regression",
                             "no_confirm"),
            },
        },
    },
    literals={
        "SWAP_CONTROL.json": "the per-replica pin file",
        "target_step": "the pin file's single field",
        "start": "canary row action", "promote": "canary row action",
        "rollback": "canary row action",
    },
    enum_checks=(
        ("canary", "action", ("start", "promote", "rollback")),
        ("canary", "reason",
         ("p99_regression", "confidence_regression", "no_confirm",
          "promoted", "single_replica")),
    ),
))
