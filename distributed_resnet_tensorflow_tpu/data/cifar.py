"""CIFAR-10/100 input pipeline — numpy-native, TPU-feeding.

Replaces BOTH reference CIFAR paths with one implementation:
  * the legacy queue-runner pipeline (reference cifar_input.py:21-115 —
    string_input_producer + FixedLengthRecordReader + RandomShuffleQueue), and
  * the tf.data pipeline duplicated in the mains (reference
    resnet_cifar_main.py:134-246).

Record format (CIFAR binary): [label bytes][3072 bytes R,G,B planes of 32x32].
CIFAR-10: 1 label byte, files data_batch_{1..5}.bin / test_batch.bin
(reference resnet_cifar_main.py:137-154). CIFAR-100: coarse+fine label bytes,
fine label used — the reference handled this only on the legacy path via
label_offset=1 (reference cifar_input.py:40-43) while its tf.data path
one-hotted to 10 classes and silently broke cifar100 (reference
resnet_cifar_main.py:171, SURVEY.md §2 bug list). Fixed here: one parser,
both datasets.

Augmentation (train): pad 32→36, random 32x32 crop, random horizontal flip,
per-image standardization (reference resnet_cifar_main.py:185-199 and
cifar_input.py:66-75). Eval: standardization only.
"""
from __future__ import annotations

import os
import time
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..utils.metrics import input_stages

IMAGE_SIZE = 32
DEPTH = 3
_REC_IMG = IMAGE_SIZE * IMAGE_SIZE * DEPTH  # 3072


def _record_layout(dataset: str) -> Tuple[int, int]:
    """(label_bytes, label_offset): cifar10 = (1, 0); cifar100 = (2, 1) —
    byte 0 coarse, byte 1 fine (reference cifar_input.py:40-43)."""
    if dataset == "cifar10":
        return 1, 0
    if dataset == "cifar100":
        return 2, 1
    raise ValueError(f"unknown cifar dataset {dataset!r}")


def dataset_filenames(dataset: str, data_dir: str, mode: str) -> List[str]:
    """Train/eval shard lists (reference resnet_cifar_main.py:140-154)."""
    if dataset == "cifar10":
        if mode == "train":
            names = [f"data_batch_{i}.bin" for i in range(1, 6)]
        else:
            names = ["test_batch.bin"]
    else:  # cifar100 binary release
        names = ["train.bin"] if mode == "train" else ["test.bin"]
    paths = [os.path.join(data_dir, n) for n in names]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        raise FileNotFoundError(f"missing CIFAR files: {missing}")
    return paths


def load_cifar(dataset: str, data_dir: str, mode: str,
               use_native: bool = False) -> Tuple[np.ndarray, np.ndarray]:
    """Parse raw records → (images uint8 NHWC, labels int32).

    Records store CHW planes; transpose to NHWC, the TPU-native layout
    (reference parse_record did the same transpose, resnet_cifar_main.py:157-182).
    ``use_native`` parses in C++ (native/dataloader.cc) — identical output,
    used for the high-rate path; falls back silently if the .so is absent.
    """
    label_bytes, label_offset = _record_layout(dataset)
    rec_len = label_bytes + _REC_IMG
    paths = dataset_filenames(dataset, data_dir, mode)
    # corrupt/truncated files must fail loudly on BOTH parsers (the C++
    # fread loop would silently stop at a partial record)
    for path in paths:
        size = os.path.getsize(path)
        if size % rec_len != 0:
            raise ValueError(f"{path}: size {size} not a multiple of "
                             f"record length {rec_len}")
    if use_native:
        from .native_loader import native_available
        if native_available():
            from .native_loader import load_cifar_native
            imgs, lbls = [], []
            for path in paths:
                im, lb = load_cifar_native(path, label_bytes, label_offset)
                imgs.append(im)
                lbls.append(lb)
            return np.concatenate(imgs), np.concatenate(lbls)
        # no toolchain/.so → behavior-identical python parser below
    images, labels = [], []
    for path in paths:
        raw = np.fromfile(path, dtype=np.uint8)
        if raw.size % rec_len != 0:
            raise ValueError(f"{path}: size {raw.size} not a multiple of "
                             f"record length {rec_len}")
        recs = raw.reshape(-1, rec_len)
        labels.append(recs[:, label_offset].astype(np.int32))
        imgs = recs[:, label_bytes:].reshape(-1, DEPTH, IMAGE_SIZE, IMAGE_SIZE)
        images.append(imgs.transpose(0, 2, 3, 1))  # CHW → HWC
    return np.concatenate(images), np.concatenate(labels)


# ---------------------------------------------------------------------------
# augmentation (vectorized over the batch)
# ---------------------------------------------------------------------------

def standardize(images: np.ndarray) -> np.ndarray:
    """Per-image standardization: (x-mean)/adjusted_std with
    adjusted_std = max(std, 1/sqrt(N)) — TF semantics the reference used
    (reference resnet_cifar_main.py:199, cifar_input.py:75)."""
    x = images.astype(np.float32)
    n = np.prod(x.shape[1:])
    mean = x.mean(axis=(1, 2, 3), keepdims=True)
    std = x.std(axis=(1, 2, 3), keepdims=True)
    adj = np.maximum(std, np.float32(1.0 / np.sqrt(float(n))))
    return ((x - mean) / adj).astype(np.float32)


def augment_train(images: np.ndarray, rng: np.random.RandomState) -> np.ndarray:
    """Pad to 36, random 32-crop, random flip (reference
    resnet_cifar_main.py:188-199). Vectorized gather-based crop."""
    b = images.shape[0]
    pad = (36 - IMAGE_SIZE) // 2
    padded = np.pad(images, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
    ys = rng.randint(0, 2 * pad + 1, size=b)
    xs = rng.randint(0, 2 * pad + 1, size=b)
    # gather crops via advanced indexing
    yy = ys[:, None] + np.arange(IMAGE_SIZE)[None, :]           # (b, 32)
    xx = xs[:, None] + np.arange(IMAGE_SIZE)[None, :]           # (b, 32)
    bidx = np.arange(b)[:, None, None]
    out = padded[bidx, yy[:, :, None], xx[:, None, :], :]       # (b,32,32,3)
    flip = rng.rand(b) < 0.5
    out[flip] = out[flip, :, ::-1, :]
    return out


# ---------------------------------------------------------------------------
# iterators
# ---------------------------------------------------------------------------

def cifar_iterator(dataset: str, data_dir: str, batch_size: int, mode: str,
                   seed: int = 0, shard_index: int = 0, num_shards: int = 1,
                   prefetch: int = 2, use_native: bool = False,
                   device_augment: bool = False
                   ) -> Iterator[Dict[str, np.ndarray]]:
    """In-memory epoch iterator with full-dataset shuffle per epoch (the
    reference shuffled a 50k buffer = full epoch, resnet_cifar_main.py:221).

    ``shard_index/num_shards`` give each process a disjoint slice — fixing the
    reference Horovod path's unsharded input (SURVEY.md §3.2).

    ``device_augment`` (train mode only): yield raw uint8 batches and leave
    crop/flip/standardize to the jitted step (ops/augment.py) — the host
    then only gathers records, which is what lets one CPU core feed TPU-rate
    training.
    """
    images, labels = load_cifar(dataset, data_dir, mode, use_native=use_native)
    if num_shards > 1:
        images = images[shard_index::num_shards]
        labels = labels[shard_index::num_shards]
    rng = np.random.RandomState(seed)
    n = images.shape[0]
    is_train = mode == "train"

    def gen():
        while True:
            order = rng.permutation(n) if is_train else np.arange(n)
            for start in range(0, n, batch_size):
                idx = order[start:start + batch_size]
                if len(idx) < batch_size:
                    if is_train:
                        break  # drop partial train batch (standard; reshuffles next epoch)
                    # eval: pad to a fixed shape (no jit recompile) and mask the
                    # padding out of the metrics — unlike the reference, which
                    # silently skipped tail images (resnet_cifar_eval.py ran
                    # fixed 50x100 batches over a 10k test set)
                    pad = batch_size - len(idx)
                    idx = np.concatenate([idx, np.zeros(pad, idx.dtype)])
                    mask = np.concatenate([np.ones(batch_size - pad, np.float32),
                                           np.zeros(pad, np.float32)])
                else:
                    mask = None
                t0 = time.perf_counter()
                batch_imgs = images[idx]
                if is_train and device_augment:
                    out = {"images": batch_imgs,  # raw uint8; device augments
                           "labels": labels[idx].copy()}
                    input_stages.add("decode", time.perf_counter() - t0,
                                     items=batch_size)
                    yield out
                    continue
                if is_train:
                    batch_imgs = augment_train(batch_imgs, rng)
                out = {"images": standardize(batch_imgs),
                       "labels": labels[idx].copy()}
                if mask is not None:
                    out["mask"] = mask
                # host-side parse/augment/standardize busy time (the cifar
                # analog of the imagenet decode stage)
                input_stages.add("decode", time.perf_counter() - t0,
                                 items=batch_size)
                yield out

    if prefetch > 0 and is_train:
        return _threaded_prefetch(gen(), prefetch)
    return gen()


def _threaded_prefetch(it: Iterator, depth: int) -> Iterator:
    """Background-thread prefetch — host-side successor of the reference's
    16-thread RandomShuffleQueue (reference cifar_input.py:77-96) and
    tf.data prefetch (resnet_cifar_main.py:232). One shared implementation
    (device_prefetch.threaded_iterator) covers worker/stop/error handling."""
    from .device_prefetch import threaded_iterator
    return threaded_iterator(it, depth, name="drt-cifar-prefetch")
