"""Checkpoint/resume + evaluator tests (reference capabilities SURVEY.md
§2.13-2.14: chief time-based checkpoints, auto-resume, polling evaluator
with best-precision tracking)."""
import os
import time

import jax
import numpy as np
import pytest

from distributed_resnet_tensorflow_tpu.checkpoint import (
    CheckpointManager, wait_for_new_checkpoint)
from distributed_resnet_tensorflow_tpu.data import learnable_synthetic_iterator
from distributed_resnet_tensorflow_tpu.train import Trainer
from distributed_resnet_tensorflow_tpu.utils.config import get_preset


def _tiny_cfg(tmp_path, **kw):
    cfg = get_preset("smoke")
    cfg.model.compute_dtype = "float32"
    cfg.model.resnet_size = 8
    cfg.model.num_classes = 4
    cfg.data.image_size = 8
    cfg.train.batch_size = 16
    cfg.optimizer.schedule = "constant"
    cfg.log_root = str(tmp_path)
    cfg.checkpoint.directory = os.path.join(str(tmp_path), "ckpt")
    cfg.checkpoint.async_save = False
    for k, v in kw.items():
        cfg.override(k, v)
    return cfg


@pytest.mark.heavy
def test_save_restore_roundtrip(tmp_path):
    cfg = _tiny_cfg(tmp_path)
    tr = Trainer(cfg)
    tr.init_state()
    it = learnable_synthetic_iterator(16, 8, 4)
    state, _ = tr.train(it, num_steps=3)

    mngr = CheckpointManager(cfg.checkpoint.directory, async_save=False)
    mngr.save(3, state)
    mngr.wait_until_finished()
    assert mngr.latest_step() == 3

    # fresh trainer restores bit-exact params at the saved step
    tr2 = Trainer(cfg)
    tr2.init_state()
    restored, step = mngr.restore(tr2.state)
    assert step == 3 and int(restored.step) == 3
    for a, b in zip(jax.tree_util.tree_leaves(state.params),
                    jax.tree_util.tree_leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    mngr.close()


@pytest.mark.slow  # re-tiered out of the 870s tier-1; runs in the full (unfiltered) suite
@pytest.mark.heavy
def test_cross_topology_restore(tmp_path):
    """Elastic resume: a checkpoint written under one mesh (fsdp=2) restores
    into trainers on DIFFERENT topologies (pure dp, and fsdp=4) bit-exactly,
    and training continues — restore reshards into the target state's
    shardings, so checkpoints are topology-portable like the reference's
    (which had a single unsharded variable set)."""
    cfg = _tiny_cfg(tmp_path)
    cfg.model.width_multiplier = 4  # wide enough that fsdp actually shards
    cfg.mesh.data = 4
    cfg.mesh.fsdp = 2
    tr = Trainer(cfg)
    tr.init_state()
    # NOTE: fresh iterator per trainer — a Trainer's cached prefetcher
    # closes its source iterator when finalized, so sharing one generator
    # across trainers is a use-after-close
    state, _ = tr.train(learnable_synthetic_iterator(16, 8, 4), num_steps=2)
    mngr = CheckpointManager(cfg.checkpoint.directory, async_save=False)
    mngr.save(2, state)
    mngr.wait_until_finished()

    for axes in ({"data": 8, "fsdp": 1}, {"data": 2, "fsdp": 4}):
        cfg2 = _tiny_cfg(tmp_path)
        cfg2.model.width_multiplier = 4
        cfg2.mesh.data = axes["data"]
        cfg2.mesh.fsdp = axes["fsdp"]
        tr2 = Trainer(cfg2)
        tr2.init_state()
        restored, step = mngr.restore(tr2.state)
        assert step == 2
        for a, b in zip(jax.tree_util.tree_leaves(state.params),
                        jax.tree_util.tree_leaves(restored.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # training continues on the new topology
        tr2.state = restored
        new_state, m = tr2.train(learnable_synthetic_iterator(16, 8, 4),
                                 num_steps=4, start_step=2)
        assert int(new_state.step) == 4
        assert np.isfinite(float(m["loss"]))
    mngr.close()


@pytest.mark.heavy
def test_restore_without_checkpoint_is_noop(tmp_path):
    cfg = _tiny_cfg(tmp_path)
    mngr = CheckpointManager(cfg.checkpoint.directory, async_save=False)
    tr = Trainer(cfg)
    st = tr.init_state()
    restored, step = mngr.restore(st)
    assert step is None and restored is st
    mngr.close()


def test_step_and_time_cadence(tmp_path):
    mngr = CheckpointManager(str(tmp_path / "c"), save_every_steps=10,
                             save_every_secs=0.0, async_save=False)
    assert not mngr.should_save(9)    # no boundary crossed yet
    assert mngr.should_save(10)
    mngr._last_save_step = 10         # as save() would record
    assert not mngr.should_save(11)   # 10-boundary already saved
    assert mngr.should_save(20)
    # time-based (reference save_checkpoint_secs=60 semantics)
    mngr2 = CheckpointManager(str(tmp_path / "c2"), save_every_steps=0,
                              save_every_secs=0.05, async_save=False)
    assert not mngr2.should_save(1)
    time.sleep(0.06)
    assert mngr2.should_save(2)
    mngr.close(); mngr2.close()


@pytest.mark.slow  # re-tiered out of the 870s tier-1; runs in the full (unfiltered) suite
@pytest.mark.heavy
def test_auto_resume_continues_training(tmp_path):
    """run_train resumes from latest checkpoint — MonitoredTrainingSession
    auto-resume parity (SURVEY.md §2.14)."""
    from distributed_resnet_tensorflow_tpu.main import run_train
    cfg = _tiny_cfg(tmp_path)
    cfg.train.train_steps = 4
    cfg.checkpoint.save_every_steps = 2
    cfg.checkpoint.save_every_secs = 0.0
    state, _ = run_train(cfg)
    assert int(state.step) == 4

    cfg.train.train_steps = 6
    state2, _ = run_train(cfg)   # must resume at 4, not 0
    assert int(state2.step) == 6
    mngr = CheckpointManager(cfg.checkpoint.directory, async_save=False)
    assert mngr.latest_step() == 6
    mngr.close()


@pytest.mark.heavy
def test_wait_for_new_checkpoint(tmp_path):
    d = str(tmp_path / "ckpt")
    assert wait_for_new_checkpoint(d, None, timeout_secs=0.0) is None
    mngr = CheckpointManager(d, async_save=False)
    cfg = _tiny_cfg(tmp_path)
    tr = Trainer(cfg); tr.init_state()
    mngr.save(5, tr.state)
    mngr.wait_until_finished()
    assert wait_for_new_checkpoint(d, None, timeout_secs=0.0) == 5
    assert wait_for_new_checkpoint(d, 5, timeout_secs=0.0) is None

    # the non-blocking variant (serve swap thread + jittered evaluator):
    # (step, path, manifest digest) triple, None when nothing newer
    from distributed_resnet_tensorflow_tpu.checkpoint import (
        poll_new_checkpoint)
    from distributed_resnet_tensorflow_tpu.resilience.manifest import (
        manifest_digest)
    hit = poll_new_checkpoint(d, None)
    assert hit is not None
    step, path, digest = hit
    assert step == 5 and path == os.path.join(d, "5")
    assert digest and digest == manifest_digest(path)  # committed → hashed
    assert poll_new_checkpoint(d, 5) is None
    assert poll_new_checkpoint(str(tmp_path / "nope"), None) is None
    mngr.close()


@pytest.mark.slow  # re-tiered out of the 870s tier-1; runs in the full (unfiltered) suite
@pytest.mark.heavy
def test_evaluator_tracks_best_precision(tmp_path):
    """Polling evaluator: evaluates each checkpoint once, tracks best
    (reference resnet_cifar_eval.py:117-133)."""
    from distributed_resnet_tensorflow_tpu.evaluator import Evaluator
    cfg = _tiny_cfg(tmp_path)
    cfg.eval.eval_batch_count = 2

    tr = Trainer(cfg)
    tr.init_state()
    it = learnable_synthetic_iterator(16, 8, 4)
    mngr = CheckpointManager(cfg.checkpoint.directory, async_save=False)
    state, _ = tr.train(it, num_steps=2)
    mngr.save(2, state)
    state, _ = tr.train(it, num_steps=30, start_step=2)
    mngr.save(30, state)
    mngr.wait_until_finished()

    ev = Evaluator(cfg, data_iter=learnable_synthetic_iterator(16, 8, 4))
    r1 = ev.evaluate_checkpoint(2)
    r2 = ev.evaluate_checkpoint(30)
    assert ev.best_precision == max(r1["precision"], r2["precision"])
    # trained-further checkpoint should do better on learnable data
    assert r2["precision"] >= r1["precision"]
    # run() with no new checkpoints exits immediately
    out = ev.run(timeout_secs=0.0)
    assert out == {} or isinstance(out, dict)
    mngr.close()


def test_layout_stamp_mismatch_refused(tmp_path):
    """A checkpoint written with the circular pipeline layout must refuse a
    restore under a different (pstages, interleave) — the stacked rows would
    silently run in a permuted network order (models/pipeline.py)."""
    state = {"x": np.arange(4.0)}

    class S:  # minimal state-like object for _saveable
        step = 0
        params = {"w": np.arange(4.0)}
        batch_stats = {}
        opt_state = {}

        def replace(self, **kw):
            return self

    circ = {"encoder_order": "circular", "pstages": 4, "interleave": 2,
            "depth": 8}
    m1 = CheckpointManager(os.path.join(str(tmp_path), "c"), async_save=False,
                           layout_stamp=circ)
    m1.save(1, S(), force=True)
    m1.wait_until_finished()
    saved = m1.saved_layout()
    assert saved.pop("applies_from_step") == 1  # crash-orphan bookkeeping
    assert saved == circ
    m1.close()

    # same layout: restore proceeds
    m_ok = CheckpointManager(os.path.join(str(tmp_path), "c"),
                             async_save=False, layout_stamp=dict(circ))
    m_ok.restore(S())
    m_ok.close()

    # different pipeline split: refused already at construction
    other = dict(circ, pstages=2)
    with pytest.raises(ValueError, match="layout|permute"):
        CheckpointManager(os.path.join(str(tmp_path), "c"),
                          async_save=False, layout_stamp=other)

    # network-order run against a circular checkpoint: refused too
    with pytest.raises(ValueError, match="layout|permute"):
        CheckpointManager(os.path.join(str(tmp_path), "c"),
                          async_save=False,
                          layout_stamp={"encoder_order": "network"})

    # an orphaned sidecar (stamp written, no step ever committed) must NOT
    # poison the directory for a different layout
    orphan_dir = os.path.join(str(tmp_path), "orphan")
    os.makedirs(orphan_dir)
    import json
    with open(os.path.join(orphan_dir, "layout.json"), "w") as f:
        json.dump(circ, f)
    m_orph = CheckpointManager(orphan_dir, async_save=False,
                               layout_stamp={"encoder_order": "network"})
    m_orph.save(1, S(), force=True)
    m_orph.wait_until_finished()
    assert m_orph.saved_layout()["encoder_order"] == "network"
    m_orph.close()

    # crash orphan OVER existing checkpoints (ADVICE r3 #4): a directory
    # holding committed network-order steps, then a circular run's save
    # crashes after the sidecar write but before the orbax commit. The
    # stamp's applies_from_step (2) is newer than every committed step (1),
    # so a network-order run must still open the directory.
    crash_dir = os.path.join(str(tmp_path), "crash")
    m_net = CheckpointManager(crash_dir, async_save=False,
                              layout_stamp={"encoder_order": "network"})
    m_net.save(1, S(), force=True)
    m_net.wait_until_finished()
    m_net.close()
    with open(os.path.join(crash_dir, "layout.json"), "w") as f:
        json.dump({**circ, "applies_from_step": 2}, f)  # commit never landed
    m_after = CheckpointManager(crash_dir, async_save=False,
                                layout_stamp={"encoder_order": "network"})
    _, step = m_after.restore(S())
    assert step == 1
    m_after.close()
    # ...while a circular run whose stamp DID commit still refuses network
    with open(os.path.join(crash_dir, "layout.json"), "w") as f:
        json.dump({**circ, "applies_from_step": 1}, f)
    with pytest.raises(ValueError, match="layout|permute"):
        CheckpointManager(crash_dir, async_save=False,
                          layout_stamp={"encoder_order": "network"})

    # a corrupt sidecar next to committed checkpoints refuses loudly for a
    # circular run (conservative network-order assumption), never permutes
    with open(os.path.join(str(tmp_path), "c", "layout.json"), "w") as f:
        f.write("{truncated")
    with pytest.raises(ValueError, match="layout|permute"):
        CheckpointManager(os.path.join(str(tmp_path), "c"),
                          async_save=False, layout_stamp=circ)

    del state


def test_repack_stacked_params_roundtrip():
    """circular->network->circular repacking is the identity, and a
    circular-stored stack repacked to network order equals the inverse
    permutation of circular_layer_order."""
    from distributed_resnet_tensorflow_tpu.models.pipeline import (
        circular_layer_order, repack_stacked_params)
    depth, P, v = 8, 2, 2
    rng = np.random.RandomState(0)
    net = {"w": rng.randn(depth, 3).astype(np.float32),
           "b": rng.randn(depth).astype(np.float32)}
    order = circular_layer_order(depth, P, v)
    stored = {k: np.asarray(a)[order] for k, a in net.items()}
    # stored (circular) -> network order
    back = repack_stacked_params(stored, depth, src=(P, v), dst=(1, 1))
    for k in net:
        np.testing.assert_array_equal(np.asarray(back[k]), net[k])
    # network -> circular == the stored layout
    fwd = repack_stacked_params(net, depth, src=(1, 1), dst=(P, v))
    for k in net:
        np.testing.assert_array_equal(np.asarray(fwd[k]), stored[k])


def test_orphan_stamp_refreshed_on_same_layout_commit(tmp_path):
    """A crash-orphaned sidecar whose applies_from_step is AHEAD of the
    steps a rerun commits must be re-stamped at commit time — otherwise
    every later reader would keep discarding a now-valid stamp and could
    restore circular params as network order (review r4 finding)."""
    import json

    class S:
        step = 0
        params = {"w": np.arange(4.0)}
        batch_stats = {}
        opt_state = {}

        def replace(self, **kw):
            return self

    circ = {"encoder_order": "circular", "pstages": 4, "interleave": 2,
            "depth": 8}
    d = os.path.join(str(tmp_path), "c")
    os.makedirs(d)
    with open(os.path.join(d, "layout.json"), "w") as f:
        json.dump({**circ, "applies_from_step": 50}, f)  # orphan from crash
    m = CheckpointManager(d, async_save=False, layout_stamp=dict(circ))
    m.save(1, S(), force=True)
    m.wait_until_finished()
    assert m.saved_layout()["applies_from_step"] == 1  # refreshed
    m.close()
    # the committed stamp now outranks nothing — a network run refuses
    with pytest.raises(ValueError, match="layout|permute"):
        CheckpointManager(d, async_save=False,
                          layout_stamp={"encoder_order": "network"})


@pytest.mark.slow
def test_crash_resume_step_exact_and_evaluator_continuity(tmp_path):
    """VERDICT r4 #6: SIGKILL a live main.py trainer mid-run, relaunch,
    and assert (a) the resumed process continues EXACTLY at
    latest_complete_checkpoint + 1 — no restart from 0, no skipped steps —
    via the per-step metrics JSONL, and (b) an evaluator tracking
    best_precision across checkpoints from BOTH sides of the crash keeps
    its monotone best (the reference got this passively from
    MonitoredTrainingSession + srun --no-kill)."""
    import json
    import signal
    import subprocess
    import sys

    from distributed_resnet_tensorflow_tpu.utils.virtual_devices import (
        virtual_cpu_env)

    ckpt_dir = os.path.join(str(tmp_path), "ckpt")
    args = [
        sys.executable, "-m", "distributed_resnet_tensorflow_tpu.main",
        "--preset", "smoke",
        "--set", "model.name=logistic",
        "--set", "model.input_size=192",
        "--set", "model.hidden_units=1200",  # slow the step a little
        "--set", "model.num_classes=10",
        "--set", "data.image_size=8",
        "--set", "train.batch_size=8",
        "--set", "train.log_every_steps=1000",
        "--set", "train.summary_every_steps=1",  # JSONL row per step
        "--set", f"log_root={tmp_path}",
        "--set", "checkpoint.save_every_steps=100",
        "--set", "checkpoint.save_every_secs=0",
    ]
    env = virtual_cpu_env(1)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")

    def ckpt_steps():
        try:
            return sorted(int(d) for d in os.listdir(ckpt_dir)
                          if d.isdigit())
        except FileNotFoundError:
            return []

    # run 1: unbounded-ish; SIGKILL once the second checkpoint lands
    p = subprocess.Popen(args + ["--set", "train.train_steps=1000000"],
                         env=env, cwd=repo,
                         stdout=subprocess.DEVNULL,
                         stderr=subprocess.DEVNULL)
    try:
        deadline = time.time() + 300
        while time.time() < deadline:
            if [s for s in ckpt_steps() if s >= 200]:
                break
            if p.poll() is not None:
                raise AssertionError("trainer exited before it was killed")
            time.sleep(0.1)
        else:
            raise AssertionError(f"no checkpoint >=200 appeared: {ckpt_steps()}")
        p.send_signal(signal.SIGKILL)
        p.wait(timeout=60)
    finally:
        if p.poll() is None:
            p.kill()
    assert p.returncode != 0  # it really died

    jsonl = os.path.join(str(tmp_path), "train", "metrics.jsonl")
    # scalar rows only: typed {"event": ...} records (input_stages
    # telemetry) share the step key and would double-count steps
    with open(jsonl) as f:
        steps_before = [r["step"]
                        for r in (json.loads(l) for l in f if l.strip())
                        if "event" not in r]
    # a SIGKILL mid-async-save may leave an orphan dir; resume must use the
    # latest COMPLETE checkpoint (crash-orphan-safe layout, round 4)
    n_rows_before = len(steps_before)

    # run 2: resume and finish a bounded run
    target = max(ckpt_steps()) + 150
    rc = subprocess.run(
        args + ["--set", f"train.train_steps={target}"], env=env, cwd=repo,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        timeout=600).returncode
    assert rc == 0
    with open(jsonl) as f:
        all_steps = [r["step"]
                     for r in (json.loads(l) for l in f if l.strip())
                     if "event" not in r]
    resumed = all_steps[n_rows_before:]
    assert resumed, "resumed run wrote no metrics"
    restart = resumed[0]
    # exact continuation: first resumed step is some checkpoint + 1 ...
    assert restart - 1 in ckpt_steps(), (restart, ckpt_steps())
    # ... within the already-trained range (no skip past the crash point)
    assert restart <= max(steps_before) + 1, (restart, max(steps_before))
    assert restart > 1, "resume restarted from scratch"
    # contiguous to the target — no repeated or skipped steps after resume
    assert resumed == list(range(restart, target + 1)), resumed[:5]

    # evaluator best-precision continuity across the crash boundary:
    # evaluate a pre-crash checkpoint, then a post-crash one, in ONE
    # evaluator; best must be the running max, never reset
    from distributed_resnet_tensorflow_tpu.evaluator import Evaluator
    from distributed_resnet_tensorflow_tpu.utils.config import get_preset
    cfg = get_preset("smoke")
    cfg.model.name = "logistic"
    cfg.model.input_size = 192
    cfg.model.hidden_units = 1200
    cfg.model.num_classes = 10
    cfg.data.image_size = 8
    cfg.train.batch_size = 8
    cfg.eval.eval_batch_count = 2
    cfg.log_root = str(tmp_path)
    steps = ckpt_steps()
    pre, post = steps[0], steps[-1]
    assert post >= target
    ev = Evaluator(cfg)
    r1 = ev.evaluate_checkpoint(pre)
    r2 = ev.evaluate_checkpoint(post)
    assert r2["best_precision"] == max(r1["precision"], r2["precision"])
    assert r2["best_precision"] >= r1["best_precision"]


# ---------------------------------------------------------------------------
# zero-stall async checkpointing (round 10): snapshot/writer charge split,
# writer-thread purity under the dispatch sanitizer, kill-during-commit
# crash consistency
# ---------------------------------------------------------------------------

def _logistic_cfg(tmp_path, **kw):
    cfg = get_preset("smoke")
    cfg.model.name = "logistic"
    cfg.model.input_size = 64
    cfg.model.hidden_units = 32
    cfg.model.num_classes = 4
    cfg.train.batch_size = 16
    cfg.log_root = str(tmp_path)
    cfg.checkpoint.directory = os.path.join(str(tmp_path), "ckpt")
    cfg.checkpoint.save_every_secs = 0.0
    for k, v in kw.items():
        cfg.override(k, v)
    return cfg


def test_async_writer_is_dispatch_free_under_sanitizer(tmp_path):
    """The async save contract: the WRITER thread does host I/O only —
    the device→host snapshot happens on the loop thread before the
    handoff. With the cross-thread dispatch sanitizer armed and the main
    thread owning multi-device dispatch, a writer-thread XLA launch
    would raise CrossThreadDispatchError out of wait_until_finished."""
    import jax.numpy as jnp
    from distributed_resnet_tensorflow_tpu.analysis import dispatch_sanitizer
    from distributed_resnet_tensorflow_tpu.parallel.sharding import (
        put_to_sharding)
    from distributed_resnet_tensorflow_tpu.parallel.mesh import replicated

    cfg = _logistic_cfg(tmp_path)
    tr = Trainer(cfg)
    tr.init_state()
    with dispatch_sanitizer.enabled():
        # claim multi-device dispatch ownership on THIS thread first —
        # otherwise a dispatching writer would silently become the owner
        rep = put_to_sharding(np.ones((8,), np.float32), replicated(tr.mesh))
        jax.block_until_ready(jax.jit(lambda x: x + 1)(rep))
        mngr = CheckpointManager(cfg.checkpoint.directory, async_save=True)
        assert mngr._async  # the path under test
        mngr.save(1, tr.state, force=True)
        mngr.wait_until_finished()  # re-raises any writer-thread error
        mngr.close()
    assert mngr.latest_step() == 1


def test_async_charge_split_and_ckpt_async_row(tmp_path, monkeypatch):
    """Only the loop thread's share of an async save (snapshot +
    backpressure) may land in the goodput 'checkpoint' bucket; the writer
    thread's stage→fsync→commit seconds ride ckpt_async_stats and the
    {"event": "ckpt_async"} row instead (ISSUE 10 charge-split fix)."""
    from distributed_resnet_tensorflow_tpu.resilience.faultinject import (
        CKPT_COMMIT_SLEEP_ENV_VAR)
    from distributed_resnet_tensorflow_tpu.telemetry.goodput import goodput
    from distributed_resnet_tensorflow_tpu.train.hooks import CkptAsyncHook
    from distributed_resnet_tensorflow_tpu.utils.metrics import (
        MetricsWriter, ckpt_async_stats, read_metrics)

    nap = 0.8
    monkeypatch.setenv(CKPT_COMMIT_SLEEP_ENV_VAR, str(nap))
    cfg = _logistic_cfg(tmp_path)
    tr = Trainer(cfg)
    tr.init_state()
    ckpt_async_stats.reset()
    base_ckpt = goodput.snapshot().get("checkpoint", 0.0)
    mngr = CheckpointManager(cfg.checkpoint.directory, async_save=True)
    t0 = time.perf_counter()
    mngr.save(1, tr.state, force=True)
    loop_secs = time.perf_counter() - t0
    # save() must return well before the writer's injected nap elapses
    assert loop_secs < nap / 2, loop_secs
    # ... and the loop thread's goodput charge must exclude the nap
    loop_charge = goodput.snapshot().get("checkpoint", 0.0) - base_ckpt
    assert loop_charge < nap / 2, loop_charge
    # wait for the commit WITHOUT blocking through wait_until_finished
    # (that wait would legitimately charge 'checkpoint' and muddy the
    # assertion that the writer's time was never loop time)
    deadline = time.monotonic() + 30
    while ckpt_async_stats.snapshot()["committed"] < 1:
        assert time.monotonic() < deadline, "writer never committed"
        time.sleep(0.05)
    snap = ckpt_async_stats.snapshot()
    assert snap["saves"] == 1 and snap["committed"] == 1
    assert snap["writer_seconds"] >= nap  # the nap ran on the writer
    assert snap["last_committed_step"] == 1
    assert snap["snapshot_seconds"] >= 0.0
    monkeypatch.delenv(CKPT_COMMIT_SLEEP_ENV_VAR)

    # the hook exports the split as a registered event row
    w = MetricsWriter(str(tmp_path / "m"), enable_tensorboard=False)
    hook = CkptAsyncHook(w, every_steps=1)
    hook(1, tr.state, {})
    w.close()
    rows = [r for r in read_metrics(str(tmp_path / "m"))
            if r.get("event") == "ckpt_async"]
    assert len(rows) == 1 and rows[0]["writer_seconds"] >= nap
    # the stats unchanged since the last export → the next cadence writes
    # nothing (but a snapshot that CHANGED — e.g. the final save's writer
    # seconds landing after an early export — re-exports)
    w2 = MetricsWriter(str(tmp_path / "m2"), enable_tensorboard=False)
    hook2 = CkptAsyncHook(w2, every_steps=1)
    hook2._exported = ckpt_async_stats.snapshot()
    hook2(2, tr.state, {})
    w2.close()
    assert not [r for r in read_metrics(str(tmp_path / "m2"))
                if r.get("event") == "ckpt_async"]
    mngr.close()


def test_save_backpressure_counts_overtake(tmp_path, monkeypatch):
    """A save cadence faster than the writer drains through backpressure:
    the second save waits for the in-flight one (commit order = step
    order) and the wait is counted (and charged as loop-thread
    checkpoint time, never dropped work)."""
    from distributed_resnet_tensorflow_tpu.resilience.faultinject import (
        CKPT_COMMIT_SLEEP_ENV_VAR)
    from distributed_resnet_tensorflow_tpu.utils.metrics import (
        ckpt_async_stats)
    monkeypatch.setenv(CKPT_COMMIT_SLEEP_ENV_VAR, "0.4")
    cfg = _logistic_cfg(tmp_path)
    tr = Trainer(cfg)
    tr.init_state()
    ckpt_async_stats.reset()
    mngr = CheckpointManager(cfg.checkpoint.directory, async_save=True)
    mngr.save(1, tr.state, force=True)
    state2 = tr.state.replace(step=tr.state.step + 1)
    mngr.save(2, state2, force=True)  # overtakes the in-flight step-1 save
    monkeypatch.delenv(CKPT_COMMIT_SLEEP_ENV_VAR)
    mngr.close()
    snap = ckpt_async_stats.snapshot()
    assert snap["overtakes"] >= 1
    assert snap["backpressure_seconds"] > 0.2
    assert mngr.all_steps() == [1, 2]


_KILL_CHILD = r"""
import os, sys
sys.path.insert(0, {repo!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from distributed_resnet_tensorflow_tpu.utils.config import get_preset
from distributed_resnet_tensorflow_tpu.train import Trainer
from distributed_resnet_tensorflow_tpu.checkpoint import CheckpointManager
from distributed_resnet_tensorflow_tpu.resilience import faultinject

cfg = get_preset("smoke")
cfg.model.name = "logistic"
cfg.model.input_size = 64
cfg.model.hidden_units = 32
cfg.model.num_classes = 4
tr = Trainer(cfg)
tr.init_state()
ckpt_dir = sys.argv[1]
marker = sys.argv[2]
m = CheckpointManager(ckpt_dir, async_save=True)
m.save(1, tr.state.replace(step=tr.state.step + 1), force=True)
m.wait_until_finished()
print("STEP1_COMMITTED", flush=True)
# arm the commit-window nap ONLY for the step-2 save, then hand it to the
# writer thread and report readiness — the parent SIGKILLs us inside the
# nap, with the staging dir fully written but uncommitted
os.environ[faultinject.CKPT_COMMIT_SLEEP_ENV_VAR] = "60"
os.environ[faultinject.CKPT_COMMIT_MARKER_ENV_VAR] = marker
m.save(2, tr.state.replace(step=tr.state.step + 2), force=True)
m.wait_until_finished()
print("UNREACHABLE", flush=True)
"""


@pytest.mark.slow  # subprocess + jax import; runs in the full suite and chaos_smoke.sh
def test_kill_during_async_commit_restores_committed_step(tmp_path):
    """THE crash-consistency acceptance case for async checkpointing:
    SIGKILL the process while the dedicated writer is mid-protocol
    (staged, not yet committed). The torn staging dir must never read as
    a checkpoint, the next manager construction sweeps it, and restore
    lands on the newest COMMITTED step."""
    import signal
    import subprocess
    import sys as _sys
    from distributed_resnet_tensorflow_tpu.resilience.manifest import (
        committed_steps, is_staging_name)

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ckpt_dir = str(tmp_path / "ckpt")
    marker = str(tmp_path / "marker")
    child = subprocess.Popen(
        [_sys.executable, "-c", _KILL_CHILD.format(repo=repo),
         ckpt_dir, marker],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    try:
        # wait until the writer reports it entered the step-2 commit window
        deadline = time.monotonic() + 180
        while not os.path.exists(marker):
            assert time.monotonic() < deadline, "writer never reached the window"
            assert child.poll() is None, "child died early"
            time.sleep(0.05)
        child.send_signal(signal.SIGKILL)
        child.wait(timeout=30)
    finally:
        if child.poll() is None:
            child.kill()
    with open(marker) as f:
        assert "2" in f.read()
    # only step 1 is committed; the torn step-2 staging dir is visible on
    # disk but invisible to every committed-step reader
    assert committed_steps(ckpt_dir) == [1]
    staging = [n for n in os.listdir(ckpt_dir) if is_staging_name(n)]
    assert staging, "expected the torn staging dir to survive the kill"
    # a fresh writer-side manager sweeps the torn staging dir...
    cfg = _logistic_cfg(tmp_path)
    mngr = CheckpointManager(ckpt_dir, async_save=False)
    assert not [n for n in os.listdir(ckpt_dir) if is_staging_name(n)]
    # ...and restore lands on the newest committed step
    tr = Trainer(cfg)
    tr.init_state()
    restored, step = mngr.restore(tr.state)
    assert step == 1 and int(restored.step) == 1
    mngr.close()


def test_snapshot_is_host_resident(tmp_path):
    """The async handoff must carry NUMPY leaves (the writer thread may
    not touch device buffers the train loop is about to donate)."""
    from distributed_resnet_tensorflow_tpu.checkpoint.manager import (
        _host_snapshot, _saveable)
    cfg = _logistic_cfg(tmp_path)
    tr = Trainer(cfg)
    tr.init_state()
    snap = _host_snapshot(_saveable(tr.state))
    for leaf in jax.tree_util.tree_leaves(snap):
        assert not isinstance(leaf, jax.Array), type(leaf)

# ---------------------------------------------------------------------------
# per-host SHARDED checkpoints (checkpoint/shards.py; ISSUE 11)
# ---------------------------------------------------------------------------

def _zero1_trainer(tmp_path, **kw):
    """A zero1 logistic trainer on the 8-device dp mesh: its optimizer
    state is genuinely data-sharded, so the sharded layout has real
    per-host pieces to write."""
    from distributed_resnet_tensorflow_tpu.parallel import create_mesh
    from distributed_resnet_tensorflow_tpu.utils.config import MeshConfig
    cfg = _logistic_cfg(tmp_path, **{"optimizer.name": "lamb",
                                     "optimizer.zero1": "on",
                                     "optimizer.zero1_min_size": "8",
                                     **kw})
    tr = Trainer(cfg, mesh=create_mesh(MeshConfig(data=8)))
    tr.init_state()
    return cfg, tr


def _train_steps(tr, n=2):
    rng = np.random.RandomState(3)
    batches = [{"images": rng.randn(16, 64).astype(np.float32),
                "labels": rng.randint(0, 4, 16).astype(np.int32)}
               for _ in range(n)]
    state, _ = tr.train(iter(batches), num_steps=n)
    return state


def test_sharded_roundtrip_and_reshard(tmp_path):
    """The sharded layout's acceptance arc in one test: an async sharded
    save commits atomically (manifest covers the shard files), restores
    bit-exact into the same topology, re-shards into a DIFFERENT device
    count + replicated (zero1 off) layout, and an orbax-written
    checkpoint still restores into a zero1 state — both layouts read
    both."""
    from distributed_resnet_tensorflow_tpu.checkpoint import shards
    from distributed_resnet_tensorflow_tpu.parallel import create_mesh
    from distributed_resnet_tensorflow_tpu.utils.config import MeshConfig

    cfg, tr = _zero1_trainer(tmp_path)
    state = _train_steps(tr)
    mngr = CheckpointManager(cfg.checkpoint.directory, async_save=True,
                             sharded="on")
    mngr.save(2, state)
    mngr.wait_until_finished()
    assert mngr.latest_step() == 2
    step_dir = os.path.join(cfg.checkpoint.directory, "2")
    assert shards.is_sharded_layout(step_dir)
    # the manifest covers the shard payload files like any other payload
    from distributed_resnet_tensorflow_tpu.resilience.manifest import (
        manifest_status)
    status, _detail = manifest_status(step_dir)
    assert status == "ok"

    # same-topology restore: bit exact, optimizer state still sharded
    _cfg2, tr2 = _zero1_trainer(tmp_path)
    restored, step = mngr.restore(tr2.state)
    assert step == 2 and int(restored.step) == 2
    for a, b in zip(jax.tree_util.tree_leaves(state.opt_state),
                    jax.tree_util.tree_leaves(restored.opt_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    sharded_leaves = [l for l in
                      jax.tree_util.tree_leaves(restored.opt_state)
                      if hasattr(l, "sharding")
                      and not l.sharding.is_fully_replicated]
    assert sharded_leaves

    # re-shard: restore into a 2-device replicated (zero1 off) trainer
    cfg3 = _logistic_cfg(tmp_path, **{"optimizer.name": "lamb"})
    from distributed_resnet_tensorflow_tpu.parallel import create_mesh
    tr3 = Trainer(cfg3, mesh=create_mesh(MeshConfig(data=2),
                                         devices=jax.devices()[:2]))
    tr3.init_state()
    reader = CheckpointManager(cfg3.checkpoint.directory, writer=False,
                               async_save=False)
    restored3, step3 = reader.restore(tr3.state)
    assert step3 == 2
    for a, b in zip(jax.tree_util.tree_leaves(state.opt_state),
                    jax.tree_util.tree_leaves(restored3.opt_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # cross-layout: an orbax save restores into the zero1 trainer
    mngr_orbax = CheckpointManager(cfg.checkpoint.directory,
                                   async_save=False, sharded="off")
    mngr_orbax.save(5, state, force=True)
    _cfg4, tr4 = _zero1_trainer(tmp_path)
    restored4, step4 = mngr_orbax.restore(tr4.state)
    assert step4 == 5
    mngr.close()
    mngr_orbax.close()
    reader.close()


def test_sharded_cross_host_count_restore(tmp_path):
    """The re-shard path proper: the SAME state written as-if by TWO
    hosts (its owned pieces split into two host files via the
    checkpoint/shards.py API the multi-process writer uses) restores
    bit-exact into a single-process trainer — and a single-host write
    restores into a different-mesh (fsdp) state. The reader never learns
    the writer count; it merges whatever host indexes exist."""
    from distributed_resnet_tensorflow_tpu.checkpoint import shards
    from distributed_resnet_tensorflow_tpu.checkpoint.manager import (
        _saveable)
    from distributed_resnet_tensorflow_tpu.parallel import create_mesh
    from distributed_resnet_tensorflow_tpu.resilience.manifest import (
        write_manifest)
    from distributed_resnet_tensorflow_tpu.utils.config import MeshConfig

    cfg, tr = _zero1_trainer(tmp_path)
    state = _train_steps(tr)
    parts = shards.host_snapshot_parts(_saveable(state))
    assert parts.owned, "zero1 state produced no sharded pieces"

    # split every sharded leaf's pieces across two synthetic hosts, as a
    # 2-process run would (each host owns a disjoint subset)
    def half(parts_owned, which):
        out = []
        for key, comps, gshape, dtype, pieces in parts_owned:
            mine = [p for i, p in enumerate(pieces) if i % 2 == which]
            if mine:
                out.append((key, comps, gshape, dtype, mine))
        return out

    staging = os.path.join(str(tmp_path), "ckpt2", "_staging.7")
    final = os.path.join(str(tmp_path), "ckpt2", "7")
    os.makedirs(os.path.dirname(final), exist_ok=True)
    shards.write_host_shards(
        staging, 0, shards.SnapshotParts(parts.base, half(parts.owned, 0)))
    shards.write_host_shards(
        staging, 1, shards.SnapshotParts([], half(parts.owned, 1)))
    write_manifest(staging, 7)
    os.replace(staging, final)

    # restore at ONE process (8-device zero1 target)
    _cfg2, tr2 = _zero1_trainer(tmp_path)
    reader = CheckpointManager(os.path.dirname(final), writer=False,
                               async_save=False)
    restored, step = reader.restore(tr2.state)
    assert step == 7
    for a, b in zip(jax.tree_util.tree_leaves(_saveable(state)),
                    jax.tree_util.tree_leaves(_saveable(restored))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # ...and the single-host write restores into an fsdp mesh (different
    # shard geometry than it was written from)
    cfg3 = _logistic_cfg(tmp_path, **{"optimizer.name": "lamb",
                                      "optimizer.zero1": "on",
                                      "optimizer.zero1_min_size": "8"})
    tr3 = Trainer(cfg3, mesh=create_mesh(MeshConfig(data=4, fsdp=2)))
    tr3.init_state()
    restored3, step3 = reader.restore(tr3.state)
    assert step3 == 7
    for a, b in zip(jax.tree_util.tree_leaves(state.params),
                    jax.tree_util.tree_leaves(restored3.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    reader.close()


def test_sharded_torn_staging_invisible_and_swept(tmp_path):
    """A staged-but-uncommitted sharded save is invisible to every
    committed-step reader and swept by the next writer-side manager."""
    from distributed_resnet_tensorflow_tpu.checkpoint import shards
    from distributed_resnet_tensorflow_tpu.checkpoint.manager import (
        _saveable)
    from distributed_resnet_tensorflow_tpu.resilience.manifest import (
        committed_steps, is_staging_name)

    cfg, tr = _zero1_trainer(tmp_path)
    state = _train_steps(tr)
    parts = shards.host_snapshot_parts(_saveable(state))
    staging = os.path.join(cfg.checkpoint.directory, "_staging.9")
    os.makedirs(cfg.checkpoint.directory, exist_ok=True)
    shards.write_host_shards(staging, 0, parts)
    shards.write_done_marker(staging, 0)  # staged, never committed
    assert committed_steps(cfg.checkpoint.directory) == []
    mngr = CheckpointManager(cfg.checkpoint.directory, async_save=False)
    assert not [n for n in os.listdir(cfg.checkpoint.directory)
                if is_staging_name(n)]
    restored, step = mngr.restore(tr.state)
    assert step is None
    mngr.close()


def test_shard_reader_torn_set_raises(tmp_path):
    """A shard set with a missing host file (incomplete coverage) must
    fail the assemble loudly — restore then falls back to an older
    committed step instead of silently zero-filling optimizer state."""
    from distributed_resnet_tensorflow_tpu.checkpoint import shards
    from distributed_resnet_tensorflow_tpu.checkpoint.manager import (
        _saveable)

    cfg, tr = _zero1_trainer(tmp_path)
    state = _train_steps(tr)
    parts = shards.host_snapshot_parts(_saveable(state))
    key0, comps0, gshape0, dtype0, pieces0 = parts.owned[0]
    assert len(pieces0) > 1
    step_dir = os.path.join(str(tmp_path), "torn", "11")
    shards.write_host_shards(
        step_dir, 0,
        shards.SnapshotParts(parts.base, [
            (key0, comps0, gshape0, dtype0, pieces0[:1])]))  # half a leaf
    with shards.ShardReader(step_dir) as reader:
        with pytest.raises(ValueError, match="torn shard set"):
            reader.assemble(key0)


def test_sharded_swap_subtree_read(tmp_path):
    """The serving hot-swap's read path: params/batch_stats/step rebuild
    as host numpy straight from the shard indexes (serve/swap.py uses
    exactly this on a sharded checkpoint)."""
    from distributed_resnet_tensorflow_tpu.checkpoint import shards

    cfg, tr = _zero1_trainer(tmp_path)
    state = _train_steps(tr)
    mngr = CheckpointManager(cfg.checkpoint.directory, async_save=False,
                             sharded="on")
    mngr.save(2, state)
    mngr.wait_until_finished()
    step_dir = os.path.join(cfg.checkpoint.directory, "2")
    with shards.ShardReader(step_dir) as reader:
        assert int(np.asarray(reader.read_subtree("step"))) == 2
        params = reader.read_subtree("params")
    flat_live = jax.tree_util.tree_flatten_with_path(state.params)[0]
    for path, leaf in flat_live:
        cur = params
        for p in path:
            cur = cur[p.key]
        np.testing.assert_array_equal(cur, np.asarray(leaf))
    mngr.close()


def test_ckpt_shard_event_row(tmp_path):
    """Per-host shard accounting rides ckpt_async_stats into the
    registered ckpt_shard event row; a second cadence with no new bytes
    writes nothing."""
    from distributed_resnet_tensorflow_tpu.train.hooks import CkptShardHook
    from distributed_resnet_tensorflow_tpu.utils.metrics import (
        MetricsWriter, ckpt_async_stats, read_metrics)

    cfg, tr = _zero1_trainer(tmp_path)
    state = _train_steps(tr)
    ckpt_async_stats.reset()
    mngr = CheckpointManager(cfg.checkpoint.directory, async_save=True,
                             sharded="on")
    mngr.save(2, state)
    mngr.wait_until_finished()
    snap = ckpt_async_stats.snapshot()
    assert snap["shard_bytes"] > 0 and snap["shard_files"] >= 2
    w = MetricsWriter(str(tmp_path / "m"), enable_tensorboard=False)
    hook = CkptShardHook(w, every_steps=1)
    hook(1, state, {})
    hook(2, state, {})  # nothing advanced — no second row
    w.close()
    rows = [r for r in read_metrics(str(tmp_path / "m"))
            if r.get("event") == "ckpt_shard"]
    assert len(rows) == 1
    assert rows[0]["shard_bytes"] == snap["shard_bytes"]
    assert rows[0]["process"] == 0
    mngr.close()


_SHARDED_KILL_CHILD = r"""
import os, sys
sys.path.insert(0, {repo!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8")
from distributed_resnet_tensorflow_tpu.utils.virtual_devices import (
    force_cpu_platform)
force_cpu_platform()
from distributed_resnet_tensorflow_tpu.utils.config import (get_preset,
                                                            MeshConfig)
from distributed_resnet_tensorflow_tpu.parallel import create_mesh
from distributed_resnet_tensorflow_tpu.train import Trainer
from distributed_resnet_tensorflow_tpu.checkpoint import CheckpointManager
from distributed_resnet_tensorflow_tpu.resilience import faultinject

cfg = get_preset("smoke")
cfg.model.name = "logistic"
cfg.model.input_size = 64
cfg.model.hidden_units = 32
cfg.model.num_classes = 4
cfg.optimizer.name = "lamb"
cfg.optimizer.zero1 = "on"
cfg.optimizer.zero1_min_size = 8
tr = Trainer(cfg, mesh=create_mesh(MeshConfig(data=8)))
tr.init_state()
ckpt_dir = sys.argv[1]
marker = sys.argv[2]
m = CheckpointManager(ckpt_dir, async_save=True, sharded="on")
m.save(1, tr.state.replace(step=tr.state.step + 1), force=True)
m.wait_until_finished()
print("STEP1_COMMITTED", flush=True)
# arm the commit-window nap ONLY for the step-2 save (it sits between the
# shard-marker finalize wait and the manifest+rename), hand it to the
# writer thread, and report readiness — the parent SIGKILLs us inside the
# nap with every shard file staged but nothing committed
os.environ[faultinject.CKPT_COMMIT_SLEEP_ENV_VAR] = "60"
os.environ[faultinject.CKPT_COMMIT_MARKER_ENV_VAR] = marker
m.save(2, tr.state.replace(step=tr.state.step + 2), force=True)
m.wait_until_finished()
print("UNREACHABLE", flush=True)
"""


@pytest.mark.slow  # subprocess + jax import; runs in the full suite and chaos_smoke.sh
def test_kill_during_sharded_commit_restores_committed_step(tmp_path):
    """Crash consistency for the SHARDED layout: SIGKILL while the writer
    sits between staging its per-host shard files (markers down) and the
    manifest+commit rename. The torn staging dir — shard files and all —
    must never read as a checkpoint, the next manager sweeps it, and
    restore lands on the newest committed step across all hosts."""
    import signal
    import subprocess
    import sys as _sys
    from distributed_resnet_tensorflow_tpu.resilience.manifest import (
        committed_steps, is_staging_name)

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ckpt_dir = str(tmp_path / "ckpt")
    marker = str(tmp_path / "marker")
    child = subprocess.Popen(
        [_sys.executable, "-c", _SHARDED_KILL_CHILD.format(repo=repo),
         ckpt_dir, marker],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    try:
        deadline = time.monotonic() + 180
        while not os.path.exists(marker):
            assert time.monotonic() < deadline, \
                "writer never reached the commit window"
            assert child.poll() is None, "child died early"
            time.sleep(0.05)
        child.send_signal(signal.SIGKILL)
        child.wait(timeout=30)
    finally:
        if child.poll() is None:
            child.kill()
    assert committed_steps(ckpt_dir) == [1]
    staging = [n for n in os.listdir(ckpt_dir) if is_staging_name(n)]
    assert staging, "expected the torn staging dir to survive the kill"
    # fresh writer-side manager sweeps it; restore lands on step 1
    cfg, tr = _zero1_trainer(tmp_path)
    mngr = CheckpointManager(ckpt_dir, async_save=False)
    assert not [n for n in os.listdir(ckpt_dir) if is_staging_name(n)]
    restored, step = mngr.restore(tr.state)
    assert step == 1 and int(restored.step) == 1
    mngr.close()
