"""unsharded-opt-state: a ZeRO-1 preset must actually shard something.

``optimizer.zero1=on`` promises the operator that per-replica optimizer
memory shrinks by ~(N-1)/N. The rule table (parallel/sharding.zero1_rules)
keeps that promise only when the model's optimizer-state leaves have a
dim the ``data`` axis divides — a preset whose shapes defeat every rule
(all leaves below ``zero1_min_size``, or no divisible dim on the
canonical dp layout) trains with the FULL replicated state while the
config claims otherwise: silent replicated memory, the exact failure
mode the Trainer's dead-axis checks exist to prevent, except this one
only shows up as an OOM at scale.

This rule RESOLVES each registered preset that sets ``optimizer.zero1``
to ``"on"`` (the static promise; ``auto`` presets make no unconditional
claim) against the canonical 8-way dp layout via the real rule table and
abstract state init — zero devices, zero compute — and flags the preset
FACTORY (file:line in utils/config.py) when the resolution leaves every
optimizer-state leaf replicated.
"""
from __future__ import annotations

import inspect
import os
from typing import Iterable

from ..report import Finding

RULE_NAME = "unsharded-opt-state"
DOC = __doc__

#: canonical layout the promise is checked against — the smallest mesh
#: every dp preset must scale to
CANONICAL_DATA_SHARDS = 8


def _zero1_resolves_sharded(cfg) -> bool:
    """True when at least one optimizer-state leaf shards over ``data``
    on the canonical dp layout. Pure shape/spec work (eval_shape + the
    rule table with a sizes-only mesh stand-in) — no devices needed."""
    from ...models import create_model
    from ...parallel.sharding import (ZERO1_MIN_SIZE, Zero1Report,
                                      _SizesMesh, match_partition_rules,
                                      zero1_rules)
    from ...train.optimizers import create_optimizer
    from ...train.schedules import create_schedule
    from ...train.state import abstract_train_state

    model = create_model(cfg.model, cfg.data.dataset)
    tx = create_optimizer(cfg.optimizer, create_schedule(cfg.optimizer))
    shape = (1, cfg.data.image_size, cfg.data.image_size, 3) \
        if cfg.model.name != "logistic" else (1, cfg.model.input_size)
    state = abstract_train_state(model, tx, shape)
    report = Zero1Report(CANONICAL_DATA_SHARDS)
    match_partition_rules(
        zero1_rules(_SizesMesh({"data": CANONICAL_DATA_SHARDS}),
                    min_size=cfg.optimizer.zero1_min_size
                    or ZERO1_MIN_SIZE,
                    report=report),
        state.opt_state)
    return report.sharded_leaves > 0


def check(ctx) -> Iterable[Finding]:
    from ...utils.config import PRESETS
    for name, factory in sorted(PRESETS.items()):
        try:
            cfg = factory()
        except Exception:
            continue  # a broken preset is someone else's finding
        if cfg.optimizer.zero1 != "on":
            continue
        try:
            if _zero1_resolves_sharded(cfg):
                continue
        except Exception as e:
            detail = f"{type(e).__name__}: {e}"
            yield _finding(ctx, name, factory,
                           f"preset {name!r}: optimizer.zero1=on but the "
                           f"resolution itself failed ({detail[:200]})")
            continue
        yield _finding(
            ctx, name, factory,
            f"preset {name!r} sets optimizer.zero1=on but the rule table "
            f"resolves EVERY optimizer-state leaf replicated on the "
            f"{CANONICAL_DATA_SHARDS}-way dp layout — the config promises "
            "a (N-1)/N per-replica memory cut it cannot deliver; pick "
            "shapes a data axis divides or drop the knob")


def _finding(ctx, name: str, factory, message: str) -> Finding:
    """Anchor the finding at the preset factory's def line, repo-relative
    when the factory lives under the linted root."""
    try:
        path = inspect.getsourcefile(factory) or ""
        line = inspect.getsourcelines(factory)[1]
    except (OSError, TypeError):
        path, line = "", 0
    rel = os.path.relpath(path, ctx.root) if path else \
        "distributed_resnet_tensorflow_tpu/utils/config.py"
    if rel.startswith(".."):
        rel = path
    return Finding(RULE_NAME, rel, line, message)
