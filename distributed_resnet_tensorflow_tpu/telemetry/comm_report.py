"""Per-collective runtime attribution: ``main.py comm-report``.

PR 13's hangcheck committed the STATIC collective schedule
(``analysis/collective_schedules.json``: ordered kind/axes/bytes per
traced step variant) and the overlap plan rows record WHAT should move
per bucket — but neither says what each bucket actually COSTS. This
reducer joins three sources into one per-bucket table:

  * the static schedule (kind + axes per collective, committed artifact),
  * the plan (``{"event": "comm_overlap"}``: per-bucket grad/wire bytes
    and leaf counts, issue order),
  * the measurement (``{"event": "comm_timing"}``: each bucket's
    collective timed STANDALONE on the live mesh by
    ``parallel/overlap.probe_comm_plan``, plus the measured live step
    time)

into achieved bytes/sec per bucket, each bucket's share of the total
exchange cost, and the overlap headroom ``comm_step_ratio`` — the share
of every step the exchange would cost if NOTHING were hidden. That makes
"the bucketed exchange is slow" answerable as "bucket 3 (the 14.7 MB
conv block) runs at 2.1 GB/s while its peers do 9" instead of one
aggregate ratio.

Semantics worth being precise about (docs/observability.md):
``probe_secs`` is the bucket's collective fully EXPOSED — the overlapped
step hides some or all of it behind backprop, so ``comm_step_ratio`` is
an upper bound on what communication can be costing, not a measurement
of what it does cost. The achieved OVERLAP FRACTION needs a no-exchange
step time to difference against; pass one with ``--step-secs-off``
(e.g. the ``off`` leg of ``bench.py``'s overlap row) and the report
computes ``1 − (step_on − step_off) / comm_secs_total``, clamped to
[0, 1].
"""
from __future__ import annotations

import argparse
import json
import logging
import os
from typing import Dict, List, Optional, Tuple

log = logging.getLogger(__name__)

#: schedule ops that can carry a gradient-exchange bucket's payload
_EXCHANGE_OPS = ("psum", "psum_scatter")

#: staged (hierarchical) plans additionally issue an intra-tier
#: all-gather; admitted only when the signature carries the per-op wire
#: ledger (a forward fsdp all-gather must never steal a flat match)
_EXCHANGE_OPS_HIER = _EXCHANGE_OPS + ("all_gather",)


def default_schedule_path() -> str:
    from .. import analysis
    return os.path.join(os.path.dirname(analysis.__file__),
                        "collective_schedules.json")


def load_schedules(path: Optional[str] = None) -> Dict[str, dict]:
    """The committed schedule artifact's ``signatures`` map; empty when
    the file is absent/unreadable (the report degrades to measured-only
    — a run on an uncommitted preset must still be reportable)."""
    path = path or default_schedule_path()
    try:
        with open(path) as f:
            return json.load(f).get("signatures", {})
    except (OSError, ValueError) as e:
        log.warning("comm-report: no readable schedule at %s (%s)", path, e)
        return {}


def _expanded_ops(signature: dict) -> List[dict]:
    """The signature's op list with RLE counts expanded — one entry per
    collective, schedule order."""
    out: List[dict] = []
    for op in signature.get("ops", []):
        for _ in range(int(op.get("count", 1))):
            out.append({k: v for k, v in op.items() if k != "count"})
    return out


def _match_buckets(buckets: List[dict],
                   signature: Optional[dict]) -> Tuple[int, List[dict]]:
    """In-order subsequence match of the measured buckets' wire bytes
    against the schedule's exchange-capable ops (the same matching
    discipline analysis/collectives.py uses for the declared plan).
    Returns (matched count, buckets annotated with static kind/axes).

    Hierarchical signatures (a ``plan.bucket_op_wire_bytes`` ledger):
    the measured bucket's probe payload is the bucket WIRE bytes, but
    the staged trace opens with a reduce-scatter whose input is the
    padded payload — so each bucket matches against its ledger's FIRST
    op bytes instead, and the staged all-gather joins the admissible op
    set."""
    annotated = [dict(b) for b in buckets]
    if not signature:
        return 0, annotated
    plan = signature.get("plan") or {}
    op_wire = plan.get("bucket_op_wire_bytes")
    exchange_ops = _EXCHANGE_OPS_HIER if op_wire else _EXCHANGE_OPS
    ops = _expanded_ops(signature)
    cursor = 0
    matched = 0
    for j, b in enumerate(annotated):
        want = int(b["wire_bytes"])
        if op_wire and j < len(op_wire) and op_wire[j]:
            want = int(op_wire[j][0])
        hit = None
        for i in range(cursor, len(ops)):
            op = ops[i]
            if op.get("op") in exchange_ops and \
                    int(op.get("bytes", -1)) == want:
                hit = i
                break
        if hit is None:
            b["static"] = None
            continue
        cursor = hit + 1
        matched += 1
        b["static"] = {"kind": ops[hit].get("op"),
                       "axes": ops[hit].get("axes"),
                       "operands": ops[hit].get("operands")}
        if ops[hit].get("tier"):
            b["static"]["tier"] = ops[hit]["tier"]
            b["static"]["groups"] = ops[hit].get("groups")
    return matched, annotated


def select_schedule_key(signatures: Dict[str, dict],
                        buckets: List[dict],
                        key: Optional[str] = None
                        ) -> Tuple[Optional[str], List[str]]:
    """Resolve which schedule signature to join against. An explicit
    ``key`` wins (missing = error); otherwise the overlap-variant keys
    whose op stream fully matches the measured buckets are candidates —
    a unique one is used, several report the ambiguity."""
    if key is not None:
        if key not in signatures:
            raise KeyError(
                f"schedule key {key!r} not in the committed artifact; "
                f"available: {sorted(signatures)}")
        return key, [key]
    candidates = []
    for k in sorted(signatures):
        # exchange-bearing variants only: the bucketed exchange traces as
        # .../overlap, .../overlap+zero1 or (halved wire bytes under
        # comm.compress) .../bf16+compress — train/serve variants carry no
        # per-bucket exchange to join against
        variant = k.rsplit("/", 1)[-1]
        if "overlap" not in variant and "compress" not in variant:
            continue
        matched, _ = _match_buckets(buckets, signatures[k])
        if buckets and matched == len(buckets):
            candidates.append(k)
    return (candidates[0] if len(candidates) == 1 else None), candidates


def find_rows(root: str) -> Tuple[Optional[dict], Optional[dict]]:
    """The newest ``comm_timing`` and ``comm_overlap`` rows under a
    log_root (any stream — the chief writes both)."""
    from ..utils.metrics import iter_metric_streams
    timing = overlap = None
    for rows in iter_metric_streams(root):
        for row in rows:
            if row.get("event") == "comm_timing":
                if timing is None or row.get("time", 0) > \
                        timing.get("time", 0):
                    timing = row
            elif row.get("event") == "comm_overlap":
                if overlap is None or row.get("time", 0) > \
                        overlap.get("time", 0):
                    overlap = row
    return timing, overlap


def synthesize_timing(overlap: dict,
                      catalog: Optional[dict] = None) -> Optional[dict]:
    """A ``comm_timing``-shaped dict MODELED from the run's
    ``comm_overlap`` bucket plan × the fabric's persisted bandwidth
    catalog (telemetry/bandwidth.py) — the no-live-probe path: a run
    whose probe was off (telemetry.comm_timing=false) or whose mesh is
    gone can still be attributed from what this fabric has measured
    before. Buckets carry ``modeled: True`` so the report and its
    consumers cannot mistake a model for a measurement. None when
    either side is missing."""
    from . import bandwidth as bw_mod
    from .planner import BandwidthTable
    if not overlap or not overlap.get("bucket_wire_bytes"):
        return None
    catalog = catalog if catalog is not None else bw_mod.load_catalog()
    table = BandwidthTable.from_catalog(catalog)
    if table is None:
        return None
    wires = overlap["bucket_wire_bytes"]
    sizes = overlap.get("bucket_bytes") or wires
    leaves = overlap.get("bucket_leaves") or [0] * len(wires)
    sigs = overlap.get("bucket_reduce_axes") or ["data"] * len(wires)
    buckets = []
    total = 0.0
    for i, (wire, size, nl, sig) in enumerate(
            zip(wires, sizes, leaves, sigs)):
        bps, lat = table.lookup(sig)
        secs = lat + int(wire) / bps
        total += secs
        buckets.append({
            "bucket": i, "bytes": int(size), "wire_bytes": int(wire),
            "leaves": int(nl), "axes": sig,
            "probe_secs": round(secs, 6),
            "wire_bytes_per_sec": round(int(wire) / secs, 1)
            if secs > 0 else 0.0,
            "modeled": True,
        })
    return {"buckets": buckets, "comm_secs_total": round(total, 6),
            "reps": 0, "axes": sorted({a for s in sigs
                                       for a in s.split("+")}),
            "compress": overlap.get("compress", "off"),
            "modeled_from_catalog": (catalog or {}).get("fabric", "?")}


def build_report(timing: dict, overlap: Optional[dict] = None,
                 signatures: Optional[Dict[str, dict]] = None,
                 key: Optional[str] = None,
                 step_secs_off: Optional[float] = None,
                 schedule_path: Optional[str] = None) -> dict:
    """The joined per-bucket attribution. ``timing`` is a comm_timing
    row (or comm_timing_stats snapshot); everything else is optional —
    the report degrades gracefully to measured-only."""
    signatures = signatures or {}
    buckets = [dict(b) for b in timing.get("buckets", [])]
    candidates: List[str] = []
    resolved = None
    if signatures:
        resolved, candidates = select_schedule_key(signatures, buckets, key)
    matched, buckets = _match_buckets(
        buckets, signatures.get(resolved) if resolved else None)
    comm_total = float(timing.get("comm_secs_total") or 0.0)
    for b in buckets:
        b["pct_of_comm"] = round(100.0 * b["probe_secs"] / comm_total, 2) \
            if comm_total > 0 else 0.0
    report: dict = {
        "buckets": buckets,
        "modeled_from_catalog": timing.get("modeled_from_catalog"),
        "comm_secs_total": comm_total,
        "compress": timing.get("compress", "off"),
        "axes": timing.get("axes"),
        "reps": timing.get("reps"),
        "schedule_key": resolved,
        "schedule_candidates": candidates,
        "schedule_matched": matched,
        "schedule_path": schedule_path or
        (default_schedule_path() if signatures else None),
    }
    if buckets:
        slowest = max(buckets, key=lambda b: b["probe_secs"])
        narrowest = min(buckets, key=lambda b: b["wire_bytes_per_sec"])
        report["bottleneck_bucket"] = slowest["bucket"]
        report["lowest_bandwidth_bucket"] = narrowest["bucket"]
    step_secs = timing.get("step_secs")
    if step_secs:
        report["step_secs"] = float(step_secs)
        report["comm_step_ratio"] = round(comm_total / float(step_secs), 4)
    if overlap is not None:
        report["plan"] = {
            "buckets": overlap.get("buckets"),
            "bucket_cap_bytes": overlap.get("bucket_cap_bytes"),
            "grad_bytes": overlap.get("grad_bytes"),
            "wire_bytes": overlap.get("wire_bytes"),
            "leaves": overlap.get("leaves"),
        }
    if step_secs_off is not None and step_secs and comm_total > 0:
        exposed = max(0.0, float(step_secs) - float(step_secs_off))
        report["step_secs_off"] = float(step_secs_off)
        report["overlap_fraction"] = round(
            min(1.0, max(0.0, 1.0 - exposed / comm_total)), 4)
    return report


def render(report: dict) -> str:
    lines = ["== comm-report :: per-bucket runtime attribution =="]
    if report.get("modeled_from_catalog"):
        lines.append("  NOTE: timings MODELED from the bandwidth "
                     f"catalog (fabric {report['modeled_from_catalog']}"
                     ") — no live probe ran (docs/planner.md)")
    if report.get("schedule_key"):
        lines.append(f"  schedule: {report['schedule_key']} "
                     f"({report['schedule_matched']}/"
                     f"{len(report['buckets'])} buckets matched)")
    elif report.get("schedule_candidates"):
        lines.append("  schedule: ambiguous — candidates "
                     f"{report['schedule_candidates']} (pass --key)")
    else:
        lines.append("  schedule: no matching signature (measured-only "
                     "report)")
    hdr = (f"  {'bkt':>3} {'leaves':>6} {'bytes':>12} {'wire':>12} "
           f"{'secs':>9} {'GB/s':>7} {'%comm':>6}  static")
    lines.append(hdr)
    for b in report["buckets"]:
        st = b.get("static")
        st_txt = f"{st['kind']}@{','.join(st['axes'])}" if st else "-"
        lines.append(
            f"  {b['bucket']:>3} {b['leaves']:>6} {b['bytes']:>12} "
            f"{b['wire_bytes']:>12} {b['probe_secs']:>9.6f} "
            f"{b['wire_bytes_per_sec'] / 1e9:>7.2f} "
            f"{b['pct_of_comm']:>6.1f}  {st_txt}")
    lines.append(f"  total exchange (exposed): "
                 f"{report['comm_secs_total'] * 1e3:.2f} ms "
                 f"(compress={report.get('compress')}, "
                 f"axes={report.get('axes')})")
    if "step_secs" in report:
        lines.append(
            f"  measured step: {report['step_secs'] * 1e3:.2f} ms -> "
            f"comm/step ratio {report['comm_step_ratio']:.3f} "
            "(upper bound: the exchange fully exposed)")
    if "bottleneck_bucket" in report:
        lines.append(f"  bottleneck: bucket {report['bottleneck_bucket']} "
                     "(largest standalone cost); lowest bandwidth: "
                     f"bucket {report['lowest_bandwidth_bucket']}")
    if "overlap_fraction" in report:
        lines.append(
            f"  overlap fraction vs step_secs_off="
            f"{report['step_secs_off'] * 1e3:.2f} ms: "
            f"{report['overlap_fraction']:.3f}")
    return "\n".join(lines)


def main_comm_report(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="main.py comm-report",
        description="join the committed collective schedule with the "
                    "measured per-bucket exchange timings "
                    "(docs/observability.md)")
    ap.add_argument("--root", default="/tmp/drt_tpu",
                    help="the run's log_root (comm_timing/comm_overlap "
                         "rows)")
    ap.add_argument("--schedules", default="",
                    help="collective_schedules.json path (default: the "
                         "committed analysis artifact)")
    ap.add_argument("--key", default=None,
                    help="schedule signature key, e.g. "
                         "'cifar10_resnet50@dp_fsdp/overlap' (default: "
                         "unique fully-matching overlap variant)")
    ap.add_argument("--step-secs-off", type=float, default=None,
                    help="a no-/unbucketed-exchange step time to "
                         "difference against (bench overlap row 'off' "
                         "leg) -> achieved overlap fraction")
    ap.add_argument("--catalog", default=None,
                    help="bandwidth-catalog path to model timings from "
                         "when no comm_timing row exists (default: this "
                         "fabric's results/bandwidth/<fabric>.json)")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as JSON")
    ns = ap.parse_args(argv)
    timing, overlap = find_rows(ns.root)
    if timing is None and overlap is not None:
        # no live probe, but the run left its bucket plan and the fabric
        # has a persisted catalog: model the timings instead of refusing
        from . import bandwidth as bw_mod
        catalog = bw_mod.load_catalog(path=ns.catalog) \
            if ns.catalog else bw_mod.load_catalog()
        timing = synthesize_timing(overlap, catalog)
    if timing is None:
        print(f"comm-report: no comm_timing row under {ns.root} — the "
              "probe runs when comm.overlap is active and "
              "telemetry.comm_timing is on (and no comm_overlap row + "
              "bandwidth catalog existed to model from)")
        return 1
    schedule_path = ns.schedules or default_schedule_path()
    signatures = load_schedules(schedule_path)
    try:
        report = build_report(timing, overlap, signatures, key=ns.key,
                              step_secs_off=ns.step_secs_off,
                              schedule_path=schedule_path)
    except KeyError as e:
        print(f"comm-report: {e.args[0]}")
        return 1
    print(json.dumps(report) if ns.json else render(report))
    return 0
