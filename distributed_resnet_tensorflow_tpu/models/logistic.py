"""Toy 1-hidden-layer MLP — debug stand-in for ResNet.

Parity with reference logist_model.py (LRNet: flattened image → dense(hidden)
→ ReLU → dense(classes), reference logist_model.py:14-58). Used to debug the
distribution layer without conv cost, like the reference's commented swap at
resnet_cifar_main.py:257.
"""
from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


class LogisticNet(nn.Module):
    num_classes: int = 10
    hidden_units: int = 100

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        del train
        x = x.reshape((x.shape[0], -1)).astype(jnp.float32)
        x = nn.Dense(self.hidden_units)(x)
        x = nn.relu(x)
        return nn.Dense(self.num_classes)(x)
