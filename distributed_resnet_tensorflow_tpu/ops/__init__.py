from .batch_norm import GroupedBatchNorm  # noqa: F401
from .attention import (  # noqa: F401
    attention,
    blockwise_attention,
    ring_attention,
    ring_attention_sharded,
)
