"""Benchmark — one JSON line covering the framework's headline numbers.

Workloads (all single-chip, synthetic data unless noted):
  * CIFAR-10 ResNet-50 (6·8+2) gbs=128 — the reference's flagship single-node
    number: 13.94 steps/sec on 1× P100 (reference README.md:28-30; BASELINE.md).
  * The SAME workload fed by the real input pipeline (CIFAR-format files on
    disk → parse → augment → standardize → threaded stack → device put) —
    proves the fused-dispatch input path keeps up with compute.
  * ImageNet ResNet-50 224² bf16 at the largest per-chip batch that fits —
    the BASELINE.md north-star workload (reference: 0.96 steps/sec at bs=128
    on P100, README.md:50), with MFU from XLA's own cost analysis.

Prints ONE JSON line: the headline metric stays the CIFAR steps/sec
(round-over-round comparable), everything else rides in extra keys.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import time

import jax
import numpy as np

# persistent compile cache: the bench compiles several large RN50/ViT scan
# programs; repeat runs (driver + dev) should pay XLA only once
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

CIFAR_BASELINE_STEPS_PER_SEC = 13.94      # reference README.md:28-30 (1x P100)
IMAGENET_BASELINE_IMAGES_PER_SEC = 122.9  # 0.96 st/s × bs 128 (README.md:50)


def _best_time(fn, state, batches, loops: int, reps: int = 5, fence=None):
    """Best-of-reps wall time for ``loops`` dispatches (remote-tunnel TPU is
    noisy). Returns (final_state, best_seconds).

    ``fence`` syncs host and device at the end of each rep; the default is
    ``block_until_ready(state.params)`` (the long-standing rows' timing,
    kept round-over-round comparable). Pass a host-pull fence for new rows:
    on the tunneled backend block_until_ready can return before compute
    finishes on some programs (docs/perf_vit_r5.md measurement note).
    Measured (round 5): both fences agree within 0.8% on the legacy WRN
    (33.7 vs 33.6 steps/s) and ImageNet-bs128 (23.2 vs 23.0) rows, so the
    default is sound for those programs — the early-return pathology was
    only ever observed on the large dense-attention program."""
    if fence is None:
        fence = lambda st: jax.block_until_ready(st.params)  # noqa: E731
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        for i in range(loops):
            state, m = fn(state, batches[i % len(batches)])
        fence(state)
        best = min(best, time.perf_counter() - t0)
    return state, best


def _host_pull_fence(state):
    """Fence through a host transfer of a param sum — the sync that is
    reliable on the tunneled backend (see _best_time)."""
    import jax.numpy as jnp
    return float(jnp.sum(jax.tree_util.tree_leaves(state.params)[0]
                         .astype(jnp.float32)))


def bench_cifar():
    """Synthetic + real-input CIFAR ResNet-50, sharing one compiled step."""
    from distributed_resnet_tensorflow_tpu.parallel.sharding import (
        shard_batch, shard_stacked_batch)
    from distributed_resnet_tensorflow_tpu.train import Trainer
    from distributed_resnet_tensorflow_tpu.utils import profiling
    from distributed_resnet_tensorflow_tpu.utils.config import get_preset

    cfg = get_preset("cifar10_resnet50")  # resnet_size=50, bs=128, momentum
    # dataset=cifar10 (not synthetic) so the step includes the device-side
    # augmentation exactly as real training runs it (ops/augment.py)
    cfg.data.data_dir = _synth_cifar_files()
    cfg.data.prefetch_batches = 2
    k = 20
    cfg.train.steps_per_loop = k
    cfg.mesh.data = len(jax.devices())
    trainer = Trainer(cfg)
    trainer.init_state()
    multi_fn = trainer.jitted_multi_step(k)

    rng = np.random.RandomState(0)
    batch = shard_stacked_batch({
        "images": rng.randn(k, 128, 32, 32, 3).astype(np.float32),
        "labels": rng.randint(0, 10, (k, 128)).astype(np.int32),
    }, trainer.mesh)

    state = trainer.state
    for _ in range(2):  # warmup / compile
        state, _m = multi_fn(state, batch)
    jax.block_until_ready(state.params)
    loops = 10
    state, dt = _best_time(multi_fn, state, [batch], loops)
    steps_per_sec = loops * k / dt

    # per-step FLOPs via the single-step jit (same computation the scan runs)
    single = trainer.jitted_train_step()
    one = shard_batch({"images": np.asarray(batch["images"])[0],
                       "labels": np.asarray(batch["labels"])[0]}, trainer.mesh)
    step_flops = profiling.flops_per_step(single, state, one)
    util = profiling.mfu(steps_per_sec, step_flops) if step_flops else None

    # ---- real input through Trainer.train ------------------------------
    # (a) device-resident dataset — what run_train does on TPU: data in HBM,
    # host ships only indices (data/device_dataset.py)
    from distributed_resnet_tensorflow_tpu.data import (
        create_input_iterator, epoch_index_iterator, load_cifar)
    images, labels = load_cifar("cifar10", cfg.data.data_dir, "train")
    trainer.state = state
    trainer.attach_device_dataset(images, labels)
    it_idx = epoch_index_iterator(len(labels), 128, seed=1)
    trainer.train(it_idx, num_steps=k)  # warmup: compiles the index scan
    jax.block_until_ready(trainer.state.params)
    n_real = 400
    t0 = time.perf_counter()
    trainer.train(it_idx, num_steps=n_real)
    jax.block_until_ready(trainer.state.params)
    real_steps_per_sec = n_real / (time.perf_counter() - t0)

    # (b) streamed raw-uint8 batches — the multi-host path (per-process
    # shards can't live in one HBM); bounded by host+transfer
    trainer.detach_device_dataset()
    it = create_input_iterator(cfg, mode="train")
    trainer.train(it, num_steps=k)  # warmup: compiles the raw-uint8 trace
    jax.block_until_ready(trainer.state.params)
    # best-of-2: this path is bounded by host->device transfer, which on a
    # tunneled link swings by several x between runs
    n_s = 100
    streamed_steps_per_sec = 0.0
    for _ in range(2):
        t0 = time.perf_counter()
        trainer.train(it, num_steps=n_s)
        jax.block_until_ready(trainer.state.params)
        streamed_steps_per_sec = max(streamed_steps_per_sec,
                                     n_s / (time.perf_counter() - t0))

    # (c) the streamed path's decomposition, so the number above is
    # attributable: the host-side pipeline alone (draw raw-uint8 batches,
    # no device), and the raw host→device transfer bandwidth at the
    # stacked-group granularity. On this machine the device link is a
    # remote tunnel (MB/s, swings several×) — the streamed rate IS the
    # transfer rate; a TPU-VM's PCIe moves the same batches ~1000× faster.
    it2 = create_input_iterator(cfg, mode="train")
    next(it2)
    t0 = time.perf_counter()
    n_h = 300
    for _ in range(n_h):
        next(it2)
    host_only = n_h / (time.perf_counter() - t0)
    import jax.numpy as jnp
    blob = np.random.RandomState(1).randint(
        0, 256, 8 * 10 ** 6, dtype=np.uint8)
    # raw-link probe: measuring device_put itself IS the point here
    jax.device_put(blob).block_until_ready()  # shardcheck: ok(stray-device-put)
    best_put = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        y = jax.device_put(blob)  # shardcheck: ok(stray-device-put)
        float(jnp.sum(y[:8].astype(jnp.float32)))  # fence via host pull
        best_put = min(best_put, time.perf_counter() - t0)

    return {
        "steps_per_sec": round(steps_per_sec, 2),
        "mfu": round(util, 4) if util else None,
        "real_input_steps_per_sec": round(real_steps_per_sec, 2),
        "real_vs_synthetic": round(real_steps_per_sec / steps_per_sec, 3),
        "streamed_input_steps_per_sec": round(streamed_steps_per_sec, 2),
        "streamed_host_only_batches_per_sec": round(host_only, 1),
        "device_put_MBps": round(8.0 / best_put, 1),
    }


def _synth_cifar_files() -> str:
    """CIFAR-10-format binary files (random content) for the input-pipeline
    bench — the full parse/augment path without shipping the dataset."""
    d = os.path.join(tempfile.gettempdir(), "drt_bench_cifar")
    marker = os.path.join(d, "data_batch_5.bin")
    if not os.path.exists(marker):
        os.makedirs(d, exist_ok=True)
        rng = np.random.RandomState(0)
        for i in range(1, 6):
            rec = rng.randint(0, 256, size=(10000, 3073), dtype=np.uint8)
            rec[:, 0] = rng.randint(0, 10, size=10000)
            rec.tofile(os.path.join(d, f"data_batch_{i}.bin"))
    return d


def _synth_imagenet_files(n_images: int = 256) -> str:
    """Small ImageNet-format JPEG TFRecord shards (tools/make_synth_imagenet
    content model) cached in /tmp — enough images to measure steady-state
    decode throughput; the iterator loops epochs so count doesn't matter."""
    d = os.path.join(tempfile.gettempdir(), "drt_bench_imagenet")
    marker = os.path.join(d, "validation-00001-of-00002")
    if not os.path.exists(marker):
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "tools"))
        from make_synth_imagenet import write_split
        os.makedirs(d, exist_ok=True)
        write_split(d, "train", 4, 4, num_classes=16,
                    per_class=max(1, n_images // 16), seed=0)
        write_split(d, "validation", 2, 2, num_classes=16,
                    per_class=max(1, n_images // 32), seed=1)
    return d


def bench_imagenet_input(budget_left):  # budget_left: () -> seconds left
    """The SURVEY §7 #1 hard part, measured: streamed JPEG→VGG→device
    ImageNet training. Reports the host pipeline's standalone decode rate
    (per-core ceiling) and the end-to-end streamed step rate."""
    from distributed_resnet_tensorflow_tpu.data import create_input_iterator
    from distributed_resnet_tensorflow_tpu.data.imagenet import (
        imagenet_iterator)
    from distributed_resnet_tensorflow_tpu.train import Trainer
    from distributed_resnet_tensorflow_tpu.utils.config import get_preset

    d = _synth_imagenet_files()
    out = {}
    # (a) input pipeline alone, PIL vs the fused C++ decode: TFRecords →
    # scaled JPEG decode → uint8 crops (no device)
    ncpu = os.cpu_count() or 1

    def pipeline_rate(use_native):
        it = imagenet_iterator(d, 128, "train", device_standardize=True,
                               num_decode_threads=max(4, ncpu),
                               shuffle_buffer=256, use_native=use_native)
        next(it)  # warm the decode pool
        t0 = time.perf_counter()
        n_in = 6
        for _ in range(n_in):
            next(it)
        return round(128 * n_in / (time.perf_counter() - t0), 1)

    out["input_pipeline_images_per_sec"] = pipeline_rate(False)
    try:
        from distributed_resnet_tensorflow_tpu.data.native_loader import (
            native_jpeg_available)
        if native_jpeg_available():
            out["input_pipeline_native_images_per_sec"] = pipeline_rate(True)
    except Exception:
        pass
    out["host_cores"] = ncpu
    # the decode-pool width the auto defaults resolve to on this host
    # (data.resolve_decode_workers; explicit --set values would win)
    from distributed_resnet_tensorflow_tpu.data import resolve_decode_workers
    _p, _t = resolve_decode_workers(get_preset("imagenet_resnet50"))
    out["decode_workers_resolved"] = {"processes": _p, "threads": _t}

    # shared transfer probe: one imagenet-sized uint8 batch (128×224²×3 =
    # 19.3 MB) through device_put, so BOTH e2e rows below carry their own
    # bottleneck decomposition instead of a comment (VERDICT r4 #7)
    import jax.numpy as jnp
    bytes_per_image = 224 * 224 * 3
    probe = np.zeros((128, 224, 224, 3), np.uint8)
    # raw-link probe: measuring device_put itself IS the point here
    jax.device_put(probe).block_until_ready()  # shardcheck: ok(stray-device-put)
    best_put = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        y = jax.device_put(probe)  # shardcheck: ok(stray-device-put)
        float(jnp.sum(y[:2, :2, :2].astype(jnp.float32)))  # host-pull fence
        best_put = min(best_put, time.perf_counter() - t0)
    put_mbps = probe.nbytes / 1e6 / best_put
    ship_rate = put_mbps * 1e6 / bytes_per_image  # uint8 images/s the link moves
    out["transfer_probe"] = {"device_put_MBps": round(put_mbps, 1),
                             "images_per_sec": round(ship_rate, 1)}

    def attribute(e2e_rate, snap, extra, host_echo=1, transfer_echo=1):
        """Attribution FROM THE STAGE COUNTERS of the run itself
        (utils.metrics.input_stages; stages decode / echo / stack / stage /
        transfer instrumented in the pipeline threads), not from components
        re-measured in isolation: each stage's rate is items over its
        busiest worker's busy time DURING the e2e run, so when the stages
        genuinely overlap, e2e_vs_slowest_component sits near 1.0 — and
        when staging is serial it honestly sits low. ``extra`` carries the
        device-side probe (the one leg the input counters can't see).

        Echo awareness: with data echoing on, one decoded image feeds
        host_echo × transfer_echo steps and one shipped image feeds
        transfer_echo steps, so each stage's EFFECTIVE ceiling on the e2e
        rate is its raw busy rate times the echo factors downstream of it
        — those effective rates are what the bottleneck comparison uses
        (raw rates ride in stage_rates_raw_images_per_sec)."""
        raw = dict(extra)
        nbytes_per_s = {}
        for stage in ("decode", "echo", "stack", "stage", "transfer"):
            agg = snap.get(stage)
            if agg and agg["items"] and agg["max_thread_seconds"] > 0:
                raw[stage] = agg["items"] / agg["max_thread_seconds"]
                if agg.get("bytes"):
                    nbytes_per_s[stage] = agg["bytes"] / agg["seconds"]
        mult = {"decode": host_echo * transfer_echo, "echo": transfer_echo,
                "stack": transfer_echo, "stage": transfer_echo,
                "transfer": transfer_echo}
        rates = {k: v * mult.get(k, 1) for k, v in raw.items()}
        out = {"uint8_MB_per_image": round(bytes_per_image / 1e6, 3),
               "device_put_probe_MBps": round(put_mbps, 1),
               "stage_rates_images_per_sec": {
                   k: round(v, 1) for k, v in rates.items()},
               "dispatch_wait_seconds": round(
                   snap.get("dispatch_wait", {}).get("seconds", 0.0), 3)}
        if host_echo > 1 or transfer_echo > 1:
            out["stage_rates_raw_images_per_sec"] = {
                k: round(v, 1) for k, v in raw.items()}
            out["echo_factors"] = {"host": host_echo,
                                   "transfer": transfer_echo}
        if "transfer" in nbytes_per_s:
            # the coalesced path's measured H2D bandwidth (bytes the
            # staging thread moved over its transfer busy time)
            out["device_put_MBps"] = round(nbytes_per_s["transfer"] / 1e6, 1)
        if not rates:
            out["bottleneck"] = "no stage counters recorded"
            return out
        slowest = min(rates, key=rates.get)
        out.update({
            "bottleneck": slowest,
            "slowest_component": slowest,
            "slowest_component_images_per_sec": round(rates[slowest], 1),
            "e2e_vs_slowest_component": round(
                e2e_rate / max(rates[slowest], 1e-9), 3)})
        if e2e_rate < 0.7 * rates[slowest]:
            out["bottleneck"] = (
                f"residual serialization (components all faster; "
                f"slowest steady-state: {slowest})")
        return out

    # (a2) full validation pass (VERDICT r3 #6): the eval path is now
    # PIPELINED (Trainer.evaluate stages batches through the dedicated
    # transfer thread). Decomposed like the train rows: the HOST side
    # (decode to uint8 crops — what a TPU-VM deployment is bounded by),
    # the staged transfer, and the e2e pass, attributed from the stage
    # counters of the pass itself.
    from distributed_resnet_tensorflow_tpu.utils.metrics import input_stages
    try:
        cfg = get_preset("imagenet_resnet50")
        cfg.data.data_dir = d
        # decode pool width rides the auto defaults (resolved above)
        cfg.data.use_native_loader = True
        cfg.mesh.data = len(jax.devices())
        ev_host = create_input_iterator(cfg, mode="eval")
        t0 = time.perf_counter()
        n_host = sum(int(b.get("mask", np.ones(len(b["labels"]))).sum())
                     for b in ev_host)
        host_rate = n_host / (time.perf_counter() - t0)
        trainer = Trainer(cfg)
        trainer.init_state()
        ev_iter = create_input_iterator(cfg, mode="eval")
        # compile the eval step + the staging unpack before timing
        trainer.evaluate(ev_iter, num_batches=2)
        input_stages.reset()
        ev_iter = create_input_iterator(cfg, mode="eval")
        t0 = time.perf_counter()
        res = trainer.evaluate(ev_iter, num_batches=10 ** 9)  # to exhaustion
        dt = time.perf_counter() - t0
        ev_snap = input_stages.snapshot()
        n_ev = res["count"]
        out["eval_pass"] = {
            "images": n_ev,
            "host_decode_images_per_sec": round(host_rate, 1),
            "e2e_images_per_sec": round(n_ev / dt, 1),
            # acceptance gauge: pipelined eval should track the host
            # decode rate (≥ 0.5 = "within 2× of host decode")
            "e2e_vs_host_decode": round(n_ev / dt / max(host_rate, 1e-9), 3),
            "full_50k_pass_minutes_at_host_rate": round(
                50000 / max(host_rate, 1e-9) / 60, 2),
        }
        try:
            # device eval step rate (synthetic batches, no input pipeline):
            # the compute leg of the decomposition. Own try: a probe
            # failure must not discard the measurements above.
            dev_bs = 100
            sb = {"images": np.zeros((dev_bs, 224, 224, 3), np.uint8),
                  "labels": np.zeros((dev_bs,), np.int32)}
            trainer.evaluate(iter([sb]), num_batches=1)  # warm shape
            t0 = time.perf_counter()
            trainer.evaluate(iter([sb] * 5), num_batches=5)
            dev_eval_rate = 5 * dev_bs / (time.perf_counter() - t0)
            out["eval_pass"].update(
                device_eval_images_per_sec=round(dev_eval_rate, 1),
                **attribute(n_ev / dt, ev_snap,
                            {"device_eval": dev_eval_rate}))
        except Exception as e:
            out["eval_pass"]["device_probe_error"] = \
                f"{type(e).__name__}: {e}"[:160]
    except Exception as e:
        out["eval_pass"] = {"error": f"{type(e).__name__}: {e}"[:200]}

    if budget_left() < 60:
        out["skipped_e2e"] = "over bench budget"
        return out
    # (b) end-to-end streamed training with the round-9 input stack ON:
    # auto-scaled decode workers, data echoing over the decoded-sample
    # cache (echo_factor), transfer-level echo (echo_transfer: one H2D
    # transfer feeds echo_transfer × steps_per_loop steps, reshuffled +
    # re-augmented on device), double-buffered staging. The gap to the
    # synthetic rate IS the finding.
    from distributed_resnet_tensorflow_tpu.utils.metrics import echo_stats
    cfg = get_preset("imagenet_resnet50")
    cfg.train.batch_size = 128
    cfg.train.steps_per_loop = 4
    cfg.data.data_dir = d
    cfg.data.echo_factor = 2
    cfg.data.echo_transfer = 2
    cfg.mesh.data = len(jax.devices())
    trainer = Trainer(cfg)
    trainer.init_state()
    stream = create_input_iterator(cfg, mode="train")
    # warmup covers compile AND pipeline ramp (queues, echo cache, decode
    # pool) so the timed window is steady state
    trainer.train(stream, num_steps=16)
    jax.block_until_ready(trainer.state.params)
    # attribution counters and echo telemetry cover the timed run only
    input_stages.reset()
    echo_stats.reset()
    n_s = 24
    t0 = time.perf_counter()
    trainer.train(stream, num_steps=16 + n_s, start_step=16)
    jax.block_until_ready(trainer.state.params)
    sps = n_s / (time.perf_counter() - t0)
    train_snap = input_stages.snapshot()
    echo_snap = echo_stats.snapshot()
    out["real_input_images_per_sec"] = round(sps * 128, 1)
    out["real_input_steps_per_sec"] = round(sps, 3)
    out["echo_factor"] = cfg.data.echo_factor
    out["echo_transfer"] = cfg.data.echo_transfer
    out["echo_cache_hit_rate"] = echo_snap["hit_rate"]
    out["echo"] = {k: echo_snap[k] for k in
                   ("decoded", "emitted", "hits", "evictions",
                    "peak_cache_bytes")}
    # decomposition from the run's own stage counters (decode / stack /
    # stage / transfer busy rates) plus the device train rate — the one
    # leg the input counters can't see. The device leg reuses the
    # ALREADY-COMPILED k=4 uint8 multi-step (same trace the streamed path
    # ran), so it costs no extra compile.
    extra = {}
    try:
        from distributed_resnet_tensorflow_tpu.parallel.sharding import (
            shard_stacked_batch)
        # probe batch dtype must match the streamed path's compiled trace:
        # with the fused-unpack augmentation the step consumes augmented
        # float32; otherwise raw uint8 (the step augments)
        img_dt = np.float32 if trainer.train_put_augments else np.uint8
        stacked = shard_stacked_batch({
            "images": np.zeros((4, 128, 224, 224, 3), img_dt),
            "labels": np.zeros((4, 128), np.int32)}, trainer.mesh)
        multi = trainer.jitted_multi_step(4)
        st = trainer.state
        st, _ = multi(st, stacked)  # warm (cached trace)
        jax.block_until_ready(st.params)
        t0 = time.perf_counter()
        for _ in range(3):
            st, _ = multi(st, stacked)
        jax.block_until_ready(st.params)
        trainer.state = st
        extra["device_train"] = 3 * 4 * 128 / (time.perf_counter() - t0)
        out["device_train_images_per_sec"] = round(extra["device_train"], 1)
    except Exception as e:
        out["device_train_probe_error"] = f"{type(e).__name__}: {e}"[:160]
    out["real_input_attribution"] = attribute(
        sps * 128, train_snap, extra, host_echo=cfg.data.echo_factor,
        transfer_echo=cfg.data.echo_transfer)
    return out


def _mfu_row(cfg, bs: int, image_size: int, num_classes: int,
             k: int, loops: int, host_fence: bool = False):
    """The ONE preset→Trainer→warmup→best-time→FLOPs→MFU measurement
    harness (synthetic batches, fused k-step dispatch) behind every
    single-chip MFU row — _bench_imagenet_at, bench_wrn28_10 and
    bench_vit_large share it so timing/accounting fixes land once.
    host_fence=True fences each rep through a host pull of a param sum
    instead of block_until_ready — the tunneled backend can return from
    block_until_ready before compute finishes on some programs
    (docs/perf_vit_r5.md measurement note); new rows use it, the
    long-standing rows keep their round-over-round-comparable timing."""
    from distributed_resnet_tensorflow_tpu.parallel.sharding import (
        shard_batch, shard_stacked_batch)
    from distributed_resnet_tensorflow_tpu.train import Trainer
    from distributed_resnet_tensorflow_tpu.utils import profiling

    cfg.train.batch_size = bs
    cfg.train.steps_per_loop = k
    cfg.mesh.data = len(jax.devices())
    trainer = Trainer(cfg)
    trainer.init_state()
    multi_fn = trainer.jitted_multi_step(k)
    rng = np.random.RandomState(0)
    batch = shard_stacked_batch({
        "images": rng.randn(k, bs, image_size, image_size, 3)
        .astype(np.float32),
        "labels": rng.randint(0, num_classes, (k, bs)).astype(np.int32),
    }, trainer.mesh)
    state = trainer.state
    for _ in range(2):
        state, _m = multi_fn(state, batch)
    jax.block_until_ready(state.params)
    if host_fence:
        _host_pull_fence(state)  # drain warmup before timing
    state, dt = _best_time(multi_fn, state, [batch], loops,
                           fence=_host_pull_fence if host_fence else None)
    steps_per_sec = loops * k / dt

    single = trainer.jitted_train_step()
    one = shard_batch({"images": np.asarray(batch["images"])[0],
                       "labels": np.asarray(batch["labels"])[0]},
                      trainer.mesh)
    step_flops = profiling.flops_per_step(single, state, one)
    util = profiling.mfu(steps_per_sec, step_flops) if step_flops else None
    return {
        "batch_size": bs,
        "steps_per_sec": round(steps_per_sec, 3),
        "images_per_sec": round(steps_per_sec * bs, 1),
        "mfu": round(util, 4) if util else None,
        "step_flops": step_flops,
    }


def _bench_imagenet_at(bs: int, k: int = 8, loops: int = 5,
                       norm: str = "batch"):
    """One ImageNet RN50 row at per-chip batch ``bs``, fused k-step
    dispatch. ``norm`` selects the normalization contract
    (batch | frozen | group — models/resnet.py)."""
    from distributed_resnet_tensorflow_tpu.utils.config import get_preset
    cfg = get_preset("imagenet_resnet50")
    cfg.data.dataset = "imagenet"
    cfg.model.norm = norm
    return _mfu_row(cfg, bs, 224, 1001, k, loops)


def bench_imagenet():
    """ImageNet ResNet-50 at per-chip bs=128 (the reference's README.md:50
    row, 0.96 steps/s) and bs=32 (its README.md:49 row, 2.20 steps/s — and
    the measured v5e throughput/MFU optimum, docs/perf_imagenet_r4.md)."""
    last_err = None
    out = None
    for bs in (128, 64):  # bs128 unless HBM says otherwise
        try:
            out = _bench_imagenet_at(bs)
            break
        except Exception as e:
            last_err = e
    if out is None:
        raise RuntimeError(f"no ImageNet batch size fit: {last_err}")
    out["vs_baseline_images_per_sec"] = round(
        out["images_per_sec"] / IMAGENET_BASELINE_IMAGES_PER_SEC, 2)
    try:
        row32 = _bench_imagenet_at(32, loops=20)
        # reference bs=32 row: 2.20 steps/s × 32 img (README.md:49)
        row32["vs_baseline_images_per_sec"] = round(
            row32["images_per_sec"] / (2.20 * 32), 2)
        out["bs32"] = row32
    except Exception as e:
        out["bs32"] = {"error": f"{type(e).__name__}: {e}"[:200]}
    return out


def bench_wrn28_10(k: int = 20, loops: int = 5):
    """WRN-28-10 (shipped preset cifar100_wrn28_10) single-chip MFU — the
    measured >=0.5-MFU conv training contract (BASELINE.md round-5
    renegotiation; docs/perf_cifar_r5.md width lever: same code as the
    0.17-MFU narrow-channel flagship, channels 160-640 fill the MXU)."""
    from distributed_resnet_tensorflow_tpu.utils.config import get_preset
    # keep the preset's cifar100 dataset so the device-side augmentation
    # runs inside the timed step, exactly like the headline CIFAR row and
    # the docs/perf_cifar_r5.json artifact (dataset='synthetic' would turn
    # the augment ops off and time a different step); batches are synthetic
    # so no data_dir is needed
    cfg = get_preset("cifar100_wrn28_10")
    return _mfu_row(cfg, 128, 32, 100, k, loops)


def bench_vit_large(k: int = 8, loops: int = 3):
    """ViT-L/16 at 224² (shipped preset vit_large_224) single-chip MFU —
    the transformer-family ≥0.55-MFU contract (measured 0.57;
    docs/perf_vit_classic_r5.md). Dense attention at 196 tokens, so every
    FLOP is XLA-counted: this MFU is fully accounted, no Pallas custom-call
    bounds."""
    from distributed_resnet_tensorflow_tpu.utils.config import get_preset
    cfg = get_preset("vit_large_224")
    return _mfu_row(cfg, 32, 224, 1000, k, loops, host_fence=True)


def bench_imagenet_norm(budget_left):
    """The normalization-contract MFU table (VERDICT r4 #1): ImageNet RN50
    per-chip MFU under every norm contract the framework ships, at the
    measured-optimum bs=32 and the reference-recipe bs=128. The faithful-BN
    rows ride in bench_imagenet(); these are the BN-free (group) and
    frozen-BN contracts. docs/perf_norm_r5.md carries the full analysis."""
    out = {}
    # frozen first: it is the load-bearing row (the 0.42 normalization
    # upper bound) and must survive a tight budget; group is corroboration
    for norm in ("frozen", "group"):
        for bs, loops in ((128, 5), (32, 20)):
            if budget_left() < 90:
                out.setdefault("skipped", []).append(f"{norm}_bs{bs}")
                continue
            try:
                row = _bench_imagenet_at(bs, loops=loops, norm=norm)
                out[f"{norm}_bs{bs}"] = {
                    "mfu": row["mfu"],
                    "images_per_sec": row["images_per_sec"],
                    "steps_per_sec": row["steps_per_sec"],
                }
            except Exception as e:
                out[f"{norm}_bs{bs}"] = {
                    "error": f"{type(e).__name__}: {e}"[:160]}
    return out


def bench_goodput(budget_left):
    """The goodput/step-breakdown row (telemetry/; docs/observability.md):
    a short REAL-input streamed training run with the flight-recorder
    spans on (the default) and a live checkpoint cadence, classified by
    the goodput meter into {compute, input_wait, checkpoint, eval, stall,
    restart}. Acceptance contract: the categories sum to ~100% of the
    measured wall (compute is the remainder by construction — pct_sum is
    the witness), and the spans-on steps/s of the CIFAR headline stays
    within 2% of its baseline (the headline row itself, measured with
    spans enabled process-wide)."""
    import shutil

    from distributed_resnet_tensorflow_tpu.checkpoint import CheckpointManager
    from distributed_resnet_tensorflow_tpu.data import create_input_iterator
    from distributed_resnet_tensorflow_tpu.telemetry import goodput, recorder
    from distributed_resnet_tensorflow_tpu.train import Trainer
    from distributed_resnet_tensorflow_tpu.train.hooks import CheckpointHook
    from distributed_resnet_tensorflow_tpu.utils.config import get_preset

    if budget_left() < 60:
        return {"skipped": "over bench budget"}
    cfg = get_preset("cifar10_resnet50")
    # resnet-20: the row measures the goodput CLASSIFIER over a real
    # streamed-input train loop with a live checkpoint cadence, not model
    # throughput (the headline rows cover that) — and it must stay cheap
    # enough to run on a CPU smoke box, where RN50 would eat the budget
    cfg.model.resnet_size = 20
    cfg.data.data_dir = _synth_cifar_files()
    cfg.mesh.data = len(jax.devices())
    ckpt_dir = os.path.join(tempfile.gettempdir(), "drt_bench_goodput_ckpt")
    shutil.rmtree(ckpt_dir, ignore_errors=True)
    trainer = Trainer(cfg)
    trainer.init_state()
    # time-based cadence so the row exercises the checkpoint bucket on
    # ANY backend speed (a step cadence would never fire inside the
    # window on a slow CPU box)
    manager = CheckpointManager(ckpt_dir, save_every_steps=0,
                                save_every_secs=8.0, max_to_keep=2)
    stream = create_input_iterator(cfg, mode="train")
    trainer.train(stream, num_steps=5)  # warmup/compile
    jax.block_until_ready(trainer.state.params)
    goodput.rebase()
    # wall-bounded, not step-bounded: ~25s of steady state whether the
    # backend does 3 steps/s (CPU smoke) or 400 (TPU)
    window = min(25.0, max(10.0, budget_left() - 30))
    hook = CheckpointHook(manager)
    step, n = 5, 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < window and n < 20_000:
        trainer.train(stream, num_steps=step + 20, start_step=step,
                      hooks=(hook,))
        step += 20
        n += 20
    manager.close()  # drain the async save inside the timed window
    jax.block_until_ready(trainer.state.params)
    wall = time.perf_counter() - t0
    itv = goodput.interval()
    pct_sum = round(sum(itv["pct"].values()), 2)
    return {
        "steps": n,
        "steps_per_sec": round(n / wall, 2),
        "wall_secs": round(wall, 3),
        "classified_wall_secs": itv["wall_secs"],
        "seconds": itv["seconds"],
        "pct": itv["pct"],
        "pct_sum": pct_sum,
        "spans_recorded": len(recorder),
        "spans_enabled": recorder.enabled,
    }


def bench_overlap(budget_left):
    """The zero-stall step-loop row (ROADMAP open item 5; ISSUE 10): (a)
    step time + goodput checkpoint share with checkpointing disabled vs
    SYNC vs ASYNC at a live time cadence, plus a cadence sweep — the
    acceptance bar is async checkpoint_pct ≤ 2% and mean step time within
    5% of checkpointing-disabled; (b) the bucketed gradient-communication
    A/B (comm.overlap off / on-bucketed / on-single-bucket) on a
    multi-device mesh — run in-process when this backend has >1 device,
    else in a subprocess with 8 virtual CPU devices (structure check +
    honest CPU numbers; collectives only overlap for real on TPU/DCN)."""
    import shutil

    from distributed_resnet_tensorflow_tpu.checkpoint import CheckpointManager
    from distributed_resnet_tensorflow_tpu.data import create_input_iterator
    from distributed_resnet_tensorflow_tpu.telemetry import goodput
    from distributed_resnet_tensorflow_tpu.train import Trainer
    from distributed_resnet_tensorflow_tpu.train.hooks import CheckpointHook
    from distributed_resnet_tensorflow_tpu.utils.config import get_preset
    from distributed_resnet_tensorflow_tpu.utils.metrics import (
        ckpt_async_stats)

    if budget_left() < 90:
        return {"skipped": "over bench budget"}
    out = {}
    cfg = get_preset("cifar10_resnet50")
    cfg.model.resnet_size = 20  # the classifier row's model: measures the
    cfg.data.data_dir = _synth_cifar_files()  # machinery, not conv MFU
    cfg.mesh.data = len(jax.devices())
    trainer = Trainer(cfg)
    trainer.init_state()
    stream = create_input_iterator(cfg, mode="train")
    trainer.train(stream, num_steps=5)  # warmup/compile
    jax.block_until_ready(trainer.state.params)
    step = 5

    def measure(window, manager):
        nonlocal step
        hooks = (CheckpointHook(manager),) if manager is not None else ()
        goodput.rebase()
        n = 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < window and n < 20_000:
            trainer.train(stream, num_steps=step + 10, start_step=step,
                          hooks=hooks)
            step += 10
            n += 10
        if manager is not None:
            manager.close()  # drain inside the timed window (honest)
        jax.block_until_ready(trainer.state.params)
        wall = time.perf_counter() - t0
        itv = goodput.interval()
        return {"steps": n, "steps_per_sec": round(n / wall, 2),
                "checkpoint_pct": itv["pct"]["checkpoint"],
                "checkpoint_secs": itv["seconds"]["checkpoint"],
                "wall_secs": round(wall, 2)}

    window = min(12.0, max(6.0, (budget_left() - 60) / 5))
    ckpt_root = os.path.join(tempfile.gettempdir(), "drt_bench_overlap_ckpt")

    def manager_for(mode, cadence):
        d = os.path.join(ckpt_root, f"{mode}_{cadence}")
        shutil.rmtree(d, ignore_errors=True)
        return CheckpointManager(d, save_every_steps=0,
                                 save_every_secs=cadence, max_to_keep=2,
                                 async_save=(mode == "async"))

    base = measure(window, None)
    out["ckpt_disabled"] = base
    cadence = max(2.0, window / 4)
    out["ckpt_cadence_secs"] = round(cadence, 1)
    out["ckpt_sync"] = measure(window, manager_for("sync", cadence))
    ckpt_async_stats.reset()
    out["ckpt_async"] = measure(window, manager_for("async", cadence))
    out["ckpt_async"]["stats"] = ckpt_async_stats.snapshot()
    out["async_step_time_vs_disabled"] = round(
        base["steps_per_sec"] /
        max(out["ckpt_async"]["steps_per_sec"], 1e-9), 3)
    # cadence sweep: how the checkpoint share scales with save frequency
    sweep = {}
    for cad in (cadence / 2, cadence * 2):
        if budget_left() < window + 30:
            sweep[f"{cad:.1f}s"] = {"skipped": "over bench budget"}
            continue
        ckpt_async_stats.reset()
        row = measure(window, manager_for("async", cad))
        row["saves"] = ckpt_async_stats.snapshot()["saves"]
        sweep[f"{cad:.1f}s"] = row
    out["ckpt_cadence_sweep"] = sweep

    # (b) bucketed gradient-exchange A/B
    if budget_left() < 60:
        out["bucketed"] = {"skipped": "over bench budget"}
        return out
    try:
        if len(jax.devices()) > 1:
            out["bucketed"] = _overlap_ab()
        else:
            # single-device backend (CPU smoke box): re-run under a
            # virtual 8-device mesh in a subprocess — the XLA flag must
            # be set before the backend initializes
            import subprocess
            env = dict(os.environ)
            env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                                " --xla_force_host_platform_device_count=8")
            env.setdefault("JAX_PLATFORMS", "cpu")
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--overlap-ab"],
                capture_output=True, text=True, env=env,
                timeout=max(60, budget_left()))
            if proc.returncode != 0:
                raise RuntimeError(proc.stderr[-300:])
            out["bucketed"] = json.loads(proc.stdout.strip().splitlines()[-1])
            out["bucketed"]["virtual_devices"] = 8
    except Exception as e:
        out["bucketed"] = {"error": f"{type(e).__name__}: {e}"[:200]}
    return out


def _overlap_ab(n_steps: int = 20):
    """comm.overlap off / bucketed / single-bucket step time on THIS
    backend's devices (call with >1 device; bench_overlap re-launches
    under virtual devices otherwise). Uses synthetic sharded batches
    through the single-step jit so the row times the exchange, not the
    input pipeline. On a real accelerator mesh the bucketed-vs-off delta
    IS the hidden-communication win; on virtual CPU devices collectives
    are memcpys and the row mostly witnesses structure + overhead. The
    model stays small (rn8) so three multi-device compiles fit a smoke
    box's budget."""
    from distributed_resnet_tensorflow_tpu.parallel.overlap import (
        overlap_stats)
    from distributed_resnet_tensorflow_tpu.parallel.sharding import (
        shard_batch)
    from distributed_resnet_tensorflow_tpu.train import Trainer
    from distributed_resnet_tensorflow_tpu.utils.config import get_preset

    rng = np.random.RandomState(0)
    bs = 64
    images = rng.randn(bs, 32, 32, 3).astype(np.float32)
    labels = rng.randint(0, 10, (bs,)).astype(np.int32)
    rows = {}
    for label, overlap, bucket_mb in (("off", "off", 4.0),
                                      ("bucketed", "on", 0.25),
                                      ("single_bucket", "on", 4096.0)):
        cfg = get_preset("cifar10_resnet50")
        cfg.model.resnet_size = 8
        cfg.train.batch_size = bs
        cfg.comm.overlap = overlap
        cfg.comm.bucket_mb = bucket_mb
        cfg.mesh.data = len(jax.devices())
        trainer = Trainer(cfg)
        trainer.init_state()
        step_fn = trainer.jitted_train_step()
        batch = shard_batch({"images": images, "labels": labels},
                            trainer.mesh)
        state = trainer.state
        for _ in range(3):  # compile + warm
            state, _m = step_fn(state, batch)
        jax.block_until_ready(state.params)
        state, dt = _best_time(step_fn, state, [batch], n_steps, reps=3)
        rows[label] = {"steps_per_sec": round(n_steps / dt, 2),
                       "step_ms": round(dt / n_steps * 1000, 2)}
        if overlap == "on":
            rows[label]["plan"] = overlap_stats.snapshot()
        if label == "bucketed":
            # per-bucket runtime attribution (ISSUE 14): probe each
            # planned bucket's collective standalone and join with the
            # committed static schedule + the off leg's step time, so the
            # row says WHICH bucket is the bottleneck, not one ratio
            try:
                from distributed_resnet_tensorflow_tpu.parallel.overlap \
                    import probe_comm_plan
                from distributed_resnet_tensorflow_tpu.telemetry.\
                    comm_report import build_report, load_schedules
                timing = probe_comm_plan(trainer.mesh)
                if timing is not None:
                    timing["step_secs"] = dt / n_steps
                    report = build_report(
                        timing, signatures=load_schedules(),
                        step_secs_off=rows["off"]["step_ms"] / 1000.0)
                    rows[label]["comm_report"] = {
                        k: report.get(k)
                        for k in ("buckets", "comm_secs_total",
                                  "comm_step_ratio", "overlap_fraction",
                                  "bottleneck_bucket",
                                  "lowest_bandwidth_bucket",
                                  "schedule_key")}
                    # what-if planner cross-check (ISSUE 17): cost this
                    # very leg from its own probe bandwidths + the off
                    # leg's measured compute, and hold the prediction
                    # against the measured step — the tolerance the
                    # drift sentinel and tests/test_planner.py assume
                    from distributed_resnet_tensorflow_tpu.telemetry.\
                        planner import BandwidthTable, OVERLAP_EFFICIENCY
                    bw = BandwidthTable.from_probe(timing) \
                        or BandwidthTable.reference()
                    snap = rows[label]["plan"]
                    comm = 0.0
                    for wire, sig in zip(
                            snap["bucket_wire_bytes"],
                            snap.get("bucket_reduce_axes",
                                     ["data"] * snap["buckets"])):
                        bps, lat = bw.lookup(sig)
                        comm += lat + int(wire) / bps
                    compute = rows["off"]["step_ms"] / 1000.0
                    exposed = max(0.0,
                                  comm - OVERLAP_EFFICIENCY * compute)
                    predicted = compute + exposed
                    measured = dt / n_steps
                    rows[label]["planner"] = {
                        "predicted_step_ms": round(predicted * 1e3, 3),
                        "measured_step_ms": round(measured * 1e3, 3),
                        "predicted_over_measured": round(
                            predicted / measured, 3),
                        "predicted_comm_ms": round(comm * 1e3, 3),
                        "measured_comm_ms": round(
                            timing["comm_secs_total"] * 1e3, 3),
                        "bandwidth_source": bw.source}
            except Exception as e:  # the A/B numbers stand alone
                rows[label]["comm_report"] = {
                    "error": f"{type(e).__name__}: {e}"[:200]}
    # hierarchical-exchange A/B leg (ISSUE 18): the bucketed cfg again
    # with the staged RS -> inter-psum -> AG exchange forced via
    # comm.intra_axis_size (virtual devices have no real host boundary).
    # Reports steps/s plus per-tier wire bytes: the inter-tier bytes
    # must drop to ~1/intra_k of the flat leg's — on a real multi-host
    # mesh that tier is the slow DCN hop, so the ratio IS the win; on
    # virtual CPU the row witnesses structure + the declared ledger.
    try:
        dsize = len(jax.devices())
        k = 4 if dsize > 4 and dsize % 4 == 0 else \
            (dsize // 2 if dsize >= 4 and dsize % 2 == 0 else 0)
        if k < 2:
            raise RuntimeError(
                f"{dsize} device(s) cannot factor into 2 tiers")
        cfg = get_preset("cifar10_resnet50")
        cfg.model.resnet_size = 8
        cfg.train.batch_size = bs
        cfg.comm.overlap = "on"
        cfg.comm.bucket_mb = 0.25
        cfg.comm.hierarchy = "on"
        cfg.comm.intra_axis_size = k
        cfg.mesh.data = dsize
        trainer = Trainer(cfg)
        trainer.init_state()
        step_fn = trainer.jitted_train_step()
        batch = shard_batch({"images": images, "labels": labels},
                            trainer.mesh)
        state = trainer.state
        for _ in range(3):  # compile + warm
            state, _m = step_fn(state, batch)
        jax.block_until_ready(state.params)
        state, dt = _best_time(step_fn, state, [batch], n_steps, reps=3)
        snap = overlap_stats.snapshot()
        flat = rows["bucketed"]["plan"]
        rows["hierarchy"] = {
            "steps_per_sec": round(n_steps / dt, 2),
            "step_ms": round(dt / n_steps * 1000, 2),
            "intra_k": snap.get("hierarchy"),
            "wire_bytes": sum(snap["bucket_wire_bytes"]),
            "inter_wire_bytes": sum(snap["bucket_inter_wire_bytes"]),
            "flat_inter_wire_bytes": sum(flat["bucket_inter_wire_bytes"]),
            "hier_vs_flat_steps": round(
                (n_steps / dt) / rows["bucketed"]["steps_per_sec"], 3),
            "plan": snap}
    except Exception as e:  # the A/B numbers stand alone
        rows["hierarchy"] = {"error": f"{type(e).__name__}: {e}"[:200]}
    rows["bucketed_vs_off"] = round(
        rows["bucketed"]["steps_per_sec"] / rows["off"]["steps_per_sec"], 3)
    rows["families"] = _overlap_family_sweep()
    return rows


def _overlap_family_sweep(n_steps: int = 4):
    """The universal-envelope family sweep (ISSUE 15): comm.overlap
    off/on steps/s AND per-step wire bytes for one leg per newly
    in-envelope family — conv dp (the PR-10 baseline leg rides above),
    vit dp_tp (partial-auto tensor), MoE dp_pp_ep (inline pipeline,
    per-expert-group buckets) and conv dp with grad_accum_steps=4 (the
    scan inside the body: wire/step must stay 1× the gradient bytes,
    i.e. shrink by exactly the accumulation factor vs a per-microbatch
    exchange). On virtual CPU devices collectives are memcpys, so
    steps/s mostly witnesses structure; wire accounting is exact
    everywhere."""
    from distributed_resnet_tensorflow_tpu.parallel import create_mesh
    from distributed_resnet_tensorflow_tpu.parallel.overlap import (
        overlap_stats)
    from distributed_resnet_tensorflow_tpu.parallel.sharding import (
        shard_batch)
    from distributed_resnet_tensorflow_tpu.train import Trainer
    from distributed_resnet_tensorflow_tpu.utils.config import (MeshConfig,
                                                                get_preset)

    def vit_cfg(experts=0):
        cfg = get_preset("smoke")
        cfg.model.name = "vit"
        cfg.model.num_classes = 10
        cfg.model.vit_patch_size = 4
        cfg.model.vit_dim = 32
        cfg.model.vit_depth = 4
        cfg.model.vit_heads = 2
        cfg.model.vit_num_experts = experts
        cfg.data.image_size = 16
        cfg.optimizer.name = "adam"
        return cfg

    def conv_cfg():
        cfg = get_preset("cifar10_resnet50")
        cfg.model.resnet_size = 8
        return cfg

    n_dev = len(jax.devices())
    legs = {
        "vit_dp_tp": (vit_cfg(), MeshConfig(data=max(2, n_dev // 2),
                                            tensor=2)),
        "moe_dp_pp_ep": (vit_cfg(experts=2),
                         MeshConfig(data=max(1, n_dev // 4), pipeline=2,
                                    expert=2)),
        "conv_dp_accum4": (conv_cfg(), MeshConfig(data=n_dev)),
    }
    rng = np.random.RandomState(0)
    out = {}
    for leg, (cfg0, mesh_cfg) in legs.items():
        row = {}
        for mode in ("off", "on"):
            try:
                import copy
                cfg = copy.deepcopy(cfg0)
                cfg.train.batch_size = 64
                cfg.train.grad_accum_steps = 4 if "accum" in leg else 1
                cfg.comm.overlap = mode
                cfg.comm.bucket_mb = 0.25
                cfg.checkpoint.save_every_secs = 0.0
                cfg.mesh = mesh_cfg
                overlap_stats.reset()
                trainer = Trainer(cfg)
                trainer.init_state()
                s = cfg.data.image_size
                images = rng.randn(64, s, s, 3).astype(np.float32)
                labels = rng.randint(0, 10, (64,)).astype(np.int32)
                batch = shard_batch({"images": images, "labels": labels},
                                    trainer.mesh)
                step_fn = trainer.jitted_train_step()
                state = trainer.state
                for _ in range(2):  # compile + warm
                    state, _m = step_fn(state, batch)
                jax.block_until_ready(state.params)
                state, dt = _best_time(step_fn, state, [batch], n_steps,
                                       reps=1)
                row[mode] = {
                    "steps_per_sec": round(n_steps / dt, 2),
                    "step_ms": round(dt / n_steps * 1000, 2),
                }
                if mode == "on":
                    plan = overlap_stats.snapshot()
                    row[mode].update({
                        "wire_bytes_per_step": plan["wire_bytes"],
                        "grad_bytes": plan["grad_bytes"],
                        "buckets": plan["buckets"],
                        "bucket_reduce_axes": sorted(
                            set(plan["bucket_reduce_axes"])),
                        "accum_steps": plan["accum_steps"],
                        # what a per-microbatch exchange would have moved
                        # per optimizer step — the accumulation saving's
                        # denominator
                        "wire_bytes_per_step_unfused":
                            plan["wire_bytes"] * plan["accum_steps"],
                    })
            except Exception as e:
                row[mode] = {"error": f"{type(e).__name__}: {e}"[:200]}
        if "steps_per_sec" in row.get("on", {}) and \
                "steps_per_sec" in row.get("off", {}):
            row["on_vs_off"] = round(row["on"]["steps_per_sec"] /
                                     row["off"]["steps_per_sec"], 3)
        out[leg] = row
    return out


def bench_zero1(budget_left):
    """The ZeRO-1 sharded-weight-update row (ISSUE 11; arXiv:2004.13336):
    per-replica optimizer-state bytes + steps/s for dp vs dp+ZeRO-1 (and
    the comm.overlap composition) on a multi-device mesh, plus the
    reduce-scatter / all-gather payload accounting from the bucket plan.
    Runs in-process when this backend has >1 device, else in a subprocess
    with 8 virtual CPU devices (the --overlap-ab pattern: structure check
    + honest CPU numbers; the memory win is layout-true everywhere, the
    step-time story needs a real mesh)."""
    if budget_left() < 60:
        return {"skipped": "over bench budget"}
    try:
        if len(jax.devices()) > 1:
            return _zero1_ab()
        import subprocess
        env = dict(os.environ)
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                            " --xla_force_host_platform_device_count=8")
        env.setdefault("JAX_PLATFORMS", "cpu")
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--zero1-ab"],
            capture_output=True, text=True, env=env,
            timeout=max(60, budget_left()))
        if proc.returncode != 0:
            raise RuntimeError(proc.stderr[-300:])
        out = json.loads(proc.stdout.strip().splitlines()[-1])
        out["virtual_devices"] = 8
        return out
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"[:200]}


def _zero1_ab(n_steps: int = 20):
    """optimizer.zero1 off / on / on+overlap step time AND per-replica
    optimizer-state bytes on THIS backend's devices. The byte numbers
    are measured from the LIVE state's shardings (per-device shard
    shapes), not projected — the (N-1)/N shrink for shardable leaves is
    the acceptance claim. LAMB (mu+nu — double moments) makes the memory
    story visible at rn8 scale."""
    from distributed_resnet_tensorflow_tpu.parallel.overlap import (
        overlap_stats)
    from distributed_resnet_tensorflow_tpu.parallel.sharding import (
        shard_batch, zero1_stats)
    from distributed_resnet_tensorflow_tpu.train import Trainer
    from distributed_resnet_tensorflow_tpu.utils.config import get_preset

    rng = np.random.RandomState(0)
    bs = 64
    images = rng.randn(bs, 32, 32, 3).astype(np.float32)
    labels = rng.randint(0, 10, (bs,)).astype(np.int32)

    def opt_bytes_per_replica(state):
        total = 0
        for leaf in jax.tree_util.tree_leaves(state.opt_state):
            if not hasattr(leaf, "sharding"):
                continue
            shard_shape = leaf.sharding.shard_shape(leaf.shape)
            total += int(np.prod(shard_shape, dtype=np.int64)) * \
                leaf.dtype.itemsize
        return total

    rows = {}
    for label, zero1, overlap in (("off", "off", "off"),
                                  ("zero1", "on", "off"),
                                  ("zero1_overlap", "on", "on")):
        cfg = get_preset("cifar10_resnet50")
        cfg.model.resnet_size = 8
        cfg.train.batch_size = bs
        cfg.optimizer.name = "lamb"
        cfg.optimizer.weight_decay = 1e-4
        cfg.optimizer.zero1 = zero1
        cfg.optimizer.zero1_min_size = 256
        cfg.comm.overlap = overlap
        cfg.comm.bucket_mb = 0.25
        cfg.mesh.data = len(jax.devices())
        zero1_stats.reset()
        overlap_stats.reset()
        trainer = Trainer(cfg)
        trainer.init_state()
        step_fn = trainer.jitted_train_step()
        batch = shard_batch({"images": images, "labels": labels},
                            trainer.mesh)
        state = trainer.state
        for _ in range(3):  # compile + warm
            state, _m = step_fn(state, batch)
        jax.block_until_ready(state.params)
        per_replica = opt_bytes_per_replica(state)
        state, dt = _best_time(step_fn, state, [batch], n_steps, reps=3)
        rows[label] = {"steps_per_sec": round(n_steps / dt, 2),
                       "step_ms": round(dt / n_steps * 1000, 2),
                       "opt_bytes_per_replica": per_replica}
        if zero1 == "on":
            rows[label]["plan"] = zero1_stats.snapshot()
        if overlap == "on":
            rows[label]["comm_plan"] = overlap_stats.snapshot()
    rows["opt_bytes_ratio_off_over_zero1"] = round(
        rows["off"]["opt_bytes_per_replica"] /
        max(rows["zero1"]["opt_bytes_per_replica"], 1), 2)
    plan = rows["zero1"].get("plan") or {}
    if plan.get("sharded_bytes"):
        # the acceptance claim: shardable leaves shrink by (N-1)/N
        n = plan.get("data_shards", 1)
        rows["shardable_bytes_per_replica"] = plan["sharded_bytes"] // n
        rows["shardable_reduction"] = round(
            1 - (plan["sharded_bytes"] // n) / plan["sharded_bytes"], 4)
        rows["expected_reduction"] = round((n - 1) / n, 4)
    return rows


def bench_precision(budget_left):
    """The low-precision row (ISSUE 12; docs/precision.md): steps/s AND
    exchanged bucket bytes for f32 vs bf16 vs bf16+compressed-exchange
    on a multi-device mesh — in-process when this backend has >1 device,
    else the --overlap-ab subprocess pattern (virtual 8-device CPU mesh:
    the byte accounting is layout-true everywhere; the bf16 step-time
    story needs real MXUs, which is why the CPU rows are structure
    checks, not speedups)."""
    if budget_left() < 60:
        return {"skipped": "over bench budget"}
    try:
        if len(jax.devices()) > 1:
            return _precision_ab()
        import subprocess
        env = dict(os.environ)
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                            " --xla_force_host_platform_device_count=8")
        env.setdefault("JAX_PLATFORMS", "cpu")
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--precision-ab"],
            capture_output=True, text=True, env=env,
            timeout=max(60, budget_left()))
        if proc.returncode != 0:
            raise RuntimeError(proc.stderr[-300:])
        out = json.loads(proc.stdout.strip().splitlines()[-1])
        out["virtual_devices"] = 8
        return out
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"[:200]}


def _precision_ab(n_steps: int = 20):
    """train.precision / comm.compress A/B on THIS backend's devices,
    all three rows over the SAME bucketed exchange (comm.overlap=on,
    one bucket plan) so the per-bucket byte columns compare like for
    like: f32 (the oracle), bf16 step (f32 wire), bf16 step + bf16 wire
    (the arXiv:1811.05233 recipe). The plan's grad_bytes/wire_bytes pair
    IS the acceptance claim: same buckets, half the exchanged bytes."""
    from distributed_resnet_tensorflow_tpu.parallel.overlap import (
        overlap_stats)
    from distributed_resnet_tensorflow_tpu.parallel.sharding import (
        shard_batch)
    from distributed_resnet_tensorflow_tpu.train import Trainer
    from distributed_resnet_tensorflow_tpu.utils.config import get_preset

    rng = np.random.RandomState(0)
    bs = 64
    images = rng.randn(bs, 32, 32, 3).astype(np.float32)
    labels = rng.randint(0, 10, (bs,)).astype(np.int32)
    rows = {}
    for label, precision, compress in (("f32", "off", "off"),
                                       ("bf16", "bf16", "off"),
                                       ("bf16_compress", "bf16", "bf16")):
        cfg = get_preset("cifar10_resnet50")
        cfg.model.resnet_size = 8
        cfg.model.compute_dtype = "float32"  # the policy is the knob
        cfg.train.batch_size = bs
        cfg.train.precision = precision
        cfg.comm.overlap = "on"
        cfg.comm.bucket_mb = 0.25
        cfg.comm.compress = compress
        cfg.mesh.data = len(jax.devices())
        overlap_stats.reset()
        trainer = Trainer(cfg)
        trainer.init_state()
        step_fn = trainer.jitted_train_step()
        batch = shard_batch({"images": images, "labels": labels},
                            trainer.mesh)
        state = trainer.state
        for _ in range(3):  # compile + warm
            state, _m = step_fn(state, batch)
        jax.block_until_ready(state.params)
        state, dt = _best_time(step_fn, state, [batch], n_steps, reps=3)
        plan = overlap_stats.snapshot() or {}
        rows[label] = {"steps_per_sec": round(n_steps / dt, 2),
                       "step_ms": round(dt / n_steps * 1000, 2),
                       "grad_bytes": plan.get("grad_bytes"),
                       "wire_bytes": plan.get("wire_bytes"),
                       "buckets": plan.get("buckets"),
                       "bucket_wire_bytes": plan.get("bucket_wire_bytes")}
    rows["bf16_vs_f32_steps"] = round(
        rows["bf16"]["steps_per_sec"] / rows["f32"]["steps_per_sec"], 3)
    rows["compress_wire_ratio"] = round(
        rows["bf16_compress"]["wire_bytes"] /
        max(rows["f32"]["wire_bytes"], 1), 3)
    rows["same_bucket_plan"] = \
        rows["bf16_compress"]["buckets"] == rows["f32"]["buckets"]
    return rows


def bench_serving(budget_left):
    """The serving row (serve/; docs/serving.md): open-loop synthetic load
    against the AOT-compiled batched inference server — p50/p99 request
    latency and QPS per batch bucket, plus the startup compile cost. Uses
    the smoke-scale ResNet so the row measures the SERVING machinery
    (batcher coalescing, staging, bucket dispatch), comparable
    round-over-round like the CIFAR headline."""
    from distributed_resnet_tensorflow_tpu.serve.loadgen import run_open_loop
    from distributed_resnet_tensorflow_tpu.serve.server import InferenceServer
    from distributed_resnet_tensorflow_tpu.utils.config import get_preset

    cfg = get_preset("smoke")
    cfg.data.eval_batch_size = 64          # buckets: pad, 2x, ... 64
    cfg.mesh.data = len(jax.devices())
    cfg.serve.max_queue_delay_ms = 2.0
    # (batch, variant) buckets (docs/precision.md): the same replica
    # carries the f32 oracle, a bf16 weight/compute variant AND the int8
    # weight-only variant (per-channel-quantized kernels dequantized into
    # an f32 forward); the row drives one open loop per variant so
    # p50/p99/QPS read per dtype
    cfg.serve.variants = ("f32", "bf16", "int8")
    cfg.checkpoint.directory = os.path.join(
        tempfile.gettempdir(), "drt_bench_serve_empty_ckpt")  # no ckpt:
    # serving fresh-init params — the row times the serving path, not
    # training; hot-swap cost is covered by tests/serve_smoke.sh
    server = InferenceServer(cfg)
    by_variant = {}
    try:
        server.start()
        duration = min(8.0, max(3.0, (budget_left() - 30) /
                                len(server.variants)))
        for variant in server.variants:
            t0 = time.perf_counter()
            done_before = server.completed
            load = run_open_loop(server, qps=50.0, duration_secs=duration,
                                 seed=0, variant=variant)
            wall = time.perf_counter() - t0
            by_variant[variant] = {
                "offered_qps": load["offered_qps"],
                "achieved_qps": round(
                    (server.completed - done_before) / max(wall, 1e-9), 1),
                "failed": load.get("failed", 0),
            }
    finally:
        server.close()
    rep = server.report()
    return {
        "variants": rep["variants"],
        "by_variant": by_variant,
        "achieved_qps": rep["qps"],
        "dropped": rep["dropped"],
        "batches": rep["batches"],
        "buckets": rep["buckets"],
        "latency_by_bucket_ms": rep["latency_by_bucket_ms"],
        "aot_warm_secs": rep["compile"]["warm_secs"],
        "serve_time_compiles": rep["compile"]["serve_time_compiles"],
    }


def bench_serving_fleet(budget_left):
    """The fleet front door row (serve/router.py + serve/fleet.py;
    docs/serving.md fleet section): three legs against a real 3-replica
    routed fleet — steady open-loop load, a SIGKILL'd replica mid-load
    (hedged retries bound client errors while the watchdog replaces it),
    and a checkpoint published mid-load that rides the canary to a
    promote. Replicas are real ``main.py`` serve subprocesses, so the
    row also prices replica warm-up (spawn -> READY) and recovery
    (kill -> readmit) in wall seconds."""
    import shutil
    import signal
    import subprocess

    from distributed_resnet_tensorflow_tpu.resilience.manifest import \
        committed_steps
    from distributed_resnet_tensorflow_tpu.serve.fleet import FleetSupervisor
    from distributed_resnet_tensorflow_tpu.serve.loadgen import (
        run_open_loop, synthetic_requests)
    from distributed_resnet_tensorflow_tpu.serve.router import Router
    from distributed_resnet_tensorflow_tpu.serve.server import serve_image_spec
    from distributed_resnet_tensorflow_tpu.serve.wire import TcpReplicaClient
    from distributed_resnet_tensorflow_tpu.utils.config import (
        ExperimentConfig, get_preset)

    if budget_left() < 300:
        return {"skipped": "over bench budget (the fleet legs need ~300s)"}
    root = tempfile.mkdtemp(prefix="drt_bench_fleet.")
    ckpt_dir = os.path.join(root, "ckpt")
    cfg = get_preset("smoke")
    # serve_smoke.sh's SHRINK scale: the row measures the ROUTING tier
    # (dispatch, hedging, replace, canary), not model compute
    cfg.model.resnet_size = 8
    cfg.model.compute_dtype = "float32"
    cfg.data.image_size = 8
    cfg.train.batch_size = 16
    cfg.data.eval_batch_size = 16
    cfg.mesh.data = 1
    cfg.log_root = root
    cfg.checkpoint.directory = ckpt_dir
    cfg.checkpoint.async_save = False
    cfg.checkpoint.save_every_secs = 0
    cfg.checkpoint.save_every_steps = 2
    cfg.serve.variants = ("f32",)
    cfg.serve.max_queue_delay_ms = 5.0
    cfg.serve.poll_interval_secs = 0.5
    cfg.route.replicas = 3
    cfg.route.health_interval_secs = 0.5
    cfg.route.row_interval_secs = 2.0
    cfg.route.watch_interval_secs = 0.5
    cfg.route.replica_grace_secs = 2.0
    cfg.route.request_timeout_ms = 8000
    cfg.route.attempt_timeout_ms = 2000
    cfg.route.hedge_ms = 250
    cfg.route.canary_window_secs = 6.0
    cfg.route.canary_min_samples = 8
    cfg.route.canary_confirm_secs = 30.0

    # replica/train subprocesses must come up as plain single-device CPU
    # jax whatever this process was launched with
    saved_env = {k: os.environ.get(k) for k in ("JAX_PLATFORMS", "XLA_FLAGS")}
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("XLA_FLAGS", None)
    fleet = router = None
    out = {"replicas": cfg.route.replicas}
    try:
        # 1) four training steps -> committed checkpoints 2 and 4; stash
        # 4 under a non-committed name so it can be atomically PUBLISHED
        # mid-load for the canary leg (commit = bare-step rename, the
        # manifest protocol's own primitive)
        tcfg = ExperimentConfig.from_dict(cfg.to_dict())
        tcfg.mode = "train"
        tcfg.train.train_steps = 4
        tpath = os.path.join(root, "train.json")
        with open(tpath, "w") as f:
            f.write(tcfg.to_json())
        subprocess.run(
            [sys.executable, "-m", "distributed_resnet_tensorflow_tpu.main",
             "--config_json", tpath],
            check=True, timeout=max(120.0, budget_left() - 180),
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        steps = committed_steps(ckpt_dir)
        assert steps and steps[-1] >= 4, f"training left {steps}"
        hold = os.path.join(root, "ckpt_hold_4")
        os.rename(os.path.join(ckpt_dir, "4"), hold)

        t0 = time.monotonic()
        fleet = FleetSupervisor(cfg).start()
        out["warm_secs"] = round(time.monotonic() - t0, 1)
        clients = {rid: TcpReplicaClient("127.0.0.1", port)
                   for rid, port in fleet.ports.items()}
        shape, dtype = serve_image_spec(cfg)
        from distributed_resnet_tensorflow_tpu.serve.fleet import write_pin
        router = Router(
            cfg.route, clients, shape, dtype,
            beats_dir=fleet.beats_dir,
            committed_steps_fn=lambda: committed_steps(ckpt_dir),
            pin_fn=lambda rid, step: write_pin(root, rid, step),
            initial_step=fleet.pinned_step).start()
        fleet.attach_router(router)
        fleet.start_watch()

        # leg 1: steady open-loop load across the healthy fleet
        out["steady"] = run_open_loop(router, qps=30.0, duration_secs=6.0,
                                      seed=0)
        # leg 2: SIGKILL one replica mid-load — hedges absorb the loss,
        # the watchdog replaces; client errors stay bounded
        errors_before = router.report()["errors"]
        os.kill(fleet.procs[0].pid, signal.SIGKILL)
        kill = run_open_loop(router, qps=30.0, duration_secs=8.0, seed=1)
        kill["errors_during"] = router.report()["errors"] - errors_before
        t1 = time.monotonic()
        deadline = t1 + min(90.0, max(20.0, budget_left() - 90))
        while (router.health_state(0) not in ("ready", "degraded")
               and time.monotonic() < deadline):
            time.sleep(0.5)
        kill["replaces"] = fleet.replaces
        kill["recovered"] = router.health_state(0) in ("ready", "degraded")
        kill["recover_secs"] = round(time.monotonic() - t1, 1)
        out["kill"] = kill

        # leg 3: publish the stashed checkpoint mid-trickle — the canary
        # fraction serves it first; the verdict promotes it fleet-wide
        if budget_left() > 60:
            os.rename(hold, os.path.join(ckpt_dir, "4"))
            pool = synthetic_requests(router.image_shape,
                                      router.image_dtype, pool=4, seed=2)
            t2 = time.monotonic()
            deadline = t2 + min(
                cfg.route.canary_window_secs
                + cfg.route.canary_confirm_secs + 20.0,
                max(20.0, budget_left() - 30))
            i = 0
            while (router.canary.fleet_step < 4
                   and 4 not in router.canary.bad_steps
                   and time.monotonic() < deadline):
                # concurrent bursts, not one-at-a-time: sequential probes
                # all tie-break onto the lowest rid and starve the control
                # arm of the verdict samples
                futs = []
                for _ in range(4):
                    futs.append(router.submit(pool[i % len(pool)]))
                    i += 1
                for fut in futs:
                    try:
                        fut.result(timeout=10.0)
                    except Exception:  # noqa: BLE001 — probe losses ok
                        pass
                time.sleep(0.2)
            out["canary"] = {
                "published_step": 4,
                "promoted": router.canary.fleet_step == 4,
                "rolled_back": 4 in router.canary.bad_steps,
                "verdict_secs": round(time.monotonic() - t2, 1),
            }
        else:
            out["canary"] = {"skipped": "over bench budget"}
        rep = router.report()
        out["router"] = {k: rep[k] for k in
                         ("requests", "completed", "errors", "shed",
                          "degraded", "hedges", "retries", "fleet_step")}
    finally:
        if router is not None:
            router.close()
        if fleet is not None:
            fleet.stop()
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        shutil.rmtree(root, ignore_errors=True)
    return out


def attention_grad_ms(attn_fn, q, k, v, iters=10, reps=3):
    """ms per fwd+bwd of ``attn_fn`` timed inside a lax.scan (the remote-
    tunnel dispatch floor would swamp per-call timing), fenced through a
    host transfer (on the tunneled backend block_until_ready can return
    before compute finishes). The ONE measurement harness shared by this
    bench and tools/tune_flash_attention.py — methodology fixes land once."""
    import jax.numpy as jnp
    g = jax.grad(lambda q, k, v: attn_fn(q, k, v)
                 .astype(jnp.float32).sum(), argnums=(0, 1, 2))

    @jax.jit
    def run(q, k, v):
        def body(qq, _):
            dq, dk, dv = g(qq, k, v)
            return qq + 1e-6 * dq.astype(qq.dtype), ()
        return jax.lax.scan(body, q, None, length=iters)[0]

    float(jnp.sum(run(q, k, v).astype(jnp.float32)))  # compile + fence
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = run(q, k, v)
        float(jnp.sum(out.astype(jnp.float32)))
        best = min(best, (time.perf_counter() - t0) / iters * 1000)
    return best


def bench_flash_attention(iters=10):
    """Long-context attention: fused Pallas flash (fwd+bwd kernels, tuned
    tiles — docs/flash_tune_r3.json) vs XLA dense autodiff, causal bf16, at
    the 4k crossover regime and the 8k regime where dense's O(T²) memory
    collapses."""
    import jax.numpy as jnp
    from distributed_resnet_tensorflow_tpu.ops.attention import attention
    from distributed_resnet_tensorflow_tpu.ops.pallas import flash_attention

    out = {}
    rng = np.random.RandomState(0)
    for t, h in ((4096, 8), (8192, 4)):  # constant tensor sizes (T·h·d);
        # attention FLOPs (∝ h·T²·d) still double at 8k
        q, k, v = (jnp.asarray(rng.randn(1, t, h, 64).astype(np.float32))
                   .astype(jnp.bfloat16) for _ in range(3))
        fused = attention_grad_ms(
            lambda q, k, v: flash_attention(q, k, v, True, False),
            q, k, v, iters)
        dense = attention_grad_ms(
            lambda q, k, v: attention(q, k, v, causal=True), q, k, v, iters)
        out[f"T{t}"] = {"fused_grad_ms": round(fused, 2),
                        "dense_grad_ms": round(dense, 2),
                        "speedup": round(dense / fused, 2)}
    return out


def main():
    """Headline-first with a wall-clock budget: the CIFAR headline always
    prints even if a slow tunnel day would push the extra sections past an
    external timeout (a killed bench emits nothing, which is worse than a
    bench missing secondary sections)."""
    if "--overlap-ab" in sys.argv:
        # bench_overlap's multi-device re-entry (virtual 8-device CPU mesh
        # via env XLA_FLAGS; single JSON line on stdout)
        print(json.dumps(_overlap_ab()))
        return
    if "--zero1-ab" in sys.argv:
        # bench_zero1's multi-device re-entry (same contract)
        print(json.dumps(_zero1_ab()))
        return
    if "--precision-ab" in sys.argv:
        # bench_precision's multi-device re-entry (same contract)
        print(json.dumps(_precision_ab()))
        return
    t0 = time.monotonic()
    try:
        budget = float(os.environ.get("BENCH_BUDGET_SECS", "900"))
    except ValueError:
        budget = 900.0
    cifar = bench_cifar()
    out = {
        "metric": "cifar10_resnet50_bs128_train_steps_per_sec",
        "value": cifar["steps_per_sec"],
        "unit": "steps/sec",
        "vs_baseline": round(
            cifar["steps_per_sec"] / CIFAR_BASELINE_STEPS_PER_SEC, 2),
        "cifar": cifar,
        "device": jax.devices()[0].device_kind,
    }
    budget_left = lambda: budget - (time.monotonic() - t0)  # noqa: E731
    # norm-contract rows run LAST: they are a spot-check of the full sweep
    # artifact (docs/perf_norm_r5.json) and must not starve the
    # round-over-round sections under the wall-clock budget
    for key, fn in (("imagenet_resnet50", bench_imagenet),
                    ("flash_attention_causal", bench_flash_attention),
                    ("imagenet_input", lambda: bench_imagenet_input(budget_left)),
                    ("cifar100_wrn28_10", bench_wrn28_10),
                    # vit_large before the norm contracts: it is the round-5
                    # ≥0.55-MFU transformer contract (one row), while the
                    # norm table is corroboration of docs/perf_norm_r5.json
                    # and already degrades row-by-row under the budget
                    ("vit_large_224",
                     lambda: bench_vit_large() if budget_left() > 150
                     else {"skipped": "over bench budget"}),
                    # the serving row (serve/): p50/p99 + QPS per bucket
                    ("serving", lambda: bench_serving(budget_left)),
                    # the fleet front door row (serve/router.py): steady
                    # load, a replica SIGKILL mid-load, a mid-load canary
                    # publish -> promote
                    ("serving_fleet",
                     lambda: bench_serving_fleet(budget_left)),
                    # goodput/step-breakdown (telemetry/): where a real
                    # streamed training run's wall-clock went — the
                    # before/after number for ROADMAP items 2 and 5
                    ("goodput_breakdown",
                     lambda: bench_goodput(budget_left)),
                    # zero-stall step loop (ROADMAP item 5): async-vs-sync
                    # checkpoint stall + the bucketed-exchange A/B
                    ("overlap", lambda: bench_overlap(budget_left)),
                    # ZeRO-1 sharded weight update (ISSUE 11): per-replica
                    # optimizer bytes + steps/s, dp vs dp+ZeRO-1, with the
                    # reduce-scatter/all-gather payload plan
                    ("zero1", lambda: bench_zero1(budget_left)),
                    # low-precision hot paths (ISSUE 12): bf16 step +
                    # compressed exchange A/B with per-bucket wire bytes
                    ("precision", lambda: bench_precision(budget_left)),
                    ("imagenet_norm_contracts",
                     lambda: bench_imagenet_norm(budget_left))):
        if time.monotonic() - t0 > budget:
            out[key] = {"skipped": f"over {budget:.0f}s bench budget"}
            continue
        try:
            out[key] = fn()
        except Exception as e:  # a failed section must not eat the headline
            out[key] = {"error": f"{type(e).__name__}: {e}"[:200]}
    print(json.dumps(out))
    if any(isinstance(v, dict) and "error" in v for v in out.values()):
        sys.exit(1)  # headline printed, but a section genuinely failed


if __name__ == "__main__":
    main()
