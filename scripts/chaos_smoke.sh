#!/bin/bash
# Chaos smoke — run the fault-injection suite (resilience/faultinject.py):
# signal delivery mid-run, torn/bit-rotted checkpoints, injected NaN loss,
# plus the watchdog cases (killed peer, frozen peer, straggler —
# tests/test_watchdog.py + the subprocess kill-and-detect tests in
# tests/test_resilience.py). Everything runs on the fake-CPU mesh
# (tests/conftest.py) — no accelerator needed.
#
#   scripts/chaos_smoke.sh            # the FULL chaos set (incl. the
#                                     # slow-tier multi-process subprocess
#                                     # kill/freeze tests — ~minutes of real
#                                     # training children)
#   scripts/chaos_smoke.sh --fast     # seconds-fast pre-merge gate:
#                                     # shardcheck + -m "not slow and not heavy"
#   scripts/chaos_smoke.sh -k nan     # just the NaN-recovery cases
#
# NOTE: the subprocess/watchdog chaos tests are marked `slow` (tier-1 of
# the main suite excludes them for the 870 s budget) — this script is
# where they run, so the default mode deliberately applies NO marker
# filter over the two chaos test files.
set -euo pipefail
cd "$(dirname "$0")/.."

MARK_ARGS=()
if [[ "${1:-}" == "--fast" ]]; then
  MARK_ARGS=(-m "not slow and not heavy")
  shift
  # the fast pre-merge gate also runs shardcheck (lint + static
  # elaboration, scripts/analysis_gate.sh): spec/config/invariant bugs
  # should die here, in seconds, not on the cluster
  scripts/analysis_gate.sh
fi

# ${arr[@]+...} form: bash <4.4 trips set -u on expanding an empty array
exec env JAX_PLATFORMS=cpu python -m pytest \
  tests/test_resilience.py tests/test_watchdog.py -q \
  ${MARK_ARGS[@]+"${MARK_ARGS[@]}"} -p no:cacheprovider "$@"
