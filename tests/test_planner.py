"""What-if performance planner (telemetry/planner.py, docs/planner.md).

The load-bearing claims, pinned here:

* the analytic cost model is internally consistent over the COMMITTED
  collective schedules (step = compute + exposed, accumulation scales
  compute, compression narrows wire bytes, rankings sort), and the
  probe-fed prediction of a LIVE virtual-8 bucketed leg lands inside
  the documented ``telemetry.plan_tolerance`` band of the measured
  step — the same band the drift sentinel enforces;
* ``analysis/plan_catalog.json`` is byte-identical across consecutive
  gate runs AND matches the committed file (the artifact must only
  ever diff on a real model/schedule change);
* a seeded bandwidth-table lie is caught twice over: statically by the
  gate's catalog-vs-micro-probe cross-check, and live by the
  DriftSentinel — which fires exactly ONCE per divergence episode,
  with a cooldown;
* the bandwidth catalog round-trips probe measurements (merge-best),
  and ``tools/bench_trajectory.py`` joins the BENCH rounds with
  correct per-key deltas.
"""
import json
import os

import numpy as np
import pytest

import jax

from distributed_resnet_tensorflow_tpu.telemetry import planner
from distributed_resnet_tensorflow_tpu.telemetry.comm_report import (
    load_schedules)
from distributed_resnet_tensorflow_tpu.utils.config import (MeshConfig,
                                                            get_preset)


# ---------------------------------------------------------------------------
# cost-model pieces
# ---------------------------------------------------------------------------

def test_layout_label_vocabulary():
    assert planner.layout_label(MeshConfig(data=8)) == "dp"
    assert planner.layout_label(MeshConfig(data=4, fsdp=2)) == "dp_fsdp"
    assert planner.layout_label(
        MeshConfig(data=2, pipeline=2, expert=2)) == "dp_pp_ep"


def test_ring_scale_shape():
    # 2(n-1)/n, clamped at the 2-device floor; large n → 2
    assert planner._ring_scale(2) == 1.0
    assert planner._ring_scale(1) == planner._ring_scale(2)
    assert 1.7 < planner._ring_scale(8) < planner._ring_scale(256) < 2.0


def test_flops_per_example_families():
    rn50 = get_preset("imagenet_resnet50")
    # anchored on the XLA-counted 4.1 GFLOP rn50@224 forward pass
    assert 3e9 < planner.flops_per_example(rn50) < 6e9
    cifar = get_preset("cifar10_resnet50")
    assert 0 < planner.flops_per_example(cifar) \
        < planner.flops_per_example(rn50)
    vit = get_preset("vit_moe")
    assert planner.flops_per_example(vit) > 0


def test_bandwidth_table_lookup_fallbacks():
    t = planner.BandwidthTable(
        source="test",
        axes={"data": (1e9, 1e-4), "data+fsdp": (2e9, 2e-4)},
        default_bps=5e8, default_latency=3e-4)
    assert t.lookup("data") == (1e9, 1e-4)
    # unseen signature sharing an axis falls back to the closest entry
    bps, _lat = t.lookup("data+expert")
    assert bps == 1e9
    # nothing shared -> defaults
    assert t.lookup("tensor") == (5e8, 3e-4)


# ---------------------------------------------------------------------------
# predictions over the committed schedules
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def signatures():
    sigs = load_schedules()
    assert sigs, "committed collective_schedules.json missing"
    return sigs


def test_plan_consistency_over_committed_schedules(signatures):
    """Internal consistency of every candidate the gate commits —
    the documented contract for reference-constant predictions is
    ranking + consistency, not stopwatch accuracy (docs/planner.md)."""
    for preset in ("cifar10_resnet50", "imagenet_resnet50", "vit_moe"):
        plan = planner.plan_for_preset(preset, signatures,
                                       include_hbm=False)
        cands = plan["candidates"]
        assert cands, preset
        for key, c in cands.items():
            assert np.isfinite(c["step_secs"]) and c["step_secs"] > 0
            assert c["comm_exposed_secs"] <= c["comm_secs"] + 1e-12
            assert c["step_secs"] == pytest.approx(
                c["compute_secs"] + c["comm_exposed_secs"], rel=1e-6)
            assert 0.0 <= c["comm_fraction"] <= 1.0
        # ranking is by predicted step time
        steps = [cands[k]["step_secs"] for k in plan["ranked"]]
        assert steps == sorted(steps)
        # the recommendation compares overlap variants with each other
        assert plan["recommended"].endswith("/overlap")


def test_accum_and_compress_variants_scale_the_model(signatures):
    plan = planner.plan_for_preset("cifar10_resnet50", signatures,
                                   include_hbm=False)
    c = plan["candidates"]
    # accumulation multiplies the compute term, not the exchange
    assert c["dp/overlap+accum4"]["compute_secs"] == pytest.approx(
        4 * c["dp/overlap"]["compute_secs"], rel=1e-6)
    assert c["dp/overlap+accum4"]["comm_secs"] == pytest.approx(
        c["dp/overlap"]["comm_secs"], rel=1e-6)
    # bf16 compression halves the exchange payload on the wire
    assert c["dp_fsdp/bf16+compress"]["wire_bytes"] == pytest.approx(
        c["dp/overlap"]["wire_bytes"] / 2, rel=0.1)
    # the zero1 variant exists for the preset that pins the knob
    lamb = planner.plan_for_preset("imagenet_resnet50_lamb4k",
                                   signatures, include_hbm=False)
    zero1 = [k for k in lamb["candidates"] if k.endswith("overlap+zero1")]
    assert zero1 and all(
        lamb["candidates"][k]["comm_secs"] > 0 for k in zero1)


def test_vit_moe_plan_covers_transformer_layouts(signatures):
    plan = planner.plan_for_preset("vit_moe", signatures,
                                   include_hbm=False)
    layouts = {k.split("/", 1)[0] for k in plan["candidates"]}
    assert {"dp", "dp_fsdp", "dp_tp", "dp_pp", "dp_pp_ep"} <= layouts


def test_recommend_layout_returns_mesh(signatures):
    rec = planner.recommend_layout("vit_moe", n_devices=8)
    assert rec is not None
    layout, mesh_cfg = rec
    assert hasattr(mesh_cfg, "data")
    assert planner.recommend_layout("no_such_preset") is None


# ---------------------------------------------------------------------------
# live virtual-8 leg: probe-fed prediction vs measured step
# ---------------------------------------------------------------------------

def _tiny_overlap_cfg():
    cfg = get_preset("smoke")
    cfg.model.compute_dtype = "float32"
    cfg.model.resnet_size = 8
    cfg.model.num_classes = 4
    cfg.data.image_size = 8
    cfg.train.batch_size = 16
    cfg.comm.overlap = "on"
    cfg.comm.bucket_mb = 0.05
    cfg.optimizer.schedule = "constant"
    cfg.checkpoint.save_every_secs = 0.0
    return cfg


@pytest.fixture(scope="module")
def tiny_overlap_trainer(devices):
    from distributed_resnet_tensorflow_tpu.parallel import create_mesh
    from distributed_resnet_tensorflow_tpu.train import Trainer
    cfg = _tiny_overlap_cfg()
    tr = Trainer(cfg, mesh=create_mesh(MeshConfig(data=8)))
    tr.init_state()
    return cfg, tr


def _batches(n, bs=16, size=8, classes=4):
    rng = np.random.RandomState(7)
    return [{"images": rng.randn(bs, size, size, 3).astype(np.float32),
             "labels": rng.randint(0, classes, (bs,)).astype(np.int32)}
            for _ in range(n)]


def test_probe_fed_prediction_within_documented_tolerance(
        tiny_overlap_trainer):
    """The bench.py discipline (docs/planner.md 'Tolerances'): measured
    compute + probe-fed bandwidths must predict the bucketed leg's step
    inside the plan_tolerance band the live sentinel enforces."""
    import time as _time
    from distributed_resnet_tensorflow_tpu.parallel.overlap import (
        overlap_stats, probe_comm_plan)
    cfg, tr = tiny_overlap_trainer
    state, _ = tr.train(iter(_batches(2)), num_steps=2)  # compile+warm
    n = 6
    t0 = _time.perf_counter()
    state, _ = tr.train(iter(_batches(n)), num_steps=n)
    jax.block_until_ready(state.params)
    measured_step = (_time.perf_counter() - t0) / n

    timing = probe_comm_plan(tr.mesh)
    assert timing is not None and timing["buckets"]
    bw = planner.BandwidthTable.from_probe(timing)
    assert bw is not None and bw.source == "probe"
    snap = overlap_stats.snapshot()
    comm = 0.0
    for wire, sig in zip(snap["bucket_wire_bytes"],
                         snap["bucket_reduce_axes"]):
        bps, lat = bw.lookup(sig)
        comm += lat + int(wire) / bps
    # CPU "compute" is the measured step itself net of the probed
    # exchange — the off-leg substitution bench.py records
    compute = max(measured_step - timing["comm_secs_total"], 1e-9)
    exposed = max(0.0, comm - planner.OVERLAP_EFFICIENCY * compute)
    predicted = compute + exposed
    tol = cfg.telemetry.plan_tolerance
    assert predicted / measured_step < tol
    assert measured_step / predicted < tol


def test_predict_live_builds_after_trace(tiny_overlap_trainer):
    cfg, tr = tiny_overlap_trainer
    pred = planner.predict_live(cfg, tr,
                                bandwidth=planner.BandwidthTable
                                .reference())
    assert pred is not None
    for k in ("step_secs", "compute_secs", "comm_secs",
              "comm_exposed_secs", "comm_fraction", "wire_bytes",
              "hbm_bytes"):
        assert k in pred, k
    assert pred["hbm_bytes"] >= pred["state_bytes"] > 0


def test_plan_drift_hook_fires_once_on_seeded_bandwidth_lie(
        tiny_overlap_trainer, tmp_path, monkeypatch):
    """Satellite contract: a lying bandwidth table (comm predicted as
    ~free, so the whole step is predicted orders of magnitude faster
    than a CPU can step) must arm the sentinel and produce exactly ONE
    plan_drift row per episode — plus the arming plan row."""
    from distributed_resnet_tensorflow_tpu.train.hooks import (
        PlanDriftHook)
    from distributed_resnet_tensorflow_tpu.utils.metrics import (
        MetricsWriter)
    cfg, tr = tiny_overlap_trainer
    monkeypatch.setattr(
        planner, "measured_bandwidth_table",
        lambda: planner.BandwidthTable(source="catalog",
                                       axes={}, default_bps=1e18,
                                       default_latency=0.0))
    cfg.telemetry.plan_drift_window = 2
    cfg.telemetry.plan_drift_cooldown_secs = 0.0
    w = MetricsWriter(str(tmp_path), enable_tensorboard=False)
    hook = PlanDriftHook(w, cfg, tr, every_steps=1)
    n = 8
    tr.train(iter(_batches(n)), num_steps=n, hooks=[hook])
    w.flush()
    w.close()
    rows = [json.loads(l) for l in
            open(os.path.join(str(tmp_path), "metrics.jsonl"))]
    plan_rows = [r for r in rows if r.get("event") == "plan"]
    drift_rows = [r for r in rows if r.get("event") == "plan_drift"]
    assert len(plan_rows) == 1
    assert plan_rows[0]["layout"] == "dp"
    assert plan_rows[0]["bandwidth_source"] == "catalog"
    # one episode, one firing — step_secs stays divergent the whole run
    step_firings = [r for r in drift_rows if r["metric"] == "step_secs"]
    assert len(step_firings) == 1
    assert step_firings[0]["ratio"] > cfg.telemetry.plan_tolerance
    assert step_firings[0]["windows"] >= cfg.telemetry.plan_drift_window


# ---------------------------------------------------------------------------
# DriftSentinel episode/cooldown semantics (fake clock)
# ---------------------------------------------------------------------------

def _sentinel(**kw):
    clock = {"t": 0.0}
    kw.setdefault("tolerance", 3.0)
    kw.setdefault("window", 3)
    kw.setdefault("cooldown_secs", 100.0)
    s = planner.DriftSentinel({"step_secs": 1.0, "comm_secs": 0.01},
                              clock=lambda: clock["t"], **kw)
    return s, clock


def test_sentinel_fires_exactly_once_per_episode():
    s, _clock = _sentinel()
    assert s.check("step_secs", 1.1) is None          # in tolerance
    for _ in range(2):
        assert s.check("step_secs", 10.0) is None     # streak building
    firing = s.check("step_secs", 10.0)               # window reached
    assert firing and firing["metric"] == "step_secs"
    assert firing["ratio"] == pytest.approx(10.0)
    for _ in range(20):                               # still divergent
        assert s.check("step_secs", 10.0) is None     # episode: silent
    assert s.check("step_secs", 1.0) is None          # episode ends
    for _ in range(2):
        assert s.check("step_secs", 10.0) is None


def test_sentinel_cooldown_defers_but_does_not_lose_the_fire():
    s, clock = _sentinel()
    for _ in range(2):
        s.check("step_secs", 10.0)
    assert s.check("step_secs", 10.0)                 # fires at t=0
    s.check("step_secs", 1.0)                         # episode ends
    # new episode inside the cooldown: suppressed, streak kept
    for _ in range(5):
        assert s.check("step_secs", 10.0) is None
    clock["t"] = 101.0                                # cooldown elapsed
    assert s.check("step_secs", 10.0) is not None


def test_sentinel_metrics_are_independent():
    s, _clock = _sentinel(window=2)
    s.check("comm_secs", 0.5)
    assert s.check("comm_secs", 0.5)["metric"] == "comm_secs"
    # step_secs' streak is untouched by comm's episode
    s.check("step_secs", 10.0)
    assert s.check("step_secs", 10.0) is None         # cooldown gates it
    assert s.check("hbm_bytes", 1e12) is None         # not predicted


# ---------------------------------------------------------------------------
# gate artifact: byte-identity + seeded-lie findings
# ---------------------------------------------------------------------------

def test_plan_catalog_byte_identical_across_runs(tmp_path, signatures):
    from distributed_resnet_tensorflow_tpu.analysis.plan_drift import (
        build_catalog, write_plan_catalog)
    fs1, doc1 = build_catalog(signatures)
    fs2, doc2 = build_catalog(signatures)
    assert fs1 == [] and fs2 == []
    p1 = write_plan_catalog(doc1, str(tmp_path / "a.json"))
    p2 = write_plan_catalog(doc2, str(tmp_path / "b.json"))
    b1, b2 = open(p1, "rb").read(), open(p2, "rb").read()
    assert b1 == b2
    doc = json.loads(b1)
    assert doc["schema_version"] == 1
    assert set(doc["plans"]) >= {"cifar10_resnet50",
                                 "imagenet_resnet50", "vit_moe"}


def test_committed_plan_catalog_is_fresh(tmp_path, signatures):
    """The committed artifact matches a fresh reference-constant build
    — like collective_schedules.json, a diff must mean a real change,
    and a stale commit must fail here, not confuse a reviewer."""
    from distributed_resnet_tensorflow_tpu.analysis.plan_drift import (
        build_catalog, plan_catalog_path, write_plan_catalog)
    _fs, doc = build_catalog(signatures)
    fresh = write_plan_catalog(doc, str(tmp_path / "fresh.json"))
    assert open(plan_catalog_path(), "rb").read() == \
        open(fresh, "rb").read()


def test_seeded_bandwidth_lie_is_a_gate_finding(tmp_path, monkeypatch):
    from distributed_resnet_tensorflow_tpu.analysis.plan_drift import (
        check_bandwidth_catalog)
    from distributed_resnet_tensorflow_tpu.telemetry import bandwidth
    monkeypatch.setenv(bandwidth.DIR_ENV, str(tmp_path))
    fabric = bandwidth.fabric_id()
    lie = {"schema_version": 1, "fabric": fabric, "platform": "cpu",
           "device_kind": "", "devices": 8,
           "axes": {"data": {"bytes_per_sec": 4.0e13,
                             "latency_secs": 1e-6, "samples": 1,
                             "min_wire_bytes": 1,
                             "max_wire_bytes": 1}}}
    path = bandwidth.catalog_path(fabric)
    with open(path, "w") as f:
        json.dump(lie, f)
    found = check_bandwidth_catalog(probe_bps=4.0e8)
    assert len(found) == 1
    assert "micro-probe" in found[0].message
    # a truthful catalog is silent
    lie["axes"]["data"]["bytes_per_sec"] = 5.0e8
    with open(path, "w") as f:
        json.dump(lie, f)
    assert check_bandwidth_catalog(probe_bps=4.0e8) == []


# ---------------------------------------------------------------------------
# bandwidth catalog round-trip
# ---------------------------------------------------------------------------

def test_catalog_roundtrip_and_merge_best(tmp_path, monkeypatch):
    from distributed_resnet_tensorflow_tpu.telemetry import bandwidth
    monkeypatch.setenv(bandwidth.DIR_ENV, str(tmp_path))
    snap = {"buckets": [
        {"bucket": 0, "bytes": 100, "wire_bytes": 100, "leaves": 1,
         "axes": "data", "probe_secs": 2e-4,
         "wire_bytes_per_sec": 5e5}],
        "comm_secs_total": 2e-4, "reps": 3, "axes": ["data"],
        "compress": "off"}
    path = bandwidth.update_from_probe(snap)
    assert path and os.path.exists(path)
    doc = bandwidth.load_catalog(path)
    assert doc["axes"]["data"]["bytes_per_sec"] == 5e5
    assert doc["axes"]["data"]["samples"] == 1
    # a better later probe wins; a worse one does not regress the entry
    snap["buckets"][0]["wire_bytes_per_sec"] = 9e5
    snap["buckets"][0]["probe_secs"] = 1e-4
    bandwidth.update_from_probe(snap)
    snap["buckets"][0]["wire_bytes_per_sec"] = 1e5
    snap["buckets"][0]["probe_secs"] = 9e-4
    bandwidth.update_from_probe(snap)
    doc = bandwidth.load_catalog(path)
    assert doc["axes"]["data"]["bytes_per_sec"] == 9e5
    assert doc["axes"]["data"]["latency_secs"] == 1e-4
    assert doc["axes"]["data"]["samples"] == 3


def test_comm_report_synthesizes_from_catalog():
    from distributed_resnet_tensorflow_tpu.telemetry.comm_report import (
        synthesize_timing)
    overlap_row = {"bucket_wire_bytes": [1000, 2000],
                   "bucket_bytes": [1000, 2000],
                   "bucket_leaves": [3, 4],
                   "bucket_reduce_axes": ["data", "data+fsdp"],
                   "compress": "off"}
    catalog = {"schema_version": 1, "fabric": "cpu-8",
               "axes": {"data": {"bytes_per_sec": 1e6,
                                 "latency_secs": 1e-4}}}
    timing = synthesize_timing(overlap_row, catalog)
    assert timing["modeled_from_catalog"] == "cpu-8"
    assert len(timing["buckets"]) == 2
    assert all(b["modeled"] for b in timing["buckets"])
    assert timing["comm_secs_total"] == pytest.approx(
        2e-4 + 3000 / 1e6, rel=1e-6)


# ---------------------------------------------------------------------------
# main.py plan CLI + bench trajectory
# ---------------------------------------------------------------------------

def test_main_plan_cli_ranks_three_presets(capsys):
    rc = planner.main_plan(["--preset", "cifar10_resnet50",
                            "--preset", "imagenet_resnet50",
                            "--preset", "vit_moe",
                            "--no-hbm", "--json"])
    assert rc == 0
    plans = json.loads(capsys.readouterr().out)
    assert [p["preset"] for p in plans] == [
        "cifar10_resnet50", "imagenet_resnet50", "vit_moe"]
    for p in plans:
        assert p["recommended"] in p["candidates"]
    moe = plans[-1]
    assert any(k.startswith("dp_pp_ep/") for k in moe["candidates"])


def test_main_plan_writes_registered_rows(tmp_path, capsys):
    rc = planner.main_plan(["--preset", "cifar10_resnet50", "--no-hbm",
                            "--root", str(tmp_path)])
    assert rc == 0
    rows = [json.loads(l) for l in
            open(os.path.join(str(tmp_path), "plan", "metrics.jsonl"))]
    plan_rows = [r for r in rows if r.get("event") == "plan"]
    assert plan_rows
    assert sum(r["recommended"] for r in plan_rows) == 1
    for r in plan_rows:
        assert {"preset", "layout", "devices", "knobs", "predicted",
                "bandwidth_source", "recommended"} <= set(r)


def _repo_root():
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_trajectory_joins_rounds(tmp_path):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bench_trajectory",
        os.path.join(_repo_root(), "tools", "bench_trajectory.py"))
    bt = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bt)
    for name, parsed in (
            ("BENCH_r01.json", {"a": {"x": 10.0}, "ok": True}),
            ("BENCH_r02.json", {}),                      # the r05 shape
            ("BENCH_r03.json", {"a": {"x": 15.0}, "b": 2})):
        with open(tmp_path / name, "w") as f:
            json.dump({"n": 1, "rc": 0, "cmd": "x", "parsed": parsed}, f)
    traj = bt.build_trajectory(bt.discover_rounds(str(tmp_path)))
    rows = traj["rounds"]
    assert [r["round"] for r in rows] == ["r01", "r02", "r03"]
    assert rows[1]["parsed_empty"] is True
    # the delta bridges the empty round to the last value seen
    assert rows[2]["deltas"]["a.x"] == {"abs": 5.0, "pct": 50.0}
    assert "ok" not in rows[0]["metrics"]  # bools are not magnitudes
    # the real repo rounds join too (8 rounds committed)
    real = bt.build_trajectory(bt.discover_rounds(_repo_root()))
    assert len(real["rounds"]) >= 8
    assert real["keys_tracked"] > 100


def test_monitor_bench_flag(capsys):
    from distributed_resnet_tensorflow_tpu.telemetry.monitor import (
        main_monitor)
    assert main_monitor(["--bench"]) == 0
    assert "bench trajectory" in capsys.readouterr().out
