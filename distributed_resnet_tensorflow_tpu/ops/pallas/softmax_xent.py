"""Fused softmax cross-entropy — Pallas TPU kernel with custom VJP.

The reference computed loss as softmax_cross_entropy_with_logits (a cuDNN/TF
fused op, reference resnet_model.py:78-80). The XLA default materializes
softmax probabilities in HBM between loss and grad; this kernel fuses
logsumexp + NLL in one VMEM pass per batch tile, and the backward kernel
fuses (softmax(logits) - onehot) * g without re-reading probabilities.

Shapes: logits (B, C) float32/bfloat16, labels (B,) int32 → per-example loss
(B,) float32. C is padded to a 128 multiple inside the wrapper (TPU lane
width); padded columns get -inf logits so they carry zero probability.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU backend params; absent on pure-CPU installs
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = pl.ANY

_NEG_INF = -1e30
_TILE_B = 128


def _fwd_kernel(logits_ref, labels_ref, loss_ref):
    logits = logits_ref[:].astype(jnp.float32)          # (TB, C)
    labels = labels_ref[:]                              # (TB, 1) int32
    m = jnp.max(logits, axis=-1, keepdims=True)
    lse = jnp.log(jnp.sum(jnp.exp(logits - m), axis=-1, keepdims=True)) + m
    tb, c = logits.shape
    cols = jax.lax.broadcasted_iota(jnp.int32, (tb, c), 1)
    picked = jnp.sum(jnp.where(cols == labels, logits, 0.0), axis=-1,
                     keepdims=True)
    loss_ref[:] = (lse - picked)                        # (TB, 1)


def _bwd_kernel(logits_ref, labels_ref, g_ref, grad_ref):
    logits = logits_ref[:].astype(jnp.float32)
    labels = labels_ref[:]
    g = g_ref[:]                                        # (TB, 1)
    m = jnp.max(logits, axis=-1, keepdims=True)
    e = jnp.exp(logits - m)
    p = e / jnp.sum(e, axis=-1, keepdims=True)
    tb, c = logits.shape
    cols = jax.lax.broadcasted_iota(jnp.int32, (tb, c), 1)
    onehot = (cols == labels).astype(jnp.float32)
    grad_ref[:] = ((p - onehot) * g).astype(grad_ref.dtype)


def _pad(logits: jax.Array, labels: jax.Array) -> Tuple[jax.Array, jax.Array, int, int]:
    b, c = logits.shape
    cpad = (-c) % 128
    bpad = (-b) % _TILE_B
    if cpad:
        logits = jnp.pad(logits, ((0, 0), (0, cpad)),
                         constant_values=_NEG_INF)
    if bpad:
        logits = jnp.pad(logits, ((0, bpad), (0, 0)),
                         constant_values=_NEG_INF)
        # padded rows pick class 0; their loss rows are dropped by the caller
        labels = jnp.pad(labels, (0, bpad))
    return logits, labels, b, c


def _run_fwd(logits, labels, interpret=False):
    logits, labels, b, c = _pad(logits, labels)
    bp, cp = logits.shape
    grid = (bp // _TILE_B,)
    loss = pl.pallas_call(
        _fwd_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((_TILE_B, cp), lambda i: (i, 0), memory_space=_VMEM),
            pl.BlockSpec((_TILE_B, 1), lambda i: (i, 0), memory_space=_VMEM),
        ],
        out_specs=pl.BlockSpec((_TILE_B, 1), lambda i: (i, 0),
                               memory_space=_VMEM),
        out_shape=jax.ShapeDtypeStruct((bp, 1), jnp.float32),
        interpret=interpret,
    )(logits, labels.astype(jnp.int32).reshape(-1, 1))
    return loss[:b, 0]


def _run_bwd(logits, labels, g, interpret=False):
    dtype = logits.dtype
    logits, labels, b, c = _pad(logits, labels)
    bp, cp = logits.shape
    g = jnp.pad(g.reshape(-1, 1), ((0, bp - b), (0, 0)))
    grid = (bp // _TILE_B,)
    grad = pl.pallas_call(
        _bwd_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((_TILE_B, cp), lambda i: (i, 0), memory_space=_VMEM),
            pl.BlockSpec((_TILE_B, 1), lambda i: (i, 0), memory_space=_VMEM),
            pl.BlockSpec((_TILE_B, 1), lambda i: (i, 0), memory_space=_VMEM),
        ],
        out_specs=pl.BlockSpec((_TILE_B, cp), lambda i: (i, 0),
                               memory_space=_VMEM),
        out_shape=jax.ShapeDtypeStruct((bp, cp), dtype),
        interpret=interpret,
    )(logits, labels.astype(jnp.int32).reshape(-1, 1), g)
    return grad[:b, :c]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def softmax_xent(logits: jax.Array, labels: jax.Array,
                 interpret: bool = False) -> jax.Array:
    """Per-example softmax cross-entropy, fused on TPU. ``interpret=True``
    runs the kernel in the Pallas interpreter (CPU tests)."""
    return _run_fwd(logits, labels, interpret)


def _vjp_fwd(logits, labels, interpret):
    return _run_fwd(logits, labels, interpret), (logits, labels)


def _vjp_bwd(interpret, res, g):
    logits, labels = res
    return _run_bwd(logits, labels, g, interpret), None


softmax_xent.defvjp(_vjp_fwd, _vjp_bwd)


def softmax_xent_mean(logits: jax.Array, labels: jax.Array,
                      interpret: bool = False) -> jax.Array:
    return softmax_xent(logits, labels, interpret).mean()
