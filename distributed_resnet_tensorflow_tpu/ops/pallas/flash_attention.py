"""Flash attention — Pallas TPU kernel (forward) with recompute backward.

Canonical TPU tiling: grid (batch·heads, q_blocks, k_blocks) with the k-block
dimension innermost and sequential ("arbitrary" semantics); online-softmax
accumulators (m, l, acc) live in VMEM scratch and persist across the k-block
iterations, so VMEM holds only one (block_q, d) query tile and one
(block_k, d) key/value tile at a time — O(block) VMEM, any sequence length.
Output is written on the last k iteration.

The backward pass recomputes attention via the lax blockwise implementation
(ops/attention.py) under ``jax.vjp`` — O(T) memory, one extra forward, no
O(T²) residuals (flash-attention v1 strategy). A fused Pallas backward is the
known next step.

Layout: (B, T, H, D). The wrapper pads T up to lcm(block_q, block_k) and D to
the 128-lane width; padded keys are masked via ``valid_len``, padded queries
are sliced off. Causal masking uses the dense-attention convention: with
tq == tk the diagonal, i.e. query i attends keys ≤ i.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU backend bits; fall back gracefully on CPU-only installs
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
    _HAVE_TPU_PARAMS = True
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = pl.ANY
    _HAVE_TPU_PARAMS = False

_NEG_INF = -1e30
BLOCK_Q = 256
BLOCK_K = 256


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
                  scale, causal, valid_len, block_q, block_k, nk):
    """One (q-block, k-block) tile. Scratch m/l/acc persist across the
    innermost (k-block) grid dimension."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # blocks strictly above the causal diagonal contribute nothing
    live = jnp.logical_or(not causal,
                          kj * block_k <= qi * block_q + block_q - 1)

    @pl.when(live)
    def _accumulate():
        q = q_ref[0].astype(jnp.float32) * scale          # (bq, d)
        k = k_ref[0].astype(jnp.float32)                  # (bk, d)
        v = v_ref[0].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        k_pos = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        if valid_len is not None:
            s = jnp.where(k_pos < valid_len, s, _NEG_INF)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, 1), 0)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_prev, l_prev, acc_prev = m_ref[:], l_ref[:], acc_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        m_ref[:] = m_new
        l_ref[:] = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_prev * corr + jnp.dot(
            p, v, preferred_element_type=jnp.float32)

    @pl.when(kj == nk - 1)
    def _finalize():
        l = l_ref[:]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal=False, interpret=False,
                   block_q=BLOCK_Q, block_k=BLOCK_K):
    b, t, h, d = q.shape
    scale = 1.0 / math.sqrt(d)
    # clamp blocks to the (padded) sequence, keeping them a multiple of the
    # TPU sublane tile (16 covers bf16's (16,128) and f32's (8,128)) so
    # Mosaic accepts shapes like t=196 (ViT-224/16)
    t16 = -(-t // 16) * 16
    block_q = min(block_q, t16)
    block_k = min(block_k, t16)
    step = math.lcm(block_q, block_k)
    tpad = (-t) % step
    dpad = (-d) % 128

    def fold(x):  # (B,T,H,D) → (B·H, T, D)
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    qf, kf, vf = fold(q), fold(k), fold(v)
    if tpad or dpad:
        pad = ((0, 0), (0, tpad), (0, dpad))
        qf, kf, vf = (jnp.pad(x, pad) for x in (qf, kf, vf))
    tp, dp = qf.shape[1], qf.shape[2]
    nq, nk = tp // block_q, tp // block_k
    grid = (b * h, nq, nk)

    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal,
        valid_len=(t if tpad else None), block_q=block_q, block_k=block_k,
        nk=nk)

    if not _HAVE_TPU_PARAMS:  # pragma: no cover
        raise NotImplementedError(
            "flash_attention requires the Pallas TPU backend; use "
            "ops.blockwise_attention on this platform")
    scratch = [pltpu.VMEM((block_q, 1), jnp.float32),
               pltpu.VMEM((block_q, 1), jnp.float32),
               pltpu.VMEM((block_q, dp), jnp.float32)]
    extra = {}
    if not interpret:
        extra = dict(compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")))

    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, dp), lambda bh, i, j: (bh, i, 0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, block_k, dp), lambda bh, i, j: (bh, j, 0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, block_k, dp), lambda bh, i, j: (bh, j, 0),
                         memory_space=_VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, dp), lambda bh, i, j: (bh, i, 0),
                               memory_space=_VMEM),
        out_shape=jax.ShapeDtypeStruct((b * h, tp, dp), q.dtype),
        scratch_shapes=scratch,
        interpret=interpret,
        **extra,
    )(qf, kf, vf)
    return out[:, :t, :d].reshape(b, h, t, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = False, interpret: bool = False) -> jax.Array:
    """Pallas flash attention, (B, T, H, D). Differentiable: backward
    recomputes via the lax blockwise path (O(T) memory)."""
    return _flash_forward(q, k, v, causal, interpret)


def _fa_fwd(q, k, v, causal, interpret):
    return _flash_forward(q, k, v, causal, interpret), (q, k, v)


def _fa_bwd(causal, interpret, res, g):
    from ..attention import blockwise_attention
    q, k, v = res
    _, vjp = jax.vjp(lambda q, k, v: blockwise_attention(q, k, v,
                                                         causal=causal),
                     q, k, v)
    return vjp(g)


flash_attention.defvjp(_fa_fwd, _fa_bwd)
