"""Round-5 long-context ViT MFU — measuring the transformer half of the
BASELINE.md renegotiation instead of asserting it.

The renegotiated target says the >=0.55 MFU bar applies to MXU-filling
models; WRN-28-10 is measured (0.63, docs/perf_cifar_r5.md) but the
flash-attention ViT family was not. This measures the shipped
``vit_long_context`` preset (256² images, patch 4 → 4096 tokens, dim 512,
depth 8) on one chip:

  * attention_impl=dense — every FLOP visible to XLA's cost analysis, so
    the MFU number is fully accounted;
  * attention_impl=flash — the Pallas kernels are custom calls whose FLOPs
    XLA does NOT count, so the row reports wall-clock images/s plus an
    MFU bound built from the dense program's counted FLOPs (the flash
    program does the same mathematical work minus the materialized
    softmax; using the dense count OVERSTATES flash FLOPs slightly, so
    the reported flash MFU is a mild UPPER bound and the dense-count MFU
    with flash wall-clock a fair comparison).

Writes docs/perf_vit_r5.json.
"""
from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402
import numpy as np  # noqa: E402

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

OUT = os.path.join(REPO, "docs", "perf_vit_r5.json")


def measure(attn: str, bs: int, k: int = 4, loops: int = 5, reps: int = 5,
            remat=None, **overrides):
    from distributed_resnet_tensorflow_tpu.parallel.sharding import (
        shard_batch, shard_stacked_batch)
    from distributed_resnet_tensorflow_tpu.train import Trainer
    from distributed_resnet_tensorflow_tpu.utils import profiling
    from distributed_resnet_tensorflow_tpu.utils.config import get_preset

    cfg = get_preset("vit_long_context")
    cfg.model.attention_impl = attn
    cfg.train.batch_size = bs
    cfg.train.steps_per_loop = k
    if remat is not None:
        cfg.train.remat = remat
    for dotted, v in overrides.items():
        cfg.override(dotted.replace("__", "."), v)
    cfg.mesh.data = len(jax.devices())
    trainer = Trainer(cfg)
    trainer.init_state()
    multi_fn = trainer.jitted_multi_step(k)
    rng = np.random.RandomState(0)
    batch = shard_stacked_batch({
        "images": rng.randn(k, bs, 256, 256, 3).astype(np.float32),
        "labels": rng.randint(0, 10, (k, bs)).astype(np.int32),
    }, trainer.mesh)
    state = trainer.state

    def fence(st):
        # host pull: on the tunneled backend block_until_ready can return
        # before compute finishes (r4/r5 measurement note; a dense-4096
        # row "measured" 1.8k steps/s = 14 PFLOPs without this)
        return float(jax.numpy.sum(
            jax.tree_util.tree_leaves(st.params)[0].astype(jax.numpy.float32)))

    for _ in range(2):
        state, _m = multi_fn(state, batch)
    fence(state)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(loops):
            state, _m = multi_fn(state, batch)
        fence(state)
        best = min(best, time.perf_counter() - t0)
    sps = loops * k / best
    one = shard_batch({"images": np.asarray(batch["images"])[0],
                       "labels": np.asarray(batch["labels"])[0]},
                      trainer.mesh)
    step_flops = profiling.flops_per_step(
        trainer.jitted_train_step(), state, one)
    util = profiling.mfu(sps, step_flops) if step_flops else None
    return {"attention_impl": attn, "batch_size": bs,
            "tokens_per_image": (256 // 4) ** 2,
            "steps_per_sec": round(sps, 3),
            "images_per_sec": round(sps * bs, 2),
            "counted_step_flops": step_flops,
            "mfu_from_counted_flops": round(util, 4) if util else None}


def main():
    out = {"device": jax.devices()[0].device_kind,
           "workload": "vit_long_context preset: 256^2/patch4 = 4096 "
                       "tokens, dim 512, depth 8, remat, bf16"}
    rows = []
    for attn, bs, remat in (("dense", 4, None), ("flash", 8, None),
                            ("flash", 8, False)):
        try:
            r = measure(attn, bs, remat=remat)
            r["remat"] = remat if remat is not None else True
        except Exception as e:
            r = {"attention_impl": attn, "batch_size": bs, "remat": remat,
                 "error": f"{type(e).__name__}: {e}"[:200]}
        print(json.dumps(r), flush=True)
        rows.append(r)
    # flash MFU bound: same math as dense minus the materialized softmax,
    # so the dense program's per-image FLOP count is a (slight) over-count
    # for the flash program → flash MFU from it is a fair upper-ish bound
    dense = next((r for r in rows if r.get("attention_impl") == "dense"
                  and "error" not in r), None)
    if dense:
        per_img = dense["counted_step_flops"] / dense["batch_size"]
        for r in rows:
            if r.get("attention_impl") == "flash" and "error" not in r:
                flops = per_img * r["batch_size"]
                from distributed_resnet_tensorflow_tpu.utils import profiling
                r["mfu_using_dense_flop_count"] = round(
                    profiling.mfu(r["steps_per_sec"], flops), 4)
    out["rows"] = rows
    with open(OUT, "w") as f:
        json.dump(out, f, indent=1)
    print("wrote", OUT)


if __name__ == "__main__":
    main()
