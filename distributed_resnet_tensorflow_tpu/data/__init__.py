from .synthetic import synthetic_iterator, learnable_synthetic_iterator  # noqa: F401
from .cifar import cifar_iterator, load_cifar, standardize, augment_train  # noqa: F401
from .device_dataset import (  # noqa: F401
    device_dataset_enabled, epoch_index_iterator)


def resolve_decode_workers(cfg, mode: str = "train"):
    """(decode_processes, decode_threads) the imagenet pipeline will
    actually run with — THE resolution point for the auto (-1) defaults of
    ``data.decode_processes`` / ``data.num_parallel_calls``; explicit
    (>= 0) settings always win. Auto scales to the host: processes =
    min(8, cores) when the host has more than 2 cores (below that a
    process pool only adds queue pickling — the GIL-releasing decoders
    already share the core), threads = min(8, cores) with a floor of 4
    (threads hide I/O even on small hosts). bench.py records the resolved
    pair next to ``host_cores`` in the imagenet_input row."""
    import os
    d = cfg.data
    cpu = os.cpu_count() or 1
    procs = d.decode_processes
    if procs < 0:
        procs = min(8, cpu) if cpu > 2 else 0
    threads = d.num_parallel_calls
    if threads < 0:
        threads = min(8, max(4, cpu))
    return procs, threads


def device_augment_enabled(cfg, mode: str = "train") -> bool:
    """Single source of truth for who augments/standardizes — the iterator
    (yields raw uint8) and the Trainer (applies ops/augment in the jitted
    step or fuses it into the CoalescedStager unpack) MUST agree, so both
    call this.

    cifar*: the device does crop/flip/standardize (ops/augment.py).
    imagenet: the device does the random flip (+ optional
    ``data.augment_pad`` crop jitter) and the VGG standardize
    (ops/augment.imagenet_train_augment); the host decode keeps the
    random resize/crop (tied to per-image source geometry), SKIPS its
    flip (the device takes it over — imagenet_iterator ``device_flip``),
    and ships raw uint8 crops — 4× smaller transfers, no host float
    pass, and echoed appearances of one decoded crop draw fresh
    augmentations (data/echo.py). Round 4: the imagenet EVAL path gets
    the standardize on device too (deterministic, so the only question
    is where the float pass runs; make_eval_step applies it) — cifar
    eval stays host-side (its standardize is per-image moments, fused
    into the host parse)."""
    if cfg.data.dataset not in ("cifar10", "cifar100", "imagenet"):
        return False
    if mode != "train" and cfg.data.dataset != "imagenet":
        return False
    setting = cfg.data.device_augment
    if setting == "on":
        return True
    if setting == "off":
        return False
    if setting != "auto":
        raise ValueError(f"unknown device_augment setting {setting!r}")
    import jax
    return jax.default_backend() == "tpu"


def create_input_iterator(cfg, mode: str = "train", shard_index: int = 0,
                          num_shards: int = 1, batch_size=None,
                          deterministic: bool = False):
    """Input factory — the one definition replacing the 4 near-identical
    ``input_fn`` copies in the reference mains (SURVEY.md §1 note).

    ``deterministic``: required when several processes feed the SAME
    replicated batch slice (non-batch mesh axis over processes) — the
    imagenet pipeline's parallel decode is otherwise completion-ordered
    (see imagenet_iterator). The synthetic and cifar paths are
    deterministic by construction (seeded single-generator streams)."""
    d = cfg.data
    bs = batch_size or (cfg.train.batch_size if mode == "train"
                        else d.eval_batch_size)
    if d.dataset == "synthetic":
        it = synthetic_iterator(bs, d.image_size, cfg.model.num_classes,
                                seed=cfg.train.seed)
    elif d.dataset in ("cifar10", "cifar100"):
        it = cifar_iterator(d.dataset, d.data_dir, bs, mode,
                            seed=cfg.train.seed, shard_index=shard_index,
                            num_shards=num_shards,
                            prefetch=d.prefetch_batches,
                            use_native=d.use_native_loader,
                            device_augment=device_augment_enabled(cfg, mode))
    elif d.dataset == "imagenet":
        from .imagenet import imagenet_iterator
        procs, threads = resolve_decode_workers(cfg, mode)
        dev_aug = device_augment_enabled(cfg, mode)
        it = imagenet_iterator(d.data_dir, bs, mode, image_size=d.image_size,
                               seed=cfg.train.seed, shard_index=shard_index,
                               num_shards=num_shards,
                               num_decode_threads=threads,
                               prefetch_batches=d.prefetch_batches,
                               use_native=d.use_native_loader,
                               device_standardize=dev_aug,
                               # flip moved on-device with the rest of the
                               # train augmentation (see
                               # device_augment_enabled): the host draw
                               # still happens (RNG contract) but is not
                               # applied, or train batches would be
                               # double-flipped
                               device_flip=dev_aug and mode == "train",
                               decode_processes=procs,
                               deterministic=deterministic,
                               max_corrupt_records=d.max_corrupt_records,
                               verify_crc=d.verify_crc)
    else:
        raise ValueError(f"unknown dataset {d.dataset!r}")
    if mode == "train" and d.echo_factor > 1:
        # data echoing: one decode feeds echo_factor batches, reshuffled
        # per echo out of the bounded decoded-sample cache (data/echo.py)
        from .echo import echoing_iterator
        it = echoing_iterator(it, d.echo_factor, cache_mb=d.echo_cache_mb,
                              seed=cfg.train.seed)
    return it
