"""stray-device-put: all host→device transfers live in parallel/sharding.py.

The overlapped input pipeline's thread-safety story (docs/input_pipeline.md)
rests on knowing exactly where transfers are issued: the coalesced hot path
funnels through ``_issue_device_put`` (so tests can count one transfer per
batch) and every other placement goes through ``put_to_sharding`` in the
same module. A ``jax.device_put`` sprinkled anywhere else silently escapes
transfer accounting, dtype coercion (``coerce_batch_dtypes``), and the
single-issue audit — so any call outside ``parallel/sharding.py`` is a
finding. Deliberate exceptions carry ``# shardcheck: ok(stray-device-put)``.

This explicitly covers ``serve/``: the inference server's request path
stages batches through the Trainer's put (CoalescedStager) and the hot-swap
apply goes through ``put_to_sharding`` — a raw ``device_put`` there would
also dodge the serving threading contract (the swap thread moves HOST trees
only; all device placement happens on the dispatch thread or via the
audited put paths — docs/serving.md). No new raw device_put sites.
"""
from __future__ import annotations

import ast
from typing import Iterable

from ..report import Finding

RULE_NAME = "stray-device-put"
DOC = __doc__

ALLOWED_FILES = (
    "distributed_resnet_tensorflow_tpu/parallel/sharding.py",
)


def _is_device_put(node: ast.Call) -> bool:
    fn = node.func
    if isinstance(fn, ast.Attribute) and fn.attr == "device_put":
        return True  # jax.device_put / anything.device_put
    if isinstance(fn, ast.Name) and fn.id == "device_put":
        return True  # from jax import device_put
    return False


def check(ctx) -> Iterable[Finding]:
    for sf in ctx.all_python():
        if sf.tree is None or sf.rel in ALLOWED_FILES:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call) and _is_device_put(node):
                yield Finding(
                    RULE_NAME, sf.rel, node.lineno,
                    "jax.device_put outside parallel/sharding.py — route "
                    "through put_to_sharding (or the coalesced stager) so "
                    "transfers stay auditable in one module")
