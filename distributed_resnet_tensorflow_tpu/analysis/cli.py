"""The ``check`` subcommand: lint + static elaboration in one gate.

    python -m distributed_resnet_tensorflow_tpu.main check --all-presets
    python -m distributed_resnet_tensorflow_tpu.main check --preset smoke
    python -m distributed_resnet_tensorflow_tpu.main check --lint-only

Exit code 0 = clean, 1 = findings (the exit-code contract's real-failure
code: a red gate must fail the submit). Designed to finish in well under
a minute on CPU — scripts/analysis_gate.sh runs it pre-submit
(scripts/submit_tpu_slurm.sh) and pre-merge (scripts/chaos_smoke.sh
--fast). docs/static_analysis.md is the manual.
"""
from __future__ import annotations

import argparse
import time
from typing import Optional, Sequence


def main_check(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="main.py check",
        description="shardcheck: invariant lint + static elaboration")
    scope = p.add_mutually_exclusive_group()
    scope.add_argument("--all-presets", action="store_true",
                       help="elaborate every preset (also the default)")
    scope.add_argument("--preset", action="append", default=[],
                       help="elaborate only this preset (repeatable)")
    depth = p.add_mutually_exclusive_group()
    depth.add_argument("--lint-only", action="store_true",
                       help="skip elaboration")
    depth.add_argument("--elaborate-only", action="store_true",
                       help="skip the linter")
    p.add_argument("--devices", type=int, default=8,
                   help="virtual CPU mesh size for elaboration (default 8)")
    p.add_argument("--no-zero1-sweep", action="store_true",
                   help="skip the 64/256-device ZeRO-1 big-mesh sweep "
                        "(elab-zero1)")
    p.add_argument("-v", "--verbose", action="store_true",
                   help="print finding detail (full tracebacks)")
    ns = p.parse_args(argv)

    findings = []
    t0 = time.perf_counter()
    if not ns.lint_only:
        # the virtual mesh must exist BEFORE the first jax backend use —
        # and the LINT pass is now a backend user too (unsharded-opt-state
        # resolves preset states via eval_shape), so the flags go down
        # before anything else runs. Sized for the big-mesh ZeRO-1 sweep
        # when it runs (virtual CPU devices are threads over one host
        # platform; 256 of them cost ~nothing at eval_shape-only load).
        from ..utils.virtual_devices import apply_virtual_cpu
        from .elaborate import ZERO1_SWEEP_SIZES
        n_virtual = ns.devices if ns.no_zero1_sweep \
            else max(ns.devices, max(ZERO1_SWEEP_SIZES))
        apply_virtual_cpu(n_virtual)
    if not ns.elaborate_only:
        from .lint import run_lint
        findings += run_lint()
        print(f"lint: {len(findings)} finding(s) "
              f"[{time.perf_counter() - t0:.1f}s]")
    if not ns.lint_only:
        from .elaborate import run_elaborate
        t1 = time.perf_counter()
        presets = ns.preset or None  # None = all
        efs = run_elaborate(presets, n_devices=ns.devices)
        print(f"elaborate: {len(efs)} finding(s) "
              f"[{time.perf_counter() - t1:.1f}s]")
        findings += efs
        if not ns.no_zero1_sweep:
            from .elaborate import run_elaborate_zero1
            t2 = time.perf_counter()
            zfs = run_elaborate_zero1(presets)
            print(f"elab-zero1 (64/256-device sweep): {len(zfs)} "
                  f"finding(s) [{time.perf_counter() - t2:.1f}s]")
            findings += zfs

    from .report import format_findings
    print(format_findings(findings, verbose=ns.verbose))
    print(f"shardcheck total: {time.perf_counter() - t0:.1f}s")
    return 1 if findings else 0
