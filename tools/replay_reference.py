"""One-command real-data replication of the reference's published runs
(VERDICT r3 #8 — keep the real-data door open).

The reference's accuracy numbers (93.6% CIFAR-10 @ ~80k steps on one P100,
reference README.md:28-30; 62.6-64.4% ImageNet @ ~75k steps at gbs 1024,
README.md:44-47) cannot be replicated in this environment (no dataset
egress — PARITY.md "Known gaps"). The moment real data is reachable,
replication is:

    python tools/replay_reference.py --dataset cifar10 --data_dir /data/cifar
    python tools/replay_reference.py --dataset imagenet --data_dir /data/imagenet

which runs the EXACT reference recipe (the presets encode the published
LR schedules verbatim: piecewise 0.1/0.01/0.001/0.0001 at 40k/60k/80k for
CIFAR, reference resnet_cifar_main.py:298-307; warmup->0.4 with x0.1 at
37440/74880/99840 for ImageNet gbs 1024, resnet_imagenet_main.py:236-247),
trains with periodic checkpoints + the polling evaluator's best-precision
tracking, finishes with a FULL test-set eval (10k / 50k images — the
reference's own evaluator sampled only 50x100), and writes the BASELINE.md
comparison table to <log_root>/replay_report.{json,md}.

``--smoke`` replays the same code path for a few steps on synthetic
stand-in data — the CI-checkable proof the command works end to end
(tests/test_main_cli.py::test_replay_reference_smoke).
"""
from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

REFERENCE_ROWS = {
    "cifar10": {
        "preset": "cifar10_resnet50",
        "reference_top1": 0.936,
        "reference_steps": 80000,
        "reference_hw": "1x P100 (13.94 steps/s, reference README.md:28-30)",
        "test_images": 10000,
    },
    "imagenet": {
        "preset": "imagenet_resnet50",
        "reference_top1": 0.644,  # best distributed row (README.md:47)
        "reference_steps": 75000,
        "reference_hw": "4ps-8wk P100 gbs 1024 (README.md:44-47); "
                        "north star BASELINE.md: 75.9%",
        "test_images": 50000,
    },
}


def build_config(dataset: str, data_dir: str, log_root: str,
                 batch_size: int = 0, steps: int = 0):
    from distributed_resnet_tensorflow_tpu.utils.config import get_preset
    row = REFERENCE_ROWS[dataset]
    cfg = get_preset(row["preset"])
    cfg.data.data_dir = data_dir
    cfg.data.use_native_loader = True
    cfg.log_root = log_root
    cfg.checkpoint.directory = os.path.join(log_root, "ckpt")
    cfg.eval.eval_dir = os.path.join(log_root, "eval")
    if batch_size:
        cfg.train.batch_size = batch_size
    if steps:
        cfg.train.train_steps = steps
        cfg.optimizer.total_steps = steps
    # in-loop eval cadence ~ the reference evaluator's 60 s poll; the final
    # full-set eval below is the accuracy of record
    cfg.mode = "train_and_eval"
    cfg.train.eval_every_steps = max(1, cfg.train.train_steps // 100)
    cfg.eval.eval_batch_count = math.ceil(
        row["test_images"] / cfg.data.eval_batch_size)
    return cfg


def final_full_eval(cfg):
    """Full test-set pass through the standalone evaluator machinery."""
    from distributed_resnet_tensorflow_tpu.checkpoint import CheckpointManager
    from distributed_resnet_tensorflow_tpu.data import create_input_iterator
    from distributed_resnet_tensorflow_tpu.train import Trainer

    trainer = Trainer(cfg)
    trainer.init_state()
    mngr = CheckpointManager(cfg.checkpoint.directory)
    state, step = mngr.restore(trainer.state)
    if step is None:
        raise RuntimeError(f"no checkpoint under {cfg.checkpoint.directory}")
    trainer.state = state
    it = create_input_iterator(cfg, mode="eval")
    res = trainer.evaluate(it, num_batches=cfg.eval.eval_batch_count)
    mngr.close()
    return res, step


def write_report(log_root, dataset, result, step, wall_hours):
    row = REFERENCE_ROWS[dataset]
    report = {
        "dataset": dataset,
        "top1": result["precision"],
        "eval_images": result["count"],
        "at_step": step,
        "wall_hours": round(wall_hours, 2),
        "reference_top1": row["reference_top1"],
        "reference_steps": row["reference_steps"],
        "reference_hw": row["reference_hw"],
        "delta_top1": round(result["precision"] - row["reference_top1"], 4),
    }
    jpath = os.path.join(log_root, "replay_report.json")
    with open(jpath, "w") as f:
        json.dump(report, f, indent=2)
    md = (
        f"# Reference replay — {dataset}\n\n"
        f"| | this framework (TPU) | reference |\n|---|---|---|\n"
        f"| top-1 | **{result['precision']:.4f}** ({result['count']} "
        f"images, full set) | {row['reference_top1']:.3f} "
        f"({row['reference_hw']}) |\n"
        f"| steps | {step} | ~{row['reference_steps']} |\n"
        f"| wall | {wall_hours:.2f} h | — |\n\n"
        f"Δ top-1 vs reference: **{report['delta_top1']:+.4f}**\n"
    )
    mpath = os.path.join(log_root, "replay_report.md")
    with open(mpath, "w") as f:
        f.write(md)
    print(md)
    print(f"wrote {jpath} and {mpath}")
    return report


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--dataset", choices=sorted(REFERENCE_ROWS), required=True)
    ap.add_argument("--data_dir", default="",
                    help="real dataset root (CIFAR binaries / TFRecords)")
    ap.add_argument("--log_root", default="")
    ap.add_argument("--batch_size", type=int, default=0,
                    help="override the recipe's global batch")
    ap.add_argument("--steps", type=int, default=0,
                    help="override train steps (recipe default otherwise)")
    ap.add_argument("--smoke", action="store_true",
                    help="few steps on synthetic stand-in data (CI check)")
    args = ap.parse_args(argv)

    log_root = args.log_root or os.path.join(
        "/tmp", f"drt_replay_{args.dataset}")
    data_dir = args.data_dir
    steps = args.steps
    if args.smoke:
        if args.dataset == "cifar10":
            from make_synth_cifar import make_split, write_cifar_files
            data_dir = os.path.join(log_root, "synth_data")
            images, labels = make_split(640, seed=0)
            write_cifar_files(data_dir, images, labels,
                              [f"data_batch_{i}.bin" for i in range(1, 6)])
            ti, tl = make_split(200, seed=1)
            write_cifar_files(data_dir, ti, tl, ["test_batch.bin"])
        else:
            from make_synth_imagenet import write_split
            data_dir = os.path.join(log_root, "synth_data")
            os.makedirs(data_dir, exist_ok=True)
            write_split(data_dir, "train", 2, 2, num_classes=8,
                        per_class=8, seed=0)
            write_split(data_dir, "validation", 1, 1, num_classes=8,
                        per_class=4, seed=1)
        steps = steps or 4
    if not data_dir:
        ap.error("--data_dir is required (or pass --smoke)")

    cfg = build_config(args.dataset, data_dir, log_root,
                       batch_size=args.batch_size
                       or (64 if args.smoke else 0), steps=steps)
    if args.smoke:
        cfg.train.eval_every_steps = 0
        cfg.eval.eval_batch_count = 2
        cfg.checkpoint.save_every_steps = steps
        cfg.checkpoint.save_every_secs = 0.0
        cfg.data.use_native_loader = False

    from distributed_resnet_tensorflow_tpu.main import (run_train,
                                                        run_train_and_eval)
    t0 = time.time()
    if cfg.train.eval_every_steps > 0:
        # real replays: periodic eval + best-precision tracking in-loop
        run_train_and_eval(cfg)
    else:
        run_train(cfg)  # smoke: train only; the full-set eval follows
    result, step = final_full_eval(cfg)
    return write_report(log_root, args.dataset, result, step,
                        (time.time() - t0) / 3600.0)


if __name__ == "__main__":
    main()
