#!/bin/bash
# Chaos smoke — run the fault-injection suite (resilience/faultinject.py):
# signal delivery mid-run, torn/bit-rotted checkpoints, injected NaN loss.
# Everything runs on the fake-CPU mesh (tests/conftest.py) — no accelerator
# needed. It is the same set tier-1 runs (`-m "not slow"`); note that set
# INCLUDES the @heavy SIGTERM kill-and-resume subprocess test (~1-2 min of
# real training subprocesses on a 1-core host). For a seconds-fast pass,
# add `-m "not slow and not heavy"`.
#
#   scripts/chaos_smoke.sh            # the tier-1 chaos set (incl. heavy)
#   scripts/chaos_smoke.sh -k nan     # just the NaN-recovery cases
set -euo pipefail
cd "$(dirname "$0")/.."

exec env JAX_PLATFORMS=cpu python -m pytest tests/test_resilience.py -q \
  -m "not slow" -p no:cacheprovider "$@"
