// Native data-loader core — the C++ tier of the input pipeline.
//
// The reference delegated all native input work to TensorFlow's C++ runtime
// (queue runners, tf.data — SURVEY.md §2.4-2.6, L0). This library is the
// in-tree equivalent for the TPU framework: TFRecord framing + CRC32C,
// CIFAR binary parsing with CHW→HWC transpose, and a multithreaded
// record prefetcher with a bounded ring buffer. Exposed as a plain C ABI
// consumed via ctypes (data/native_loader.py) — no pybind11 dependency.
//
// Build: make -C distributed_resnet_tensorflow_tpu/native
//
// JPEG: when jpeglib.h is present at build time (-DDRT_WITH_JPEG, see the
// Makefile), drt_decode_resize_crop provides the hot ImageNet transform as
// ONE native pass — DCT-scaled decode (libjpeg scale_num/8, decoding a
// fraction of the blocks) fused with a bilinear sample of exactly the crop
// window (+flip) — no full-size pixels, no intermediate resized image.
// ctypes releases the GIL for the call, so the Python decode thread pool
// gets true parallelism. Without libjpeg the symbol reports unavailable
// and the Python PIL path (also scaled: PIL draft) serves instead.

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// CRC32C (Castagnoli), slicing-by-8 — TFRecord integrity checks at IO speed
// ---------------------------------------------------------------------------

static uint32_t g_crc_table[8][256];
static std::atomic<bool> g_crc_init{false};
static std::mutex g_crc_mu;

static void crc32c_init() {
  if (g_crc_init.load(std::memory_order_acquire)) return;
  std::lock_guard<std::mutex> lock(g_crc_mu);
  if (g_crc_init.load(std::memory_order_relaxed)) return;
  const uint32_t poly = 0x82F63B78u;
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? (c >> 1) ^ poly : c >> 1;
    g_crc_table[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = g_crc_table[0][i];
    for (int t = 1; t < 8; t++) {
      c = g_crc_table[0][c & 0xFF] ^ (c >> 8);
      g_crc_table[t][i] = c;
    }
  }
  g_crc_init.store(true, std::memory_order_release);
}

uint32_t drt_crc32c(const uint8_t* data, uint64_t len) {
  crc32c_init();
  uint32_t crc = 0xFFFFFFFFu;
  while (len >= 8) {
    uint64_t chunk;
    memcpy(&chunk, data, 8);
    chunk ^= crc;  // little-endian assumption (x86/ARM TPU hosts)
    crc = g_crc_table[7][chunk & 0xFF] ^
          g_crc_table[6][(chunk >> 8) & 0xFF] ^
          g_crc_table[5][(chunk >> 16) & 0xFF] ^
          g_crc_table[4][(chunk >> 24) & 0xFF] ^
          g_crc_table[3][(chunk >> 32) & 0xFF] ^
          g_crc_table[2][(chunk >> 40) & 0xFF] ^
          g_crc_table[1][(chunk >> 48) & 0xFF] ^
          g_crc_table[0][(chunk >> 56) & 0xFF];
    data += 8;
    len -= 8;
  }
  while (len--) crc = g_crc_table[0][(crc ^ *data++) & 0xFF] ^ (crc >> 8);
  return crc ^ 0xFFFFFFFFu;
}

uint32_t drt_masked_crc32c(const uint8_t* data, uint64_t len) {
  uint32_t crc = drt_crc32c(data, len);
  return ((crc >> 15) | (crc << 17)) + 0xA282EAD8u;
}

// ---------------------------------------------------------------------------
// CIFAR binary parsing (reference cifar_input.py record layout):
// [label_bytes][3072 bytes CHW planes] → HWC uint8 + int32 fine label
// ---------------------------------------------------------------------------

int64_t drt_cifar_load(const char* path, int32_t label_bytes,
                       int32_t label_offset, uint8_t* images_out,
                       int32_t* labels_out, int64_t max_records) {
  const int64_t kImg = 32 * 32 * 3;
  const int64_t rec_len = label_bytes + kImg;
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  std::vector<uint8_t> rec(rec_len);
  int64_t n = 0;
  while (n < max_records && fread(rec.data(), 1, rec_len, f) == (size_t)rec_len) {
    labels_out[n] = rec[label_offset];
    const uint8_t* chw = rec.data() + label_bytes;
    uint8_t* hwc = images_out + n * kImg;
    // CHW (3,32,32) → HWC (32,32,3)
    for (int h = 0; h < 32; h++)
      for (int w = 0; w < 32; w++) {
        const int p = h * 32 + w;
        hwc[p * 3 + 0] = chw[p];
        hwc[p * 3 + 1] = chw[1024 + p];
        hwc[p * 3 + 2] = chw[2048 + p];
      }
    n++;
  }
  fclose(f);
  return n;
}

// ---------------------------------------------------------------------------
// Threaded TFRecord prefetcher: N reader threads over a file list, bounded
// ring of raw records — successor of the reference's 16-thread shuffle queue
// (reference cifar_input.py:77-96) on the IO side.
// ---------------------------------------------------------------------------

struct Record {
  std::vector<uint8_t> data;
};

struct Prefetcher {
  std::vector<std::string> files;
  std::deque<Record> ring;
  std::mutex mu;
  std::condition_variable not_empty, not_full;
  size_t capacity = 256;
  std::atomic<int64_t> next_file{0};
  std::atomic<int> live_readers{0};
  std::atomic<bool> stop{false};
  std::atomic<int64_t> crc_errors{0};
  // records lost to mid-record EOF / corrupt length framing (distinct
  // from clean end-of-file) -- surfaced so a damaged shard is loud
  // (the python reader raises on truncation; silent data loss is the
  // failure mode this counter closes)
  std::atomic<int64_t> truncated{0};
  // consumers currently inside drt_prefetch_next: destroy must not free
  // the object while a thread is blocked on not_empty using p->mu
  std::atomic<int> active_consumers{0};
  bool verify_crc = false;
  std::vector<std::thread> threads;
};

static bool read_file_records(Prefetcher* p, const std::string& path) {
  FILE* f = fopen(path.c_str(), "rb");
  if (!f) return false;
  uint8_t header[12];
  while (!p->stop.load(std::memory_order_relaxed)) {
    size_t got = fread(header, 1, 12, f);
    if (got == 0) break;  // clean end of file
    if (got != 12) { p->truncated.fetch_add(1); break; }
    uint64_t len;
    memcpy(&len, header, 8);
    if (len > (1ull << 31)) {  // corrupt length: framing is lost for the
      p->truncated.fetch_add(1);  // rest of the file
      break;
    }
    Record rec;
    rec.data.resize(len);
    if (fread(rec.data.data(), 1, len, f) != len) {
      p->truncated.fetch_add(1);
      break;
    }
    uint8_t footer[4];
    if (fread(footer, 1, 4, f) != 4) { p->truncated.fetch_add(1); break; }
    if (p->verify_crc) {
      uint32_t want;
      memcpy(&want, footer, 4);
      if (drt_masked_crc32c(rec.data.data(), len) != want) {
        p->crc_errors.fetch_add(1);
        continue;  // skip corrupt record, keep the stream alive
      }
    }
    std::unique_lock<std::mutex> lock(p->mu);
    p->not_full.wait(lock, [p] {
      return p->ring.size() < p->capacity || p->stop.load();
    });
    if (p->stop.load()) break;
    p->ring.emplace_back(std::move(rec));
    p->not_empty.notify_one();
  }
  fclose(f);
  return true;
}

static void reader_main(Prefetcher* p) {
  while (!p->stop.load(std::memory_order_relaxed)) {
    int64_t idx = p->next_file.fetch_add(1);
    if (idx >= (int64_t)p->files.size()) break;
    read_file_records(p, p->files[idx]);
  }
  if (p->live_readers.fetch_sub(1) == 1) {
    std::lock_guard<std::mutex> lock(p->mu);
    p->not_empty.notify_all();
  }
}

void* drt_prefetch_create(const char** paths, int32_t num_paths,
                          int32_t num_threads, int32_t capacity,
                          int32_t verify_crc) {
  auto* p = new Prefetcher();
  for (int i = 0; i < num_paths; i++) p->files.emplace_back(paths[i]);
  p->capacity = capacity > 0 ? capacity : 256;
  p->verify_crc = verify_crc != 0;
  int nt = num_threads > 0 ? num_threads : 2;
  p->live_readers.store(nt);
  for (int i = 0; i < nt; i++)
    p->threads.emplace_back(reader_main, p);
  return p;
}

// Returns record size (copied into buf up to cap), 0 at end of stream,
// -1 if buf too small (size returned via *needed).
int64_t drt_prefetch_next(void* handle, uint8_t* buf, int64_t cap,
                          int64_t* needed) {
  auto* p = static_cast<Prefetcher*>(handle);
  struct ConsumerGuard {
    std::atomic<int>& c;
    ~ConsumerGuard() { c.fetch_sub(1); }
  };
  p->active_consumers.fetch_add(1);
  ConsumerGuard guard{p->active_consumers};
  std::unique_lock<std::mutex> lock(p->mu);
  p->not_empty.wait(lock, [p] {
    return !p->ring.empty() || p->live_readers.load() == 0 || p->stop.load();
  });
  if (p->ring.empty()) return 0;
  Record& rec = p->ring.front();
  int64_t len = (int64_t)rec.data.size();
  if (needed) *needed = len;
  if (len > cap) return -1;  // caller re-calls with a bigger buffer
  memcpy(buf, rec.data.data(), len);
  p->ring.pop_front();
  p->not_full.notify_one();
  return len;
}

int64_t drt_prefetch_crc_errors(void* handle) {
  return static_cast<Prefetcher*>(handle)->crc_errors.load();
}

int64_t drt_prefetch_truncated(void* handle) {
  return static_cast<Prefetcher*>(handle)->truncated.load();
}

// Wake every blocked reader/consumer WITHOUT freeing anything: the python
// close() protocol is stop -> wait for its in-flight next() calls to
// return -> destroy, so a consumer blocked on not_empty can never hold up
// (or race) the free.
void drt_prefetch_stop(void* handle) {
  auto* p = static_cast<Prefetcher*>(handle);
  std::lock_guard<std::mutex> lock(p->mu);
  p->stop.store(true);
  p->not_full.notify_all();
  p->not_empty.notify_all();
}

void drt_prefetch_destroy(void* handle) {
  auto* p = static_cast<Prefetcher*>(handle);
  {
    // the lock orders the stop store against a reader's wait-predicate
    // check — an unlocked notify could fire between a reader's predicate
    // evaluation and its block, losing the wakeup and deadlocking join()
    std::lock_guard<std::mutex> lock(p->mu);
    p->stop.store(true);
    p->not_full.notify_all();
    p->not_empty.notify_all();
  }
  for (auto& t : p->threads) t.join();
  // a consumer may still be inside drt_prefetch_next (blocked on
  // not_empty, or copying a record): stop is set so its wait predicate is
  // satisfied -- keep notifying and wait for it to leave before freeing
  // the mutex/condvar it is using
  while (p->active_consumers.load() != 0) {
    {
      std::lock_guard<std::mutex> lock(p->mu);
      p->not_empty.notify_all();
      p->not_full.notify_all();
    }
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
  }
  delete p;
}

// ---------------------------------------------------------------------------
// JPEG scaled decode + fused resize/crop/flip (ImageNet train/eval transform)
// ---------------------------------------------------------------------------

int drt_has_jpeg() {
#ifdef DRT_WITH_JPEG
  return 1;
#else
  return 0;
#endif
}

#ifdef DRT_WITH_JPEG
}  // extern "C" (jpeglib.h must not be wrapped)
#include <jpeglib.h>
#include <cmath>
#include <csetjmp>
extern "C" {

namespace {
struct DrtJpegErr {
  jpeg_error_mgr pub;
  jmp_buf jump;
};
void drt_jpeg_error_exit(j_common_ptr cinfo) {
  auto* err = reinterpret_cast<DrtJpegErr*>(cinfo->err);
  longjmp(err->jump, 1);
}
}  // namespace

// Decoded-at-scale pixels of `data`, bilinear-sampled directly into the
// (out_size, out_size, 3) crop at offset (top, left) of the CONCEPTUAL
// resized image (shorter side == resize_side, aspect preserved, dims
// rounded like the Python path), horizontally flipped when flip != 0.
// Returns 0 ok; 1 unsupported content (caller falls back); 2 corrupt.
int drt_decode_resize_crop(const uint8_t* data, uint64_t len,
                           int resize_side, int top, int left,
                           int out_size, int flip, uint8_t* out) {
  jpeg_decompress_struct cinfo;
  DrtJpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = drt_jpeg_error_exit;
  // volatile: assigned between setjmp and a potential longjmp — without it
  // the error path would free an indeterminate (register-cached) pointer
  uint8_t* volatile decoded = nullptr;
  if (setjmp(jerr.jump)) {
    free(decoded);
    jpeg_destroy_decompress(&cinfo);
    return 2;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, data, len);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return 2;
  }
  const int w0 = (int)cinfo.image_width, h0 = (int)cinfo.image_height;
  if (w0 <= 0 || h0 <= 0) { jpeg_destroy_decompress(&cinfo); return 2; }
  if (cinfo.jpeg_color_space == JCS_CMYK ||
      cinfo.jpeg_color_space == JCS_YCCK) {
    jpeg_destroy_decompress(&cinfo);  // rare; PIL handles these
    return 1;
  }
  cinfo.out_color_space = JCS_RGB;
  // smallest scale_num/8 whose decoded shorter side still covers the
  // resize target (DCT-domain downscale: fewer blocks decoded)
  const int min0 = w0 < h0 ? w0 : h0;
  int num = 8;
  for (int s = 1; s <= 8; s++) {
    if ((long)min0 * s >= (long)resize_side * 8) { num = s; break; }
  }
  cinfo.scale_num = num;
  cinfo.scale_denom = 8;
  jpeg_calc_output_dimensions(&cinfo);
  const int dw = (int)cinfo.output_width, dh = (int)cinfo.output_height;
  decoded = (uint8_t*)malloc((size_t)dw * dh * 3);
  if (!decoded) { jpeg_destroy_decompress(&cinfo); return 2; }
  jpeg_start_decompress(&cinfo);
  if (cinfo.output_components != 3) {  // grayscale converts to RGB above;
    jpeg_abort_decompress(&cinfo);     // anything else: fall back
    jpeg_destroy_decompress(&cinfo);
    free(decoded);
    return 1;
  }
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = decoded + (size_t)cinfo.output_scanline * dw * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);

  // conceptual resized dims — EXACTLY the Python formula
  // (preprocessing._resized_dims: round(dim * resize_side / min0)).
  // lrint under the default FE_TONEAREST mode is round-half-EVEN, matching
  // Python round(); (int)(v + 0.5) would be half-up and drift by one row
  // on exact-.5 products, shifting the crop against the drawn offsets
  const double scale = (double)resize_side / (double)min0;
  int rw = (int)lrint(w0 * scale), rh = (int)lrint(h0 * scale);
  if (rw < 1) rw = 1;
  if (rh < 1) rh = 1;
  // bilinear-sample only the crop window
  uint8_t* const dec = decoded;  // non-volatile alias for the hot loop
  for (int r = 0; r < out_size; r++) {
    const int rr = top + r;
    const double sy = ((double)rr + 0.5) * dh / rh - 0.5;
    int y0 = (int)sy;
    if (sy < 0) y0 = 0;
    if (y0 > dh - 1) y0 = dh - 1;  // crop windows beyond the resized image
    int y1 = y0 + 1 < dh ? y0 + 1 : dh - 1;  // clamp-replicate edges
    double fy = sy - y0;
    if (fy < 0) fy = 0;
    if (fy > 1) fy = 1;
    uint8_t* orow = out + (size_t)r * out_size * 3;
    for (int c = 0; c < out_size; c++) {
      const int cc = left + (flip ? (out_size - 1 - c) : c);
      const double sx = ((double)cc + 0.5) * dw / rw - 0.5;
      int x0 = (int)sx;
      if (sx < 0) x0 = 0;
      if (x0 > dw - 1) x0 = dw - 1;
      int x1 = x0 + 1 < dw ? x0 + 1 : dw - 1;
      double fx = sx - x0;
      if (fx < 0) fx = 0;
      if (fx > 1) fx = 1;
      const uint8_t* p00 = dec + ((size_t)y0 * dw + x0) * 3;
      const uint8_t* p01 = dec + ((size_t)y0 * dw + x1) * 3;
      const uint8_t* p10 = dec + ((size_t)y1 * dw + x0) * 3;
      const uint8_t* p11 = dec + ((size_t)y1 * dw + x1) * 3;
      for (int ch = 0; ch < 3; ch++) {
        const double v =
            (1 - fy) * ((1 - fx) * p00[ch] + fx * p01[ch]) +
            fy * ((1 - fx) * p10[ch] + fx * p11[ch]);
        orow[c * 3 + ch] = (uint8_t)(v + 0.5);
      }
    }
  }
  free(dec);
  return 0;
}
#endif  // DRT_WITH_JPEG

}  // extern "C"
