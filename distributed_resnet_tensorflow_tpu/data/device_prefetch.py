"""Device prefetch — overlap host→device transfer with device compute.

The reference's analog was tf.data's prefetch-to-device buffering
(prefetch(2*bs), reference resnet_cifar_main.py:232). Here: wrap a host batch
iterator so batch i+1's ``device_put`` is dispatched while the jitted step for
batch i is still running — JAX transfers are asynchronous, so keeping one
batch in flight hides the PCIe/DCN copy entirely when compute per step
exceeds transfer time.
"""
from __future__ import annotations

import collections
import queue as queue_mod
import threading
from typing import Callable, Iterator


def device_prefetch(host_iter: Iterator, put: Callable, depth: int = 2
                    ) -> Iterator:
    """Yield device-resident batches with ``depth`` transfers in flight.

    ``put`` is the host→device placement fn (e.g. Trainer._put_batch). The
    queue keeps ``depth`` batches already dispatched; pulling one immediately
    dispatches the next, so transfers run behind compute.
    """
    queue: collections.deque = collections.deque()
    try:
        try:
            for _ in range(depth):
                queue.append(put(next(host_iter)))
        except StopIteration:
            pass
        while queue:
            out = queue.popleft()
            try:
                queue.append(put(next(host_iter)))
            except StopIteration:
                pass
            yield out
    finally:
        # propagate close() (e.g. Trainer replacing its cached prefetcher)
        # down to the source so worker threads shut down
        close = getattr(host_iter, "close", None)
        if close is not None:
            close()


class _WorkerError:
    def __init__(self, exc: BaseException):
        self.exc = exc


_STOP = object()


def threaded_stacker(host_iter: Iterator, k: int, depth: int = 2) -> Iterator:
    """Draw K batches and np.stack them in a background thread.

    This is the input side of the fused ``steps_per_loop`` dispatch
    (Trainer.jitted_multi_step): the K-batch draw + stack is real host work
    (decode, memcpy) that would otherwise sit between scan dispatches; a
    bounded queue of ``depth`` pre-stacked loops keeps the dispatch thread
    hot. Iterator exhaustion ends the stream cleanly (a trailing partial
    group of < k batches is dropped — the Trainer runs tails unfused);
    worker exceptions re-raise on the consuming thread. Closing the returned
    generator stops the worker thread (it would otherwise park on the
    bounded queue forever, holding stacked batches).
    """
    import numpy as np

    q: queue_mod.Queue = queue_mod.Queue(maxsize=depth)
    stop = threading.Event()

    def worker():
        try:
            while not stop.is_set():
                batches = [next(host_iter) for _ in range(k)]
                item = {key: np.stack([b[key] for b in batches])
                        for key in batches[0]}
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.2)
                        break
                    except queue_mod.Full:
                        continue
        except StopIteration:
            q.put(_STOP)
        except BaseException as e:  # surface on the consumer thread
            q.put(_WorkerError(e))

    threading.Thread(target=worker, daemon=True,
                     name="drt-batch-stacker").start()
    try:
        while True:
            item = q.get()
            if item is _STOP:
                return
            if isinstance(item, _WorkerError):
                raise item.exc
            yield item
    finally:
        stop.set()
