"""Typed configuration system.

Replaces the reference's per-entry-point ``tf.app.flags`` blocks (reference
resnet_cifar_main.py:30-88, resnet_imagenet_main.py:31-83,
resnet_cifar_eval.py:27-55 — ~25 flags redefined in every file, see SURVEY.md
§2.16) with a single set of dataclasses defined once, plus dotted-path CLI
overrides (``--train.batch_size=256``) and named presets reproducing the
reference's published configurations.
"""
from __future__ import annotations

import argparse
import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence, Tuple


@dataclass
class ModelConfig:
    """Model selection. Mirrors reference HParams (resnet_model.py:36-39) plus
    the size/width axes the reference hard-coded (resnet_model.py:71-74 pins
    resnet_size=50 for both datasets)."""

    name: str = "resnet"              # resnet | logistic | vit
    resnet_size: int = 50             # cifar: 6n+2 ∈ {20,32,44,50,56,110,...}; imagenet: 18/34/50/101/152/200
    width_multiplier: int = 1         # Wide-ResNet (e.g. 28-10 → resnet_size=28, width=10)
    num_classes: int = 10
    # bfloat16 compute with fp32 params is the TPU-native choice; the reference
    # was fp32-only (TF1.3 era).
    compute_dtype: str = "bfloat16"
    param_dtype: str = "float32"
    # Cross-replica batchnorm (lax.pmean of batch moments over the data axis)
    # fixes the per-replica-BN accuracy gap the reference suffered
    # (reference README.md:38,54). Both modes supported for comparison.
    cross_replica_bn: bool = True
    bn_momentum: float = 0.997        # reference resnet_model_official.py:37
    bn_epsilon: float = 1e-5          # reference resnet_model_official.py:38
    # >1: estimate BN batch moments from the contiguous center band of H/s
    # rows instead of every position — cuts the stat-pass HBM read to 1/s
    # (ops/batch_norm.py module docstring has the measured story). 1 = exact
    # moments (default everywhere; reference numerics).
    bn_stat_subsample: int = 1
    # normalization contract (ResNet family): "batch" = reference BN
    # semantics (default); "frozen" = BN from running stats even in
    # training (trainable scale/bias, no stat passes — the fine-tune
    # contract); "group" = GroupNorm (batch-independent, stateless — the
    # BN-free training contract; docs/perf_norm_r5.md has the measured MFU
    # of all three). models/resnet.py BatchNormRelu dispatches on this.
    norm: str = "batch"
    gn_groups: int = 32               # GroupNorm group count (norm="group")
    # evaluate the ImageNet 7x7/2 stem via space-to-depth (input [N,224,224,3]
    # -> [N,115,115,12], kernel 7x7x3 -> 4x4x12): mathematically the same
    # conv, but the contraction no longer has the MXU-hostile 3-channel
    # input. Measured +2.7% img/s on RN50 bs128 (docs/perf_imagenet_r4.md);
    # parity pinned by tests/test_models.py::test_stem_space_to_depth_parity.
    stem_space_to_depth: bool = True
    # toy MLP (reference logist_model.py:10-11)
    hidden_units: int = 100
    input_size: int = 32 * 32 * 3
    # ViT family (attention-based; beyond-reference capability)
    vit_patch_size: int = 4
    vit_dim: int = 128
    vit_depth: int = 6
    vit_heads: int = 4
    # GPipe microbatches when mesh.pipeline > 1 (0 → 2 × stages)
    vit_pipeline_microbatches: int = 0
    # >1 → circular (Megatron-interleaved) schedule: v chunks per stage,
    # bubble (P-1)/(v*M+P-1); requires depth % (P*v) == 0 and M >= P
    vit_pipeline_interleave: int = 1
    # Switch MoE: >0 replaces the block MLPs with num_experts experts
    # (models/moe.py), shardable over mesh.expert
    vit_num_experts: int = 0
    vit_expert_capacity_factor: float = 1.25
    vit_moe_top_k: int = 1            # 1 = Switch; 2 = GShard-style top-2
    # auto = gather (O(N+EC)) off the expert mesh axis; hand-scheduled
    # shard_map + lax.all_to_all exchange on it (einsum fallback when the
    # token count doesn't divide over the batch x expert shards)
    vit_moe_dispatch: str = "auto"    # auto | einsum | gather | a2a
    moe_aux_weight: float = 0.01      # Switch load-balancing loss weight
    # auto = ring if mesh.sequence>1; flash on TPU at >=2048 tokens; else dense
    attention_impl: str = "auto"      # auto | dense | blockwise | flash | ring


@dataclass
class DataConfig:
    """Input pipeline. Covers reference cifar_input.py + the tf.data paths
    (SURVEY.md §2.4-2.7)."""

    dataset: str = "cifar10"          # cifar10 | cifar100 | imagenet | synthetic
    data_dir: str = ""
    image_size: int = 32              # 32 cifar, 224 imagenet (reference resnet_imagenet_main.py image_size flag)
    shuffle_buffer: int = 50000       # full-epoch CIFAR shuffle (reference resnet_cifar_main.py:221)
    prefetch_batches: int = 2         # reference prefetches 2*bs samples (resnet_cifar_main.py:232)
    # imagenet decode THREAD pool width; -1 = auto (min(8, host cores,
    # floor 4) — data.resolve_decode_workers, the single resolution point)
    num_parallel_calls: int = -1
    use_native_loader: bool = False   # C++ threaded loader (native/)
    # >0: decode in worker PROCESSES instead of threads (imagenet) — full
    # GIL independence at the price of queue pickling; the measured
    # thread-vs-process scaling story is docs/input_scaling_r4.json.
    # -1 = auto: min(8, host cores) processes on hosts with >2 cores, else
    # 0 (threads — a process pool below that only adds pickling); 0 =
    # explicit threads-only. Explicit settings always win over auto.
    decode_processes: int = -1
    # -- data echoing + decoded-sample cache (data/echo.py) --------------
    # >1: each decoded sample feeds this many training batches overall —
    # samples enter a bounded host cache of decoded uint8 crops and every
    # emitted batch is a fresh seeded reshuffle of the cache, so one JPEG
    # decode feeds echo_factor steps (arXiv:1811.05233's input-bound
    # regime). Train-mode streams only; 1 = off
    echo_factor: int = 1
    # byte bound on the decoded-sample cache; overflowing samples are
    # evicted oldest-first (counted — {"event": "input_echo"} rows) even
    # if they still had echo uses left: the memory bound wins
    echo_cache_mb: float = 256.0
    # >1: re-dispatch each staged device-resident batch group this many
    # times before drawing the next — ONE host→device transfer feeds
    # echo_transfer × steps_per_loop optimizer steps. Each reuse
    # reshuffles the group's batch composition on device (seeded
    # permutation inside the jitted multi-step) and re-draws the device
    # augmentation (step-keyed RNG), so echoed steps stay diverse. The
    # lever past the H2D link ceiling (BENCH_r05: 49 MB/s moves only
    # ~326 uint8 img/s); composes with echo_factor (total echo =
    # echo_factor × echo_transfer decodes saved per step). 1 = off
    echo_transfer: int = 1
    # imagenet on-device augmentation: random-crop jitter padding in
    # pixels (ops/augment.imagenet_train_augment). 0 = flip + VGG
    # standardize only (reference-faithful distribution: the host decode
    # keeps its random resize/crop, the device takes over the flip and
    # the float pass); >0 adds a CIFAR-style pad/crop jitter so echoed
    # appearances of one decoded crop also differ spatially
    augment_pad: int = 0
    # train-time device-side input work (ops/augment.py), auto = on iff TPU.
    # cifar*: crop/flip/standardize inside the jitted step; imagenet: the
    # VGG standardize only (iterator then ships raw uint8 crops) — see
    # data/__init__.py device_augment_enabled, the single source of truth.
    device_augment: str = "auto"      # auto | on | off
    # whole dataset resident in HBM, batches gathered on device, host ships
    # only indices (data/device_dataset.py) — auto = on iff TPU,
    # single-process, CIFAR-scale. Implies device_augment.
    device_dataset: str = "auto"      # auto | on | off
    # -- overlapped staging (docs/input_pipeline.md) --------------------
    # coalesce each batch into one contiguous staging buffer and issue a
    # single device_put per batch (parallel/sharding.CoalescedStager);
    # "off" falls back to per-leaf device_put. auto = on iff running on a
    # real accelerator (per-call transfer overhead is what it amortizes)
    coalesced_transfer: str = "auto"  # auto | on | off
    # device-resident batches the dedicated transfer thread keeps queued
    # ahead of dispatch (data/device_prefetch.device_prefetch). Raised
    # 2 → 3 with the double-buffered transfer issue (round 9): the staging
    # thread now packs batch N+1 while N's transfer is still in flight
    transfer_depth: int = 3
    # reused host staging buffers; must cover the transfers in flight
    # (transfer_depth + the two behind the double-buffered issue point)
    staging_ring: int = 6
    # tolerate this many corrupt/truncated TFRecord records per process
    # before raising (each skip is a counted warning + a
    # {"event": "corrupt_record"} metrics row — data/tfrecord.py); 0 =
    # strict, any corruption raises immediately. Tolerant BY DESIGN: a
    # multi-day run must not die on one rotten byte, and mass corruption
    # (a storage incident) still raises once the budget is spent — set 0
    # to restore the old fail-fast behavior. Truncation is always
    # detected; CRC-detectable corruption (flipped payload bytes) only
    # with verify_crc=True below
    max_corrupt_records: int = 10
    # verify TFRecord CRCs on the python reader path. Costs a pure-python
    # CRC32C pass over every record — reserve for suspect storage; off,
    # only truncated records/headers are detected (and skipped/counted
    # under max_corrupt_records)
    verify_crc: bool = False
    # eval pipeline
    eval_batch_size: int = 100        # reference resnet_cifar_eval.py batch of 100


@dataclass
class OptimizerConfig:
    """Optimizer + LR schedule. Reference: SGD / momentum-0.9
    (resnet_model.py:96-99), step-piecewise LR (resnet_cifar_main.py:298-307),
    warmup+piecewise for ImageNet (resnet_imagenet_main.py:236-247).
    Adds LARS for large-batch (bs=32k) scaling."""

    name: str = "momentum"            # sgd | momentum | adam | adamw | lars | lamb
    momentum: float = 0.9
    learning_rate: float = 0.1
    weight_decay: float = 2e-4        # cifar train value (reference resnet_cifar_main.py:99); imagenet: 1e-4
    # True = reference-faithful L2 over ALL trainables incl. BN scale/bias
    # (reference resnet_model.py:85-86); False (default) = kernels only
    decay_all_params: bool = False
    # -- ZeRO-1 sharded weight update (parallel/sharding.py rule table +
    # train/loop.py; arXiv:2004.13336) ---------------------------------
    # shard the optimizer state and the weight update across the `data`
    # mesh axis: gradients reduce-scatter into each replica's optimizer
    # shard, the update runs on 1/N of the state per replica, and the
    # parameter updates all-gather back (bucketed when comm.overlap is
    # active). auto = on iff the run has >1 process (where per-replica
    # optimizer memory is the binding constraint); on = force (raises the
    # unsupported reason outside the envelope); off = the replicated
    # update — the bit-identical exactness oracle the ZeRO-1 path is
    # tested against
    zero1: str = "off"                # auto | on | off
    # leaves smaller than this many ELEMENTS stay replicated under ZeRO-1
    # (a sharded BN-scale moment buys nothing and costs a collective);
    # counted in the zero1 partition report
    zero1_min_size: int = 2048
    # schedule: piecewise | warmup_piecewise | cosine | warmup_poly | constant
    schedule: str = "piecewise"
    boundaries: Tuple[int, ...] = (40000, 60000, 80000)      # reference resnet_cifar_main.py:298-307
    values: Tuple[float, ...] = (0.1, 0.01, 0.001, 0.0001)
    warmup_steps: int = 0             # imagenet recipe: 6240 (reference resnet_imagenet_main.py:236-247)
    warmup_start: float = 0.1
    total_steps: int = 100000
    label_smoothing: float = 0.0
    grad_clip_norm: float = 0.0       # 0 = off
    # LARS
    lars_trust_coefficient: float = 0.001
    lars_eps: float = 0.0


@dataclass
class MeshConfig:
    """Device mesh. Replaces the reference's two comm backends (grpc PS +
    Horovod ring, SURVEY.md §2.8-2.9) with named mesh axes. Values of 0/1
    collapse the axis. -1 on exactly one axis means "all remaining devices"."""

    data: int = -1                    # data parallel (the reference's only axis)
    fsdp: int = 1                     # ZeRO-like param/optimizer sharding
    tensor: int = 1                   # tensor parallelism
    pipeline: int = 1                 # pipeline parallelism
    sequence: int = 1                 # sequence/context parallelism (ring attention)
    expert: int = 1                   # expert parallelism
    # multi-host
    coordinator_address: str = ""     # empty = single process
    num_processes: int = 1
    process_id: int = 0


@dataclass
class TrainConfig:
    batch_size: int = 128             # GLOBAL batch (reference global bs semantics, README.md:41-42)
    train_steps: int = 100000
    eval_every_steps: int = 0         # 0 = no in-loop eval
    log_every_steps: int = 20         # reference LoggingTensorHook cadence (resnet_cifar_main.py:280-285)
    summary_every_steps: int = 100    # reference SummarySaverHook (resnet_cifar_main.py:274-278)
    seed: int = 0
    # gradient accumulation (for large global batches on few chips)
    grad_accum_steps: int = 1
    remat: bool = False               # jax.checkpoint the block stack
    # fuse K optimizer steps into one XLA dispatch (lax.scan over K batches).
    # Amortizes host dispatch — the TPU analog of TPUEstimator's
    # iterations_per_loop. Hooks/logging fire at loop boundaries.
    steps_per_loop: int = 1
    # unroll factor for the steps_per_loop lax.scan. The while-loop form
    # double-buffers the ~430-leaf TrainState carry on TPU (~1.1k tiny
    # async copies/step, measured 2.5 ms/step on ImageNet RN50 bs128 —
    # docs/perf_imagenet_r4.md); full unroll (scan_unroll >= steps_per_loop)
    # removes the loop so the state updates in place. Cost: program size and
    # compile time scale with the factor.
    scan_unroll: int = 1
    # Pallas fused softmax-xent kernel in the train loss (replaces the
    # reference's fused TF op, resnet_model.py:78-80):
    # auto = on iff TPU | on | interpret (CPU tests) | off
    fused_xent: str = "auto"
    # -- mixed-precision training policy (parallel/precision.py;
    # docs/precision.md) ------------------------------------------------
    # "bf16": activations/matmuls compute in bfloat16 with float32 MASTER
    # weights and f32 BN-moment/softmax/loss accumulations — the model is
    # built with a bf16 compute dtype (overriding model.compute_dtype;
    # the policy cast wraps model apply), gradients and the whole
    # optimizer update stay f32, and checkpoints always persist the f32
    # masters so save/restore and serve hot-swap are policy-agnostic.
    # "off" (default): the legacy model.compute_dtype contract, BIT-
    # identical to the pre-policy step — the exactness oracle the cast
    # path is pinned against. fp16 is refused here (needs loss scaling;
    # see comm.compress for the fp16 exchange payload).
    precision: str = "off"            # off | bf16
    # print MFU in the logging hook (XLA cost-analysis FLOPs / peak)
    log_mfu: bool = False


@dataclass
class CheckpointConfig:
    """Reference: chief-only time-based ckpt every 60s via
    MonitoredTrainingSession (resnet_cifar_main.py:327-329), auto-resume."""

    directory: str = ""
    save_every_steps: int = 1000
    save_every_secs: float = 60.0     # time-based like the reference; 0 = off
    max_to_keep: int = 5
    async_save: bool = True
    resume: bool = True               # auto-resume from latest
    # -- per-host SHARDED checkpoints (checkpoint/shards.py) -------------
    # each host stages + fsyncs only the state shards its own devices
    # address (the ZeRO-1 optimizer shard, fsdp param shards) plus a
    # chief-written base of the replicated leaves, all under the existing
    # manifest/commit protocol; the multi-process finalize coordinates
    # over marker FILES on the shared directory — no collectives on the
    # writer thread, so multi-process saves can finally run async.
    # Restore re-assembles leaves from whatever host count wrote them and
    # re-shards into the live state's rule-table layout. auto = on iff
    # the run has >1 process; off = the single-payload orbax layout
    sharded: str = "auto"             # auto | on | off
    # how long a sharded save's finalize may wait on peer-host shard
    # markers (and peers on the chief's commit) before failing the save
    finalize_timeout_secs: float = 300.0


@dataclass
class CommConfig:
    """Gradient-communication overlap (parallel/overlap.py; arXiv:1711.00705
    bucketed allreduce interleaved with backprop). When enabled, the dp /
    dp_fsdp gradient exchange is rebuilt as size-bucketed per-bucket psums
    inside a ``shard_map``-wrapped step so XLA's latency-hiding scheduler
    can overlap each bucket's collective with the remaining backward pass —
    numerically identical leaf-by-leaf to the unbucketed exchange (same
    per-leaf all-reduce over the same operands)."""

    # auto = on iff the run has >1 process (the DCN multi-host dp path the
    # bucketing exists for) AND the (model, mesh, train) combination
    # supports it; on = force (raises with the reason when unsupported —
    # tests and single-host bring-up); off = the default XLA-propagation
    # exchange
    overlap: str = "auto"             # auto | on | off
    # target bucket size: gradient leaves are greedily grouped (in reverse
    # parameter order, approximating backprop availability — output layers
    # first) into buckets of at most this many MB; each bucket is one psum
    # issue. Smaller buckets start communicating earlier but amortize less
    # per-collective overhead (the DDP knob, arXiv:1711.00705 §4)
    bucket_mb: float = 4.0
    # compressed gradient exchange (docs/precision.md): cast each bucket's
    # psum / reduce-scatter payload (and the ZeRO-1 param-update
    # all-gather) to this dtype on the wire, re-materializing f32 on
    # arrival — halves (bf16/fp16) the inter-host bytes the overlap
    # machinery must hide, on the SAME bucket plan (arXiv:1811.05233:
    # ImageNet/RN50 to reference accuracy with half-precision allreduce).
    # Rides the bucketed exchange: with comm.overlap resolved off nothing
    # compresses (the Trainer warns loudly). Local gradient accumulation
    # and the optimizer update stay f32 either way.
    compress: str = "off"             # off | bf16 | fp16
    # hierarchical (two-tier) data-axis exchange (arXiv:1811.05233 2D-torus
    # allreduce; arXiv:1711.04325 intra-node-reduce-then-inter-node): when
    # the ``data`` mesh axis factors into intra-host × inter-host groups
    # (host-aware device order, parallel/mesh.py), each bucket is
    # reduce-scattered over the fast intra-host tier first, psummed as a
    # 1/k shard over the slow inter-host tier, then all-gathered back
    # intra-host — inter-host wire bytes drop to 1/intra_k per bucket.
    # auto = on iff the bucketed exchange is on AND a non-trivial
    # factorization exists; on = force (raises with the reason when no
    # factorization exists); off = flat single-tier collectives
    hierarchy: str = "off"            # off | auto | on
    # explicit intra-tier group size override: 0 = derive from the mesh's
    # host layout (jax.process_count / device process indices); a value
    # k with 1 < k < data_axis_size and k | data_axis_size forces the
    # factorization — the virtual-8 CPU test path ("2 hosts × 4 devices")
    intra_axis_size: int = 0
    # self-tuning comm plan (telemetry/planner.py tune_comm_plan): at the
    # first step boundary a probe (probe_comm_plan, extended to time flat
    # vs hierarchical legs per reduce-axis set) feeds the planner's cost
    # model, which picks bucket_mb, compress (never introducing a lossy
    # wire dtype the operator didn't opt into) and flat-vs-hierarchical
    # per axis set; the chosen plan is recorded in the comm_overlap row
    # and analysis/plan_catalog.json, and the step is rebuilt once.
    # Requires telemetry.comm_timing (the probe) — startup warns and
    # degrades to off without it.
    autotune: str = "off"             # off | startup


@dataclass
class WatchdogConfig:
    """Distributed health watchdog (resilience/watchdog.py +
    resilience/heartbeat.py): per-process heartbeat daemon + detection of
    dead peers, hung steps, and stragglers, with coordinated teardown
    (graceful stop when peers respond, hard exit 75 when the step loop is
    wedged in a collective). docs/resilience.md has the full story."""

    # auto = on iff the run has >1 process (single-process runs have no
    # peers to watch and no collective to hang in)
    enabled: str = "auto"             # auto | on | off
    # heartbeat publish cadence AND watchdog poll cadence
    interval_secs: float = 1.0
    # a peer whose latest beat is older than this is declared lost
    peer_timeout_secs: float = 20.0
    # hang deadline = max(min_step_timeout_secs,
    #                     step_timeout_scale * rolling per-step-time EWMA)
    step_timeout_scale: float = 10.0
    min_step_timeout_secs: float = 120.0
    # window between requesting a graceful coordinated stop and hard
    # os._exit(75) when the main thread never reaches a stop poll
    grace_secs: float = 10.0
    # straggler accounting window (also the heartbeat/straggler
    # metrics.jsonl export cadence)
    straggler_window_secs: float = 30.0
    # flag a host whose step rate is slower than the median by this factor
    straggler_ratio: float = 1.5
    # beat exchange directory; empty = <log_root>/heartbeats (must be on a
    # filesystem all processes share, like the checkpoint dir). A
    # standalone mode=eval job always gets an "eval"-scoped subdir (of
    # this or of log_root) — its own jax world must not impersonate
    # trainer process 0
    heartbeat_dir: str = ""


@dataclass
class ElasticConfig:
    """Elastic mesh (resilience/elastic.py; docs/resilience.md): on a
    peer-loss verdict the survivors reshard into a smaller mesh
    GENERATION and keep training from the last committed checkpoint
    instead of exiting 75 for a full SLURM requeue; a respawned/replaced
    peer grows the next generation back. 75 remains the FALLBACK when a
    reshard is impossible (chief lost, fewer than min_hosts survivors,
    barrier timeout, max_generations exhausted)."""

    # off by default: the exit-75 requeue contract stays the baseline
    # behavior; "on" requires >1 process and the file watchdog transport
    enabled: str = "off"              # on | off
    # what happens to the global batch when the host count changes:
    #   per_host    — keep each host's per-host batch; the global batch
    #                 scales with the generation's host count (LR is NOT
    #                 rescaled — deliberate, documented)
    #   keep_global — keep the ORIGINAL global batch when it divides the
    #                 new batch-shard count, else fall back to per_host
    #                 with a loud warning
    batch_policy: str = "per_host"    # per_host | keep_global
    # below this many survivors, give up and exit 75 (requeue)
    min_hosts: int = 2
    # membership must be stable this long before the chief commits a
    # generation (absorbs several near-simultaneous failures into ONE
    # reshard instead of a cascade)
    settle_secs: float = 2.0
    # give up on the join barrier (→ exit 75) after this long
    barrier_timeout_secs: float = 60.0
    # bound on one whole transition (barrier + teardown + re-init +
    # restore + rebuild) — ALSO how long the watchdog defers its
    # peer-lost hard-exit while this process can still reshard
    # (resilience/watchdog.py escalation fork)
    reshard_timeout_secs: float = 180.0
    # how long a respawned/replacement peer waits for the live fleet to
    # notice its join and commit the grown generation before giving up
    # with exit 75 (the fleet only polls between steps and may be mid-
    # save — patient by default)
    rejoin_timeout_secs: float = 600.0
    # how long the abandoned distributed-client shutdown thread gets
    # before the survivor proceeds without it
    teardown_timeout_secs: float = 5.0
    # join-file poll cadence inside the barrier; also the throttle for the
    # chief's between-steps pending-join (grow) check
    poll_secs: float = 0.5
    # generation g re-initializes at coordinator port base + g * stride
    # (parallel/distributed.py elastic_coordinator)
    port_stride: int = 7
    # hard cap on transitions in one process lifetime (0 = unlimited);
    # a flapping host cannot thrash the job forever — past the cap the
    # next verdict falls back to exit 75
    max_generations: int = 8
    # barrier/membership state directory; empty = <log_root>/elastic
    # (must be on the shared filesystem, like heartbeats)
    state_dir: str = ""


@dataclass
class ResilienceConfig:
    """Fault-tolerance knobs (resilience/ subsystem; docs/resilience.md).
    The reference had none of this — failure handling was "SLURM restarts
    the job" (SURVEY.md §4.4)."""

    # SIGTERM/SIGINT → finish the step, commit a checkpoint, exit with the
    # resumable code (75) so launchers requeue instead of failing
    handle_signals: bool = True
    # > 0: stop resumable after this many seconds even without a signal —
    # maintenance-window / max-walltime preemption (set it slightly under
    # the SLURM time limit so the final checkpoint beats the SIGKILL)
    deadline_secs: float = 0.0
    # NaN/Inf sentinel: on non-finite loss/grad-norm, roll back to the last
    # good checkpoint, re-seed the data stream, retry with the LR scaled by
    # backoff**strikes; give up loudly after max_strikes rollbacks.
    # 0 strikes = detection only (the guard raises, run dies — old behavior)
    nan_max_strikes: int = 3
    nan_lr_backoff: float = 0.5
    # guard cadence; 0 = follow train.log_every_steps. Keep at or below the
    # checkpoint cadence, else a save can land between blow-up and detection
    nan_check_every_steps: int = 0
    # verify checkpoint manifests (size + sha256 per file) before restoring;
    # damaged checkpoints are skipped in favor of the newest valid one
    verify_on_restore: bool = True
    # bounded-retry policy for checkpoint I/O (resilience/retry.py)
    io_retries: int = 3
    # distributed health watchdog knobs (resilience.watchdog.*)
    watchdog: WatchdogConfig = field(default_factory=WatchdogConfig)
    # elastic mesh shrink/grow knobs (resilience.elastic.*)
    elastic: ElasticConfig = field(default_factory=ElasticConfig)


@dataclass
class AnalysisConfig:
    """Static-analysis / debug instrumentation (analysis/ subsystem;
    docs/static_analysis.md). The ``check`` gate itself is config-free —
    these knobs control the RUNTIME aids."""

    # opt-in: raise at the call site the moment a second thread launches a
    # multi-device XLA execution (the cross-thread dispatch deadlock class,
    # docs/input_pipeline.md threading model) instead of wedging the next
    # collective. Costs a lock per dispatch — debug runs, not production.
    dispatch_sanitizer: bool = False


@dataclass
class TelemetryConfig:
    """Flight recorder + goodput accounting (telemetry/;
    docs/observability.md). The reference's only observability was stdout
    logs and TensorBoard scalars (SURVEY.md §2.15); these knobs control the
    span tracer, its anomaly-triggered dumps, and the goodput export."""

    # record spans into the bounded in-memory ring (telemetry/tracer.py).
    # Measured negligible (<2% on the CIFAR headline — the bench acceptance
    # bar), so on by default; off = every span is a shared no-op.
    enabled: bool = True
    # ring capacity in span events — the flight recorder's memory bound
    # (~100 bytes/event; 65536 ≈ the last few minutes of a busy run)
    ring_events: int = 65536
    # where trace.json dumps land; empty = <log_root>/telemetry
    trace_dir: str = ""
    # goodput metrics-row cadence in steps; 0 = ride
    # train.summary_every_steps
    goodput_every_steps: int = 0
    # when a watchdog anomaly fires, also bracket an on-demand
    # jax.profiler window (utils/profiling.trace_window) of profile_secs
    # into <trace_dir>/profile — device-side visibility at the price of
    # profiler overhead during the incident; once per process
    profile_on_anomaly: bool = False
    profile_secs: float = 5.0
    # metrics.jsonl size-triggered rotation (utils/metrics.MetricsWriter):
    # rotate past this many MB, keep this many rotated segments. A
    # week-long serve/monitor run must not fill the disk. 0 MB = unbounded
    metrics_max_mb: float = 256.0
    metrics_max_segments: int = 4
    # -- per-collective runtime attribution (parallel/overlap.py probe) --
    # once per process, after the bucketed exchange has traced, time each
    # planned bucket's collective standalone on the live mesh (wire
    # dtype/bytes) — the measured side of the comm_timing row and
    # `main.py comm-report`. Cost: a handful of tiny collective programs
    # at the first loop boundary; every process participates (the probe
    # is SPMD), the chief records. Off = plan-only telemetry.
    comm_timing: bool = True
    # number of timed repetitions per bucket (best-of)
    comm_timing_reps: int = 3
    # -- device-memory telemetry (telemetry/memory.py) -------------------
    # sample per-device live-array bytes (+ allocator stats where the
    # backend reports them), host RSS, echo-cache and staging-ring
    # occupancy into {"event": "memory"} rows at the summary cadence
    # (train loop) and the serve report cadence. `main.py monitor` rolls
    # the per-host HBM watermark up. Off = no memory rows.
    memory: bool = True
    # -- perf-anomaly sentinel (resilience/watchdog.py) ------------------
    # online step-time outlier detection over a rolling median+MAD
    # window: a slow-but-alive step (no hang, no teardown) triggers a
    # {"event": "perf_anomaly"} row + the flight-recorder dump — today's
    # 2×-slow step should page like a hang does, not wait for the wall
    # clock. Rides the watchdog's detection thread, so it arms with the
    # watchdog (resilience.watchdog.enabled).
    anomaly_detection: bool = True
    # rolling window of per-step-time samples the median/MAD come from
    anomaly_window: int = 32
    # minimum samples before the detector arms (a cold window's MAD is
    # noise)
    anomaly_min_samples: int = 16
    # outlier threshold: median + max(anomaly_mad_k × MAD,
    # (anomaly_min_ratio − 1) × median). The MAD term adapts to the
    # run's jitter; the ratio floor keeps an ultra-steady run (MAD ~ 0)
    # from flagging micro-hiccups.
    anomaly_mad_k: float = 6.0
    anomaly_min_ratio: float = 1.5
    # minimum gap between fired anomalies (a persistently slow host must
    # not dump a trace per detection tick); the episode also re-arms only
    # after a healthy sample
    anomaly_cooldown_secs: float = 60.0
    # -- predicted-vs-measured drift sentinel (train/hooks.PlanDriftHook,
    # telemetry/planner.py, docs/planner.md) ----------------------------
    # arm the sentinel: at run start the chief predicts step time / comm
    # seconds / HBM from the live bucket plan × the fabric's bandwidth
    # catalog, emits one {"event": "plan"} row, then compares measured
    # values (heartbeat EWMA step time, comm_timing probe, memory rows)
    # each cadence. "auto" = on when the prediction can be built (overlap
    # active), "on" forces a warning when it cannot, "off" disarms.
    plan_drift: str = "auto"
    # divergence band: fire when measured/predicted leaves
    # [1/tol, tol] for plan_drift_window consecutive checks. The analytic
    # model is a roofline, not a simulator — 3x either way means the
    # model or the machine is wrong, not that the model is 20% off.
    plan_tolerance: float = 3.0
    plan_drift_window: int = 8
    # minimum gap between plan_drift firings (each one dumps the flight
    # recorder); an episode re-arms only after an in-tolerance check
    plan_drift_cooldown_secs: float = 300.0


@dataclass
class EvalConfig:
    """Standalone polling evaluator (reference resnet_cifar_eval.py:85-141)."""

    # reference eval_batch_count flag (=50, i.e. 50×100 CIFAR images).
    # For the full ImageNet validation set size it to cover all 50,000
    # images: ceil(50000 / data.eval_batch_size) (=500 at the default 100);
    # the iterator masks the final partial batch, and a larger count just
    # stops at stream exhaustion, so overshooting is safe single-process.
    # The measured full-pass wall time rides in bench.py's
    # imagenet_input.eval_pass key (native decode + uint8 ship + device
    # standardize, docs r4).
    eval_batch_count: int = 50
    eval_once: bool = False
    poll_interval_secs: float = 60.0  # reference sleeps 60s between polls
    eval_dir: str = ""
    # a polling evaluator skips damaged/vanished checkpoints; this bounds
    # how many it may skip IN A ROW before exiting nonzero — a persistently
    # broken checkpoint stream must page someone, not spin forever
    max_consecutive_failures: int = 5


@dataclass
class ServeConfig:
    """AOT-compiled batched inference server (serve/; docs/serving.md).
    Surfaced as ``main.py serve``; the reference had no serving story at
    all — checkpoints were the end of the line (ROADMAP open item 3)."""

    # request-batch cap; 0 = data.eval_batch_size. Buckets are powers of
    # two (in multiples of Trainer.eval_pad_multiple) up to this cap
    max_batch: int = 0
    # how long the batcher holds the FIRST queued request to coalesce more
    # into a bigger bucket — the p50-latency vs throughput knob (0 =
    # dispatch immediately, smallest bucket)
    max_queue_delay_ms: float = 5.0
    # hot-swap poll cadence (jittered ±50%): how often the background swap
    # thread looks for a newer committed checkpoint
    poll_interval_secs: float = 5.0
    # AOT-compile every bucket at startup so the first request never pays
    # a compile; off = compile lazily on first use (counted + warned)
    warm_buckets: bool = True
    # -- open-loop synthetic load generator (serve/loadgen.py) ------------
    # main.py serve drives it when load_qps > 0, then prints a JSON report
    # and exits; load_qps = 0 serves until SIGINT/SIGTERM
    load_qps: float = 0.0
    load_duration_secs: float = 10.0
    load_seed: int = 0
    # after the load completes, keep serving (idle) until a hot swap has
    # landed or this many extra seconds pass — scripts/serve_smoke.sh's
    # determinism knob; 0 = exit right after the load
    wait_for_swap_secs: float = 0.0
    # reduced-precision serving variants (docs/precision.md): compile-
    # cache buckets become (batch, variant) and every listed variant gets
    # its own weight copy + AOT programs — "bf16" serves from bf16-cast
    # weights through a bf16-compute predict step (about half the weight
    # HBM and MXU-rate matmuls per replica); "int8" is WEIGHT-ONLY
    # quantization (per-output-channel scales, ¼ the kernel HBM,
    # f32 compute over dequantized weights — the parity bound vs the f32
    # variant is pinned in tests/test_precision.py). The FIRST entry is
    # the default a variant-less request is served from; hot swaps
    # rebuild every variant from the new f32 masters. Checkpoints are
    # untouched (serving quantizes/casts at swap time, never at rest).
    variants: Tuple[str, ...] = ("f32",)
    # -- fleet-replica identity (serve/fleet.py spawns replicas with
    # these set; standalone `main.py serve` leaves them off) -------------
    # replica id within a routed fleet: >= 0 moves the metrics stream /
    # READY marker to <log_root>/serve-r<id> and publishes heartbeats
    # into <log_root>/heartbeats-serve under this process_id
    replica_id: int = -1
    # TCP request port (127.0.0.1): > 0 starts the replica listener
    # (serve/wire.py ReplicaListener) so a router can forward requests
    listen_port: int = 0
    # gate hot swaps on the router's per-replica control file
    # (<serve dir>/SWAP_CONTROL.json {"target_step": N}): the swapper
    # only moves to the pinned step — forward for a canary/promote,
    # BACKWARD for a rollback — instead of chasing the newest commit,
    # and HOLDS while no control file exists (an unpinned gated replica
    # must not leak an unvalidated checkpoint past the canary).
    swap_gate: bool = False


@dataclass
class RouteConfig:
    """Serving-fleet front door (serve/router.py + serve/fleet.py;
    ``main.py route``, docs/serving.md fleet section): health-routed
    replicas, watchdog-driven replace, canary rollout with auto-rollback,
    SLO-aware degradation."""

    # -- fleet shape -----------------------------------------------------
    replicas: int = 3
    # first replica's TCP port; replica i listens on base_port + i.
    # 0 = pick free ports at spawn time
    base_port: int = 0
    # forwarding worker threads (each blocks on one attempt at a time,
    # so this bounds the router's concurrent in-flight attempts)
    workers: int = 4
    # -- request path ----------------------------------------------------
    # client-visible deadline: past it the request fails loudly
    request_timeout_ms: float = 10000.0
    # per-attempt transport deadline (connect + send + response)
    attempt_timeout_ms: float = 4000.0
    # hedge: a duplicate attempt goes to ANOTHER replica after this long
    # without a response — requests in flight on a dying replica land on
    # a survivor instead of waiting out attempt_timeout_ms
    hedge_ms: float = 400.0
    # total attempts per request (first + hedges + retries)
    max_attempts: int = 3
    # -- health ----------------------------------------------------------
    health_interval_secs: float = 1.0
    # heartbeat age past which a replica is declared dead (its publisher
    # daemon beats ~1/s even when the dispatch thread is stuck)
    beat_stale_secs: float = 15.0
    # consecutive transport failures: suspect (deprioritized), then dead
    # (drained + replaced by the fleet supervisor)
    suspect_after_failures: int = 2
    dead_after_failures: int = 5
    # route summary-row cadence ({"event": "route"})
    row_interval_secs: float = 5.0
    # -- canary rollout --------------------------------------------------
    # fraction of the fleet a new checkpoint is published to first
    # (ceil(fraction × replicas), never the whole fleet when N > 1)
    canary_fraction: float = 0.34
    # measurement window after every canary replica confirms the step
    canary_window_secs: float = 15.0
    # minimum responses per arm before a promote/rollback verdict
    canary_min_samples: int = 20
    # rollback when canary p99 / control p99 exceeds this
    canary_p99_ratio: float = 2.0
    # rollback when the canary arm's mean top-1 softmax confidence (the
    # accuracy proxy) drops below the control arm's by more than this
    canary_conf_drop: float = 0.2
    # rollback when the canary replicas never confirm the step
    canary_confirm_secs: float = 60.0
    # -- SLO-aware degradation / load shedding ---------------------------
    # p99 above this marks a replica degraded (slo_pressure); 0 = off
    slo_p99_ms: float = 0.0
    # estimated queue delay above this reroutes default-variant traffic
    # to degrade_variant (0 = off); above shed_queue_ms requests are
    # refused with the shed verdict instead of queueing unbounded
    degrade_queue_ms: float = 0.0
    degrade_variant: str = ""
    shed_queue_ms: float = 2000.0
    # -- fleet supervisor (watchdog replace) -----------------------------
    watch_interval_secs: float = 1.0
    # drain + SIGTERM grace before SIGKILL on a replace
    replica_grace_secs: float = 10.0
    # respawn → READY deadline before the replace is abandoned
    warm_timeout_secs: float = 240.0
    # total replaces before the supervisor stops trying (crash-loop cap)
    max_replaces: int = 8
    # -- open-loop load generator (mirrors serve.load_*) -----------------
    load_qps: float = 0.0
    load_duration_secs: float = 10.0
    load_seed: int = 0
    # arrival-schedule shape: steady | diurnal | burst | spike
    # (serve/loadgen.py — all coordinated-omission-free)
    load_shape: str = "steady"


@dataclass
class ExperimentConfig:
    model: ModelConfig = field(default_factory=ModelConfig)
    data: DataConfig = field(default_factory=DataConfig)
    optimizer: OptimizerConfig = field(default_factory=OptimizerConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    comm: CommConfig = field(default_factory=CommConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    checkpoint: CheckpointConfig = field(default_factory=CheckpointConfig)
    resilience: ResilienceConfig = field(default_factory=ResilienceConfig)
    eval: EvalConfig = field(default_factory=EvalConfig)
    analysis: AnalysisConfig = field(default_factory=AnalysisConfig)
    serve: ServeConfig = field(default_factory=ServeConfig)
    route: RouteConfig = field(default_factory=RouteConfig)
    telemetry: TelemetryConfig = field(default_factory=TelemetryConfig)
    mode: str = "train"        # train | eval | train_and_eval | serve | route
    log_root: str = "/tmp/drt_tpu"    # reference log_root flag

    # ---- serialization ----
    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)

    @classmethod
    def from_dict(cls, d: dict) -> "ExperimentConfig":
        cfg = cls()
        _apply_dict(cfg, d)
        return cfg

    def override(self, dotted: str, value: Any) -> None:
        """Apply one dotted-path override, e.g. ("train.batch_size", 256)."""
        obj = self
        parts = dotted.split(".")
        for p in parts[:-1]:
            obj = getattr(obj, p)
        leaf = parts[-1]
        if not hasattr(obj, leaf):
            raise KeyError(f"unknown config key: {dotted}")
        cur = getattr(obj, leaf)
        setattr(obj, leaf, _coerce(value, cur))


def _coerce(value: Any, template: Any) -> Any:
    if isinstance(value, str):
        if isinstance(template, bool):
            return value.lower() in ("1", "true", "yes", "on")
        if isinstance(template, int) and not isinstance(template, bool):
            return int(value)
        if isinstance(template, float):
            return float(value)
        if isinstance(template, tuple):
            if not value.strip():
                return ()
            elems = [v.strip() for v in value.split(",") if v.strip()]
            # element type follows the template's first element; string
            # tuples (serve.variants) pass through unconverted
            if template and isinstance(template[0], float):
                et = float
            elif template and isinstance(template[0], str):
                et = str
            else:
                et = int
            return tuple(et(e) for e in elems)
    if isinstance(template, tuple) and isinstance(value, list):
        return tuple(value)
    return value


def _apply_dict(obj: Any, d: dict) -> None:
    for k, v in d.items():
        if not hasattr(obj, k):
            raise KeyError(f"unknown config key: {k}")
        cur = getattr(obj, k)
        if dataclasses.is_dataclass(cur) and isinstance(v, dict):
            _apply_dict(cur, v)
        else:
            setattr(obj, k, _coerce(v, cur))


# ---------------------------------------------------------------------------
# Presets: named configs reproducing the reference's published runs
# (BASELINE.md table; reference README.md:22-52).
# ---------------------------------------------------------------------------

def _cifar10_resnet50() -> ExperimentConfig:
    """Reference flagship: CIFAR-10 ResNet-50, gbs=128, piecewise LR
    (README.md:28-30 — 93.6% top-1 @ ~80k steps)."""
    cfg = ExperimentConfig()
    cfg.model = ModelConfig(resnet_size=50, num_classes=10)
    cfg.data = DataConfig(dataset="cifar10", image_size=32)
    cfg.optimizer = OptimizerConfig(
        name="momentum", learning_rate=0.1, weight_decay=2e-4,
        schedule="piecewise", boundaries=(40000, 60000, 80000),
        values=(0.1, 0.01, 0.001, 0.0001), total_steps=100000)
    cfg.train = TrainConfig(batch_size=128, train_steps=100000)
    return cfg


def _cifar10_resnet50_bs512() -> ExperimentConfig:
    """Throughput variant of the flagship: gbs=512 is the measured
    single-chip optimum (+19% img/s over the faithful gbs=128 recipe,
    docs/perf_cifar_r5.md). LR and boundaries follow the linear-scaling
    rule (×4 with 4× fewer steps) so the epoch budget matches the
    reference recipe; the gbs=128 preset remains the accuracy-replay
    default."""
    cfg = _cifar10_resnet50()
    cfg.train.batch_size = 512
    cfg.train.train_steps = 25000
    cfg.optimizer = OptimizerConfig(
        name="momentum", learning_rate=0.4, weight_decay=2e-4,
        schedule="warmup_piecewise", warmup_steps=1000, warmup_start=0.1,
        boundaries=(10000, 15000, 20000),
        values=(0.4, 0.04, 0.004, 0.0004), total_steps=25000)
    return cfg


def _cifar100_wrn2810() -> ExperimentConfig:
    """Wide-ResNet-28-10 on CIFAR-100 (BASELINE.json config 4; exercises the
    width/depth generalization of reference resnet_model_official.py:217-278)."""
    cfg = _cifar10_resnet50()
    cfg.model = ModelConfig(resnet_size=28, width_multiplier=10, num_classes=100)
    cfg.data = DataConfig(dataset="cifar100", image_size=32)
    cfg.optimizer.weight_decay = 5e-4
    return cfg


def _imagenet_resnet50() -> ExperimentConfig:
    """ImageNet ResNet-50 gbs=1024, Intel-Caffe 8-node recipe the reference
    used (resnet_imagenet_main.py:236-247; README.md:42)."""
    cfg = ExperimentConfig()
    cfg.model = ModelConfig(resnet_size=50, num_classes=1001)
    cfg.data = DataConfig(dataset="imagenet", image_size=224)
    cfg.optimizer = OptimizerConfig(
        name="momentum", learning_rate=0.4, weight_decay=1e-4,
        schedule="warmup_piecewise", warmup_steps=6240, warmup_start=0.1,
        boundaries=(37440, 74880, 99840),
        values=(0.4, 0.04, 0.004, 0.0004), total_steps=112640)
    cfg.train = TrainConfig(batch_size=1024, train_steps=112640,
                            log_every_steps=40)
    cfg.checkpoint.save_every_secs = 600.0  # imagenet default cadence (SURVEY §2.14)
    return cfg


def _imagenet_resnet50_lars32k() -> ExperimentConfig:
    """Large-batch: bs=32k + LARS (BASELINE.json config 5). ZeRO-1 resolves
    on under multi-process (auto): at this scale the per-replica optimizer
    state, not FLOPs, caps what fits (arXiv:2004.13336)."""
    cfg = _imagenet_resnet50()
    cfg.optimizer = OptimizerConfig(
        name="lars", learning_rate=29.0, weight_decay=1e-4,
        schedule="cosine", zero1="auto",
        warmup_steps=800, total_steps=3600, label_smoothing=0.1)
    cfg.train = TrainConfig(batch_size=32768, train_steps=3600,
                            log_every_steps=10,
                            # the arXiv:1811.05233 recipe shape: bf16
                            # step + half-precision gradient exchange
                            # (docs/precision.md)
                            precision="bf16")
    cfg.comm.compress = "bf16"
    return cfg


#: ImageNet train-set size — the epoch↔step conversion the large-batch
#: warmup recipes are specified in (arXiv:1711.04325 / 1811.05233 give
#: warmup in EPOCHS; steps depend on the global batch)
IMAGENET_TRAIN_IMAGES = 1_281_167


def large_batch_steps(batch_size: int, epochs: float) -> int:
    """Steps covering ``epochs`` ImageNet epochs at ``batch_size`` — the
    one conversion both large-batch presets and ad-hoc ``--set`` overrides
    use, so a changed batch size keeps the epoch budget."""
    return max(1, round(epochs * IMAGENET_TRAIN_IMAGES / batch_size))


def _imagenet_resnet50_lars4k() -> ExperimentConfig:
    """Large-batch bs=4096 + LARS, the arXiv:1711.04325 / 1811.05233
    recipe shape: 5-epoch linear warmup (the cure for the bs>512 accuracy
    cliff the reference README documents at 32k), polynomial(2) decay to
    zero over 90 epochs, label smoothing 0.1. ZeRO-1 on: the optimizer
    state shards across the data axis (arXiv:2004.13336), so per-replica
    memory stops scaling with the replica count's optimizer copies."""
    cfg = _imagenet_resnet50()
    bs = 4096
    cfg.optimizer = OptimizerConfig(
        name="lars", learning_rate=13.0, weight_decay=1e-4,
        schedule="warmup_poly", zero1="on",
        warmup_steps=large_batch_steps(bs, 5),
        total_steps=large_batch_steps(bs, 90), label_smoothing=0.1)
    cfg.train = TrainConfig(batch_size=bs,
                            train_steps=large_batch_steps(bs, 90),
                            log_every_steps=20,
                            precision="bf16")  # arXiv:1811.05233 recipe
    cfg.comm.compress = "bf16"
    return cfg


def _imagenet_resnet50_lamb4k() -> ExperimentConfig:
    """Large-batch bs=4096 + LAMB (trust-ratio-scaled Adam): the same
    5-epoch linear warmup + 90-epoch budget as the LARS recipe, cosine
    decay (LAMB's usual pairing). ZeRO-1 on — LAMB doubles the moment
    state (m AND v per param), which is exactly the memory the sharded
    update exists to split."""
    cfg = _imagenet_resnet50()
    bs = 4096
    cfg.optimizer = OptimizerConfig(
        name="lamb", learning_rate=10.0, weight_decay=1e-4,
        schedule="cosine", zero1="on",
        warmup_steps=large_batch_steps(bs, 5),
        total_steps=large_batch_steps(bs, 90), label_smoothing=0.1)
    cfg.train = TrainConfig(batch_size=bs,
                            train_steps=large_batch_steps(bs, 90),
                            log_every_steps=20,
                            precision="bf16")  # arXiv:1811.05233 recipe
    cfg.comm.compress = "bf16"
    return cfg


def _vit_long_context() -> ExperimentConfig:
    """Long-context ViT: 256² images at patch 4 → 4096 tokens/image — the
    regime the Pallas flash kernel exists for (attention_impl='auto'
    resolves to 'flash' on TPU past the measured ~2k-token crossover,
    models/transformer.py). Beyond-reference capability; the shipped config
    that exercises the kernel by default."""
    cfg = ExperimentConfig()
    cfg.model = ModelConfig(
        name="vit", num_classes=10, vit_patch_size=4, vit_dim=512,
        vit_depth=8, vit_heads=8)
    cfg.data = DataConfig(dataset="synthetic", image_size=256)
    cfg.optimizer = OptimizerConfig(
        name="adam", learning_rate=1e-3, weight_decay=0.0,
        schedule="cosine", warmup_steps=500, total_steps=20000)
    cfg.train = TrainConfig(batch_size=8, train_steps=20000, remat=True)
    return cfg


def _vit_large_224() -> ExperimentConfig:
    """Classic ViT-L/16 at 224² (196 tokens, dense attention): the
    transformer-family ≥0.55-MFU contract — measured 0.57 MFU at the
    preset's bs=32 per chip, every FLOP XLA-counted
    (docs/perf_vit_classic_r5.md). Per-chip batch is pinned at the
    measured optimum; scale global batch over the `data` mesh axis
    (bs 128 per chip measured ~0.45 — XLA picks a worse program there)."""
    cfg = ExperimentConfig()
    cfg.model = ModelConfig(
        name="vit", num_classes=1000, vit_patch_size=16, vit_dim=1024,
        vit_depth=24, vit_heads=16, attention_impl="dense")
    cfg.data = DataConfig(dataset="synthetic", image_size=224)
    cfg.optimizer = OptimizerConfig(
        name="adamw", learning_rate=3e-4, weight_decay=0.05,
        schedule="cosine", warmup_steps=10000, total_steps=300000)
    cfg.train = TrainConfig(batch_size=32, train_steps=300000,
                            steps_per_loop=8, remat=False)
    return cfg


def _vit_moe() -> ExperimentConfig:
    """Switch-MoE ViT — the expert-parallel member of the preset zoo.
    Sized so every transformer layout elaborates on the virtual 8-device
    gate mesh (dp / dp_fsdp / dp_pp / dp_tp / dp_pp_ep: depth 8 % 2
    stages, heads 4 % tensor 2, experts 4 % expert 2, bs 64 % shards ×
    microbatches), giving the MoE/pipeline overlap + collective-schedule
    families a shipped config instead of test-only ad-hoc ones."""
    cfg = ExperimentConfig()
    cfg.model = ModelConfig(
        name="vit", num_classes=10, vit_patch_size=4, vit_dim=128,
        vit_depth=8, vit_heads=4, vit_num_experts=4,
        attention_impl="dense")
    cfg.data = DataConfig(dataset="synthetic", image_size=32)
    cfg.optimizer = OptimizerConfig(
        name="adamw", learning_rate=3e-4, weight_decay=0.02,
        schedule="cosine", warmup_steps=1000, total_steps=50000)
    cfg.train = TrainConfig(batch_size=64, train_steps=50000)
    return cfg


def _cifar10_smoke() -> ExperimentConfig:
    """Local smoke test analog of reference scripts/submit_mac_dist.sh
    (1ps+2wk, bs=10, 100 steps on CPU — SURVEY.md §4.1)."""
    cfg = _cifar10_resnet50()
    cfg.model.resnet_size = 20
    cfg.data.dataset = "synthetic"
    cfg.train = TrainConfig(batch_size=10, train_steps=100, log_every_steps=10)
    cfg.optimizer.total_steps = 100
    cfg.checkpoint.save_every_secs = 0.0
    return cfg


PRESETS = {
    "cifar10_resnet50": _cifar10_resnet50,
    "cifar10_resnet50_bs512": _cifar10_resnet50_bs512,
    "cifar100_wrn28_10": _cifar100_wrn2810,
    "imagenet_resnet50": _imagenet_resnet50,
    "imagenet_resnet50_lars32k": _imagenet_resnet50_lars32k,
    "imagenet_resnet50_lars4k": _imagenet_resnet50_lars4k,
    "imagenet_resnet50_lamb4k": _imagenet_resnet50_lamb4k,
    "vit_long_context": _vit_long_context,
    "vit_large_224": _vit_large_224,
    "vit_moe": _vit_moe,
    "smoke": _cifar10_smoke,
}


def resolve_checkpoint_dir(cfg: ExperimentConfig) -> str:
    """Single source of truth for the checkpoint directory — trainer and
    evaluator MUST agree (their only interface is this directory, as in the
    reference, SURVEY.md §3.3)."""
    import os
    return cfg.checkpoint.directory or os.path.join(cfg.log_root, "ckpt")


def stacked_layout_stamp(cfg: ExperimentConfig):
    """Storage-order declaration for depth-stacked encoder params, recorded
    next to checkpoints: the circular pipeline schedule
    (model.vit_pipeline_interleave > 1) stores stage-major layer order, so a
    restore under a different (mesh.pipeline, interleave) must be refused
    (models/pipeline.py circular_layer_order / repack_stacked_params).
    None = no stacked params in this model family."""
    if cfg.model.name != "vit":
        return None
    v = cfg.model.vit_pipeline_interleave
    p = cfg.mesh.pipeline
    if v <= 1 or p <= 1:
        return {"encoder_order": "network"}
    return {"encoder_order": "circular", "pstages": p, "interleave": v,
            "depth": cfg.model.vit_depth}


def get_preset(name: str) -> ExperimentConfig:
    if name not in PRESETS:
        raise KeyError(f"unknown preset {name!r}; have {sorted(PRESETS)}")
    return PRESETS[name]()


def parse_args(argv: Optional[Sequence[str]] = None) -> ExperimentConfig:
    """CLI: ``--preset cifar10_resnet50 --set train.batch_size=256 ...``"""
    p = argparse.ArgumentParser(description="distributed_resnet_tensorflow_tpu trainer")
    p.add_argument("--preset", default="cifar10_resnet50", choices=sorted(PRESETS))
    p.add_argument("--set", action="append", default=[], metavar="KEY=VALUE",
                   help="dotted config override, e.g. --set train.batch_size=256")
    p.add_argument("--config_json", default="", help="path to a JSON config to load")
    ns = p.parse_args(argv)
    if ns.config_json:
        with open(ns.config_json) as f:
            cfg = ExperimentConfig.from_dict(json.load(f))
    else:
        cfg = get_preset(ns.preset)
    for ov in ns.set:
        if "=" not in ov:
            raise ValueError(f"--set expects KEY=VALUE, got {ov!r}")
        k, v = ov.split("=", 1)
        cfg.override(k, v)
    return cfg
