#!/usr/bin/env python
"""Join the per-round bench records (BENCH_r*.json at the repo root)
into ONE machine-readable perf trajectory.

Each round's freeform ``parsed`` blob is flattened to dotted numeric
keys (``overlap_ab.bucketed.steps_per_sec``, ...), and every key that
also existed in the PREVIOUS round gets a delta row ``{abs, pct}`` —
the cross-round regression signal the per-round files cannot show on
their own. Rounds whose ``parsed`` is empty (r05: the harness crashed
after the run, only the tail survived) are carried with
``parsed_empty: true`` so a gap in the trajectory reads as a gap, not
as a flat line.

    python tools/bench_trajectory.py [--root DIR] [--json]
    python -m distributed_resnet_tensorflow_tpu.main monitor --bench

Stdlib-only on purpose: ``main.py monitor --bench`` loads this file by
path (telemetry/monitor.py), so it must import without the package (or
jax) on the path.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional, Sequence


def flatten_numeric(node: Any, prefix: str = "") -> Dict[str, float]:
    """Dotted-key view of every numeric leaf (bools excluded: rc-style
    flags are identity, not magnitude). List elements key by index."""
    out: Dict[str, float] = {}
    if isinstance(node, bool):
        return out
    if isinstance(node, (int, float)):
        out[prefix or "value"] = float(node)
        return out
    if isinstance(node, dict):
        for k in sorted(node):
            key = f"{prefix}.{k}" if prefix else str(k)
            out.update(flatten_numeric(node[k], key))
    elif isinstance(node, (list, tuple)):
        for i, v in enumerate(node):
            key = f"{prefix}.{i}" if prefix else str(i)
            out.update(flatten_numeric(v, key))
    return out


def discover_rounds(root: str) -> List[str]:
    return sorted(glob.glob(os.path.join(root, "BENCH_r*.json")))


def build_trajectory(paths: Sequence[str]) -> dict:
    """The joined trajectory doc: one row per round, in filename order
    (BENCH_rNN sorts chronologically), each with its flattened metrics
    and the per-key delta against the PREVIOUS round that carried the
    same key — not necessarily the adjacent round, so an empty round
    (r05) does not sever every downstream delta."""
    rows: List[dict] = []
    last_seen: Dict[str, float] = {}  # key -> most recent value
    for path in paths:
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError) as e:
            rows.append({"round": os.path.basename(path),
                         "error": str(e)})
            continue
        metrics = flatten_numeric(rec.get("parsed") or {})
        deltas: Dict[str, dict] = {}
        for key, val in metrics.items():
            prev = last_seen.get(key)
            if prev is None:
                continue
            d: Dict[str, float] = {"abs": round(val - prev, 9)}
            if prev != 0:
                d["pct"] = round((val - prev) / abs(prev) * 100.0, 2)
            deltas[key] = d
        last_seen.update(metrics)
        rows.append({
            "round": os.path.basename(path).replace("BENCH_", "")
                                           .replace(".json", ""),
            "n": rec.get("n"),
            "rc": rec.get("rc"),
            "cmd": rec.get("cmd"),
            "parsed_empty": not metrics,
            "metrics": metrics,
            "deltas": deltas,
        })
    return {"schema_version": 1, "rounds": rows,
            "keys_tracked": len(last_seen)}


def render(traj: dict, top: int = 5) -> str:
    lines = ["== bench trajectory :: "
             f"{len(traj['rounds'])} round(s), "
             f"{traj['keys_tracked']} metric key(s) =="]
    for row in traj["rounds"]:
        if "error" in row:
            lines.append(f"  {row['round']}: UNREADABLE ({row['error']})")
            continue
        if row["parsed_empty"]:
            lines.append(f"  {row['round']}: no parsed metrics "
                         "(harness died post-run; tail only)")
            continue
        lines.append(f"  {row['round']}: {len(row['metrics'])} metric(s), "
                     f"{len(row['deltas'])} delta(s) vs prior")
        movers = sorted(
            ((k, d) for k, d in row["deltas"].items() if "pct" in d),
            key=lambda kd: -abs(kd[1]["pct"]))[:top]
        for key, d in movers:
            lines.append(f"      {d['pct']:>+8.1f}%  {key}")
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="join BENCH_r*.json rounds into one perf trajectory")
    ap.add_argument("--root",
                    default=os.path.dirname(os.path.dirname(
                        os.path.abspath(__file__))),
                    help="directory holding BENCH_r*.json (default: "
                         "the repo root this script lives in)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full machine-readable trajectory")
    ap.add_argument("--top", type=int, default=5,
                    help="biggest percentage movers to print per round")
    ns = ap.parse_args(argv)
    paths = discover_rounds(ns.root)
    if not paths:
        print(f"bench-trajectory: no BENCH_r*.json under {ns.root}")
        return 1
    traj = build_trajectory(paths)
    if ns.json:
        print(json.dumps(traj, indent=1, sort_keys=True))
    else:
        print(render(traj, top=ns.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
