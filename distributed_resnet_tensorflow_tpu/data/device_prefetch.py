"""Device prefetch + background input threads.

The reference's analog was tf.data's prefetch buffering and the 16-thread
queue runners (reference resnet_cifar_main.py:232, cifar_input.py:77-96).
Here:

  * ``device_prefetch``   — a DEDICATED transfer thread runs the host→device
    placement fn and feeds a bounded queue of already-device-resident
    batches, so decode, stacking, H2D transfer and dispatch each own a
    thread and run concurrently. (The pre-overlap version dispatched
    transfers inline on the consumer thread — staging was serial with
    dispatch, which is exactly the "serial staging" bottleneck BENCH_r05
    measured.)
  * ``threaded_iterator`` — run ANY iterator on a background thread with a
    bounded queue; the single implementation of the worker/stop/error
    machinery used by every threaded input stage.
  * ``threaded_stacker``  — draw K batches + np.stack on a background thread
    (the input side of the fused ``steps_per_loop`` dispatch).

Every stage records busy time + item counts into
``utils.metrics.input_stages`` (stages: decode / stack / stage / transfer /
dispatch_wait — see docs/input_pipeline.md), so attribution of the
end-to-end input rate comes from the pipeline as it actually ran.

All returned generators stop their worker thread when closed — a replaced
or abandoned pipeline must not leave a thread parked on its queue holding
batches.
"""
from __future__ import annotations

import logging
import queue as queue_mod
import threading
import time
from typing import Callable, Iterator, Optional

log = logging.getLogger(__name__)


def _batch_items(batch) -> int:
    """Number of examples a host batch carries (for stage-rate counters):
    the label leaf's element count covers both flat (B,) and stacked (K, B)
    batches; index batches ({"idx"}) count indices."""
    try:
        for key in ("labels", "idx"):
            leaf = batch.get(key) if hasattr(batch, "get") else None
            if leaf is not None:
                return int(getattr(leaf, "size", len(leaf)))
        leaf = next(iter(batch.values()))
        return int(leaf.shape[0])
    except Exception:
        return 0


def device_prefetch(host_iter: Iterator, put: Callable, depth: int = 2
                    ) -> Iterator:
    """Yield device-resident batches staged by a dedicated transfer thread.

    ``put`` is the host→device placement fn (e.g. Trainer._put_batch). It
    runs on its own thread: while the consumer dispatches step N, the
    transfer thread is already staging batches N+1.. into a bounded queue
    of ``depth`` device-resident batches, with one more transfer kept in
    flight behind the current ``put`` call. A slow ``put`` therefore never
    blocks the consumer while staged batches remain queued.

    A put returning a ``StagedBatch`` (the coalesced stager) is finalized
    on the CONSUMER thread: the staging thread then only moves data, and
    every multi-device XLA execution (unpack + step) is dispatched from
    one thread — launching them from two threads interleaves per-device
    enqueue order and can deadlock against a collective-bearing step.

    Closing the returned generator stops the transfer thread and propagates
    close() to ``host_iter`` (so upstream worker threads shut down too).
    """
    import jax

    from ..telemetry.tracer import span
    from ..utils.metrics import input_stages

    # a put that records its own stage counters (CoalescedStager splits
    # pack → "stage" and issue → "transfer") must not have its items
    # double-counted; we then only charge the completion wait
    put_records = getattr(put, "records_stages", False)

    def staged():
        # Batches are yielded the moment their transfer is ISSUED (jax
        # arrays are futures — the consumer's dispatch does not need them
        # materialized), so a put() blocked on batch N never withholds an
        # already-issued batch from the consumer. The issue point is
        # DOUBLE-BUFFERED (round 9): up to two issued transfers ride
        # behind the current put before the thread waits on the oldest,
        # so packing batch N+1 (host memcpy, the "stage" counter) overlaps
        # batch N's H2D DMA instead of serializing with it — the residue
        # behind BENCH_r05's e2e_vs_slowest_component = 0.544. The wait on
        # the oldest still makes the "transfer" counter reflect true H2D
        # throughput (issue alone is async and near-free), and the
        # staging ring bounds how far the host buffers can run ahead
        # (a slot is only rewritten once its transfer completed).
        from collections import deque
        pending = deque()  # (device_batch, items, issue_seconds)

        def charge(entry):
            dev, items, issue_s = entry
            t0 = time.perf_counter()
            try:
                # StagedBatch exposes block_until_ready (transfer only);
                # plain pytrees block leaf-wise
                with span("input.transfer"):
                    blocker = getattr(dev, "block_until_ready", None)
                    if blocker is not None:
                        blocker()
                    else:
                        jax.block_until_ready(dev)
            except Exception:
                pass  # non-jax payloads (tests stub put with plain values)
            wait_s = time.perf_counter() - t0
            if put_records:
                input_stages.add("transfer", wait_s)
            else:
                input_stages.add("transfer", issue_s + wait_s, items=items)

        try:
            for batch in host_iter:
                items = _batch_items(batch)
                t0 = time.perf_counter()
                with span("input.stage"):
                    out = put(batch)
                issue_s = time.perf_counter() - t0
                pending.append((out, items, issue_s))
                while len(pending) > 2:  # double-buffered issue window
                    charge(pending.popleft())
                yield out
            while pending:
                charge(pending.popleft())
        finally:
            # propagate close() (e.g. Trainer replacing its cached
            # prefetcher) down to the source so worker threads shut down
            close = getattr(host_iter, "close", None)
            if close is not None:
                close()

    inner = threaded_iterator(staged(), depth, name="drt-device-stage",
                              wait_stage="dispatch_wait")

    def finalized():
        # runs on the CONSUMER thread: resolve StagedBatch handles into
        # their leaf pytrees (an async multi-device dispatch, ~µs)
        try:
            for item in inner:
                fin = getattr(item, "finalize", None)
                yield fin() if fin is not None else item
        finally:
            inner.close()

    return finalized()


class _WorkerError:
    def __init__(self, exc: BaseException):
        self.exc = exc


_STOP = object()


def threaded_iterator(src: Iterator, depth: int = 2,
                      name: str = "drt-input-worker",
                      wait_stage: Optional[str] = None) -> Iterator:
    """Run ``src`` on a daemon thread feeding a bounded queue of ``depth``.

    Worker exceptions re-raise on the consuming thread; closing the returned
    generator (or GC'ing it) sets a stop event that EVERY queue put honors —
    including the terminal sentinel/error puts — so the thread can never
    park forever on a full queue.

    ``wait_stage``: when set, consumer time spent blocked on an empty queue
    is recorded under that stage name in ``utils.metrics.input_stages``
    (the dispatch-wait counter: how long input made the consumer wait).
    """
    q: queue_mod.Queue = queue_mod.Queue(maxsize=depth)
    stop = threading.Event()
    if wait_stage is not None:
        from ..utils.metrics import input_stages

    def put_checked(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.2)
                # re-check after the put: the consumer's shutdown drain may
                # have freed the slot we just filled — starting another
                # next(src) now would outlive the join and leak nested
                # workers, so report shutdown even though the put landed
                return not stop.is_set()
            except queue_mod.Full:
                continue
        return False

    def worker():
        try:
            for item in src:
                if not put_checked(item):
                    return
            put_checked(_STOP)
        except BaseException as e:  # surface on the consumer thread
            put_checked(_WorkerError(e))

    thread = threading.Thread(target=worker, daemon=True, name=name)
    thread.start()

    def get_checked():
        # timed get + liveness re-check (hangcheck untimed-blocking-call,
        # docs/static_analysis.md): a worker killed without posting its
        # _STOP/error sentinel (interpreter teardown, a hard native
        # crash) must become a loud RuntimeError on the consumer thread,
        # not a permanent park on an empty queue
        while True:
            try:
                return q.get(timeout=5.0)
            except queue_mod.Empty:
                if not thread.is_alive():
                    try:  # a sentinel may have landed after the timeout
                        return q.get_nowait()
                    except queue_mod.Empty:
                        raise RuntimeError(
                            f"input worker thread {name!r} died without "
                            "reporting — upstream iterator lost") from None

    try:
        while True:
            if wait_stage is None:
                item = get_checked()
            else:
                t0 = time.perf_counter()
                item = get_checked()
                input_stages.add(wait_stage, time.perf_counter() - t0,
                                 items=1)
            if item is _STOP:
                return
            if isinstance(item, _WorkerError):
                raise item.exc
            yield item
    finally:
        stop.set()
        # The worker may still be executing next(src); a generator cannot be
        # closed from another thread while executing, so unblock any pending
        # put and join (briefly) before closing. A worker stuck in blocking
        # IO is a daemon thread — abandoned after the timeout, and close()
        # then tolerates the cross-thread race.
        try:
            q.get_nowait()
        except queue_mod.Empty:
            pass
        try:
            thread.join(timeout=1.0)
        except TypeError:
            # interpreter teardown: a GC'd generator can land here after
            # threading internals are already None'd out
            pass
        close = getattr(src, "close", None)
        if close is not None:
            try:
                close()
            except ValueError:  # generator still executing on the worker
                pass


def threaded_stacker(host_iter: Iterator, k: int, depth: int = 2) -> Iterator:
    """Draw K batches and np.stack them in a background thread.

    This is the input side of the fused ``steps_per_loop`` dispatch
    (Trainer.jitted_multi_step): the K-batch draw + stack is real host work
    (decode, memcpy) that would otherwise sit between scan dispatches; a
    bounded queue of ``depth`` pre-stacked loops keeps the dispatch thread
    hot. Iterator exhaustion ends the stream cleanly; a trailing partial
    group of < k batches cannot be dispatched as a fused loop and is
    dropped — logged once at stream end, never silently (the no-silent-caps
    rule). Closing the returned generator stops the worker thread.
    """
    import numpy as np

    from ..telemetry.tracer import span
    from ..utils.metrics import input_stages

    def groups():
        while True:
            batches = []
            try:
                for _ in range(k):
                    batches.append(next(host_iter))
            except StopIteration:
                if batches:
                    log.warning(
                        "threaded_stacker: dropping %d trailing batch(es) "
                        "at stream end (shorter than the k=%d fused-loop "
                        "group)", len(batches), k)
                return
            t0 = time.perf_counter()
            with span("input.stack"):
                out = {key: np.stack([b[key] for b in batches])
                       for key in batches[0]}
            input_stages.add("stack", time.perf_counter() - t0,
                             items=_batch_items(out))
            yield out

    return threaded_iterator(groups(), depth, name="drt-batch-stacker")
